"""Perf tables in README/docs must quote the committed measurements.

The satellite guard behind the PERF.json -> docs regeneration: every
headline number the prose quotes is re-derived here from the committed
measurement and string-matched against the documents, so a re-measure
that edits `PERF.json` without regenerating the tables fails loudly
instead of drifting (the r5 state quoted 124.6 TF/s against a
committed 124.8957, and 131.6 Gcell/s against 131.7385).

Pure text checks — no JAX, no devices.
"""

import json
import os
from decimal import ROUND_HALF_UP, Decimal

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _load():
    with open(os.path.join(ROOT, "PERF.json")) as f:
        perf = json.load(f)
    return {m["metric"]: m for m in perf["metrics"]}


def _read(name):
    with open(os.path.join(ROOT, name)) as f:
        return f.read()


def _round(value, places: int) -> str:
    """Round-half-up to the doc's quoted precision (Python's round()
    is banker's rounding — 1.275 must quote as 1.28, not 1.27)."""
    q = Decimal(1).scaleb(-places)
    return str(Decimal(str(value)).quantize(q, rounding=ROUND_HALF_UP))


#: (metric, decimals, files the quote must appear in). Decimals follow
#: the tables' own precision: 1 for TF/s / Gcell/s rates, 2 for
#: Mtoken/s throughputs.
HEADLINES = [
    ("stencil_temporal_gcells", 1, ("README.md", "docs/perf_notes.md")),
    ("stencil_fused_gcells", 1, ("README.md",)),
    ("stencil_temporal_vs_fused", 1, ("README.md",)),
    ("flash_attn_fwd_s32768_bf16_causal", 1,
     ("README.md", "docs/perf_notes.md")),
    ("flash_attn_fwd_s8192_bf16", 1, ("README.md",)),
    ("flash_attn_fwd_s16384_bf16", 1, ("README.md",)),
    ("flash_attn_fwd_s32768_bf16_window4096", 1, ("README.md",)),
    ("flash_attn_train_tflops_bf16", 1, ("README.md",)),
    ("flash_attn_train_tokens_s32768_window4096_bf16", 2, ("README.md",)),
    ("flash_attn_train_tokens_s65536_window4096_bf16", 2, ("README.md",)),
    ("flash_attn_train_tokens_s131072_window4096_bf16", 2, ("README.md",)),
    ("flash_attn_train_tokens_s262144_gqa8_window4096_bf16", 2,
     ("README.md",)),
    ("flash_attn_train_tokens_s524288_gqa8_window4096_bf16", 2,
     ("README.md",)),
    ("flash_vs_stock_default", 1, ("README.md", "docs/perf_notes.md")),
    ("flash_vs_stock_swept", 2, ("README.md",)),
    ("transformer_train_tokens_s32768_window4096_bf16", 2, ("README.md",)),
    ("transformer_train_tokens_s8192_window4096_l4_bf16", 3,
     ("README.md",)),
    ("transformer_train_tokens_s32768_window4096_l4_bf16", 3,
     ("README.md",)),
]


@pytest.mark.parametrize("metric,places,files", HEADLINES,
                         ids=[m for m, _, _ in HEADLINES])
def test_doc_quotes_committed_measurement(metric, places, files):
    metrics = _load()
    assert metric in metrics, f"{metric} missing from PERF.json"
    want = _round(metrics[metric]["value"], places)
    for name in files:
        text = _read(name)
        assert want in text, (
            f"{name} does not quote {metric} = {want} "
            f"(committed value {metrics[metric]['value']}); the perf "
            f"table drifted from PERF.json — regenerate the quoted "
            f"number"
        )


def test_no_known_stale_values_left():
    """The two drifts this PR fixed must not reappear verbatim."""
    readme = _read("README.md")
    notes = _read("docs/perf_notes.md")
    assert "124.6 TFLOP/s" not in readme + notes
    assert "131.6 Gcell/s" not in readme
