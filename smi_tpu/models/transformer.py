"""Long-context transformer-block training on a DP x SP mesh.

The reference's applications compose its communication layer with
compute kernels (stencil: halo exchange in a sweep loop,
``examples/kernels/stencil_smi.cl``; K-means: collectives inside the
iteration, ``kmeans_smi.cl:132-190``). This module is the same
composition exercised at the framework's long-context frontier: one
pre-norm transformer block whose attention is the sequence-parallel
ring (``models/ring_attention.py``, flash tier on TPU), trained
data-parallel — the canonical 2-D ``(dp, sp)`` mesh.

Layout per shard: activations ``(B_local, S_local, E)`` with batch
sharded over ``dp`` and sequence over ``sp``; parameters replicated.
Attention folds the local batch into the head axis — heads are
independent, so ``(S, B_local*H, D)`` rides the existing per-head ring
schedule unchanged — and causal masking stays exact because offsets
come from the ``sp`` axis index. The training step runs entirely inside
one ``shard_map``: local loss, local autodiff (through the flash tier's
custom VJP), explicit ``psum`` of gradients over both axes, SGD update
— returning replicated parameters, the reference's
collectives-inside-the-loop shape (§2.10 DP) applied to training.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from smi_tpu.models import ring_attention as ra
from smi_tpu.parallel.mesh import Communicator
from smi_tpu.utils.compile import tpu_compiler_options


@dataclasses.dataclass(frozen=True)
class BlockConfig:
    embed: int = 256
    heads: int = 2
    head_dim: int = 128          # flash tier wants multiples of 128
    mlp_ratio: int = 2
    causal: bool = True
    window: Optional[int] = None
    #: grouped-query attention: number of K/V heads (None = heads, plain
    #: MHA). Must divide ``heads``; only the smaller K/V ride the ring.
    kv_heads: Optional[int] = None
    #: mixed precision: matmuls and the attention ring run in this
    #: dtype ("bfloat16" for the MXU's native pass — the flash tier
    #: measures ~4.7x the f32 rate) while parameters, layernorm
    #: statistics, gradients, and the optimizer state stay f32 (the
    #: standard master-weight scheme). "float32" = full precision.
    compute_dtype: str = "float32"

    @property
    def _cdtype(self):
        return jnp.dtype(self.compute_dtype)

    @property
    def _kv(self) -> int:
        kv = self.kv_heads if self.kv_heads is not None else self.heads
        if self.heads % kv:
            raise ValueError(
                f"kv_heads {kv} must divide heads {self.heads}"
            )
        return kv


def init_params(config: BlockConfig, seed: int = 0) -> dict:
    """Replicated block parameters (f32)."""
    e, h, d = config.embed, config.heads, config.head_dim
    rng = np.random.RandomState(seed)

    def w(shape, scale):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)

    kv = config._kv
    return {
        "wqkv": w((e, (h + 2 * kv) * d), e ** -0.5),
        "wo": w((h * d, e), (h * d) ** -0.5),
        "w1": w((e, config.mlp_ratio * e), e ** -0.5),
        "w2": w((config.mlp_ratio * e, e), (config.mlp_ratio * e) ** -0.5),
    }


def init_stack_params(config: BlockConfig, layers: int,
                      seed: int = 0) -> dict:
    """Stacked parameters for a ``layers``-deep block stack: each leaf
    is ``(layers, ...)`` — the ``lax.scan``-ready layout (one traced
    block, not ``layers`` inlined copies)."""
    per_layer = [
        init_params(config, seed=seed + i) for i in range(layers)
    ]
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_layer
    )


def _layernorm(x):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + 1e-6)


def block_shard(
    params: dict,
    x: jax.Array,               # (B_local, S_local, E)
    comm: Communicator,
    config: BlockConfig,
    sp_axis: str = "sp",
    use_flash: Optional[bool] = None,
    interpret: bool = False,
) -> jax.Array:
    """One pre-norm block on this rank's activation shard."""
    b, s, e = x.shape
    h, d = config.heads, config.head_dim
    cd = config._cdtype

    def mm(a, w):
        """Matmul in the compute dtype (params cast per-use; autodiff
        transposes the casts, so gradients land back in f32)."""
        return (a.astype(cd) @ params[w].astype(cd)).astype(jnp.float32)

    kv = config._kv
    xn = _layernorm(x)
    qkv = mm(xn.reshape(b * s, e), "wqkv")               # MXU
    qkv = qkv.reshape(b, s, h + 2 * kv, d)
    q = qkv[:, :, :h]
    k = qkv[:, :, h:h + kv]
    v = qkv[:, :, h + kv:]
    # fold batch into heads: (B, S, Hx, D) -> (S, B*Hx, D); heads are
    # independent so the per-head ring schedule applies unchanged, and
    # the GQA group mapping hh // (H/KV) stays correct because each
    # batch's heads are contiguous
    fold = lambda t, hx: t.transpose(1, 0, 2, 3).reshape(s, b * hx, d)
    attn = ra.ring_attention_shard(
        fold(q, h).astype(cd), fold(k, kv).astype(cd),
        fold(v, kv).astype(cd),
        comm, causal=config.causal, axis_name=sp_axis,
        use_flash=use_flash, interpret=interpret,
        window=config.window,
    ).astype(jnp.float32)                                 # (S, B*H, D)
    attn = attn.reshape(s, b, h * d).transpose(1, 0, 2)   # (B, S, H*D)
    x = x + mm(attn.reshape(b * s, h * d), "wo").reshape(b, s, e)

    yn = _layernorm(x).reshape(b * s, e)
    mlp = mm(jax.nn.gelu(mm(yn, "w1")), "w2")
    return x + mlp.reshape(b, s, e)


def stack_shard(
    params: dict,                # stacked: every leaf (layers, ...)
    x: jax.Array,
    comm: Communicator,
    config: BlockConfig,
    sp_axis: str = "sp",
    use_flash: Optional[bool] = None,
    interpret: bool = False,
) -> jax.Array:
    """A ``layers``-deep stack of pre-norm blocks on this rank's shard.

    ``lax.scan`` over the stacked parameters traces ONE block; each
    block is rematerialized under differentiation (``jax.checkpoint``),
    so training memory holds one block's residuals plus the per-layer
    activations — the standard deep-stack recipe, required at 32k+
    tokens where 4 layers of flash residuals would not fit otherwise.
    """
    block = jax.checkpoint(
        lambda p, xc: block_shard(
            p, xc, comm, config, sp_axis=sp_axis,
            use_flash=use_flash, interpret=interpret,
        )
    )

    def body(xc, p):
        return block(p, xc), None

    out, _ = lax.scan(body, x, params)
    return out


def make_train_step(
    comm: Communicator,
    config: BlockConfig,
    lr: float = 1e-3,
    use_flash: Optional[bool] = None,
    interpret: bool = False,
    layers: int = 1,
):
    """Jitted SGD training step over the communicator's (dp, sp) mesh.

    ``(params, x, y) -> (new_params, loss)`` with ``x``/``y`` of global
    shape ``(B, S, E)`` — batch over the first mesh axis, sequence over
    the second — and replicated parameters/loss. With ``layers > 1``,
    ``params`` is the stacked tree from :func:`init_stack_params` and
    the model is that many blocks deep (scan + per-block remat).
    """
    dp_axis, sp_axis = comm.axis_names
    axes = (dp_axis, sp_axis)

    def step_shard(params, x, y):
        n_total = x.shape[0] * x.shape[1] * comm.size  # per-shard equal

        def local_loss(p):
            fwd = stack_shard if layers > 1 else block_shard
            pred = fwd(
                p, x, comm, config, sp_axis=sp_axis,
                use_flash=use_flash, interpret=interpret,
            )
            return jnp.sum((pred - y) ** 2)

        lval, grads = jax.value_and_grad(local_loss)(params)
        # DP+SP allreduce of gradients and loss (the K-means shape)
        grads = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axes), grads
        )
        loss = lax.psum(lval, axes) / n_total
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g / n_total, params, grads
        )
        return new_params, loss

    data_spec = P(dp_axis, sp_axis)
    return jax.jit(
        jax.shard_map(
            step_shard, mesh=comm.mesh,
            in_specs=(P(), data_spec, data_spec),
            out_specs=(P(), P()),
            check_vma=False,
        ),
        # admit the ring schedule's VMEM-resident loop carry
        # (utils/compile.py — default scoped budget rejects it)
        compiler_options=tpu_compiler_options(comm.is_tpu),
    )


def reference_block(params, x, config: BlockConfig) -> np.ndarray:
    """Single-device float64-ish reference of the block (numpy/jnp on
    the gathered arrays) for verification."""
    b, s, e = x.shape
    h, d = config.heads, config.head_dim
    kv = config._kv
    xn = _layernorm(x)
    qkv = (xn.reshape(b * s, e) @ params["wqkv"]).reshape(
        b, s, h + 2 * kv, d
    )
    q = qkv[:, :, :h]
    k = qkv[:, :, h:h + kv]
    v = qkv[:, :, h + kv:]
    if kv != h:
        # reference semantics: each K/V head serves heads//kv query heads
        k = jnp.repeat(k, h // kv, axis=2)
        v = jnp.repeat(v, h // kv, axis=2)
    outs = []
    for bi in range(b):
        outs.append(
            ra.reference_attention(
                np.asarray(q[bi]), np.asarray(k[bi]),
                np.asarray(v[bi]), causal=config.causal,
                window=config.window,
            )
        )
    attn = jnp.asarray(np.stack(outs), jnp.float32)       # (B, S, H, D)
    x = x + (attn.reshape(b * s, h * d) @ params["wo"]).reshape(b, s, e)
    yn = _layernorm(x).reshape(b * s, e)
    mlp = jax.nn.gelu(yn @ params["w1"]) @ params["w2"]
    return np.asarray(x + mlp.reshape(b, s, e))
