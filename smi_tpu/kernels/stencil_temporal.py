"""Temporally-blocked Jacobi: k sweeps per HBM pass.

The single-sweep fused kernel (:mod:`smi_tpu.kernels.stencil`) is
HBM-bound: every sweep reads and writes the whole block (~8 B/cell). This
kernel applies the classic trapezoid/temporal-blocking transform — the
same overlap-the-halo idea the reference exploits spatially with its
bridge kernels (``examples/kernels/stencil_smi.cl:236-386``), extended in
time:

- halos are exchanged ``k`` deep and *corner-complete* (two-phase
  exchange, so diagonal-neighbour values arrive via the vertical
  neighbours — the 4-point stencil's k-sweep dependency cone is the
  Manhattan ball of radius k);
- each row-stripe is loaded into VMEM once, ``k`` full sweeps run over a
  (stripe + 2k)-row working tile whose valid region shrinks by one ring
  per sweep, and the stripe's final rows are written back — ``k`` sweeps
  for one read + one write of the block;
- the Dirichlet global-boundary mask is re-applied every sweep from
  global coordinates, so results are bit-identical to k serial sweeps.

Stripes ride the standard one-step software pipeline (stripe *i* is
fetched while stripe *i-1* computes); the working tile itself is the
pipeline carry — its centre is refilled with the just-fetched stripe at
the end of each step, so no separate previous-stripe buffer is needed and
the stripe can be twice as tall within the ~16 MB VMEM budget.

The distributed state stays in an *extended layout* ``(H, W+256)`` across
passes — 128 lanes of padding per side holding the k halo columns plus
dead zero lanes — so only the k-wide halo columns are refreshed between
passes (two narrow in-place updates), not rebuilt with a full-width
concatenate. The 120 dead lanes per side sit inside the shrink margin and
never reach valid output.

Sweeps-per-pass ``k`` plays the reference's "asynchronicity degree" role
(``rewrite.py:26-33``): a buffer-depth knob trading working-set size for
fewer round trips.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from smi_tpu.parallel.halo import (
    halo_exchange_2d_corners_finish,
    halo_exchange_2d_corners_start,
)
from smi_tpu.parallel.mesh import Communicator

#: lane padding per side of the extended array (one full register tile)
LANE_PAD = 128

#: VMEM budget for stripe selection. Live rows ≈ 2 input + 2 output
#: stripe buffers, the working tile, the k-row tail, and ~3 working-tile-
#: sized stack temporaries inside the unrolled sweep chain (measured via
#: Mosaic's scoped-vmem accounting); keep the total under ~16 MB.
VMEM_BYTES_TARGET = 14_000_000


def pick_stripe_explained(h: int, w: int, depth: int):
    """``(stripe, note)``: the full-width temporal stripe with its
    reason, or ``(None, reason)`` naming exactly why — the r18
    no-silent-caps companion of :func:`_pick_stripe` rendered by
    ``tune --explain stencil``."""
    lane_bytes = (w + 2 * LANE_PAD) * 4
    for t in range(h, 7, -1):
        if h % t or t % 8 or t < depth:
            continue
        rows_live = 4 * t + 4 * (t + 2 * depth) + depth
        if rows_live * lane_bytes <= VMEM_BYTES_TARGET:
            return t, (f"stripe {t}: tallest 8-aligned divisor of "
                       f"h={h} >= depth {depth} whose live rows fit "
                       f"the {VMEM_BYTES_TARGET} B working set")
    return None, (f"EXCLUDED: no 8-aligned divisor of h={h} >= depth "
                  f"{depth} keeps the full-width working set under "
                  f"{VMEM_BYTES_TARGET} B at w={w} — column-tiled or "
                  f"unfused fallback")


def _pick_stripe(h: int, w: int, depth: int) -> Optional[int]:
    """Largest divisor of ``h``: multiple of 8, ≥ depth, VMEM-fitting."""
    return pick_stripe_explained(h, w, depth)[0]


def _plan(h: int, w: int, depth: int):
    """Choose the cheaper kernel shape: full-width short stripes vs
    column-tiled tall stripes. Returns ("full", t) | ("tiled", (t, wc))
    | None, minimizing swept area per useful cell."""
    wp = w + 2 * LANE_PAD
    candidates = []
    t_full = _pick_stripe(h, w, depth)
    if t_full is not None:
        candidates.append(
            ((t_full + 2 * depth) / t_full * wp, ("full", t_full))
        )
    wc = _pick_col_tile(wp)
    if wc is not None:
        t_tiled = _pick_stripe_tiled(h, wc, depth)
        if t_tiled is not None:
            n_cols = wp // wc
            swept = (t_tiled + 2 * depth) / t_tiled * (
                wp + n_cols * 2 * LANE_PAD
            )
            candidates.append((swept, ("tiled", (t_tiled, wc))))
    if not candidates:
        return None
    return min(candidates)[1]


def pick_temporal_depth(h: int, w: int, dtype, iterations: int):
    """Deepest supported sweeps-per-pass for a block, preferring 16
    (measured fastest on v5e vs 8/24/32) and falling back to 8 before
    abandoning the temporal tier. Returns None when unsupported.

    Why 16 is the knee (v5e, 8192² f32; see ``vs_tpu_roofline`` in
    ``bench.py`` output): the hypothetical HBM bound is 819 GB/s / (8 B
    per cell per k sweeps) ≈ 12.8k Gcell/s at k=16 — two orders above
    the measured ~86, so by k=16 the kernel is decisively *not*
    HBM-bound; it is VPU-bound (~10 vector ops per cell·sweep, roughly
    a third of the ~6.2 TFLOP/s f32 VPU peak once shrink-margin
    recompute is counted). Past the knee, larger k only adds cost: the
    working tile grows by 2k rows of halo whose rings are recomputed
    every sweep, VMEM pressure halves the stripe height, and the k-deep
    corner-complete halo exchange widens — all while the HBM term it
    amortizes is already negligible. k=24/32 measured slower; k=16 vs
    k=8 measured ~5% faster."""
    return next(
        (
            d for d in (16, 8)
            if d <= iterations and temporal_supported(h, w, dtype, d)
        ),
        None,
    )


def temporal_supported(h: int, w: int, dtype, depth: int = 8) -> bool:
    return (
        dtype == jnp.float32
        and depth >= 1
        and depth % 8 == 0
        and depth <= LANE_PAD
        and w % 128 == 0
        and _plan(h, w, depth) is not None
    )


def _sweep_trapezoid(val, boundary, t: int, k: int, lane_w: int):
    """``k`` Jacobi sweeps over a ``(t + 2k, lane_w)`` working tile with
    the 8-aligned trapezoid shrink (r4, measured +3.7% same-session).

    Sweep ``s`` only has to produce rows ``[s+1, R-s-1)`` — later
    sweeps never read above/below that validity cone — so the working
    array drops vreg-aligned 8-row bands as the sweeps advance
    (``lo = 8*(s//8)`` per side). The slice-edge rows pick up roll wrap
    garbage, but they sit strictly outside the cone (the full-tile loop
    wrote garbage there too), so output is bit-identical. The fully
    unaligned trapezoid (1 row/sweep, ~10% fewer rows) measures worse:
    every slice would sit at a sublane offset ≢ 0 (mod 8), forcing a
    realign on all four rolls (``docs/perf_notes.md``).

    Returns ``(val, off)``: the shrunken array and its absolute row
    offset; callers slice their output rows as
    ``val[k - off : t + k - off]``.
    """
    off = 0
    R = t + 2 * k
    for s in range(k):
        lo = 8 * (s // 8)
        if lo > off:
            d = lo - off
            val = val[d : val.shape[0] - d, :]
            off = lo
        rows = R - 2 * off
        # association order matters to Mosaic's port scheduling: this
        # sublane-first left-assoc tree measures 4-6% faster than
        # lane-first or interleaved pairings (r4 A/B, same session:
        # 132.1 vs 124.4 / 126.9 Gcell/s) — the trailing lane rolls
        # overlap the adds of the cheap sublane pair
        avg = 0.25 * (
            pltpu.roll(val, 1, axis=0)
            + pltpu.roll(val, rows - 1, axis=0)
            + pltpu.roll(val, 1, axis=1)
            + pltpu.roll(val, lane_w - 1, axis=1)
        )
        val = jnp.where(boundary[off : R - off, :], val, avg)
    return val, off


def _temporal_kernel(
    offs_ref,    # scalar prefetch: [row0, col0] of this block
    x_ref,       # (T, W+256) one stripe of the extended block
    top_ref,     # (k, W+256) corner-complete halo above, pre-padded
    bottom_ref,  # (k, W+256) below
    o_ref,       # (T, W+256) output stripe (for the previous grid step)
    a_ref,       # scratch: (T+2k, W+256) working tile / pipeline carry
    tail_ref,    # scratch: last k rows of the stripe before the carried one
    *,
    tile: int,
    width: int,   # W (unpadded)
    depth: int,
    gh: int,
    gw: int,
):
    i = pl.program_id(0)
    n = pl.num_programs(0) - 1  # number of stripes
    t, k = tile, depth
    wp = width + 2 * LANE_PAD
    cur = x_ref[...]

    @pl.when(i > 0)
    def _compute():
        j = i - 1
        # The tile centre already carries stripe j (set at the end of the
        # previous step); add the k boundary rows above and below.
        @pl.when(j == 0)
        def _top_edge():
            a_ref[0:k, :] = top_ref[...]

        @pl.when(j > 0)
        def _top_interior():
            a_ref[0:k, :] = tail_ref[...]

        @pl.when(j == n - 1)
        def _bottom_edge():
            a_ref[t + k : t + 2 * k, :] = bottom_ref[...]

        @pl.when(j < n - 1)
        def _bottom_interior():
            a_ref[t + k : t + 2 * k, :] = cur[0:k, :]

        # ---- sweep-invariant Dirichlet masks from global coordinates ----
        # (n, 1)/(1, m) shapes broadcast inside the selects, avoiding
        # full-tile int32 temporaries.
        g_row = (
            offs_ref[0] + j * t - k
            + lax.broadcasted_iota(jnp.int32, (t + 2 * k, 1), 0)
        )
        g_col = (
            offs_ref[1] - LANE_PAD
            + lax.broadcasted_iota(jnp.int32, (1, wp), 1)
        )
        row_b = (g_row == 0) | (g_row == gh - 1)
        col_b = (g_col == 0) | (g_col == gw - 1)
        # one boundary mask per stripe, amortized over the k sweeps
        boundary = row_b | col_b

        # ---- k sweeps in VMEM; valid region shrinks one ring each ----
        val, off = _sweep_trapezoid(a_ref[...], boundary, t, k, wp)
        o_ref[...] = val[k - off : t + k - off, :]

    # Rotate the pipeline: save the carried stripe's last k rows as the
    # next step's upper boundary, then refill the centre with the stripe
    # fetched this step.
    tail_ref[...] = a_ref[t : t + k, :]
    a_ref[k : t + k, :] = cur


def _temporal_pass_ext(
    xext: jax.Array,
    comm: Communicator,
    gh: int,
    gw: int,
    depth: int,
    interpret: bool,
) -> jax.Array:
    """One k-sweep pass over the extended-layout state ``(H, W+256)``.

    Dispatches to the cheaper kernel shape: column-tiled tall stripes
    when the block is wide (less vertical recompute), full-width short
    stripes otherwise.
    """
    row_axis, col_axis = comm.axis_names
    h, wp = xext.shape
    w = wp - 2 * LANE_PAD
    k = depth
    plan = _plan(h, w, k)
    if plan is None:
        raise ValueError(f"no VMEM-fitting stripe for block ({h}, {w})")
    if plan[0] == "tiled":
        t, wc = plan[1]
        return _temporal_pass_ext_tiled(
            xext, comm, gh, gw, k, wc, t, interpret
        )
    t = plan[1]
    n = h // t

    # --- corner-complete halo refresh; only halo-width slices move ---
    # (XLA fuses the block view into the ppermute operands, so no full
    # copy of the centre columns is materialized). Split form: the
    # column updates below consume only the phase-1 (horizontal) slabs,
    # so they are scheduled while the phase-2 vertical ppermutes fly —
    # the inter-pass refresh overlaps its own assembly work.
    exchange = halo_exchange_2d_corners_start(
        xext[:, LANE_PAD : LANE_PAD + w], comm, depth=k
    )
    xext = lax.dynamic_update_slice(xext, exchange.left,
                                    (0, LANE_PAD - k))
    xext = lax.dynamic_update_slice(xext, exchange.right,
                                    (0, LANE_PAD + w))
    zrow = jnp.zeros((k, LANE_PAD - k), xext.dtype)
    rx = lax.axis_index(row_axis)
    cy = lax.axis_index(col_axis)
    offs = jnp.stack([rx * h, cy * w]).astype(jnp.int32)
    halos = halo_exchange_2d_corners_finish(exchange)
    top_ext = jnp.concatenate([zrow, halos.top, zrow], axis=1)
    bottom_ext = jnp.concatenate([zrow, halos.bottom, zrow], axis=1)

    kernel = functools.partial(
        _temporal_kernel, tile=t, width=w, depth=k, gh=gh, gw=gw
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        # one extra step drains the pipeline (stripe j computes at j+1)
        grid=(n + 1,),
        in_specs=[
            pl.BlockSpec(
                (t, wp),
                lambda i, offs: (jnp.minimum(i, n - 1), 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (t, wp),
            lambda i, offs: (jnp.maximum(i - 1, 0), 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((t + 2 * k, wp), jnp.float32),
            pltpu.VMEM((k, wp), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, wp), xext.dtype),
        interpret=interpret,
    )(offs, xext, top_ext, bottom_ext)


def pick_col_tile_explained(wp: int):
    """``(width, note)``: the column-tile width with its reason, or
    ``(None, reason)`` — the r18 no-silent-caps companion of
    :func:`_pick_col_tile` rendered by ``tune --explain stencil``."""
    for wc in range(min(wp, 2048), 127, -128):
        if wp % wc == 0 and wc % 128 == 0:
            return wc, (f"column tile {wc}: widest 128-lane divisor "
                        f"of wp={wp} at or under the measured "
                        f"2048-lane sweet spot")
    return None, (f"EXCLUDED: wp={wp} has no 128-lane divisor at or "
                  f"under 2048 lanes — full-width or unfused fallback")


def _pick_col_tile(wp: int) -> Optional[int]:
    """Column-tile width: the largest 128-multiple divisor of ``wp``
    that is ≤ 2048. Wider tiles mean less horizontal recompute (the two
    128-lane aprons amortize over more columns), but tile rows must stay
    small enough that the row stripe can be tall — 2048 lanes keeps a
    128-row stripe within VMEM (measured sweet spot on v5e; 2816-lane
    tiles with 64-row stripes time the same, wider regresses). Returns
    None when ``wp`` has no such divisor."""
    return pick_col_tile_explained(wp)[0]


def _pick_stripe_tiled(h: int, wc: int, depth: int) -> Optional[int]:
    """Row-stripe height for the column-tiled kernel: 3x2 input blocks +
    2 output blocks of (t, wc), working tile + ~3 stack temporaries of
    (t+2k, wc+256)."""
    for t in range(h, 7, -1):
        if h % t or t % 8 or t < depth:
            continue
        live = (8 * t * wc + 4 * (t + 2 * depth) * (wc + 2 * LANE_PAD)) * 4
        if live <= VMEM_BYTES_TARGET:
            return t
    return None


def _tiled_kernel(
    offs_ref,    # scalar prefetch: [row0, col0] of this block
    left_ref,    # (T, WC) column tile c-1 (clamped)
    x_ref,       # (T, WC) column tile c
    right_ref,   # (T, WC) column tile c+1 (clamped)
    top_ref,     # (k, WP+256) halo above, padded 128 per side
    bottom_ref,  # (k, WP+256) below
    o_ref,       # (T, WC) output tile (for the previous row step)
    a_ref,       # scratch: (T+2k, WC+256) working tile / pipeline carry
    tail_ref,    # scratch: last k rows of the carried stripe (3 tiles wide)
    *,
    tile: int,
    wc: int,
    depth: int,
    n_rows: int,
    gh: int,
    gw: int,
):
    c = pl.program_id(0)
    i = pl.program_id(1)
    t, k = tile, depth
    n = n_rows
    pad = LANE_PAD
    wca = wc + 2 * pad

    cur_l, cur, cur_r = left_ref[...], x_ref[...], right_ref[...]

    @pl.when(i > 0)
    def _compute():
        j = i - 1

        @pl.when(j == 0)
        def _top_edge():
            a_ref[0:k, :] = top_ref[:, pl.ds(c * wc, wca)]

        @pl.when(j > 0)
        def _top_interior():
            a_ref[0:k, :] = tail_ref[...]

        @pl.when(j == n - 1)
        def _bottom_edge():
            a_ref[t + k : t + 2 * k, :] = bottom_ref[:, pl.ds(c * wc, wca)]

        @pl.when(j < n - 1)
        def _bottom_interior():
            a_ref[t + k : t + 2 * k, pad : pad + wc] = cur[0:k, :]
            a_ref[t + k : t + 2 * k, pad - k : pad] = (
                cur_l[0:k, wc - k : wc]
            )
            a_ref[t + k : t + 2 * k, pad + wc : pad + wc + k] = (
                cur_r[0:k, 0:k]
            )

        g_row = (
            offs_ref[0] + j * t - k
            + lax.broadcasted_iota(jnp.int32, (t + 2 * k, 1), 0)
        )
        g_col = (
            offs_ref[1] - LANE_PAD + c * wc - pad
            + lax.broadcasted_iota(jnp.int32, (1, wca), 1)
        )
        row_b = (g_row == 0) | (g_row == gh - 1)
        col_b = (g_col == 0) | (g_col == gw - 1)
        boundary = row_b | col_b

        val, off = _sweep_trapezoid(a_ref[...], boundary, t, k, wca)
        o_ref[...] = val[k - off : t + k - off, pad : pad + wc]

    # rotate the pipeline; the carry holds this column tile plus k halo
    # columns from each neighbouring tile
    tail_ref[...] = a_ref[t : t + k, :]
    a_ref[k : t + k, pad : pad + wc] = cur
    a_ref[k : t + k, pad - k : pad] = cur_l[:, wc - k : wc]
    a_ref[k : t + k, pad + wc : pad + wc + k] = cur_r[:, 0:k]


def _temporal_pass_ext_tiled(
    xext: jax.Array,
    comm: Communicator,
    gh: int,
    gw: int,
    depth: int,
    wc: int,
    t: int,
    interpret: bool,
) -> jax.Array:
    """Column-tiled k-sweep pass: same contract as the full-width pass,
    but the row stripe is decoupled from the array width so it can be
    tall (less vertical recompute). Neighbour columns come from reading
    three adjacent column tiles per step (clamped at the edges — the
    clamped garbage lands inside the 120 dead lanes and never reaches
    valid output)."""
    row_axis, col_axis = comm.axis_names
    h, wp = xext.shape
    w = wp - 2 * LANE_PAD
    k = depth
    n_rows = h // t
    n_cols = wp // wc

    exchange = halo_exchange_2d_corners_start(
        xext[:, LANE_PAD : LANE_PAD + w], comm, depth=k
    )
    xext = lax.dynamic_update_slice(xext, exchange.left,
                                    (0, LANE_PAD - k))
    xext = lax.dynamic_update_slice(xext, exchange.right,
                                    (0, LANE_PAD + w))
    halos = halo_exchange_2d_corners_finish(exchange)
    zrow = jnp.zeros((k, LANE_PAD - k), xext.dtype)
    zpad = jnp.zeros((k, LANE_PAD), xext.dtype)
    # pad a full register tile per side so per-tile slices never clamp
    top_ext = jnp.concatenate(
        [zpad, zrow, halos.top, zrow, zpad], axis=1
    )
    bottom_ext = jnp.concatenate(
        [zpad, zrow, halos.bottom, zrow, zpad], axis=1
    )

    rx = lax.axis_index(row_axis)
    cy = lax.axis_index(col_axis)
    offs = jnp.stack([rx * h, cy * w]).astype(jnp.int32)

    kernel = functools.partial(
        _tiled_kernel, tile=t, wc=wc, depth=k, n_rows=n_rows, gh=gh, gw=gw
    )
    # index maps take grid coords (c, i) and return (row_block, col_block)
    block = lambda dc: (
        lambda c, i, offs, _dc=dc: (
            jnp.minimum(i, n_rows - 1),
            jnp.clip(c + _dc, 0, n_cols - 1),
        )
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_cols, n_rows + 1),  # row dim fastest: carries per column
        in_specs=[
            pl.BlockSpec((t, wc), block(-1), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, wc), block(0), memory_space=pltpu.VMEM),
            pl.BlockSpec((t, wc), block(+1), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (t, wc),
            lambda c, i, offs: (jnp.maximum(i - 1, 0), c),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((t + 2 * k, wc + 2 * LANE_PAD), jnp.float32),
            pltpu.VMEM((k, wc + 2 * LANE_PAD), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, wp), xext.dtype),
        interpret=interpret,
    )(offs, xext, xext, xext, top_ext, bottom_ext)


def temporal_pass(
    block: jax.Array,
    comm: Communicator,
    gh: int,
    gw: int,
    depth: int = 8,
    interpret: bool = False,
) -> jax.Array:
    """``depth`` fused sweeps over a plain ``(H, W)`` block (one pass)."""
    h, w = block.shape
    zcols = jnp.zeros((h, LANE_PAD), block.dtype)
    xext = jnp.concatenate([zcols, block, zcols], axis=1)
    out = _temporal_pass_ext(xext, comm, gh, gw, depth, interpret)
    return out[:, LANE_PAD : LANE_PAD + w]


def make_temporal_stencil_fn(
    comm: Communicator,
    iterations: int,
    gh: int,
    gw: int,
    depth: int = 8,
    interpret: bool = False,
):
    """Jitted distributed stencil at ``depth`` sweeps per memory pass.

    The state stays in extended layout across passes, so per pass only
    the halo columns/rows move between ranks and the block is touched by
    exactly one kernel read and one write. ``iterations`` need not divide
    evenly: the remainder runs on the single-sweep fused kernel (or the
    jnp sweep where that is unsupported).
    """
    from jax.sharding import PartitionSpec as P

    from smi_tpu.kernels import stencil as kstencil
    from smi_tpu.models.stencil import jacobi_step_block

    row_axis, col_axis = comm.axis_names
    spec = P(row_axis, col_axis)
    full, rem = divmod(iterations, depth)

    def shard_fn(block):
        h, w = block.shape
        b = block
        if full:
            zcols = jnp.zeros((h, LANE_PAD), block.dtype)
            xe = jnp.concatenate([zcols, block, zcols], axis=1)
            xe = lax.fori_loop(
                0,
                full,
                lambda _, x: _temporal_pass_ext(
                    x, comm, gh, gw, depth, interpret
                ),
                xe,
            )
            b = xe[:, LANE_PAD : LANE_PAD + w]
        if rem and kstencil.pallas_supported(h, w, block.dtype):
            b = lax.fori_loop(
                0,
                rem,
                lambda _, x: kstencil.jacobi_step_block_fused(
                    x, comm, gh, gw, interpret=interpret
                ),
                b,
            )
        elif rem:
            b = lax.fori_loop(
                0, rem, lambda _, x: jacobi_step_block(x, comm), b
            )
        return b

    return jax.jit(
        jax.shard_map(
            shard_fn, mesh=comm.mesh, in_specs=spec, out_specs=spec,
            check_vma=False,
        )
    )
