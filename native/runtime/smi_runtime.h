// smi_runtime: native host runtime support library.
//
// C++ equivalent of the reference's host-side native layer
// (include/utils/smi_utils.hpp — LoadRoutingTable, kChannelsPerRank;
// include/utils/utils.hpp — microsecond/nanosecond timers; plus the
// table staging that the generated SmiInit_<program> performs,
// codegen/templates/host_hlslib.cl:20-38). Exposed as a C ABI so the
// Python side binds via ctypes (no pybind11 in the image).

#pragma once

#include <cstdint>

extern "C" {

// Library/version info ---------------------------------------------------
const char* smi_runtime_version();

// Timing (include/utils/utils.hpp:10-23 parity) --------------------------
int64_t smi_time_usecs();
int64_t smi_time_nsecs();

// Routing table IO -------------------------------------------------------
// Tables are little-endian fixed-width unsigned entries, one file per
// (kind, rank, channel) named "{kind}-rank{r}-channel{c}"
// (include/utils/smi_utils.hpp:24-39). Returns the entry count, or -1 on
// IO error, or -2 if the buffer is too small (required size is written
// nowhere; call with a larger buffer).
int32_t smi_load_routing_table(const char* dir, const char* kind,
                               int32_t rank, int32_t channel,
                               uint8_t* out, int32_t capacity);

// Write `count` single-byte entries to the table file. Returns 0, or -1
// on IO error.
int32_t smi_store_routing_table(const char* dir, const char* kind,
                                int32_t rank, int32_t channel,
                                const uint8_t* data, int32_t count);

// Communicator bootstrap -------------------------------------------------
// The reference's SmiInit returns SMI_Comm{rank, size} after staging
// tables (host_hlslib.cl:87-89). Here the bootstrap validates that all
// 2*channels tables for `rank` exist in `dir` and reports the logical
// port count implied by the cks table size (entries / max_ranks).
// Returns the port count, or -1 if any table file is missing/invalid.
int32_t smi_bootstrap_rank(const char* dir, int32_t rank,
                           int32_t channels, int32_t max_ranks);

}  // extern "C"
