"""Halo exchange for multi-dimensional domain decomposition.

Reference parity: the stencil application's bridge kernels
(``examples/kernels/stencil_smi.cl:236-386``) — eight ``Convert{Send,
Receive}{Top,Bottom,Left,Right}`` kernels that stream one-deep halos
between the four grid neighbours over SMI P2P ports 0-3, concurrently with
compute. This is the reference's expression of spatial (sequence-like)
parallelism: a large domain scaled across devices with nearest-neighbour
exchange (SURVEY §5.7).

TPU re-design: the process grid is a real 2-D mesh axis pair and each halo
is one non-wrapping masked ``lax.ppermute`` along its axis — four shifts
riding four ICI directions simultaneously, which XLA schedules in parallel
because they have no data dependencies. Edge ranks receive zeros (the
reference's edge bridges simply have no peer to pop from).

The same primitive with wrap-around (``ring=True``) is the ring-attention/
context-parallel schedule step (SURVEY §2.10: ring `ppermute` schedules).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from smi_tpu.parallel.backend import check_backend
from smi_tpu.parallel.mesh import Communicator


def shift_along(
    x: jax.Array,
    axis_name: str,
    n: int,
    direction: int,
    ring: bool = False,
    backend: str = "xla",
    comm: Optional[Communicator] = None,
    stream: int = 0,
) -> jax.Array:
    """Move ``x`` to the rank ``direction`` steps up the axis.

    ``direction=+1`` sends towards higher ranks (rank r receives r-1's
    data); ``-1`` the opposite. Without ``ring``, edge ranks receive
    zeros; with it, the permutation wraps (the pipeline/ring pattern,
    ``pipeline.cl:16-31``).

    ``backend="ring"`` moves the slab over the explicit neighbour RDMA
    kernel instead of ``lax.ppermute`` — ``comm`` is then REQUIRED so
    device ids resolve on the full mesh; ``stream`` selects the
    barrier-semaphore domain (shifts that may run concurrently must not
    share one — the reference's distinct P2P port per direction). The
    kernel's ring wraps, so the non-``ring`` contract is restored by
    zeroing the edge rank's received slab.
    """
    if direction not in (1, -1):
        raise ValueError(f"direction must be +1 or -1, got {direction}")
    if check_backend(backend) == "ring" and x.size:
        if comm is None:
            raise ValueError(
                "shift_along(backend='ring') needs comm= to resolve "
                "device ids on the full mesh (identity ids would "
                "cross-signal other rings' devices)"
            )
        from smi_tpu.kernels import ring as _ring

        # one chunk in flat row layout (1, 1, size): a column slab's
        # natural (H, depth=1) shape has a width-1 lane dimension, and
        # Mosaic rejects the width-1 slice of the lane-padded VMEM
        # buffer ("Slice shape along dimension 2 must be aligned to
        # tiling (128)") — caught by the AOT topology tier
        # (halo_ring_4dir, tests/test_aot_tpu.py); interpret mode has
        # no tiling and accepts the slab shape unchanged
        got = _ring.neighbour_stream(
            x.reshape(1, 1, -1), axis_name, n, direction=direction,
            interpret=not comm.is_tpu, stream=stream,
            mesh_axes=_ring.mesh_axes_of(comm),
        ).reshape(x.shape)
        if ring:
            return got
        # non-wrapping: the edge rank has no upstream — its received
        # slab is the wrapped neighbour's and must read as zeros
        edge = 0 if direction == 1 else n - 1
        return jnp.where(lax.axis_index(axis_name) == edge,
                         jnp.zeros_like(got), got)
    if ring:
        perm = [(i, (i + direction) % n) for i in range(n)]
    elif direction == 1:
        perm = [(i, i + 1) for i in range(n - 1)]
    else:
        perm = [(i + 1, i) for i in range(n - 1)]
    return lax.ppermute(x, axis_name, perm)


class Halos(NamedTuple):
    """Received halo slabs around a 2-D block (zeros at domain edges).

    Shapes depend on the producer: from :func:`halo_exchange_2d`,
    top/bottom are ``(depth, W)``; from :func:`halo_exchange_2d_corners`,
    top/bottom are ``(depth, W+2·depth)`` (side-halo columns included).
    left/right are ``(H, depth)`` from both."""

    top: jax.Array
    bottom: jax.Array
    left: jax.Array
    right: jax.Array


class HaloExchange(NamedTuple):
    """In-flight halo exchange handle (:func:`halo_exchange_start`).

    At the jnp level "in flight" is dataflow, not a hardware handle:
    the four ppermutes exist as issued ops with no consumers yet, so
    everything the caller computes between ``start`` and ``finish``
    is, by construction, independent of the transfers — exactly the
    compute XLA's latency-hiding scheduler may run between the lowered
    ``collective-permute-start``/``done`` pair. ``finish`` returns the
    slabs and thereby places the first data dependence on them.
    :func:`smi_tpu.parallel.traffic.overlap_report` verifies the
    resulting schedule property on compiled HLO.
    """

    halos: Halos
    depth: int


def halo_exchange_start(
    block: jax.Array,
    comm: Communicator,
    depth: int = 1,
    ring: bool = False,
    backend: str = "xla",
) -> HaloExchange:
    """Issue the four neighbour transfers; do NOT consume them yet.

    Split form of :func:`halo_exchange_2d`: between ``start`` and
    :func:`halo_exchange_finish` the caller computes its
    halo-independent interior, giving XLA compute to schedule while
    the edge ppermutes fly (the reference's bridge kernels running
    concurrently with the compute pipeline, ``stencil_smi.cl:236-386``).
    """
    return HaloExchange(
        halos=halo_exchange_2d(block, comm, depth=depth, ring=ring,
                               backend=backend),
        depth=depth,
    )


def halo_exchange_finish(exchange: HaloExchange) -> Halos:
    """Consume an in-flight exchange: returns the four neighbour slabs.

    The first use of the returned arrays is the synchronization point —
    XLA places the ``collective-permute-done`` right before it.
    """
    return exchange.halos


def halo_exchange_2d(
    block: jax.Array,
    comm: Communicator,
    depth: int = 1,
    ring: bool = False,
    backend: str = "xla",
) -> Halos:
    """Exchange ``depth``-deep halos with the four 2-D mesh neighbours.

    ``comm`` must span two axes ``(row_axis, col_axis)``; ``block`` is this
    rank's ``(H, W)`` tile of the global grid, laid out so that the rank at
    row-coordinate ``r`` holds global rows ``[r*H, (r+1)*H)`` (the
    reference's block decomposition, ``stencil.h.in:32-38``).

    Returns the four neighbour slabs: ``top`` is the last ``depth`` rows of
    the block above, etc. All four transfers are independent ppermutes —
    XLA overlaps them across ICI directions, the analog of the reference's
    eight concurrently-running bridge kernels.
    """
    if len(comm.axis_names) != 2:
        raise ValueError(
            f"halo_exchange_2d needs a 2-axis communicator, got axes "
            f"{comm.axis_names}"
        )
    row_axis, col_axis = comm.axis_names
    nrow = comm.mesh.shape[row_axis]
    ncol = comm.mesh.shape[col_axis]

    # one stream (= barrier-semaphore domain) per direction, the
    # reference's four bridge-kernel ports (stencil_smi.cl:236-386)
    top = shift_along(block[-depth:, :], row_axis, nrow, +1, ring,
                      backend=backend, comm=comm, stream=0)
    bottom = shift_along(block[:depth, :], row_axis, nrow, -1, ring,
                         backend=backend, comm=comm, stream=1)
    left = shift_along(block[:, -depth:], col_axis, ncol, +1, ring,
                       backend=backend, comm=comm, stream=2)
    right = shift_along(block[:, :depth], col_axis, ncol, -1, ring,
                        backend=backend, comm=comm, stream=3)
    return Halos(top=top, bottom=bottom, left=left, right=right)


def halo_exchange_2d_corners(
    block: jax.Array,
    comm: Communicator,
    depth: int = 1,
    ring: bool = False,
    backend: str = "xla",
) -> Halos:
    """Corner-complete ``depth``-deep halo exchange (two-phase).

    :func:`halo_exchange_2d` leaves the four ``depth × depth`` corner
    patches unknown — enough for one sweep of a 4-point stencil, but a
    *k-sweep* temporal block depends on the full Manhattan ball of radius
    k, corners included. The standard two-phase scheme fills them with no
    extra neighbours: first the left/right column slabs move, then the
    top/bottom slabs are sent *including the just-received side halos*
    (width ``W+2·depth``), so diagonal values arrive via the vertical
    neighbour — two dependent ppermute rounds, the same trick as the
    reference routing packets through an intermediate device
    (``ckr.cl:50-60``).

    Returns ``top``/``bottom`` of shape ``(depth, W+2·depth)`` (halo
    columns included) and ``left``/``right`` of shape ``(H, depth)``.
    """
    return halo_exchange_2d_corners_finish(
        halo_exchange_2d_corners_start(block, comm, depth=depth,
                                       ring=ring, backend=backend)
    )


class CornerHaloExchange(NamedTuple):
    """In-flight corner-complete exchange: phase-1 slabs exposed, the
    dependent phase-2 (vertical) transfers issued but unconsumed.

    ``left``/``right`` arrived in phase 1 and already fed phase 2's
    operands, so consuming them immediately costs no overlap; the
    caller's compute between start and finish runs while the top/bottom
    ppermutes fly (the temporal stencil updates its extended-layout
    halo COLUMNS in that window — ``stencil_temporal.py``).
    """

    left: jax.Array
    right: jax.Array
    top: jax.Array
    bottom: jax.Array


def halo_exchange_2d_corners_start(
    block: jax.Array,
    comm: Communicator,
    depth: int = 1,
    ring: bool = False,
    backend: str = "xla",
) -> CornerHaloExchange:
    """Issue both phases of the corner-complete exchange; expose the
    phase-1 (horizontal) slabs for immediate use and leave the phase-2
    (vertical) transfers in flight for :func:`halo_exchange_2d_corners_finish`."""
    if len(comm.axis_names) != 2:
        raise ValueError(
            f"halo_exchange_2d_corners needs a 2-axis communicator, got "
            f"axes {comm.axis_names}"
        )
    row_axis, col_axis = comm.axis_names
    nrow = comm.mesh.shape[row_axis]
    ncol = comm.mesh.shape[col_axis]
    d = depth

    left = shift_along(block[:, -d:], col_axis, ncol, +1, ring,
                       backend=backend, comm=comm, stream=2)
    right = shift_along(block[:, :d], col_axis, ncol, -1, ring,
                        backend=backend, comm=comm, stream=3)
    # phase 2: only the edge rows of the side-extended array move
    ext_top = jnp.concatenate([left[:d], block[:d], right[:d]], axis=1)
    ext_bottom = jnp.concatenate(
        [left[-d:], block[-d:], right[-d:]], axis=1
    )
    top = shift_along(ext_bottom, row_axis, nrow, +1, ring,
                      backend=backend, comm=comm, stream=0)
    bottom = shift_along(ext_top, row_axis, nrow, -1, ring,
                         backend=backend, comm=comm, stream=1)
    return CornerHaloExchange(left=left, right=right, top=top,
                              bottom=bottom)


def halo_exchange_2d_corners_finish(
    exchange: CornerHaloExchange,
) -> Halos:
    """Consume the in-flight vertical transfers; returns the four slabs
    in :class:`Halos` layout (top/bottom side-extended)."""
    return Halos(top=exchange.top, bottom=exchange.bottom,
                 left=exchange.left, right=exchange.right)


def pad_with_halos(block: jax.Array, halos: Halos, depth: int = 1) -> jax.Array:
    """Assemble the ``(H+2d, W+2d)`` padded tile (corners zero)."""
    h, w = block.shape
    padded = jnp.zeros((h + 2 * depth, w + 2 * depth), block.dtype)
    padded = lax.dynamic_update_slice(padded, block, (depth, depth))
    padded = lax.dynamic_update_slice(padded, halos.top, (0, depth))
    padded = lax.dynamic_update_slice(padded, halos.bottom, (h + depth, depth))
    padded = lax.dynamic_update_slice(padded, halos.left, (depth, 0))
    padded = lax.dynamic_update_slice(padded, halos.right, (depth, w + depth))
    return padded
