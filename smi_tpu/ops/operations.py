"""The SMI operation taxonomy.

Reference parity: ``codegen/ops.py:24-210``. Every communication primitive a
program uses is declared (or discovered by the manifest tool) as one
``SmiOperation`` carrying its logical *port*, element *dtype*, and tuning
knobs. The collection of operations is what the reference calls a per-rank
*program*; on TPU it drives:

- validation (port uniqueness per operation family,
  ``codegen/program.py:37-50``),
- assignment of logical ports onto *streams* — the TPU analog of the
  reference's four physical QSFP channels (``codegen/program.py:53-80``) —
  which decides which concurrent collectives may overlap and which ring
  direction a P2P port prefers,
- chunking/pipelining depth for streamed transfers (the ``buffer_size`` /
  "asynchronicity degree" knob, ``codegen/ops.py:42-54``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Optional, Type, Union

from smi_tpu.ops.types import (
    SmiDtype,
    SmiOp,
    buffer_size_to_packets,
    elements_per_packet,
)

#: Stream-usage classes. The reference distinguishes the four per-op hardware
#: FIFO groups ``{cks,ckr}_{data,control}`` (``codegen/ops.py:30-37``); here
#: the same four keys name *virtual streams*: out/in × payload/flow-control.
OUT_DATA = "out_data"
OUT_CTRL = "out_ctrl"
IN_DATA = "in_data"
IN_CTRL = "in_ctrl"
ALL_STREAM_KEYS = (OUT_DATA, OUT_CTRL, IN_DATA, IN_CTRL)

#: Default pipelining depth (in packets) when a channel does not specify an
#: asynchronicity degree — matches the reference's default channel depth
#: (``codegen/ops.py:42-54``).
DEFAULT_BUFFER_PACKETS = 16


def pipeline_depth_packets(buffer_size: Optional[int], dtype) -> int:
    """In-flight chunk budget for a channel: the declared asynchronicity
    degree rounded as the reference rounds it, or the default depth.

    Single source of truth for both the program model and the runtime
    channel implementation."""
    if buffer_size is None:
        return DEFAULT_BUFFER_PACKETS
    return buffer_size_to_packets(buffer_size, dtype)


@dataclasses.dataclass(frozen=True)
class SmiOperation:
    """One declared communication op at a logical port.

    Subclasses define ``NAME`` (the JSON/manifest tag) and ``STREAMS`` (which
    virtual streams the op occupies — used by the port allocator to spread
    concurrent ops across streams the way the reference round-robins hardware
    ports across its 4 QSFP channels).
    """

    port: int
    dtype: SmiDtype = SmiDtype.FLOAT
    buffer_size: Optional[int] = None  # elements; None = default depth

    NAME: str = dataclasses.field(default="op", init=False, repr=False)
    STREAMS: FrozenSet[str] = dataclasses.field(
        default=frozenset(), init=False, repr=False
    )

    def __post_init__(self):
        if self.port < 0:
            raise ValueError(f"port must be non-negative, got {self.port}")
        object.__setattr__(self, "dtype", SmiDtype.parse(self.dtype))

    @property
    def pipeline_packets(self) -> int:
        """In-flight chunk budget for streamed transfers."""
        return pipeline_depth_packets(self.buffer_size, self.dtype)

    @property
    def elements_per_chunk(self) -> int:
        return elements_per_packet(self.dtype)

    def streams(self, rendezvous: bool = True) -> FrozenSet[str]:
        """Virtual streams this op occupies (``codegen/ops.py:82-92``:
        P2P ops drop their flow-control stream under the eager protocol)."""
        del rendezvous
        return self.STREAMS

    # Identity used for validation: ops conflict if same family+port.
    @property
    def family(self) -> str:
        return self.NAME


@dataclasses.dataclass(frozen=True)
class Push(SmiOperation):
    """P2P send endpoint (``include/smi/push.h``, ``templates/push.cl``)."""

    NAME = "push"
    STREAMS = frozenset({OUT_DATA, IN_CTRL})  # data out, credits back in

    def streams(self, rendezvous: bool = True) -> FrozenSet[str]:
        return self.STREAMS if rendezvous else frozenset({OUT_DATA})


@dataclasses.dataclass(frozen=True)
class Pop(SmiOperation):
    """P2P receive endpoint (``include/smi/pop.h``, ``templates/pop.cl``)."""

    NAME = "pop"
    STREAMS = frozenset({IN_DATA, OUT_CTRL})

    def streams(self, rendezvous: bool = True) -> FrozenSet[str]:
        return self.STREAMS if rendezvous else frozenset({IN_DATA})


@dataclasses.dataclass(frozen=True)
class Broadcast(SmiOperation):
    """One-to-all (``include/smi/bcast.h``, ``templates/bcast.cl``)."""

    NAME = "broadcast"
    STREAMS = frozenset(ALL_STREAM_KEYS)


@dataclasses.dataclass(frozen=True)
class Reduce(SmiOperation):
    """All-to-one reduction (``include/smi/reduce.h``, ``templates/reduce.cl``)."""

    op: SmiOp = SmiOp.ADD
    NAME = "reduce"
    STREAMS = frozenset(ALL_STREAM_KEYS)

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "op", SmiOp.parse(self.op))

    @property
    def accumulation_lanes(self) -> int:
        """Latency-hiding accumulation width.

        The reference masks FP-add pipeline latency with a shift register of
        4 partial accumulators for float/double (``codegen/ops.py:110-141``,
        ``templates/reduce.cl:63-70``). The TPU analog is the unroll width of
        partial accumulators in the Pallas reduction kernels.
        """
        return 4 if self.dtype in (SmiDtype.FLOAT, SmiDtype.DOUBLE) else 1


@dataclasses.dataclass(frozen=True)
class Scatter(SmiOperation):
    """Root distributes contiguous slices (``include/smi/scatter.h``)."""

    NAME = "scatter"
    STREAMS = frozenset(ALL_STREAM_KEYS)


@dataclasses.dataclass(frozen=True)
class Gather(SmiOperation):
    """Root collects contiguous slices (``include/smi/gather.h``)."""

    NAME = "gather"
    STREAMS = frozenset(ALL_STREAM_KEYS)


OP_REGISTRY: Dict[str, Type[SmiOperation]] = {
    cls.NAME: cls for cls in (Push, Pop, Broadcast, Reduce, Scatter, Gather)
}

#: Families whose ports share one namespace: a Push and a Pop at the same
#: port are two ends of one channel and therefore *not* a conflict, but two
#: Pushes at one port are (``codegen/program.py:37-50``).
P2P_FAMILIES = ("push", "pop")
COLLECTIVE_FAMILIES = ("broadcast", "reduce", "scatter", "gather")


def make_operation(name: str, port: int, dtype: Union[str, SmiDtype] = "float",
                   buffer_size: Optional[int] = None, **kwargs) -> SmiOperation:
    """Construct an op by manifest tag (used by serialization + C++ manifest)."""
    try:
        cls = OP_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown operation {name!r}; expected one of {sorted(OP_REGISTRY)}"
        ) from None
    return cls(port=port, dtype=dtype, buffer_size=buffer_size, **kwargs)
