"""Plan-engine tests: the CPU-deterministic tier of the tuning marker.

Covers the ISSUE 4 acceptance surface with no hardware in the loop:

- every seeded cache entry resolves through the engine to the
  measured-best plan (layer ``cache``);
- with the cache removed, the analytic ranking matches the alpha-beta
  prediction — ring wins small payloads, rs+ag wins large — across a
  size sweep x 3 dtypes, flipping exactly once at the crossover;
- the trace-time gate is *conservative*: on an untuned host it agrees
  with the pre-engine heuristic at every payload size (enabling the
  engine cannot move a compiled program);
- plan-cache JSON round-trips, rejects mismatched schema versions
  loudly, and merging prefers the better measured cost;
- ``smi-tpu tune --explain all_reduce`` runs on CPU and prints the
  candidate table naming the deciding layer per knob;
- ``$SMI_TPU_RS_AG_MIN_BYTES`` overrides the switch tier (malformed
  values are loud); trace paths consult injected caches; bench.py's
  additive ``plan`` field keeps the one-line contract.

The measured-sweep smoke runs the real driver at a tiny size on the
CPU fake mesh (the mechanics, not the numbers); wide sweeps belong to
the hardware tier and are marked ``slow``.
"""

import json

import pytest

pytestmark = pytest.mark.tuning

import jax.numpy as jnp  # noqa: E402  (conftest pins the CPU backend)

from smi_tpu.parallel import collectives as C  # noqa: E402
from smi_tpu.parallel.mesh import make_communicator  # noqa: E402
from smi_tpu.tuning import (  # noqa: E402
    CacheEntry,
    PlanCache,
    PlanCacheError,
    PlanEngine,
    PlanKey,
    seeded_cache,
)
from smi_tpu.tuning import cost_model as cm  # noqa: E402
from smi_tpu.tuning import engine as eng  # noqa: E402
from smi_tpu.tuning.plan import (  # noqa: E402
    normalize_device_kind,
    payload_bucket,
)
from smi_tpu.tuning.seeded import SEEDED_DEVICE_KIND  # noqa: E402


@pytest.fixture
def fresh_engine():
    """Restore the process-global engine after a test installs one."""
    saved = eng.get_engine()
    yield
    eng.set_engine(saved)


# ---------------------------------------------------------------------------
# Seeded cache -> engine returns the measured-best plan
# ---------------------------------------------------------------------------


def test_seeded_entries_resolve_to_measured_best():
    e = PlanEngine(cache=seeded_cache(), device_kind=SEEDED_DEVICE_KIND)
    assert e.flash_blocks("bfloat16", windowed=False) == (
        1024, 1024, "cache"
    )
    assert e.flash_blocks("bfloat16", windowed=True) == (
        1024, 512, "cache"
    )
    assert e.flash_blocks("float32", windowed=False) == (
        512, 512, "cache"
    )
    assert e.stencil_depth(8192) == (16, "cache")
    assert e.rs_ag_threshold() == (C.RS_AG_MIN_BYTES, "cache")


def test_every_seeded_entry_is_reachable_through_the_engine():
    """No orphan seeds: each shipped entry must be the value some
    engine query actually returns (else a future key-schema change
    could silently strand the measured optima)."""
    cache = seeded_cache()
    e = PlanEngine(cache=cache, device_kind=SEEDED_DEVICE_KIND)
    for sig, entry in cache.entries.items():
        key = PlanKey.from_signature(sig)
        if key.op == "flash_fwd":
            got = e.flash_blocks(key.dtype, key.detail == "window")
            assert got is not None, sig
            assert (got[0], got[1]) == (
                entry.knobs["block_q"], entry.knobs["block_k"]
            ), sig
            assert got[2] == "cache"
        elif key.op == "stencil_temporal":
            assert e.stencil_depth(int(key.detail), key.dtype) == (
                entry.knobs["depth"], "cache"
            ), sig
        elif key.op == "stencil_pipeline":
            got = e.stencil_pipeline_knobs(int(key.detail), key.dtype)
            assert got is not None, sig
            assert got[0] == dict(entry.knobs), sig
            assert got[1] == "cache", sig
        elif key.op == "all_reduce" and key.detail == "threshold":
            assert e.rs_ag_threshold() == (
                entry.knobs["rs_ag_min_bytes"], "cache"
            ), sig
        else:  # pragma: no cover - fails on unknown seed shapes
            pytest.fail(f"seeded entry {sig} has no engine query")


def test_normalized_device_kinds_agree():
    # PERF.json's device string and jax's device_kind key identically
    assert normalize_device_kind("TPU v5 lite0") == SEEDED_DEVICE_KIND
    assert normalize_device_kind("TPU v5 lite") == SEEDED_DEVICE_KIND
    assert normalize_device_kind(None) == "unknown"


def test_seeded_entries_never_hit_on_other_device_kinds():
    e = PlanEngine(cache=seeded_cache(), device_kind="cpu")
    assert e.flash_blocks("bfloat16", windowed=False) is None
    assert e.stencil_depth(8192) == (None, "heuristic")
    assert e.rs_ag_threshold() == (C.RS_AG_MIN_BYTES, "heuristic")


# ---------------------------------------------------------------------------
# Analytic model: the alpha-beta ranking (cache removed)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype,itemsize", [
    ("float32", 4), ("bfloat16", 2), ("int32", 4),
])
def test_model_ranking_matches_alpha_beta_prediction(dtype, itemsize):
    """Ring wins small payloads, rs+ag wins large, across a size sweep
    — and the preference flips exactly once, at the model's crossover."""
    e = PlanEngine(cache=PlanCache(), device_kind="cpu")
    topo = cm.TopologySpec(n=8)
    xover = cm.rs_ag_crossover_bytes(8)
    choices = []
    for k in range(8, 27):
        elems = 2 ** k
        payload = elems * itemsize
        plan = e.allreduce_plan(payload, topo, dtype)
        assert plan.decided_by["algorithm"] == "model"
        want = "rs_ag" if payload > xover else "ring"
        assert plan.knobs["algorithm"] == want, f"payload {payload}"
        # the winning candidate leads the ranked table with the
        # smaller modeled cost
        assert plan.candidates[0].knobs["algorithm"] == want
        assert (plan.candidates[0].modeled_us
                <= plan.candidates[1].modeled_us)
        choices.append(want)
    assert "ring" in choices and "rs_ag" in choices
    flip = choices.index("rs_ag")
    assert all(c == "ring" for c in choices[:flip])
    assert all(c == "rs_ag" for c in choices[flip:])


def test_crossover_is_calibrated_to_the_measured_switch():
    """DEFAULT_ALPHA_S is not arbitrary: the 8-rank crossover must sit
    on the HLO-verified 1 MiB tier (within 10%), and a 2-ring must
    never prefer the decomposition."""
    xover = cm.rs_ag_crossover_bytes(8)
    assert abs(xover - C.RS_AG_MIN_BYTES) / C.RS_AG_MIN_BYTES < 0.1
    assert cm.rs_ag_crossover_bytes(2) == float("inf")


def test_link_constants_match_the_traffic_model():
    from smi_tpu.parallel import traffic

    assert cm.V5E_ICI_BETA_BYTES_PER_S == traffic.V5E_ICI_LINK_BYTES_PER_S


def test_hierarchical_candidate_on_two_tier_meshes():
    topo = cm.TopologySpec(n=16, inner=8, outer=2)
    cands = cm.allreduce_candidates(256 << 20, topo)
    names = [c.name for c in cands]
    assert "hierarchical" in names
    # at a quarter-GiB payload the DCN-crossing-once shape must beat
    # the flat ring (the reference's route-inside-the-node economics)
    assert names.index("hierarchical") < names.index("ring")


def test_kernel_roofline_from_cost_facts():
    # pure HBM-bound: one second of traffic at the v5e rate
    assert cm.kernel_roofline_us(0, cm.V5E_HBM_BYTES_PER_S) == (
        pytest.approx(1e6)
    )
    # pure compute-bound at bf16 peak
    assert cm.kernel_roofline_us(
        cm.V5E_PEAK_FLOPS["bfloat16"], 0, "bfloat16"
    ) == pytest.approx(1e6)
    assert cm.kernel_roofline_us(None, None) is None


def test_flash_candidates_are_vmem_gated():
    cands = cm.flash_block_candidates(8192, 128, "bfloat16", False)
    assert all(
        cm.flash_fwd_vmem_bytes(
            c.knobs["block_q"], c.knobs["block_k"], 128, 2
        ) <= cm.VMEM_LIMIT_BYTES
        for c in cands
    )
    # an absurd head_dim excludes every wide tile rather than ranking it
    assert cm.flash_block_candidates(
        8192, 8192, "float32", False
    ) == []


# ---------------------------------------------------------------------------
# Trace-time conservatism: the engine cannot move an untuned program
# ---------------------------------------------------------------------------


def test_untuned_gate_agrees_with_the_heuristic_everywhere():
    e = PlanEngine(cache=PlanCache(), device_kind="cpu")
    topo = cm.TopologySpec(n=8)
    for k in range(6, 28):
        payload = 2 ** k
        got, layer = e.use_rs_ag(payload, topo, "float32")
        assert got == (payload >= C.RS_AG_MIN_BYTES), f"payload {payload}"
        assert layer in ("model", "heuristic")


def test_env_threshold_outranks_even_a_measured_cache_entry():
    """The explicit override decides ALONE: an operator pinning the
    bit-exact single-psum form must win over a swept rs_ag entry."""
    cache = PlanCache()
    cache.put(
        PlanKey("all_reduce", payload_bucket(1 << 30), "float32",
                "cpu", "n8"),
        CacheEntry({"algorithm": "rs_ag", "chunks": 1}, cost_us=1.0,
                   provenance="sweep:test"),
    )
    e = PlanEngine(cache=cache, device_kind="cpu")
    got, layer = e.use_rs_ag(1 << 30, cm.TopologySpec(n=8), "float32",
                             threshold=1 << 31)
    assert got is False and layer == "env"
    # without the override, the measured entry decides
    assert e.use_rs_ag(1 << 30, cm.TopologySpec(n=8), "float32") == (
        True, "cache"
    )


def test_value_junk_flash_entry_falls_back_to_heuristics(fresh_engine):
    """A schema-valid entry with untileable knob values must cost
    tuning, not the trace: flash_blocks rejects it and the dtype
    constants apply."""
    from smi_tpu.kernels import flash as F

    for junk in ({"block_q": 7, "block_k": 512},
                 {"block_q": 512, "block_k": 0},
                 {"block_q": "big", "block_k": 512},
                 {"block_q": True, "block_k": 512}):
        cache = PlanCache()
        cache.put(PlanKey("flash_fwd", "causal", "float32", "cpu",
                          "chip"),
                  CacheEntry(dict(junk), cost_us=1.0))
        e = PlanEngine(cache=cache, device_kind="cpu")
        assert e.flash_blocks("float32", False) is None, junk
        eng.set_engine(e)
        assert F._fwd_block_targets(jnp.float32, None) == (512, 512)


def test_sweep_threshold_is_the_smallest_winning_payload(monkeypatch):
    """An unsorted --sizes-kb grid must still distill min(payload where
    rs+ag won), not the first iteration's payload."""
    from smi_tpu.tuning import sweep as S

    calls = {"i": 0}

    def fake_measure(make_fn, x, runs):
        # per size the driver times ring first, rs_ag second — make
        # the second (rs_ag) always measure faster
        calls["i"] += 1
        return 2.0 if calls["i"] % 2 else 1.0
    monkeypatch.setattr(S, "_measure", fake_measure)
    comm = make_communicator()
    cache = S.sweep_allreduce(comm, sizes_kb=[64, 4],
                              chunk_candidates=[1], runs=1)
    thr = cache.lookup(
        PlanKey("all_reduce", "threshold", "", "cpu", "any")
    )
    assert thr is not None
    # 4 KiB, not the first-iterated 64 KiB
    assert thr.knobs["rs_ag_min_bytes"] == 4 * 1024
    for sig, entry in cache.entries.items():
        if sig.startswith("all_reduce|pow2:"):
            assert entry.knobs["algorithm"] == "rs_ag"


def test_cache_entry_decides_the_gate():
    cache = PlanCache()
    key = PlanKey("all_reduce", payload_bucket(5 << 20), "float32",
                  "cpu", "n8")
    cache.put(key, CacheEntry({"algorithm": "ring", "chunks": 1},
                              cost_us=10.0, provenance="sweep:test"))
    e = PlanEngine(cache=cache, device_kind="cpu")
    # 5 MiB would switch by size; the measured entry overrides
    assert e.use_rs_ag(5 << 20, cm.TopologySpec(n=8), "float32") == (
        False, "cache"
    )


# ---------------------------------------------------------------------------
# Persistent cache: round-trip, loud schema rejection, best-cost merge
# ---------------------------------------------------------------------------


def test_cache_json_round_trips_to_identical_plans(tmp_path):
    cache = seeded_cache()
    cache.put(
        PlanKey("all_reduce", "pow2:22", "float32", "cpu", "n8"),
        CacheEntry({"algorithm": "rs_ag", "chunks": 2}, cost_us=123.4,
                   provenance="sweep:test"),
    )
    path = str(tmp_path / "plans.json")
    cache.save(path)
    loaded = PlanCache.load(path)
    assert loaded.to_json() == cache.to_json()
    # identical plans through the engine, not just identical JSON
    e1 = PlanEngine(cache=cache, device_kind=SEEDED_DEVICE_KIND)
    e2 = PlanEngine(cache=loaded, device_kind=SEEDED_DEVICE_KIND)
    assert (e1.flash_blocks("bfloat16", False)
            == e2.flash_blocks("bfloat16", False))
    assert (e1.use_rs_ag(5 << 20, cm.TopologySpec(n=8), "float32")
            == e2.use_rs_ag(5 << 20, cm.TopologySpec(n=8), "float32"))


def test_schema_version_mismatch_is_loud(tmp_path):
    payload = seeded_cache().to_json()
    payload["schema_version"] = 99
    path = tmp_path / "bad.json"
    path.write_text(json.dumps(payload))
    with pytest.raises(PlanCacheError, match="schema_version"):
        PlanCache.load(str(path))
    with pytest.raises(PlanCacheError, match="schema_version"):
        PlanCache.from_json({"entries": {}})


def test_malformed_entries_and_keys_are_loud(tmp_path):
    with pytest.raises(PlanCacheError, match="knobs"):
        PlanCache.from_json({
            "schema_version": 1,
            "entries": {"a|b|c|d|e": {"cost_us": 1.0}},
        })
    with pytest.raises((PlanCacheError, ValueError), match="signature"):
        PlanCache.from_json({
            "schema_version": 1,
            "entries": {"not-a-key": {"knobs": {}}},
        })
    bad = tmp_path / "junk.json"
    bad.write_text("{nope")
    with pytest.raises(PlanCacheError, match="JSON"):
        PlanCache.load(str(bad))


def test_merge_prefers_the_better_measured_cost():
    key = PlanKey("all_reduce", "pow2:20", "float32", "cpu", "n8")
    slow = CacheEntry({"algorithm": "ring"}, cost_us=100.0)
    fast = CacheEntry({"algorithm": "rs_ag"}, cost_us=50.0)
    unmeasured = CacheEntry({"algorithm": "ring"})

    a = PlanCache()
    a.put(key, slow)
    a.merge(_single(key, fast))
    assert a.lookup(key).knobs["algorithm"] == "rs_ag"

    b = PlanCache()
    b.put(key, fast)
    b.merge(_single(key, slow))   # worse incoming entry loses
    assert b.lookup(key).cost_us == 50.0

    c = PlanCache()
    c.put(key, unmeasured)
    c.merge(_single(key, slow))   # measured beats unmeasured
    assert c.lookup(key).cost_us == 100.0
    c.merge(_single(key, unmeasured))  # and survives a later unmeasured
    assert c.lookup(key).cost_us == 100.0


def _single(key, entry):
    cache = PlanCache()
    cache.put(key, entry)
    return cache


# ---------------------------------------------------------------------------
# Env override of the rs+ag switch tier (satellite)
# ---------------------------------------------------------------------------


def test_rs_ag_env_override(monkeypatch):
    monkeypatch.delenv(C.RS_AG_ENV, raising=False)
    assert C.rs_ag_min_bytes() == C.RS_AG_MIN_BYTES
    monkeypatch.setenv(C.RS_AG_ENV, "4096")
    assert C.rs_ag_min_bytes() == 4096
    monkeypatch.setenv(C.RS_AG_ENV, "  1048576 ")
    assert C.rs_ag_min_bytes() == 1 << 20


@pytest.mark.parametrize("bad", ["garbage", "-5", "1.5"])
def test_rs_ag_env_malformed_is_loud(monkeypatch, bad):
    monkeypatch.setenv(C.RS_AG_ENV, bad)
    with pytest.raises(ValueError, match=C.RS_AG_ENV):
        C.rs_ag_min_bytes()


def test_env_threshold_moves_the_trace_time_switch(monkeypatch):
    from smi_tpu.ops.types import SmiOp

    comm = make_communicator()
    x = jnp.ones((64, 16), jnp.float32)  # 4 KiB, rs+ag-eligible
    monkeypatch.delenv(C.RS_AG_ENV, raising=False)
    assert C._use_rs_ag(x, comm, SmiOp.ADD, None) is False
    monkeypatch.setenv(C.RS_AG_ENV, "1024")
    assert C._use_rs_ag(x, comm, SmiOp.ADD, None) is True
    # the loud rejection on ineligible payloads is untouched
    with pytest.raises(ValueError, match="rs_ag=True"):
        C._use_rs_ag(jnp.float32(1.0), comm, SmiOp.ADD, True)


# ---------------------------------------------------------------------------
# Trace-path consultation (flash tiles, collective/ring chunks)
# ---------------------------------------------------------------------------


def test_flash_targets_follow_an_injected_cache(fresh_engine):
    from smi_tpu.kernels import flash as F

    cache = PlanCache()
    cache.put(
        PlanKey("flash_fwd", "causal", "float32", "cpu", "chip"),
        CacheEntry({"block_q": 256, "block_k": 256}, cost_us=1.0,
                   provenance="sweep:test"),
    )
    eng.set_engine(PlanEngine(cache=cache, device_kind="cpu"))
    assert F._fwd_block_targets(jnp.float32, None) == (256, 256)
    eng.set_engine(PlanEngine(cache=PlanCache(), device_kind="cpu"))
    # no entry: the dtype heuristics, byte-for-byte
    assert F._fwd_block_targets(jnp.float32, None) == (512, 512)
    assert F._fwd_block_targets(jnp.bfloat16, None) == (1024, 1024)
    assert F._fwd_block_targets(jnp.bfloat16, 4096) == (1024, 512)


def test_collective_chunks_follow_the_cache(fresh_engine):
    comm = make_communicator()
    x = jnp.ones((64, 16), jnp.float32)        # 4 KiB -> pow2:12
    assert C._resolve_chunks(None, x, comm, "all_reduce") == 1
    assert C._resolve_chunks(4, x, comm, "all_reduce") == 4
    with pytest.raises(ValueError):
        C._resolve_chunks(0, x, comm, "all_reduce")
    with pytest.raises(TypeError):
        C._resolve_chunks(True, x, comm, "all_reduce")
    cache = PlanCache()
    cache.put(
        PlanKey("all_reduce", payload_bucket(64 * 16 * 4), "float32",
                "cpu", "n8"),
        CacheEntry({"algorithm": "ring", "chunks": 3}, cost_us=5.0,
                   provenance="sweep:test"),
    )
    eng.set_engine(PlanEngine(cache=cache, device_kind="cpu"))
    assert C._resolve_chunks(None, x, comm, "all_reduce") == 3
    # an explicit chunks=1 still means ONE collective, not "ask"
    assert C._resolve_chunks(1, x, comm, "all_reduce") == 1


def test_ring_chunks_follow_the_cache(fresh_engine):
    from smi_tpu.kernels.ring import _planned_ring_chunks

    x = jnp.ones((16, 128), jnp.float32)
    assert _planned_ring_chunks(x, 4) == 1
    cache = PlanCache()
    cache.put(
        PlanKey("ring_all_reduce", payload_bucket(16 * 128 * 4),
                "float32", "cpu", "n4"),
        CacheEntry({"chunks": 2}, cost_us=5.0, provenance="sweep:test"),
    )
    eng.set_engine(PlanEngine(cache=cache, device_kind="cpu"))
    assert _planned_ring_chunks(x, 4) == 2


# ---------------------------------------------------------------------------
# CLI + explain surfaces
# ---------------------------------------------------------------------------


def test_cli_tune_explain_all_reduce_runs_on_cpu(capsys):
    from smi_tpu.__main__ import main

    assert main(["tune", "--explain", "all_reduce"]) == 0
    out = capsys.readouterr().out
    assert "ring" in out and "rs_ag" in out
    assert "modeled_us" in out and "measured_us" in out
    # the deciding layer is named per knob
    assert "[model]" in out or "[cache]" in out
    assert "[heuristic]" in out
    assert "rs_ag_min_bytes" in out and "chunks" in out


def test_cli_tune_explain_unknown_op_fails_loudly(capsys):
    from smi_tpu.__main__ import main

    assert main(["tune", "--explain", "bogus"]) == 2
    assert "unknown op" in capsys.readouterr().err


def test_plan_explain_api_names_layers():
    e = PlanEngine(cache=seeded_cache(), device_kind=SEEDED_DEVICE_KIND)
    plan = e.flash_plan(dtype="bfloat16", windowed=False)
    text = plan.explain()
    assert "block_q = 1024" in text and "[cache]" in text
    assert plan.source == "cache"
    # an untuned decision reads as model/heuristic, never cache
    plan2 = PlanEngine(
        cache=PlanCache(), device_kind="cpu"
    ).allreduce_plan(4096, cm.TopologySpec(n=8))
    assert plan2.source == "model"
    assert "[model]" in plan2.explain()


def test_context_explain_plan_scopes_to_the_communicator():
    from smi_tpu.parallel.context import SmiContext

    comm = make_communicator()
    text = SmiContext(comm=comm).explain_plan("all_reduce")
    assert f"n={comm.size}" in text
    assert "ring" in text and "rs_ag" in text


# ---------------------------------------------------------------------------
# Measured sweep (smoke: the mechanics on the CPU fake mesh)
# ---------------------------------------------------------------------------


def test_sweep_allreduce_smoke_writes_a_mergeable_cache(tmp_path):
    from smi_tpu.tuning.sweep import sweep_allreduce

    comm = make_communicator()
    cache = sweep_allreduce(comm, sizes_kb=[4], chunk_candidates=[1],
                            runs=1)
    sigs = [s for s in cache.entries if s.startswith("all_reduce|pow2:")]
    assert sigs, cache.entries
    entry = cache.entries[sigs[0]]
    assert entry.knobs["algorithm"] in ("ring", "rs_ag")
    assert entry.cost_us is not None and entry.cost_us > 0
    assert entry.provenance.startswith("sweep:allreduce")
    # the measured entry is keyed by the MEASURED device kind: a CPU
    # sweep must never shadow a v5e seed
    key = PlanKey.from_signature(sigs[0])
    assert key.device_kind == normalize_device_kind("cpu")
    path = str(tmp_path / "plans.json")
    cache.save(path)
    assert PlanCache.load(path).to_json() == cache.to_json()


@pytest.mark.slow
def test_sweep_allreduce_full_grid(tmp_path):
    """The hardware-shaped sweep (multiple sizes x chunk candidates) —
    slow tier: minutes of compile+measure even on the fake mesh."""
    from smi_tpu.tuning.sweep import sweep_allreduce

    comm = make_communicator()
    cache = sweep_allreduce(comm, sizes_kb=[4, 64],
                            chunk_candidates=[1, 2], runs=2)
    assert len([s for s in cache.entries
                if s.startswith("all_reduce|pow2:")]) == 2


# ---------------------------------------------------------------------------
# bench.py additive plan field (satellite)
# ---------------------------------------------------------------------------


def test_bench_line_with_plan_field_stays_single_line():
    import bench

    payload = {
        "metric": "m", "value": 1, "unit": "u", "vs_baseline": 1,
        "plan": {"stencil_depth": {"value": 16, "source": "cache"},
                 "device_kind": "tpu v5 lite"},
    }
    line = bench.render_line(payload)
    assert "\n" not in line
    assert json.loads(line)["plan"]["stencil_depth"]["source"] == "cache"
    # legacy keys stay mandatory with the new field present
    with pytest.raises(ValueError, match="legacy key"):
        bench.render_line({"metric": "m", "value": 1, "unit": "u",
                           "plan": {}})


def test_bench_plan_fields_never_claim_false_cache_provenance():
    import bench

    fields = bench.plan_fields(16)
    assert fields["stencil_depth"]["value"] == 16
    # this host is not the seeded device kind: the knob matches the
    # seeded VALUE but must not claim cache provenance
    assert fields["stencil_depth"]["source"] == "heuristic"
    assert "device_kind" in fields
