"""Double-buffered HBM->VMEM stencil pipeline with explicit DMA semaphores.

The r18 roofline-closure tier. The temporal kernel
(:mod:`smi_tpu.kernels.stencil_temporal`) streams stripes through the
implicit BlockSpec pipeline: Mosaic owns the fetch schedule and the
halo rows ride two extra VMEM operands stitched in per grid step. This
module takes the fetch schedule back, the exact shape of SNIPPETS.md
[1] (``pltpu.SemaphoreType.DMA`` scratch under ``shard_map``): the
block lives in HBM (``memory_space=ANY``), a three-slot VMEM rotation
carries the stripes, and every move is an explicit
``pltpu.make_async_copy`` against a DMA-semaphore slot —

    fetch stripe i+1 -> slot (i+1)%3     (starts before compute)
    compute stripe i  in slot  i%3       (k trapezoid sweeps, in place)
    write back i-1 from slot (i-1)%3     (landed two steps later)

so the stripe fetch, the k-sweep compute, and the writeback of the
previous stripe are in flight *simultaneously*, and the halo refresh is
fused into the same pipeline: the corner-complete halo rows are
prepended/appended to the extended state ONCE per pass, after which
every stripe DMA carries its own ``k``-row aprons — there is no
separate halo-application pass and no extra VMEM operand.

Knobs (all priced in ``tuning/cost_model.stencil_pipeline_candidates``
and swept by ``tuning/sweep.sweep_stencil``):

- ``depth``    — sweeps per HBM pass (8..32; beyond the temporal
  tier's 16, because overlap changes the knee — see
  docs/perf_notes.md "Roofline closure (r18)");
- ``stripe``   — rows per DMA chunk (the stripe-width sweep);
- ``compute_dtype`` — ``float32`` (bit-identical to the reference
  Jacobi step) or ``bfloat16`` (neighbour values rounded to bf16, the
  4-point average accumulated in f32 — the property-bounded-error
  contract, tests/test_stencil_pipeline.py);
- ``buffering`` — 3 (the pipeline) or 1 (the synchronous control the
  sweep and the perf decomposer compare against; never shipped).

VMEM cost is ``buffering * (stripe + 2*depth) * (w + 256) * 4`` bytes
(the working buffers are always f32 — bf16 exists only inside the
sweep arithmetic). The mirror lives in
``cost_model.stencil_pipeline_vmem_bytes`` and is drift-guarded.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from smi_tpu.parallel.halo import (
    halo_exchange_2d_corners_finish,
    halo_exchange_2d_corners_start,
)
from smi_tpu.parallel.mesh import Communicator
from smi_tpu.kernels.stencil_temporal import LANE_PAD, _sweep_trapezoid

#: Slot count of the shipped rotation: fetch / compute / writeback each
#: own one buffer generation. 1 is the synchronous control path.
PIPELINE_SLOTS = 3

#: VMEM budget the stripe picker plans against — the full Mosaic
#: scoped-VMEM frame, because the pipeline's three slots ARE the
#: buffering (there is no hidden BlockSpec double-buffer on top).
#: MUST equal ``cost_model.VMEM_LIMIT_BYTES`` (drift-guarded).
PIPELINE_VMEM_BYTES = 16 * 1024 * 1024

#: Compute dtypes the sweep arithmetic accepts.
COMPUTE_DTYPES = ("float32", "bfloat16")


def pipeline_vmem_bytes(stripe: int, w: int, depth: int,
                        buffering: int = PIPELINE_SLOTS) -> int:
    """VMEM footprint of the slot rotation (buffers are always f32)."""
    return buffering * (stripe + 2 * depth) * (w + 2 * LANE_PAD) * 4


def pick_pipeline_stripe_explained(
    h: int, w: int, depth: int, buffering: int = PIPELINE_SLOTS,
) -> Tuple[Optional[int], str]:
    """``(stripe, note)``: the tallest feasible stripe, or ``(None,
    reason)`` naming exactly why the shape falls back to the unfused
    path — the no-silent-caps contract ``tune --explain stencil``
    prints (the r18 small-fix: the legacy pickers returned a bare
    ``None``)."""
    if depth < 1 or depth % 8 or depth > LANE_PAD:
        return None, (
            f"depth {depth} outside the sublane-aligned range "
            f"8..{LANE_PAD} (must be a multiple of 8)"
        )
    if w % 128:
        return None, (
            f"w={w} is not lane-aligned (128) — the extended layout "
            f"cannot pad it; falls back to the unfused jnp path"
        )
    best = None
    for t in range(h, 7, -1):
        if h % t or t % 8 or t < depth:
            continue
        if pipeline_vmem_bytes(t, w, depth, buffering) <= PIPELINE_VMEM_BYTES:
            best = t
            break
    if best is None:
        floor = pipeline_vmem_bytes(8, w, depth, buffering)
        return None, (
            f"no 8-aligned stripe divides h={h} within the "
            f"{PIPELINE_VMEM_BYTES // 1024} KiB VMEM frame at "
            f"depth {depth} ({buffering} slots; even an 8-row stripe "
            f"needs {floor // 1024} KiB) — falls back to the "
            f"unfused path"
        )
    return best, f"stripe {best} ({buffering} slots)"


def _pick_pipeline_stripe(h: int, w: int, depth: int,
                          buffering: int = PIPELINE_SLOTS) -> Optional[int]:
    return pick_pipeline_stripe_explained(h, w, depth, buffering)[0]


def pipeline_supported(
    h: int, w: int, dtype, depth: int,
    stripe: Optional[int] = None,
    compute_dtype: str = "float32",
    buffering: int = PIPELINE_SLOTS,
) -> bool:
    """True when the explicit-DMA pipeline can run this block shape."""
    if dtype != jnp.float32 or compute_dtype not in COMPUTE_DTYPES:
        return False
    if buffering not in (1, PIPELINE_SLOTS):
        return False
    if stripe is not None:
        return (
            depth >= 1 and depth % 8 == 0 and depth <= LANE_PAD
            and w % 128 == 0
            and h % stripe == 0 and stripe % 8 == 0 and stripe >= depth
            and pipeline_vmem_bytes(stripe, w, depth, buffering)
            <= PIPELINE_VMEM_BYTES
        )
    return _pick_pipeline_stripe(h, w, depth, buffering) is not None


def _sweep_trapezoid_mixed(val, boundary, t: int, k: int, lane_w: int,
                           compute_dtype: str):
    """The k-sweep trapezoid with the bf16-compute/f32-accumulate
    variant.

    ``float32`` delegates to the temporal tier's
    :func:`_sweep_trapezoid` UNCHANGED — the f32 path is bit-identical
    to the reference Jacobi step by construction, not by tolerance.

    ``bfloat16`` rounds the neighbour values to bf16 before the four
    rolls (the traffic the crossbar would carry on hardware) and
    accumulates the 4-point average in f32: the state array stays f32
    across sweeps, so error is one bf16 input-rounding per neighbour
    per sweep — the property-bounded contract the tests pin.
    """
    if compute_dtype == "float32":
        return _sweep_trapezoid(val, boundary, t, k, lane_w)
    off = 0
    R = t + 2 * k
    for s in range(k):
        lo = 8 * (s // 8)
        if lo > off:
            d = lo - off
            val = val[d : val.shape[0] - d, :]
            off = lo
        rows = R - 2 * off
        vb = val.astype(jnp.bfloat16)
        # same sublane-first association as the f32 tier; each rolled
        # bf16 operand widens back to f32 before it joins the sum
        avg = 0.25 * (
            pltpu.roll(vb, 1, axis=0).astype(jnp.float32)
            + pltpu.roll(vb, rows - 1, axis=0).astype(jnp.float32)
            + pltpu.roll(vb, 1, axis=1).astype(jnp.float32)
            + pltpu.roll(vb, lane_w - 1, axis=1).astype(jnp.float32)
        )
        val = jnp.where(boundary[off : R - off, :], val, avg)
    return val, off


def _pipeline_kernel(
    offs_ref,   # scalar prefetch: [row0, col0] of this block
    x_ref,      # (H + 2k, W+256) extended state + fused halo rows, ANY
    o_ref,      # (H, W+256) output, ANY
    buf_ref,    # scratch: (slots, stripe + 2k, W+256) VMEM rotation
    in_sems,    # scratch: DMA((slots,)) fetch semaphores
    out_sems,   # scratch: DMA((slots,)) writeback semaphores
    *,
    tile: int,
    width: int,  # W (unpadded)
    depth: int,
    gh: int,
    gw: int,
    compute_dtype: str,
    buffering: int,
):
    t, k = tile, depth
    wp = width + 2 * LANE_PAD
    h = o_ref.shape[0]
    n = h // t  # stripe count (static)

    def fetch(i, slot):
        # stripe i's interior plus both k-row aprons in ONE copy: the
        # halo refresh is fused into the stripe stream (rows [i*t,
        # i*t + t + 2k) of the (H+2k)-row extended array)
        return pltpu.make_async_copy(
            x_ref.at[pl.ds(i * t, t + 2 * k)],
            buf_ref.at[slot],
            in_sems.at[slot],
        )

    def writeback(i, slot):
        return pltpu.make_async_copy(
            buf_ref.at[slot, pl.ds(k, t)],
            o_ref.at[pl.ds(i * t, t)],
            out_sems.at[slot],
        )

    def compute(i, slot):
        # sweep-invariant Dirichlet masks from global coordinates
        g_row = (
            offs_ref[0] + i * t - k
            + lax.broadcasted_iota(jnp.int32, (t + 2 * k, 1), 0)
        )
        g_col = (
            offs_ref[1] - LANE_PAD
            + lax.broadcasted_iota(jnp.int32, (1, wp), 1)
        )
        boundary = ((g_row == 0) | (g_row == gh - 1)
                    | (g_col == 0) | (g_col == gw - 1))
        val, off = _sweep_trapezoid_mixed(
            buf_ref[slot], boundary, t, k, wp, compute_dtype
        )
        # in-place: the slot's interior rows become the output stripe
        buf_ref[slot, pl.ds(k, t)] = val[k - off : t + k - off, :]

    if buffering == 1:
        # the synchronous control path: every stage serializes
        def sync_body(i, carry):
            fetch(i, 0).start()
            fetch(i, 0).wait()
            compute(i, 0)
            writeback(i, 0).start()
            writeback(i, 0).wait()
            return carry

        lax.fori_loop(0, n, sync_body, 0)
        return

    slots = PIPELINE_SLOTS
    fetch(0, 0).start()

    def body(i, carry):
        slot = lax.rem(i, slots)
        nxt = lax.rem(i + 1, slots)

        @pl.when(i + 1 < n)
        def _prefetch():
            # slot `nxt` last held stripe i-2; its writeback must have
            # landed before the fetch overwrites it
            @pl.when(i + 1 >= slots)
            def _reclaim():
                writeback(i - 2, nxt).wait()

            fetch(i + 1, nxt).start()

        fetch(i, slot).wait()
        compute(i, slot)
        writeback(i, slot).start()
        return carry

    lax.fori_loop(0, n, body, 0)
    # drain: the last min(3, n) writebacks never had a reclaiming fetch
    for j in range(max(0, n - slots), n):
        writeback(j, j % slots).wait()


def _pipeline_pass_ext(
    xext: jax.Array,
    comm: Communicator,
    gh: int,
    gw: int,
    depth: int,
    stripe: Optional[int],
    compute_dtype: str,
    buffering: int,
    interpret: bool,
) -> jax.Array:
    """One k-sweep explicit-DMA pass over the extended state (H, W+256)."""
    row_axis, col_axis = comm.axis_names
    h, wp = xext.shape
    w = wp - 2 * LANE_PAD
    k = depth
    t = stripe if stripe is not None else _pick_pipeline_stripe(
        h, w, k, buffering
    )
    if t is None or not pipeline_supported(
        h, w, xext.dtype, k, stripe=t, compute_dtype=compute_dtype,
        buffering=buffering,
    ):
        if stripe is not None:
            note = (
                f"requested stripe {stripe} is not an 8-aligned "
                f"divisor of h={h} that is >= depth {k} and fits the "
                f"{PIPELINE_VMEM_BYTES // 1024} KiB VMEM frame"
            )
        else:
            _, note = pick_pipeline_stripe_explained(h, w, k, buffering)
        raise ValueError(
            f"stencil pipeline unsupported for block ({h}, {w}) at "
            f"depth {k}: {note}"
        )

    # --- corner-complete halo refresh, fused into the stripe stream ---
    # Identical split form to the temporal tier: the column updates
    # consume only phase-1 slabs while the vertical ppermutes fly. The
    # received rows then become the FIRST and LAST k rows of the
    # extended array, so every stripe DMA carries its own aprons.
    exchange = halo_exchange_2d_corners_start(
        xext[:, LANE_PAD : LANE_PAD + w], comm, depth=k
    )
    xext = lax.dynamic_update_slice(xext, exchange.left,
                                    (0, LANE_PAD - k))
    xext = lax.dynamic_update_slice(xext, exchange.right,
                                    (0, LANE_PAD + w))
    zrow = jnp.zeros((k, LANE_PAD - k), xext.dtype)
    rx = lax.axis_index(row_axis)
    cy = lax.axis_index(col_axis)
    offs = jnp.stack([rx * h, cy * w]).astype(jnp.int32)
    halos = halo_exchange_2d_corners_finish(exchange)
    top_ext = jnp.concatenate([zrow, halos.top, zrow], axis=1)
    bottom_ext = jnp.concatenate([zrow, halos.bottom, zrow], axis=1)
    xfull = jnp.concatenate([top_ext, xext, bottom_ext], axis=0)

    kernel = functools.partial(
        _pipeline_kernel, tile=t, width=w, depth=k, gh=gh, gw=gw,
        compute_dtype=compute_dtype, buffering=buffering,
    )
    slots = 1 if buffering == 1 else PIPELINE_SLOTS
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((slots, t + 2 * k, wp), jnp.float32),
            # the explicit DMA semaphores (SNIPPETS.md [1] shape): one
            # slot per in-flight fetch and per in-flight writeback
            pltpu.SemaphoreType.DMA((slots,)),
            pltpu.SemaphoreType.DMA((slots,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, wp), xext.dtype),
        interpret=interpret,
    )(offs, xfull)


def pipeline_pass(
    block: jax.Array,
    comm: Communicator,
    gh: int,
    gw: int,
    depth: int = 8,
    stripe: Optional[int] = None,
    compute_dtype: str = "float32",
    buffering: int = PIPELINE_SLOTS,
    interpret: bool = False,
) -> jax.Array:
    """``depth`` fused sweeps over a plain ``(H, W)`` block, one
    explicit-DMA pipeline pass."""
    h, w = block.shape
    zcols = jnp.zeros((h, LANE_PAD), block.dtype)
    xext = jnp.concatenate([zcols, block, zcols], axis=1)
    out = _pipeline_pass_ext(
        xext, comm, gh, gw, depth, stripe, compute_dtype, buffering,
        interpret,
    )
    return out[:, LANE_PAD : LANE_PAD + w]


def make_pipeline_stencil_fn(
    comm: Communicator,
    iterations: int,
    gh: int,
    gw: int,
    depth: int = 8,
    stripe: Optional[int] = None,
    compute_dtype: str = "float32",
    buffering: int = PIPELINE_SLOTS,
    interpret: bool = False,
):
    """Jitted distributed stencil on the explicit-DMA pipeline.

    Same contract as ``make_temporal_stencil_fn``: the state stays in
    extended layout across the ``iterations // depth`` full passes (one
    kernel read + one write per pass), and the remainder runs on the
    single-sweep fused kernel (or the jnp sweep where unsupported).
    """
    from jax.sharding import PartitionSpec as P

    from smi_tpu.kernels import stencil as kstencil
    from smi_tpu.models.stencil import jacobi_step_block

    row_axis, col_axis = comm.axis_names
    spec = P(row_axis, col_axis)
    full, rem = divmod(iterations, depth)

    def shard_fn(block):
        h, w = block.shape
        b = block
        if full:
            zcols = jnp.zeros((h, LANE_PAD), block.dtype)
            xe = jnp.concatenate([zcols, block, zcols], axis=1)
            xe = lax.fori_loop(
                0,
                full,
                lambda _, x: _pipeline_pass_ext(
                    x, comm, gh, gw, depth, stripe, compute_dtype,
                    buffering, interpret,
                ),
                xe,
            )
            b = xe[:, LANE_PAD : LANE_PAD + w]
        if rem and kstencil.pallas_supported(h, w, block.dtype):
            b = lax.fori_loop(
                0,
                rem,
                lambda _, x: kstencil.jacobi_step_block_fused(
                    x, comm, gh, gw, interpret=interpret
                ),
                b,
            )
        elif rem:
            b = lax.fori_loop(
                0, rem, lambda _, x: jacobi_step_block(x, comm), b
            )
        return b

    return jax.jit(
        jax.shard_map(
            shard_fn, mesh=comm.mesh, in_specs=spec, out_specs=spec,
            check_vma=False,
        )
    )
