"""Observability tier: event schema, flight recorder, metrics,
Perfetto export, error tails, sample sink.

Four claims, each pinned:

- **determinism** — same seed ⇒ byte-identical event stream, metrics
  snapshot, and exported trace file;
- **exactness** — every exported trace's per-rank span tiling ends at
  the rank clock bit-identically, its makespan equals
  ``RingSimulator.elapsed_seconds()`` bit-identically, and its
  component attribution matches the PR 11 decomposer — over the FULL
  registered-protocol grid;
- **no silent caps** — ring-buffer overflow is counted
  (``dropped_events``) in every snapshot and tail;
- **one bookkeeping** — the metrics registry's admitted/shed/delivered
  counters equal the campaign gate's own accounting on a seeded
  chaos-under-load run.
"""

import copy
import json
import pickle

import pytest

from smi_tpu.obs.events import (
    DEFAULT_RECORDER_CAPACITY,
    DEFAULT_TAIL_EVENTS,
    EVENT_KINDS,
    FlightRecorder,
    format_tail,
)
from smi_tpu.obs.metrics import MetricsRegistry, SampleSink, payload_bucket
from smi_tpu.obs.trace import (
    trace_all,
    trace_name,
    trace_protocol,
    trace_to_json_bytes,
    validate_chrome_trace,
)
from smi_tpu.analysis.verifier import DEFAULT_SHAPES
from smi_tpu.parallel import credits as C
from smi_tpu.parallel import faults as F

pytestmark = pytest.mark.obs

GRID = [
    (protocol, shape)
    for protocol, shapes in DEFAULT_SHAPES.items()
    for shape in shapes
]


def _grid_id(case):
    protocol, shape = case
    return protocol + "," + ",".join(
        f"{k}={v}" for k, v in sorted(shape.items())
    )


# ---------------------------------------------------------------------------
# Event schema + flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_schema_is_well_formed(self):
        for kind, (plane, fields) in EVENT_KINDS.items():
            assert plane in ("sim", "serving", "control", "tuning",
                             "slo")
            assert isinstance(fields, tuple)

    def test_unknown_kind_is_loud(self):
        rec = FlightRecorder()
        with pytest.raises(ValueError, match="unknown event kind"):
            rec.emit("serve.frobnicate", 0)

    def test_missing_required_field_is_loud(self):
        rec = FlightRecorder()
        with pytest.raises(ValueError, match="missing required field"):
            rec.emit("serve.shed", 0, tenant="t0", qos="batch")

    def test_extra_fields_ride_along(self):
        rec = FlightRecorder()
        e = rec.emit("credit.grant", 3, rank=0, src=0, dst=1, index=1,
                     mult=2)
        assert e.to_json()["mult"] == 2

    def test_reserved_envelope_keys_cannot_be_shadowed(self):
        """A field named like an envelope key would clobber the causal
        emission counter in ``to_json`` — rejected at the source (the
        reason chunk sequence numbers travel as ``chunk``)."""
        rec = FlightRecorder()
        with pytest.raises(ValueError, match="reserved envelope"):
            rec.emit("serve.shed", 0, tenant="t", qos="batch",
                     reason="r", seq=5)
        # and to_json keeps the emission counter authoritative
        e = rec.emit("serve.send", 1, rank=0, tenant="t", qos="batch",
                     chunk=0, dst=0)
        assert e.to_json()["seq"] == 0 and e.to_json()["chunk"] == 0

    def test_ring_bound_counts_drops(self):
        rec = FlightRecorder(capacity=4)
        for i in range(10):
            rec.emit("barrier.wait", i, rank=0)
        assert rec.total_events == 10
        assert rec.dropped_events == 6
        snap = rec.snapshot()
        assert snap["dropped_events"] == 6  # never silent
        assert len(snap["events"]) == 4
        assert snap["counts"] == {"barrier.wait": 10}

    def test_tail_is_bounded_and_honest(self):
        rec = FlightRecorder(capacity=100)
        for i in range(60):
            rec.emit("barrier.wait", i, rank=0)
        tail = rec.tail()
        assert len(tail["events"]) == DEFAULT_TAIL_EVENTS
        assert tail["dropped_events"] == 0
        assert tail["omitted"] == 60 - DEFAULT_TAIL_EVENTS
        assert tail["events"][-1]["seq"] == 59
        assert "barrier.wait" in format_tail(tail)

    def test_capacity_must_be_positive(self):
        with pytest.raises(ValueError):
            FlightRecorder(capacity=0)

    def test_default_capacity_is_documented_value(self):
        assert FlightRecorder().capacity == DEFAULT_RECORDER_CAPACITY


# ---------------------------------------------------------------------------
# Simulator events: determinism + error tails
# ---------------------------------------------------------------------------


class TestSimulatorEvents:
    def _stream(self, seed=0):
        rec = FlightRecorder(capacity=10_000)
        C.simulate_all_reduce(4, C.Strategy(seed), recorder=rec)
        return [e.to_json() for e in rec.events()]

    def test_same_seed_identical_event_stream(self):
        assert self._stream(7) == self._stream(7)

    def test_different_seed_different_schedule(self):
        # the event stream reflects the schedule: distinct seeds must
        # be distinguishable (else the stream carries no ordering)
        assert self._stream(0) != self._stream(1)

    def test_sim_plane_kinds_cover_the_primitives(self):
        rec = FlightRecorder(capacity=10_000)
        C.simulate_all_reduce(3, C.Strategy(0), recorder=rec)
        assert set(rec.counts) == {
            "credit.grant", "credit.wait", "dma.start", "dma.land",
            "barrier.signal", "barrier.wait",
        }
        # one landing per start, schedule-independent
        assert rec.counts["dma.start"] == rec.counts["dma.land"]

    def test_deadlock_carries_the_tail(self):
        plan = F.FaultPlan(dropped_grants=(F.DroppedGrant(0, 0),))
        rec = FlightRecorder(capacity=8)
        with pytest.raises(C.DeadlockError) as info:
            C.simulate_all_reduce(3, C.Strategy(0), faults=plan,
                                  recorder=rec)
        e = info.value
        assert e.recorder_tail["events"]
        assert e.recorder_tail["dropped_events"] > 0  # ring wrapped
        assert "flight_recorder" in e.state
        # the formatted dump renders the history
        assert "flight recorder" in str(e)

    def test_integrity_error_carries_the_tail(self):
        plan = F.FaultPlan(bit_flips=(F.BitFlipPayload(0, 0),))
        verdict = F.run_under_faults(
            "all_reduce", 3, plan, recorder=FlightRecorder()
        )
        assert verdict.kind == "detected"
        assert isinstance(verdict.error, C.IntegrityError)
        assert verdict.error.recorder_tail["events"]

    def test_no_recorder_is_the_default_and_free(self):
        sim = C.RingSimulator(
            C.all_to_all_generators(3), C.Strategy(0)
        )
        sim.run()
        assert sim.recorder is None
        assert "flight_recorder" not in sim.state_dump()


# ---------------------------------------------------------------------------
# Metrics registry + sample sink
# ---------------------------------------------------------------------------


class TestMetrics:
    def test_snapshot_is_sorted_and_deterministic(self):
        def build():
            m = MetricsRegistry()
            m.counter("b", x=2).inc(3)
            m.counter("a").inc()
            m.gauge("g").set(5)
            m.gauge("g").set(2)
            m.histogram("h", qos="batch").observe(3)
            return json.dumps(m.snapshot(), sort_keys=True)

        assert build() == build()
        snap = json.loads(build())
        assert snap["counters"] == {"a": 1, "b{x=2}": 3}
        assert snap["gauges"]["g"] == {"value": 2, "max": 5}
        assert snap["histograms"]["h{qos=batch}"]["count"] == 1

    def test_type_confusion_is_loud(self):
        m = MetricsRegistry()
        m.counter("n").inc()
        with pytest.raises(TypeError, match="is a Counter"):
            m.gauge("n")

    def test_histogram_overflow_is_explicit(self):
        m = MetricsRegistry()
        h = m.histogram("h")
        h.observe(2.0 ** 40)  # beyond the fixed bucket grid
        assert h.to_json()["overflow"] == 1

    def test_sample_sink_aggregates_per_key(self):
        s = SampleSink()
        s.record("allreduce", 1e-3, payload_bytes=900_000, tenant="t0")
        s.record("allreduce", 3e-3, payload_bytes=1_000_000,
                 tenant="t0")
        s.record("allreduce", 2e-3, payload_bytes=5_000_000,
                 tenant="t0")
        entries = s.entries()
        assert len(entries) == 2  # two payload buckets
        first = entries[0]
        assert first["knobs"]["payload_bucket_bytes"] == payload_bucket(
            1_000_000
        )
        assert first["knobs"]["samples"] == 2
        assert first["cost_us"] == pytest.approx(2000.0)

    def test_sample_sink_entries_load_as_plan_cache_entries(self):
        """The ROADMAP-3 contract: a sink aggregate IS a plan-cache
        entry — `CacheEntry.from_json` must accept it unchanged."""
        from smi_tpu.tuning.cache import CacheEntry

        s = SampleSink()
        s.record("flash_fwd", 5e-4, payload_bytes=1 << 20)
        entry = CacheEntry.from_json("probe", s.entries()[0])
        assert entry.cost_us == pytest.approx(500.0)
        assert entry.provenance == "obs:sample_sink"

    def test_negative_sample_is_loud(self):
        with pytest.raises(ValueError):
            SampleSink().record("op", -1.0)


class TestTimedSink:
    def test_timed_records_into_a_sample_sink(self):
        from smi_tpu.utils.tracing import timed

        sink = SampleSink()
        result, seconds = timed(
            lambda: 42, sink=sink, op="probe",
            payload_bytes=2048, tenant="t1",
        )
        assert result == 42
        assert len(sink) == 1
        entry = sink.entries()[0]
        assert entry["knobs"]["op"] == "probe"
        assert entry["knobs"]["tenant"] == "t1"
        assert entry["knobs"]["payload_bucket_bytes"] == 2048
        assert entry["cost_us"] == pytest.approx(seconds * 1e6)

    def test_timed_accepts_a_plain_callable(self):
        from smi_tpu.utils.tracing import timed

        seen = []
        timed(lambda: 1, sink=lambda op, s: seen.append((op, s)))
        assert seen and seen[0][0] == "timed"

    def test_timed_without_sink_is_unchanged(self):
        from smi_tpu.utils.tracing import timed

        result, seconds = timed(lambda: "x")
        assert result == "x" and seconds >= 0.0


# ---------------------------------------------------------------------------
# Perfetto export: schema, determinism, exactness over the full grid
# ---------------------------------------------------------------------------


class TestTraceExport:
    def test_export_validates_against_the_pinned_schema(self):
        validate_chrome_trace(trace_protocol("all_reduce", 3))

    def test_schema_validator_rejects_drift(self):
        payload = trace_protocol("all_reduce", 2)
        payload["otherData"]["schema_version"] = 999
        with pytest.raises(ValueError, match="schema_version"):
            validate_chrome_trace(payload)
        broken = trace_protocol("all_reduce", 2)
        broken["traceEvents"][len(broken["traceEvents"]) - 1].pop("cat")
        with pytest.raises(ValueError, match="missing"):
            validate_chrome_trace(broken)

    def test_same_seed_byte_identical_file(self):
        a = trace_to_json_bytes(trace_protocol("allreduce_pod", 4,
                                               slices=2, seed=3))
        b = trace_to_json_bytes(trace_protocol("allreduce_pod", 4,
                                               slices=2, seed=3))
        assert a == b

    def test_trace_all_covers_the_registry_and_names_are_unique(self):
        traces = trace_all()
        assert len(traces) == len(GRID)
        names = [trace_name(t) for t in traces]
        assert len(set(names)) == len(names)
        for t in traces:
            validate_chrome_trace(t)

    def test_unknown_protocol_is_loud(self):
        with pytest.raises(ValueError, match="unknown protocol"):
            trace_all(["nope"])

    @pytest.mark.parametrize("case", GRID, ids=_grid_id)
    def test_span_sums_bit_identical_to_elapsed_seconds(self, case):
        """The acceptance criterion, over the FULL registered grid:
        per-rank span tiling ends at the rank clock, the makespan
        equals ``elapsed_seconds()``, and the component attribution
        matches the PR 11 decomposer — all compared exactly, no
        tolerance anywhere."""
        from smi_tpu.analysis.perf import decompose_protocol

        protocol, shape = case
        payload = trace_protocol(protocol, **shape)
        other = payload["otherData"]
        # trace-internal exactness (the exporter asserts it too —
        # re-derived here so a weakened exporter assert can't hide)
        assert other["span_makespan_us"] == other["makespan_us"]
        for row in other["per_rank"]:
            assert row["span_end_us"] == row["clock_us"]
        # the decomposer and the exporter price the same run: same
        # makespan bit-identically, same per-rank component split
        report = decompose_protocol(protocol, **shape, verify=False)
        assert report.makespan_s * 1e6 == other["makespan_us"]
        for row, dec_row in zip(other["per_rank"], report.per_rank):
            assert row["components_us"] == dec_row["components_us"]

    def test_pod_vector_renders_the_committed_makespan(self):
        """The 2x2 4 MiB two-tier pod trace must carry the committed
        1197.3 us acceptance vector (ANALYTIC_EXPECTED_US) as its
        makespan — the exporter and the analytic expectation table
        describe the same simulator."""
        from smi_tpu.analysis.perf import ANALYTIC_EXPECTED_US

        payload = trace_protocol("allreduce_pod", 4, slices=2)
        assert round(payload["otherData"]["makespan_us"], 1) == \
            ANALYTIC_EXPECTED_US["pod_allreduce_two_tier_2x2_4mib_us"]

    def test_spans_are_contiguous_and_component_labeled(self):
        payload = trace_protocol("all_reduce", 3)
        per_tid = {}
        for e in payload["traceEvents"]:
            if e["ph"] == "X":
                per_tid.setdefault(e["tid"], []).append(e)
        assert per_tid
        for tid, events in per_tid.items():
            t = 0.0
            for e in events:
                assert e["ts"] == t  # boundaries tile exactly
                t = e["ts"] + e["dur"]
                assert e["cat"] in ("alpha", "beta", "serialization",
                                    "idle")


# ---------------------------------------------------------------------------
# Serving + control plane: one bookkeeping, deterministic snapshots
# ---------------------------------------------------------------------------


@pytest.mark.serving
class TestServingObservability:
    def test_metrics_snapshot_equals_campaign_bookkeeping(self):
        """The acceptance criterion: a seeded chaos-under-load cell's
        metrics snapshot must agree with the campaign gate's own
        accounting — counter for counter."""
        from smi_tpu.serving.campaign import run_load_cell
        from smi_tpu.serving.qos import QOS_CLASSES

        rep = run_load_cell(n=4, seed=11, duration=160, overload=2.0)
        assert rep["ok"], rep["verdict"]
        counters = rep["metrics"]["counters"]
        for qos in QOS_CLASSES:
            assert counters.get(f"admitted_total{{qos={qos}}}", 0) \
                == rep["accepted"][qos]
            assert counters.get(f"delivered_total{{qos={qos}}}", 0) \
                == rep["delivered"][qos]
            for reason, count in rep["shed"][qos].items():
                key = f"shed_total{{qos={qos},reason={reason}}}"
                assert counters.get(key, 0) == count
        # and nothing in the registry claims sheds the gate never saw
        metric_shed = sum(
            v for k, v in counters.items()
            if k.startswith("shed_total{")
        )
        assert metric_shed == sum(
            sum(rep["shed"][q].values()) for q in QOS_CLASSES
        )

    def test_snapshot_and_event_stream_deterministic_per_seed(self):
        from smi_tpu.serving.campaign import run_load_cell

        a = run_load_cell(n=4, seed=5, duration=120, overload=2.0)
        b = run_load_cell(n=4, seed=5, duration=120, overload=2.0)
        assert json.dumps(a["metrics"], sort_keys=True) == \
            json.dumps(b["metrics"], sort_keys=True)
        assert a["obs"] == b["obs"]
        assert a["obs"]["dropped_events"] == (
            a["obs"]["total_events"]
            - min(a["obs"]["total_events"],
                  a["obs"]["recorder_capacity"])
        )

    def test_kill_cell_emits_control_plane_events(self):
        from smi_tpu.serving.campaign import run_load_cell

        rep = run_load_cell(n=4, seed=1, duration=240, kill_rank=2,
                            kill_at=60)
        assert rep["ok"], rep["verdict"]
        counts = rep["obs"]["event_counts"]
        assert counts.get("ctl.suspect", 0) >= 1
        assert counts.get("ctl.confirm", 0) == 1
        assert counts.get("ctl.shrink", 0) == 1
        assert counts.get("serve.replay", 0) >= 1
        counters = rep["metrics"]["counters"]
        assert counters.get("epoch_bumps_total{reason=shrink}") == 1

    def test_admission_rejected_carries_the_tail(self):
        from smi_tpu.serving.frontend import ServingFrontend
        from smi_tpu.serving.qos import AdmissionRejected

        fe = ServingFrontend(4, seed=0, tenant_rate=0.25,
                             tenant_burst=1.0)
        fe.submit("t0", "batch", ("c0",))
        with pytest.raises(AdmissionRejected) as info:
            fe.submit("t0", "batch", ("c1",))  # bucket empty
        e = info.value
        assert e.reason == "tenant-rate"
        assert e.recorder_tail is not None
        assert e.recorder_tail["events"]
        # the tail survives the copy/pickle paths the model checker
        # and campaign reports exercise
        assert copy.copy(e).recorder_tail == e.recorder_tail
        assert pickle.loads(pickle.dumps(e)).recorder_tail \
            == e.recorder_tail

    def test_integrity_error_tail_at_the_serving_tier(self):
        import dataclasses as dc

        from smi_tpu.parallel.credits import IntegrityError, make_frame
        from smi_tpu.parallel.recovery import ProgressLog
        from smi_tpu.serving.scheduler import (
            StreamState,
            WireLane,
            _InFlight,
            verify_chunk,
        )
        from smi_tpu.serving.qos import Request
        from smi_tpu.utils.watchdog import Deadline

        st = StreamState(
            request=Request("t0", "batch", ("payload",), 0),
            index=0, dst=1, deadline=Deadline(None),
            wal=ProgressLog(0),
        )
        frame = dc.replace(make_frame(0, 0, "payload"),
                           payload="tampered")
        item = _InFlight(ready_at=0, stream=st, seq=0, frame=frame)
        rec = FlightRecorder()
        rec.emit("serve.send", 0, rank=1, tenant="t0", qos="batch",
                 chunk=0, dst=1)
        lane = WireLane(1)
        with pytest.raises(IntegrityError) as info:
            verify_chunk(lane, item, recorder=rec)
        assert info.value.kind == "checksum"
        assert info.value.recorder_tail["events"]

    def test_watchdog_timeout_carries_the_tail(self):
        from smi_tpu.utils.watchdog import Deadline, WatchdogTimeout

        rec = FlightRecorder()
        rec.emit("ctl.confirm", 0, rank=1)
        deadline = Deadline(0.0, recorder=rec)
        with pytest.raises(WatchdogTimeout) as info:
            deadline.check("probe")
        assert info.value.recorder_tail["events"]
        # with_provider keeps the recorder (the front-end swaps dump
        # providers per check without restarting the budget)
        with pytest.raises(WatchdogTimeout) as info2:
            deadline.with_provider(lambda: "dump").check("probe")
        assert info2.value.recorder_tail["events"]

    def test_membership_view_emits_epoch_events(self):
        from smi_tpu.parallel.membership import MembershipView

        rec = FlightRecorder()
        view = MembershipView(4).attach_recorder(rec)
        view.confirm_dead(1)
        view.regrow(1)
        kinds = [e.kind for e in rec.events()]
        assert kinds == ["ctl.shrink", "ctl.regrow"]
        assert [e.to_json()["epoch"] for e in rec.events()] == [1, 2]

    def test_recovery_emits_recover_events(self):
        from smi_tpu.parallel.recovery import run_with_recovery

        rec = FlightRecorder(capacity=4096)
        out = run_with_recovery(
            "all_reduce", 4,
            F.FaultPlan(stalled_ranks=(F.StalledRank(2, 5),)),
            recorder=rec,
        )
        assert out.recovered
        recovers = [e for e in rec.events()
                    if e.kind == "ctl.recover"]
        assert recovers, "recovery never emitted its transitions"
        fields = dict(recovers[0].fields)
        assert fields["protocol"] == "all_reduce"


# ---------------------------------------------------------------------------
# bench.py additive obs field
# ---------------------------------------------------------------------------


def test_bench_obs_field_schema_and_legacy_contract():
    import bench

    fields = bench.obs_fields()
    assert set(fields) == {
        "probe", "events", "dropped_events", "recorder_capacity",
        "recorder_overhead_pct",
    }
    assert fields["events"] > 0
    assert fields["recorder_overhead_pct"] >= 0.0
    # additive: the legacy single-line contract is untouched
    line = bench.render_line({
        "metric": "m", "value": 1, "unit": "u", "vs_baseline": 1.0,
        "obs": fields,
    })
    assert json.loads(line)["obs"] == fields
