"""Property-based routing tests: random topologies through the full
table pipeline.

The reference pins routing behaviour with a handful of hand-built
topologies (``codegen/tests/test_routing_table.py``, ported verbatim in
``test_routing.py``); this suite complements them with randomized
coverage: any connected random topology must route all pairs, produce
tables whose every entry is a valid target code, survive the binary
round trip bit-exactly, and agree with ``egress_link_toward`` — and any
disconnected one must fail loudly with ``NoRouteFound``.
"""

import networkx
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from smi_tpu.ops.operations import Pop, Push  # noqa: E402
from smi_tpu.ops.program import Device, Program, ProgramMapping  # noqa: E402
from smi_tpu.ops.serialization import Topology  # noqa: E402
from smi_tpu.parallel.routing import (  # noqa: E402
    EGRESS_LOCAL,
    EGRESS_WIRE,
    LINKS_PER_DEVICE,
    Link,
    NoRouteFound,
    build_routing_context,
    deserialize_table,
    egress_link_toward,
    egress_tables,
    ingress_table,
    serialize_table,
)


def _devices(n):
    # spread devices over nodes of 2, like the reference's
    # SMI_DEVICES_PER_NODE grouping
    return [Device(node=f"N{i // 2}", index=i % 2) for i in range(n)]


@st.composite
def topologies(draw, min_devices=2, max_devices=5):
    """A random topology: some subset of possible (device, link) pairs
    wired together, each physical port used at most once."""
    n = draw(st.integers(min_devices, max_devices))
    devs = _devices(n)
    ports = [(d, li) for d in devs for li in range(LINKS_PER_DEVICE)]
    k = draw(st.integers(1, len(ports) // 2))
    perm = draw(st.permutations(ports))
    conn = {}
    for i in range(k):
        a, b = perm[2 * i], perm[2 * i + 1]
        if a[0] == b[0]:
            continue  # no self-links: ports on one device mesh for free
        conn[a] = b
        conn[b] = a
    program = Program([Push(0), Pop(0)])
    mapping = ProgramMapping(
        programs=[program], device_to_program={d: program for d in devs}
    )
    return Topology(connections=conn, mapping=mapping)


def _is_connected(topo):
    g = networkx.Graph()
    g.add_nodes_from(topo.devices)
    for (a, _), (b, _) in topo.connections.items():
        g.add_edge(a, b)
    return networkx.is_connected(g)


@given(topo=topologies())
@settings(max_examples=60, deadline=None)
def test_random_topology_tables(topo):
    program = topo.mapping.programs[0]
    ctx = build_routing_context(topo)
    n = len(topo.devices)
    if not _is_connected(topo):
        with pytest.raises(NoRouteFound):
            for dev in topo.devices:
                egress_tables(dev, ctx, program)
        return
    for dev in topo.devices:
        tables = egress_tables(dev, ctx, program)
        assert set(tables) == {
            Link(dev, li) for li in range(LINKS_PER_DEVICE)
        }
        for link, table in tables.items():
            # every entry is WIRE, LOCAL, or a valid sibling forward
            for code in table.flat():
                assert code in (EGRESS_WIRE, EGRESS_LOCAL) or (
                    2 <= code < 2 + LINKS_PER_DEVICE - 1
                ), code
            # the binary encoding round-trips bit-exactly
            flat = table.flat()
            assert deserialize_table(serialize_table(flat)) == flat
            ing = ingress_table(link, ctx, program)
            assert (
                deserialize_table(serialize_table(ing.flat()))
                == ing.flat()
            )

        # egress_link_toward (the TPU consumer of the tables) agrees
        # with them: for every remote destination it must name a local
        # link wired to the returned neighbouring device
        for dst in topo.devices:
            if dst == dev:
                continue
            li, peer = egress_link_toward(
                dev, dst, ctx, program, tables=tables
            )
            assert 0 <= li < LINKS_PER_DEVICE
            assert peer != dev
            peer_end = topo.connections.get((dev, li))
            assert peer_end is not None and peer_end[0] == peer


@given(topo=topologies(min_devices=3, max_devices=5))
@settings(max_examples=30, deadline=None)
def test_random_topology_first_hop_progress(topo):
    """Following first hops from any source must reach the destination
    in at most n-1 steps — the tables encode loop-free routes."""
    hypothesis.assume(_is_connected(topo))
    ctx = build_routing_context(topo)
    program = topo.mapping.programs[0]
    devs = topo.devices
    n = len(devs)
    all_tables = {d: egress_tables(d, ctx, program) for d in devs}
    for src in devs:
        for dst in devs:
            if src == dst:
                continue
            cur, hops = src, 0
            while cur != dst:
                _, cur = egress_link_toward(
                    cur, dst, ctx, program, tables=all_tables[cur]
                )
                hops += 1
                assert hops < n, (
                    f"route {src} -> {dst} did not converge"
                )
