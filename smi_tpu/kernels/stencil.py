"""Fused Jacobi sweep: one Pallas pass per iteration.

The jnp sweep in :mod:`smi_tpu.models.stencil` materializes a padded tile
(five ``dynamic_update_slice``s) plus the average and the boundary mask —
roughly seven memory passes per iteration. This kernel does the whole
sweep in a single read + write of the block:

- the block is read stripe-by-stripe with a one-step software pipeline:
  stripe *i* is prefetched while stripe *i-1* (held in VMEM scratch) is
  computed, so each stripe's vertical neighbours are its own rolled rows
  plus one boundary row from the neighbouring stripes (no overlapping
  fetches, all blocks sublane-aligned);
- horizontal neighbours use an in-register ``pltpu.roll`` with the
  neighbour columns patched in from the exchanged halos;
- the Dirichlet boundary mask is computed from global coordinates
  (scalar-prefetched shard offsets) and applied in the same pass.

Halo exchange stays outside the kernel (four masked ``ppermute``s of edge
slabs — O(W) bytes, negligible next to the O(H·W) sweep), mirroring the
reference's split between bridge kernels and the compute pipeline
(``stencil_smi.cl:9-18`` vs ``:236-386``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from smi_tpu.parallel.halo import halo_exchange_2d
from smi_tpu.parallel.mesh import Communicator

#: VMEM budget per stripe buffer; ~6 stripe-sized buffers are live at
#: once (double-buffered in/out, prev scratch), so keep each ≤2.5 MB.
STRIPE_BYTES_TARGET = 2_500_000


def pick_tile_explained(h: int, w: int):
    """``(tile, note)``: the fused kernel's stripe height with its
    reason, or ``(None, reason)`` naming exactly why the shape falls
    back to the unfused path — the r18 no-silent-caps companion of
    :func:`_pick_tile` that ``tune --explain stencil`` renders."""
    limit = max(8, STRIPE_BYTES_TARGET // (w * 4))
    for t in range(min(limit, h), 7, -1):
        if h % t == 0 and t % 8 == 0:
            return t, (f"tile {t}: largest 8-aligned divisor of h={h} "
                       f"inside the {STRIPE_BYTES_TARGET} B stripe "
                       f"budget at w={w}")
    return None, (f"EXCLUDED: no 8-aligned divisor of h={h} at or "
                  f"under {min(limit, h)} rows fits the "
                  f"{STRIPE_BYTES_TARGET} B stripe budget at w={w} — "
                  f"unfused fallback")


def _pick_tile(h: int, w: int) -> Optional[int]:
    """Largest divisor of ``h`` that is a multiple of the f32 sublane
    count (8) and fits the per-stripe VMEM budget."""
    return pick_tile_explained(h, w)[0]


def pallas_supported(h: int, w: int, dtype) -> bool:
    return (
        dtype == jnp.float32
        and w % 128 == 0
        and _pick_tile(h, w) is not None
    )


def _sweep_kernel(
    offs_ref,  # scalar prefetch: [row0, col0] global offsets of this block
    x_ref,     # (TILE, W) current stripe (one ahead of the one computed)
    top_ref,   # (1, W) halo row from the block above
    bottom_ref,  # (1, W) halo row from below
    left_ref,  # (H, 1) halo column from the left
    right_ref,  # (H, 1) halo column from the right
    o_ref,     # (TILE, W) output stripe (for the previous grid step)
    prev_ref,  # scratch: stripe loaded on the previous step
    tail_ref,  # scratch: last row of the stripe before that
    *,
    tile: int,
    width: int,
    gh: int,
    gw: int,
):
    # One-step software pipeline over the grid: at step i we hold stripe
    # i in x_ref and compute stripe j = i-1 from prev_ref, using
    # tail_ref (last row of stripe j-1) and x_ref's first row (first row
    # of stripe j+1) as the vertical boundary neighbours.
    i = pl.program_id(0)
    n = pl.num_programs(0) - 1  # number of stripes
    t, w = tile, width
    cur = x_ref[...]

    @pl.when(i > 0)
    def _compute():
        j = i - 1
        center = prev_ref[...]
        row_ids = lax.broadcasted_iota(jnp.int32, (t, w), 0)
        col_ids = lax.broadcasted_iota(jnp.int32, (t, w), 1)

        up_row = jnp.where(j == 0, top_ref[...], tail_ref[...])  # (1, w)
        up = jnp.where(row_ids == 0, up_row, pltpu.roll(center, 1, axis=0))
        down_row = jnp.where(i == n, bottom_ref[...], cur[0:1, :])
        down = jnp.where(
            row_ids == t - 1, down_row, pltpu.roll(center, t - 1, axis=0)
        )

        # Horizontal neighbours: lane roll + halo column patch.
        left_col = left_ref[pl.ds(j * t, t), :]   # (t, 1)
        right_col = right_ref[pl.ds(j * t, t), :]
        lefts = jnp.where(
            col_ids == 0, left_col, pltpu.roll(center, 1, axis=1)
        )
        rights = jnp.where(
            col_ids == w - 1, right_col, pltpu.roll(center, w - 1, axis=1)
        )

        avg = 0.25 * (up + down + lefts + rights)

        # Dirichlet: cells on the global boundary hold their value.
        g_row = offs_ref[0] + j * t + row_ids
        g_col = offs_ref[1] + col_ids
        boundary = (
            (g_row == 0) | (g_row == gh - 1)
            | (g_col == 0) | (g_col == gw - 1)
        )
        o_ref[...] = jnp.where(boundary, center, avg)

    # Rotate the pipeline registers (order matters: tail first).
    tail_ref[...] = prev_ref[t - 1 : t, :]
    prev_ref[...] = cur


def fused_sweep(
    block: jax.Array,
    top: jax.Array,
    bottom: jax.Array,
    left: jax.Array,
    right: jax.Array,
    row0: jax.Array,
    col0: jax.Array,
    gh: int,
    gw: int,
    interpret: bool = False,
) -> jax.Array:
    """One fused Jacobi sweep over a block given its exchanged halos."""
    h, w = block.shape
    tile = _pick_tile(h, w)
    if tile is None:
        raise ValueError(f"no valid row tile for block {block.shape}")
    n = h // tile

    kernel = functools.partial(
        _sweep_kernel, tile=tile, width=w, gh=gh, gw=gw
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        # one extra step drains the pipeline (stripe j computes at step j+1)
        grid=(n + 1,),
        in_specs=[
            pl.BlockSpec(
                (tile, w),
                lambda i, offs: (jnp.minimum(i, n - 1), 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, w), lambda i, offs: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w), lambda i, offs: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile, w),
            lambda i, offs: (jnp.maximum(i - 1, 0), 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((tile, w), jnp.float32),
            pltpu.VMEM((1, w), jnp.float32),
        ],
    )
    offs = jnp.stack([row0, col0]).astype(jnp.int32)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, w), block.dtype),
        interpret=interpret,
    )(offs, block, top, bottom, left, right)


def jacobi_step_block_fused(
    block: jax.Array,
    comm: Communicator,
    gh: int,
    gw: int,
    interpret: bool = False,
) -> jax.Array:
    """Distributed fused sweep: halo exchange + one-pass kernel."""
    row_axis, col_axis = comm.axis_names
    h, w = block.shape
    halos = halo_exchange_2d(block, comm, depth=1)
    rx = lax.axis_index(row_axis)
    cy = lax.axis_index(col_axis)
    return fused_sweep(
        block,
        halos.top,
        halos.bottom,
        halos.left,
        halos.right,
        rx * h,
        cy * w,
        gh,
        gw,
        interpret=interpret,
    )


def make_fused_stencil_fn(
    comm: Communicator, iterations: int, gh: int, gw: int,
    interpret: bool = False,
):
    """Jitted distributed stencil using the fused kernel per sweep."""
    from jax.sharding import PartitionSpec as P

    row_axis, col_axis = comm.axis_names
    spec = P(row_axis, col_axis)

    def shard_fn(block):
        return lax.fori_loop(
            0,
            iterations,
            lambda _, b: jacobi_step_block_fused(
                b, comm, gh, gw, interpret=interpret
            ),
            block,
        )

    return jax.jit(
        jax.shard_map(
            shard_fn, mesh=comm.mesh, in_specs=spec, out_specs=spec,
            check_vma=False,
        )
    )
