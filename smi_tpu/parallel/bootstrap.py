"""Multi-host control plane: process bootstrap from a hostfile.

Reference parity: MPI is the reference's control plane — process launch
via the generated hostfile (``codegen/common.py:15-19``), rank/size from
``MPI_Comm_rank/size``, host barriers and bulk staging
(``bandwidth_benchmark.cpp:24,142-154``). The data plane (the NoC) never
touches MPI. Here the split is the same: ``jax.distributed`` is the
control plane that assembles one global device pool from many hosts, and
the data plane is XLA collectives over ICI/DCN.

Typical multi-host launch (one process per host, any launcher — the
reference uses ``mpirun``, here anything that sets a process id works)::

    opts = distributed_options("smi-routes/hostfile", process_id=my_id)
    init_distributed(opts)          # jax.distributed.initialize
    comm = make_communicator()      # global mesh over all hosts' chips

The hostfile is the one ``python -m smi_tpu route`` writes: one line per
rank, host node first, ``#`` comments after.
"""

from __future__ import annotations

import dataclasses
import os
import random
import re
import time
from typing import Callable, List, Optional, Union

DEFAULT_COORDINATOR_PORT = 8476

#: Retry/backoff defaults for coordinator connection (see
#: :func:`init_distributed`): total deadline, first backoff, cap, and
#: the ± jitter fraction applied to every sleep.
DEFAULT_INIT_DEADLINE_S = 300.0
DEFAULT_INITIAL_BACKOFF_S = 1.0
DEFAULT_MAX_BACKOFF_S = 30.0
DEFAULT_BACKOFF_JITTER = 0.25


class HostfileError(ValueError):
    """A hostfile failed validation; the message says how to fix it."""


class BootstrapTimeout(TimeoutError):
    """Coordinator connection did not succeed within the deadline."""


_RANK_RE = re.compile(r"\brank\s*(\d+)\s*$")


def parse_hostfile(text: str) -> List[str]:
    """Hostfile lines → ordered node list (one entry per rank).

    Mirrors the writer (``smi_tpu.__main__.write_nodefile``): node name
    first, optional ``# device, rankN`` comment. Validation is strict —
    a malformed hostfile must fail *here*, before a launcher grabs a
    pod and hangs on a bad node list:

    - an empty (or comments-only) file raises :class:`HostfileError`;
    - a node entry containing whitespace (two tokens on one line)
      raises — the writer never emits it, it is a hand-edit gone wrong;
    - when rank comments are present, duplicate or non-contiguous rank
      numbers raise (a duplicated rank would silently double-assign a
      process id).

    CRLF line endings and trailing whitespace are tolerated (hostfiles
    get scp'd through Windows-touched tooling).
    """
    nodes: List[str] = []
    ranks: List[Optional[int]] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line, _, comment = raw.partition("#")
        line = line.strip()  # also eats the \r of CRLF files
        if not line:
            continue
        if len(line.split()) != 1:
            raise HostfileError(
                f"hostfile line {lineno}: expected one node name, got "
                f"{line!r} (one rank per line, node first, '#' comments)"
            )
        match = _RANK_RE.search(comment.strip())
        nodes.append(line)
        ranks.append(int(match.group(1)) if match else None)
    if not nodes:
        raise HostfileError(
            "hostfile lists no nodes (empty or comments-only); expected "
            "one line per rank, e.g. 'node-a  # node-a:0, rank0'"
        )
    annotated = [r for r in ranks if r is not None]
    if annotated:
        dupes = sorted({r for r in annotated if annotated.count(r) > 1})
        if dupes:
            raise HostfileError(
                f"hostfile assigns rank(s) {dupes} more than once; each "
                f"rank comment must be unique"
            )
        # even a partially annotated file must not name impossible
        # ranks (a mangled comment on a hand-edited file). Combined
        # with the duplicate check this also forces fully annotated
        # files to be exactly the contiguous set 0..n-1.
        out_of_range = sorted(r for r in annotated if r >= len(nodes))
        if out_of_range:
            raise HostfileError(
                f"hostfile rank comment(s) {out_of_range} out of range "
                f"for {len(nodes)} listed rank(s); ranks must be "
                f"0..{len(nodes) - 1} — regenerate with "
                f"`python -m smi_tpu route`"
            )
    return nodes


@dataclasses.dataclass(frozen=True)
class DistributedOptions:
    """Arguments for ``jax.distributed.initialize``, derived from the
    hostfile: one JAX process per distinct node, coordinator on the
    first node."""

    coordinator_address: str
    num_processes: int
    process_id: int

    def __post_init__(self):
        if not (0 <= self.process_id < self.num_processes):
            raise ValueError(
                f"process_id {self.process_id} out of range for "
                f"{self.num_processes} processes"
            )


def distributed_options(
    hostfile: Union[str, os.PathLike],
    process_id: Optional[int] = None,
    coordinator_port: int = DEFAULT_COORDINATOR_PORT,
) -> DistributedOptions:
    """Derive the multi-host bootstrap arguments from a hostfile.

    ``hostfile`` is a path or the raw text. Distinct nodes become JAX
    processes in first-appearance order (several ranks/chips on one node
    stay one process, as the reference packs ``SMI_DEVICES_PER_NODE``
    FPGAs per host). ``process_id`` defaults to, in order:
    ``$SMI_PROCESS_ID``, then 0.
    """
    text = hostfile
    if os.path.exists(str(hostfile)):
        with open(hostfile) as f:
            text = f.read()
    nodes = parse_hostfile(str(text))  # raises HostfileError when empty
    distinct = list(dict.fromkeys(nodes))
    if process_id is None:
        process_id = int(os.environ.get("SMI_PROCESS_ID", "0"))
    return DistributedOptions(
        coordinator_address=f"{distinct[0]}:{coordinator_port}",
        num_processes=len(distinct),
        process_id=process_id,
    )


def backoff_schedule(
    initial_backoff_s: float = DEFAULT_INITIAL_BACKOFF_S,
    max_backoff_s: float = DEFAULT_MAX_BACKOFF_S,
    jitter: float = DEFAULT_BACKOFF_JITTER,
    seed: Optional[int] = None,
):
    """Yield sleep durations: exponential growth, capped, ± jitter.

    Jitter decorrelates the retry storms of many hosts restarting at
    once (every rank of a preempted pod reconnects together; without
    jitter they hammer the coordinator in lockstep). ``seed`` makes the
    schedule reproducible for tests; the default seeds from process
    entropy. The generator is infinite — the *caller* owns the total
    deadline.
    """
    rng = random.Random(seed)
    delay = initial_backoff_s
    while True:
        yield max(0.0, delay * (1.0 + jitter * (2.0 * rng.random() - 1.0)))
        delay = min(delay * 2.0, max_backoff_s)


def init_distributed(
    opts: DistributedOptions,
    total_deadline_s: float = DEFAULT_INIT_DEADLINE_S,
    initial_backoff_s: float = DEFAULT_INITIAL_BACKOFF_S,
    max_backoff_s: float = DEFAULT_MAX_BACKOFF_S,
    jitter: float = DEFAULT_BACKOFF_JITTER,
    initialize: Optional[Callable[..., None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    seed: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` with retry, backoff, and a deadline.

    The reference's control plane (mpirun over the hostfile) retries
    connection at the launcher layer; ``jax.distributed.initialize``
    does not — a coordinator that is still booting (or a transiently
    unroutable DCN path) fails the whole job, and a *hung* connect
    stalls it forever. Here every attempt gets a per-attempt timeout
    (the remaining budget), failures back off exponentially with
    jitter (:func:`backoff_schedule`), and the total budget is a hard
    deadline: on expiry a :class:`BootstrapTimeout` names the
    coordinator, the attempt count, and the last error — actionable
    from a launch log.

    Single-process pools (one node) skip initialization entirely — the
    local runtime already owns every chip, and initialize() would block
    waiting for peers. ``initialize``/``sleep``/``clock`` are
    injectable for tests.
    """
    if opts.num_processes <= 1:
        return
    if initialize is None:
        import jax

        initialize = jax.distributed.initialize

    # probe ONCE whether the initializer takes initialization_timeout=
    # (older jax.distributed.initialize does not) — probing per attempt
    # would double every call and make a genuine TypeError from a real
    # bug indistinguishable from the signature gap
    import inspect

    try:
        params = inspect.signature(initialize).parameters
        supports_timeout = "initialization_timeout" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in params.values()
        )
    except (TypeError, ValueError):  # no introspectable signature
        supports_timeout = True

    start = clock()
    attempts = 0
    last_error: Optional[BaseException] = None
    delays = backoff_schedule(
        initial_backoff_s, max_backoff_s, jitter, seed
    )
    while True:
        remaining = total_deadline_s - (clock() - start)
        if remaining <= 0:
            break
        attempts += 1
        kwargs = dict(
            coordinator_address=opts.coordinator_address,
            num_processes=opts.num_processes,
            process_id=opts.process_id,
        )
        if supports_timeout:
            # each attempt gets the REMAINING budget: a hung connect
            # cannot eat more than the total deadline
            kwargs["initialization_timeout"] = max(1, int(remaining))
        try:
            initialize(**kwargs)
            return
        except TypeError as e:
            if supports_timeout and "initialization_timeout" in str(e):
                # signature introspection lied (e.g. a wrapper): drop
                # the kwarg for all further attempts
                supports_timeout = False
                continue
            last_error = e
        except Exception as e:
            last_error = e
        delay = next(delays)
        remaining = total_deadline_s - (clock() - start)
        if remaining <= 0:
            break
        sleep(min(delay, remaining))
    raise BootstrapTimeout(
        f"could not connect to coordinator {opts.coordinator_address} as "
        f"process {opts.process_id}/{opts.num_processes} within "
        f"{total_deadline_s:.3g}s ({attempts} attempts); last error: "
        f"{type(last_error).__name__ if last_error else 'none'}: "
        f"{last_error}"
    )
