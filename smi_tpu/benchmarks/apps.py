"""Application benchmarks: the reference's example workloads, timed.

Reference hosts time their kernels and verify results in the same run
(``examples/host/stencil_smi.cpp:316-340``, ``gesummv_smi.cpp``,
``kmeans_smi.cpp``); these do the same — each measurement verifies the
payload against the serial reference before reporting.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from smi_tpu.benchmarks.stats import Measurement, timed_samples
from smi_tpu.parallel.mesh import Communicator


def _grid_for(comm: Communicator):
    """Factor a 1-axis communicator's devices into a 2-D mesh."""
    from smi_tpu.parallel.mesh import make_communicator

    n = comm.size
    px = max(d for d in range(1, int(n**0.5) + 1) if n % d == 0)
    return make_communicator(
        shape=(px, n // px), axis_names=("sx", "sy"),
        devices=list(comm.mesh.devices.flat),
    )


def bench_stencil(
    comm: Communicator, size: int = 1024, iterations: int = 32,
    runs: int = 5,
) -> Measurement:
    """Distributed Jacobi throughput (cells/s); verified once vs serial."""
    from smi_tpu.kernels import stencil_temporal as kt
    from smi_tpu.models import stencil

    comm2d = _grid_for(comm)
    px, py = comm2d.axis_sizes
    if size % px or size % py:
        raise ValueError(
            f"grid {size}x{size} not divisible by process grid "
            f"({px}, {py}); pick a size divisible by both"
        )
    h, w = size // px, size // py
    depth = kt.pick_temporal_depth(h, w, jnp.float32, iterations)
    if depth is not None:
        fn = kt.make_temporal_stencil_fn(
            comm2d, iterations, size, size, depth=depth
        )
    else:
        fn = stencil.make_stencil_fn(comm2d, iterations)
    g = jnp.asarray(stencil.initial_grid(size, size))

    out = np.asarray(fn(g))
    ref = stencil.reference_stencil(np.asarray(g), iterations)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    samples = timed_samples(lambda: np.asarray(jnp.sum(fn(g))), runs)
    rates = [size * size * iterations / t / 1e9 for t in samples]
    return Measurement(
        "app-stencil", "Gcell/s", rates,
        {"size": size, "iterations": iterations,
         "mesh": f"{px}x{py}"},
    )


def bench_gesummv(
    comm: Communicator, n: int = 1024, runs: int = 5
) -> Measurement:
    """2-rank GESUMMV GFLOP/s (2 matvecs = 4n² flops); verified."""
    from smi_tpu.models import gesummv
    from smi_tpu.parallel.mesh import make_communicator

    devices = list(comm.mesh.devices.flat)
    if len(devices) < 2:
        raise ValueError(
            "app_gesummv is the 2-rank MPMD workload "
            "(gesummv_rank{0,1}.cl); it needs at least 2 devices"
        )
    comm_tp = make_communicator(2, devices=devices[:2])
    rng = np.random.RandomState(0)
    a = rng.rand(n, n).astype(np.float32)
    b = rng.rand(n, n).astype(np.float32)
    x = rng.rand(n).astype(np.float32)
    ab = jnp.stack([jnp.asarray(a), jnp.asarray(b)])
    xj = jnp.asarray(x)
    fn = gesummv.make_gesummv_fn(comm_tp, n, 1.5, 0.5)

    out = np.asarray(fn(ab, xj))
    ref = gesummv.reference_gesummv(a, b, x, 1.5, 0.5)
    np.testing.assert_allclose(out, ref, rtol=2e-3)

    samples = timed_samples(lambda: np.asarray(jnp.sum(fn(ab, xj))), runs)
    rates = [4 * n * n / t / 1e9 for t in samples]
    return Measurement("app-gesummv", "GFLOP/s", rates, {"n": n})


def bench_kmeans(
    comm: Communicator, points: int = 65536, k: int = 8, dims: int = 2,
    iterations: int = 10, runs: int = 5,
) -> Measurement:
    """Data-parallel K-means iteration rate; verified vs serial."""
    from smi_tpu.models import kmeans

    points -= points % comm.size
    rng = np.random.RandomState(0)
    pts = rng.rand(points, dims).astype(np.float32)
    init = pts[:k].copy()
    fn = kmeans.make_kmeans_fn(comm, iterations=iterations)
    pts_j, init_j = jnp.asarray(pts), jnp.asarray(init)

    out = np.asarray(fn(pts_j, init_j))
    ref = kmeans.reference_kmeans(pts, init, iterations)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)

    samples = timed_samples(
        lambda: np.asarray(jnp.sum(fn(pts_j, init_j))), runs
    )
    rates = [points * iterations / t / 1e6 for t in samples]
    return Measurement(
        "app-kmeans", "Mpoint-iters/s", rates,
        {"points": points, "k": k, "dims": dims,
         "iterations": iterations},
    )


def _default_seq_per_rank(comm: Communicator) -> int:
    """1024 on TPU; 128 on CPU meshes, where a quadratic-attention step
    at 8k global tokens runs long enough to trip XLA's 40 s collective
    rendezvous timeout (threads that are still computing look stuck)."""
    return 1024 if comm.is_tpu else 128


def bench_ring_attention(
    comm: Communicator, seq_per_rank: Optional[int] = None, heads: int = 8,
    head_dim: int = 128, runs: int = 5, causal: bool = True,
    precision=None, reps: int = 8, window: Optional[int] = None,
) -> Measurement:
    """Sequence-parallel attention throughput (global tokens/s).

    The long-context workload: each rank holds ``seq_per_rank`` tokens
    and K/V blocks circulate the ring (``models/ring_attention.py``;
    the flash kernel tier on TPU). A sampled subset of query rows is
    verified against the reference before timing (full verification is
    O(S²) host memory, unaffordable at benchmark scale). ``precision``
    defaults to HIGHEST (exactness; tight tolerance); pass
    ``jax.lax.Precision.DEFAULT`` to measure the bf16-operand MXU rate,
    verified at bf16-level tolerance.

    Each timed sample chains ``reps`` attention applications inside one
    jit (output fed back as the next query), so per-dispatch/readback
    latency — ~100 ms on tunneled chips, swamping a single application —
    amortizes out of the reported rate.
    """
    from jax import lax

    from smi_tpu.models import ring_attention as ra

    if precision is None:
        precision = lax.Precision.HIGHEST
    if seq_per_rank is None:
        seq_per_rank = _default_seq_per_rank(comm)
    n = comm.size
    s = n * seq_per_rank
    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(s, heads, head_dim).astype(np.float32))
        for _ in range(3)
    )
    fn = ra.make_ring_attention_fn(
        comm, causal=causal, precision=precision, window=window
    )

    out = np.asarray(fn(q, k, v))
    idx = np.linspace(0, s - 1, num=min(s, 128), dtype=np.int64)
    ref = ra.reference_attention_rows(
        q, k, v, idx, causal=causal, window=window
    )
    tol = 5e-4 if precision == lax.Precision.HIGHEST else 2e-2
    np.testing.assert_allclose(out[idx], ref, rtol=tol, atol=tol)

    chained = ra.make_ring_attention_fn(
        comm, causal=causal, precision=precision, reps=reps, window=window
    )
    samples = timed_samples(
        lambda: np.asarray(jnp.sum(chained(q, k, v))), runs
    )
    rates = [reps * s / t / 1e6 for t in samples]
    return Measurement(
        "app-ring-attention", "Mtoken/s", rates,
        {"seq": s, "seq_per_rank": seq_per_rank, "heads": heads,
         "head_dim": head_dim, "causal": causal, "ranks": n,
         "precision": str(precision), "reps": reps, "window": window},
    )


def bench_ring_attention_train(
    comm: Communicator, seq_per_rank: Optional[int] = None, heads: int = 8,
    head_dim: int = 128, runs: int = 5, causal: bool = True,
    reps: int = 4, window: Optional[int] = None,
) -> Measurement:
    """Training-step throughput: forward + backward tokens/s.

    Exercises the flash tier's custom-VJP backward on TPU (the jnp
    tier's autodiff elsewhere). Gradients are verified against the
    other tier's autodiff before timing; timed samples chain ``reps``
    fwd+bwd pairs inside one jit (gradient of a ``reps``-chained loss),
    amortizing dispatch latency like the forward benchmark.
    """
    import jax

    from smi_tpu.models import ring_attention as ra

    if seq_per_rank is None:
        seq_per_rank = _default_seq_per_rank(comm)
    n = comm.size
    s = n * seq_per_rank
    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(s, heads, head_dim).astype(np.float32))
        for _ in range(3)
    )

    def make_grad(use_flash, reps_):
        fn = ra.make_ring_attention_fn(
            comm, causal=causal, use_flash=use_flash, reps=reps_,
            window=window,
        )
        return jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v) ** 2), argnums=(0, 1, 2)
        ))

    # Verify the custom-VJP backward against jnp-tier autodiff — only
    # where the tiers actually differ (flash auto-dispatches), and at a
    # capped size: autodiff through the jnp tier stores per-step
    # quadratic probability tensors, unaffordable at the long-context
    # sizes this benchmark exists to measure.
    if ra._use_flash_default(
        comm, seq_per_rank, heads, head_dim, q.dtype
    ):
        s_v = n * min(seq_per_rank, 2048)
        args_v = (q[:s_v], k[:s_v], v[:s_v])
        g_auto = make_grad(None, 1)(*args_v)
        g_jnp = make_grad(False, 1)(*args_v)
        for a, b, nm in zip(g_auto, g_jnp, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4,
                err_msg=nm,
            )

    timed = make_grad(None, reps)
    samples = timed_samples(
        lambda: np.asarray(jnp.sum(timed(q, k, v)[0])), runs
    )
    rates = [reps * s / t / 1e6 for t in samples]
    return Measurement(
        "app-ring-attention-train", "Mtoken/s", rates,
        {"seq": s, "seq_per_rank": seq_per_rank, "heads": heads,
         "head_dim": head_dim, "causal": causal, "ranks": n,
         "reps": reps, "window": window},
    )


APP_BENCHMARKS = {
    "app_stencil": bench_stencil,
    "app_gesummv": bench_gesummv,
    "app_kmeans": bench_kmeans,
    "app_ring_attention": bench_ring_attention,
    "app_ring_attention_train": bench_ring_attention_train,
}
