"""Credit flow-control protocol: executable specification + schedule fuzzer.

Reference parity: the SMI NoC is deadlock- and clobber-free because every
writer holds *credits* for the receiver's buffer space — P2P rendezvous
tokens (``templates/push.cl:21-31``, replenished by ``pop.cl:35-51``) and
the collectives' explicit credit windows (``reduce.cl:13-32``). The
emulator's strict channel-depth model exists to make violations reproduce
(``CMakeLists.txt:188-191``).

The TPU ring kernels (:mod:`smi_tpu.kernels.ring`) use the same idea over
``make_async_remote_copy``: a rank may only RDMA into a neighbour's buffer
slot after the neighbour granted that slot via a remote semaphore signal.
This module is the **protocol specification**, written as per-rank state
machines (Python generators mirroring the kernels' step structure
one-yield-per-primitive) plus a discrete-event simulator that executes
them under arbitrary schedules — random, adversarial, or exhaustive — and
checks the protocol invariants the hardware would punish:

- **no clobber**: a DMA never lands on a slot holding unconsumed data;
- **no deadlock**: some rank or in-flight DMA can always make progress;
- **credit balance**: every semaphore drains to zero at exit (a leaked
  count would poison the next collective reusing the semaphore — Pallas
  TPU interpret mode reports exactly this);
- **correct delivery**: every rank terminates with the right payload.

``tests/test_credits.py`` fuzzes all four ring protocols across sizes and
schedules, and demonstrates that with flow control *disabled* the
simulator catches the clobber — evidence the harness can see the race the
credits exist to prevent.

Fault injection: the simulator optionally executes under a *fault plan*
(:mod:`smi_tpu.parallel.faults`) that drops or duplicates credit grants,
delays DMA completions, crash-stops ranks, and takes links down — the
unhealthy schedules the reference's strict-depth emulator cannot
express. The plan is consulted through a narrow hook interface
(``grant_multiplier`` / ``dma_hold`` / ``stall_after`` / ``link_down``)
so this module never imports the fault layer; with no plan the simulator
behaves bit-identically to the healthy fuzzer. Every deadlock now
carries a per-rank protocol-state dump (:meth:`RingSimulator.state_dump`)
— the same dump the runtime watchdogs attach to timeout errors.

Concurrent composites (the 4-direction ring halo exchange, the
burst-interleaved ``stream_concurrent`` schedule) run SEVERAL kernel
instances per rank; :func:`halo_generators` /
:func:`concurrent_stream_generators` model them with scratch
slots/semaphores shared across sequential instances (reused VMEM
addresses) and the barrier semaphore keyed by the stream's domain
(``collective_id``) — see the section comment below for what aliases
and why. The mutation tests show the fuzzer catches a shared barrier
domain between cross-axis streams (clobber), divergent per-rank
instance order (deadlock — or clobber once a shared domain removes the
loud failure), the pre-fix identity device-id mapping of subset-axis
rings (clobber/deadlock — the round-3 ``_logical_id_fn`` bug), and
surplus credit grants (leak).
"""

from __future__ import annotations

import bisect
import dataclasses
import itertools
import random
import zlib
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Primitive actions yielded by protocol generators
# ---------------------------------------------------------------------------
# ("signal", target_rank, sem_name, index, inc)   remote/local semaphore +=
# ("wait", sem_name, index, amount)               block until local sem >=,
#                                                 then decrement
# ("dma", target_rank, slot, payload, send_index, recv_index)
#                                                 start async copy into the
#                                                 target's buffer slot. The
#                                                 payload is snapshotted and
#                                                 send[send_index] signals
#                                                 IMMEDIATELY (hardware only
#                                                 promises the source buffer
#                                                 is reusable — the data may
#                                                 still be in flight); the
#                                                 copy lands when the
#                                                 scheduler picks it, then
#                                                 signals recv[recv_index] at
#                                                 the target. In-flight copies
#                                                 may land in ANY order —
#                                                 the credit protocol, not
#                                                 the wire, must prevent
#                                                 overtaking writes.
# ("read_slot", slot)                             -> payload (marks the slot
#                                                 consumed)
# ("write_slot", slot, payload)                   local slot init
# ("output", key, payload)                        record a result

SEM_SEND = "send"
SEM_RECV = "recv"
SEM_CREDIT = "credit"
SEM_BARRIER = "barrier"

# ---------------------------------------------------------------------------
# Protocol registries — the ONE source of truth
# ---------------------------------------------------------------------------
# Every consumer that enumerates "the registered protocols" — the fault
# matrix (`faults.run_under_faults`), the static verifier
# (`analysis/verifier.py`), the perf decomposer (`analysis/perf.py`),
# and the `route --check --lint` launch gate — reads these tuples (the
# fault layer re-exports them under its historical names). Keeping the
# definitions HERE, next to the state machines they name, means a new
# protocol family registers once and every tier follows; the
# seed-pinned chaos sweep stays byte-stable because PROTOCOLS itself is
# digest-tested (tests/test_alltoall.py) and the newer families live in
# their own tuples, never appended to it.

#: The four base ring protocols — the seed-pinned chaos sweep's draw
#: set. NEVER extend this tuple: a fifth name would silently re-roll
#: every pinned campaign cell (add a new registry tuple instead).
PROTOCOLS = ("all_gather", "all_reduce", "reduce_scatter",
             "neighbour_stream")

#: Pipelined variants runnable through the fault harness but NOT in the
#: seed-pinned base sweep.
CHUNKED_PROTOCOLS = ("all_reduce_chunked",)

#: The two-tier pod composition, same discipline.
POD_PROTOCOLS = ("allreduce_pod",)

#: The all-to-all family (sparse, data-dependent traffic): the pairwise
#: exchange reference, the Bruck-style log-step variant (power-of-two
#: ranks only — a non-power-of-two request fails loudly), and the
#: two-tier ICI x DCN variant. Same seed-pinning discipline: its own
#: tuple, never folded into PROTOCOLS.
ALLTOALL_PROTOCOLS = ("all_to_all", "all_to_all_bruck", "all_to_all_pod")

#: The compressed-wire allreduce family (r19): the quantized two-tier
#: composition (``all_reduce_quantized`` — the pod state machine with a
#: wire codec applied at the boundary; the reduced int8/bf16 byte width
#: lives in the :class:`TierCostModel`'s per-tier message sizing, never
#: in the state machine) and the top-k sparse variant
#: (``all_reduce_sparse`` — opaque (index, value) bundles gathered
#: around the ring and reduced locally). Same seed-pinning discipline:
#: its own tuple, never folded into PROTOCOLS.
QUANTIZED_PROTOCOLS = ("all_reduce_quantized", "all_reduce_sparse")


def all_protocol_registries() -> Dict[str, Tuple[str, ...]]:
    """Every protocol registry, by name, in declaration order — the
    single enumeration the fault layer, the static verifier, the perf
    decomposer, and the launch gate all derive their coverage from.
    Returned fresh per call (a dict, so a consumer cannot mutate the
    shared tuples through it); digest-tested so a registry edit is a
    conscious, test-visible act rather than a silent re-roll of the
    seed-pinned chaos sweep."""
    return {
        "PROTOCOLS": PROTOCOLS,
        "CHUNKED_PROTOCOLS": CHUNKED_PROTOCOLS,
        "POD_PROTOCOLS": POD_PROTOCOLS,
        "ALLTOALL_PROTOCOLS": ALLTOALL_PROTOCOLS,
        "QUANTIZED_PROTOCOLS": QUANTIZED_PROTOCOLS,
    }


def registered_protocols() -> Tuple[str, ...]:
    """The flattened registry: every protocol every analysis tier must
    cover, in registry declaration order."""
    out: Tuple[str, ...] = ()
    for names in all_protocol_registries().values():
        out += names
    return out


class ProtocolError(AssertionError):
    """A protocol invariant was violated under some schedule."""


class ClobberError(ProtocolError):
    pass


class DeadlockError(ProtocolError):
    """No entity can make progress.

    ``state`` carries the per-rank protocol-state dump taken at the
    moment of the deadlock (:meth:`RingSimulator.state_dump`), so a
    failure names *where* every rank stood — the same dump the runtime
    watchdogs attach to timeout errors."""

    def __init__(self, message: str, state: Optional[dict] = None):
        super().__init__(message)
        self.state = state


class CreditLeakError(ProtocolError):
    pass


class IntegrityError(ProtocolError):
    """The verified-transport framing caught a corrupted, truncated, or
    missequenced chunk.

    Carries enough to debug the wire: the receiving ``rank``, the
    claimed source ``src``, the frame's sequence number ``seq``, the
    detection ``kind`` (``"checksum"`` or ``"sequence"``), and the
    ``expected`` vs ``got`` values (CRCs for a checksum miss, sequence
    numbers for a reorder). Payload corruption must surface HERE, never
    as silently wrong delivery — the invariant
    :mod:`smi_tpu.parallel.faults` extends its matrix with.
    """

    def __init__(self, message: str, rank: Optional[int] = None,
                 src: Optional[int] = None, seq: Optional[int] = None,
                 expected=None, got=None, kind: Optional[str] = None):
        super().__init__(message)
        self.rank = rank
        self.src = src
        self.seq = seq
        self.expected = expected
        self.got = got
        self.kind = kind


def format_state_dump(state: dict) -> str:
    """Render a :meth:`RingSimulator.state_dump` as indented text."""
    lines = []
    for r in sorted(k for k in state if isinstance(k, int)):
        entry = state[r]
        pending = entry.get("pending")
        desc = entry["state"]
        if pending is not None:
            desc += f" at {pending}"
        lines.append(
            f"  rank {r}: {desc} ({entry['outputs']} outputs)"
        )
    if state.get("inflight"):
        lines.append(f"  in-flight DMAs: {state['inflight']}")
    if state.get("undeliverable"):
        lines.append(
            f"  undeliverable DMAs (down links): {state['undeliverable']}"
        )
    if state.get("sems"):
        lines.append(f"  non-zero semaphores: {state['sems']}")
    fr = state.get("flight_recorder")
    if fr and fr.get("events"):
        lines.append(
            f"  flight recorder (last {len(fr['events'])} of "
            f"{fr['total_events']} events, {fr['dropped_events']} "
            f"dropped by the ring bound):"
        )
        for e in fr["events"]:
            who = f" rank {e['rank']}" if "rank" in e else ""
            detail = " ".join(
                f"{k}={v}" for k, v in sorted(e.items())
                if k not in ("seq", "tick", "plane", "kind", "rank")
            )
            lines.append(
                f"    [{e['seq']}@t{e['tick']}]{who} {e['kind']}"
                + (f" {detail}" if detail else "")
            )
    return "\n".join(lines)


@dataclasses.dataclass
class _Slot:
    payload: object = None
    full: bool = False
    consumed: bool = True  # nothing to lose initially


@dataclasses.dataclass
class _Dma:
    src: int
    target: int
    slot: int
    payload: object
    send_index: int
    recv_index: int
    #: wire-time the copy lands (cost model active; 0.0 otherwise)
    ready_at: float = 0.0
    #: (rank, step) of the issuing primitive in the rank's executed
    #: sequence — the same coordinates the static analysis tiers name
    #: events by, so a timing attribution can point back at the exact
    #: ``("dma", ...)`` primitive that started the copy
    origin: Optional[Tuple[int, int]] = None


def _identity(rank: int) -> int:
    return rank


def _barrier_steps(me: int, n: int, to_global: Callable[[int], int] = _identity):
    """Signal both ring neighbours, wait for both — mirrors
    ``ring._neighbour_barrier``. ``to_global`` maps a ring-local rank to
    the global simulator rank, mirroring ``ring._logical_id_fn`` (rings
    over a subset of a mesh's axes must target the right global device;
    the identity is only correct when the ring spans the whole mesh)."""
    yield ("signal", to_global((me - 1) % n), SEM_BARRIER, 0, 1)
    yield ("signal", to_global((me + 1) % n), SEM_BARRIER, 0, 1)
    yield ("wait", SEM_BARRIER, 0, 2)


# ---------------------------------------------------------------------------
# Protocol state machines (mirror smi_tpu/kernels/ring.py kernel bodies)
# ---------------------------------------------------------------------------


def all_gather_rank(me: int, n: int, chunk, flow_control: bool = True,
                    to_global: Callable[[int], int] = _identity):
    """Mirrors ``_ring_all_gather_kernel``: forward the chunk received
    last step to the right neighbour; slots alternate; slot 1 granted at
    start; per-step re-grant after the onward send except the final step."""
    left, right = to_global((me - 1) % n), to_global((me + 1) % n)
    if flow_control:
        yield from _barrier_steps(me, n, to_global)
    yield ("output", me, chunk)
    yield ("write_slot", 0, chunk)
    if flow_control:
        yield ("signal", left, SEM_CREDIT, 1, 1)
    for s in range(n - 1):
        slot, nslot = s % 2, (s + 1) % 2
        if flow_control:
            yield ("wait", SEM_CREDIT, nslot, 1)
        payload = yield ("read_slot", slot)
        yield ("dma", right, nslot, payload, slot, nslot)
        yield ("wait", SEM_SEND, slot, 1)
        yield ("wait", SEM_RECV, nslot, 1)
        if flow_control and s < n - 2:
            yield ("signal", left, SEM_CREDIT, slot, 1)
        arrived = yield ("read_slot", nslot)
        yield ("output", (me - s - 1) % n, arrived)


def all_reduce_rank(me: int, n: int, value, combine: Callable,
                    flow_control: bool = True,
                    to_global: Callable[[int], int] = _identity):
    """Mirrors ``_ring_all_reduce_kernel``: circulate the running partial
    rightward, folding the local contribution into each arrival."""
    left, right = to_global((me - 1) % n), to_global((me + 1) % n)
    if flow_control:
        yield from _barrier_steps(me, n, to_global)
    yield ("write_slot", 0, value)
    if flow_control:
        yield ("signal", left, SEM_CREDIT, 1, 1)
    for s in range(n - 1):
        slot, nslot = s % 2, (s + 1) % 2
        if flow_control:
            yield ("wait", SEM_CREDIT, nslot, 1)
        payload = yield ("read_slot", slot)
        yield ("dma", right, nslot, payload, slot, nslot)
        yield ("wait", SEM_SEND, slot, 1)
        yield ("wait", SEM_RECV, nslot, 1)
        if flow_control and s < n - 2:
            yield ("signal", left, SEM_CREDIT, slot, 1)
        arrived = yield ("read_slot", nslot)
        yield ("write_slot", nslot, combine(arrived, value))
    final = yield ("read_slot", (n - 1) % 2)
    yield ("output", 0, final)


def all_reduce_chunked_rank(me: int, n: int, values: Sequence,
                            combine: Callable, flow_control: bool = True,
                            to_global: Callable[[int], int] = _identity):
    """Mirrors ``_ring_all_reduce_chunked_kernel``: the payload split
    into ``len(values)`` pipeline chunks, each circulating on its own
    double-buffered slot pair (flat layout ``2*c + parity``). Per ring
    step, phase A starts EVERY chunk's DMA (after its credit), phase B
    combines each arrival — chunk ``c`` folds while chunks ``c+1..``
    are still in flight — and phase C re-grants each slot once its
    onward send completed. Per chunk the credit discipline is identical
    to :func:`all_reduce_rank`; the phases interleave the chunks, which
    is exactly what the verified-transport framing must survive (wire
    sequence numbers advance across the chunk interleave in send order,
    and the receiver consumes in the same order)."""
    left, right = to_global((me - 1) % n), to_global((me + 1) % n)
    k = len(values)
    if flow_control:
        yield from _barrier_steps(me, n, to_global)
    for c in range(k):
        yield ("write_slot", 2 * c, values[c])
        if flow_control:
            yield ("signal", left, SEM_CREDIT, 2 * c + 1, 1)
    for s in range(n - 1):
        slot, nslot = s % 2, (s + 1) % 2
        for c in range(k):  # phase A: start all chunk RDMAs
            if flow_control:
                yield ("wait", SEM_CREDIT, 2 * c + nslot, 1)
            payload = yield ("read_slot", 2 * c + slot)
            yield ("dma", right, 2 * c + nslot, payload,
                   2 * c + slot, 2 * c + nslot)
        for c in range(k):  # phase B: combine arrivals in chunk order
            yield ("wait", SEM_RECV, 2 * c + nslot, 1)
            arrived = yield ("read_slot", 2 * c + nslot)
            yield ("write_slot", 2 * c + nslot,
                   combine(arrived, values[c]))
        for c in range(k):  # phase C: sends drained -> re-grant slots
            yield ("wait", SEM_SEND, 2 * c + slot, 1)
            if flow_control and s < n - 2:
                yield ("signal", left, SEM_CREDIT, 2 * c + slot, 1)
    final_slot = (n - 1) % 2
    for c in range(k):
        final = yield ("read_slot", 2 * c + final_slot)
        yield ("output", c, final)


def reduce_scatter_rank(me: int, n: int, blocks: Sequence, combine: Callable,
                        flow_control: bool = True,
                        to_global: Callable[[int], int] = _identity):
    """Mirrors ``_ring_reduce_scatter_kernel``: at step ``s`` send the
    partial of block ``(me - s - 1) % n``, fold the local share into the
    arriving partial of block ``(me - s - 2) % n``."""
    left, right = to_global((me - 1) % n), to_global((me + 1) % n)
    if flow_control:
        yield from _barrier_steps(me, n, to_global)
    yield ("write_slot", 0, blocks[(me - 1) % n])
    if flow_control:
        yield ("signal", left, SEM_CREDIT, 1, 1)
    for s in range(n - 1):
        slot, nslot = s % 2, (s + 1) % 2
        if flow_control:
            yield ("wait", SEM_CREDIT, nslot, 1)
        payload = yield ("read_slot", slot)
        yield ("dma", right, nslot, payload, slot, nslot)
        yield ("wait", SEM_SEND, slot, 1)
        yield ("wait", SEM_RECV, nslot, 1)
        if flow_control and s < n - 2:
            yield ("signal", left, SEM_CREDIT, slot, 1)
        arrived = yield ("read_slot", nslot)
        yield ("write_slot", nslot, combine(arrived, blocks[(me - s - 2) % n]))
    final = yield ("read_slot", (n - 1) % 2)
    yield ("output", me, final)


def neighbour_stream_rank(me: int, n: int, chunks: Sequence,
                          direction: int = 1, flow_control: bool = True,
                          to_global: Callable[[int], int] = _identity):
    """Mirrors ``_neighbour_stream_kernel``: stream own chunks one hop
    downstream while consuming the upstream's; both slots start granted,
    waits begin at chunk 2, grants stop when nobody would consume them."""
    dst = to_global((me + direction) % n)
    upstream = to_global((me - direction) % n)
    if flow_control:
        yield from _barrier_steps(me, n, to_global)
    total = len(chunks)
    for c, chunk in enumerate(chunks):
        slot = c % 2
        if flow_control and c >= 2:
            yield ("wait", SEM_CREDIT, slot, 1)
        yield ("dma", dst, slot, chunk, slot, slot)
        yield ("wait", SEM_RECV, slot, 1)
        arrived = yield ("read_slot", slot)
        yield ("output", c, arrived)
        if flow_control and c + 2 < total:
            yield ("signal", upstream, SEM_CREDIT, slot, 1)
        yield ("wait", SEM_SEND, slot, 1)


# ---------------------------------------------------------------------------
# Two-tier pod protocol (ICI x DCN)
# ---------------------------------------------------------------------------
# A pod is (slices x ranks_per_slice): fast ICI wires inside a slice,
# slow DCN wires between slices — the reference's intra-node cost-1 /
# inter-node QSFP cost-100 split (codegen/program.py:7-8) at datacenter
# scale. The hierarchical allreduce crosses the slow tier exactly once,
# with already-combined shards:
#
#   phase A  reduce-scatter within the slice ring (ICI): slice-local
#            rank i ends holding the slice-partial of block i;
#   phase B  ring allreduce of that shard across slices (DCN), over
#            the cross ring {s*k + i : s} of same-index ranks — each
#            DCN wire carries 1/k of the payload;
#   phase C  all-gather of the k global blocks back around the slice
#            ring (ICI).
#
# Each phase runs on its OWN slot pair (rs: 0/1, xs: 2/3, ag: 4/5 —
# distinct scratch addresses, so phases can never alias each other's
# buffers), its own credit indices (= the slot indices), and its own
# barrier-semaphore domain (("rs"|"xs"|"ag"), the pod analog of the
# per-stream collective_id): a fast rank racing into phase C cannot
# satisfy a neighbour's phase-A barrier or clobber phase-B scratch.
# The per-phase credit discipline is byte-identical to the base ring
# protocols, which is what lets the verified-transport framing ride
# the composition unchanged (wire sequence numbers simply keep
# advancing across phases).

#: slot base per pod phase — distinct scratch, distinct credit indices
POD_PHASE_SLOTS = {"rs": 0, "xs": 2, "ag": 4}


def pod_slice_of(per_slice: int) -> Callable[[int], int]:
    """Global rank -> slice id for a (slices, per_slice) pod in
    row-major rank order (slice s owns ranks [s*k, (s+1)*k))."""
    if per_slice < 1:
        raise ValueError(f"per_slice must be >= 1, got {per_slice}")
    return lambda g: g // per_slice


def _pod_barrier(me: int, n: int, to_global, domain: str):
    """Per-phase neighbour barrier on the phase's own semaphore domain
    (mirrors :func:`_barrier_steps` with a namespaced index)."""
    yield ("signal", to_global((me - 1) % n), SEM_BARRIER, (domain, 0), 1)
    yield ("signal", to_global((me + 1) % n), SEM_BARRIER, (domain, 0), 1)
    yield ("wait", SEM_BARRIER, (domain, 0), 2)


def _pod_ring_lap(idx: int, n: int, to_global, domain: str, seed,
                  arrival, flow_control: bool, prologue=(),
                  final_read: bool = True):
    """One double-buffered ring lap on a pod phase's own slot pair and
    barrier domain — the base ring credit discipline (write, credit
    signal, dma, send/recv waits, re-credit) shared by all three pod
    phases, with the per-step payload policy injected. ``arrival(st,
    nslot, arrived)`` returns the single step to emit after each
    arrival; ``prologue`` steps run between the barrier and the seed
    write; ``final_read`` returns the last slot's payload (the
    reduction phases) or skips it (the all-gather, which has already
    delivered every block). Keeping one copy here is what makes the
    per-phase credit discipline identical by construction."""
    left, right = to_global((idx - 1) % n), to_global((idx + 1) % n)
    base = POD_PHASE_SLOTS[domain]
    if flow_control:
        yield from _pod_barrier(idx, n, to_global, domain)
    for step in prologue:
        yield step
    yield ("write_slot", base + 0, seed)
    if flow_control:
        yield ("signal", left, SEM_CREDIT, base + 1, 1)
    for st in range(n - 1):
        slot = base + st % 2
        nslot = base + (st + 1) % 2
        if flow_control:
            yield ("wait", SEM_CREDIT, nslot, 1)
        payload = yield ("read_slot", slot)
        yield ("dma", right, nslot, payload, slot, nslot)
        yield ("wait", SEM_SEND, slot, 1)
        yield ("wait", SEM_RECV, nslot, 1)
        if flow_control and st < n - 2:
            yield ("signal", left, SEM_CREDIT, slot, 1)
        arrived = yield ("read_slot", nslot)
        yield arrival(st, nslot, arrived)
    if final_read:
        return (yield ("read_slot", base + (n - 1) % 2))
    return None


def allreduce_pod_rank(g: int, slices: int, per_slice: int,
                       blocks: Sequence, combine: Callable,
                       flow_control: bool = True):
    """One rank's two-tier hierarchical allreduce over a pod.

    ``blocks`` is this rank's contribution split into ``per_slice``
    pipeline blocks (the reduce-scatter granularity). Degenerate tiers
    collapse exactly: ``per_slice == 1`` skips phases A/C (a slice of
    one has nothing to scatter), ``slices == 1`` skips phase B (no DCN
    tier) — so the 1x1 pod is a no-op delivery of the local blocks.
    Delivery: one ``("output", c, payload)`` per block ``c`` holding
    the full reduction, on every rank — bit-identical to what the flat
    ring delivers for the same contributions.
    """
    k = per_slice
    if len(blocks) != k:
        raise ValueError(
            f"rank {g} got {len(blocks)} blocks for per_slice={k}"
        )
    if slices < 1 or k < 1:
        raise ValueError(f"pod must be >= 1x1, got {slices}x{k}")
    s, i = divmod(g, k)

    def in_slice(r: int) -> int:
        return s * k + r

    def x_slice(t: int) -> int:
        return t * k + i

    # -- phase A: reduce-scatter within the slice (ICI) ----------------
    if k > 1:
        shard = yield from _pod_ring_lap(
            i, k, in_slice, "rs", blocks[(i - 1) % k],
            lambda st, nslot, arrived: (
                "write_slot", nslot,
                combine(arrived, blocks[(i - st - 2) % k])),
            flow_control)
    else:
        shard = blocks[0]

    # -- phase B: circulate the shard across slices (DCN) --------------
    if slices > 1:
        block = yield from _pod_ring_lap(
            s, slices, x_slice, "xs", shard,
            lambda st, nslot, arrived: (
                "write_slot", nslot, combine(arrived, shard)),
            flow_control)
    else:
        block = shard

    # -- phase C: all-gather the global blocks within the slice (ICI) --
    if k > 1:
        yield from _pod_ring_lap(
            i, k, in_slice, "ag", block,
            lambda st, nslot, arrived: (
                "output", (i - st - 1) % k, arrived),
            flow_control, prologue=(("output", i, block),),
            final_read=False)
    else:
        yield ("output", 0, block)


# ---------------------------------------------------------------------------
# Compressed-wire allreduce family (r19)
# ---------------------------------------------------------------------------
# Hockney says a large-payload collective is pure bytes/beta, and no
# protocol before r19 ever shrank the bytes. Two state machines attack
# the term. ``all_reduce_quantized_rank`` is the two-tier pod
# composition with an explicit wire codec at the boundary: the rank
# encodes its OWN blocks before the first hop, circulates and combines
# in wire (quantized) space, and decodes only at delivery — arrivals
# are still never observed by control flow (encode/decode/combine are
# caller policy applied to opaque values), so the symbolic replay stays
# exact and all four static checks carry over from ``allreduce_pod``
# unchanged. The byte-width claim itself lives where PR 12 put message
# sizing: the :class:`TierCostModel`'s per-tier ``ici_bytes`` /
# ``dcn_bytes``, scaled by :data:`PRECISION_WIRE_RATIO`.
# ``all_reduce_sparse_rank`` ships top-k (index, value) bundles: no
# in-flight combine is possible without opening a bundle (the indices
# decide alignment), so the honest wire shape is a ring all-gather of
# the n opaque bundles with the reduction applied LOCALLY at the end —
# (n-1) hops of k pairs instead of (n-1) hops of the dense payload.

#: Wire bytes per element relative to f32 — the beta ratios the
#: quantized family exists for (f32 4 B, bf16 2 B, int8 1 B / element).
PRECISION_WIRE_RATIO = {"f32": 1.0, "bf16": 0.5, "int8": 0.25}

#: The sparse variant's default density (top-k keeps 1/16 of the
#: elements) and per-kept-element overhead (a 4 B index rides along
#: with each 4 B value), the pricing convention ``_costs_for`` and the
#: plan engine share.
SPARSE_TOPK_DENSITY = 1.0 / 16.0
SPARSE_INDEX_OVERHEAD = 2.0


def _identity_codec(v):
    return v


def all_reduce_quantized_rank(g: int, slices: int, per_slice: int,
                              blocks: Sequence, combine: Callable,
                              encode: Optional[Callable] = None,
                              decode: Optional[Callable] = None,
                              flow_control: bool = True):
    """One rank's two-tier allreduce in quantized wire form.

    Identical phase/slot/credit structure to :func:`allreduce_pod_rank`
    (rs over ICI, shard ring over DCN, ag over ICI — which is why the
    static safety checks and the verified-transport framing carry over
    byte-for-byte); the difference is the codec boundary: ``encode`` is
    applied to this rank's own ``blocks`` before the first hop,
    ``combine`` operates on wire-space values, and ``decode`` runs only
    at the ``("output", ...)`` edge. Numeric quantization (scale,
    rounding, error feedback) is the JAX layer's job; here the codec is
    symbolic and the wire-width claim is the cost model's per-tier
    bytes. Delivery: one output per block holding the decoded full
    reduction, on every rank."""
    enc = encode or _identity_codec
    dec = decode or _identity_codec
    k = per_slice
    if len(blocks) != k:
        raise ValueError(
            f"rank {g} got {len(blocks)} blocks for per_slice={k}"
        )
    if slices < 1 or k < 1:
        raise ValueError(f"pod must be >= 1x1, got {slices}x{k}")
    s, i = divmod(g, k)
    wire = [enc(b) for b in blocks]

    def in_slice(r: int) -> int:
        return s * k + r

    def x_slice(t: int) -> int:
        return t * k + i

    # -- phase A: reduce-scatter the encoded blocks in-slice (ICI) -----
    if k > 1:
        shard = yield from _pod_ring_lap(
            i, k, in_slice, "rs", wire[(i - 1) % k],
            lambda st, nslot, arrived: (
                "write_slot", nslot,
                combine(arrived, wire[(i - st - 2) % k])),
            flow_control)
    else:
        shard = wire[0]

    # -- phase B: circulate the encoded shard across slices (DCN) ------
    if slices > 1:
        block = yield from _pod_ring_lap(
            s, slices, x_slice, "xs", shard,
            lambda st, nslot, arrived: (
                "write_slot", nslot, combine(arrived, shard)),
            flow_control)
    else:
        block = shard

    # -- phase C: all-gather, decoding at the delivery edge (ICI) ------
    if k > 1:
        yield from _pod_ring_lap(
            i, k, in_slice, "ag", block,
            lambda st, nslot, arrived: (
                "output", (i - st - 1) % k, dec(arrived)),
            flow_control, prologue=(("output", i, dec(block)),),
            final_read=False)
    else:
        yield ("output", 0, dec(block))


def all_reduce_sparse_rank(me: int, n: int, bundle, combine: Callable,
                           flow_control: bool = True,
                           to_global: Callable[[int], int] = _identity):
    """One rank's top-k sparse allreduce: ring all-gather of opaque
    (index, value) bundles, reduced locally.

    The wire discipline is :func:`all_gather_rank`'s (alternating
    slots, slot-1 credit at start, per-step re-grant except the final
    step), but arrivals are ASSEMBLED by ring position instead of
    delivered per source — the protocol never opens a bundle, it only
    knows which source each hop's arrival came from. Delivery: one
    ``("output", 0, combine(bundles))`` where ``bundles`` is the
    n-tuple of every rank's bundle in source order and ``combine`` is
    the caller's local densify-and-reduce policy."""
    left, right = to_global((me - 1) % n), to_global((me + 1) % n)
    if flow_control:
        yield from _barrier_steps(me, n, to_global)
    gathered: list = [None] * n
    gathered[me] = bundle
    yield ("write_slot", 0, bundle)
    if flow_control:
        yield ("signal", left, SEM_CREDIT, 1, 1)
    for s in range(n - 1):
        slot, nslot = s % 2, (s + 1) % 2
        if flow_control:
            yield ("wait", SEM_CREDIT, nslot, 1)
        payload = yield ("read_slot", slot)
        yield ("dma", right, nslot, payload, slot, nslot)
        yield ("wait", SEM_SEND, slot, 1)
        yield ("wait", SEM_RECV, nslot, 1)
        if flow_control and s < n - 2:
            yield ("signal", left, SEM_CREDIT, slot, 1)
        arrived = yield ("read_slot", nslot)
        gathered[(me - s - 1) % n] = arrived
    yield ("output", 0, combine(tuple(gathered)))


# ---------------------------------------------------------------------------
# All-to-all protocol family
# ---------------------------------------------------------------------------
# The first protocol family whose traffic matrix is not a ring or a
# tree: every rank holds one block per destination (MoE expert routing,
# distributed shuffle, K-means reassignment). Three variants, one
# delivery contract each, all one-yield-per-primitive and
# schedule-independent (no generator ever observes a received payload —
# receipts are forwarded or delivered opaquely, which is what keeps the
# static verifier's symbolic replay exact):
#
# - ``all_to_all_rank`` — the pairwise-exchange reference: step ``s``
#   sends to ``(me + s) % n`` and receives from ``(me - s) % n``,
#   double-buffered slots (``s % 2``), one credit per step granted by
#   the receiver two steps ahead. Per-STEP semaphore indices keep every
#   credit/send/recv domain single-producer (the shape the verifier's
#   happens-before matching is exact for); the verified-transport
#   framing rides unchanged because ``verified_steps`` already numbers
#   wire sequences PER DESTINATION — all-to-all is the protocol that
#   finally exercises more than one lane per sender.
# - ``all_to_all_bruck_rank`` — the Bruck-style log-step variant:
#   ``log2(n)`` rounds, round ``k`` forwarding every buffer index with
#   bit ``k`` set to rank ``me + 2^k``. Aggregation is modeled by
#   pricing (the harness prices each round's messages at the
#   ``n/2``-block aggregate the real kernel would coalesce into one
#   send); n must be a power of two — anything else is a loud
#   ValueError, never a silent fallback.
# - ``all_to_all_pod_rank`` — the two-tier ICI x DCN variant: phase A
#   exchanges per-destination items within the slice over ICI (routing
#   each block to the slice-mate whose COLUMN matches the block's
#   destination position), phase B crosses DCN exactly once per
#   destination slice with a k-block bundle, and the local redistribute
#   delivers per-source-slice bundles. DCN alphas drop from
#   ``(n - per_slice)`` per rank (flat pairwise) to ``slices - 1``.


def all_to_all_rank(me: int, n: int, blocks: Sequence,
                    flow_control: bool = True,
                    to_global: Callable[[int], int] = _identity):
    """Pairwise-exchange all-to-all: ``blocks[d]`` is this rank's block
    for destination ``d``; delivery is one ``("output", src, block)``
    per source rank (own block delivered locally).

    Credit discipline: step ``s`` lands in slot ``s % 2`` at the
    receiver; the receiver grants step ``s``'s credit (semaphore index
    ``s`` — single-producer, single-consumer) to that step's sender
    after consuming the slot's previous tenant at step ``s - 2`` (the
    first two steps are granted upfront — both slots start free). A
    duplicate grant admits a clobber, a dropped one deadlocks: the
    same failure surface as the ring protocols, on a rotating-partner
    schedule.
    """
    if n < 1:
        raise ValueError(f"all_to_all needs n >= 1, got {n}")
    if len(blocks) != n:
        raise ValueError(
            f"rank {me} got {len(blocks)} blocks for n={n}"
        )
    if flow_control and n > 1:
        yield from _barrier_steps(me, n, to_global)
    yield ("output", me, blocks[me])
    if flow_control:
        for s in range(1, min(3, n)):
            # both slots start free: grant the first tenant of each
            yield ("signal", to_global((me - s) % n), SEM_CREDIT, s, 1)
    for s in range(1, n):
        dst = to_global((me + s) % n)
        src = (me - s) % n
        if flow_control:
            yield ("wait", SEM_CREDIT, s, 1)
        yield ("dma", dst, s % 2, blocks[(me + s) % n], s, s)
        yield ("wait", SEM_SEND, s, 1)
        yield ("wait", SEM_RECV, s, 1)
        arrived = yield ("read_slot", s % 2)
        yield ("output", src, arrived)
        if flow_control and s + 2 < n:
            # slot s % 2 is consumed: its next tenant may come
            yield ("signal", to_global((me - (s + 2)) % n),
                   SEM_CREDIT, s + 2, 1)


def all_to_all_bruck_rank(me: int, n: int, blocks: Sequence,
                          flow_control: bool = True,
                          to_global: Callable[[int], int] = _identity):
    """Bruck-style log-step all-to-all (power-of-two ``n`` ONLY — a
    non-power-of-two rank count raises, it is never silently padded or
    rerouted).

    Round ``k`` forwards every buffer index ``i`` with bit ``k`` set to
    rank ``me + 2^k`` and refills the same indices from ``me - 2^k``;
    after ``log2(n)`` rounds buffer index ``i`` holds the block from
    rank ``(me - i) % n``, delivered per source. Received values are
    forwarded OPAQUELY (buffer entries, never inspected), so the
    sequence is schedule-independent and the verified-transport
    framing re-frames each hop on the forwarder's own destination
    lane. Each round's ``n/2`` copies start together and the harness
    prices them at the aggregate message size — the coalesced send a
    real Bruck kernel performs.

    Per-round-per-index semaphore domains (``("c"|"s"|"r", k, i)``)
    keep every lane single-producer; slot ``i`` is reused across the
    rounds whose bit is set in ``i``, protected by the per-round
    credit granted only after the previous tenant was read.
    """
    if n < 1 or (n & (n - 1)):
        raise ValueError(
            f"all_to_all_bruck needs a power-of-two rank count, got "
            f"n={n} — use the pairwise variant (or a padded shape) "
            f"for non-power-of-two rings"
        )
    if len(blocks) != n:
        raise ValueError(
            f"rank {me} got {len(blocks)} blocks for n={n}"
        )
    if flow_control and n > 1:
        yield from _barrier_steps(me, n, to_global)
    yield ("output", me, blocks[me])
    # local rotation: buf[i] = the block destined (me + i) % n
    buf = {i: blocks[(me + i) % n] for i in range(1, n)}
    rounds = n.bit_length() - 1
    for k in range(rounds):
        hop = 1 << k
        dst = to_global((me + hop) % n)
        src = to_global((me - hop) % n)
        idxs = [i for i in range(1, n) if i & hop]
        if flow_control:
            for i in idxs:
                # slot i's previous tenant (if any) was read in the
                # last round whose bit is below k — program order
                # makes this grant safe
                yield ("signal", src, SEM_CREDIT, ("c", k, i), 1)
        for i in idxs:  # phase A: start every copy of the round
            if flow_control:
                yield ("wait", SEM_CREDIT, ("c", k, i), 1)
            yield ("dma", dst, i, buf[i], ("s", k, i), ("r", k, i))
        for i in idxs:  # phase B: drain sends, refill the buffer
            yield ("wait", SEM_SEND, ("s", k, i), 1)
            yield ("wait", SEM_RECV, ("r", k, i), 1)
            buf[i] = yield ("read_slot", i)
    for i in range(1, n):
        yield ("output", (me - i) % n, buf[i])


def all_to_all_pod_rank(g: int, slices: int, per_slice: int,
                        blocks: Sequence, flow_control: bool = True):
    """One rank's two-tier ICI x DCN all-to-all over a pod.

    ``blocks[d]`` is this rank's block for global destination ``d``
    (row-major pod order, ``credits.pod_slice_of``). Routing: the
    block from ``(s, i)`` to ``(t, j)`` hops ICI to the in-slice
    COLUMN owner ``(s, j)`` (phase A), then crosses DCN once inside
    the ``(t, j)`` column as part of a ``per_slice``-block bundle
    (phase B). Delivery: one ``("output", ("slice", t), bundle)`` per
    source slice ``t``, where ``bundle[j]`` is the block from rank
    ``(t, j)`` — the concatenation over slices and positions is the
    flat variants' per-source delivery, re-grouped by slice (bundles
    stay opaque end to end, so the protocol never indexes a received
    payload and the symbolic replay stays exact).

    Degenerate tiers collapse exactly: ``per_slice == 1`` skips phase
    A, ``slices == 1`` skips phase B, and the 1x1 pod is a local
    delivery. Phase A/B run on disjoint slot spaces (``("Ad"|"At",
    ...)`` vs ``("B", ...)``, each written once per run) with
    per-phase neighbour barriers on their own semaphore domains.
    """
    m, k = slices, per_slice
    if m < 1 or k < 1:
        raise ValueError(f"pod must be >= 1x1, got {m}x{k}")
    n = m * k
    if len(blocks) != n:
        raise ValueError(
            f"rank {g} got {len(blocks)} blocks for a {m}x{k} pod"
        )
    s, i = divmod(g, k)

    def in_slice(r: int) -> int:
        return s * k + r

    def x_slice(t: int) -> int:
        return t * k + i

    # -- phase A: per-destination-position exchange in the slice (ICI)
    direct: Dict[int, object] = {}       # slice-mate pos -> block to me
    transit: Dict[Tuple[int, int], object] = {}  # (dst slice, src pos)
    if k > 1:
        if flow_control:
            yield from _pod_barrier(i, k, in_slice, "a2a_ici")
        for o in range(1, k):
            j = (i + o) % k
            yield ("dma", in_slice(j), ("Ad", i), blocks[s * k + j],
                   ("Ads", j), ("Ad", i))
            for u in range(1, m):
                t = (s + u) % m
                yield ("dma", in_slice(j), ("At", i, t),
                       blocks[t * k + j], ("Ats", j, t), ("At", i, t))
        for o in range(1, k):
            j = (i + o) % k
            yield ("wait", SEM_SEND, ("Ads", j), 1)
            for u in range(1, m):
                t = (s + u) % m
                yield ("wait", SEM_SEND, ("Ats", j, t), 1)
        for o in range(1, k):
            p = (i - o) % k
            yield ("wait", SEM_RECV, ("Ad", p), 1)
            direct[p] = yield ("read_slot", ("Ad", p))
            for u in range(1, m):
                t = (s + u) % m
                yield ("wait", SEM_RECV, ("At", p, t), 1)
                transit[(t, p)] = yield ("read_slot", ("At", p, t))
    own_bundle = tuple(
        blocks[s * k + i] if p == i else direct[p] for p in range(k)
    )
    yield ("output", ("slice", s), own_bundle)

    # -- phase B: one bundle per destination slice across DCN ----------
    if m > 1:
        if flow_control:
            yield from _pod_barrier(s, m, x_slice, "a2a_dcn")
        for u in range(1, m):
            t = (s + u) % m
            bundle = tuple(
                blocks[t * k + i] if p == i else transit[(t, p)]
                for p in range(k)
            )
            yield ("dma", x_slice(t), ("B", s), bundle, ("Bs", t),
                   ("B", s))
        for u in range(1, m):
            t = (s + u) % m
            yield ("wait", SEM_SEND, ("Bs", t), 1)
        for u in range(1, m):
            src_slice = (s - u) % m
            yield ("wait", SEM_RECV, ("B", src_slice), 1)
            bundle = yield ("read_slot", ("B", src_slice))
            yield ("output", ("slice", src_slice), bundle)


# ---------------------------------------------------------------------------
# Verified-transport framing
# ---------------------------------------------------------------------------
# The credit protocol guarantees ORDERING and FLOW CONTROL, but it
# trusts the wire: a payload corrupted in flight lands as cleanly as a
# healthy one and becomes silently wrong delivery — the one outcome the
# fault matrix forbids, and the one the simulator alone cannot catch at
# the point of damage. The framing layer closes that hole the way every
# production collective transport does: each chunk moves as a Frame
# carrying (src, per-source sequence number, CRC over src+seq+payload),
# and the receiver verifies both on consumption. Corruption or
# truncation → checksum mismatch; reordering or loss-then-replay →
# sequence mismatch; either raises a named IntegrityError instead of
# propagating bad data into a reduction.
#
# Framing is an adapter around a rank's protocol generator
# (:func:`verified_steps`), exactly like :func:`instance_steps`: the
# protocol state machines stay byte-identical, and with no tampering
# the framed run is behaviourally identical to the bare one. Local
# slot writes are framed too (a rank's own scratch re-reads verify on
# a separate per-rank "local" lane), so every read_slot in the system
# is covered.


@dataclasses.dataclass(frozen=True)
class Frame:
    """One verified-transport chunk: payload + integrity envelope.

    ``wire`` separates the two sequence lanes a rank emits on: True for
    RDMA'd chunks (the receiver checks them against the sender's wire
    lane), False for the rank's own local slot writes (checked against
    its local lane) — the lanes interleave arbitrarily in slot usage
    but are each strictly ordered.
    """

    src: int
    seq: int
    wire: bool
    payload: object
    crc: int


def frame_crc(src: int, seq: int, wire: bool, payload) -> int:
    """Deterministic checksum over the frame's identity and payload.

    ``repr`` keys the CRC: the simulator's payloads are plain Python
    values (strings, ints, frozensets, tuples of those) whose repr is
    stable within a run — and across runs for everything the harnesses
    use. Sorting frozensets would be needed for cross-process
    stability; within one campaign process this is exact.
    """
    return zlib.crc32(
        repr((src, seq, wire, payload)).encode()
    ) & 0xFFFFFFFF


def make_frame(src: int, seq: int, payload, wire: bool = True) -> Frame:
    return Frame(src, seq, wire, payload,
                 frame_crc(src, seq, wire, payload))


def _verify_frame(me: int, frame, next_seq: Dict,
                  accepted: Dict) -> object:
    """Receiver-side check: CRC then per-source sequence. Returns the
    unwrapped payload; raises :class:`IntegrityError` naming the miss.

    A re-read of the exact frame last accepted on a lane is legal (the
    all-gather kernel reads a slot once to deliver and once to forward);
    only a DIFFERENT frame with a non-successor sequence number is a
    reordering violation.
    """
    if not isinstance(frame, Frame):
        raise IntegrityError(
            f"rank {me} consumed an unframed payload {frame!r} on the "
            f"verified transport",
            rank=me, kind="unframed", got=frame,
        )
    want = frame_crc(frame.src, frame.seq, frame.wire, frame.payload)
    if want != frame.crc:
        raise IntegrityError(
            f"rank {me}: checksum mismatch on chunk seq={frame.seq} "
            f"from rank {frame.src}: frame declares crc={frame.crc:#010x}"
            f" but payload hashes to {want:#010x} (payload corrupted or"
            f" truncated in flight)",
            rank=me, src=frame.src, seq=frame.seq,
            expected=frame.crc, got=want, kind="checksum",
        )
    lane = (frame.src, frame.wire)
    if frame == accepted.get(lane):
        return frame.payload  # verified re-read of the same chunk
    expected = next_seq.get(lane, 0)
    if frame.seq != expected:
        raise IntegrityError(
            f"rank {me}: out-of-sequence chunk from rank {frame.src}: "
            f"expected seq={expected}, got seq={frame.seq} (chunks "
            f"reordered or lost in flight)",
            rank=me, src=frame.src, seq=frame.seq,
            expected=expected, got=frame.seq, kind="sequence",
        )
    next_seq[lane] = expected + 1
    accepted[lane] = frame
    return frame.payload


def verified_steps(gen, me: int):
    """Verified-transport framing around one rank's protocol generator.

    Outgoing ``dma`` payloads are framed on the rank's wire lane,
    local ``write_slot`` payloads on its local lane; every ``read_slot``
    result is CRC- and sequence-checked, then unwrapped before the
    inner generator sees it. All other actions pass through untouched,
    so a framed healthy run is behaviourally identical to a bare one —
    only tampering (:class:`smi_tpu.parallel.faults.FaultPlan`'s
    ``tamper`` hook) can make the checks fire.

    Sequence checking relies on the credit protocol's own ordering
    guarantee: within one (src, lane) the ring protocols consume
    chunks in send order, so a regression is genuine reordering. The
    sender numbers its wire lane PER DESTINATION (a receiver's lane
    sees a dense sequence even when the sender also serves other
    rings) — identical to a single global counter for every
    single-destination protocol, and what lets the two-tier pod
    composition (in-slice ring + cross-slice ring per rank) ride the
    framing unchanged. The composite multi-instance programs re-use
    scratch across instances with their own ordering rules; frame
    those per instance, not across a whole composite.
    """
    wire_seqs: Dict[int, int] = {}
    local_seq = 0
    next_seq: Dict = {}
    accepted: Dict = {}
    value = None
    while True:
        try:
            action = gen.send(value)
        except StopIteration:
            return
        kind = action[0]
        if kind == "dma":
            _, target, slot, payload, send_index, recv_index = action
            wire_seq = wire_seqs.get(target, 0)
            frame = make_frame(me, wire_seq, payload, wire=True)
            wire_seqs[target] = wire_seq + 1
            value = yield ("dma", target, slot, frame, send_index,
                           recv_index)
        elif kind == "write_slot":
            _, slot, payload = action
            frame = make_frame(me, local_seq, payload, wire=False)
            local_seq += 1
            value = yield ("write_slot", slot, frame)
        elif kind == "read_slot":
            frame = yield action
            value = _verify_frame(me, frame, next_seq, accepted)
        else:
            value = yield action


# ---------------------------------------------------------------------------
# Concurrent multi-stream composition
# ---------------------------------------------------------------------------
# A composite program (the 4-direction ring halo exchange, concurrent
# P2P streams) runs SEVERAL kernel instances per rank in program order.
# The hardware resources they touch alias in two different ways, and the
# model must reproduce both:
#
# - comm-buffer slots and the send/recv/credit semaphores are
#   kernel-local *scratch*: sequential same-shaped instances reuse the
#   same VMEM/semaphore addresses. They are therefore NOT namespaced —
#   instance k+1's RDMAs physically land on the addresses instance k
#   used, and only protocol ordering keeps that safe.
# - the cross-device BARRIER semaphore is keyed by ``collective_id``
#   (the stream's semaphore domain, ``ring.ring_collective_id``). It is
#   namespaced by the instance's declared *domain*: instances on
#   distinct streams own distinct barriers; instances SHARING a domain
#   share one — which lets a fast rank satisfy its barrier wait with
#   signals meant for a neighbour's *other* instance, enter early, and
#   clobber scratch the neighbour is still consuming. That is exactly
#   the cross-stream hazard distinct domains exist to prevent, and
#   :func:`simulate_halo_exchange` + the mutation tests fuzz it.


def instance_steps(gen, domain, instance):
    """Run one kernel-instance generator inside a composite program.

    Namespaces the BARRIER semaphore by ``domain`` (collective_id) and
    the output keys by ``instance`` (so verification can tell instances
    apart); leaves slots and send/recv/credit semaphore indices alone —
    they are scratch addresses shared across sequential instances.
    """
    value = None
    while True:
        try:
            action = gen.send(value)
        except StopIteration:
            return
        kind = action[0]
        if kind == "signal" and action[2] == SEM_BARRIER:
            _, target, name, index, inc = action
            value = yield ("signal", target, name, (domain, index), inc)
        elif kind == "wait" and action[1] == SEM_BARRIER:
            _, name, index, amount = action
            value = yield ("wait", name, (domain, index), amount)
        elif kind == "output":
            _, key, payload = action
            value = yield ("output", (instance, key), payload)
        else:
            value = yield action


def chain_programs(*gens):
    """One rank's composite program: kernel instances in program order
    (a TPU core launches them sequentially), ``send``-transparent."""
    for gen in gens:
        value = None
        while True:
            try:
                action = gen.send(value)
            except StopIteration:
                break
            value = yield action


def halo_generators(
    nrow: int,
    ncol: int,
    chunks: int = 1,
    domains: Sequence[int] = (0, 1, 2, 3),
    flow_control: bool = True,
    wrong_ids: bool = False,
):
    """Per-rank composite programs of the 4-direction ring halo exchange.

    Mirrors ``halo.halo_exchange_2d(backend="ring")`` on an
    ``nrow x ncol`` mesh: per rank, four neighbour-stream instances in
    program order — up/down along the row axis (one ring per column),
    left/right along the column axis (one ring per row) — with stream
    ``s`` on barrier domain ``domains[s]`` (the per-direction semaphore
    domains, ``halo.py``). Rings span a SUBSET of the mesh axes, so
    ring-local ranks resolve through ``to_global`` exactly as the
    kernels' ``_logical_id_fn`` does; ``wrong_ids=True`` reinstates the
    pre-fix identity mapping (the round-3 subset-axis bug) so tests can
    prove the harness catches it.
    """
    programs = []
    for g in range(nrow * ncol):
        r, c = divmod(g, ncol)
        subs = []
        for stream, (axis, direction) in enumerate(
            (("row", 1), ("row", -1), ("col", 1), ("col", -1))
        ):
            if axis == "row":
                ring_n, ring_me = nrow, r
                to_global = (lambda rr, c=c: rr * ncol + c)
            else:
                ring_n, ring_me = ncol, c
                to_global = (lambda cc, r=r: r * ncol + cc)
            if wrong_ids:
                to_global = _identity
            labels = [((g, stream), k) for k in range(chunks)]
            subs.append(
                instance_steps(
                    neighbour_stream_rank(
                        ring_me, ring_n, labels, direction=direction,
                        flow_control=flow_control, to_global=to_global,
                    ),
                    domain=domains[stream], instance=stream,
                )
            )
        programs.append(chain_programs(*subs))
    return programs


def simulate_halo_exchange(
    nrow: int,
    ncol: int,
    strategy: Strategy,
    chunks: int = 1,
    domains: Sequence[int] = (0, 1, 2, 3),
    flow_control: bool = True,
    wrong_ids: bool = False,
) -> None:
    """Fuzz one schedule of the 4-direction halo composite and verify
    per-stream delivery: stream ``s`` at rank ``g`` must receive its
    ring-upstream's labels for that stream."""
    outputs = RingSimulator(
        halo_generators(nrow, ncol, chunks, domains, flow_control,
                        wrong_ids),
        strategy,
    ).run()
    for g in range(nrow * ncol):
        r, c = divmod(g, ncol)
        want = {}
        for stream, (axis, direction) in enumerate(
            (("row", 1), ("row", -1), ("col", 1), ("col", -1))
        ):
            if axis == "row":
                up = ((r - direction) % nrow) * ncol + c
            else:
                up = r * ncol + (c - direction) % ncol
            for k in range(chunks):
                want[(stream, k)] = ((up, stream), k)
        if outputs[g] != want:
            raise ProtocolError(
                f"rank {g} received {outputs[g]}, wanted {want}"
            )


def concurrent_stream_generators(
    n: int,
    channels: Sequence[Tuple[int, int]],
    bursts: int = 2,
    chunks_per_burst: int = 4,
    domains: Optional[Sequence[int]] = None,
    flow_control: bool = True,
    swap_order_rank: Optional[int] = None,
    chunk_counts: Optional[Sequence[int]] = None,
):
    """Per-rank composite programs of burst-interleaved concurrent P2P
    streams over one ``n``-ring.

    Mirrors ``channels._stream_concurrent_ring``: each round moves one
    burst of every channel (in channel order) before any channel
    advances — each burst a fresh neighbour-stream kernel instance (one
    ``_ring_move`` hop) in the channel's port stream domain. Every rank
    runs every hop (SPMD), so a channel is just ``(port, direction)``;
    ``domains`` overrides the per-channel barrier domains (defaults to
    the ports — pass duplicates to model the shared-domain mutation).

    ``swap_order_rank`` makes ONE rank run each burst's channels in
    reversed order — the divergent-MPMD ordering bug the collective
    schedule must never contain. With distinct domains that rank
    deadlocks loudly at the misordered barrier; with a shared domain
    the barrier lets it through and the fuzzer sees the resulting
    scratch clobber instead — both detectable, which is the point.

    ``chunk_counts`` (per-channel TOTAL chunks) models UNEQUAL tenant
    streams sharing the wire: round ``b`` moves channel ``i``'s chunks
    ``[b*cpb, (b+1)*cpb)`` while any remain, exhausted channels simply
    stop contributing instances — the ``READS_LIMIT`` fairness bound
    between unequal sources, where a small stream must finish within
    its own rounds instead of queueing behind a large one. Overrides
    ``bursts``; chunk labels carry the channel-absolute index.
    """
    if domains is None:
        domains = [port for port, _ in channels]
    if chunk_counts is not None:
        if len(chunk_counts) != len(channels):
            raise ValueError(
                f"need one chunk count per channel, got "
                f"{len(chunk_counts)} for {len(channels)}"
            )
        if any(c < 1 for c in chunk_counts):
            raise ValueError(f"chunk counts must be >= 1: {chunk_counts}")
        bursts = max(
            -(-total // chunks_per_burst) for total in chunk_counts
        )
    programs = []
    for g in range(n):
        subs = []
        for b in range(bursts):
            order = list(enumerate(channels))
            if g == swap_order_rank:
                order = order[::-1]
            for i, (port, direction) in order:
                if chunk_counts is None:
                    ks = range(chunks_per_burst)
                else:
                    ks = range(
                        b * chunks_per_burst,
                        min((b + 1) * chunks_per_burst, chunk_counts[i]),
                    )
                    if not ks:
                        continue  # this stream already drained
                labels = [((g, i, b), k) for k in ks]
                subs.append(
                    instance_steps(
                        neighbour_stream_rank(
                            g, n, labels, direction=direction,
                            flow_control=flow_control,
                        ),
                        domain=domains[i], instance=(i, b),
                    )
                )
        programs.append(chain_programs(*subs))
    return programs


def simulate_stream_concurrent(
    n: int,
    strategy: Strategy,
    bursts: int = 2,
    chunks_per_burst: int = 4,
    domains: Optional[Sequence[int]] = None,
    flow_control: bool = True,
    swap_order_rank: Optional[int] = None,
) -> None:
    """Fuzz one schedule of two burst-interleaved concurrent streams
    (the ``stream_concurrent(backend="ring")`` shape: distinct ports,
    opposite directions) and verify per-instance delivery."""
    channels = [(0, 1), (1, -1)]
    outputs = RingSimulator(
        concurrent_stream_generators(
            n, channels, bursts, chunks_per_burst, domains, flow_control,
            swap_order_rank,
        ),
        strategy,
    ).run()
    for g in range(n):
        want = {}
        for b in range(bursts):
            for i, (_, direction) in enumerate(channels):
                up = (g - direction) % n
                for k in range(chunks_per_burst):
                    want[((i, b), k)] = ((up, i, b), k)
        if outputs[g] != want:
            raise ProtocolError(
                f"rank {g} received {outputs[g]}, wanted {want}"
            )


def simulate_tenant_streams(
    n: int,
    strategy: Strategy,
    chunk_counts: Sequence[int],
    chunks_per_burst: int = 2,
    flow_control: bool = True,
) -> List[Dict]:
    """Fuzz one schedule of UNEQUAL concurrent tenant streams on one
    wire (every channel direction +1 around the same ring, distinct
    port domains) and verify per-stream delivery. Returns the per-rank
    output dicts — their insertion order IS each rank's consumption
    order, which is what the fairness regression measures
    (:func:`fairness_gap`)."""
    channels = [(i, 1) for i in range(len(chunk_counts))]
    outputs = RingSimulator(
        concurrent_stream_generators(
            n, channels, chunks_per_burst=chunks_per_burst,
            flow_control=flow_control, chunk_counts=chunk_counts,
        ),
        strategy,
    ).run()
    for g in range(n):
        up = (g - 1) % n
        want = {}
        for i, total in enumerate(chunk_counts):
            for k in range(total):
                b, c = divmod(k, chunks_per_burst)
                # output keys are burst-relative positions (the
                # kernel's chunk loop index); payloads carry the
                # channel-absolute chunk label
                want[((i, b), c)] = ((up, i, b), k)
        if outputs[g] != want:
            raise ProtocolError(
                f"rank {g} received {outputs[g]}, wanted {want}"
            )
    return outputs


def fairness_gap(rank_outputs: Dict, stream: int) -> int:
    """Largest number of OTHER streams' chunks consumed between two
    consecutive chunks of ``stream`` (including before its first) in
    one rank's delivery order — the interleaving-gap metric of the
    starvation regression: the burst-interleaved schedule must bound
    it by ``(streams - 1) * chunks_per_burst`` no matter how adversarial
    the schedule, because the credit discipline admits at most one
    burst of each other stream between a live stream's bursts."""
    gap = 0
    run = 0
    for (instance, _k) in rank_outputs:
        if instance[0] == stream:
            gap = max(gap, run)
            run = 0
        else:
            run += 1
    return gap


# ---------------------------------------------------------------------------
# Discrete-event simulator
# ---------------------------------------------------------------------------


class Strategy:
    """Picks the next runnable entity. Subclass for adversarial orders."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def pick(self, choices: List):  # choices: ("rank", r) | ("dma", i)
        return self.rng.choice(choices)


class DelayDmaStrategy(Strategy):
    """Adversarial: let ranks run as far ahead as possible before any DMA
    lands — maximizes the window for clobbers."""

    def pick(self, choices):
        ranks = [c for c in choices if c[0] == "rank"]
        return self.rng.choice(ranks) if ranks else self.rng.choice(choices)


class FavourRankStrategy(Strategy):
    """Adversarial: one rank races ahead, the others lag."""

    def __init__(self, favourite: int, seed: int = 0):
        super().__init__(seed)
        self.favourite = favourite

    def pick(self, choices):
        favoured = [
            c for c in choices
            if c == ("rank", self.favourite)
        ]
        if favoured and self.rng.random() < 0.85:
            return favoured[0]
        return self.rng.choice(choices)


class FavourSetStrategy(Strategy):
    """Adversarial: a GROUP of ranks races ahead together.

    A single favoured rank cannot get a whole kernel instance ahead of
    its neighbours in a composite program — barrier counting holds it
    back — but a contiguous *plateau* of favoured ranks can carry its
    interior a full instance ahead of the trailing ranks, which is the
    schedule shape that turns a shared barrier domain into a clobber
    (see the shared-domain mutation tests)."""

    def __init__(self, favoured, seed: int = 0, bias: float = 0.9):
        super().__init__(seed)
        self.favoured = set(favoured)
        self.bias = bias

    def pick(self, choices):
        favoured = [
            c for c in choices
            if c[0] == "rank" and c[1] in self.favoured
        ]
        if favoured and self.rng.random() < self.bias:
            return self.rng.choice(favoured)
        return self.rng.choice(choices)


# ---------------------------------------------------------------------------
# Wire-tier cost model (simulated wall-clock)
# ---------------------------------------------------------------------------
# The simulator's schedule space proves SAFETY; the cost model prices
# PERFORMANCE on the same runs. Every wire event — a DMA landing, a
# cross-rank semaphore signal — carries a logical timestamp priced by
# the Hockney alpha-beta model of its tier (ICI within a slice, DCN
# between slices), and each rank's clock advances to the latest
# timestamp it consumed at a wait. The makespan (max rank clock at
# exit) is deterministic per (protocol, strategy, cost model) and
# schedule-shape-faithful: it is how the two-tier protocol's
# cross-the-slow-wire-once claim becomes an asserted number instead of
# prose. Fault plans perturb *ordering* only; the model prices the
# healthy wire (a held DMA still lands at start + transit).


@dataclasses.dataclass(frozen=True)
class LinkCost:
    """Hockney alpha-beta price of one wire tier."""

    alpha_s: float
    beta_bytes_per_s: float

    def dma_seconds(self, payload_bytes: float) -> float:
        return self.alpha_s + payload_bytes / self.beta_bytes_per_s


@dataclasses.dataclass(frozen=True)
class TierCostModel:
    """Per-tier event prices for one simulator run.

    ``bytes_per_message`` is the payload size of every DMA in the run
    (the simulator's payloads are symbolic; the harness knows the
    protocol's message granularity — the full payload for the flat
    circulating ring, ``payload / per_slice`` for every phase of the
    pod protocol). ``per_slice == 0`` means single-tier: every wire is
    ICI, which keeps all pre-pod harnesses pricable unchanged.

    ``ici_bytes`` / ``dcn_bytes`` optionally override the message size
    PER TIER — the two-tier all-to-all moves per-destination blocks on
    ICI but ``per_slice``-block bundles across DCN, so one global
    granularity cannot price both wire populations. ``None`` (the
    default) keeps the single ``bytes_per_message``, so every existing
    harness prices identically.
    """

    bytes_per_message: float
    ici: LinkCost
    dcn: LinkCost
    per_slice: int = 0
    ici_bytes: Optional[float] = None
    dcn_bytes: Optional[float] = None

    def crosses_dcn(self, a: int, b: int) -> bool:
        return bool(
            self.per_slice
            and a // self.per_slice != b // self.per_slice
        )

    def link(self, a: int, b: int) -> LinkCost:
        return self.dcn if self.crosses_dcn(a, b) else self.ici

    def tier_bytes(self, src: int, dst: int) -> float:
        """The message size this (src, dst) wire carries: the tier's
        override when set, else the run-wide granularity."""
        if self.crosses_dcn(src, dst):
            if self.dcn_bytes is not None:
                return self.dcn_bytes
        elif self.ici_bytes is not None:
            return self.ici_bytes
        return self.bytes_per_message

    def dma_seconds(self, src: int, dst: int) -> float:
        return self.link(src, dst).dma_seconds(
            self.tier_bytes(src, dst)
        )

    def signal_seconds(self, src: int, dst: int) -> float:
        """A bare semaphore signal pays its tier's latency (no payload)."""
        if src == dst:
            return 0.0
        return self.link(src, dst).alpha_s


def default_tier_costs(bytes_per_message: float, per_slice: int = 0,
                       ici: Optional[LinkCost] = None,
                       dcn: Optional[LinkCost] = None,
                       ici_bytes: Optional[float] = None,
                       dcn_bytes: Optional[float] = None) -> TierCostModel:
    """Tier costs at the cost model's published rates: v5e ICI for the
    fast tier, the DCN alpha/beta (env-overridable beta,
    ``$SMI_TPU_DCN_BETA``) for the slow one. Deferred import — credits
    stays importable without the tuning package. ``ici_bytes`` /
    ``dcn_bytes`` pass through the per-tier message-size overrides
    (the two-tier all-to-all's mixed granularities)."""
    from smi_tpu.tuning import cost_model as cm

    return TierCostModel(
        bytes_per_message=bytes_per_message,
        ici=ici if ici is not None else LinkCost(
            cm.DEFAULT_ALPHA_S, cm.V5E_ICI_BETA_BYTES_PER_S
        ),
        dcn=dcn if dcn is not None else LinkCost(
            cm.DCN_ALPHA_S, cm.dcn_beta_bytes_per_s()
        ),
        per_slice=per_slice,
        ici_bytes=ici_bytes,
        dcn_bytes=dcn_bytes,
    )


class RingSimulator:
    """Execute per-rank protocol generators under one schedule.

    ``coarse=True`` makes a scheduled rank run atomically until a
    *communication boundary*: a DMA start (which creates a new schedulable
    landing) or a wait it cannot yet satisfy. This is a partial-order
    reduction — local actions and counting-semaphore signals commute with
    other ranks' actions, so only the DMA-landing / rank-progress
    interleavings carry nondeterminism. It shrinks the schedule space
    enough for :func:`explore_all_schedules` to cover tiny configurations
    completely without losing any detectable race.

    ``faults`` is an optional fault plan (duck-typed; the canonical
    implementation is :class:`smi_tpu.parallel.faults.FaultPlan`)
    providing four hooks:

    - ``grant_multiplier(rank, nth) -> int`` — 0 drops / 2 duplicates
      the ``nth`` credit grant signalled by ``rank`` (1 = healthy);
    - ``dma_hold(src, nth) -> int`` — scheduler events for which the
      ``nth`` DMA started by ``src`` may not land (delay, never loss:
      a held DMA becomes landable when nothing else can run); a plan
      may instead provide ``dma_hold_to(src, dst, nth)`` (preferred
      when present) to make the hold destination-aware — how the DCN
      tier's cross-slice-only delays are expressed;
    - ``stall_after(rank) -> Optional[int]`` — crash-stop ``rank``
      after that many executed actions (None = healthy);
    - ``link_down(a, b) -> bool`` — all traffic between global ranks
      ``a`` and ``b`` (signals and DMAs, both directions) is lost; a
      plan may instead provide ``link_blocked(src, dst, tick)``
      (preferred when present) — tick-aware and DIRECTIONAL, which is
      how windowed partitions, asymmetric cuts (A hears B while B
      stops hearing A), and seeded flapping links are expressed;
      the tick is the scheduler's ``sim_tick`` logical clock;
    - ``tamper(src, nth, payload) -> payload`` (optional) — damage the
      ``nth`` DMA payload started by ``src`` in flight (bit flip,
      truncation, sequence swap). The simulator applies it blindly;
      detection is the verified-transport framing's job
      (:func:`verified_steps`).

    ``recorder`` is an optional flight recorder (duck-typed — the
    canonical implementation is
    :class:`smi_tpu.obs.events.FlightRecorder`; this module never
    imports the obs layer, the fault-plan discipline): every credit
    grant/wait, barrier, and DMA start/landing emits a structured
    event stamped with the scheduler's logical tick, and every
    :class:`ProtocolError` leaving :meth:`run` (and every
    :meth:`state_dump`) carries the recorder's bounded tail — a
    deadlock names its causal history, not just its final state. With
    no recorder the hot path is untouched (one ``is None`` test per
    primitive).
    """

    def __init__(self, generators: Sequence[Iterator], strategy: Strategy,
                 coarse: bool = False, faults=None,
                 costs: Optional[TierCostModel] = None,
                 recorder=None):
        self.gens = list(generators)
        self.n = len(self.gens)
        self.strategy = strategy
        self.coarse = coarse
        self.faults = faults
        # structured-event hook (None = zero overhead); sim_tick is
        # the scheduler's executed-event count — the logical clock
        # every emitted event is stamped with
        self.recorder = recorder
        self.sim_tick = 0
        # wire-tier cost model: logical timestamps on every semaphore
        # increment + per-rank clocks -> simulated wall-clock
        self.costs = costs
        self.clock: List[float] = [0.0] * self.n
        self.sem_times: Dict[Tuple[int, str, object], List[float]] = {}
        self.sems: Dict[Tuple[int, str, int], int] = {}
        self.slots: Dict[Tuple[int, int], _Slot] = {}
        self.inflight: List[Optional[_Dma]] = []
        self.outputs: List[Dict] = [dict() for _ in range(self.n)]
        # fault bookkeeping: per-rank executed actions / issued credit
        # grants / started DMAs, per-DMA remaining hold, lost DMAs
        self.actions_done: List[int] = [0] * self.n
        self.grants_done: List[int] = [0] * self.n
        self.dmas_started: List[int] = [0] * self.n
        self.dma_holds: Dict[int, int] = {}
        self.undeliverable: List[_Dma] = []
        # (pending_action, value_to_send) per rank; None action = finished
        self.state: List[Optional[Tuple]] = []
        for gen in self.gens:
            try:
                action = next(gen)
                self.state.append((action, None))
            except StopIteration:
                self.state.append(None)

    # -- helpers --
    def _sem(self, rank: int, name: str, index: int) -> int:
        return self.sems.get((rank, name, index), 0)

    def _add(self, rank: int, name: str, index: int, inc: int) -> None:
        key = (rank, name, index)
        self.sems[key] = self.sems.get(key, 0) + inc

    def _slot(self, rank: int, index: int) -> _Slot:
        return self.slots.setdefault((rank, index), _Slot())

    # -- wire-time accounting (cost model active only) --
    def _push_time(self, key, at: float, times: int = 1) -> None:
        lane = self.sem_times.setdefault(key, [])
        for _ in range(times):
            bisect.insort(lane, at)

    def _pop_times(self, key, amount: int) -> float:
        """Availability time of the ``amount`` earliest increments a
        wait consumed (FIFO-by-time pairing)."""
        lane = self.sem_times.get(key, [])
        take = min(amount, len(lane))
        if take == 0:
            return 0.0
        popped = lane[:take]
        del lane[:take]
        return popped[-1]

    def elapsed_seconds(self) -> float:
        """Simulated wall-clock of the run (0.0 without a cost model):
        the slowest rank's clock — deterministic per (protocol,
        strategy, cost model)."""
        if self.costs is None or not self.clock:
            return 0.0
        return max(self.clock)

    # -- fault hooks --
    def _stalled(self, r: int) -> bool:
        if self.faults is None:
            return False
        after = self.faults.stall_after(r)
        return after is not None and self.actions_done[r] >= after

    def _link_down(self, a: int, b: int) -> bool:
        if self.faults is None:
            return False
        # tick-aware directional hook preferred when the plan has one
        # (windowed partitions / asymmetric cuts / flapping links heal
        # mid-run, so the answer depends on WHEN and WHICH WAY); plans
        # without it keep the static symmetric semantics bit-for-bit
        blocked = getattr(self.faults, "link_blocked", None)
        if blocked is not None:
            return blocked(a, b, self.sim_tick)
        return self.faults.link_down(a, b)

    # -- flight-recorder hooks (no-ops without a recorder) --
    @staticmethod
    def _obs_scalar(value):
        """Semaphore indexes / slots may be tuples (phase domains,
        per-round lanes); events carry JSON scalars."""
        return value if isinstance(value, (int, float, str)) else str(value)

    def _attach_recorder_tail(self, error: BaseException) -> None:
        """Bounded causal history onto an escaping error — on the
        ``recorder_tail`` attribute, and inside the structured
        ``state`` dict when the error carries one. Never raises (the
        tail must not mask the error it annotates)."""
        if self.recorder is None:
            return
        try:
            tail = self.recorder.tail()
            error.recorder_tail = tail
            state = getattr(error, "state", None)
            if isinstance(state, dict):
                state.setdefault("flight_recorder", tail)
        except Exception:
            pass

    # -- execution --
    def _runnable(self) -> List:
        out = []
        for r, st in enumerate(self.state):
            if st is None or self._stalled(r):
                continue
            action, _ = st
            if action[0] == "wait":
                _, name, index, amount = action
                if self._sem(r, name, index) >= amount:
                    out.append(("rank", r))
            else:
                out.append(("rank", r))
        held = []
        for i, dma in enumerate(self.inflight):
            if dma is not None:
                if self.dma_holds.get(i, 0) > 0:
                    held.append(("dma", i))
                else:
                    out.append(("dma", i))
        if not out and held:
            # a delayed DMA is slow, never lost: once nothing else can
            # run, the oldest held copy completes rather than deadlock
            return held[:1]
        return out

    def _advance(self, r: int, value=None) -> None:
        try:
            action = self.gens[r].send(value)
            self.state[r] = (action, None)
        except StopIteration:
            self.state[r] = None

    def _execute_rank(self, r: int) -> None:
        while True:
            kind = self.state[r][0][0]
            self._execute_one(r)
            if not self.coarse or kind == "dma":
                return  # dma start is a boundary: its landing must be
                        # schedulable before this rank continues
            st = self.state[r]
            if st is None or self._stalled(r):
                return
            nxt = st[0]
            if nxt[0] == "wait":
                _, name, index, amount = nxt
                if self._sem(r, name, index) < amount:
                    return  # blocked

    def _execute_one(self, r: int) -> None:
        action, _ = self.state[r]
        kind = action[0]
        self.actions_done[r] += 1
        self.sim_tick += 1
        if kind == "wait":
            _, name, index, amount = action
            if self.recorder is not None:
                if name == SEM_CREDIT:
                    self.recorder.emit(
                        "credit.wait", self.sim_tick, rank=r,
                        index=self._obs_scalar(index),
                    )
                elif name == SEM_BARRIER:
                    self.recorder.emit("barrier.wait", self.sim_tick,
                                       rank=r)
            self._add(r, name, index, -amount)
            if self.costs is not None:
                self.clock[r] = max(
                    self.clock[r],
                    self._pop_times((r, name, index), amount),
                )
            self._advance(r)
        elif kind == "signal":
            _, target, name, index, inc = action
            mult = 1
            if self.faults is not None:
                if target != r and self._link_down(r, target):
                    mult = 0  # lost on the dead wire
                elif name == SEM_CREDIT:
                    mult = self.faults.grant_multiplier(
                        r, self.grants_done[r]
                    )
            if name == SEM_CREDIT:
                self.grants_done[r] += 1
            if self.recorder is not None:
                if name == SEM_CREDIT:
                    extra = {} if mult == 1 else {"mult": mult}
                    self.recorder.emit(
                        "credit.grant", self.sim_tick, rank=r,
                        src=r, dst=target,
                        index=self._obs_scalar(index), **extra,
                    )
                elif name == SEM_BARRIER:
                    self.recorder.emit("barrier.signal", self.sim_tick,
                                       rank=r, src=r, dst=target)
            if mult:
                self._add(target, name, index, inc * mult)
                if self.costs is not None:
                    self._push_time(
                        (target, name, index),
                        self.clock[r]
                        + self.costs.signal_seconds(r, target),
                        times=inc * mult,
                    )
            self._advance(r)
        elif kind == "dma":
            _, target, slot, payload, send_index, recv_index = action
            nth = self.dmas_started[r]
            self.dmas_started[r] += 1
            if self.faults is not None:
                # in-flight payload tampering (bit flips, truncation,
                # reordering): the wire damages the snapshot, the
                # protocol machinery never notices — only the framing
                # layer (verified_steps) can turn this into a named
                # IntegrityError instead of silent corruption
                tamper = getattr(self.faults, "tamper", None)
                if tamper is not None:
                    payload = tamper(r, nth, payload)
            if self.recorder is not None:
                self.recorder.emit(
                    "dma.start", self.sim_tick, rank=r,
                    src=r, dst=target, slot=self._obs_scalar(slot),
                )
            dma = _Dma(src=r, target=target, slot=slot, payload=payload,
                       send_index=send_index, recv_index=recv_index,
                       origin=(r, self.actions_done[r] - 1))
            if self.costs is not None:
                dma.ready_at = (
                    self.clock[r] + self.costs.dma_seconds(r, target)
                )
            if target != r and self._link_down(r, target):
                # the wire is dead: neither the remote landing nor the
                # local send completion ever fires — the writer's
                # wait(SEM_SEND) is where the loss becomes visible
                self.undeliverable.append(dma)
                self._advance(r)
                return
            self.inflight.append(dma)
            if self.faults is not None:
                # destination-aware holds (the DCN tier's cross-slice
                # delays) when the plan provides them, else the
                # original per-source hook
                hold_to = getattr(self.faults, "dma_hold_to", None)
                hold = (hold_to(r, target, nth) if hold_to is not None
                        else self.faults.dma_hold(r, nth))
                if hold:
                    self.dma_holds[len(self.inflight) - 1] = hold
            # send completion = source buffer reusable; worst case this is
            # immediate, long before the remote landing
            self._add(r, SEM_SEND, send_index, 1)
            if self.costs is not None:
                self._push_time((r, SEM_SEND, send_index), self.clock[r])
            self._advance(r)
        elif kind == "write_slot":
            _, slot, payload = action
            s = self._slot(r, slot)
            s.payload, s.full, s.consumed = payload, True, False
            self._advance(r)
        elif kind == "read_slot":
            _, slot = action
            s = self._slot(r, slot)
            if not s.full:
                raise ProtocolError(
                    f"rank {r} read empty slot {slot}"
                )
            s.consumed = True
            self._advance(r, s.payload)
        elif kind == "output":
            _, key, payload = action
            self.outputs[r][key] = payload
            self._advance(r)
        else:  # pragma: no cover
            raise ValueError(f"unknown action {action!r}")

    def _land_dma(self, i: int) -> None:
        dma = self.inflight[i]
        self.inflight[i] = None
        self.sim_tick += 1
        if self.recorder is not None:
            self.recorder.emit(
                "dma.land", self.sim_tick, rank=dma.target,
                src=dma.src, dst=dma.target,
                slot=self._obs_scalar(dma.slot),
            )
        s = self._slot(dma.target, dma.slot)
        if s.full and not s.consumed:
            raise ClobberError(
                f"DMA from rank {dma.src} landed on rank {dma.target} "
                f"slot {dma.slot} holding unconsumed data"
            )
        s.payload, s.full, s.consumed = dma.payload, True, False
        self._add(dma.target, SEM_RECV, dma.recv_index, 1)
        if self.costs is not None:
            self._push_time(
                (dma.target, SEM_RECV, dma.recv_index), dma.ready_at
            )

    def run(self, max_steps: int = 1_000_000) -> List[Dict]:
        try:
            return self._run(max_steps)
        except ProtocolError as e:
            # a deadlock / clobber / integrity failure leaves with the
            # recorder's bounded causal history attached (the dump in
            # a DeadlockError.state already carries it via state_dump)
            self._attach_recorder_tail(e)
            raise

    def _run(self, max_steps: int) -> List[Dict]:
        for _ in range(max_steps):
            if all(st is None for st in self.state) and not any(
                d is not None for d in self.inflight
            ):
                self._check_drained()
                return self.outputs
            choices = self._runnable()
            if not choices:
                state = self.state_dump()
                raise DeadlockError(
                    "no runnable entity; per-rank protocol state:\n"
                    + format_state_dump(state),
                    state=state,
                )
            if self.dma_holds:
                # delayed DMAs age one scheduler event per iteration
                self.dma_holds = {
                    i: h - 1 for i, h in self.dma_holds.items() if h > 1
                }
            kind, idx = self.strategy.pick(choices)
            if kind == "rank":
                self._execute_rank(idx)
            else:
                self._land_dma(idx)
        raise ProtocolError("simulation did not terminate")

    def state_dump(self) -> Dict:
        """Per-rank protocol state: what each rank is doing (finished /
        stalled / blocked-at-wait / runnable), its output count, plus
        in-flight and lost DMAs and non-zero semaphores. Attached to
        every :class:`DeadlockError` and surfaced by the runtime
        watchdogs (:mod:`smi_tpu.utils.watchdog`)."""
        dump: Dict = {}
        for r, st in enumerate(self.state):
            if st is None:
                dump[r] = {"state": "finished", "pending": None,
                           "outputs": len(self.outputs[r])}
                continue
            action = st[0]
            if self._stalled(r):
                state = "stalled"
            elif action[0] == "wait":
                _, name, index, amount = action
                state = (
                    "blocked"
                    if self._sem(r, name, index) < amount else "runnable"
                )
            else:
                state = "runnable"
            dump[r] = {"state": state, "pending": action,
                       "outputs": len(self.outputs[r])}
        dump["inflight"] = [
            (d.src, d.target, d.slot)
            for d in self.inflight if d is not None
        ]
        dump["undeliverable"] = [
            (d.src, d.target, d.slot) for d in self.undeliverable
        ]
        dump["sems"] = {k: v for k, v in self.sems.items() if v != 0}
        if self.recorder is not None:
            # the causal history behind the final state: bounded,
            # dropped-event-counted (never silently truncated)
            try:
                dump["flight_recorder"] = self.recorder.tail()
            except Exception:
                pass
        return dump

    def _check_drained(self) -> None:
        leaked = {k: v for k, v in self.sems.items() if v != 0}
        if leaked:
            raise CreditLeakError(
                f"semaphores non-zero at exit: {leaked}"
            )


# ---------------------------------------------------------------------------
# Exhaustive exploration (tiny configurations)
# ---------------------------------------------------------------------------


class ScheduleCount(int):
    """The count :func:`explore_all_schedules` returns, with coverage.

    Behaves as the plain ``int`` it always was (``explored`` complete
    schedules), plus the no-silent-caps bookkeeping:

    - ``explored`` — complete schedules verified (== ``int(self)``);
    - ``truncated`` — True when the budget stopped the DFS before the
      space was exhausted;
    - ``frontier`` — unexplored branch prefixes remaining at the stop
      (each leads to >= 1 further schedule);
    - ``estimated_total`` — the space size this run can attest:
      exactly ``explored`` when the DFS completed, else the LOWER BOUND
      ``explored + frontier`` (the true total is usually far larger —
      the bound is what one truncated run can honestly claim).
    """

    explored: int
    truncated: bool
    frontier: int
    estimated_total: int

    def __new__(cls, explored: int, truncated: bool = False,
                frontier: int = 0):
        self = super().__new__(cls, explored)
        self.explored = explored
        self.truncated = truncated
        self.frontier = frontier
        self.estimated_total = explored + frontier
        return self

    def to_json(self) -> dict:
        """Machine-readable coverage (the ``smi-tpu lint --json``
        field shape): a truncating budget is never a warning-only
        event — report consumers see explored/estimated_total/
        truncated explicitly."""
        return {
            "explored": self.explored,
            "truncated": self.truncated,
            "frontier": self.frontier,
            "estimated_total": self.estimated_total,
        }


def explore_all_schedules(make_generators: Callable[[], Sequence[Iterator]],
                          max_schedules: int = 200_000,
                          allow_budget: bool = False) -> "ScheduleCount":
    """Depth-first over *every* scheduler choice for a tiny configuration.

    Re-instantiates the generators per path (generators are single-shot),
    replaying a prefix of choices then branching. Returns the number of
    complete schedules explored — a :class:`ScheduleCount`, an ``int``
    subclass carrying explored/estimated-total coverage — and raises on
    any invariant violation.

    ``allow_budget=True`` turns budget exhaustion from an error into a
    clean return of the count: the caller asserts "the first
    ``max_schedules`` schedules in deterministic DFS order all hold"
    — the honest claim for composites whose full space is beyond
    exhaustive reach (the 4-rank two-tier pod, the 2x2 halo), where
    exceeding the budget is the expected outcome, not a test bug. A
    truncating budget is never silent: the returned count has
    ``truncated=True`` and a ``RuntimeWarning`` states how much of the
    space the run actually covered.
    """

    class _Replay(Strategy):
        def __init__(self, prefix: List):
            self.prefix = list(prefix)
            self.trace: List = []
            self.branch_points: List[Tuple[int, List]] = []

        def pick(self, choices):
            choices = sorted(choices)
            i = len(self.trace)
            if i < len(self.prefix):
                choice = self.prefix[i]
                if choice not in choices:
                    raise ProtocolError(
                        "schedule replay diverged; simulator is "
                        "nondeterministic beyond scheduler choice"
                    )
            else:
                choice = choices[0]
                if len(choices) > 1:
                    self.branch_points.append((i, choices[1:]))
            self.trace.append(choice)
            return choice

    stack: List[List] = [[]]
    explored = 0
    while stack:
        prefix = stack.pop()
        strategy = _Replay(prefix)
        RingSimulator(make_generators(), strategy, coarse=True).run()
        explored += 1
        if explored >= max_schedules:
            if allow_budget:
                # "no silent caps": the pending frontier bounds what
                # was NOT covered — say so loudly instead of letting a
                # capped DFS read as full coverage
                frontier = len(stack) + sum(
                    len(alts) for i, alts in strategy.branch_points
                    if i >= len(prefix)
                )
                if frontier:
                    import warnings

                    warnings.warn(
                        f"explore_all_schedules: budget of "
                        f"{max_schedules} truncated the space after "
                        f"{explored} schedules; >= "
                        f"{explored + frontier} exist ({frontier} "
                        f"unexplored branch prefixes remain) — the "
                        f"verified claim is 'the first {explored} "
                        f"schedules in DFS order hold', NOT full "
                        f"coverage",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                return ScheduleCount(explored, truncated=bool(frontier),
                                     frontier=frontier)
            raise ProtocolError(
                f"exploration budget exceeded ({max_schedules} schedules)"
            )
        for i, alternatives in strategy.branch_points:
            if i >= len(prefix):  # only branch beyond the replayed prefix
                for alt in alternatives:
                    stack.append(strategy.trace[:i] + [alt])
    return ScheduleCount(explored)


# ---------------------------------------------------------------------------
# Convenience harnesses
# ---------------------------------------------------------------------------


def _maybe_verified(gens: Sequence[Iterator], verified: bool):
    """Wrap each rank in the verified-transport framing when asked —
    the harness knob that decides whether payload tampering surfaces
    as a named IntegrityError (framed) or as silently wrong delivery
    (bare transport, caught only by the harness's output check)."""
    if not verified:
        return list(gens)
    return [verified_steps(gen, r) for r, gen in enumerate(gens)]


def simulate_all_gather(n: int, strategy: Strategy,
                        flow_control: bool = True, faults=None,
                        verified: bool = False, recorder=None) -> None:
    gens = [
        all_gather_rank(r, n, f"chunk{r}", flow_control=flow_control)
        for r in range(n)
    ]
    outputs = RingSimulator(
        _maybe_verified(gens, verified), strategy, faults=faults,
        recorder=recorder,
    ).run()
    expected = {i: f"chunk{i}" for i in range(n)}
    for r in range(n):
        if outputs[r] != expected:
            raise ProtocolError(
                f"rank {r} gathered {outputs[r]}, wanted {expected}"
            )


def simulate_all_reduce(n: int, strategy: Strategy,
                        flow_control: bool = True, faults=None,
                        verified: bool = False,
                        costs: Optional[TierCostModel] = None,
                        recorder=None) -> float:
    gens = [
        all_reduce_rank(r, n, frozenset([r]), lambda a, b: a | b,
                        flow_control=flow_control)
        for r in range(n)
    ]
    sim = RingSimulator(
        _maybe_verified(gens, verified), strategy, faults=faults,
        costs=costs, recorder=recorder,
    )
    outputs = sim.run()
    want = frozenset(range(n))
    for r in range(n):
        if outputs[r] != {0: want}:
            raise ProtocolError(f"rank {r} reduced {outputs[r]}, wanted {want}")
    return sim.elapsed_seconds()


def simulate_all_reduce_chunked(n: int, chunks: int, strategy: Strategy,
                                flow_control: bool = True, faults=None,
                                verified: bool = False,
                                recorder=None) -> None:
    """Chunked pipelined all-reduce harness: rank ``r`` contributes
    ``frozenset({(r, c)})`` per chunk ``c``; every rank must finish
    holding the full per-chunk union — wrong delivery in ANY pipeline
    chunk is a :class:`ProtocolError`."""
    gens = [
        all_reduce_chunked_rank(
            r, n, [frozenset([(r, c)]) for c in range(chunks)],
            lambda a, b: a | b, flow_control=flow_control,
        )
        for r in range(n)
    ]
    outputs = RingSimulator(
        _maybe_verified(gens, verified), strategy, faults=faults,
        recorder=recorder,
    ).run()
    want = {
        c: frozenset((src, c) for src in range(n)) for c in range(chunks)
    }
    for r in range(n):
        if outputs[r] != want:
            raise ProtocolError(
                f"rank {r} reduced {outputs[r]}, wanted {want}"
            )


def simulate_reduce_scatter(n: int, strategy: Strategy,
                            flow_control: bool = True,
                            faults=None, verified: bool = False,
                            recorder=None) -> None:
    gens = [
        reduce_scatter_rank(
            r, n, [frozenset([(r, b)]) for b in range(n)],
            lambda a, b: a | b, flow_control=flow_control,
        )
        for r in range(n)
    ]
    outputs = RingSimulator(
        _maybe_verified(gens, verified), strategy, faults=faults,
        recorder=recorder,
    ).run()
    for r in range(n):
        want = frozenset((src, r) for src in range(n))
        if outputs[r] != {r: want}:
            raise ProtocolError(
                f"rank {r} got {outputs[r]}, wanted {want}"
            )


def allreduce_pod_generators(slices: int, per_slice: int,
                             flow_control: bool = True):
    """Per-rank two-tier allreduce programs with the standard symbolic
    contributions: rank ``g`` contributes ``frozenset({(g, c)})`` per
    block ``c``."""
    n = slices * per_slice
    return [
        allreduce_pod_rank(
            g, slices, per_slice,
            [frozenset([(g, c)]) for c in range(per_slice)],
            lambda a, b: a | b, flow_control=flow_control,
        )
        for g in range(n)
    ]


def simulate_allreduce_pod(slices: int, per_slice: int, strategy: Strategy,
                           flow_control: bool = True, faults=None,
                           verified: bool = False,
                           costs: Optional[TierCostModel] = None,
                           recorder=None) -> float:
    """Fuzz one schedule of the two-tier pod allreduce and verify that
    every rank holds the full per-block reduction — wrong delivery in
    ANY block of ANY phase is a :class:`ProtocolError`. Returns the
    simulated wall-clock (0.0 without a cost model)."""
    n = slices * per_slice
    sim = RingSimulator(
        _maybe_verified(
            allreduce_pod_generators(slices, per_slice, flow_control),
            verified,
        ),
        strategy, faults=faults, costs=costs, recorder=recorder,
    )
    outputs = sim.run()
    want = {
        c: frozenset((g, c) for g in range(n))
        for c in range(per_slice)
    }
    for g in range(n):
        if outputs[g] != want:
            raise ProtocolError(
                f"rank {g} reduced {outputs[g]}, wanted {want}"
            )
    return sim.elapsed_seconds()


def pod_wallclock_comparison(slices: int, per_slice: int,
                             payload_bytes: float, seed: int = 0,
                             ici: Optional[LinkCost] = None,
                             dcn: Optional[LinkCost] = None) -> Dict:
    """Same allreduce payload, flat ring vs two-tier pod protocol, on
    the same deterministic schedule seed and wire rates.

    The flat circulating ring moves the FULL payload per message and
    its rank order makes two wires per lap cross slices (between slice
    boundaries and on the wrap); the pod protocol's every message is a
    ``payload / per_slice`` shard and only phase B touches DCN. Both
    runs must deliver the identical reduction — the bit-identity half
    of the claim — and the returned dict carries the two makespans for
    the perf half. Deterministic per (shape, payload, seed, rates).
    """
    n = slices * per_slice
    flat_costs = default_tier_costs(payload_bytes, per_slice,
                                    ici=ici, dcn=dcn)
    hier_costs = default_tier_costs(payload_bytes / per_slice, per_slice,
                                    ici=ici, dcn=dcn)
    # flat: every rank contributes ALL its blocks in one payload
    flat_gens = [
        all_reduce_rank(
            g, n, frozenset((g, c) for c in range(per_slice)),
            lambda a, b: a | b,
        )
        for g in range(n)
    ]
    flat_sim = RingSimulator(flat_gens, Strategy(seed), costs=flat_costs)
    flat_out = flat_sim.run()
    want = frozenset(
        (g, c) for g in range(n) for c in range(per_slice)
    )
    for g in range(n):
        if flat_out[g] != {0: want}:
            raise ProtocolError(
                f"flat rank {g} reduced {flat_out[g]}, wanted {want}"
            )
    hier_sim = RingSimulator(
        allreduce_pod_generators(slices, per_slice),
        Strategy(seed), costs=hier_costs,
    )
    hier_out = hier_sim.run()
    want_blocks = {
        c: frozenset((g, c) for g in range(n))
        for c in range(per_slice)
    }
    for g in range(n):
        if hier_out[g] != want_blocks:
            raise ProtocolError(
                f"pod rank {g} reduced {hier_out[g]}, "
                f"wanted {want_blocks}"
            )
    return {
        "slices": slices,
        "per_slice": per_slice,
        "payload_bytes": payload_bytes,
        "flat_s": flat_sim.elapsed_seconds(),
        "hierarchical_s": hier_sim.elapsed_seconds(),
    }


def _q_encode(v):
    """The harness wire codec: tag every element — content-addressed,
    so the delivery check proves the codec round-tripped through every
    hop (wrong bits OR a skipped decode both fail)."""
    return frozenset(("q8", e) for e in v)


def _q_decode(w):
    """Inverse of :func:`_q_encode`, type-preserving under in-flight
    damage: an element that is not a recognized tag (a bitflipped
    marker, a truncated pair) decodes to itself, so bare-transport
    corruption COMPLETES with wrong delivery — the silent-corruption
    outcome the framing exists to catch — instead of crashing."""
    return frozenset(
        e[1] if isinstance(e, tuple) and len(e) == 2 and e[0] == "q8"
        else e
        for e in w
    )


def all_reduce_quantized_generators(slices: int, per_slice: int,
                                    flow_control: bool = True):
    """Per-rank quantized two-tier allreduce programs with the standard
    symbolic contributions under the tagging wire codec."""
    n = slices * per_slice
    return [
        all_reduce_quantized_rank(
            g, slices, per_slice,
            [frozenset([(g, c)]) for c in range(per_slice)],
            lambda a, b: a | b, _q_encode, _q_decode,
            flow_control=flow_control,
        )
        for g in range(n)
    ]


def simulate_all_reduce_quantized(slices: int, per_slice: int,
                                  strategy: Strategy,
                                  flow_control: bool = True, faults=None,
                                  verified: bool = False,
                                  costs: Optional[TierCostModel] = None,
                                  recorder=None) -> float:
    """Fuzz one schedule of the quantized pod allreduce and verify that
    every rank's DECODED outputs hold the full per-block reduction —
    wrong delivery in any block, or a codec that failed to round-trip,
    is a :class:`ProtocolError`. Returns the simulated wall-clock."""
    n = slices * per_slice
    sim = RingSimulator(
        _maybe_verified(
            all_reduce_quantized_generators(slices, per_slice,
                                            flow_control),
            verified,
        ),
        strategy, faults=faults, costs=costs, recorder=recorder,
    )
    outputs = sim.run()
    want = {
        c: frozenset((g, c) for g in range(n))
        for c in range(per_slice)
    }
    for g in range(n):
        if outputs[g] != want:
            raise ProtocolError(
                f"rank {g} reduced {outputs[g]}, wanted {want}"
            )
    return sim.elapsed_seconds()


def _sparse_bundle(src: int):
    """The standard symbolic sparse contribution: one opaque
    (index, value) bundle, content-addressed per source."""
    return (("idx", src), ("val", src))


def all_reduce_sparse_generators(n: int, flow_control: bool = True):
    """Per-rank sparse allreduce programs with the standard bundles.
    The local ``combine`` is the identity on the gathered tuple — the
    harness's delivery check addresses every bundle by content, so
    wrong routing and wrong bits both fail."""
    return [
        all_reduce_sparse_rank(r, n, _sparse_bundle(r), lambda bs: bs,
                               flow_control=flow_control)
        for r in range(n)
    ]


def simulate_all_reduce_sparse(n: int, strategy: Strategy,
                               flow_control: bool = True, faults=None,
                               verified: bool = False,
                               costs: Optional[TierCostModel] = None,
                               recorder=None) -> float:
    """Fuzz one schedule of the sparse allreduce and verify that every
    rank gathered every source's bundle in source order — a missing,
    damaged, or misrouted bundle is a :class:`ProtocolError`. Returns
    the simulated wall-clock."""
    sim = RingSimulator(
        _maybe_verified(
            all_reduce_sparse_generators(n, flow_control), verified
        ),
        strategy, faults=faults, costs=costs, recorder=recorder,
    )
    outputs = sim.run()
    want = {0: tuple(_sparse_bundle(src) for src in range(n))}
    for r in range(n):
        if outputs[r] != want:
            raise ProtocolError(
                f"rank {r} reduced {outputs[r]}, wanted {want}"
            )
    return sim.elapsed_seconds()


def quantized_wallclock_comparison(slices: int, per_slice: int,
                                   payload_bytes: float,
                                   precision: str = "int8",
                                   seed: int = 0,
                                   ici: Optional[LinkCost] = None,
                                   dcn: Optional[LinkCost] = None) -> Dict:
    """Same two-tier allreduce, f32 wire vs quantized wire, on the same
    deterministic schedule seed and rates — the r19 A/B vector.

    Both runs are the pod composition at ``payload/per_slice`` shard
    granularity; the quantized run's every wire message is scaled by
    :data:`PRECISION_WIRE_RATIO` through the per-tier ``ici_bytes`` /
    ``dcn_bytes`` (the PR-12 sizing), and its protocol is
    ``all_reduce_quantized`` — codec applied, delivery verified
    per-block against the identical reduction the f32 run must also
    deliver. The dict carries the two makespans plus each run's
    analytic DCN-phase wall-clock ((slices-1) crossings of the shard
    at the tier's alpha-beta) — the phase the beta attack targets.
    Deterministic per (shape, payload, precision, seed, rates)."""
    if precision not in PRECISION_WIRE_RATIO:
        raise ValueError(
            f"unknown precision {precision!r}; known: "
            f"{sorted(PRECISION_WIRE_RATIO)}"
        )
    ratio = PRECISION_WIRE_RATIO[precision]
    shard = payload_bytes / per_slice
    f32_costs = default_tier_costs(shard, per_slice, ici=ici, dcn=dcn)
    q_costs = default_tier_costs(
        shard * ratio, per_slice, ici=ici, dcn=dcn,
        ici_bytes=shard * ratio, dcn_bytes=shard * ratio,
    )
    n = slices * per_slice
    want = {
        c: frozenset((g, c) for g in range(n))
        for c in range(per_slice)
    }
    f32_sim = RingSimulator(
        allreduce_pod_generators(slices, per_slice),
        Strategy(seed), costs=f32_costs,
    )
    f32_out = f32_sim.run()
    for g in range(n):
        if f32_out[g] != want:
            raise ProtocolError(
                f"f32 rank {g} reduced {f32_out[g]}, wanted {want}"
            )
    q_sim = RingSimulator(
        all_reduce_quantized_generators(slices, per_slice),
        Strategy(seed), costs=q_costs,
    )
    q_out = q_sim.run()
    for g in range(n):
        if q_out[g] != want:
            raise ProtocolError(
                f"quantized rank {g} reduced {q_out[g]}, wanted {want}"
            )

    def dcn_phase(costs: TierCostModel) -> float:
        if slices < 2:
            return 0.0
        # one rank's phase-B lap: (slices - 1) steps, each one DCN
        # crossing of the shard at the slow tier's alpha + bytes/beta
        return (slices - 1) * costs.dma_seconds(0, per_slice)

    return {
        "slices": slices,
        "per_slice": per_slice,
        "payload_bytes": payload_bytes,
        "precision": precision,
        "f32_s": f32_sim.elapsed_seconds(),
        "quantized_s": q_sim.elapsed_seconds(),
        "f32_dcn_s": dcn_phase(f32_costs),
        "quantized_dcn_s": dcn_phase(q_costs),
    }


def _alltoall_block(src: int, dst: int) -> str:
    """The standard symbolic all-to-all payload: content-addressed per
    (source, destination), so wrong routing OR wrong bits both fail
    the delivery check."""
    return f"b{src}->{dst}"


def all_to_all_generators(n: int, variant: str = "pairwise",
                          flow_control: bool = True):
    """Per-rank flat all-to-all programs with the standard blocks."""
    if variant == "pairwise":
        rank_fn = all_to_all_rank
    elif variant == "bruck":
        if n < 1 or (n & (n - 1)):
            # eager (factory-time) refusal: generators raise lazily,
            # and a non-power-of-two Bruck request must fail before a
            # harness starts consuming rank sequences
            raise ValueError(
                f"all_to_all_bruck needs a power-of-two rank count, "
                f"got n={n}"
            )
        rank_fn = all_to_all_bruck_rank
    else:
        raise ValueError(
            f"unknown all_to_all variant {variant!r}; known: "
            f"pairwise, bruck (the pod variant builds through "
            f"all_to_all_pod_generators)"
        )
    return [
        rank_fn(r, n, [_alltoall_block(r, d) for d in range(n)],
                flow_control=flow_control)
        for r in range(n)
    ]


def simulate_all_to_all(n: int, strategy: Strategy,
                        variant: str = "pairwise",
                        flow_control: bool = True, faults=None,
                        verified: bool = False,
                        costs: Optional[TierCostModel] = None,
                        recorder=None) -> float:
    """Fuzz one schedule of a flat all-to-all variant and verify that
    every rank received exactly its per-source blocks — wrong delivery
    from ANY source is a :class:`ProtocolError`. Returns the simulated
    wall-clock (0.0 without a cost model)."""
    sim = RingSimulator(
        _maybe_verified(
            all_to_all_generators(n, variant, flow_control), verified
        ),
        strategy, faults=faults, costs=costs, recorder=recorder,
    )
    outputs = sim.run()
    for r in range(n):
        want = {src: _alltoall_block(src, r) for src in range(n)}
        if outputs[r] != want:
            raise ProtocolError(
                f"rank {r} received {outputs[r]}, wanted {want}"
            )
    return sim.elapsed_seconds()


def all_to_all_pod_generators(slices: int, per_slice: int,
                              flow_control: bool = True):
    """Per-rank two-tier all-to-all programs with the standard blocks."""
    n = slices * per_slice
    return [
        all_to_all_pod_rank(
            g, slices, per_slice,
            [_alltoall_block(g, d) for d in range(n)],
            flow_control=flow_control,
        )
        for g in range(n)
    ]


def simulate_all_to_all_pod(slices: int, per_slice: int,
                            strategy: Strategy,
                            flow_control: bool = True, faults=None,
                            verified: bool = False,
                            costs: Optional[TierCostModel] = None,
                            recorder=None) -> float:
    """Fuzz one schedule of the two-tier pod all-to-all and verify
    delivery: every rank must hold, per source slice, the bundle of
    that slice's blocks for it (the bundles' concatenation IS the flat
    per-source delivery). Returns the simulated wall-clock."""
    n = slices * per_slice
    sim = RingSimulator(
        _maybe_verified(
            all_to_all_pod_generators(slices, per_slice, flow_control),
            verified,
        ),
        strategy, faults=faults, costs=costs, recorder=recorder,
    )
    outputs = sim.run()
    for g in range(n):
        want = {
            ("slice", t): tuple(
                _alltoall_block(t * per_slice + j, g)
                for j in range(per_slice)
            )
            for t in range(slices)
        }
        if outputs[g] != want:
            raise ProtocolError(
                f"rank {g} received {outputs[g]}, wanted {want}"
            )
    return sim.elapsed_seconds()


def alltoall_wallclock_comparison(slices: int, per_slice: int,
                                  block_bytes: float, seed: int = 0,
                                  ici: Optional[LinkCost] = None,
                                  dcn: Optional[LinkCost] = None) -> Dict:
    """Same all-to-all traffic, flat pairwise vs the two-tier pod
    variant, on the same deterministic schedule seed and wire rates.

    The flat pairwise exchange sends one ``block_bytes`` message per
    (source, destination) pair — ``per_slice * (slices - 1)`` of a
    rank's ``n - 1`` messages cross DCN, each paying the DCN alpha.
    The pod variant's ICI messages stay at block granularity but its
    DCN crossings are ``slices - 1`` bundles of ``per_slice`` blocks —
    the alpha amortization the hierarchy exists for. Both runs must
    deliver the identical routing (each against its own delivery
    contract — the bundles' concatenation is the flat delivery); the
    returned dict carries the two makespans. Deterministic per
    (shape, block size, seed, rates)."""
    n = slices * per_slice
    flat_costs = default_tier_costs(block_bytes, per_slice,
                                    ici=ici, dcn=dcn)
    flat_s = simulate_all_to_all(n, Strategy(seed), costs=flat_costs)
    pod_costs = default_tier_costs(
        block_bytes, per_slice, ici=ici, dcn=dcn,
        ici_bytes=block_bytes, dcn_bytes=per_slice * block_bytes,
    )
    pod_s = simulate_all_to_all_pod(slices, per_slice, Strategy(seed),
                                    costs=pod_costs)
    return {
        "slices": slices,
        "per_slice": per_slice,
        "block_bytes": block_bytes,
        "pairwise_s": flat_s,
        "hierarchical_s": pod_s,
    }


def alltoall_variant_wallclocks(n: int, block_bytes: float,
                                seed: int = 0,
                                ici: Optional[LinkCost] = None) -> Dict:
    """Pairwise vs Bruck on one single-tier ring at one block size.

    Pairwise pays ``n - 1`` message alphas at block granularity; Bruck
    pays ``log2(n)`` round alphas at ``n/2``-block aggregate
    granularity (each round's copies are priced at the coalesced
    message a real kernel sends). Small blocks are alpha-bound — Bruck
    wins; large blocks are volume-bound — pairwise's ``(n-1) * b``
    total beats Bruck's ``log2(n) * n/2 * b``. Deterministic per
    (n, block size, seed, rates); ``n`` must be a power of two."""
    pair_costs = default_tier_costs(block_bytes, 0, ici=ici)
    pairwise_s = simulate_all_to_all(n, Strategy(seed),
                                     costs=pair_costs)
    bruck_costs = default_tier_costs(n * block_bytes / 2.0, 0, ici=ici)
    bruck_s = simulate_all_to_all(n, Strategy(seed), variant="bruck",
                                  costs=bruck_costs)
    return {
        "n": n,
        "block_bytes": block_bytes,
        "pairwise_s": pairwise_s,
        "bruck_s": bruck_s,
    }


def simulate_neighbour_stream(n: int, chunks: int, strategy: Strategy,
                              direction: int = 1,
                              flow_control: bool = True,
                              faults=None,
                              verified: bool = False,
                              recorder=None) -> None:
    gens = [
        neighbour_stream_rank(
            r, n, [(r, c) for c in range(chunks)],
            direction=direction, flow_control=flow_control,
        )
        for r in range(n)
    ]
    outputs = RingSimulator(
        _maybe_verified(gens, verified), strategy, faults=faults,
        recorder=recorder,
    ).run()
    for r in range(n):
        upstream = (r - direction) % n
        want = {c: (upstream, c) for c in range(chunks)}
        if outputs[r] != want:
            raise ProtocolError(
                f"rank {r} received {outputs[r]}, wanted {want}"
            )
