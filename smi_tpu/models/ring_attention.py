"""Ring attention: sequence-parallel attention over a device ring.

The reference has no attention (SURVEY §2.10), but its scaling substrate
for a too-large domain — neighbour streaming fully overlapped with
compute (``pipeline.cl:16-31``, the stencil bridge kernels) — is exactly
the ring-attention schedule: shard the sequence across the mesh axis,
keep Q local, and circulate K/V blocks around the ring with one
``ppermute`` per step while accumulating attention online. This module
supplies that capability as a first-class model on the framework's
primitives (``ring_shift`` inside ``shard_map``), so a sequence ``n``×
longer than one chip's memory is attended at full exactness.

The accumulation is the numerically-stable online softmax (running
row-max ``m``, normalizer ``l``, weighted value sum ``acc``) — streamed
consumption of in-flight data, the same shape as ``P2PChannel.stream``'s
consumer overlap. Causality is enforced from *global* positions, so the
result is bit-comparable to full attention on the gathered sequence.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from smi_tpu.kernels.flash import (
    NEG_INF,
    flash_attend_fused,
    flash_block_attend,
    flash_block_backward_dkdv,
    flash_block_backward_dq,
    flash_supported,
)
from smi_tpu.parallel.channels import ring_shift
from smi_tpu.parallel.mesh import Communicator


def _block_attend(q, k, v, m, l, acc, q_off, k_off, causal, scale,
                  precision, window=None):
    """Fold one K/V block into the online-softmax state.

    q: (Sq, H, D); k/v: (Sk, H, D); m/l: (H, Sq); acc: (Sq, H, D).
    ``q_off``/``k_off`` are the blocks' global sequence offsets.
    """
    scores = (
        jnp.einsum("qhd,khd->hqk", q, k, precision=precision) * scale
    )  # (H, Sq, Sk)
    if causal:
        sq, sk = q.shape[0], k.shape[0]
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        masked = k_pos > q_pos
        if window is not None:
            masked |= k_pos < q_pos - (window - 1)
        scores = jnp.where(masked[None], NEG_INF, scores)
    m_new = jnp.maximum(m, scores.max(axis=-1))        # (H, Sq)
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])             # (H, Sq, Sk)
    l_new = l * correction + p.sum(axis=-1)
    acc_new = (
        acc * correction.transpose(1, 0)[..., None]
        + jnp.einsum("hqk,khd->qhd", p, v, precision=precision)
    )
    return m_new, l_new, acc_new


def _ring_schedule(fold, comm, axis, k0, v0, carry0):
    """The ring circuit shared by both attention tiers: hold Q, pass
    K/V to the right neighbour each step, fold the currently-held block
    into the carry with its *origin rank* (for global causal offsets).
    ``fold(src_rank, k_block, v_block, carry) -> carry``."""
    n = comm.mesh.shape[axis]
    rank = lax.axis_index(axis)

    def step(s, state):
        k_cur, v_cur, carry = state
        # the block currently held originated at rank - s (mod n)
        src = lax.rem(rank - s + jnp.int32(n), jnp.int32(n))
        carry = fold(src, k_cur, v_cur, carry)
        # pass K/V to the right neighbour for the next step; the fold
        # and the shift both only read k_cur/v_cur, so XLA overlaps the
        # ICI hop with the block math
        k_cur = ring_shift(k_cur, comm, offset=1, axis_name=axis)
        v_cur = ring_shift(v_cur, comm, offset=1, axis_name=axis)
        return k_cur, v_cur, carry

    # n-1 looped fold+shift steps, then the last block folds without a
    # (dead) trailing shift
    k_last, v_last, carry = lax.fori_loop(0, n - 1, step, (k0, v0, carry0))
    src_last = lax.rem(rank + 1, jnp.int32(n))
    return fold(src_last, k_last, v_last, carry)


def _padded_head_dim(d: int) -> int:
    """Head dim rounded up to the MXU lane width (128)."""
    return -(-d // 128) * 128


def _use_flash_default(comm: Communicator, s_local, h, d, dtype) -> bool:
    # non-lane-aligned head dims run flash via zero-padding to 128
    return comm.is_tpu and flash_supported(
        s_local, s_local, _padded_head_dim(d), dtype
    )


def _flash_forward(q, k, v, comm, causal, axis, precision, interpret,
                   window, scale=None):
    """Flash-tier ring forward: head-major layouts, one Pallas launch
    per ring step (``kernels/flash.py``), K/V moved by ``ring_shift``.
    Returns ``(out, m, l)`` — the statistics are the backward pass's
    residuals. With grouped K/V heads, only the smaller K/V circulate —
    the kernel reads them grouped, nothing is repeated."""
    rank = lax.axis_index(axis)
    s_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    qT = q.swapaxes(0, 1)  # (H, S, D)
    if comm.mesh.shape[axis] == 1:
        # single-rank ring: the whole K/V extent is one launch, so the
        # fused kernel applies — fresh state in scratch, normalized
        # output written directly (no (m, l, acc) HBM round trip)
        out, m, l = flash_attend_fused(
            qT, k.swapaxes(0, 1), v.swapaxes(0, 1), 0, 0, causal,
            scale, precision, interpret=interpret, window=window,
        )
        return out.swapaxes(0, 1), m, l

    # online-softmax state is always f32, whatever the input dtype; the
    # statistics ride in compact (H, 1, S) row layout (column vectors
    # would be lane-padded 128x by TPU tiling — see kernels/flash.py)
    m0 = jnp.full((h, 1, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((h, 1, s_local), jnp.float32)
    acc0 = jnp.zeros(qT.shape, jnp.float32)
    q_off = rank * s_local

    def fold(src, k_cur, v_cur, carry):
        m, l, acc = carry
        return flash_block_attend(
            qT, k_cur, v_cur, m, l, acc,
            q_off, src * s_local, causal, scale, precision,
            interpret=interpret, window=window,
        )

    m, l, acc = _ring_schedule(
        fold, comm, axis,
        k.swapaxes(0, 1), v.swapaxes(0, 1), (m0, l0, acc0),
    )
    safe_l = jnp.where(l == 0.0, 1.0, l)  # (H, 1, S)
    out = (acc / safe_l.swapaxes(1, 2)).swapaxes(0, 1).astype(q.dtype)
    return out, m, l


def _flash_ring_backward(
    q, k, v, out, m, l, dout, comm, causal, axis, precision, interpret,
    window, scale=None,
):
    """FlashAttention-2 backward over the ring.

    Probabilities are recomputed blockwise from the saved ``(m, l)``
    (``kernels/flash.py`` backward kernels — nothing quadratic is
    stored). K/V blocks make one more ring circuit, this time carrying
    their ``(dk, dv)`` accumulators with them: after ``n`` fold+shift
    steps each block arrives home with the gradient contributions of
    every rank's queries on board. ``dq`` accumulates locally.
    """
    n = comm.mesh.shape[axis]
    rank = lax.axis_index(axis)
    s_local, h, d = q.shape
    h_kv = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    q_off = rank * s_local

    qT = q.swapaxes(0, 1)
    doutT = dout.swapaxes(0, 1).astype(q.dtype)
    outT = out.swapaxes(0, 1).astype(jnp.float32)
    # all statistics stay in compact (H, 1, S) row layout end-to-end:
    # the forward saves rows, delta reduces straight into a row, and
    # both backward kernels consume rows (dq transposes per-tile
    # in-kernel) — no lane-padded (H, S, 1) tensor ever hits HBM
    linv = 1.0 / jnp.where(l == 0.0, 1.0, l)           # (H, 1, S)
    delta = jnp.sum(
        doutT.astype(jnp.float32) * outT, axis=-1
    )[:, None, :]                                       # (H, 1, S)

    dq0 = jnp.zeros((h, s_local, d), jnp.float32)
    state0 = (
        k.swapaxes(0, 1), v.swapaxes(0, 1),
        jnp.zeros((h_kv, s_local, d), jnp.float32),
        jnp.zeros((h_kv, s_local, d), jnp.float32),
        dq0,
    )

    def fold(s, k_cur, v_cur, dk_cur, dv_cur, dq):
        src = lax.rem(rank - s + jnp.int32(n), jnp.int32(n))
        k_off = src * s_local
        dq = dq + flash_block_backward_dq(
            qT, k_cur, v_cur, doutT, m, linv, delta,
            q_off, k_off, causal, scale, precision, interpret=interpret,
            window=window,
        )
        dkc, dvc = flash_block_backward_dkdv(
            qT, k_cur, v_cur, doutT, m, linv, delta,
            q_off, k_off, causal, scale, precision, interpret=interpret,
            window=window,
        )
        return dk_cur + dkc, dv_cur + dvc, dq

    shift = lambda x: ring_shift(x, comm, offset=1, axis_name=axis)

    def step(s, state):
        k_cur, v_cur, dk_cur, dv_cur, dq = state
        dk_cur, dv_cur, dq = fold(s, k_cur, v_cur, dk_cur, dv_cur, dq)
        # the accumulators travel WITH their block; after n shifts both
        # are back at the block's owner
        return (shift(k_cur), shift(v_cur), shift(dk_cur), shift(dv_cur),
                dq)

    # n-1 looped fold+shift steps; the last block folds without the
    # dead trailing k/v shift — only its accumulators make the final
    # hop home
    k_l, v_l, dk_l, dv_l, dqT = lax.fori_loop(0, n - 1, step, state0)
    dk_l, dv_l, dqT = fold(n - 1, k_l, v_l, dk_l, dv_l, dqT)
    dkT, dvT = shift(dk_l), shift(dv_l)
    return (
        dqT.swapaxes(0, 1).astype(q.dtype),
        dkT.swapaxes(0, 1).astype(k.dtype),
        dvT.swapaxes(0, 1).astype(v.dtype),
    )


def _ring_attention_shard_flash(
    q, k, v, comm, causal, axis, precision, interpret, window,
    scale=None,
):
    """Flash tier with a custom VJP: forward saves the online-softmax
    statistics; backward recomputes probabilities blockwise and rides
    the ring in reverse — long-context attention stays trainable at
    sizes where the jnp tier cannot even materialize the scores."""

    @jax.custom_vjp
    def attn(q, k, v):
        out, _, _ = _flash_forward(
            q, k, v, comm, causal, axis, precision, interpret, window,
            scale=scale,
        )
        return out

    def fwd(q, k, v):
        out, m, l = _flash_forward(
            q, k, v, comm, causal, axis, precision, interpret, window,
            scale=scale,
        )
        return out, (q, k, v, out, m, l)

    def bwd(res, dout):
        q, k, v, out, m, l = res
        return _flash_ring_backward(
            q, k, v, out, m, l, dout, comm, causal, axis, precision,
            interpret, window, scale=scale,
        )

    attn.defvjp(fwd, bwd)
    return attn(q, k, v)


def ring_attention_shard(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    comm: Communicator,
    causal: bool = False,
    axis_name: Optional[str] = None,
    precision=lax.Precision.HIGHEST,
    use_flash: Optional[bool] = None,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jax.Array:
    """Per-shard ring attention (call inside ``shard_map``).

    ``q`` is this rank's ``(S_local, H, D)`` sequence shard; ``k``/``v``
    are ``(S_local, H_kv, D)`` with ``H_kv`` dividing ``H``
    (grouped-query attention; ``H_kv == H`` is plain MHA). K/V make a
    full ring circuit (one ``ppermute`` per step, n-1 hops); XLA
    overlaps each hop with the previous block's attention math — the
    stencil bridge-kernel overlap, applied to attention.

    On TPU with flash-compatible shapes the per-step block fold runs as
    the VMEM-resident Pallas kernel (``kernels/flash.py``); otherwise
    the jnp online-softmax below. ``use_flash`` forces the choice (pass
    ``interpret=True`` to run the flash tier off-TPU). ``window``
    (requires ``causal``) restricts each query to its ``window`` most
    recent positions — sliding-window attention; the flash tier skips
    out-of-window blocks entirely, so compute scales with
    ``S * window`` instead of ``S²``.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError(
            "sliding window requires causal attention and window >= 1"
        )
    axis = axis_name or comm.axis_names[0]
    rank = lax.axis_index(axis)
    s_local, h, d = q.shape
    h_kv = k.shape[1]
    if h % h_kv or v.shape[1] != h_kv:
        raise ValueError(
            f"kv heads {k.shape[1]}/{v.shape[1]} must agree and divide "
            f"query heads {h}"
        )
    group = h // h_kv
    if use_flash is None:
        use_flash = _use_flash_default(comm, s_local, h, d, q.dtype)
    if use_flash:
        dp = _padded_head_dim(d)
        if dp != d:
            # zero-pad the head dim to the 128-lane tile: padded lanes
            # contribute 0 to every dot product, so scores and outputs
            # are exact; the explicit scale keeps 1/sqrt(d_original).
            # Padding sits OUTSIDE the custom-VJP boundary, so autodiff
            # pads dout / slices dq,dk,dv automatically.
            pad = [(0, 0), (0, 0), (0, dp - d)]
            out = _ring_attention_shard_flash(
                jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad),
                comm, causal, axis, precision, interpret, window,
                scale=1.0 / math.sqrt(d),
            )
            return out[..., :d]
        return _ring_attention_shard_flash(
            q, k, v, comm, causal, axis, precision, interpret, window
        )
    scale = 1.0 / math.sqrt(d)

    m0 = jnp.full((h, s_local), NEG_INF, q.dtype)
    l0 = jnp.zeros((h, s_local), q.dtype)
    acc0 = jnp.zeros_like(q)
    q_off = rank * s_local

    def fold(src, k_cur, v_cur, carry):
        m, l, acc = carry
        if group > 1:
            # repeat per fold so only the small K/V ride the ring
            k_cur = jnp.repeat(k_cur, group, axis=1)
            v_cur = jnp.repeat(v_cur, group, axis=1)
        return _block_attend(
            q, k_cur, v_cur, m, l, acc,
            q_off, src * s_local, causal, scale, precision,
            window=window,
        )

    m, l, acc = _ring_schedule(fold, comm, axis, k, v, (m0, l0, acc0))
    # safe_l only guards the l == 0 "no fold ran" case (unreachable in the
    # ring schedule: the self-block always contributes the diagonal). NOTE
    # a row with zero *live* keys would NOT land here: its m stays NEG_INF,
    # every key scores p = exp(0) = 1, and l ends up equal to the key
    # count — the output would be a mean of v, not 0. Any future
    # cross-attention or padded-row path must mask p where m == NEG_INF
    # instead of relying on this guard.
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return acc / safe_l.transpose(1, 0)[..., None]


def make_ring_attention_fn(
    comm: Communicator, causal: bool = False,
    precision=lax.Precision.HIGHEST,
    use_flash: Optional[bool] = None,
    interpret: bool = False,
    reps: int = 1,
    window: Optional[int] = None,
    remat_reps: bool = False,
):
    """Jitted sequence-parallel attention over the communicator's axis.

    Takes global ``(S, H, D)`` q/k/v sharded on the sequence dimension;
    returns the global attention output with the same sharding.
    ``precision`` defaults to HIGHEST so results verify against full
    f32 attention (TPU matmuls otherwise round operands to bf16); pass
    ``lax.Precision.DEFAULT`` to trade exactness for MXU throughput.

    ``reps > 1`` chains that many applications inside the jit (output
    fed back as the next query) — a timing harness that amortizes
    per-dispatch latency out of benchmark samples. ``remat_reps``
    rematerializes each rep under differentiation: grad-of-reps
    otherwise saves per-rep residuals (reps x the k/v footprint —
    8 GB at S=64k/reps=64, an HBM OOM). It costs ~20% recompute, so
    it stays off where the chain fits.
    """
    axis = comm.axis_names[0]

    def once(q, k, v):
        return ring_attention_shard(
            q, k, v, comm, causal=causal, precision=precision,
            use_flash=use_flash, interpret=interpret, window=window,
        )

    if reps == 1:
        shard_fn = once
    else:
        chained = jax.checkpoint(once) if remat_reps else once

        def shard_fn(q, k, v):
            return lax.fori_loop(
                0, reps, lambda _, x: chained(x, k, v), q
            )

    spec = P(axis)
    # NOTE: no compiler_options here — the returned fn is meant to be
    # composed (jax.grad / outer jit), and XLA rejects options on a jit
    # that ends up nested. Multi-rank compiled rings that trip the
    # scoped-VMEM default should pass utils.compile.TPU_COMPILER_OPTIONS
    # to their own top-level jit (make_train_step already does).
    return jax.jit(
        jax.shard_map(
            shard_fn, mesh=comm.mesh,
            in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    )


def reference_attention(q, k, v, causal: bool = False,
                        window=None) -> np.ndarray:
    """Full (gathered) attention for verification."""
    q, k, v = (np.asarray(a, np.float64) for a in (q, k, v))
    s, _h, d = q.shape
    scores = np.einsum("qhd,khd->hqk", q, k) / math.sqrt(d)
    if causal:
        mask = np.triu(np.ones((s, s), bool), 1)
        if window is not None:
            mask |= np.tril(np.ones((s, s), bool), -window)
        scores = np.where(mask[None], -np.inf, scores)
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("hqk,khd->qhd", p, v)


def reference_attention_rows(q, k, v, rows, causal: bool = False,
                             window=None) -> np.ndarray:
    """Reference attention for a subset of query rows — O(len(rows)·S)
    host memory, for verification at benchmark scale."""
    q, k, v = (np.asarray(a, np.float64) for a in (q, k, v))
    rows = np.asarray(rows)
    _s, _h, d = q.shape
    scores = np.einsum("qhd,khd->hqk", q[rows], k) / math.sqrt(d)
    if causal:
        k_pos = np.arange(k.shape[0])
        masked = k_pos[None, None] > rows[None, :, None]
        if window is not None:
            masked |= k_pos[None, None] < rows[None, :, None] - (window - 1)
        scores = np.where(masked, -np.inf, scores)
    scores -= scores.max(axis=-1, keepdims=True)
    p = np.exp(scores)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("hqk,khd->qhd", p, v)
