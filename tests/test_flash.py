"""Flash-attention kernel tier (``kernels/flash.py``) — interpret mode
on the CPU fake mesh, verified against the jnp block fold and full
attention. Mirrors how the kernel is used: one launch per ring step
with carried online-softmax state and global causal offsets."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

import smi_tpu as smi
from smi_tpu.kernels import flash
from smi_tpu.models import ring_attention as ra


def _qkv(s, h, d, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(
        jnp.asarray(rng.randn(s, h, d).astype(np.float32)) for _ in range(3)
    )


def test_flash_supported_gating():
    f32, bf16 = jnp.float32, jnp.bfloat16
    assert flash.flash_supported(512, 512, 128, f32)
    assert flash.flash_supported(8, 16, 256, f32)
    assert flash.flash_supported(512, 512, 128, bf16)
    assert not flash.flash_supported(512, 512, 64, f32)    # lanes
    assert not flash.flash_supported(512, 512, 1024, f32)  # head_dim cap
    assert not flash.flash_supported(512, 512, 128, jnp.float64)
    assert not flash.flash_supported(7, 512, 128, f32)     # untileable
    assert not flash.flash_supported(8, 512, 128, bf16)    # bf16 sublane
    assert flash._pick_block(8192, 512) == 512
    assert flash._pick_block(24, 512) == 24
    assert flash._pick_block(24, 512, multiple=16) is None
    assert flash._pick_block(32, 512, multiple=16) == 32


def test_tuned_tile_selection():
    """The r5 measured tile policy (docs/perf_notes.md): bf16 forward
    takes wide 1024-row query tiles (the backward cannot — its VMEM
    frame overflows at 1024, so it keeps BLOCK_Q=512); the bf16
    WINDOWED forward narrows its key tile to 512 while the causal
    forward keeps 1024; f32 is untouched by both."""
    bf16, f32 = jnp.bfloat16, jnp.float32
    assert flash._block_q_fwd(bf16) == 1024
    assert flash._block_q_fwd(f32) == flash.BLOCK_Q == 512
    assert flash._block_k_fwd(bf16, None) == 1024
    assert flash._block_k_fwd(bf16, 4096) == 512
    assert flash._block_k_fwd(f32, 4096) == flash.BLOCK_K == 512
    # the backward's pick is the unsplit constants
    assert flash._block_k(bf16) == 1024


@pytest.mark.parametrize("n", [1, 2])
def test_flash_ring_attention_bf16(eight_devices, n):
    """bf16 inputs, f32 online-softmax state; bf16-level tolerance."""
    comm = smi.make_communicator(n, devices=eight_devices[:n])
    s, h, d = n * 32, 2, 128
    q, k, v = (a.astype(jnp.bfloat16) for a in _qkv(s, h, d, seed=4))
    fn = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=True, interpret=True
    )
    res = fn(q, k, v)
    assert res.dtype == jnp.bfloat16  # output keeps the input dtype
    out = np.asarray(res.astype(jnp.float32))
    ref = ra.reference_attention(
        np.asarray(q.astype(jnp.float32)),
        np.asarray(k.astype(jnp.float32)),
        np.asarray(v.astype(jnp.float32)), causal=True,
    )
    np.testing.assert_allclose(out, ref, rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("carry", ["fresh", "carried"])
def test_flash_block_matches_jnp_block(causal, carry):
    """One kernel launch == one `_block_attend` fold, including carried
    state and non-zero global offsets (a mid-ring step)."""
    s_q, s_k, h, d = 32, 48, 2, 128
    q, k, v = _qkv(max(s_q, s_k), h, d, seed=1)
    q = q[:s_q]
    k, v = k[:s_k], v[:s_k]
    scale = 1.0 / math.sqrt(d)
    # q rows 16..47, k cols 32..79: partially causal-live, so both
    # tiers take their live path (for a *fully* masked block the tiers
    # intentionally differ in transient state — see
    # test_flash_skips_fully_masked_block)
    q_off, k_off = 16, 32

    m0 = jnp.full((h, s_q), ra.NEG_INF, jnp.float32)
    l0 = jnp.zeros((h, s_q), jnp.float32)
    acc0 = jnp.zeros_like(q)
    if carry == "carried":
        # run one jnp fold first so the kernel starts from live state
        m0, l0, acc0 = ra._block_attend(
            q, k, v, m0, l0, acc0, q_off, 0, causal, scale,
            lax.Precision.HIGHEST,
        )
    m_ref, l_ref, acc_ref = ra._block_attend(
        q, k, v, m0, l0, acc0, q_off, k_off, causal, scale,
        lax.Precision.HIGHEST,
    )

    m_f, l_f, acc_f = flash.flash_block_attend(
        q.swapaxes(0, 1), k.swapaxes(0, 1), v.swapaxes(0, 1),
        m0[:, None, :], l0[:, None, :], acc0.swapaxes(0, 1),
        q_off, k_off, causal, scale, interpret=True,
    )
    # tolerances cover matmul accumulation-order noise only
    np.testing.assert_allclose(
        np.asarray(m_f)[:, 0, :], np.asarray(m_ref), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(l_f)[:, 0, :], np.asarray(l_ref), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(acc_f).swapaxes(0, 1), np.asarray(acc_ref),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("n", [1, 2, 4])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_attention_matches_full(eight_devices, n, causal):
    comm = smi.make_communicator(n, devices=eight_devices[:n])
    s, h, d = n * 16, 2, 128
    q, k, v = _qkv(s, h, d, seed=2)
    fn = ra.make_ring_attention_fn(
        comm, causal=causal, use_flash=True, interpret=True
    )
    out = np.asarray(fn(q, k, v))
    ref = ra.reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_flash_multi_chunk_carry(eight_devices):
    """Sequences longer than one key chunk exercise the scratch carry
    across grid steps (kci > 0) and the causal chunk skip."""
    comm = smi.make_communicator(1, devices=eight_devices[:1])
    s, h, d = 64, 1, 128
    q, k, v = _qkv(s, h, d, seed=5)
    old_chunk, old_bk = flash.KV_CHUNK_BUDGET, flash.BLOCK_K
    old_bq = flash.BLOCK_Q
    try:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = 16, 8, 32768
        fn = ra.make_ring_attention_fn(
            comm, causal=True, use_flash=True, interpret=True
        )
        out = np.asarray(fn(q, k, v))
    finally:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = (
            old_bq, old_bk, old_chunk
        )
    ref = ra.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_flash_skips_fully_masked_block():
    """A block wholly inside the causal future leaves the carry
    untouched (the jnp tier instead accumulates transient garbage that
    a later live block's correction zeroes; both converge)."""
    s_q, s_k, h, d = 16, 16, 1, 128
    q, k, v = _qkv(16, h, d, seed=9)
    scale = 1.0 / math.sqrt(d)
    m0 = jnp.full((h, 1, s_q), ra.NEG_INF, jnp.float32)
    l0 = jnp.zeros((h, 1, s_q), jnp.float32)
    acc0 = jnp.zeros((h, s_q, d), jnp.float32)
    m, l, acc = flash.flash_block_attend(
        q.swapaxes(0, 1), k.swapaxes(0, 1), v.swapaxes(0, 1),
        m0, l0, acc0, 0, 1000, True, scale, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(l), 0.0)
    np.testing.assert_array_equal(np.asarray(acc), 0.0)
    np.testing.assert_array_equal(np.asarray(m), np.float32(ra.NEG_INF))


def test_auto_dispatch_prefers_jnp_off_tpu(eight_devices):
    """On the CPU mesh the auto tier must not pick the Pallas path
    (non-interpret Pallas is TPU-only)."""
    comm = smi.make_communicator(2, devices=eight_devices[:2])
    assert not ra._use_flash_default(comm, 512, 4, 128, jnp.float32)


@pytest.mark.parametrize("n,causal", [(1, True), (1, False), (2, True),
                                      (4, True)])
def test_flash_ring_attention_gradients(eight_devices, n, causal):
    """The custom-VJP ring backward (blockwise recompute, gradients
    riding the ring home) matches autodiff of the jnp tier."""
    comm = smi.make_communicator(n, devices=eight_devices[:n])
    s, h, d = n * 16, 2, 128
    rng = np.random.RandomState(3)
    q, k, v, w = (
        jnp.asarray(rng.randn(s, h, d).astype(np.float32))
        for _ in range(4)
    )

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * w)

    fn_f = ra.make_ring_attention_fn(
        comm, causal=causal, use_flash=True, interpret=True
    )
    fn_j = ra.make_ring_attention_fn(comm, causal=causal, use_flash=False)
    gf = jax.grad(loss(fn_f), argnums=(0, 1, 2))(q, k, v)
    gj = jax.grad(loss(fn_j), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gj, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
            err_msg=name,
        )


def test_flash_ring_attention_gradients_bf16(eight_devices):
    """bf16 tier is differentiable; gradients keep the input dtype."""
    comm = smi.make_communicator(2, devices=eight_devices[:2])
    s, h, d = 64, 2, 128
    rng = np.random.RandomState(5)
    q, k, v = (
        jnp.asarray(rng.randn(s, h, d).astype(np.float32)).astype(
            jnp.bfloat16
        )
        for _ in range(3)
    )
    fn = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=True, interpret=True
    )
    g = jax.grad(
        lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for x in g:
        assert x.dtype == jnp.bfloat16
        assert bool(jnp.isfinite(x.astype(jnp.float32)).all())


@pytest.mark.parametrize("h,h_kv", [(1, 1), (2, 1)])
def test_flash_gradients_multi_chunk(eight_devices, h, h_kv):
    """Backward kernels with several chunks and sub-tiles per grid
    step: scratch accumulation across kci/qci > 0, causal n_live
    clipping (dq), the s0 start-index clip (dk/dv), and — for the GQA
    case — the in-kernel group reduction across contiguous head
    revisits."""
    comm = smi.make_communicator(2, devices=eight_devices[:2])
    s, d = 128, 128
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(s, h, d).astype(np.float32))
    w = jnp.asarray(rng.randn(s, h, d).astype(np.float32))
    k, v = (
        jnp.asarray(rng.randn(s, h_kv, d).astype(np.float32))
        for _ in range(2)
    )
    old = flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET
    try:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = 16, 8, 32768
        for causal in (True, False):
            fn_f = ra.make_ring_attention_fn(
                comm, causal=causal, use_flash=True, interpret=True
            )
            fn_j = ra.make_ring_attention_fn(
                comm, causal=causal, use_flash=False
            )
            gf = jax.grad(
                lambda q, k, v: jnp.sum(fn_f(q, k, v) * w),
                argnums=(0, 1, 2),
            )(q, k, v)
            gj = jax.grad(
                lambda q, k, v: jnp.sum(fn_j(q, k, v) * w),
                argnums=(0, 1, 2),
            )(q, k, v)
            for a, b, name in zip(gf, gj, ("dq", "dk", "dv")):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5,
                    err_msg=f"{name} causal={causal}",
                )
    finally:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = old


@pytest.mark.parametrize("use_flash", [True, False])
def test_ring_attention_gqa(eight_devices, use_flash):
    """Grouped-query attention: H_kv < H heads of K/V; both tiers match
    full attention over the repeated K/V."""
    comm = smi.make_communicator(2, devices=eight_devices[:2])
    s, h, h_kv, d = 64, 4, 2, 128
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(s, h, d).astype(np.float32))
    k, v = (
        jnp.asarray(rng.randn(s, h_kv, d).astype(np.float32))
        for _ in range(2)
    )
    fn = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=use_flash, interpret=use_flash
    )
    out = np.asarray(fn(q, k, v))
    ref = ra.reference_attention(
        q, np.repeat(np.asarray(k), h // h_kv, axis=1),
        np.repeat(np.asarray(v), h // h_kv, axis=1), causal=True,
    )
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_ring_attention_gqa_gradients(eight_devices):
    """GQA gradients: flash custom-VJP (incl. the per-query-head dk/dv
    group reduction) vs jnp-tier autodiff through the repeat."""
    comm = smi.make_communicator(2, devices=eight_devices[:2])
    s, h, h_kv, d = 32, 4, 2, 128
    rng = np.random.RandomState(13)
    q = jnp.asarray(rng.randn(s, h, d).astype(np.float32))
    k, v = (
        jnp.asarray(rng.randn(s, h_kv, d).astype(np.float32))
        for _ in range(2)
    )
    w = jnp.asarray(rng.randn(s, h, d).astype(np.float32))
    fn_f = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=True, interpret=True
    )
    fn_j = ra.make_ring_attention_fn(comm, causal=True, use_flash=False)
    gf = jax.grad(lambda q, k, v: jnp.sum(fn_f(q, k, v) * w),
                  argnums=(0, 1, 2))(q, k, v)
    gj = jax.grad(lambda q, k, v: jnp.sum(fn_j(q, k, v) * w),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gj, ("dq", "dk", "dv")):
        assert a.shape == b.shape
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
            err_msg=name,
        )


def test_ring_attention_rejects_bad_kv_heads(eight_devices):
    comm = smi.make_communicator(1, devices=eight_devices[:1])
    q, _, _ = _qkv(16, 4, 128)
    k, v, _ = _qkv(16, 3, 128, seed=1)
    fn = ra.make_ring_attention_fn(comm, use_flash=False)
    with pytest.raises(ValueError, match="divide"):
        fn(q, k, v)


@pytest.mark.parametrize("use_flash", [True, False])
@pytest.mark.parametrize("n,window", [(1, 8), (2, 8), (4, 24)])
def test_ring_attention_sliding_window(eight_devices, use_flash, n, window):
    """Sliding-window attention: each query attends its `window` most
    recent positions; both tiers match the windowed reference."""
    comm = smi.make_communicator(n, devices=eight_devices[:n])
    s, h, d = n * 16, 2, 128
    q, k, v = _qkv(s, h, d, seed=17)
    fn = ra.make_ring_attention_fn(
        comm, causal=True, window=window,
        use_flash=use_flash, interpret=use_flash,
    )
    out = np.asarray(fn(q, k, v))
    ref = ra.reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_ring_attention_window_gradients_multi_chunk(eight_devices):
    """Windowed gradients with several chunks/sub-tiles per grid step —
    exercises the two-sided clipping (n_live and s0/n_end) in all three
    kernels."""
    comm = smi.make_communicator(2, devices=eight_devices[:2])
    s, h, d = 128, 2, 128
    window = 24
    rng = np.random.RandomState(19)
    q, k, v, w = (
        jnp.asarray(rng.randn(s, h, d).astype(np.float32))
        for _ in range(4)
    )
    old = flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET
    try:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = 16, 8, 32768
        fn_f = ra.make_ring_attention_fn(
            comm, causal=True, window=window,
            use_flash=True, interpret=True,
        )
        fn_j = ra.make_ring_attention_fn(
            comm, causal=True, window=window, use_flash=False
        )
        out_f = np.asarray(fn_f(q, k, v))
        ref = ra.reference_attention(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(out_f, ref, rtol=2e-5, atol=2e-5)
        gf = jax.grad(lambda q, k, v: jnp.sum(fn_f(q, k, v) * w),
                      argnums=(0, 1, 2))(q, k, v)
        gj = jax.grad(lambda q, k, v: jnp.sum(fn_j(q, k, v) * w),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gj, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5,
                err_msg=name,
            )
    finally:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = old


def test_causal_fetch_clamp_equivalence(eight_devices):
    """The causal dead-chunk fetch clamp (index map folds future chunks
    onto the last live one; the kernel gates them off) must be exactly
    output-equivalent to the plain causal schedule, including on a ring
    (nonzero k_off, fully-dead and fully-live blocks)."""
    rng = np.random.RandomState(29)
    s, h, d = 256, 2, 128
    q, k, v = (
        jnp.asarray(rng.randn(s, h, d).astype(np.float32))
        for _ in range(3)
    )
    old = flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET
    old_min = flash.CAUSAL_CLAMP_MIN_CHUNKS
    outs = {}
    try:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = (
            16, 8, 1 << 20
        )
        for clamp, min_chunks in (("on", 1), ("off", 1 << 30)):
            flash.CAUSAL_CLAMP_MIN_CHUNKS = min_chunks
            for n in (1, 2):
                comm = smi.make_communicator(
                    n, devices=eight_devices[:n]
                )
                fn = ra.make_ring_attention_fn(
                    comm, causal=True, use_flash=True, interpret=True
                )
                outs[(clamp, n)] = np.asarray(fn(q, k, v))
    finally:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = old
        flash.CAUSAL_CLAMP_MIN_CHUNKS = old_min
    for n in (1, 2):
        np.testing.assert_array_equal(
            outs[("on", n)], outs[("off", n)]
        )
    ref = ra.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(outs[("on", 1)], ref, rtol=2e-5,
                               atol=2e-5)


@pytest.mark.parametrize("n", [1, 2])
def test_ring_attention_window_chunk_offset(eight_devices, n):
    """Windowed schedules with a live span much shorter than the K/V
    extent — the grid's streamed axis is *relative* (fewer grid chunks
    than total chunks) and the BlockSpec index maps offset it by a
    nonzero ``chunk0``. Guards the index-map/kernel agreement on which
    chunk each grid step fetched; every other windowed test resolves to
    ``n_grid == n_total`` where the offset is identically zero.

    ``n=1`` routes through the FUSED single-shot kernel (its own grid
    offset arithmetic; previously only the opt-in TPU tier compiled
    it), ``n=2`` through the carried ring kernel. The window (24) is
    deliberately not a multiple of the K/V chunk (16), so the live
    span straddles chunk boundaries."""
    comm = smi.make_communicator(n, devices=eight_devices[:n])
    s, h, d = 256, 2, 128
    window = 24
    rng = np.random.RandomState(23)
    q, k, v, w = (
        jnp.asarray(rng.randn(s, h, d).astype(np.float32))
        for _ in range(4)
    )
    old = flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET
    try:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = (
            16, 8, 1 << 20
        )
        # precondition: the relative axis is genuinely shorter than the
        # extent, so chunk0 takes nonzero values (the point of the test)
        per_rank = s // n
        kc = flash._window_chunk(per_rank, 8, d, 4)
        n_kc, n_total = flash._window_chunks(per_rank, kc, 16, window)
        assert n_kc < n_total, (n_kc, n_total)
        assert window % kc != 0, (window, kc)
        fn_f = ra.make_ring_attention_fn(
            comm, causal=True, window=window,
            use_flash=True, interpret=True,
        )
        fn_j = ra.make_ring_attention_fn(
            comm, causal=True, window=window, use_flash=False
        )
        out_f = np.asarray(fn_f(q, k, v))
        ref = ra.reference_attention(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(out_f, ref, rtol=2e-5, atol=2e-5)
        gf = jax.grad(lambda q, k, v: jnp.sum(fn_f(q, k, v) * w),
                      argnums=(0, 1, 2))(q, k, v)
        gj = jax.grad(lambda q, k, v: jnp.sum(fn_j(q, k, v) * w),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gj, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5,
                err_msg=name,
            )
    finally:
        flash.BLOCK_Q, flash.BLOCK_K, flash.KV_CHUNK_BUDGET = old


def test_ring_attention_window_requires_causal(eight_devices):
    comm = smi.make_communicator(1, devices=eight_devices[:1])
    q, k, v = _qkv(16, 2, 128)
    fn = ra.make_ring_attention_fn(comm, causal=False, window=8,
                                   use_flash=False)
    with pytest.raises(ValueError, match="causal"):
        fn(q, k, v)


@pytest.mark.parametrize("d", [64, 96])
@pytest.mark.parametrize("n", [1, 2])
def test_flash_pads_unaligned_head_dim(eight_devices, n, d):
    """head_dim not a multiple of 128 runs the flash tier via zero
    padding to the lane tile — exact scores (padded lanes dot to 0) and
    the original 1/sqrt(d) scale."""
    comm = smi.make_communicator(n, devices=eight_devices[:n])
    s, h = n * 32, 2
    rng = np.random.RandomState(7)
    q, k, v = (
        jnp.asarray(rng.randn(s, h, d).astype(np.float32))
        for _ in range(3)
    )
    fn = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=True, interpret=True
    )
    out = np.asarray(fn(q, k, v))
    assert out.shape == (s, h, d)
    ref = ra.reference_attention(
        np.asarray(q), np.asarray(k), np.asarray(v), causal=True
    )
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_flash_padded_head_dim_gradients(eight_devices):
    """Autodiff through the pad/slice boundary matches the jnp tier."""
    comm = smi.make_communicator(2, devices=eight_devices[:2])
    s, h, d = 64, 2, 64
    rng = np.random.RandomState(8)
    q, k, v = (
        jnp.asarray(rng.randn(s, h, d).astype(np.float32))
        for _ in range(3)
    )

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    fn_f = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=True, interpret=True
    )
    fn_j = ra.make_ring_attention_fn(comm, causal=True, use_flash=False)
    gf = jax.grad(loss(fn_f), argnums=(0, 1, 2))(q, k, v)
    gj = jax.grad(loss(fn_j), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gj, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name}",
        )
