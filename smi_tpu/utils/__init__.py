"""Host-side utilities: native-library bindings, timing, logging.

Reference parity: ``include/utils/`` (the C++ host support layer) plus
the codegen-side process plumbing (``codegen/rewrite.py``).
"""
