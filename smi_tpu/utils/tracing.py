"""Profiling/tracing: the TPU equivalent of the reference's host timing.

The reference measures with hlslib kernel-event futures
(``bandwidth_benchmark.cpp:144-162``) and wall-clock helpers
(``include/utils/utils.hpp:10-23``), plus offline aoc area reports. On
TPU the device-side story is the JAX profiler: traces open in
XProf/TensorBoard and show the ICI collectives, Pallas kernels, and the
HBM/VMEM picture the FPGA reports approximated.

- :func:`trace` — context manager writing an XPlane trace directory.
- :func:`annotate` — named region visible on the trace timeline (the
  analog of per-kernel event naming).
- :func:`timed` — wall-clock timing of a callable with completion forced
  by readback, returning (result, seconds); the host-side
  ``current_time_usecs`` bracket pattern every benchmark host uses.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Iterator, Optional, Tuple

import jax


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: Optional[int] = None) -> Iterator[None]:
    """Collect a profiler trace of the enclosed block into ``log_dir``.

    View with TensorBoard's profile plugin or xprof. ``host_tracer_level``
    is forwarded to the profiler options when given.
    """
    options = None
    if host_tracer_level is not None:
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
    jax.profiler.start_trace(log_dir, profiler_options=options)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named timeline region: ``with annotate("halo-exchange"): ...``.

    Also usable as a decorator via ``jax.profiler.annotate_function``
    semantics; inside jit the annotation attaches to the traced op's
    metadata.
    """
    return jax.profiler.TraceAnnotation(name)


def timed(fn: Callable[[], Any]) -> Tuple[Any, float]:
    """Run ``fn`` and return (result, elapsed seconds).

    Completion is forced with a host readback of every array leaf (not
    ``block_until_ready``, which tunneled backends can resolve before
    execution finishes — see ``smi_tpu.benchmarks.stats``), so on-device
    async dispatch doesn't fake a fast time — the role of the reference's
    event-completion waits.
    """
    import numpy as np

    t0 = time.perf_counter()
    result = fn()
    jax.tree_util.tree_map(np.asarray, result)
    return result, time.perf_counter() - t0
