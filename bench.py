"""Headline benchmark: distributed-stencil throughput per chip.

Runs the flagship workload (4-point Jacobi with halo machinery engaged —
BASELINE.json north star config, 8192x8192 float32) on the available TPU
and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` compares against the reference hardware's per-device
stencil roofline: the SMI paper's FPGA design computes a 16-wide vector
per cycle at Fmax 480 MHz (``examples/CMakeLists.txt:5-7`` W=16,
``CMakeLists.txt:9`` SMI_FMAX=480), i.e. 7.68e9 cell updates/s/FPGA peak.
The repo publishes no measured numbers (BASELINE.md), so the documented
peak is the baseline denominator.

``vs_tpu_roofline`` makes the absolute number interpretable against the
*TPU's* ceilings (VERDICT r1 #8), using the v5e model documented in
``smi_tpu/benchmarks/surface.py``:

- ``hbm``: achieved HBM traffic fraction — a depth-k temporal pass moves
  8 bytes per cell per k sweeps, so traffic = cells/s · 8/k vs 819 GB/s.
  A small value *proves the kernel is no longer HBM-bound* (temporal
  blocking's purpose).
- ``vpu``: achieved VPU-op fraction — ~10 vector ops per cell·sweep
  (4 essential FLOPs + 4 shifted-operand reads + 2 boundary selects) vs
  the ~6.2 TFLOP/s f32 VPU peak. This is the binding ceiling: the sweep
  is elementwise work, so the VPU, not the MXU, is the roofline. The
  depth-16 choice is the measured knee — beyond it the extra halo-ring
  recompute (+2k rows/cols per sweep) cancels the HBM savings (tuning
  notes: ``kernels/stencil_temporal.py::pick_temporal_depth``).
"""

import json
import time

import numpy as np

REFERENCE_CELLS_PER_SEC_PER_DEVICE = 16 * 480e6  # W=16 @ 480 MHz

#: Scoreboard pass/regress tolerance: a metric is a regression when it
#: lands more than this fraction worse than its committed baseline.
SCOREBOARD_TOLERANCE = 0.05

#: The BENCH_r05 headline (cells/s/chip) — the scoreboard's stencil
#: baseline, read from BENCH_r05.json when present; this constant is
#: the committed fallback and is drift-guarded against the JSON by
#: tests/test_perf_docs.py.
BENCH_R05_STENCIL_CELLS = 131890507290.4

#: PERF.json metric the flash scoreboard row quotes (drift-guarded).
SCOREBOARD_FLASH_METRIC = "flash_attn_train_tflops_bf16"

#: The committed flash baseline (TF/s) the row compares against — a
#: PINNED constant, deliberately not re-read from PERF.json: the row's
#: job is to regress when a re-measure lands PERF.json lower, which a
#: self-comparison could never do. Drift-guarded by
#: tests/test_perf_docs.py against the committed PERF.json value.
SCOREBOARD_FLASH_TFLOPS_BASELINE = 101.69

#: The VPU roofline fraction of the BENCH_r05 headline
#: (``surface.stencil_roofline(BENCH_R05_STENCIL_CELLS, 16)
#: ["vs_vpu_roofline"]``) — PINNED for the same reason as the flash
#: baseline: the roofline row must regress when a roofline-model edit
#: (STENCIL_VPU_OPS, PEAK_VPU_F32, the depth plumbing) silently
#: deflates the achieved fraction, which a self-comparison could
#: never do. Drift-guarded by tests/test_perf_docs.py against the
#: recomputed value.
SCOREBOARD_STENCIL_VPU_ROOFLINE_BASELINE = 0.2142


def render_line(payload: dict) -> str:
    """The ONE output line, exactly as consumers parse it.

    The driver extracts the last JSON line of stdout (the BENCH_r*
    ``parsed`` field), so the contract is: single line, legacy keys
    ``metric``/``value``/``unit``/``vs_baseline`` always present, new
    fields strictly additive. Guarded by ``tests/test_overlap.py``'s
    schema test. A ``scoreboard`` field, when present, must carry a
    pass/regress verdict per metric — the multi-metric regression
    gate is part of the printed contract, not an optional decoration.
    """
    for key in ("metric", "value", "unit", "vs_baseline"):
        if key not in payload:
            raise ValueError(f"bench payload dropped legacy key {key!r}")
    board = payload.get("scoreboard")
    if board is not None and "error" not in board:
        for name, entry in board.items():
            if entry.get("verdict") not in ("pass", "regress"):
                raise ValueError(
                    f"scoreboard metric {name!r} has no pass/regress "
                    f"verdict"
                )
        srow = board.get("stencil_gcells_per_chip")
        if srow is not None:
            # r18: the stencil row must state its roofline fraction —
            # and a roofline regression is not a printable verdict but
            # a loud failure: a headline that passes on raw Gcell/s
            # while the achieved-fraction plumbing deflated is exactly
            # the silent drift this gate exists to refuse.
            roof = srow.get("roofline")
            if not isinstance(roof, dict) or roof.get("verdict") not in (
                    "pass", "regress"):
                raise ValueError(
                    "stencil scoreboard row has no roofline verdict"
                )
            if roof["verdict"] == "regress":
                raise ValueError(
                    f"stencil roofline regression: achieved VPU "
                    f"fraction {roof.get('vpu_fraction')} vs committed "
                    f"{roof.get('baseline')} "
                    f"(ratio {roof.get('ratio')})"
                )
    line = json.dumps(payload)
    if "\n" in line:
        raise ValueError("bench payload rendered to multiple lines")
    return line


def _repo_json(name: str):
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path) as f:
        return json.load(f)


def scoreboard_fields(stencil_per_chip=None, stencil_depth=16) -> dict:
    """Additive multi-metric scoreboard: stencil Gcell/s vs the
    BENCH_r05 headline, flash train TF/s vs the committed PERF.json
    measurement, and the analytic allreduce payload curve vs the
    committed expectations — each with a pass/regress verdict, so a
    perf regression ANYWHERE in the measured or modeled surface is as
    loud in the bench line as a test failure.

    Rows not re-measured by this run carry ``measured: False`` and
    quote the committed value (their verdict then guards the
    *expectation plumbing*, not fresh hardware numbers); the stencil
    row is live whenever the headline measurement is passed in. The
    allreduce row is recomputed from today's cost model every run —
    a code change that reprices the curve regresses the scoreboard
    even though no TPU was involved (the `analytic-regression` lint
    rule's bench-side mirror).
    """

    def verdict(ratio: float) -> str:
        return "pass" if ratio >= 1.0 - SCOREBOARD_TOLERANCE else "regress"

    board = {}
    try:
        stencil_base = float(
            _repo_json("BENCH_r05.json")["parsed"]["value"]
        )
    except Exception:
        stencil_base = BENCH_R05_STENCIL_CELLS
    measured = stencil_per_chip is not None
    value = float(stencil_per_chip) if measured else stencil_base
    # r18: the row carries BOTH comparisons — raw Gcell/s vs the
    # BENCH_r05 headline AND the achieved VPU roofline fraction vs its
    # pinned committed value (the ONE pricing in
    # ``surface.stencil_roofline``). The row's verdict is the worse of
    # the two, and render_line refuses to print a roofline regression
    # at all.
    from smi_tpu.benchmarks.surface import stencil_roofline

    roof = stencil_roofline(value, stencil_depth)
    roof_ratio = (roof["vs_vpu_roofline"]
                  / SCOREBOARD_STENCIL_VPU_ROOFLINE_BASELINE)
    board["stencil_gcells_per_chip"] = {
        "value": round(value / 1e9, 2),
        "baseline": round(stencil_base / 1e9, 2),
        "ratio": round(value / stencil_base, 4),
        "measured": measured,
        "roofline": {
            "vpu_fraction": round(roof["vs_vpu_roofline"], 4),
            "hbm_fraction": round(roof["vs_hbm_roofline"], 4),
            "depth": stencil_depth,
            "baseline": SCOREBOARD_STENCIL_VPU_ROOFLINE_BASELINE,
            "ratio": round(roof_ratio, 4),
            "verdict": verdict(roof_ratio),
        },
        "verdict": verdict(min(value / stencil_base, roof_ratio)),
    }
    perf_metrics = {
        m["metric"]: m for m in _repo_json("PERF.json")["metrics"]
    }
    flash_value = round(
        float(perf_metrics[SCOREBOARD_FLASH_METRIC]["value"]), 2
    )
    board["flash_train_tflops"] = {
        "value": flash_value,
        "baseline": SCOREBOARD_FLASH_TFLOPS_BASELINE,
        "ratio": round(flash_value / SCOREBOARD_FLASH_TFLOPS_BASELINE, 4),
        "measured": False,
        "verdict": verdict(flash_value / SCOREBOARD_FLASH_TFLOPS_BASELINE),
    }
    from smi_tpu.analysis import perf as P

    sizes_kb = P.ALLREDUCE_CURVE_SIZES_KB
    # the ONE curve pricing shared with the analytic-regression rule
    predicted = P.allreduce_curve_us(sizes_kb)
    expected = [
        P.ANALYTIC_EXPECTED_US[f"allreduce_n8_{kb}kib_us"]
        for kb in sizes_kb
    ]
    # lower is better for a latency curve: the worst per-point ratio
    # (expected/predicted < 1 means the prediction got slower)
    worst = min(e / p for e, p in zip(expected, predicted))
    board["allreduce_payload_curve_us"] = {
        "payload_kib": list(sizes_kb),
        "value": predicted,
        "baseline": expected,
        "ratio": round(worst, 4),
        "measured": False,
        "verdict": verdict(worst),
    }
    # the all-to-all payload curve (best flat candidate: pairwise vs
    # Bruck) — same one-pricing discipline: P.alltoall_curve_us is the
    # SINGLE pricing shared with the analytic-regression lint rule, so
    # a cost-model change that reprices the curve regresses the
    # scoreboard even with no TPU in the loop
    a2a_sizes = P.ALLTOALL_CURVE_SIZES_KB
    a2a_predicted = P.alltoall_curve_us(a2a_sizes)
    a2a_expected = [
        P.ANALYTIC_EXPECTED_US[f"alltoall_n8_{kb}kib_us"]
        for kb in a2a_sizes
    ]
    a2a_worst = min(
        e / p for e, p in zip(a2a_expected, a2a_predicted)
    )
    board["alltoall_payload_curve_us"] = {
        "payload_kib": list(a2a_sizes),
        "value": a2a_predicted,
        "baseline": a2a_expected,
        "ratio": round(a2a_worst, 4),
        "measured": False,
        "verdict": verdict(a2a_worst),
    }
    # r19: the compressed-collectives curve — the int8 wire width
    # priced by the SAME quantized_curve_us pricing the
    # analytic-regression lint rule and test_perf_docs re-derive, vs
    # its committed expectations. A cost-model change that reprices
    # the beta win (or silently loses it) regresses the scoreboard
    # with no TPU in the loop.
    q_sizes = P.ALLREDUCE_CURVE_SIZES_KB
    q_predicted = P.quantized_curve_us(q_sizes)
    q_expected = [
        P.ANALYTIC_EXPECTED_US[f"allreduce_int8_n8_{kb}kib_us"]
        for kb in q_sizes
    ]
    q_worst = min(e / p for e, p in zip(q_expected, q_predicted))
    board["compression"] = {
        "payload_kib": list(q_sizes),
        "precision": "int8",
        "value": q_predicted,
        "baseline": q_expected,
        "ratio": round(q_worst, 4),
        "measured": False,
        "verdict": verdict(q_worst),
    }
    return board


def overlap_fields(compiled) -> dict:
    """Additive multichip evidence: the statically-verified
    comm/compute overlap of the headline executable
    (:func:`smi_tpu.parallel.traffic.overlap_report`), so the one JSON
    line records not just throughput but whether the halo exchange
    actually hides behind compute on this build."""
    from smi_tpu.parallel import traffic

    rep = traffic.overlap_report(compiled)
    return {
        "collectives": rep["collectives"],
        "async_pairs": rep["async_pairs"],
        "overlappable_bytes": rep["overlappable_bytes"],
        "overlap_fraction": round(rep["overlap_fraction"], 4),
    }


def elastic_fields() -> dict:
    """Additive elastic-runtime provenance: whether the run was
    checkpointed (``$SMI_TPU_CHECKPOINT_DIR``), at what cadence, and
    the failure-detector configuration that would police it
    (:mod:`smi_tpu.parallel.checkpoint`/``membership``) — so a
    multichip number states the durability regime it was measured
    under. ``{"enabled": False}`` when the env does not opt in; the
    legacy metric/value/unit/vs_baseline contract is untouched either
    way (schema-guarded by ``tests/test_elastic.py``)."""
    from smi_tpu.parallel.checkpoint import elastic_env_config

    cfg = elastic_env_config()
    if cfg is None:
        return {"enabled": False}
    return {
        "enabled": True,
        "cadence": cfg["cadence"],
        "dir": cfg["dir"],
        "detector": cfg["detector"],
    }


def hierarchy_fields() -> dict:
    """Additive two-tier provenance on multichip measurements: how
    many slices the backend reports, the alpha-beta rates both wire
    tiers are priced at (env-resolved DCN beta, so a fleet override is
    recorded next to the number it shaped), and which plan-engine
    layer would gate the hierarchical allreduce here. Single-slice
    hosts record ``slices: 1`` with the flat plan — the field states
    the tier regime either way; the legacy metric/value/unit/
    vs_baseline contract is untouched (schema-guarded)."""
    import jax

    from smi_tpu.tuning import cost_model as cm

    devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", None) or 0 for d in devices}
    slices = max(1, len(slice_ids))
    fields = {
        "slices": slices,
        "tier_betas": {
            "ici_bytes_per_s": cm.V5E_ICI_BETA_BYTES_PER_S,
            "dcn_bytes_per_s": cm.dcn_beta_bytes_per_s(),
        },
    }
    if slices > 1 and len(devices) % slices == 0:
        from smi_tpu.parallel.collectives import _hier_env_min_slices
        from smi_tpu.tuning.engine import get_engine

        topo = cm.TopologySpec(
            n=len(devices), inner=len(devices) // slices, outer=slices
        )
        engaged, layer = get_engine().use_hierarchical(
            1 << 20, topo, min_slices=_hier_env_min_slices()
        )
        fields["plan"] = {"hierarchical": engaged, "source": layer}
    else:
        fields["plan"] = {"hierarchical": False, "source": "heuristic"}
    return fields


def serving_fields() -> dict:
    """Additive serving provenance on multichip measurements: a
    deterministic CPU smoke of the multi-tenant front-end
    (:func:`smi_tpu.serving.campaign.bench_fields` — pure Python,
    milliseconds, fixed seed) reporting the offered load vs modeled
    capacity, per-class accepted/shed counts, and p50/p99 admission
    latency in step-clock ticks — the serving regime this build
    sustains, measured next to the throughput it would serve. The
    legacy metric/value/unit/vs_baseline contract is untouched
    (schema-guarded by ``tests/test_serving.py``)."""
    from smi_tpu.serving.campaign import bench_fields

    return bench_fields()


def obs_fields() -> dict:
    """Additive observability provenance: the flight recorder's
    measured overhead on the credits simulator (the always-on
    ring-buffer's cost, measured rather than asserted) plus the event
    accounting of one deterministic probe run — event count and
    ``dropped_events`` (the no-silent-caps bookkeeping). Pure Python,
    milliseconds; the legacy metric/value/unit/vs_baseline contract
    is untouched (schema-guarded by ``tests/test_obs.py``)."""
    import time as _time

    from smi_tpu.obs.events import FlightRecorder
    from smi_tpu.parallel import credits as C

    def probe(recorder=None) -> float:
        t0 = _time.perf_counter()
        C.simulate_all_reduce(8, C.Strategy(0), recorder=recorder)
        return _time.perf_counter() - t0

    # best-of-N on each side to damp host scheduling noise; fresh
    # recorder per run so ring state never carries over
    runs = 5
    bare_s = min(probe() for _ in range(runs))
    recorders = [FlightRecorder() for _ in range(runs)]
    recorded_s = min(probe(r) for r in recorders)
    sample = recorders[0]
    overhead = ((recorded_s - bare_s) / bare_s * 100.0
                if bare_s > 0 else 0.0)
    return {
        "probe": "simulate_all_reduce n=8 seed=0",
        "events": sample.total_events,
        "dropped_events": sample.dropped_events,
        "recorder_capacity": sample.capacity,
        "recorder_overhead_pct": round(max(0.0, overhead), 1),
    }


def slo_fields() -> dict:
    """Additive SLO provenance: the burn-rate health and p99 blame of
    a deterministic fair-weather serving smoke (pure Python,
    milliseconds, fixed seed, 0.5x load — the regime where zero
    alarms is the contract). Reports the per-class worst burn rate
    observed (the noise floor — should be 0.0 in fair weather), total
    breaches, and the slow-decile blame component shares — so the one
    JSON line records not just throughput but how close the serving
    tier sat to its error budgets while sustaining it. The legacy
    metric/value/unit/vs_baseline contract is untouched
    (schema-guarded by ``tests/test_slo.py``)."""
    from smi_tpu.serving.campaign import run_load_cell

    rep = run_load_cell(n=4, seed=0, duration=160, overload=0.5)
    health = rep["health"]
    blame = rep["blame"]
    binding = blame["binding"]
    return {
        "cell": "fair-weather 0.5x",
        "fair_weather_burn": {
            qos: c["worst_burn"]
            for qos, c in health["classes"].items()
        },
        "breaches": health["breaches_total"],
        "p99_blame": {
            qos: {
                "p99_ticks": row["p99"],
                "binding": row["binding"],
                "resource": row["resource"],
                "shares": row["shares"],
            }
            for qos, row in blame["by_qos"].items()
            if row is not None
        },
        "binding": {
            "component": binding["component"],
            "resource": binding["resource"],
            "share": binding["share"],
        },
        "span_exact": rep["span_exact"],
        "ok": rep["ok"],
    }


def retune_fields() -> dict:
    """Additive online-retuning provenance: the seeded payload-shift
    cell (:func:`smi_tpu.serving.campaign.run_retune_cell` — pure
    Python, deterministic per seed, milliseconds) reporting samples
    ingested, proposals, swaps, rollbacks, and the convergence ticks
    from the mid-run distribution shift to the committed hot-swap —
    the live-retuning regime this build sustains, measured next to
    the throughput headline. The legacy metric/value/unit/vs_baseline
    contract is untouched (schema-guarded by ``tests/test_retune.py``)."""
    from smi_tpu.serving.campaign import run_retune_cell

    rep = run_retune_cell(n=4, seed=0, duration=160)
    rt = rep["retune"]
    return {
        "samples_ingested": rt["samples_ingested"],
        "proposals": rt["proposals"],
        "swaps": rt["swaps"],
        "rollbacks": rt["rollbacks"],
        "convergence_ticks": rep["convergence_ticks"],
        "converged_algorithm": rep["converged_algorithm"],
        "expected_algorithm": rep["expected_algorithm"],
        "stale_plan_rejections": rt["stale_plan_rejections"],
        "ok": rep["ok"],
    }


def elasticity_fields() -> dict:
    """Additive demand-elasticity provenance: the seeded flash-crowd
    cell (:func:`smi_tpu.serving.campaign.run_flash_crowd_cell` —
    pure Python, deterministic per seed, sub-second) reporting the
    scale-out/scale-in arc, the blame-driven live migration, and the
    loss accounting — the elasticity regime this build sustains,
    measured next to the throughput headline. The legacy
    metric/value/unit/vs_baseline contract is untouched."""
    from smi_tpu.serving.campaign import run_flash_crowd_cell

    rep = run_flash_crowd_cell(n=4, seed=0)
    el = rep["elasticity"]
    migs = el["migrations"]
    return {
        "scale_outs": el["scale_outs"],
        "scale_ins": el["scale_ins"],
        "parked": el["parked"],
        "migrations": len(migs),
        "migrations_committed": sum(
            1 for m in migs if m["state"] == "committed"
        ),
        "migrated_streams": el["migrated_streams"],
        "stale_epoch_rejections": rep["stale_epoch_rejections"],
        "lost_accepted": rep["lost_accepted"],
        "ok": rep["ok"],
    }


def partition_fields() -> dict:
    """Additive partition-tolerance provenance: the seeded clean
    partition/heal cell (:func:`smi_tpu.serving.campaign.
    run_partition_cell` — pure Python, deterministic per seed,
    seconds) reporting the park / loud-refusal / heal-rejoin arc,
    the split-brain count the fence holds at zero, and the A/B
    bit-identity against the no-partition control — the partition
    regime this build sustains, measured next to the throughput
    headline. The legacy metric/value/unit/vs_baseline contract is
    untouched."""
    from smi_tpu.serving.campaign import run_partition_cell

    rep = run_partition_cell(n=4, seed=0)
    part = rep["partition"]
    return {
        "quorum_losses": part["quorum_losses"],
        "quorum_rejections": part["quorum_rejections"],
        "heal_rejoins": part["heal_rejoins"],
        "split_brain_incidents": part["split_brain_incidents"],
        "stale_epoch_rejections": rep["stale_epoch_rejections"],
        "lost_accepted": rep["lost_accepted"],
        "digest_match": rep["digest_match"],
        "ok": rep["ok"],
    }


def streaming_inference_fields() -> dict:
    """Additive streaming-inference provenance: a small deterministic
    disaggregated prefill/decode smoke (:func:`smi_tpu.serving.
    campaign.inference_fields` — pure Python, deterministic per seed,
    sub-second) reporting prefill/decode rates, interactive TTFT p99,
    and the KV-handoff/replay counters a healthy no-fault run keeps
    at zero — the streaming-serving regime this build sustains,
    measured next to the throughput headline. The legacy
    metric/value/unit/vs_baseline contract is untouched."""
    from smi_tpu.serving.campaign import inference_fields

    return inference_fields(seed=0)


def pipeline_fields() -> dict:
    """Additive r18 stencil-pipeline provenance: the knobs the plan
    engine would run the double-buffered HBM→VMEM pipeline with
    (buffering/depth/stripe/compute dtype, with the tuning layer that
    decided them) plus the overlap fraction the stripe-stream replay
    *proves* for that buffering level
    (:func:`smi_tpu.analysis.perf.decompose_stencil_stream` — the
    statically-verified generator pair through the timestamped
    simulator, CPU-deterministic). ``{"enabled": False}`` when no
    pipeline plan resolves; the legacy metric/value/unit/vs_baseline
    contract is untouched either way (schema-guarded by
    ``tests/test_stencil_pipeline.py``)."""
    from smi_tpu.analysis import perf as P
    from smi_tpu.tuning.engine import get_engine

    eng = get_engine()
    got = eng.stencil_pipeline_knobs()
    if got is not None:
        knobs, layer = got
    else:
        plan = eng.stencil_pipeline_plan()
        knobs, layer = plan.knobs, plan.decided_by
        if isinstance(layer, dict):  # per-knob map; one layer decided all
            layer = layer.get("algorithm", "model")
    if knobs.get("algorithm") == "unfused" or "buffering" not in knobs:
        return {"enabled": False, "source": layer}
    buffering = int(knobs["buffering"])
    rep = P.decompose_stencil_stream(buffering=buffering)
    return {
        "enabled": True,
        "algorithm": knobs.get("algorithm"),
        "buffering": buffering,
        "depth": knobs.get("depth"),
        "stripe": knobs.get("stripe"),
        "compute_dtype": knobs.get("compute_dtype"),
        "overlap_fraction": round(P.stencil_overlap_fraction(rep), 4),
        "source": layer,
    }


def plan_fields(depth) -> dict:
    """Additive plan-provenance evidence: which tuning layer (cache /
    model / heuristic) produced the knobs behind the headline metric
    (:mod:`smi_tpu.tuning`). ``source`` is ``cache`` only when the knob
    actually used matches the plan cache's measured-best entry for this
    device kind — a number measured with drifted knobs must never claim
    cache provenance."""
    from smi_tpu.tuning.engine import get_engine

    eng = get_engine()
    planned_depth, layer = eng.stencil_depth()
    used = depth if depth is not None else 1
    return {
        "stencil_depth": {
            "value": used,
            "source": layer if planned_depth == used else "heuristic",
        },
        "device_kind": eng.device_kind(),
    }


def main():
    import jax
    import jax.numpy as jnp

    from smi_tpu.models import stencil
    from smi_tpu.parallel.mesh import make_communicator

    devices = jax.devices()
    n = len(devices)
    # factor the device count into the squarest (px, py) grid
    px = max(d for d in range(1, int(n**0.5) + 1) if n % d == 0)
    py = n // px

    x = y = 8192
    comm = make_communicator(
        shape=(px, py), axis_names=("sx", "sy"), devices=devices
    )
    from smi_tpu.benchmarks.surface import diff_rate
    from smi_tpu.kernels import stencil as kstencil
    from smi_tpu.kernels import stencil_temporal as ktemporal

    block_h, block_w = x // px, y // py
    depth = ktemporal.pick_temporal_depth(
        block_h, block_w, jnp.float32, 256
    )
    if depth is not None and n == 1:
        # single chip = the configuration the seeded plan was measured
        # at: a cache entry (seeded or swept) overrides the heuristic
        # knee; multichip block shapes keep the per-block heuristic
        # (never swept — the engine reports them as such). Best-effort:
        # tuning must never cost the headline run.
        try:
            from smi_tpu.tuning.engine import get_engine

            planned, _layer = get_engine().stencil_depth(x)
            if planned is not None:
                depth = planned
        except Exception:
            pass
    base_iters = (depth or 1) * 16  # iteration quantum per rep

    def make_jit(r):
        """The jitted stencil for ``r`` iteration quanta (fastest
        supported tier)."""
        iters = r * base_iters
        if depth is not None:
            # k sweeps per HBM pass (temporal blocking) — the fast path
            return ktemporal.make_temporal_stencil_fn(
                comm, iters, x, y, depth=depth
            )
        if kstencil.pallas_supported(block_h, block_w, jnp.float32):
            return kstencil.make_fused_stencil_fn(comm, iters, x, y)
        return stencil.make_stencil_fn(
            comm, iterations=iters, overlap=n > 1
        )

    def make_fn(r):
        """A timed closure doing ``r`` iteration quanta; the scalar
        readback forces completion — on tunneled backends
        block_until_ready alone resolves before the computation
        finishes."""
        fn = make_jit(r)
        return lambda: np.asarray(jnp.sum(fn(grid)))

    grid = jnp.asarray(stencil.initial_grid(x, y))

    # differential timing: time r and 4r iteration quanta (best-of-N
    # each against the shared chip's load variance) and divide the
    # *extra* cells by the *extra* time — the ~100-200 ms tunnel
    # dispatch+readback cost cancels, so the number is the kernel's
    # sustained throughput rather than the tunnel's latency
    cells_per_sec, _trace = diff_rate(
        make_fn, x * y * base_iters, runs=5
    )
    per_chip = cells_per_sec / n
    from smi_tpu.benchmarks.surface import stencil_roofline

    roof = stencil_roofline(per_chip, depth if depth is not None else 1)
    payload = {
        "metric": "stencil_8192x8192_cells_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "cells/s/chip",
        "vs_baseline": round(
            per_chip / REFERENCE_CELLS_PER_SEC_PER_DEVICE, 3
        ),
        "vs_tpu_roofline": {
            "hbm": round(roof["vs_hbm_roofline"], 4),
            "vpu": round(roof["vs_vpu_roofline"], 4),
            "depth": roof["depth"],
        },
    }
    if n > 1:
        # additive multichip field: the headline executable's static
        # overlap report (best-effort — a report failure must never
        # cost the throughput line)
        try:
            payload["overlap"] = overlap_fields(
                make_jit(1).lower(grid).compile()
            )
        except Exception as e:
            payload["overlap"] = {"error": f"{type(e).__name__}: {e}"}
        # additive elastic-provenance field (same best-effort contract)
        try:
            payload["elastic"] = elastic_fields()
        except Exception as e:
            payload["elastic"] = {"error": f"{type(e).__name__}: {e}"}
        # additive two-tier provenance field (same best-effort contract)
        try:
            payload["hierarchy"] = hierarchy_fields()
        except Exception as e:
            payload["hierarchy"] = {"error": f"{type(e).__name__}: {e}"}
        # additive serving-regime field (same best-effort contract)
        try:
            payload["serving"] = serving_fields()
        except Exception as e:
            payload["serving"] = {"error": f"{type(e).__name__}: {e}"}
    # additive plan-provenance field (same best-effort contract)
    try:
        payload["plan"] = plan_fields(depth)
    except Exception as e:
        payload["plan"] = {"error": f"{type(e).__name__}: {e}"}
    # additive r18 pipeline-provenance field (same best-effort
    # contract): the planned double-buffered pipeline knobs plus the
    # overlap fraction the stripe-stream replay proves for them
    try:
        payload["pipeline"] = pipeline_fields()
    except Exception as e:
        payload["pipeline"] = {"error": f"{type(e).__name__}: {e}"}
    # additive observability field (same best-effort contract): the
    # flight recorder's measured overhead + event accounting
    try:
        payload["obs"] = obs_fields()
    except Exception as e:
        payload["obs"] = {"error": f"{type(e).__name__}: {e}"}
    # additive online-retuning field (same best-effort contract): the
    # seeded payload-shift cell's ingest/propose/swap accounting
    try:
        payload["retune"] = retune_fields()
    except Exception as e:
        payload["retune"] = {"error": f"{type(e).__name__}: {e}"}
    # additive demand-elasticity field (same best-effort contract):
    # the seeded flash-crowd cell's scale/migration accounting
    try:
        payload["elasticity"] = elasticity_fields()
    except Exception as e:
        payload["elasticity"] = {"error": f"{type(e).__name__}: {e}"}
    # additive partition-tolerance field (same best-effort contract):
    # the seeded clean-cut cell's park/refuse/rejoin accounting
    try:
        payload["partition"] = partition_fields()
    except Exception as e:
        payload["partition"] = {"error": f"{type(e).__name__}: {e}"}
    # additive SLO field (same best-effort contract): fair-weather
    # burn rates + p99 blame component shares from the deterministic
    # serving smoke
    try:
        payload["slo"] = slo_fields()
    except Exception as e:
        payload["slo"] = {"error": f"{type(e).__name__}: {e}"}
    # additive streaming-inference field (same best-effort contract):
    # the disaggregated prefill/decode smoke's rate/TTFT/handoff
    # accounting
    try:
        payload["inference"] = streaming_inference_fields()
    except Exception as e:
        payload["inference"] = {"error": f"{type(e).__name__}: {e}"}
    # additive multi-metric scoreboard (same best-effort contract):
    # the measured stencil plus the committed flash/allreduce
    # baselines, each with a pass/regress verdict
    try:
        payload["scoreboard"] = scoreboard_fields(
            per_chip, depth if depth is not None else 1
        )
    except Exception as e:
        payload["scoreboard"] = {"error": f"{type(e).__name__}: {e}"}
    print(render_line(payload))


if __name__ == "__main__":
    main()
