"""Sharded application checkpoints: CRC-framed shards, atomic manifests.

The SCR half of the elastic runtime (PAPERS.md — Moody et al.,
"Design, Modeling, and Evaluation of a Scalable Multi-level
Checkpointing System", SC'10): long iterative jobs (Jacobi, K-means)
periodically persist **per-rank shards** so a crash at iteration *i*
restores from the latest *complete* checkpoint and replays only the
tail — never from iteration 0, never from a torn write.

Durability discipline (shared with the tuning plan cache and the
durable :class:`~smi_tpu.parallel.recovery.ProgressLog`):

- every file is written to a temp name in the same directory,
  ``fsync``\\ ed, then atomically renamed into place — a reader never
  observes a half-written shard or manifest;
- every shard carries the CRC+seq framing already proven on the wire
  by :class:`~smi_tpu.parallel.credits.Frame`: a JSON header naming
  ``(rank, step, nbytes, crc)`` followed by the raw payload bytes. A
  shard whose payload hashes differently from its header — bit rot,
  torn write that survived rename, wrong file — raises
  :class:`CheckpointIntegrityError` naming rank, step, and expected
  vs got, never deserializes into garbage state;
- the **manifest** (``manifest-<step>.json``, schema-versioned) lists
  every shard with its CRC and is written *after* all shards land, so
  a manifest's existence certifies a complete checkpoint. Restore
  scans manifests newest-first and takes the first whose shards all
  verify — a crash between shard writes leaves the previous manifest
  intact and authoritative.

:func:`run_iterative` is the generic driver; :func:`run_jacobi` and
:func:`run_kmeans` wrap the two streamed HPC models with it. Both are
bit-identical under crash/restore because each iteration is the same
per-step function applied to restored state — the invariant
``tests/test_checkpoint.py`` pins.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import tempfile
import zlib
from typing import Callable, Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

#: Default checkpoint cadence (iterations between checkpoints); the
#: cadence-vs-replay trade is documented in docs/robustness.md
#: (drift-guarded by tests/test_perf_docs.py). Env overrides:
CADENCE_ENV = "SMI_TPU_CHECKPOINT_CADENCE"
DIR_ENV = "SMI_TPU_CHECKPOINT_DIR"
DEFAULT_CADENCE = 8


class CheckpointError(RuntimeError):
    """A checkpoint could not be written or restored."""


class CheckpointIntegrityError(CheckpointError):
    """A shard's payload does not hash to its framed CRC.

    Mirrors :class:`~smi_tpu.parallel.credits.IntegrityError` for data
    at rest: names the ``rank``, ``step``, and ``expected`` vs ``got``
    CRCs so corruption is debuggable, and guarantees damaged state is
    never silently restored."""

    def __init__(self, message: str, rank: Optional[int] = None,
                 step: Optional[int] = None, expected=None, got=None):
        super().__init__(message)
        self.rank = rank
        self.step = step
        self.expected = expected
        self.got = got


def fsync_rename(tmp_path: str, final_path: str) -> None:
    """The durability idiom every persistent artifact here uses: flush
    + fsync the temp file's contents, atomically rename it into place,
    then fsync the directory so the rename itself is durable."""
    fd = os.open(tmp_path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp_path, final_path)
    dfd = os.open(os.path.dirname(final_path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename landed
    finally:
        os.close(dfd)


def write_atomic(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via temp file + fsync + rename."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-",
                               suffix=os.path.basename(path))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    dfd = os.open(d, os.O_RDONLY)
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


# ---------------------------------------------------------------------------
# Shard framing (CRC + seq, the credits.Frame discipline at rest)
# ---------------------------------------------------------------------------


def _encode_payload(payload) -> Tuple[bytes, dict]:
    """Serialize one shard payload. ndarrays round-trip exactly
    (dtype + shape + raw bytes); everything else goes through pickle —
    the same round-trip-exact encoding the durable ProgressLog uses.
    JSON would silently mutate containers on restore (tuples become
    lists, int dict keys become strings), and a resumed run whose
    state changed *type* diverges from the fault-free run, which is
    the exact silent divergence this layer exists to prevent."""
    try:
        import numpy as np
    except Exception:  # pragma: no cover - numpy is a hard dep
        np = None
    if np is not None and isinstance(payload, np.ndarray):
        return payload.tobytes(order="C"), {
            "kind": "ndarray",
            "dtype": str(payload.dtype),
            "shape": list(payload.shape),
        }
    import pickle

    return pickle.dumps(payload), {"kind": "pickle"}


def _decode_payload(data: bytes, meta: dict):
    if meta.get("kind") == "ndarray":
        import numpy as np

        return np.frombuffer(
            data, dtype=np.dtype(meta["dtype"])
        ).reshape(meta["shape"]).copy()
    if meta.get("kind") == "pickle":
        import pickle

        return pickle.loads(data)
    raise CheckpointIntegrityError(
        f"shard payload kind {meta.get('kind')!r} is unknown to this "
        f"build"
    )


def shard_name(rank: int, step: int) -> str:
    return f"shard-step{step:08d}-rank{rank}.bin"


def pack_shard(rank: int, step: int, payload) -> Tuple[bytes, int]:
    """Frame one shard in memory; returns ``(blob, crc)``.

    The exact bytes :func:`write_shard` puts on disk — a JSON header
    line framing the payload's length and CRC, then the payload. Split
    out so the framing is usable as a *transport*: a live-migration
    handoff ships a tenant's in-flight stream state through this
    discipline (pack → move → :func:`unpack_shard`) without touching a
    filesystem, and torn or bit-flipped state is rejected exactly like
    a damaged checkpoint at rest.
    """
    data, meta = _encode_payload(payload)
    crc = zlib.crc32(data) & 0xFFFFFFFF
    header = dict(
        meta, rank=rank, step=step, nbytes=len(data), crc=crc,
        schema_version=SCHEMA_VERSION,
    )
    return json.dumps(header, sort_keys=True).encode() + b"\n" + data, crc


def unpack_shard(blob: bytes, origin: str = "<memory>"):
    """Verify + decode a framed shard blob; returns
    ``(rank, step, payload, crc)``. ``origin`` names the blob's source
    in errors (a file path, a migration handoff, ...).

    Raises :class:`CheckpointIntegrityError` on a CRC or length
    mismatch — a damaged shard names itself instead of deserializing.
    """
    nl = blob.find(b"\n")
    if nl < 0:
        raise CheckpointIntegrityError(
            f"shard {origin!r} has no header line (torn or foreign file)"
        )
    try:
        header = json.loads(blob[:nl].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CheckpointIntegrityError(
            f"shard {origin!r} header is not JSON: {e}"
        ) from e
    data = blob[nl + 1:]
    rank, step = header.get("rank"), header.get("step")
    if len(data) != header.get("nbytes"):
        raise CheckpointIntegrityError(
            f"shard {origin!r} (rank {rank}, step {step}) payload is "
            f"{len(data)} bytes but the header framed "
            f"{header.get('nbytes')} (torn write)",
            rank=rank, step=step,
            expected=header.get("nbytes"), got=len(data),
        )
    crc = zlib.crc32(data) & 0xFFFFFFFF
    if crc != header.get("crc"):
        raise CheckpointIntegrityError(
            f"shard {origin!r} (rank {rank}, step {step}): payload "
            f"hashes to {crc:#010x} but the header framed "
            f"{header.get('crc'):#010x} (corrupted at rest)",
            rank=rank, step=step, expected=header.get("crc"), got=crc,
        )
    return rank, step, _decode_payload(data, header), crc


def write_shard(directory: str, rank: int, step: int,
                payload) -> Tuple[str, int]:
    """Write one CRC-framed shard atomically; returns its filename and
    the framed CRC (so the manifest can quote it without re-encoding
    the payload)."""
    blob, crc = pack_shard(rank, step, payload)
    name = shard_name(rank, step)
    write_atomic(os.path.join(directory, name), blob)
    return name, crc


def read_shard(path: str):
    """Read + verify one shard; returns ``(rank, step, payload, crc)``
    (``crc`` is the framed checksum, for callers holding an external
    record of what this shard should be — the manifest).

    Raises :class:`CheckpointIntegrityError` on a CRC or length
    mismatch — a damaged shard names itself instead of deserializing.
    """
    with open(path, "rb") as f:
        blob = f.read()
    return unpack_shard(blob, origin=path)


# ---------------------------------------------------------------------------
# Manifests + the store
# ---------------------------------------------------------------------------

_MANIFEST_RE = re.compile(r"^manifest-(\d+)\.json$")


@dataclasses.dataclass
class Manifest:
    """One complete checkpoint's table of contents."""

    step: int
    epoch: int
    shards: Dict[int, Dict]  # rank -> {"file": ..., "crc": ...}

    def to_json(self) -> dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "step": self.step,
            "epoch": self.epoch,
            "shards": {str(r): s for r, s in sorted(self.shards.items())},
        }

    @staticmethod
    def from_json(payload: object, path: str) -> "Manifest":
        if not isinstance(payload, dict):
            raise CheckpointError(
                f"manifest {path!r} must be a JSON object"
            )
        version = payload.get("schema_version")
        if version != SCHEMA_VERSION:
            raise CheckpointError(
                f"manifest {path!r} schema_version {version!r} does "
                f"not match this build's {SCHEMA_VERSION}; refusing to "
                f"reinterpret checkpoint layout across schema changes"
            )
        shards = payload.get("shards")
        if not isinstance(shards, dict) or not shards:
            raise CheckpointError(
                f"manifest {path!r} has no shard table"
            )
        return Manifest(
            step=int(payload["step"]),
            epoch=int(payload.get("epoch", 0)),
            shards={int(r): dict(s) for r, s in shards.items()},
        )


class CheckpointStore:
    """A directory of CRC-framed shards + atomic versioned manifests."""

    def __init__(self, directory: str, keep: int = 2):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = directory
        self.keep = keep

    def save(self, step: int, shards: Dict[int, object],
             epoch: int = 0) -> str:
        """Persist one complete checkpoint: all shards first, the
        manifest last (its rename is the commit point). Returns the
        manifest path. Old checkpoints beyond ``keep`` are pruned
        after the new manifest is durable."""
        if not shards:
            raise CheckpointError("refusing to checkpoint zero shards")
        table: Dict[int, Dict] = {}
        for rank in sorted(shards):
            name, crc = write_shard(self.directory, rank, step,
                                    shards[rank])
            table[rank] = {"file": name, "crc": crc}
        manifest = Manifest(step=step, epoch=epoch, shards=table)
        path = os.path.join(self.directory, f"manifest-{step:08d}.json")
        write_atomic(
            path, (json.dumps(manifest.to_json(), indent=2,
                              sort_keys=True) + "\n").encode(),
        )
        self._prune()
        return path

    def manifests(self) -> List[str]:
        """Manifest paths, newest step first."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        found = []
        for name in names:
            m = _MANIFEST_RE.match(name)
            if m:
                found.append((int(m.group(1)), name))
        return [
            os.path.join(self.directory, name)
            for _, name in sorted(found, reverse=True)
        ]

    def restore(self) -> Optional[Tuple[int, Dict[int, object], int]]:
        """``(step, shards, epoch)`` from the latest manifest whose
        shards all exist and verify; None when no checkpoint is
        complete. An incomplete or damaged newest checkpoint falls
        back to the previous one — the SCR recovery rule. Two kinds of
        shard trouble are distinguished: a shard that fails its OWN
        framed CRC is bit rot and is raised, never skipped; a shard
        that self-verifies but does not match the CRC the manifest
        recorded belongs to a *different generation* of the same step
        (an interrupted re-save overwrote it after the manifest
        committed) — that manifest is incomplete, and restore falls
        back rather than silently mixing generations."""
        for path in self.manifests():
            try:
                with open(path) as f:
                    manifest = Manifest.from_json(json.load(f), path)
            except (OSError, json.JSONDecodeError):
                continue  # torn manifest never renamed in: not a commit
            shards: Dict[int, object] = {}
            complete = True
            for rank, entry in manifest.shards.items():
                spath = os.path.join(self.directory, entry["file"])
                if not os.path.exists(spath):
                    complete = False
                    break
                srank, sstep, payload, crc = read_shard(spath)
                if srank != rank or sstep != manifest.step:
                    raise CheckpointIntegrityError(
                        f"shard {spath!r} frames (rank {srank}, step "
                        f"{sstep}) but manifest {path!r} expects "
                        f"(rank {rank}, step {manifest.step})",
                        rank=rank, step=manifest.step,
                        expected=(rank, manifest.step),
                        got=(srank, sstep),
                    )
                if crc != entry.get("crc"):
                    # self-consistent shard, wrong generation: an
                    # interrupted re-save of this step overwrote it —
                    # the manifest no longer describes a complete
                    # checkpoint
                    complete = False
                    break
                shards[rank] = payload
            if complete:
                return manifest.step, shards, manifest.epoch
        return None

    def latest_step(self) -> Optional[int]:
        restored = self.restore()
        return None if restored is None else restored[0]

    def _prune(self) -> None:
        for path in self.manifests()[self.keep:]:
            try:
                with open(path) as f:
                    manifest = Manifest.from_json(json.load(f), path)
                for entry in manifest.shards.values():
                    try:
                        os.unlink(
                            os.path.join(self.directory, entry["file"])
                        )
                    except OSError:
                        pass
                os.unlink(path)
            except (OSError, json.JSONDecodeError, CheckpointError):
                pass  # pruning is best-effort; restore stays correct


# ---------------------------------------------------------------------------
# Iterative drivers
# ---------------------------------------------------------------------------


def run_iterative(
    state,
    step_fn: Callable,
    iterations: int,
    store: Optional[CheckpointStore] = None,
    cadence: int = DEFAULT_CADENCE,
    shard_fn: Optional[Callable] = None,
    unshard_fn: Optional[Callable] = None,
    resume: bool = True,
    epoch: Optional[int] = None,
):
    """Run ``state = step_fn(state)`` for ``iterations`` steps with
    periodic sharded checkpoints.

    ``shard_fn(state) -> {rank: payload}`` splits the state for the
    store and ``unshard_fn(shards) -> state`` reassembles it (both
    default to a single rank-0 shard). With ``resume`` and a complete
    manifest in the store, the run restores the latest checkpointed
    state and **replays only the tail** — iteration ``k`` of a resumed
    run applies the same ``step_fn`` to the same state as iteration
    ``k`` of an uninterrupted run, so results are bit-identical.
    ``epoch`` stamps the manifests; when omitted, a resumed run keeps
    the restored manifest's epoch (the membership audit field must not
    regress to 0 just because the resuming caller did not restate it).
    Returns ``(state, start_iteration)``.
    """
    if cadence < 1:
        raise ValueError(f"cadence must be >= 1, got {cadence}")
    shard_fn = shard_fn or (lambda s: {0: s})
    unshard_fn = unshard_fn or (lambda shards: shards[0])
    start = 0
    if store is not None and resume:
        restored = store.restore()
        if restored is not None:
            start, shards, saved_epoch = restored
            if start > iterations:
                raise CheckpointError(
                    f"checkpoint is at iteration {start} but the run "
                    f"only asks for {iterations}"
                )
            state = unshard_fn(shards)
            if epoch is None:
                epoch = saved_epoch
    epoch = 0 if epoch is None else epoch
    if store is not None and start == 0:
        store.save(0, shard_fn(state), epoch=epoch)
    for it in range(start, iterations):
        state = step_fn(state)
        done = it + 1
        if store is not None and (
            done % cadence == 0 or done == iterations
        ):
            store.save(done, shard_fn(state), epoch=epoch)
    return state, start


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        value = int(raw)
    except ValueError as e:
        raise CheckpointError(
            f"${name}={raw!r} is not an integer"
        ) from e
    if value < 1:
        raise CheckpointError(f"${name}={value} must be >= 1")
    return value


def elastic_env_config() -> Optional[Dict]:
    """The env-driven elastic configuration, or None when disabled.

    ``$SMI_TPU_CHECKPOINT_DIR`` enables checkpointing for the
    iterative drivers and the bench provenance field;
    ``$SMI_TPU_CHECKPOINT_CADENCE`` overrides :data:`DEFAULT_CADENCE`.
    Malformed values raise loudly (:class:`CheckpointError`) — a typo
    must not silently disable durability.
    """
    directory = os.environ.get(DIR_ENV, "").strip()
    if not directory:
        return None
    from smi_tpu.parallel import membership as M

    return {
        "dir": directory,
        "cadence": _env_int(CADENCE_ENV) or DEFAULT_CADENCE,
        "detector": {
            "suspect_phi": M.SUSPECT_PHI,
            "dead_phi": M.DEAD_PHI,
            "heartbeat_interval": M.HEARTBEAT_INTERVAL,
            "confirm_grace_ticks": M.CONFIRM_GRACE_TICKS,
        },
    }


def run_jacobi(
    grid,
    iterations: int,
    comm=None,
    store: Optional[CheckpointStore] = None,
    cadence: int = DEFAULT_CADENCE,
    px: int = 2,
    py: int = 4,
    devices=None,
):
    """The Jacobi model under the checkpointing driver.

    One compiled sweep (``models.stencil.make_stencil_fn(comm, 1)``)
    per iteration; the grid is sharded into the store one row-band per
    process-grid row. A crash at iteration *i* restores from the
    latest complete manifest and replays only the tail — bit-identical
    to the uninterrupted run, because every iteration is the same
    compiled program applied to the same state.
    """
    import numpy as np

    from smi_tpu.models.stencil import make_stencil_fn
    from smi_tpu.parallel.mesh import make_communicator

    if comm is None:
        comm = make_communicator(
            shape=(px, py), axis_names=("sx", "sy"), devices=devices
        )
    px, py = comm.axis_sizes
    step = make_stencil_fn(comm, iterations=1)
    rows = np.asarray(grid).shape[0]
    if rows % px:
        raise ValueError(
            f"grid rows {rows} not divisible by process rows {px}"
        )
    band = rows // px

    def shard(state):
        host = np.asarray(state)
        return {
            r: host[r * band:(r + 1) * band] for r in range(px)
        }

    def unshard(shards):
        import jax.numpy as jnp

        return jnp.asarray(
            np.concatenate([shards[r] for r in range(px)])
        )

    import jax.numpy as jnp

    state, _start = run_iterative(
        jnp.asarray(grid), step, iterations, store=store,
        cadence=cadence, shard_fn=shard, unshard_fn=unshard,
    )
    return state


def run_kmeans(
    points,
    init_means,
    iterations: int,
    comm=None,
    store: Optional[CheckpointStore] = None,
    cadence: int = DEFAULT_CADENCE,
    devices=None,
):
    """The K-means model under the checkpointing driver.

    The iterated state is the replicated means (the points are static
    input); one compiled update (``models.kmeans.make_kmeans_fn(comm,
    1)``) per iteration, means checkpointed as the rank-0 shard.
    Crash/restore replays only the tail, bit-identically.
    """
    import jax.numpy as jnp
    import numpy as np

    from smi_tpu.models.kmeans import make_kmeans_fn
    from smi_tpu.parallel.mesh import make_communicator

    if comm is None:
        comm = make_communicator(devices=devices)
    if np.asarray(points).shape[0] % comm.size:
        raise ValueError(
            f"point count {np.asarray(points).shape[0]} not divisible "
            f"by {comm.size} ranks"
        )
    fn = make_kmeans_fn(comm, 1)
    pts = jnp.asarray(points)

    state, _start = run_iterative(
        jnp.asarray(init_means),
        lambda means: fn(pts, means),
        iterations,
        store=store,
        cadence=cadence,
        shard_fn=lambda m: {0: np.asarray(m)},
        unshard_fn=lambda shards: jnp.asarray(shards[0]),
    )
    return state
