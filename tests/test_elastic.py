"""Elastic runtime integration: CLI, bench provenance, runtime bridge.

The seams between the new membership/checkpoint layers and everything
that already existed: ``run_with_recovery`` consulting the membership
view, ``Communicator.regrow`` as the inverse of ``shrink``, the
``chaos --elastic`` and ``route --check`` heir surfaces, the elastic
fault classes' registration (and their deliberate absence from the
seed-pinned base campaign), and the bench line's additive ``elastic``
field under the unchanged legacy schema.
"""

import dataclasses
import json

import pytest

from smi_tpu.parallel import faults as F
from smi_tpu.parallel import membership as M
from smi_tpu.parallel import recovery as R

pytestmark = pytest.mark.elastic


# ---------------------------------------------------------------------------
# Fault-class registration
# ---------------------------------------------------------------------------


def test_elastic_classes_not_in_base_fault_classes():
    """The seed-pinned base chaos campaign draws from FAULT_CLASSES;
    the elastic classes must live in their own tuple or every pinned
    cell silently re-rolls."""
    assert set(F.ELASTIC_FAULT_CLASSES) == {
        "flapping_rank", "stalled_heartbeat"
    }
    assert not set(F.ELASTIC_FAULT_CLASSES) & set(F.FAULT_CLASSES)


def test_elastic_faults_register_with_fault_plan():
    flap = F.FlappingRank(1, dies_at=2, rejoins_at=6)
    sil = F.StalledHeartbeat(0, from_tick=50, silent_for=20)
    plan = F.FaultPlan.of([flap, sil])
    assert plan.faults() == (flap, sil)
    assert not plan.empty
    assert F.FaultPlan.single(flap).flapping_ranks == (flap,)
    described = plan.describe()
    assert any("FlappingRank" in s for s in described)
    assert any("StalledHeartbeat" in s for s in described)
    # job-level faults have no simulator-hook effect
    assert plan.stall_after(1) is None
    assert plan.grant_multiplier(0, 0) == 1


def test_elastic_random_plans_seeded():
    for cls in F.ELASTIC_FAULT_CLASSES:
        a = F.FaultPlan.random(cls, 4, 17)
        assert a == F.FaultPlan.random(cls, 4, 17)
        assert len(a.faults()) == 1


def test_flapping_rank_must_die_before_rejoining():
    with pytest.raises(ValueError, match="die before it rejoins"):
        F.FlappingRank(0, dies_at=5, rejoins_at=5)


def test_base_campaign_seed_pinned_cells_unchanged():
    """Adding the elastic fields must not perturb a single base-chaos
    draw: the pinned plan for a known cell seed is byte-stable."""
    plan = R.random_chaos_plan(4, 12345, max_faults=2)
    assert plan == R.random_chaos_plan(4, 12345, max_faults=2)
    assert not plan.flapping_ranks and not plan.stalled_heartbeats


# ---------------------------------------------------------------------------
# run_with_recovery consults membership
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("protocol", F.PROTOCOLS)
def test_recovery_membership_preshrink_skips_doomed_ring(protocol):
    """A rank the detector already confirmed dead is shrunk out BEFORE
    attempt 1 — no deadlock is ever provoked, and the heirs serve the
    dead rank's logged contribution so results still match the
    fault-free run exactly."""
    view = M.MembershipView(4)
    view.confirm_dead(2)
    out = R.run_with_recovery(protocol, 4, None, membership=view)
    assert out.ok
    assert out.survivors == (0, 1, 3)
    assert out.attempts[0].verdict == "membership-shrink"
    assert out.attempts[0].failed_ranks == (2,)
    # no attempt ever deadlocked: the detector beat the error path
    assert not any("Deadlock" in a.verdict for a in out.attempts)


def test_recovery_membership_composes_with_error_parsing():
    """Detector knowledge and error-dump knowledge union: one rank
    pre-confirmed dead, another crashes mid-run."""
    view = M.MembershipView(5)
    view.confirm_dead(4)
    plan = F.FaultPlan.single(F.StalledRank(1, after=3))
    out = R.run_with_recovery("all_gather", 5, plan, membership=view)
    assert out.ok
    assert 4 not in out.survivors and 1 not in out.survivors
    assert out.fault_trail[0] == "membership-shrink"


def test_recovery_membership_annihilation_is_named():
    view = M.MembershipView(2)
    view.confirm_dead(0)
    # the view's own guard forbids removing the last member; a view
    # that nonetheless reports everyone dead (operator override, a
    # merged remote view) must surface as named annihilation
    view.members = set()
    with pytest.raises(R.UnrecoverableError) as e:
        R.run_with_recovery("all_reduce", 2, None, membership=view)
    assert e.value.annihilated


def test_recovery_without_membership_is_unchanged():
    plan = F.FaultPlan.single(F.StalledRank(2, after=5))
    a = R.run_with_recovery("all_reduce", 4, plan, strategy_seed=3)
    b = R.run_with_recovery("all_reduce", 4, plan, strategy_seed=3,
                            membership=None)
    assert a.ok and b.ok and a.fault_trail == b.fault_trail


# ---------------------------------------------------------------------------
# Communicator.regrow (runtime bridge; CPU fake mesh)
# ---------------------------------------------------------------------------


def test_regrow_is_the_inverse_of_shrink(comm8):
    small = comm8.shrink({3, 5})
    assert small.size == 6 and small.epoch == comm8.epoch + 1
    back = comm8.regrow({3, 5}, {3}, epoch=small.epoch + 1)
    assert back.size == 7 and back.epoch == 2
    orig = list(comm8.mesh.devices.flat)
    assert list(back.mesh.devices.flat) == [
        d for i, d in enumerate(orig) if i != 5
    ]
    full = comm8.regrow({3, 5}, {3, 5})
    assert full.size == 8
    assert list(full.mesh.devices.flat) == orig


def test_regrow_bare_mesh_skips_physical_check(comm8):
    """A plain JAX mesh has no wire list: two non-adjacent still-dead
    ranks must NOT spuriously strand a readmitted survivor (shrink to
    the identical membership has never required a topology either)."""
    back = comm8.regrow({1, 2, 3}, {2})
    assert back.size == 6
    orig = list(comm8.mesh.devices.flat)
    assert list(back.mesh.devices.flat) == [
        d for i, d in enumerate(orig) if i not in (1, 3)
    ]


def test_regrow_with_topology_validates_the_real_wires(eight_devices):
    """With a real topology the still-dead devices become a
    FailureSet: a regrow that strands a member on the actual wire
    graph raises RouteCutError naming the cut; one the graph can route
    around succeeds."""
    from smi_tpu.parallel.mesh import mesh_from_topology
    from smi_tpu.parallel.routing import RouteCutError, grid_topology

    ring = mesh_from_topology(grid_topology(1, 8),
                              devices=eight_devices)
    # on the 8-ring, dead {1, 3} isolate rank 2 from the others
    with pytest.raises(RouteCutError):
        ring.regrow({1, 2, 3}, {2})
    # dead {1} alone routes around via the wrap wire
    assert ring.regrow({1, 2}, {2}).size == 7
    # the 2x4 torus routes around the same dead pair fine
    torus = mesh_from_topology(grid_topology(2, 4),
                               devices=eight_devices)
    assert torus.regrow({1, 2, 3}, {2}).size == 6


def test_regrow_validates_its_arguments(comm8):
    with pytest.raises(ValueError, match="not in the excluded set"):
        comm8.regrow({3}, {4})
    with pytest.raises(ValueError, match="at least one rank"):
        comm8.regrow({3}, set())
    with pytest.raises(ValueError, match="out of range"):
        comm8.regrow({99}, {99})


def test_validate_epoch_rejects_stale_traffic(comm8):
    regrown = comm8.regrow({2}, {2})
    regrown.validate_epoch(2, regrown.epoch)  # current: fine
    with pytest.raises(M.StaleEpochError) as e:
        regrown.validate_epoch(2, 0, what="halo slab")
    assert e.value.rank == 2 and e.value.current == regrown.epoch
    assert "halo slab" in str(e.value)


def test_regrow_default_epoch_outranks_the_shrunk_incarnation(comm8):
    """The natural shrink -> regrow cycle with NO explicit epoch: the
    regrown epoch must supersede the shrunk communicator's, or a
    straggler tagged with the shrunk epoch would pass the gate — the
    exact stale traffic the epoch exists to reject."""
    shrunk = comm8.shrink({2})
    regrown = comm8.regrow({2}, {2})
    assert regrown.epoch > shrunk.epoch
    with pytest.raises(M.StaleEpochError):
        regrown.validate_epoch(2, shrunk.epoch, what="straggler")


def test_validate_epoch_names_the_split_view_side(comm8):
    """A NEWER tag than ours means WE are stale: the error must say
    split view, not tell the healthy sender to regrow."""
    with pytest.raises(M.StaleEpochError, match="split view") as e:
        comm8.validate_epoch(3, comm8.epoch + 5)
    assert "regrow()" not in str(e.value)


def test_shrink_bumps_epoch_but_not_equality(comm8):
    small = comm8.shrink({7})
    twin = dataclasses.replace(small, epoch=small.epoch + 5)
    assert twin == small  # epoch is compare=False: dispatch unaffected


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------


def test_chaos_elastic_cli_gate_and_report(tmp_path, capsys):
    from smi_tpu.__main__ import main

    out = tmp_path / "elastic.json"
    rc = main(["chaos", "--elastic", "--seed", "1729",
               "--ranks", "2", "3", "--trials", "1", "-o", str(out)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["ok"]
    assert report["silent_corruptions"] == 0
    assert report["stale_epoch_leaks"] == 0
    assert report["max_detect_ticks"] <= report["watchdog_budget_ticks"]
    printed = capsys.readouterr().out
    assert "elastic campaign ok" in printed
    assert "stale-epoch packets" in printed


def test_chaos_elastic_cli_rejects_protocols(capsys):
    from smi_tpu.__main__ import main

    rc = main(["chaos", "--elastic", "--protocols", "all_gather"])
    assert rc == 2


def test_chaos_elastic_cli_rejects_max_faults(capsys):
    """Elastic plans draw exactly one job-level fault: a --max-faults
    that silently did nothing would misrepresent the sweep."""
    from smi_tpu.__main__ import main

    rc = main(["chaos", "--elastic", "--max-faults", "3"])
    assert rc == 2
    assert "--max-faults does not apply" in capsys.readouterr().err


def _write_ring_topology(tmp_path, n=4):
    from smi_tpu.__main__ import main

    topo = tmp_path / "topo.json"
    assert main(["topology", "-n", str(n), "-p", "app",
                 "-f", str(topo), "--ring"]) == 0
    return topo


def test_route_check_names_reachable_heirs(tmp_path, capsys):
    from smi_tpu.__main__ import main

    topo = _write_ring_topology(tmp_path)
    rc = main(["route", str(topo), "--check", "--down", "device-1:0"])
    printed = capsys.readouterr().out
    assert rc == 0
    assert "heirs: ok (rank 1 -> rank 2" in printed


def test_route_check_heirless_rank_is_named(tmp_path, capsys):
    """All devices down: the all-pairs check passes trivially (no
    healthy pairs), so the heir check is the one that catches it —
    naming every stranded rank."""
    from smi_tpu.__main__ import main

    topo = _write_ring_topology(tmp_path)
    rc = main(["route", str(topo), "--check"]
              + [x for i in range(4)
                 for x in ("--down", f"device-{i}:0")])
    printed = capsys.readouterr().out
    assert rc == 1
    assert "heirs: FAIL — rank 0 (device-0:0) has no surviving heir" \
        in printed


def test_route_check_without_down_devices_prints_no_heirs(tmp_path,
                                                          capsys):
    from smi_tpu.__main__ import main

    topo = _write_ring_topology(tmp_path)
    rc = main(["route", str(topo), "--check"])
    printed = capsys.readouterr().out
    assert rc == 0
    assert "heirs:" not in printed


# ---------------------------------------------------------------------------
# bench.py: the additive elastic field under the legacy schema
# ---------------------------------------------------------------------------


def _legacy_payload():
    return {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 0.5}


def test_bench_elastic_field_is_additive_and_schema_safe(monkeypatch):
    import bench

    monkeypatch.delenv("SMI_TPU_CHECKPOINT_DIR", raising=False)
    assert bench.elastic_fields() == {"enabled": False}
    monkeypatch.setenv("SMI_TPU_CHECKPOINT_DIR", "/tmp/ck")
    monkeypatch.setenv("SMI_TPU_CHECKPOINT_CADENCE", "16")
    fields = bench.elastic_fields()
    assert fields["enabled"] and fields["cadence"] == 16
    assert fields["detector"]["suspect_phi"] == M.SUSPECT_PHI
    assert fields["detector"]["dead_phi"] == M.DEAD_PHI
    # the ONE output line: legacy keys intact with the field attached
    payload = dict(_legacy_payload(), elastic=fields)
    line = bench.render_line(payload)
    parsed = json.loads(line)
    assert parsed["metric"] == "m" and parsed["vs_baseline"] == 0.5
    assert parsed["elastic"]["cadence"] == 16
    assert "\n" not in line


def test_bench_render_line_still_rejects_dropped_legacy_keys():
    import bench

    payload = _legacy_payload()
    payload.pop("unit")
    payload["elastic"] = {"enabled": False}
    with pytest.raises(ValueError, match="legacy key"):
        bench.render_line(payload)
