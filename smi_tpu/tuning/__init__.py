"""Plan engine: cost-model-driven autotuning with a persistent cache.

Three layers turn every frozen performance knob in the framework into
an inspectable, overridable decision (ISSUE 4; PAPERS.md: ATLAS
empirical autotuning + the Hockney alpha-beta model):

1. :mod:`~smi_tpu.tuning.cost_model` — deterministic analytic ranking
   (alpha-beta link model for collectives, rooflines + VMEM gates for
   kernels), runnable on any CPU.
2. :mod:`~smi_tpu.tuning.sweep` — the measured refinement, reusing the
   ``benchmarks/micro.py`` timing harness on real hardware.
3. :mod:`~smi_tpu.tuning.cache` — the persistent, versioned, mergeable
   JSON plan cache, shipped pre-seeded with PERF.json's measured-best
   configs (:mod:`~smi_tpu.tuning.seeded`).

:mod:`~smi_tpu.tuning.engine` resolves cache -> model -> heuristic at
trace time for ``collectives.py``, ``kernels/flash.py``,
``kernels/ring.py`` and :class:`SmiContext` — never erroring, and
byte-identical to the pre-engine behavior until a cache entry or a
confident model call says otherwise. ``smi-tpu tune`` sweeps and writes
the cache; ``smi-tpu tune --explain OP`` prints the candidate table
with the deciding layer per knob; :meth:`Plan.explain` is the same
trail as an API.
"""

from smi_tpu.tuning.cache import (
    CacheEntry,
    PlanCache,
    PlanCacheError,
    default_cache_path,
)
from smi_tpu.tuning.cost_model import LinkModel, TopologySpec
from smi_tpu.tuning.engine import PlanEngine, get_engine, set_engine
from smi_tpu.tuning.online import (
    OnlineTuner,
    online_retune_enabled,
    retune_margin,
    retune_min_samples,
)
from smi_tpu.tuning.plan import Candidate, Plan, PlanKey
from smi_tpu.tuning.seeded import seeded_cache
from smi_tpu.tuning.swap import (
    PlanSwap,
    PlanSwapError,
    StalePlanError,
    SwapProposal,
)

__all__ = [
    "CacheEntry",
    "Candidate",
    "LinkModel",
    "OnlineTuner",
    "Plan",
    "PlanCache",
    "PlanCacheError",
    "PlanEngine",
    "PlanKey",
    "PlanSwap",
    "PlanSwapError",
    "StalePlanError",
    "SwapProposal",
    "TopologySpec",
    "default_cache_path",
    "get_engine",
    "online_retune_enabled",
    "retune_margin",
    "retune_min_samples",
    "seeded_cache",
    "set_engine",
]
