"""Demand elasticity: SLO-driven autoscaling and migration triggers.

ROADMAP item 2's control loop, closed over signals the stack already
ships — nothing here invents a new measurement:

- **capacity autoscaling** — the controller watches the SLO engine's
  burn rates (:meth:`smi_tpu.obs.slo.SloEngine.health`) and the
  admission gate's queue pressure, and drives the membership
  actuators :func:`~smi_tpu.parallel.membership.regrow_pod` /
  :func:`~smi_tpu.parallel.membership.shrink_pod` — *proactively*,
  before a breach, not after. Scale-out needs :data:`SCALE_OUT_SUSTAIN_TICKS`
  consecutive hot ticks; scale-in needs :data:`SCALE_IN_SUSTAIN_TICKS`
  consecutive cold ticks at under :data:`SCALE_IN_BURN_FRACTION` of
  the scale-out threshold — a hysteresis band, so burn hovering at
  the threshold can never flap capacity — and every actuation starts
  a :data:`SCALE_COOLDOWN_TICKS` cooldown (the retune min-samples /
  margin discipline applied to capacity: noise can never flip it).
- **migration triggers** — a structured
  :class:`~smi_tpu.obs.spans.BlameVerdict` naming a wire-contended
  rank (``wire:rank<r>``) for a hot tenant turns into a live
  migration request against the front-end
  (:meth:`~smi_tpu.serving.frontend.ServingFrontend.request_migration`),
  destination chosen by the same measured load signal the placement
  map uses.

Scale-in *parks* a healthy rank (membership ``scale-in`` transition,
epoch bump, ``ctl.scale`` event) — deliberately distinct from a death:
the detector's history is dropped so a parked rank is never suspected,
and scale-out re-admits it under a fresh incarnation. A victim is only
eligible when it holds **zero residents** (no active stream destined
to it) and its wire lane is empty — capacity changes never strand
accepted work.

Everything is off by default: ``$SMI_TPU_AUTOSCALE`` arms the loop
(the ``default_deadline`` loudness discipline — a typo is a ValueError
naming knob and value, never a silently different behaviour), and
``$SMI_TPU_SCALE_COOLDOWN`` / ``$SMI_TPU_SCALE_BURN_THRESHOLD``
outrank the built-ins below.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional

from smi_tpu.obs.spans import BlameVerdict
from smi_tpu.parallel.membership import regrow_pod, shrink_pod

#: Master switch (off by default — elasticity only runs where a
#: caller or the environment asked for it). Boolean vocabulary below;
#: anything else is a LOUD ValueError naming knob and value.
AUTOSCALE_ENV = "SMI_TPU_AUTOSCALE"
SCALE_COOLDOWN_ENV = "SMI_TPU_SCALE_COOLDOWN"
SCALE_BURN_ENV = "SMI_TPU_SCALE_BURN_THRESHOLD"

_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("", "0", "false", "no", "off")

#: Ticks after any actuation before the next one may fire — one
#: capacity decision must see its own effect before the next.
#: Overridable by ``$SMI_TPU_SCALE_COOLDOWN``. docs/robustness.md
#: quotes this (drift-guarded).
SCALE_COOLDOWN_TICKS = 64

#: Short-window burn rate at or above which a tick counts as *hot*
#: (1.0 = burning the error budget exactly at the breach rate).
#: Overridable by ``$SMI_TPU_SCALE_BURN_THRESHOLD``.
SCALE_BURN_THRESHOLD = 1.0

#: Consecutive hot ticks (sustained burn or queue pressure) before a
#: scale-out fires — one bursty tick can never grow the pod.
SCALE_OUT_SUSTAIN_TICKS = 12

#: Consecutive cold ticks before a scale-in fires — deliberately
#: several times the scale-out sustain: growing is cheap, stranding
#: capacity mid-crowd is not.
SCALE_IN_SUSTAIN_TICKS = 48

#: A tick is *cold* only when burn is under this fraction of the
#: scale-out threshold (and the queue is quiet) — the hysteresis band
#: between the two thresholds absorbs hover-at-threshold noise.
SCALE_IN_BURN_FRACTION = 0.25

#: The serving floor: scale-in never shrinks below this many members
#: (the front-end's own ``n >= 2`` invariant).
MIN_SERVING_RANKS = 2


def autoscale_enabled() -> bool:
    """``$SMI_TPU_AUTOSCALE``: unset/empty/0/false/no/off = OFF;
    1/true/yes/on = ON; anything else is a loud ValueError."""
    raw = os.environ.get(AUTOSCALE_ENV, "").strip().lower()
    if raw in _FALSY:
        return False
    if raw in _TRUTHY:
        return True
    raise ValueError(
        f"${AUTOSCALE_ENV} must be one of "
        f"{_TRUTHY + tuple(v for v in _FALSY if v)} (or unset), got "
        f"{os.environ.get(AUTOSCALE_ENV)!r}"
    )


def scale_cooldown_ticks() -> int:
    """``$SMI_TPU_SCALE_COOLDOWN`` (a positive tick count — it
    outranks the built-in :data:`SCALE_COOLDOWN_TICKS`), loud on
    malformed or non-positive values."""
    raw = os.environ.get(SCALE_COOLDOWN_ENV, "").strip()
    if not raw:
        return SCALE_COOLDOWN_TICKS
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"${SCALE_COOLDOWN_ENV} must be a positive integer tick "
            f"count, got {raw!r}"
        ) from None
    if value < 1:
        raise ValueError(
            f"${SCALE_COOLDOWN_ENV} must be >= 1 (a zero cooldown "
            f"would let one tick's noise flap capacity), got {raw!r}"
        )
    return value


def scale_burn_threshold() -> float:
    """``$SMI_TPU_SCALE_BURN_THRESHOLD`` (a finite burn rate > 0 — it
    outranks the built-in :data:`SCALE_BURN_THRESHOLD`), loud on
    malformed values: a non-positive threshold would mark every tick
    hot and pin capacity at the ceiling."""
    raw = os.environ.get(SCALE_BURN_ENV, "").strip()
    if not raw:
        return SCALE_BURN_THRESHOLD
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"${SCALE_BURN_ENV} must be a burn-rate threshold, got "
            f"{raw!r}"
        ) from None
    if not math.isfinite(value):
        raise ValueError(
            f"${SCALE_BURN_ENV} must be finite, got {raw!r}"
        )
    if value <= 0:
        raise ValueError(
            f"${SCALE_BURN_ENV} must be > 0 (a non-positive threshold "
            f"marks every tick hot), got {raw!r}"
        )
    return value


class ElasticityController:
    """The demand-elasticity control loop over one serving front-end.

    Deterministic on the front-end's step clock: ``bind()`` parks
    ``spares`` ranks (the grow headroom), then :meth:`step` runs once
    per tick after the SLO engine evaluates, applying at most one
    actuation per tick. ``cooldown`` / ``burn_threshold`` default to
    the env-resolved knobs (env outranks built-ins; an explicit
    argument outranks both).
    """

    def __init__(
        self,
        spares: int = 1,
        cooldown: Optional[int] = None,
        burn_threshold: Optional[float] = None,
        sustain_out: int = SCALE_OUT_SUSTAIN_TICKS,
        sustain_in: int = SCALE_IN_SUSTAIN_TICKS,
        burn_fraction: float = SCALE_IN_BURN_FRACTION,
        min_ranks: int = MIN_SERVING_RANKS,
    ):
        if spares < 0:
            raise ValueError(f"spares must be >= 0, got {spares}")
        if sustain_out < 1 or sustain_in < 1:
            raise ValueError(
                f"sustain windows must be >= 1, got "
                f"out={sustain_out} in={sustain_in}"
            )
        if not 0.0 < burn_fraction < 1.0:
            raise ValueError(
                f"burn_fraction must be in (0, 1) — it IS the "
                f"hysteresis band, got {burn_fraction}"
            )
        self.cooldown = scale_cooldown_ticks() if cooldown is None \
            else cooldown
        if self.cooldown < 1:
            raise ValueError(
                f"cooldown must be >= 1, got {self.cooldown}"
            )
        self.burn_threshold = scale_burn_threshold() \
            if burn_threshold is None else burn_threshold
        if not (math.isfinite(self.burn_threshold)
                and self.burn_threshold > 0):
            raise ValueError(
                f"burn_threshold must be finite and > 0, got "
                f"{self.burn_threshold}"
            )
        self.spares = spares
        self.sustain_out = sustain_out
        self.sustain_in = sustain_in
        self.burn_fraction = burn_fraction
        self.min_ranks = min_ranks
        self.fe = None
        #: ranks currently parked (available to scale out onto)
        self.parked: set = set()
        self.hot_ticks = 0
        self.cold_ticks = 0
        self.last_scale_tick: Optional[int] = None
        #: (tick, direction, rank) audit trail
        self.scale_events: List[tuple] = []
        self.migrations_requested = 0

    # -- wiring ---------------------------------------------------------

    def bind(self, frontend) -> None:
        """Attach to a front-end: arm load-aware placement and park
        the ``spares`` highest ranks as grow headroom (each parking is
        a real ``scale-in`` epoch bump — loud from tick zero)."""
        if self.fe is not None:
            raise RuntimeError("elasticity controller already bound")
        self.fe = frontend
        frontend.placement.armed = True
        floor = max(self.min_ranks, 2)
        for _ in range(self.spares):
            if len(frontend.view.members) <= floor:
                break
            rank = max(frontend.view.members)
            shrink_pod(frontend.view, frontend.detector, rank,
                       reason="spare",
                       token=self._mint(frontend, rank,
                                        f"scale-in of rank {rank}"))
            self.parked.add(rank)

    # -- signal reads ---------------------------------------------------

    def _burn(self) -> float:
        """The hottest short-window burn across classes — the same
        number the SLO report quotes, so an operator can always
        reproduce the controller's view from ``health()``."""
        classes = self.fe.slo.health()["classes"]
        return max(
            (c["burn"]["short"] for c in classes.values()),
            default=0.0,
        )

    def _pressure(self) -> bool:
        gate = self.fe.gate
        return gate.queue_depth() > gate.pool

    # -- the control loop -----------------------------------------------

    def step(self, now: int) -> None:
        """One controller tick: classify hot/cold, age the sustain
        counters, fire at most one actuation."""
        if self.fe is None:
            raise RuntimeError("elasticity controller is not bound")
        burn = self._burn()
        pressure = self._pressure()
        if burn >= self.burn_threshold or pressure:
            self.hot_ticks += 1
            self.cold_ticks = 0
        elif (burn < self.burn_threshold * self.burn_fraction
              and not pressure):
            self.cold_ticks += 1
            self.hot_ticks = 0
        else:
            # inside the hysteresis band: neither signal sustains
            self.hot_ticks = 0
            self.cold_ticks = 0
        if not self._cooled(now):
            return
        if self.hot_ticks >= self.sustain_out and self.parked:
            self._scale_out(now)
        elif self.cold_ticks >= self.sustain_in:
            self._scale_in(now)

    def _cooled(self, now: int) -> bool:
        return (self.last_scale_tick is None
                or now - self.last_scale_tick >= self.cooldown)

    @staticmethod
    def _mint(frontend, rank: int, what: str):
        """The front-end's quorum fencing token for an actuation —
        None when the front-end predates fencing (duck-typed, so the
        controller still binds to bare test doubles)."""
        mint = getattr(frontend, "mint_quorum_token", None)
        return mint(rank=rank, what=what) if mint is not None else None

    def _scale_out(self, now: int) -> None:
        rank = min(self.parked)
        regrow_pod(self.fe.view, self.fe.detector, rank,
                   reason="demand",
                   token=self._mint(self.fe, rank,
                                    f"scale-out of rank {rank}"))
        self.parked.discard(rank)
        self.last_scale_tick = now
        self.hot_ticks = 0
        self.scale_events.append((now, "out", rank))

    def _scale_in_victim(self) -> Optional[int]:
        """The eligible victim, or None: the highest member that holds
        zero residents, has an empty wire lane, is not party to an
        in-flight migration, and whose departure keeps the floor."""
        fe = self.fe
        if len(fe.view.members) <= max(self.min_ranks, 2):
            return None
        mig = getattr(fe, "_migration", None)
        for rank in sorted(fe.view.members, reverse=True):
            if rank in fe.killed:
                continue
            if mig is not None and rank in (mig["src"], mig["dst"]):
                continue
            if any(st.dst == rank for st in fe.active):
                continue
            # resident KV shards (r20): a decode rank whose transport
            # streams all completed still holds the KV its requests
            # generate from — stateful inventory the active-stream
            # census cannot see. Duck-typed: front-ends without an
            # inference engine bound have no inventory to refuse.
            kv = getattr(fe, "kv_shard_residents", None)
            if kv and kv.get(rank):
                continue
            lane = fe.lanes[rank]
            if lane.in_flight or lane.landed:
                continue
            return rank
        return None

    def _scale_in(self, now: int) -> None:
        rank = self._scale_in_victim()
        if rank is None:
            return
        shrink_pod(self.fe.view, self.fe.detector, rank,
                   reason="demand",
                   token=self._mint(self.fe, rank,
                                    f"scale-in of rank {rank}"))
        self.parked.add(rank)
        self.last_scale_tick = now
        self.cold_ticks = 0
        self.scale_events.append((now, "in", rank))

    # -- migration triggers ---------------------------------------------

    def offer_blame(self, verdict: BlameVerdict,
                    tenant: str) -> bool:
        """A tail-latency verdict for a hot tenant: when it convicts a
        specific wire rank, request a live migration off it. Returns
        True when a migration was actually requested."""
        if not isinstance(verdict, BlameVerdict):
            raise TypeError(
                f"offer_blame wants a BlameVerdict, got "
                f"{type(verdict).__name__}: {verdict!r}"
            )
        if self.fe is None:
            raise RuntimeError("elasticity controller is not bound")
        if verdict.kind != "wire" or verdict.rank is None:
            return False
        fe = self.fe
        if getattr(fe, "_migration", None) is not None:
            return False  # one migration at a time
        src = verdict.rank
        if fe._route_new(tenant, record=False) != src:
            return False  # the verdict convicts someone else's rank
        others = sorted(r for r in fe.view.members if r != src)
        if src not in fe.view.members or not others:
            return False
        residents = fe.placement.residents()
        dst = min(others, key=lambda r: (fe._rank_load(r),
                                         residents.get(r, 0), r))
        fe.request_migration(tenant, dst,
                             reason=f"blame:{verdict.resource}")
        self.migrations_requested += 1
        return True

    # -- report ---------------------------------------------------------

    def report(self) -> Dict:
        return {
            "cooldown": self.cooldown,
            "burn_threshold": self.burn_threshold,
            "parked": sorted(self.parked),
            "scale_outs": sum(1 for _, d, _r in self.scale_events
                              if d == "out"),
            "scale_ins": sum(1 for _, d, _r in self.scale_events
                             if d == "in"),
            "events": list(self.scale_events),
            "migrations_requested": self.migrations_requested,
        }
