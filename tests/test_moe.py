"""MoE expert dispatch: the data-dependent all-to-all workload through
the serving front-end.

What is pinned here:

- the seeded router is deterministic per (tenant, batch, seed), the
  hot-expert skew genuinely skews, and empty per-expert splits are the
  ABSENCE of a stream (the degenerate all-to-all block);
- scatter/gather is bit-identical: every fully-accepted batch
  reassembles to exactly its submitted tokens under the inverse
  routing permutation, through real admission, QoS, wire credits, and
  (in the failover test) a kill -> heir replay mid-batch;
- the hot-expert campaign cell holds its gates: zero silent
  corruption, zero lost-accepted, lowest-class-first shedding, the
  hot rank surfacing as NAMED per-route backpressure, and no
  membership transition under pure skew (saturation is not death);
- the explicit ``base_rank`` routing extension keeps the pre-MoE
  behaviour byte-for-byte when unused (``None`` = tenant hash).
"""

import pytest

from smi_tpu.serving import moe
from smi_tpu.serving.frontend import ServingFrontend, tenant_base_rank
from smi_tpu.serving.qos import QOS_CLASSES

pytestmark = pytest.mark.moe


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------


def test_router_is_deterministic_and_total():
    a = moe.route_tokens("t0", 3, 7, 32, 4)
    b = moe.route_tokens("t0", 3, 7, 32, 4)
    assert a == b
    assert all(0 <= e < 4 for e in a)
    assert moe.route_tokens("t0", 4, 7, 32, 4) != a  # batch-dependent


def test_hot_expert_skews_the_matrix():
    """At 8x weight the hot expert draws the majority of a long batch
    — the data-dependent traffic matrix the campaign samples."""
    assignment = moe.route_tokens("t1", 0, 0, 400, 4, hot_expert=2,
                                  hot_factor=8)
    counts = {e: assignment.count(e) for e in range(4)}
    assert counts[2] > sum(v for e, v in counts.items() if e != 2)


def test_empty_splits_are_absent_streams():
    splits = moe.split_by_expert([1, 1, 3], 4)
    assert set(splits) == {1, 3}   # experts 0 and 2: no stream at all
    assert splits[1] == [0, 1] and splits[3] == [2]
    with pytest.raises(ValueError, match="unknown expert"):
        moe.split_by_expert([0, 9], 4)


def test_router_validation_is_loud():
    with pytest.raises(ValueError, match="hot_expert"):
        moe.route_tokens("t", 0, 0, 4, 4, hot_expert=4)
    with pytest.raises(ValueError, match="experts"):
        moe.route_tokens("t", 0, 0, 4, 0)
    with pytest.raises(ValueError, match="expert ids"):
        moe.expert_home(-1, 4)


# ---------------------------------------------------------------------------
# Scatter/gather bit-identity
# ---------------------------------------------------------------------------


def test_dispatch_gather_roundtrip_is_bit_identical():
    fe = ServingFrontend(4, seed=0)
    d = moe.MoeDispatcher(fe, experts=4, seed=0)
    batches = [
        d.dispatch("t0", "interactive", 4),
        d.dispatch("t1", "batch", 8),
        d.dispatch("t0", "best_effort", 12),
    ]
    for _ in range(8):
        fe.step()
    fe.drain()
    for b in batches:
        assert b.accepted
        assert d.gather(b) == b.tokens
    report = fe.report()
    assert report["lost_accepted"] == 0
    assert report["silent_corruptions"] == 0


def test_gather_of_a_shed_batch_is_none_not_garbage():
    from smi_tpu.serving.qos import AdmissionRejected

    fe = ServingFrontend(4, seed=0)
    d = moe.MoeDispatcher(fe, experts=4, seed=0)
    b = d.dispatch("t0", "batch", 8)
    # simulate an aborted batch
    b.shed = AdmissionRejected("t0", "batch", 0, "tenant-rate")
    assert d.gather(b) is None


def test_base_rank_routes_to_the_expert_home():
    """The explicit base_rank extension: streams land at the expert's
    home rank, not the tenant hash — and None keeps the hash routing
    byte-for-byte."""
    fe = ServingFrontend(4, seed=0)
    fe.submit("tz", "batch", ("c0", "c1"), base_rank=3)
    assert fe.active[-1].dst == 3
    fe.submit("tz", "batch", ("c0", "c1"))
    assert fe.active[-1].dst == tenant_base_rank("tz", 4)
    with pytest.raises(ValueError, match="base_rank"):
        fe.submit("tz", "batch", ("c0",), base_rank=9)


def test_failover_keeps_the_expert_stream_and_the_batch():
    """A dead expert host mid-batch: the stream replays to the heir
    on a fresh epoch lane and the batch still reassembles
    bit-identically — the MoE path rides the front-end's failover
    unchanged."""
    fe = ServingFrontend(4, seed=0)
    d = moe.MoeDispatcher(fe, experts=4, seed=0)
    b = d.dispatch("t0", "best_effort", 12)
    assert b.accepted
    victims = {moe.expert_home(e, fe.n) for e in b.streams}
    victim = sorted(victims)[0]
    fe.step()
    fe.kill(victim)
    fe.drain()
    assert d.gather(b) == b.tokens
    report = fe.report()
    assert report["confirmed"] == [victim]
    assert report["lost_accepted"] == 0
    assert report["silent_corruptions"] == 0
    assert report["stale_epoch_leaks"] == 0


# ---------------------------------------------------------------------------
# Campaign cells
# ---------------------------------------------------------------------------


def test_uniform_cell_holds_its_gates():
    rep = moe.run_moe_cell(seed=0)
    assert rep["ok"], rep["verdict"]
    assert rep["cell"] == "moe"
    assert rep["reassembly_corruptions"] == 0
    assert rep["lost_accepted"] == 0


def test_hot_expert_cell_sheds_with_the_named_backpressure():
    """THE hot-expert acceptance cell: one expert at 8x routing
    weight saturates its home rank; the overflow surfaces as named
    ``backpressure:rank<h>`` shedding at the admission edge — zero
    silent corruption, zero lost-accepted, lowest-class-first
    brownout, no false death."""
    rep = moe.run_moe_cell(seed=0, hot_expert=1, batches_per_tick=0.75)
    assert rep["ok"], rep["verdict"]
    assert rep["cell"] == "moe-hot-expert"
    assert rep["batches_shed"] > 0
    assert rep["batch_shed_reasons"] == [
        f"backpressure:rank{rep['hot_rank']}"
    ]
    assert rep["confirmed"] == []
    assert rep["brownout_shed"]["interactive"] == 0
    assert rep["reassembly_corruptions"] == 0
    assert rep["lost_accepted"] == 0


def test_deferred_shed_is_named_never_silent_corruption():
    """A split PARKED at submit time and shed at pump time
    (admission-timeout / sustained brownout) marks its batch shed via
    the gate's on_shed hook — the batch gathers as None and the cell
    reports the loud named shed, never a bogus 'silent corruption'
    (the review repro: at 2x batch rate on a hot expert, parked
    splits time out while their siblings deliver)."""
    rep = moe.run_moe_cell(seed=18, hot_expert=2, batches_per_tick=2.0)
    assert rep["ok"], rep["verdict"]
    assert rep["reassembly_corruptions"] == 0
    assert "admission-timeout" in rep["batch_shed_reasons"]
    assert rep["orphaned_streams"] > 0   # siblings named, not hidden


def test_campaign_is_seed_deterministic_and_green():
    a = moe.moe_campaign(seed=3)
    b = moe.moe_campaign(seed=3)
    assert a == b
    assert a["ok"], a["failures"]
    assert set(a["outcomes"]) == {"moe", "moe-hot-expert"}
    assert a["silent_corruptions"] == 0
    assert a["lost_accepted"] == 0
    assert a["stale_epoch_leaks"] == 0


@pytest.mark.slow
def test_campaign_seed_sweep():
    for seed in range(16):
        rep = moe.moe_campaign(seed=seed)
        assert rep["ok"], (seed, rep["failures"])


def test_cell_duration_floor_is_loud():
    with pytest.raises(ValueError, match="minimum"):
        moe.run_moe_cell(duration=10)


def test_gate_accounting_covers_every_class():
    rep = moe.run_moe_cell(seed=1, hot_expert=0, batches_per_tick=0.75)
    assert set(rep["brownout_shed"]) == set(QOS_CLASSES)
    assert set(rep["backpressure_shed"]) == set(QOS_CLASSES)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_chaos_moe(tmp_path, capsys):
    import json

    import smi_tpu.__main__ as cli

    out = tmp_path / "moe.json"
    assert cli.main(["chaos", "--moe", "--trials", "1",
                     "-o", str(out)]) == 0
    text = capsys.readouterr().out
    assert "moe campaign ok" in text
    payload = json.loads(out.read_text())
    assert payload["ok"] is True and payload["cells"] == 2
    # usage errors, named
    assert cli.main(["chaos", "--moe", "--protocols", "all_gather"]) == 2
    assert "--protocols" in capsys.readouterr().err
    assert cli.main(["chaos", "--moe", "--max-faults", "2"]) == 2
    assert "--max-faults" in capsys.readouterr().err
    assert cli.main(["chaos", "--moe", "--elastic"]) == 2
    assert "distinct campaigns" in capsys.readouterr().err
