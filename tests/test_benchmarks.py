"""Microbenchmark harness smoke tests (fake mesh, tiny sizes).

Like the reference's emulator runs, these validate the measurement code
path and the payload-verifying math, not performance.
"""

import numpy as np
import pytest

from smi_tpu.benchmarks.micro import BENCHMARKS, run_benchmark
from smi_tpu.benchmarks.stats import Measurement


def test_all_benchmarks_run(comm8, tmp_path):
    params = {
        "bandwidth": {"size_kb": 8, "runs": 2},
        "latency": {"pingpongs": 4, "runs": 2},
        "injection": {"messages": 4, "runs": 2},
        "broadcast": {"elements": 256, "runs": 2},
        "reduce": {"elements": 256, "runs": 2, "root": 3},
        "scatter": {"elements": 64, "runs": 2},
        "gather": {"elements": 64, "runs": 2},
        "multi_collectives": {"elements": 128, "runs": 2},
        "pipeline": {"elements": 224, "rounds": 2, "runs": 2},
        "bandwidth_eager": {"size_kb": 8, "runs": 2},
        "pipeline_double_rail": {"elements": 224, "rounds": 2, "runs": 2},
        "overlap": {"size_kb": 8, "sweep_kb": (8,), "chunks": 2,
                    "repeats": 2, "runs": 2},
        "app_stencil": {"size": 64, "iterations": 4, "runs": 2},
        "app_gesummv": {"n": 64, "runs": 2},
        "app_kmeans": {"points": 256, "iterations": 2, "runs": 2},
        "app_ring_attention": {
            "seq_per_rank": 16, "heads": 2, "head_dim": 16, "runs": 2,
        },
        "app_ring_attention_train": {
            "seq_per_rank": 16, "heads": 2, "head_dim": 16, "runs": 2,
            "reps": 2,
        },
    }
    assert set(params) == set(BENCHMARKS)
    for name, p in params.items():
        m = run_benchmark(name, comm=comm8, out_dir=str(tmp_path), **p)
        assert len(m.samples) == 2
        assert m.mean > 0
        assert (tmp_path / f"{m.name}.dat").exists()
        assert (tmp_path / f"{m.name}.json").exists()


def test_pipeline_eager_mode(comm8):
    m = run_benchmark("pipeline", comm=comm8, elements=112, rounds=2,
                      runs=2, rendezvous=False)
    assert m.name == "pipeline-eager"


def test_unknown_benchmark_rejected(comm8):
    with pytest.raises(KeyError, match="unknown benchmark"):
        run_benchmark("warp-speed", comm=comm8)


def test_backendless_benchmark_rejects_non_default_tier(comm8):
    """A benchmark without backend tiers must refuse a requested
    non-default tier rather than silently recording XLA; the default
    'xla' is dropped harmlessly."""
    with pytest.raises(ValueError, match="no backend tiers"):
        run_benchmark("app_gesummv", comm=comm8, n=64, runs=2,
                      backend="ring")
    m = run_benchmark("app_gesummv", comm=comm8, n=64, runs=2,
                      backend="xla")
    assert m.mean > 0


def test_bandwidth_rendezvous_vs_eager(comm8):
    r = run_benchmark("bandwidth", comm=comm8, size_kb=8, runs=2)
    e = run_benchmark("bandwidth_eager", comm=comm8, size_kb=8, runs=2)
    assert r.name == "bandwidth" and r.config["rendezvous"] is True
    assert e.name == "bandwidth-eager" and e.config["rendezvous"] is False


def test_tracing_helpers(comm8, tmp_path):
    import jax.numpy as jnp

    from smi_tpu.utils.tracing import annotate, timed, trace

    with trace(str(tmp_path / "tb")):
        with annotate("smoke-region"):
            out, secs = timed(lambda: jnp.arange(16.0) * 2)
    assert secs >= 0
    assert float(out[2]) == 4.0
    # a trace directory with at least one event file was written
    produced = list((tmp_path / "tb").rglob("*"))
    assert produced, "profiler trace wrote nothing"


def test_measurement_stats():
    m = Measurement("x", "s", [1.0, 2.0, 3.0])
    assert m.mean == 2.0
    assert np.isclose(m.stddev, 1.0)
    assert m.ci99 > 0
