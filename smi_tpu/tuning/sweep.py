"""Measured-sweep layer: refine the analytic winner on real hardware.

The ATLAS move (PAPERS.md): enumerate the candidate configurations the
cost model ranked, *time them* with the same harness the microbenchmark
suite trusts (``benchmarks/micro.py``: jitted shard_map programs, a
scalar readback forcing completion per run, ``timed_samples``' warmup +
repeat discipline), and persist the winners as plan-cache entries. The
sweep driver is what ``smi-tpu tune`` runs; on a CPU fake mesh it is
functional (the cache mechanics and CLI are fully exercised) but the
numbers describe the emulator, so entries are keyed by the *measured*
device kind — a CPU sweep can never shadow a v5e entry.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from smi_tpu.tuning import cost_model as cm
from smi_tpu.tuning.cache import CacheEntry, PlanCache
from smi_tpu.tuning.engine import PlanEngine, _collective_topology
from smi_tpu.tuning.plan import PlanKey, normalize_device_kind, payload_bucket


def _measure(make_fn, x, runs: int) -> float:
    """Mean seconds of one candidate via the micro.py harness."""
    from smi_tpu.benchmarks.micro import force_readback
    from smi_tpu.benchmarks.stats import timed_samples

    samples = timed_samples(force_readback(lambda: make_fn(x)), runs)
    return sum(samples) / len(samples)


def sweep_allreduce(
    comm,
    sizes_kb: Sequence[int] = (64, 256, 1024, 4096),
    chunk_candidates: Sequence[int] = (1, 2, 4),
    runs: int = 5,
    device_kind: Optional[str] = None,
    verbose: bool = False,
) -> PlanCache:
    """Time ring vs rs+ag (x chunk counts) per payload size; return the
    winners as a mergeable :class:`PlanCache`.

    Also distills the measured ring/rs+ag crossover into the
    ``rs_ag_min_bytes`` threshold entry — the tuned replacement for the
    frozen constant, consumed by ``collectives.rs_ag_min_bytes``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from smi_tpu.parallel import collectives as coll

    axis = comm.axis_names[0]
    n = comm.size
    dk = normalize_device_kind(
        device_kind or jax.devices()[0].device_kind
    )
    topo = cm.TopologySpec(n=n)
    cache = PlanCache()
    rs_ag_wins = []   # payload bytes where the decomposition measured best

    for kb in sizes_kb:
        elems = max(n, (kb * 1024 // 4) // n * n)  # rs+ag-eligible
        payload_bytes = elems * 4

        def make(rs_ag: bool, chunks: int):
            def shard_fn(x):
                y = coll.allreduce(x, comm, rs_ag=rs_ag, chunks=chunks)
                return jnp.sum(y)[None]

            fn = jax.jit(jax.shard_map(
                shard_fn, mesh=comm.mesh, in_specs=P(),
                out_specs=P(axis), check_vma=False,
            ))
            return lambda x: np.asarray(fn(x))

        x = jnp.ones(elems, jnp.float32)
        results = []
        for algo, rs_ag in (("ring", False), ("rs_ag", True)):
            for chunks in chunk_candidates:
                secs = _measure(make(rs_ag, chunks), x, runs)
                results.append((secs, algo, chunks))
                if verbose:
                    print(
                        f"  {kb:>7} KiB {algo:>6} chunks={chunks}: "
                        f"{secs * 1e6:.1f} us"
                    )
        secs, algo, chunks = min(results)
        if algo == "rs_ag":
            rs_ag_wins.append(payload_bytes)
        key = PlanKey("all_reduce", payload_bucket(payload_bytes),
                      "float32", dk, _collective_topology(topo))
        cache.put(key, CacheEntry(
            {"algorithm": algo, "chunks": chunks},
            cost_us=secs * 1e6,
            provenance=f"sweep:allreduce:{kb}KiB:n{n}",
        ))

    if rs_ag_wins and n > 2:
        # the SMALLEST payload the decomposition won at, regardless of
        # --sizes-kb iteration order; skipped on n <= 2 rings, where
        # rs+ag is structurally unable to win (same volume, twice the
        # steps) and any "win" is timing noise that would lower the
        # device-wide tier for every later multi-rank trace
        cache.put(
            PlanKey("all_reduce", "threshold", "", dk, "any"),
            CacheEntry(
                {"rs_ag_min_bytes": int(min(rs_ag_wins))},
                cost_us=None,
                provenance=f"sweep:allreduce-crossover:n{n}",
            ),
        )
    return cache


def sweep_allreduce_hierarchical(
    comm,
    sizes_kb: Sequence[int] = (64, 256, 1024, 4096),
    runs: int = 5,
    device_kind: Optional[str] = None,
    verbose: bool = False,
) -> PlanCache:
    """Time flat vs two-tier allreduce per payload on a hybrid
    multi-slice communicator; persist the winners per (slices,
    payload bucket) and distill the measured crossover into the
    ``hier_threshold`` entry — the ATLAS rule applied to the DCN
    tier: the flat/hierarchical switch point is a swept artifact in
    the plan cache, never a frozen constant. Entries are keyed by the
    MEASURED device kind and the ``n{n}:dcn{slices}`` topology, so a
    CPU sweep can neither shadow a v5e entry nor leak across pod
    shapes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from smi_tpu.parallel import collectives as coll
    from smi_tpu.ops.types import SmiOp

    topo = cm.topology_from_comm(comm)
    if not topo.hierarchical_eligible:
        raise ValueError(
            f"the hierarchical sweep needs a multi-slice hybrid "
            f"communicator (make_hybrid_communicator); got axes "
            f"{comm.axis_names} with sizes {comm.axis_sizes}"
        )
    n, inner, outer = topo.n, topo.inner, topo.outer
    dk = normalize_device_kind(
        device_kind or jax.devices()[0].device_kind
    )
    spec = P(tuple(comm.axis_names))
    cache = PlanCache()
    hier_wins = []   # payload bytes where the two-tier form measured best

    for kb in sizes_kb:
        elems = max(inner, (kb * 1024 // 4) // inner * inner)
        payload_bytes = elems * 4

        def make(hierarchical: bool):
            def shard_fn(x):
                y = coll.allreduce(x, comm, hierarchical=hierarchical)
                return jnp.sum(y)[None]

            fn = jax.jit(jax.shard_map(
                shard_fn, mesh=comm.mesh, in_specs=P(),
                out_specs=spec, check_vma=False,
            ))
            return lambda x: np.asarray(fn(x))

        x = jnp.ones(elems, jnp.float32)
        results = []
        for hierarchical in (False, True):
            secs = _measure(make(hierarchical), x, runs)
            results.append((secs, hierarchical))
            if verbose:
                name = "hierarchical" if hierarchical else "flat"
                print(f"  {kb:>7} KiB {name:>12}: {secs * 1e6:.1f} us")
        secs, hierarchical = min(results)
        if hierarchical:
            hier_wins.append(payload_bytes)
            algo = "hierarchical"
        else:
            # name the flat form the gate would actually run at this
            # payload, so the entry stays one of the three candidates
            algo = ("rs_ag" if coll._use_rs_ag(x, comm, SmiOp.ADD, None)
                    else "ring")
        key = PlanKey("all_reduce", payload_bucket(payload_bytes),
                      "float32", dk, _collective_topology(topo))
        cache.put(key, CacheEntry(
            {"algorithm": algo},
            cost_us=secs * 1e6,
            provenance=f"sweep:allreduce-hier:{kb}KiB:"
                       f"{outer}x{inner}",
        ))

    if hier_wins:
        # the SMALLEST payload the two-tier form won at, regardless of
        # --sizes-kb iteration order — the measured crossover the
        # trace-time gate consults between per-bucket entries
        cache.put(
            PlanKey("all_reduce", "hier_threshold", "", dk,
                    f"dcn{outer}"),
            CacheEntry(
                {"hier_min_bytes": int(min(hier_wins))},
                cost_us=None,
                provenance=f"sweep:hier-crossover:{outer}x{inner}",
            ),
        )
    return cache


def sweep_allreduce_precision(
    comm,
    sizes_kb: Sequence[int] = (64, 256, 1024, 4096),
    runs: int = 5,
    device_kind: Optional[str] = None,
    verbose: bool = False,
) -> PlanCache:
    """Time the allreduce wire precisions (f32/bf16/int8/topk) per
    payload size; persist the winners per (slices, payload bucket) and
    distill the measured dense/lossy crossover into the
    ``precision_threshold`` entry — the ATLAS rule applied to the wire
    width: a lossy precision reaches the auto path only through this
    measured artifact (the model rung's margin equals the int8 byte
    ratio, so it can never flip numerics on its own). Runs on a flat
    or a hybrid multi-slice communicator; entries are keyed by the
    MEASURED device kind and topology, so a CPU sweep can neither
    shadow a v5e entry nor leak across pod shapes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from smi_tpu.parallel import collectives as coll

    topo = cm.topology_from_comm(comm)
    n = topo.n
    inner = topo.inner or n
    outer = (topo.outer or 0) if topo.hierarchical_eligible else 0
    dk = normalize_device_kind(
        device_kind or jax.devices()[0].device_kind
    )
    spec = (P(tuple(comm.axis_names)) if len(comm.axis_names) > 1
            else P(comm.axis_names[0]))
    cache = PlanCache()
    lossy_wins = []   # (payload bytes, precision) the lossy form won at

    for kb in sizes_kb:
        # divisible by the inner axis so every precision can ride the
        # same decomposition the auto algorithm gate would pick
        elems = max(inner, (kb * 1024 // 4) // inner * inner)
        payload_bytes = elems * 4

        def make(precision: str):
            def shard_fn(x):
                y = coll.allreduce(x, comm, precision=precision)
                return jnp.sum(y)[None]

            fn = jax.jit(jax.shard_map(
                shard_fn, mesh=comm.mesh, in_specs=P(),
                out_specs=spec, check_vma=False,
            ))
            return lambda x: np.asarray(fn(x))

        x = jnp.ones(elems, jnp.float32)
        results = []
        for precision in cm.ALLREDUCE_PRECISIONS:
            secs = _measure(make(precision), x, runs)
            results.append((secs, precision))
            if verbose:
                print(
                    f"  {kb:>7} KiB {precision:>5}: "
                    f"{secs * 1e6:.1f} us"
                )
        secs, precision = min(results)
        if precision != "f32":
            lossy_wins.append((payload_bytes, precision))
        key = PlanKey("all_reduce", payload_bucket(payload_bytes),
                      "float32", dk, _collective_topology(topo))
        cache.put(key, CacheEntry(
            {"precision": precision},
            cost_us=secs * 1e6,
            provenance=f"sweep:allreduce-precision:{kb}KiB:"
                       + (f"{outer}x{inner}" if outer else f"n{n}"),
        ))

    if lossy_wins:
        # the SMALLEST payload any lossy width won at (and that
        # winner), regardless of --sizes-kb iteration order — the
        # measured crossover the trace-time gate consults between
        # per-bucket entries
        min_bytes, precision = min(lossy_wins)
        cache.put(
            PlanKey("all_reduce", "precision_threshold", "", dk,
                    f"dcn{outer}" if outer else "flat"),
            CacheEntry(
                {"precision_min_bytes": int(min_bytes),
                 "precision": precision},
                cost_us=None,
                provenance=f"sweep:precision-crossover:"
                           + (f"{outer}x{inner}" if outer else f"n{n}"),
            ),
        )
    return cache


def sweep_alltoall(
    comm,
    sizes_kb: Sequence[int] = (64, 256, 1024, 4096),
    runs: int = 5,
    device_kind: Optional[str] = None,
    verbose: bool = False,
) -> PlanCache:
    """Time the all-to-all candidates per payload size and persist the
    winners as per-bucket ``algorithm`` entries — the ATLAS refinement
    of the alpha-beta ranking. Candidates are structural: pairwise
    always, Bruck only on power-of-two rank counts (skipped WITH a
    printed line otherwise — never silently), hierarchical only on a
    hybrid multi-slice communicator. Entries are keyed by the MEASURED
    device kind and topology, so a CPU sweep can neither shadow a v5e
    entry nor leak across pod shapes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from smi_tpu.parallel import collectives as coll

    topo = cm.topology_from_comm(comm)
    n = topo.n
    dk = normalize_device_kind(
        device_kind or jax.devices()[0].device_kind
    )
    spec = (P(tuple(comm.axis_names)) if len(comm.axis_names) > 1
            else P(comm.axis_names[0]))
    algos = ["pairwise"]
    if n >= 2 and not (n & (n - 1)):
        algos.append("bruck")
    elif verbose:
        print(f"  bruck: skipped (n={n} is not a power of two)")
    if topo.hierarchical_eligible:
        algos.append("hierarchical")
    cache = PlanCache()

    for kb in sizes_kb:
        elems = max(n, (kb * 1024 // 4) // n * n)  # divisible by n
        payload_bytes = elems * 4

        def make(algorithm):
            def shard_fn(x):
                y = coll.all_to_all(x, comm, algorithm=algorithm)
                return jnp.sum(y)[None]

            fn = jax.jit(jax.shard_map(
                shard_fn, mesh=comm.mesh, in_specs=P(),
                out_specs=spec, check_vma=False,
            ))
            return lambda x: np.asarray(fn(x))

        x = jnp.ones(elems, jnp.float32)
        results = []
        for algorithm in algos:
            secs = _measure(make(algorithm), x, runs)
            results.append((secs, algorithm))
            if verbose:
                print(
                    f"  {kb:>7} KiB {algorithm:>12}: "
                    f"{secs * 1e6:.1f} us"
                )
        secs, algorithm = min(results)
        key = PlanKey("all_to_all", payload_bucket(payload_bytes),
                      "float32", dk, _collective_topology(topo))
        cache.put(key, CacheEntry(
            {"algorithm": algorithm},
            cost_us=secs * 1e6,
            provenance=f"sweep:alltoall:{kb}KiB:n{n}",
        ))
    return cache


def sweep_flash(
    s: int = 8192,
    d: int = 128,
    h: int = 8,
    dtype_name: str = "bfloat16",
    windowed: bool = False,
    runs: int = 3,
    device_kind: Optional[str] = None,
    targets: Sequence[Tuple[int, int]] = (
        (512, 512), (512, 1024), (1024, 512), (1024, 1024),
    ),
    verbose: bool = False,
) -> PlanCache:
    """Time the flash forward at each feasible (block_q, block_k) and
    cache the winner. Hardware-tier only (the compiled Mosaic path);
    on a non-TPU backend this returns an empty cache rather than
    recording interpreter timings as kernel truth."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from smi_tpu.kernels import flash as F
    from smi_tpu.tuning import engine as eng

    if jax.devices()[0].platform != "tpu":
        return PlanCache()
    dk = normalize_device_kind(
        device_kind or jax.devices()[0].device_kind
    )
    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
    window = 4096 if windowed else None
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(jax.random.fold_in(key, i), (h, s, d), dtype)
        for i in range(3)
    )
    feasible = [
        (c.knobs["block_q"], c.knobs["block_k"])
        for c in cm.flash_block_candidates(s, d, dtype_name, windowed,
                                           targets=targets)
    ]
    plan_key = PlanKey("flash_fwd", "window" if windowed else "causal",
                       dtype_name, dk, "chip")
    results = []
    saved = eng.get_engine()
    try:
        for bq, bk in feasible:
            # candidate blocks are forced by a throwaway engine whose
            # cache carries exactly this candidate — the same consult
            # path production traces use, so the sweep times what
            # deployment would run
            trial = PlanCache()
            trial.put(plan_key,
                      CacheEntry({"block_q": bq, "block_k": bk}))
            eng.set_engine(PlanEngine(cache=trial, device_kind=dk))
            fn = jax.jit(lambda q, k, v: F.flash_attend_fused(
                q, k, v, 0, 0, causal=True, scale=1.0, window=window,
            )[0])
            try:
                secs = _measure(
                    lambda args: np.asarray(jnp.sum(fn(*args))),
                    (q, k, v), runs,
                )
            except Exception as e:
                if verbose:
                    print(f"  bq{bq}/bk{bk}: rejected ({e})")
                continue
            results.append((secs, bq, bk))
            if verbose:
                print(f"  bq{bq}/bk{bk}: {secs * 1e6:.1f} us")
    finally:
        eng.set_engine(saved)
    cache = PlanCache()
    if results:
        secs, bq, bk = min(results)
        cache.put(plan_key, CacheEntry(
            {"block_q": bq, "block_k": bk},
            cost_us=secs * 1e6,
            provenance=f"sweep:flash_fwd:S{s}:{dtype_name}"
                       + (":window" if windowed else ""),
        ))
    return cache


def sweep_stencil(
    h: int = 8192,
    w: int = 8192,
    dtype_name: str = "float32",
    depths: Sequence[int] = cm.STENCIL_PIPELINE_DEPTHS,
    stripes: Sequence[int] = cm.STENCIL_PIPELINE_STRIPES,
    runs: int = 3,
    device_kind: Optional[str] = None,
    proxy_shape: Tuple[int, int] = (256, 384),
    verbose: bool = False,
) -> PlanCache:
    """Sweep the explicit-DMA stencil pipeline's depth x stripe x
    compute-dtype grid (plus the synchronous control path) at one
    block shape and cache the winner under
    ``PlanKey("stencil_pipeline", str(h), ...)``.

    On TPU every candidate is timed for real (one fused pass through
    ``make_pipeline_stencil_fn``, normalized to us/sweep). On any
    other backend the sweep is a *proxy* tier: each candidate must
    first pass the interpret-mode correctness gate at ``proxy_shape``
    (bit-equal to the reference Jacobi step for f32, bounded error for
    bf16 — a candidate that cannot reproduce the reference is dropped,
    loudly), and is then priced as the cost model's prediction scaled
    by the perf decomposer's measured idle fraction for its buffering
    depth — the replay evidence, not just the analytic curve, ranks
    the proxy entries. Either way the entries are keyed by the
    measured device kind, so a CPU proxy sweep can never shadow a v5e
    entry (module docstring discipline).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import smi_tpu as smi
    from smi_tpu.analysis import perf as aperf
    from smi_tpu.kernels import stencil_pipeline as kpipe
    from smi_tpu.models import stencil as mstencil

    dk = normalize_device_kind(
        device_kind or jax.devices()[0].device_kind
    )
    cset = cm.stencil_pipeline_candidates(h, w, dtype_name,
                                          depths, stripes)
    cache = PlanCache()
    if not cset:
        return cache
    on_tpu = jax.devices()[0].platform == "tpu"
    comm = smi.make_communicator(
        shape=(1, 1), axis_names=("sx", "sy"), devices=jax.devices()[:1]
    )
    idle_by_buffering = {}

    def idle_factor(buffering: int) -> float:
        if buffering not in idle_by_buffering:
            rep = aperf.decompose_stencil_stream(buffering=buffering)
            idle_by_buffering[buffering] = max(
                r["idle_fraction"] for r in rep.per_rank
            )
        return idle_by_buffering[buffering]

    results = []
    for cand in cset:
        depth = cand.knobs["depth"]
        stripe = cand.knobs["stripe"]
        cdt = cand.knobs["compute_dtype"]
        buffering = cand.knobs["buffering"]
        if on_tpu:
            fn = kpipe.make_pipeline_stencil_fn(
                comm, depth, h, w, depth=depth, stripe=stripe,
                compute_dtype=cdt, buffering=buffering,
            )
            x = jnp.asarray(mstencil.initial_grid(h, w))
            try:
                secs = _measure(
                    lambda g: np.asarray(fn(g)), x, runs,
                )
            except Exception as e:
                if verbose:
                    print(f"  {cand.name}: rejected ({e})")
                continue
            cost_us = secs * 1e6 / depth
            provenance = f"sweep:stencil:{h}x{w}:{dtype_name}"
        else:
            ph, pw = proxy_shape
            gate_stripe = stripe
            if ph % stripe or stripe < depth:
                gate_stripe = None    # auto-pick at the proxy shape
            if not kpipe.pipeline_supported(
                ph, pw, jnp.float32, depth, stripe=gate_stripe,
                compute_dtype=cdt, buffering=buffering,
            ):
                if verbose:
                    print(f"  {cand.name}: no proxy gate at "
                          f"{ph}x{pw}, skipped")
                continue
            g = mstencil.initial_grid(ph, pw)
            g[:, -1] = 2.0
            g[ph // 2, :] = 0.5
            fn = kpipe.make_pipeline_stencil_fn(
                comm, depth, ph, pw, depth=depth, stripe=gate_stripe,
                compute_dtype=cdt, buffering=buffering, interpret=True,
            )
            out = np.asarray(fn(jnp.asarray(g)))
            ref = mstencil.reference_stencil(g, depth)
            if cdt == "float32":
                ok = np.array_equal(out, ref)
            else:
                ok = np.allclose(out, ref, atol=0.05)
            if not ok:
                if verbose:
                    print(f"  {cand.name}: FAILED the proxy "
                          f"correctness gate, dropped")
                continue
            cost_us = cand.modeled_us * (1.0 + idle_factor(buffering))
            provenance = (f"sweep:stencil:proxy{ph}x{pw}:"
                          f"replay-b{buffering}")
        results.append((cost_us, cand, provenance))
        if verbose:
            print(f"  {cand.name}: {cost_us:.1f} us/sweep")

    if results:
        cost_us, cand, provenance = min(
            results, key=lambda r: (r[0], r[1].name)
        )
        cache.put(
            PlanKey("stencil_pipeline", str(h), dtype_name, dk, "chip"),
            CacheEntry(dict(cand.knobs), cost_us=cost_us,
                       provenance=provenance),
        )
    return cache
