"""Multi-host bootstrap derivation (control-plane parity: the reference's
MPI hostfile launch, ``codegen/common.py:15-19``)."""

import pytest

from smi_tpu.parallel.bootstrap import (
    DistributedOptions,
    distributed_options,
    init_distributed,
    parse_hostfile,
)

HOSTFILE = """\
node-a  # node-a:0, rank0
node-a  # node-a:1, rank1
node-b  # node-b:0, rank2
node-c  # node-c:0, rank3
"""


def test_parse_hostfile_orders_and_strips_comments():
    assert parse_hostfile(HOSTFILE) == ["node-a", "node-a", "node-b", "node-c"]


def test_distributed_options_one_process_per_node(tmp_path):
    path = tmp_path / "hostfile"
    path.write_text(HOSTFILE)
    opts = distributed_options(path, process_id=2)
    assert opts.coordinator_address == "node-a:8476"
    assert opts.num_processes == 3  # node-a packs two ranks
    assert opts.process_id == 2


def test_distributed_options_from_text_and_env(monkeypatch):
    monkeypatch.setenv("SMI_PROCESS_ID", "1")
    opts = distributed_options(HOSTFILE)
    assert opts.process_id == 1


def test_distributed_options_empty_rejected():
    with pytest.raises(ValueError, match="no nodes"):
        distributed_options("# only comments\n")


def test_process_id_range_checked():
    with pytest.raises(ValueError, match="out of range"):
        DistributedOptions("x:1", 2, 5)


def test_init_distributed_single_process_noop():
    # must not call jax.distributed.initialize (which would block)
    init_distributed(DistributedOptions("solo:8476", 1, 0))
