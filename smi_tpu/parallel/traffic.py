"""Collective-traffic analysis of compiled multi-chip executables.

Reference parity: the reference's offline report workflow — ``aoc``
emits per-build area/Fmax reports that are read *before* committing
hardware time (``/root/reference/CMakeLists.txt:113-118``, the
``-rtl -report`` stage). The one multi-chip perf signal a single-chip
host can produce is the compiled artifact itself: the optimized HLO of
an AOT-compiled program names every XLA collective with its shape and
replica groups, from which per-tier ICI/DCN traffic is exact — no pod
required.

:func:`collective_traffic` parses ``compiled.as_text()`` into a list of
collective records; :func:`tier_crossing_bytes` folds them into
per-device bytes that cross a given device partition (e.g. the slice
boundary of a hybrid mesh), which is how ``docs/perf_notes.md`` proves
the hierarchical allreduce moves ``1/inner`` of the flat volume across
the slow tier.

Scope caveat: records are per HLO *occurrence*, not per execution — a
collective inside a ``while``/``fori_loop`` body prints once but runs
trip-count times (e.g. ``app_kmeans_512k``'s in-loop Reduce+Bcast), so
volume comparisons must use loop-free programs (the perf_notes tables
do) or scale by the known trip count themselves. The parser marks such
records ``in_loop: True`` (:func:`_scan_computations`), and
:func:`~smi_tpu.parallel.aot.executable_report` withholds the
``ici_predicted_us`` column for programs containing one.

Ring-tier programs move their data inside Mosaic kernels (remote DMAs
are invisible to HLO), so their traffic is *predicted* from the kernel
schedule instead: :func:`ring_traffic` implements the per-hop formulas
of ``kernels/ring.py``'s four protocols.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: HLO dtype -> bytes per element (the dtypes the framework emits)
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8,
}

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

#: one HLO instruction line: ``%name = f32[8,128]{...} all-reduce(...)``
#: the ``type`` group spans the whole result type — possibly a tuple
#: for async ``-start`` forms, whose LAST element is the result shape
#: (the leading elements alias operands)
_INSTR_RE = re.compile(
    r"%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>[^=]+?)\s(?P<op>" + "|".join(_COLLECTIVES)
    + r")(?:-start|-done)?\("
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[\d,{}]*\})\}")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[\d,{}]*\})\}")

#: megascale DCN egress: on a GENUINE multi-slice topology XLA compiles
#: one ``num_partitions=n_per_slice`` module per slice and lowers the
#: cross-slice stage of a collective to host-transfer ``send``/``recv``
#: pairs handled by the megascale runtime (frontend attribute
#: ``_xla_host_transfer_handler_name="xla_megascale_runtime"``) — the
#: slice-crossing payload never appears in any replica group, so the
#: parser must book the send's tuple payload instead
_SEND_RE = re.compile(
    r"%?(?P<name>[\w.\-]+)\s*=\s*(?P<type>[^=]+?)\ssend\("
)


def _parse_groups(text: str) -> List[List[int]]:
    """``{{0,1},{2,3}}`` (inner part) -> [[0,1],[2,3]]."""
    return [
        [int(x) for x in grp.split(",") if x]
        for grp in re.findall(r"\{([\d,]*)\}", text)
    ]


def _elems(shape: str) -> int:
    """``"2,1,128"`` -> 256 (empty shape = scalar = 1)."""
    n = 1
    for dim in shape.split(","):
        if dim:
            n *= int(dim)
    return n


#: computation header: ``%name (params) -> type {`` or ``ENTRY %name ...{``.
#: Params may nest parens (tuple-typed while carries), so the regex
#: stops at the opening paren — headers are the only lines whose name
#: is followed by ``(`` with no ``=`` (instructions are ``%name = ...``),
#: and the caller additionally requires the line to end with ``{``.
_COMP_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(")
#: computation references on an instruction line
_CALLED_RE = re.compile(r"(?:body|condition|to_apply|calls)=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")


def _scan_computations(
    lines: Sequence[str],
) -> Tuple[Set[str], List[Optional[str]]]:
    """One pass over pre-split HLO lines: ``(loop_comps, comp_of_line)``.

    ``loop_comps`` — computation names reachable from any ``while``
    instruction's body/condition (regions whose instructions execute
    trip-count times per run, not once per HLO occurrence).
    ``comp_of_line[i]`` — the computation containing line ``i``, so the
    instruction parser shares this scan instead of re-matching headers
    over the multi-MB text."""
    refs: Dict[str, Set[str]] = {}
    roots: List[str] = []
    cur: Optional[str] = None
    comp_of_line: List[Optional[str]] = []
    for line in lines:
        mc = _COMP_RE.match(line)
        if mc and line.rstrip().endswith("{"):
            cur = mc.group(1)
            refs.setdefault(cur, set())
        elif line.strip() == "}":
            # a computation's closing brace ends its scope. Without
            # this reset, a header the regex cannot match (some print
            # options drop the parameter list) leaves ``cur`` pointing
            # at the PREVIOUS computation — e.g. a while body — and
            # every later instruction inherits a false ``in_loop``.
            # (Inline braces — constants, replica groups — never put a
            # lone ``}`` on its own line.)
            cur = None
        else:
            called = _CALLED_RE.findall(line)
            mb = _BRANCHES_RE.search(line)
            if mb:
                called += [
                    c.strip().lstrip("%")
                    for c in mb.group(1).split(",") if c.strip()
                ]
            if cur is not None:
                refs[cur].update(called)
            # while-roots are collected even in unrecognized scope —
            # a while whose enclosing header the regex missed still
            # makes its body's collectives per-iteration records
            if re.search(r"\swhile\(", line):
                roots.extend(called)
        comp_of_line.append(cur)
    reachable: Set[str] = set()
    stack = roots
    while stack:
        c = stack.pop()
        if c in reachable:
            continue
        reachable.add(c)
        stack.extend(refs.get(c, ()))
    return reachable, comp_of_line


def collective_traffic(compiled, hlo_text: Optional[str] = None) -> List[dict]:
    """Every XLA collective of a compiled executable, with exact bytes.

    Returns one record per collective instruction: ``op``, ``dtype``,
    element count and payload ``bytes`` (per participating device's
    operand), and the ``groups`` (replica groups, or source->target
    pairs for collective-permute). ``-start``/``-done`` async halves are
    deduplicated by instruction name. ``hlo_text`` lets a caller that
    already rendered ``compiled.as_text()`` (a multi-MB string for
    large programs) avoid a second render.

    A record whose instruction lives inside a ``while`` body (directly
    or through nested calls) carries ``in_loop: True`` — its bytes are
    per HLO occurrence, an under-count by the loop trip count, so
    volume columns must either exclude it or scale it themselves.
    """
    records = []
    seen: Set[Tuple[str, str]] = set()
    if hlo_text is None:
        hlo_text = compiled.as_text()
    lines = hlo_text.splitlines()
    loop_comps, comp_of_line = _scan_computations(lines)
    for lineno, line in enumerate(lines):
        cur_comp = comp_of_line[lineno]
        m = _INSTR_RE.search(line)
        if not m:
            ms = _SEND_RE.search(line)
            if (
                ms
                and "is_host_transfer=true" in line
                and "_xla_megascale" in line
            ):
                # DCN egress of a multi-slice collective: payload is
                # the largest array of the (data, u32[], token[]) tuple
                shapes = [
                    (dt, _elems(sh), _elems(sh) * _DTYPE_BYTES[dt])
                    for dt, sh in _SHAPE_RE.findall(ms.group("type"))
                    if dt in _DTYPE_BYTES
                ]
                if shapes:
                    dt, el, by = max(shapes, key=lambda t: t[2])
                    rec = {
                        "op": "megascale-send", "name": ms.group("name"),
                        "dtype": dt, "elements": el, "bytes": by,
                        "megascale": True, "computation": cur_comp,
                    }
                    if cur_comp in loop_comps:
                        rec["in_loop"] = True
                    records.append(rec)
            continue
        name = m.group("name")
        # async halves share a base name and describe ONE collective;
        # sync instructions are keyed by their full (unique) name so a
        # sync 'all-gather.3' never collides with an async pair whose
        # base normalizes to the same string
        if re.search(r"-(start|done)(\.|$)", name):
            key = ("async", re.sub(r"-(start|done)(\.|$)", r"\2", name))
        else:
            key = ("sync", name)
        if key in seen:
            continue
        base = key[1]
        # Payload bytes from a possibly-tuple result type. Two tuple
        # flavors exist and need opposite rules:
        # - async ``-start`` tuples are (operand aliases..., result,
        #   u32[] contexts...): the payload is the LARGEST array
        #   (picking "last" once recorded a 4 MB permute as its 4-byte
        #   context scalar; picking "first" understates an all-gather
        #   by its operand/result ratio);
        # - a SYNC tuple is a fused collective (XLA combines gradient
        #   psums into one all-reduce over many tensors): the payload
        #   is the SUM of the arrays (the max rule recorded a fused
        #   3-tensor psum as its largest member).
        shapes = [
            (dtype, _elems(shape), _elems(shape) * _DTYPE_BYTES[dtype])
            for dtype, shape in _SHAPE_RE.findall(m.group("type"))
            if dtype in _DTYPE_BYTES
        ]
        if not shapes:
            # token-typed line carries no payload shape; leave the key
            # unseen so the paired half (e.g. the -done) can record it
            continue
        seen.add(key)
        # an all-reduce's (sync or -start) tuple holds only results —
        # XLA fuses several reduced tensors into one op — so SUM them;
        # other async -start tuples are POSITIONALLY (operand
        # aliases..., results..., u32[] context scalars...): drop the
        # context scalars and sum the second half — the results. The
        # split is by position, not size: a reduce-scatter-start's
        # result is SMALLER than its operand (1/n), so "take the
        # largest array" overbooked it n-fold, and booked a fused pair
        # of gathers as one. ("Take the last" once recorded a 4 MB
        # permute as its 4-byte context scalar.) Bytes are summed
        # directly per-array so mixed-dtype fusions don't truncate
        # through one dtype's width; ``dtype`` reports the largest
        # member's, ``elements`` the summed element count.
        if key[0] == "async" and m.group("op") != "all-reduce":
            arrays = [
                s for s in shapes
                if not (s[0] in ("u32", "s32") and s[1] == 1)
            ] or shapes
            selected = arrays[(len(arrays) + 1) // 2:] or arrays
        else:
            selected = shapes
        rec = {
            "op": m.group("op"),
            "name": base,
            "dtype": max(selected, key=lambda t: t[2])[0],
            "elements": sum(e for _, e, _ in selected),
            "bytes": sum(b for _, _, b in selected),
            "computation": cur_comp,
        }
        if cur_comp in loop_comps:
            rec["in_loop"] = True
        g = _GROUPS_RE.search(line)
        if g:
            rec["groups"] = _parse_groups(g.group(1))
        p = _PAIRS_RE.search(line)
        if p:
            rec["pairs"] = _parse_groups(p.group(1))
        records.append(rec)
    return records


def has_collectives(hlo_text: str) -> bool:
    """Does the HLO text name any collective instruction?

    The companion check for :func:`collective_traffic`, kept next to
    the parser so the two rule sets stay in sync: text for which this
    is true but ``collective_traffic`` returns zero records is a
    parser miss (e.g. a print-option variant), not a collective-free
    program.

    Megascale host-transfer ``send`` instructions count too: on a
    genuine multi-slice artifact the cross-slice stage of a collective
    lowers to host-transfer sends handled by the megascale runtime
    (see ``_SEND_RE``), so a line carrying `` send(`` with
    ``is_host_transfer=true`` and a megascale marker is collective
    traffic even when no classic collective op appears — and a
    megascale-send parser regression is then flagged exactly like a
    collective-parser miss instead of reading as a collective-free
    program. The marker here is the bare string ``"xla_megascale"``,
    deliberately LOOSER than the parser's ``_xla_megascale`` attribute
    key: it also matches the handler-name value
    (``...handler_name="xla_megascale_runtime"``), so a renamed
    attribute escapes the parser but still trips this check. Host
    *callbacks* (``jax.debug.print`` / ``io_callback``) also lower to
    host-transfer sends but carry no megascale marker — they must NOT
    count, or every collective-free program with a debug print would
    book a spurious parser-miss error.
    """
    if any(
        f"{op}(" in hlo_text or f"{op}-start(" in hlo_text
        for op in _COLLECTIVES
    ):
        return True
    return any(
        " send(" in line and "is_host_transfer=true" in line
        and "xla_megascale" in line
        for line in hlo_text.splitlines()
    )


# ---------------------------------------------------------------------------
# Comm/compute overlap verification
# ---------------------------------------------------------------------------

#: one generic instruction definition: ``%name = <type> opcode(...``
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>[^=]+?)\s(?P<op>[\w\-]+)\("
)
#: candidate operand/attribute reference tokens after the opcode's ``(``
_TOKEN_RE = re.compile(r"%?([\w.\-]+)")

#: opcodes that move, reshape or describe data (or carry control)
#: rather than computing — excluded from the "compute scheduled during
#: communication" buckets so a ``pad``/``slice`` shuffle cannot
#: masquerade as hidden arithmetic. Fusions, elementwise ops, dots,
#: convolutions, selects, reduces all count.
_NON_COMPUTE_OPS = frozenset({
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "copy", "copy-start", "copy-done", "after-all", "partition-id",
    "replica-id", "while", "call", "conditional", "send", "send-done",
    "recv", "recv-done", "infeed", "outfeed", "domain", "opt-barrier",
    "pad", "reshape", "transpose", "slice", "dynamic-slice",
    "dynamic-update-slice", "concatenate", "broadcast", "iota",
    "reverse", "gather", "scatter", "rng", "rng-bit-generator",
}) | frozenset(_COLLECTIVES) | frozenset(
    f"{op}-{half}" for op in _COLLECTIVES for half in ("start", "done")
) | frozenset({"async-start", "async-update", "async-done"})


def _result_bytes_elems(type_str: str) -> Tuple[int, int]:
    """(bytes, elements) of a result type — arrays summed over tuples."""
    shapes = [
        (_elems(sh) * _DTYPE_BYTES[dt], _elems(sh))
        for dt, sh in _SHAPE_RE.findall(type_str)
        if dt in _DTYPE_BYTES
    ]
    return sum(b for b, _ in shapes), sum(e for _, e in shapes)


def _closure(start: str, edges: Dict[str, Set[str]]) -> Set[str]:
    """Transitive closure of ``edges`` from ``start`` (start excluded)."""
    seen: Set[str] = set()
    stack = list(edges.get(start, ()))
    while stack:
        nxt = stack.pop()
        if nxt in seen:
            continue
        seen.add(nxt)
        stack.extend(edges.get(nxt, ()))
    return seen


def overlap_report(compiled=None, hlo_text: Optional[str] = None) -> dict:
    """Statically verify comm/compute overlap on compiled HLO.

    For every collective instruction, measure the compute the scheduler
    can (or did) run while the transfer is in flight — turning "XLA will
    overlap it" from a hope into a checked property of the artifact:

    - an **async pair** (``collective-permute-start``/``done``,
      async all-reduce/all-gather/reduce-scatter) reports the compute
      instructions literally printed between start and done: compiled
      executables are scheduled modules, so between-ness in the text IS
      the schedule (``scheduled_ops``/``scheduled_bytes``);
    - a **sync** collective (the CPU backend, pre-scheduling dumps)
      has no printed flight window, so the report falls back to
      dataflow: compute in the same computation that neither feeds the
      collective's operands nor consumes its result — exactly the set
      the scheduler is free to place between start and done once the
      op is asynced (``independent_ops``/``independent_bytes``).

    Per-collective independence alone can flatter a bulk-synchronous
    program (the compute feeding collective B is "independent" of
    collective A), so the summary's headline bucket is stricter:
    ``overlappable_bytes`` counts compute independent of **every**
    collective in its computation — work the scheduler could run while
    the whole exchange is in flight. The naive stencil step reports
    ~zero there (every cell consumes the halos; only loop bookkeeping
    is free); the overlapped step reports its interior — the
    deterministic CPU-HLO assertion in ``tests/test_overlap.py``.

    Summary keys: ``scheduled_bytes`` (async pairs only — achieved
    overlap in the printed schedule; an instruction sitting inside
    several overlapping flight windows books once, though each pair's
    own ``scheduled_bytes`` still reports its full window),
    ``overlappable_bytes`` (the
    independent-of-all-collectives bucket), ``overlapped_bytes``
    (scheduled when the module has async pairs, else overlappable —
    the strongest overlap evidence this artifact supports),
    ``compute_bytes`` (all compute in collective-bearing
    computations), and ``overlap_fraction`` = overlappable/compute.
    ``flops_estimate`` is a 1-op-per-result-element lower bound
    (dots/convolutions undercounted) — a comparator between two
    schedules of one program, not an absolute flop count.
    """
    if hlo_text is None:
        hlo_text = compiled.as_text()
    lines = hlo_text.splitlines()
    _, comp_of_line = _scan_computations(lines)

    # per-computation: defs in print (schedule) order with deps
    comps: Dict[Optional[str], dict] = {}
    for lineno, line in enumerate(lines):
        m = _DEF_RE.match(line)
        if not m:
            continue
        comp = comps.setdefault(
            comp_of_line[lineno],
            {"order": [], "op": {}, "deps": {}, "bytes": {}, "elems": {}},
        )
        name, op = m.group("name"), m.group("op")
        nbytes, nelems = _result_bytes_elems(m.group("type"))
        comp["order"].append(name)
        comp["op"][name] = op
        comp["bytes"][name] = nbytes
        comp["elems"][name] = nelems
        rest = line[m.end():]
        comp["deps"][name] = {
            t for t in _TOKEN_RE.findall(rest) if t != name
        }
    for comp in comps.values():
        defs = set(comp["order"])
        comp["deps"] = {
            n: (d & defs) for n, d in comp["deps"].items()
        }
        users: Dict[str, Set[str]] = {n: set() for n in defs}
        for n, d in comp["deps"].items():
            for o in d:
                users[o].add(n)
        comp["users"] = users

    records = []
    scheduled_bytes = 0
    overlappable_bytes = 0
    overlappable_ops = 0
    flops_estimate = 0
    compute_bytes = 0
    for comp_name, comp in comps.items():
        order, ops = comp["order"], comp["op"]
        index = {n: i for i, n in enumerate(order)}
        coll_names = [
            n for n in order
            if ops[n] in _COLLECTIVES
            or any(ops[n] == f"{c}-start" for c in _COLLECTIVES)
        ]
        if not coll_names:
            continue
        # every collective-ish instruction (starts, syncs, AND dones):
        # the ancestor test below uses it to tell "this transfer is a
        # link of a dependent collective chain" — the input the perf
        # tier's serialized-dma rule consumes
        collectivish = {
            n for n in order
            if ops[n] in _COLLECTIVES
            or any(ops[n] in (f"{c}-start", f"{c}-done")
                   for c in _COLLECTIVES)
        }
        compute = [n for n in order if ops[n] not in _NON_COMPUTE_OPS]
        comp_compute_bytes = sum(comp["bytes"][n] for n in compute)
        compute_bytes += comp_compute_bytes
        windowed_names: Set[str] = set()
        # the strict bucket: compute linked to NO collective at all —
        # upstream of none (not operand prep), downstream of none (not
        # a consumer) — schedulable while the whole exchange flies
        linked: Set[str] = set()
        for cname in coll_names:
            linked |= _closure(cname, comp["deps"])
            linked |= _closure(cname, comp["users"])
            linked.add(cname)
        free_all = [n for n in compute if n not in linked]
        overlappable_bytes += sum(comp["bytes"][n] for n in free_all)
        overlappable_ops += len(free_all)
        flops_estimate += sum(comp["elems"][n] for n in free_all)
        for name in coll_names:
            op = ops[name]
            is_start = any(op == f"{c}-start" for c in _COLLECTIVES)
            rec = {
                "op": op[: -len("-start")] if is_start else op,
                "name": name,
                "computation": comp_name,
                "async": is_start,
                # total compute in the surrounding computation: the
                # denominator that tells "nothing to overlap" (0) apart
                # from "overlap impossible" (>0 but fully dependent) —
                # what traffic_lint's sync-no-overlap rule needs
                "computation_compute_bytes": comp_compute_bytes,
            }
            # per-collective freedom: neither upstream nor downstream
            # of THIS collective (looser than free_all — operand prep
            # for a sibling collective counts here)
            ancestors = _closure(name, comp["deps"])
            descendants = _closure(name, comp["users"])
            free = [
                n for n in compute
                if n != name and n not in ancestors
                and n not in descendants
            ]
            rec["independent_ops"] = len(free)
            rec["independent_bytes"] = sum(comp["bytes"][n] for n in free)
            # additive chain column: the nearest upstream collective
            # this transfer's start depends on (None = chain head) —
            # a dependent chain whose links move with zero scheduled
            # compute is the perf tier's serialized-dma finding
            upstream = [n for n in ancestors
                        if n != name and n in collectivish]
            rec["depends_on_collective"] = (
                max(upstream, key=lambda n: index[n]) if upstream
                else None
            )
            if is_start:
                done = next(
                    (
                        n for n in order
                        if any(ops[n] == f"{c}-done" for c in _COLLECTIVES)
                        and name in comp["deps"].get(n, ())
                    ),
                    None,
                )
                rec["done"] = done
                lo = index[name]
                hi = index[done] if done is not None else len(order)
                between = [
                    n for n in compute
                    if n != name and lo < index[n] < hi
                ]
                rec["scheduled_ops"] = len(between)
                rec["scheduled_bytes"] = sum(
                    comp["bytes"][n] for n in between
                )
                # summary dedup: compute inside several overlapping
                # flight windows (4 starts, interior, 4 dones) must
                # book ONCE, or the headline would quadruple-count it
                windowed_names.update(between)
            records.append(rec)
        scheduled_bytes += sum(
            comp["bytes"][n] for n in windowed_names
        )

    async_pairs = sum(1 for r in records if r["async"])
    return {
        "collectives": len(records),
        "async_pairs": async_pairs,
        "scheduled_bytes": scheduled_bytes,
        "overlappable_bytes": overlappable_bytes,
        "overlappable_ops": overlappable_ops,
        "overlapped_bytes": (
            scheduled_bytes if async_pairs else overlappable_bytes
        ),
        "compute_bytes": compute_bytes,
        "flops_estimate": flops_estimate,
        "overlap_fraction": (
            overlappable_bytes / compute_bytes if compute_bytes else 0.0
        ),
        "per_collective": records,
    }


def _group_crossing(group: Sequence[int], partition: Dict[int, int]) -> bool:
    """Does a replica group span more than one partition cell?"""
    return len({partition[d] for d in group}) > 1


def tier_crossing_bytes(
    records: Sequence[dict], partition: Dict[int, int]
) -> Dict[str, float]:
    """Per-device payload bytes whose collective spans the partition.

    ``partition`` maps device id -> tier cell (e.g. slice index of the
    hybrid mesh). A collective whose replica group stays inside one
    cell rides the fast tier only; one that spans cells must move its
    payload across the slow boundary. Returns
    ``{"crossing": B, "local": B}`` — the result-shape bytes of each
    class (floats: proportional accounting splits a record's bytes
    fractionally), the quantity the hierarchical-vs-flat comparison
    needs. For an all-reduce, every participating device contributes
    and receives the full result shape, so result bytes IS the
    per-device volume; for collective-permute, pairs that cross count.
    For gather-type collectives (all-gather, reduce-scatter) the
    per-device LINK traffic is smaller than the result shape — using
    result bytes is a deliberate upper-bound approximation, consistent
    across the programs being compared.

    Accounting is proportional: a device's payload counts as crossing
    when ITS replica group (or permute pair) spans the partition, so a
    record whose groups are part-local part-crossing contributes
    ``bytes * crossing_fraction`` to each bucket (e.g. a ring permute
    on a two-slice mesh crosses on exactly the 2 of n wrap links). A
    record whose group structure did not parse (``replica_groups={}``
    meaning all replicas, or the iota ``[n,m]<=[k]`` form) is counted
    as fully CROSSING — conservatively overstating the slow-tier
    volume rather than silently dropping payload.

    If any record carries ``in_loop`` the result gains an
    ``in_loop_records`` count: those records' bytes are per HLO
    occurrence (an under-count by the loop trip count), so both
    buckets are lower bounds for such programs.

    Multi-slice caveat: on a GENUINE multi-slice artifact XLA compiles
    ONE ``num_partitions=n_per_slice`` module PER SLICE, and the
    records come from a single module's text. The ``crossing`` bucket
    is still exact — every slice-crossing byte appears as a megascale
    send in whichever module it leaves — but ``local`` counts only the
    one compiled module's in-slice traffic, i.e. it is PER-MODULE, not
    the job-wide total (slices run the same SPMD program, so the
    job-wide figure is ``local × n_slices`` when you need it). Only
    compare ``local`` across programs compiled for the same topology.
    """
    out = {"crossing": 0.0, "local": 0.0}
    in_loop = sum(1 for rec in records if rec.get("in_loop"))
    if in_loop:
        # per-occurrence bytes of a while-body collective under-count
        # by the trip count — the volumes below are LOWER BOUNDS; the
        # key makes the understatement visible instead of silent
        out["in_loop_records"] = in_loop
    for rec in records:
        if rec.get("megascale"):
            # a megascale send exists ONLY to cross the slice boundary
            # (in-slice traffic stays in replica-grouped collectives)
            out["crossing"] += rec["bytes"]
            continue
        sets = rec.get("groups") or rec.get("pairs")
        if sets:
            ncross = sum(
                1 for g in sets if _group_crossing(g, partition)
            )
            frac = ncross / len(sets)
        else:
            frac = 1.0  # unknown structure: assume it crosses
        out["crossing"] += rec["bytes"] * frac
        out["local"] += rec["bytes"] * (1.0 - frac)
    return out


def ring_traffic(
    kind: str,
    n: int,
    payload_bytes: int,
    chunks: int = 1,
    hops: int = 1,
) -> Dict[str, int]:
    """Predicted per-device ICI traffic of a ring-tier program.

    The remote DMAs live inside Mosaic kernels, so HLO shows nothing;
    the schedule, however, is static (``kernels/ring.py``), and each
    protocol's per-device send volume follows from it:

    - ``all_gather``: each device forwards ``n - 1`` units of the
      per-rank payload around the ring.
    - ``all_reduce``: the running partial makes ``n - 1`` hops.
    - ``reduce_scatter``: ``n - 1`` block-sized partials leave each
      device.
    - ``neighbour_stream``: every chunk moves one hop per call;
      ``hops`` calls move ``chunks * hops`` chunk payloads.

    ``payload_bytes`` is the per-unit payload (the per-rank chunk for
    all_gather/all_reduce, the per-destination block for
    reduce_scatter, the chunk row for neighbour_stream). Returns
    ``{"ici_send_bytes": B}`` per device; receives are symmetric.
    """
    if kind in ("all_gather", "all_reduce", "reduce_scatter"):
        return {"ici_send_bytes": (n - 1) * payload_bytes}
    if kind == "neighbour_stream":
        return {"ici_send_bytes": chunks * hops * payload_bytes}
    raise ValueError(f"unknown ring protocol {kind!r}")


#: v5e one-way ICI bandwidth per link (the public scaling-book figure,
#: jax-ml.github.io/scaling-book: ~4.5e10 B/s one-way per link, 4 links
#: per chip in the 2-D torus). All predictions below are BANDWIDTH-ONLY
#: lower bounds at one link's rate — no per-hop latency, no multi-link
#: credit, no compute overlap — the compiled-evidence column that lets
#: the ring and XLA tiers be compared without owning a pod.
V5E_ICI_LINK_BYTES_PER_S = 4.5e10


def predicted_us(
    send_bytes: float,
    link_bytes_per_s: float = V5E_ICI_LINK_BYTES_PER_S,
) -> float:
    """Bandwidth-only wall-clock bound of moving ``send_bytes`` over
    one ICI link at the v5e rate, in microseconds."""
    return send_bytes / link_bytes_per_s * 1e6


def collective_wire_bytes(rec: dict) -> float:
    """Per-device ICI wire bytes of one HLO collective record under the
    standard ring algorithms (the basis of the predicted wall-clock
    column): all-reduce moves ``2(n-1)/n`` of its payload per device,
    all-gather and all-to-all ``(n-1)/n`` of the result, reduce-scatter
    ``(n-1)x`` its scattered output piece, collective-permute one hop
    of its payload. ``n`` comes from the record's largest replica
    group (default 2 when the group structure did not parse —
    a conservative under-count flagged by the default's rarity)."""
    op, b = rec["op"], rec["bytes"]
    groups = rec.get("groups")
    n = max((len(g) for g in groups), default=2) if groups else 2
    if op == "all-reduce":
        return 2 * (n - 1) / n * b
    if op in ("all-gather", "all-to-all"):
        return (n - 1) / n * b
    if op == "reduce-scatter":
        return (n - 1) * b
    return float(b)


def predicted_program_us(
    records: Sequence[dict],
    link_bytes_per_s: float = V5E_ICI_LINK_BYTES_PER_S,
) -> float:
    """Predicted per-device ICI wall-clock of a program's collectives,
    summed serially (no overlap credit) at the v5e link rate."""
    return sum(
        predicted_us(collective_wire_bytes(r), link_bytes_per_s)
        for r in records
    )


# ---------------------------------------------------------------------------
# HLO lint tier
# ---------------------------------------------------------------------------

#: The lint rules ``traffic_lint`` applies — documented one-for-one in
#: docs/analysis.md (drift-guarded by tests/test_perf_docs).
TRAFFIC_LINT_CHECKS = ("sync-no-overlap", "collective-in-loop",
                      "unframed-channel")

#: ``unframed-channel`` abstains on groups whose largest record is at
#: most this many bytes: at header scale a payload and a frame header
#: are the same shape, so the rule cannot classify them.
_UNFRAMED_MIN_BYTES = 64

#: A frame-header candidate must be at most 1/this of the payload it
#: vouches for — a real ``transfer_verified`` header is one s32 per
#: chunk (4 B per >=chunk_elements-element chunk), far below this; two
#: similarly-sized bare s32 transfers stay above it and both get
#: flagged instead of silently clearing each other.
_UNFRAMED_HEADER_RATIO = 8


def traffic_lint(compiled=None, hlo_text: Optional[str] = None) -> List[dict]:
    """Lint a compiled artifact's collective usage.

    The static-artifact counterpart of the protocol verifier: each rule
    flags a pattern that costs real wall-clock or durability at serving
    scale, checkable from ``compiled.as_text()`` alone:

    - ``sync-no-overlap`` — a sync collective in a computation that HAS
      compute, yet no compute is independent of it
      (``overlap_report``'s pairing): the transfer serializes the whole
      step, the exact shape the overlap engine (PR 3) exists to fix. A
      computation with no compute at all is NOT flagged — there is
      nothing to overlap.
    - ``collective-in-loop`` — a collective inside a ``while`` body: it
      is re-traced per iteration, its traffic is invisible to volume
      accounting (``in_loop`` records under-count by the trip count),
      and ``executable_report`` must withhold predicted wall-clock.
      Hoist or unroll it.
    - ``unframed-channel`` — a P2P channel payload (a single-pair
      ``collective-permute``) with no verified-transport frame header
      riding the same route. A framed transfer
      (``P2PChannel.transfer_verified``) moves its s32 checksum vector
      over an identical source-target pair in the same computation, at
      most ``1/_UNFRAMED_HEADER_RATIO`` of the payload's bytes; a bare
      payload is silent-corruption surface (the PR 2 fault matrix's
      existence proof). Every record of an unframed group is flagged
      (two bare transfers on one route are two findings, and two bare
      s32 transfers cannot clear each other as pseudo-headers).
      Multi-pair permutes (ring shifts, halo exchanges) are NOT
      channels and are not flagged, and groups at or below
      ``_UNFRAMED_MIN_BYTES`` are skipped — at header scale payload
      and header are indistinguishable by shape.

    Returns one dict per finding: ``{"check", "name", "op", "bytes",
    "message"}`` (empty list = clean) — the ``smi-tpu traffic --lint``
    payload, exit-nonzero-on-findings at the CLI.
    """
    if hlo_text is None:
        hlo_text = compiled.as_text()
    findings: List[dict] = []
    records = collective_traffic(None, hlo_text=hlo_text)

    report = overlap_report(hlo_text=hlo_text)
    for rec in report["per_collective"]:
        if rec["async"]:
            continue
        if (rec["computation_compute_bytes"] > 0
                and rec["independent_bytes"] == 0):
            findings.append({
                "check": "sync-no-overlap",
                "name": rec["name"],
                "op": rec["op"],
                "bytes": 0,
                "message": (
                    f"sync {rec['op']} %{rec['name']} gates every "
                    f"compute instruction in its computation "
                    f"({rec['computation_compute_bytes']} B of compute, "
                    f"0 B independent) — the transfer cannot overlap "
                    f"anything; restructure so some compute is free of "
                    f"it (see overlap_report)"
                ),
            })

    for rec in records:
        if rec.get("in_loop"):
            findings.append({
                "check": "collective-in-loop",
                "name": rec["name"],
                "op": rec["op"],
                "bytes": rec["bytes"],
                "message": (
                    f"{rec['op']} %{rec['name']} sits inside a while "
                    f"body: it runs trip-count times per occurrence, "
                    f"its volume is under-counted by traffic "
                    f"accounting, and predicted wall-clock is withheld "
                    f"— hoist it out of the loop or scale its budget "
                    f"by the trip count explicitly"
                ),
            })

    by_pairs: Dict[tuple, List[dict]] = {}
    for rec in records:
        pairs = rec.get("pairs")
        if rec["op"] == "collective-permute" and pairs:
            # a header only vouches for a payload in its OWN
            # computation — an unrelated framed transfer elsewhere in
            # the module must not clear this one
            by_pairs.setdefault(
                (rec.get("computation"),
                 tuple(tuple(p) for p in pairs)), []
            ).append(rec)
    for (_, pairs), group in sorted(
        by_pairs.items(), key=lambda kv: (str(kv[0][0]), kv[0][1])
    ):
        if len(pairs) != 1:
            continue  # ring/halo shifts, not point-to-point channels
        top = max(r["bytes"] for r in group)
        if top <= _UNFRAMED_MIN_BYTES:
            # below the classification floor a payload is the same
            # size as a frame header — undecidable from shapes alone,
            # so the rule abstains (documented in docs/analysis.md)
            continue
        framed = any(
            r["dtype"] in ("s32", "u32")
            and r["bytes"] * _UNFRAMED_HEADER_RATIO <= top
            for r in group
        )
        if framed:
            continue
        # no plausible header: EVERY record in the group is a bare
        # channel payload (not just the largest — two unframed
        # transfers on one route are two findings)
        for rec in group:
            findings.append({
                "check": "unframed-channel",
                "name": rec["name"],
                "op": rec["op"],
                "bytes": rec["bytes"],
                "message": (
                    f"P2P channel payload %{rec['name']} "
                    f"({rec['bytes']} B over pair "
                    f"{list(pairs[0])}) moves with no verified-"
                    f"transport frame header on the same route — "
                    f"in-flight corruption lands silently; use "
                    f"transfer_verified/stream_verified"
                ),
            })
    return findings
