"""Rooted collectives: Bcast, Reduce, Scatter, Gather.

Reference parity: ``include/smi/{bcast,reduce,scatter,gather}.h`` and the
per-port support kernels ``templates/{bcast,reduce,scatter,gather}.cl``.
Reference semantics to preserve:

- every collective takes an arbitrary *root* rank and a logical *port*;
- Reduce supports ADD/MAX/MIN (``include/smi/reduce_operations.h``);
- collectives on distinct ports may run concurrently without interference
  (``microbenchmarks/kernels/multi_collectives.cl``);
- only the root observes Reduce/Gather results, only non-roots receive
  Scatter slices of the root's buffer.

TPU re-design: two selectable implementation tiers per collective
(``backend=``):

- ``"xla"`` (default): one XLA collective over the communicator axis —
  the always-running support kernels, ready-to-receive handshakes and
  credit windows (``bcast.cl:18-33``, ``reduce.cl:13-32``) have no
  equivalent because XLA's collectives are internally flow-controlled.
- ``"ring"``: the framework's own explicit-schedule tier — neighbour
  RDMA Pallas kernels with credit flow control
  (:mod:`smi_tpu.kernels.ring`), the faithful analog of the reference's
  NoC being its data plane. Compiled on TPU meshes; on the CPU fake
  mesh it runs under Pallas TPU interpret mode with the full credit
  protocol live.

Rooted-ness is expressed by masking: a broadcast is a ``psum`` of the
value masked to the root (one all-reduce, which XLA lowers to an
ICI-optimal pattern); rooted results are masked to zeros off-root so
program behaviour matches the reference's "non-participants never see
the data". The *port* selects the stream assignment from the program
model (distinct ports → independent collectives XLA is free to overlap;
there is no false serialization because the ops share no data
dependencies).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax import lax

from smi_tpu.ops.types import SmiOp
from smi_tpu.parallel.backend import BACKENDS, check_backend as _check_backend
from smi_tpu.parallel.mesh import Communicator
from smi_tpu.utils.watchdog import Deadline


def _check_deadline(deadline: Optional[Deadline], family: str,
                    comm: Communicator) -> None:
    """Ring-tier watchdog gate: before dispatching an explicit-schedule
    collective, an expired deadline raises ``WatchdogTimeout`` carrying
    the protocol's per-rank state mirror
    (:func:`smi_tpu.parallel.faults.mirror_state_provider`) — the
    degraded-mode analog of an indefinite device hang becoming a named,
    debuggable error. Host-side only: under ``jit`` this fires at trace
    time; compiled re-executions are not re-checked (hard-bound those
    with ``watchdog.run_with_deadline`` around the readback)."""
    if deadline is None:
        return
    from smi_tpu.parallel.faults import mirror_state_provider

    # structured=True rides the raw dump on WatchdogTimeout.state, so
    # a caller can hand the error straight to
    # recovery.recover_communicator for a ULFM-style shrink-and-retry
    deadline.with_provider(
        mirror_state_provider(family, comm.size, structured=True)
    ).check(f"ring {family} over {comm.size} ranks")


def _ring():
    # deferred: smi_tpu.kernels.ring imports parallel.mesh at module load
    from smi_tpu.kernels import ring

    return ring


def _stream_for(port: Optional[int], program, family: str) -> int:
    """Stream slot of a collective's port — the runtime consumer of the
    program model's port->stream deal (``ops/program.py``): ring
    collectives on distinct streams use distinct barrier-semaphore
    domains (``kernels/ring.py::ring_collective_id``), so they can
    genuinely overlap, mirroring ``multi_collectives.cl``.

    With a program, a declared stream slot beyond the ring tier's
    semaphore-domain count is a loud error — sharing a domain between
    potentially-concurrent rings is exactly the aliasing the deal
    prevents. Without a program the port wraps modulo the domain count
    (a heuristic: nothing declares which collectives may run
    concurrently, so ports ≥ RING_STREAMS may alias; declare a program
    for the guarantee).
    """
    from smi_tpu.kernels.ring import RING_STREAMS
    from smi_tpu.ops.operations import OUT_DATA

    if port is None:
        return 0
    if program is not None:
        op = program.find(family, port)
        if op is not None:
            stream = program.stream_of(op, OUT_DATA)
            if stream >= RING_STREAMS:
                raise ValueError(
                    f"{family} port {port} was dealt to stream {stream}, "
                    f"beyond the ring tier's {RING_STREAMS} barrier-"
                    f"semaphore domains; reduce the program's "
                    f"num_streams or the concurrent-collective count"
                )
            return stream
    return port % RING_STREAMS


def _axis(comm: Communicator):
    """Collective axis argument: the name, or the ordered tuple for a
    multi-axis communicator (XLA collectives and the ring kernels both
    treat a tuple as one flattened axis in row-major rank order — the
    same flattening as ``Communicator.rank``)."""
    names = comm.axis_names
    return names[0] if len(names) == 1 else names


def _mesh_axes(comm: Communicator):
    """Full-mesh (name, size) context for the ring kernels' device-id
    resolution (``kernels/ring.py::mesh_axes_of``)."""
    from smi_tpu.kernels.ring import mesh_axes_of

    return mesh_axes_of(comm)


def _is_root(comm: Communicator, root: int) -> jax.Array:
    if not (0 <= root < comm.size):
        raise ValueError(
            f"root={root} out of range for comm size {comm.size}"
        )
    return comm.rank() == root


def bcast(x: jax.Array, comm: Communicator, root: int = 0,
          port: Optional[int] = None, backend: str = "xla",
          program=None, deadline: Optional[Deadline] = None) -> jax.Array:
    """One-to-all: every rank returns the root's ``x``.

    Reference: ``SMI_Bcast`` (``bcast.h:43-63``); the root's support kernel
    unicasts a copy per rank (``bcast.cl:36-43``) — here a single masked
    all-reduce whose only non-zero contribution is the root's value, which
    XLA lowers to a bandwidth-optimal ICI broadcast (or, under
    ``backend="ring"``, circulates around the explicit credit-controlled
    ring).
    """
    _check_backend(backend)
    if backend == "ring":
        _check_deadline(deadline, "broadcast", comm)
    mask = _is_root(comm, root)
    contrib = jnp.where(mask, x, jnp.zeros_like(x))
    if backend == "ring":
        return _ring().ring_all_reduce(
            contrib, _axis(comm), comm.size, op=SmiOp.ADD,
            interpret=not comm.is_tpu,
            stream=_stream_for(port, program, "broadcast"),
            mesh_axes=_mesh_axes(comm),
        )
    # on the XLA tier the port is metadata only: distinct ports are
    # independent by dataflow
    return lax.psum(contrib, _axis(comm))


def reduce(x: jax.Array, comm: Communicator, op: Union[str, SmiOp] = SmiOp.ADD,
           root: int = 0, port: Optional[int] = None,
           all_ranks: bool = False, backend: str = "xla",
           program=None, deadline: Optional[Deadline] = None) -> jax.Array:
    """All-to-one reduction with ADD/MAX/MIN.

    Reference: ``SMI_Reduce`` (``reduce.h:18-76``): every rank contributes,
    only the root receives the result (zeros elsewhere here). With
    ``all_ranks=True`` behaves as an allreduce (no masking) — the fused
    Reduce+Bcast idiom of kmeans (``kmeans_smi.cl:132-190``) without the
    second collective. ``backend="ring"`` runs the circulating-partial
    ring kernel (``kernels/ring.py``) instead of ``lax.psum``.
    """
    _check_backend(backend)
    op = SmiOp.parse(op)
    if backend == "ring":
        _check_deadline(deadline, "reduce", comm)
    name = _axis(comm)
    if backend == "ring":
        out = _ring().ring_all_reduce(
            x, name, comm.size, op=op, interpret=not comm.is_tpu,
            stream=_stream_for(port, program, "reduce"),
            mesh_axes=_mesh_axes(comm),
        )
    elif op is SmiOp.ADD:
        out = lax.psum(x, name)
    elif op is SmiOp.MAX:
        out = lax.pmax(x, name)
    else:
        out = lax.pmin(x, name)
    if all_ranks:
        return out
    return jnp.where(_is_root(comm, root), out, jnp.zeros_like(out))


def allreduce(x: jax.Array, comm: Communicator,
              op: Union[str, SmiOp] = SmiOp.ADD,
              backend: str = "xla", program=None,
              deadline: Optional[Deadline] = None) -> jax.Array:
    """Reduce + Bcast in one collective (convenience; no reference analog
    because SMI composes it from Reduce then Bcast, ``kmeans_smi.cl``)."""
    return reduce(x, comm, op=op, all_ranks=True, backend=backend,
                  program=program, deadline=deadline)


def allreduce_hierarchical(x: jax.Array, comm: Communicator,
                           op: Union[str, SmiOp] = SmiOp.ADD,
                           inner: Optional[str] = None,
                           outer: Optional[str] = None) -> jax.Array:
    """Two-tier allreduce for hybrid (slice × in-slice) communicators.

    Reference parity: SMI's router keeps traffic inside a node when it
    can — intra-node links cost 1, inter-node QSFP routes cost 100
    (``codegen/program.py:7-8``) — so a reduction crosses the expensive
    tier once with already-combined data. The TPU rendition for a
    ``make_hybrid_communicator`` mesh: reduce-scatter over the ICI
    axis, reduce the shards across slices over DCN (each shard crosses
    the slow tier exactly once, at 1/per_slice the full volume per
    link), then all-gather back over ICI. MAX/MIN have no scatter
    form, so they run the two psum-tier stages directly.

    ``x``'s leading dimension must be divisible by the inner axis size
    for the ADD path. Defaults take the communicator's axes as
    ``(outer, inner)``.
    """
    if len(comm.axis_names) != 2 and (inner is None or outer is None):
        raise ValueError(
            "hierarchical allreduce needs a 2-axis communicator or "
            "explicit inner=/outer= axis names"
        )
    outer = outer if outer is not None else comm.axis_names[0]
    inner = inner if inner is not None else comm.axis_names[1]
    if inner == outer:
        raise ValueError(
            f"inner and outer tiers must be distinct axes, got "
            f"{inner!r} for both"
        )
    for name in (inner, outer):
        if name not in comm.mesh.axis_names:
            raise ValueError(
                f"axis {name!r} not in mesh axes {comm.mesh.axis_names}"
            )
    op = SmiOp(op)
    if op is not SmiOp.ADD:
        fn = lax.pmax if op is SmiOp.MAX else lax.pmin
        return fn(fn(x, inner), outer)
    inner_size = comm.mesh.shape[inner]
    if x.shape[0] % inner_size != 0:
        raise ValueError(
            f"leading dim {x.shape[0]} not divisible by inner axis "
            f"size {inner_size}"
        )
    shard = lax.psum_scatter(x, inner, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, outer)
    return lax.all_gather(shard, inner, axis=0, tiled=True)


def scatter(x: jax.Array, comm: Communicator, root: int = 0,
            port: Optional[int] = None, backend: str = "xla",
            program=None, deadline: Optional[Deadline] = None) -> jax.Array:
    """Root distributes contiguous slices; rank r returns slice r.

    Reference: ``SMI_Scatter`` (``scatter.h:49-72``) — the root splits its
    ``size * count`` buffer and streams one ``count``-slice per rank
    (``scatter.cl:46-91``, including the root's self-copy). Here the root's
    masked buffer goes through one ``psum_scatter``: each rank receives
    only its own slice, so the data volume on ICI matches the reference's
    per-destination unicasts instead of a full broadcast.

    ``x`` must have leading dimension ``size * count`` (valid at root).
    ``backend="ring"`` uses the explicit ring reduce-scatter kernel.
    """
    _check_backend(backend)
    size = comm.size
    if x.shape[0] % size != 0:
        raise ValueError(
            f"scatter buffer leading dim {x.shape[0]} not divisible by "
            f"comm size {size}"
        )
    if backend == "ring":
        _check_deadline(deadline, "scatter", comm)
    contrib = jnp.where(_is_root(comm, root), x, jnp.zeros_like(x))
    if backend == "ring":
        return _ring().ring_reduce_scatter(
            contrib, _axis(comm), size, op=SmiOp.ADD,
            interpret=not comm.is_tpu,
            stream=_stream_for(port, program, "scatter"),
            mesh_axes=_mesh_axes(comm),
        )
    return lax.psum_scatter(contrib, _axis(comm), scatter_dimension=0,
                            tiled=True)


def gather(x: jax.Array, comm: Communicator, root: int = 0,
           port: Optional[int] = None, all_ranks: bool = False,
           backend: str = "xla", program=None,
           deadline: Optional[Deadline] = None) -> jax.Array:
    """Root collects contiguous slices; returns ``size * count`` at root.

    Reference: ``SMI_Gather`` (``gather.h:47-68``) — the root pulls each
    contributor's ``count`` elements in rank order (``gather.cl:47-99``).
    Here one ``all_gather`` rides ICI and the result is masked off-root
    (or kept everywhere with ``all_ranks=True``). ``backend="ring"``
    forwards chunks neighbour-to-neighbour around the explicit ring.
    """
    _check_backend(backend)
    if backend == "ring":
        _check_deadline(deadline, "gather", comm)
        out = _ring().ring_all_gather(
            x, _axis(comm), comm.size, interpret=not comm.is_tpu,
            stream=_stream_for(port, program, "gather"),
            mesh_axes=_mesh_axes(comm),
        )
    else:
        out = lax.all_gather(x, _axis(comm), axis=0, tiled=True)
    if all_ranks:
        return out
    return jnp.where(_is_root(comm, root), out, jnp.zeros_like(out))
