"""Static performance analyzer: critical-path decomposition + roofline lint.

The third analysis tier. PR 7's verifier and PR 10's model checker
prove *safety* — nothing in the repo could say why a slow protocol or
kernel is slow. This module closes that gap with two sub-tiers, both
pure Python (no JAX, no devices), both priced the way Hockney's
alpha-beta model prices a message (``T(m) = alpha + m/beta``,
PAPERS.md) and both subordinate to measurement per ATLAS: the analyzer
*names bottlenecks and catches drift*; the plan cache's measured
entries keep the last word on every knob.

Sub-tier (a): critical-path decomposition over protocols
--------------------------------------------------------
Reuses PR 7's single symbolic replay (the safety precondition — a
protocol must verify clean before a makespan means anything) and runs
the PR 6 timestamped simulator (``RingSimulator(costs=TierCostModel)``)
once under the canonical deterministic schedule, with instrumentation
that attributes every clock advance. A rank's clock only moves at
waits, and every jump is split against the *producing* event's window:

- **alpha** — the portion inside an inbound DMA's per-message latency
  window (the Hockney alpha of the data tier);
- **beta** — the portion inside its bandwidth window (bytes/beta);
- **serialization** — the portion inside a control signal's latency
  window (credit grants, barriers — the flow-control handshake cost);
- **idle** — the remainder: time the rank sat blocked *before the
  producing event was even issued*. Idle is genuine upstream lateness;
  on the healthy registered protocols it is exactly zero, which is
  what makes the `idle-fraction` rule a sharp detector.

The components sum to each rank's clock by construction, the makespan
is ``max`` of the rank clocks — **bit-identical to
``RingSimulator.elapsed_seconds()``**, because the decomposition runs
the same simulator on the same schedule (the 4894.3 us flat vs
1197.3 us two-tier pod numbers are test vectors). The timestamps are
schedule-independent for this zoo (single-producer time lanes push
monotonically; the only multi-producer domain is the symmetric
barrier, consumed whole), so the canonical schedule prices every
schedule.

Sub-tier (b): HLO/kernel roofline lint
---------------------------------------
``traffic_lint``-style rules fed by ``aot.cost_facts()``-shaped facts
and :mod:`smi_tpu.tuning.cost_model`:

- ``no-double-buffer`` — a kernel tile whose single-buffer VMEM
  footprint exceeds :data:`VMEM_DOUBLE_BUFFER_BOUND`
  (``VMEM_LIMIT_BYTES / 2``): the HBM->VMEM pipeline cannot
  double-buffer, so every tile load serializes against compute.
- ``below-roofline-tile`` — a tile choice whose forced HBM traffic
  (k/v re-read once per q-tile pass) pushes its achievable fraction of
  the ideal ``kernel_roofline_us`` under
  :data:`BELOW_ROOFLINE_FRACTION`.
- ``serialized-dma`` — an async collective pair that moved with ZERO
  compute scheduled in its flight window while being part of a
  dependent collective chain (extends ``overlap_report``'s new
  ``depends_on_collective`` column).
- ``analytic-regression`` — a statically predicted cost drifted more
  than :data:`ANALYTIC_DRIFT_FRACTION` *worse* than the committed
  expectation for the same knobs (:data:`ANALYTIC_EXPECTED_US`, the
  plan-cache/PERF.json discipline applied to the model itself).

Scope: fault-free schedules only (same honesty clause as the
verifier), and analytic throughout — a finding is a *named hypothesis*
about where the time goes; the measured sweep (``smi-tpu tune``)
outranks it on any knob it has measured. ``docs/analysis.md`` states
the full does/does-not-prove table; ``tests/test_perf_docs.py`` pins
every threshold here against its cost-model mirror.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from smi_tpu.parallel import credits as C
from smi_tpu.tuning import cost_model as cm

from smi_tpu.analysis.verifier import (
    DEFAULT_SHAPES,
    AnalysisError,
    VerifyEvent,
    _describe,
    build_generators,
    verify_generators,
)

#: Decomposition-tier rules (sub-tier a) — documented one-for-one in
#: docs/analysis.md (drift-guarded by tests/test_perf_docs).
PERF_PROTOCOL_CHECKS = ("idle-fraction", "serialized-critical-path")

#: Roofline-lint rules (sub-tier b), same documentation discipline.
PERF_LINT_CHECKS = ("no-double-buffer", "below-roofline-tile",
                    "serialized-dma", "analytic-regression")

PERF_CHECKS = PERF_PROTOCOL_CHECKS + PERF_LINT_CHECKS

#: A rank genuinely blocked (upstream had not even issued the awaited
#: event) for more than this fraction of the makespan is a finding.
#: Healthy registered protocols measure exactly 0.0 here — every wait
#: lands inside its producer's latency/bandwidth window — so the
#: threshold's only job is absorbing float dust and tiny topologies.
IDLE_FRACTION_THRESHOLD = 0.05

#: Single-buffer VMEM footprint above which a kernel tile cannot
#: double-buffer the HBM->VMEM pipeline inside the Mosaic scoped-VMEM
#: frame. MUST equal ``cost_model.VMEM_LIMIT_BYTES // 2``
#: (drift-guarded by tests/test_perf_docs).
VMEM_DOUBLE_BUFFER_BOUND = cm.VMEM_LIMIT_BYTES // 2

#: Minimum achievable fraction of the ideal kernel roofline a tile
#: choice may cost before ``below-roofline-tile`` fires.
BELOW_ROOFLINE_FRACTION = 0.5

#: ``analytic-regression`` fires when a recomputed static prediction is
#: more than this fraction WORSE than its committed expectation.
ANALYTIC_DRIFT_FRACTION = 0.25

#: Float-dust floor for the idle component (seconds): a jump's idle
#: part is ``delta - alpha - beta`` and can carry 1-ulp subtraction
#: residue; anything below a picosecond — seven orders of magnitude
#: under the smallest real alpha — is arithmetic, not lateness.
IDLE_DUST_S = 1e-12

#: Total collective payload each protocol instance is priced at; the
#: per-message granularity follows the protocol (full payload for the
#: circulating rings, ``payload/chunks`` for the pipelined ring,
#: ``payload/per_slice`` for every pod phase — the
#: ``pod_wallclock_comparison`` convention).
PERF_PAYLOAD_BYTES = 4 << 20

#: Canonical flash shape the roofline-lint rules price tiles at
#: (sequence length, head dim — the PERF.json S=8192 d=128 surface).
FLASH_CANONICAL_S = 8192
FLASH_CANONICAL_D = 128


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PerfFinding:
    """One performance defect (or drift), named the way the verifier
    names safety findings: ``events`` carries (rank, step, primitive)
    coordinates where they exist; the structured fields let tests
    convict mutants without string parsing."""

    check: str
    message: str
    events: Tuple[VerifyEvent, ...] = ()
    rank: Optional[int] = None
    lane: Optional[Tuple[int, int]] = None
    tier: Optional[str] = None
    fraction: Optional[float] = None
    expected: Optional[object] = None
    got: Optional[object] = None

    def to_json(self) -> dict:
        out = {
            "check": self.check,
            "message": self.message,
            "events": [e.to_json() for e in self.events],
        }
        for key in ("rank", "tier", "fraction"):
            if getattr(self, key) is not None:
                out[key] = getattr(self, key)
        if self.lane is not None:
            out["lane"] = list(self.lane)
        if self.expected is not None:
            out["expected"] = str(self.expected)
        if self.got is not None:
            out["got"] = str(self.got)
        return out

    def __str__(self) -> str:
        lines = [f"[{self.check}] {self.message}"]
        lines.extend(f"    at {e}" for e in self.events)
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Timed replay: the instrumented PR 6 simulator
# ---------------------------------------------------------------------------


class _TimedReplay(C.RingSimulator):
    """One deterministic run of the timestamped simulator with every
    clock advance attributed to its producing event.

    The base simulator's arithmetic is untouched (every ``super()``
    call runs the exact float operations ``elapsed_seconds()`` is built
    from — the bit-exactness claim); this subclass only shadows the
    semaphore time lanes with provenance metadata, mirroring the base's
    ``bisect.insort`` order (ties broken by push sequence, the
    insort-right order the base uses on bare floats).
    """

    def __init__(self, generators, strategy, costs):
        #: shadow time lanes: key -> sorted [(time, push_seq, meta)]
        self._meta: Dict[tuple, list] = {}
        self._push_seq = 0
        self._ctx: Optional[tuple] = None
        self._last_pop: list = []
        #: id(dma) -> issue/ready window + naming coordinates
        self._dmas: Dict[int, dict] = {}
        #: (src, dst) wire lane -> [dma ids] in issue order
        self._lanes: Dict[Tuple[int, int], List[int]] = {}
        #: (rank, tier) -> {component: seconds}
        self._parts: Dict[Tuple[int, str], Dict[str, float]] = {}
        #: (rank, lane) -> idle seconds attributed to that lane
        self._lane_idle: Dict[Tuple[int, Tuple[int, int]], float] = {}
        #: rank -> its most recent (hence final) clock-setting jump
        self._last_jump: Dict[int, dict] = {}
        #: rank -> its largest idle jump (the binding wait edge of an
        #: idle-fraction finding)
        self._max_idle_jump: Dict[int, dict] = {}
        super().__init__(generators, strategy, costs=costs)

    # -- shadow lanes ---------------------------------------------------
    def _push_time(self, key, at, times=1):
        super()._push_time(key, at, times)
        lane = self._meta.setdefault(key, [])
        for _ in range(times):
            bisect.insort(lane, (at, self._push_seq, self._ctx))
            self._push_seq += 1

    def _pop_times(self, key, amount):
        t = super()._pop_times(key, amount)
        lane = self._meta.get(key, [])
        take = min(amount, len(lane))
        self._last_pop = lane[:take]
        del lane[:take]
        return t

    # -- event context --------------------------------------------------
    def _land_dma(self, i):
        dma = self.inflight[i]
        self._ctx = ("land", id(dma))
        try:
            super()._land_dma(i)
        finally:
            self._ctx = None

    def _execute_one(self, r):
        action, _ = self.state[r]
        kind = action[0]
        step = self.actions_done[r]
        before = self.clock[r]
        self._last_pop = []
        if kind in ("signal", "dma"):
            self._ctx = (kind, r, step, action, before)
        try:
            super()._execute_one(r)
        finally:
            self._ctx = None
        if kind == "dma":
            dma = self.inflight[-1]
            src, origin_step = dma.origin
            # "obj" pins the _Dma alive: the simulator nulls its
            # inflight slot at landing, and a freed object's id() can
            # be RECYCLED by a later DMA — which would silently rewire
            # every attribution through this table
            self._dmas[id(dma)] = {
                "src": src, "dst": action[1], "step": origin_step,
                "action": action, "issue": before,
                "ready": dma.ready_at,
                "gate": self._last_jump.get(r),
                "obj": dma,
            }
            self._lanes.setdefault((r, action[1]), []).append(id(dma))
        elif kind == "wait" and self.clock[r] > before and self._last_pop:
            self._classify(r, step, action, before, self.clock[r])

    # -- attribution ----------------------------------------------------
    def _tier(self, a: int, b: int) -> str:
        if a == b:
            return "local"
        return "dcn" if self.costs.crosses_dcn(a, b) else "ici"

    def _book(self, r: int, tier: str, component: str, s: float) -> None:
        if s <= 0.0:
            return
        slot = self._parts.setdefault((r, tier), {})
        slot[component] = slot.get(component, 0.0) + s

    def _classify(self, r, step, action, before, after):
        """Split the jump ``after - before`` against the max popped
        entry's producing window (module docstring: alpha / beta /
        serialization / idle)."""
        delta = after - before
        _, _, ctx = self._last_pop[-1]
        waiter = VerifyEvent(r, step, _describe(action))
        if ctx is not None and ctx[0] == "land":
            info = self._dmas[ctx[1]]
            src, dst = info["src"], info["dst"]
            tier = self._tier(src, dst)
            link = self.costs.link(src, dst)
            alpha = link.alpha_s
            beta_s = info["ready"] - info["issue"] - alpha
            covered = max(0.0, info["ready"] - max(info["issue"], before))
            beta_part = min(covered, beta_s)
            alpha_part = min(covered - beta_part, alpha)
            idle_part = delta - beta_part - alpha_part
            if idle_part < IDLE_DUST_S:
                alpha_part += max(0.0, idle_part)
                idle_part = 0.0
            self._book(r, tier, "alpha", alpha_part)
            self._book(r, tier, "beta", beta_part)
            self._book(r, tier, "idle", idle_part)
            producer = VerifyEvent(info["src"], info["step"],
                                   _describe(info["action"]))
            lane = (src, dst)
        elif ctx is not None and ctx[0] == "signal":
            _, src, sstep, saction, sclock = ctx
            dst = saction[1]
            tier = self._tier(src, dst)
            alpha = self.costs.signal_seconds(src, dst)
            covered = max(0.0, (sclock + alpha) - max(sclock, before))
            ser_part = min(covered, alpha)
            idle_part = delta - ser_part
            if idle_part < IDLE_DUST_S:
                ser_part += max(0.0, idle_part)
                idle_part = 0.0
            self._book(r, tier, "serialization", ser_part)
            self._book(r, tier, "idle", idle_part)
            producer = VerifyEvent(src, sstep, _describe(saction))
            lane = (src, dst)
        else:
            # a SEM_SEND completion (pushed at the sender's own clock)
            # can never raise the sender's clock; anything else books
            # whole as serialization so the sum invariant holds
            tier, lane, idle_part = "local", (r, r), 0.0
            self._book(r, tier, "serialization", delta)
            producer = waiter
        jump = {"waiter": waiter, "producer": producer, "jump_s": delta,
                "idle_s": max(0.0, idle_part), "lane": lane, "tier": tier}
        self._last_jump[r] = jump
        if idle_part > 0.0:
            key = (r, lane)
            self._lane_idle[key] = self._lane_idle.get(key, 0.0) + idle_part
            best = self._max_idle_jump.get(r)
            if best is None or idle_part > best["idle_s"]:
                self._max_idle_jump[r] = jump


# ---------------------------------------------------------------------------
# Decomposition report
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PerfReport:
    """Makespan decomposition of one protocol instance."""

    protocol: str
    shape: Dict[str, int]
    ranks: int
    payload_bytes: float
    message_bytes: float
    pipeline_chunks: int
    makespan_s: float
    critical_rank: int
    #: the critical rank's per-tier component split (seconds)
    components: Dict[str, Dict[str, float]]
    #: one row per rank: clock, components, idle fraction, binding edge
    per_rank: Tuple[dict, ...]
    #: one row per wire lane: tier, messages, busy/span, pipeline depth
    wires: Tuple[dict, ...]
    findings: Tuple[PerfFinding, ...]
    #: the critical rank's final clock-setting wait edge
    binding: Optional[dict]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {
            "protocol": self.protocol,
            "shape": dict(self.shape),
            "ranks": self.ranks,
            "payload_bytes": self.payload_bytes,
            "message_bytes": self.message_bytes,
            "pipeline_chunks": self.pipeline_chunks,
            "makespan_us": self.makespan_s * 1e6,
            "critical_rank": self.critical_rank,
            "components_us": {
                tier: {k: v * 1e6 for k, v in comps.items()}
                for tier, comps in self.components.items()
            },
            "per_rank": [dict(row) for row in self.per_rank],
            "wires": [dict(w) for w in self.wires],
            "ok": self.ok,
            "findings": [f.to_json() for f in self.findings],
            "binding": self.binding,
        }

    def describe(self) -> str:
        shape = ", ".join(f"{k}={v}" for k, v in sorted(self.shape.items()))
        comps = []
        for tier in sorted(self.components):
            inner = ", ".join(
                f"{k} {v * 1e6:.1f}"
                for k, v in sorted(self.components[tier].items())
            )
            comps.append(f"{tier}: {inner}")
        head = (f"{self.protocol} [{shape}]: makespan "
                f"{self.makespan_s * 1e6:.1f} us on rank "
                f"{self.critical_rank} ({'; '.join(comps) or 'free'})")
        if self.binding is not None:
            head += f"\n  binding edge: {self.binding['text']}"
        if self.ok:
            return head
        body = "\n".join(f"  {line}" for f in self.findings
                         for line in str(f).splitlines())
        return f"{head}\n{body}"


def _wire_stats(replay: _TimedReplay) -> List[dict]:
    makespan = replay.elapsed_seconds()
    out = []
    for (src, dst), ids in sorted(replay._lanes.items()):
        infos = [replay._dmas[i] for i in ids]
        windows = sorted((i["issue"], i["ready"]) for i in infos)
        busy = sum(r - i for i, r in windows)
        span = max(r for _, r in windows) - min(i for i, _ in windows)
        # max concurrently-in-flight copies (strict overlap): the
        # measured pipeline depth of this wire
        depth = 0
        for i, (issue, _ready) in enumerate(windows):
            depth = max(depth, sum(
                1 for i2, (is2, rd2) in enumerate(windows)
                if is2 <= issue < rd2 or (i2 == i)
            ))
        out.append({
            "src": src, "dst": dst,
            "tier": replay._tier(src, dst),
            "messages": len(ids),
            "busy_us": busy * 1e6,
            "span_us": span * 1e6,
            "depth": depth,
            "idle_fraction": (
                max(0.0, 1.0 - busy / span)
                if span > 0 and len(ids) >= 2 else 0.0
            ),
            "utilization": busy / makespan if makespan else 0.0,
        })
    return out


def _costs_for(protocol: str, shape: Dict[str, int],
               payload_bytes: float) -> Tuple[C.TierCostModel, float, int]:
    """(costs, message_bytes, pipeline_chunks) for one registered
    instance — the ``pod_wallclock_comparison`` pricing convention."""
    n = shape["n"]
    chunks = shape.get("chunks", 1)
    if protocol == "allreduce_pod":
        per_slice = n // shape["slices"]
        message = payload_bytes / max(1, per_slice)
        return (
            C.default_tier_costs(message, per_slice),
            message, 1,
        )
    if protocol == "all_to_all":
        # per-destination block granularity: the payload splits n ways
        message = payload_bytes / max(1, n)
        return C.default_tier_costs(message, 0), message, 1
    if protocol == "all_to_all_bruck":
        # each round's n/2 block copies coalesce into one aggregate
        # message (the alltoall_variant_wallclocks pricing convention)
        message = payload_bytes / 2.0
        return C.default_tier_costs(message, 0), message, 1
    if protocol == "all_to_all_pod":
        per_slice = n // shape["slices"]
        block = payload_bytes / max(1, n)
        # mixed granularity: blocks on ICI, per_slice-block bundles on
        # DCN (the alltoall_wallclock_comparison convention)
        return (
            C.default_tier_costs(block, per_slice, ici_bytes=block,
                                 dcn_bytes=per_slice * block),
            block, 1,
        )
    if protocol == "all_reduce_quantized":
        # the pod shard granularity scaled by the int8 wire ratio —
        # the reduced bytes ride the per-tier sizing on BOTH tiers
        per_slice = n // shape["slices"]
        message = (payload_bytes / max(1, per_slice)
                   ) * C.PRECISION_WIRE_RATIO["int8"]
        return (
            C.default_tier_costs(message, per_slice, ici_bytes=message,
                                 dcn_bytes=message),
            message, 1,
        )
    if protocol == "all_reduce_sparse":
        # k (index, value) pairs per hop instead of the dense payload:
        # density * (index + value) overhead of the kept elements
        message = (payload_bytes * C.SPARSE_TOPK_DENSITY
                   * C.SPARSE_INDEX_OVERHEAD)
        return C.default_tier_costs(message, 0), message, 1
    if protocol == "all_reduce_chunked":
        message = payload_bytes / max(1, chunks)
        return C.default_tier_costs(message, 0), message, chunks
    if protocol == "neighbour_stream":
        message = payload_bytes / max(1, chunks)
        return C.default_tier_costs(message, 0), message, 1
    if protocol == "reduce_scatter":
        message = payload_bytes / max(1, n)
        return C.default_tier_costs(message, 0), message, 1
    return C.default_tier_costs(payload_bytes, 0), payload_bytes, 1


def decompose_generators(
    make_generators: Callable[[], Sequence[Iterator]],
    costs: C.TierCostModel,
    protocol: str = "<anonymous>",
    shape: Optional[Dict[str, int]] = None,
    payload_bytes: float = float(PERF_PAYLOAD_BYTES),
    pipeline_chunks: int = 1,
    seed: int = 0,
    verify: bool = True,
) -> PerfReport:
    """Decompose one protocol instance's makespan.

    ``make_generators`` follows the verifier's zero-arg-factory
    contract; with ``verify=True`` (the default) the PR 7 static
    verifier runs first — a protocol that can deadlock or race has no
    meaningful makespan, and the failure is the safety tier's finding,
    not a perf number (:class:`AnalysisError` naming it).
    """
    shape = dict(shape or {})
    if verify:
        safety = verify_generators(make_generators, protocol=protocol,
                                   shape=shape)
        if not safety.ok:
            raise AnalysisError(
                f"{protocol}: cannot decompose an unsafe protocol — "
                f"the static verifier found: "
                + "; ".join(f.check for f in safety.findings)
            )
    replay = _TimedReplay(make_generators(), C.Strategy(seed), costs)
    replay.run()
    makespan = replay.elapsed_seconds()
    ranks = replay.n
    critical = max(range(ranks), key=lambda r: replay.clock[r])

    per_rank: List[dict] = []
    findings: List[PerfFinding] = []
    for r in range(ranks):
        tiers: Dict[str, Dict[str, float]] = {}
        for (rank, tier), comps in replay._parts.items():
            if rank == r:
                tiers[tier] = {k: round(v * 1e6, 6)
                               for k, v in comps.items()}
        idle_s = sum(
            comps.get("idle", 0.0)
            for (rank, _t), comps in replay._parts.items() if rank == r
        )
        idle_fraction = idle_s / makespan if makespan else 0.0
        row = {
            "rank": r,
            "clock_us": replay.clock[r] * 1e6,
            "components_us": tiers,
            "idle_fraction": idle_fraction,
        }
        jump = replay._last_jump.get(r)
        if jump is not None:
            row["binding"] = _jump_json(jump)
        per_rank.append(row)
        if idle_fraction > IDLE_FRACTION_THRESHOLD:
            worst = replay._max_idle_jump.get(r)
            lane_key = max(
                ((lane, s) for (rk, lane), s in replay._lane_idle.items()
                 if rk == r),
                key=lambda kv: kv[1], default=((r, r), 0.0),
            )[0]
            tier = replay._tier(*lane_key)
            events = ()
            detail = ""
            if worst is not None:
                events = (worst["waiter"], worst["producer"])
                detail = (f", critical path blocked at "
                          f"{worst['waiter']} waiting on "
                          f"{worst['producer']}")
            findings.append(PerfFinding(
                check="idle-fraction",
                message=(
                    f"idle fraction {idle_fraction:.2f} on {tier} lane "
                    f"{lane_key[0]}->{lane_key[1]}: rank {r} sat "
                    f"blocked {idle_s * 1e6:.1f} us of the "
                    f"{makespan * 1e6:.1f} us makespan before the "
                    f"awaited event was even issued"
                    + detail
                ),
                events=events, rank=r, lane=lane_key, tier=tier,
                fraction=idle_fraction,
                expected=IDLE_FRACTION_THRESHOLD, got=idle_fraction,
            ))

    wires = _wire_stats(replay)
    if pipeline_chunks > 1 and wires:
        max_depth = max(w["depth"] for w in wires)
        if max_depth <= 1:
            busiest = max(wires, key=lambda w: w["busy_us"])
            lane = (busiest["src"], busiest["dst"])
            ids = replay._lanes[lane]
            gate = next(
                (replay._dmas[i]["gate"] for i in ids[1:]
                 if replay._dmas[i]["gate"] is not None),
                None,
            )
            events = ()
            detail = ""
            if gate is not None:
                events = (gate["waiter"], gate["producer"])
                detail = (f"; the pipeline collapses at "
                          f"{gate['waiter']} (gated by "
                          f"{gate['producer']})")
            findings.append(PerfFinding(
                check="serialized-critical-path",
                message=(
                    f"declared pipeline of {pipeline_chunks} chunks "
                    f"but no two copies were ever in flight together "
                    f"on any wire (measured depth {max_depth} on "
                    f"{busiest['tier']} lane "
                    f"{lane[0]}->{lane[1]}): every transfer sits on "
                    f"the critical path instead of overlapping its "
                    f"siblings" + detail
                ),
                events=events, lane=lane, tier=busiest["tier"],
                expected=pipeline_chunks, got=max_depth,
            ))

    binding = None
    jump = replay._last_jump.get(critical)
    if jump is not None:
        binding = _jump_json(jump)
    components = {
        tier: dict(comps)
        for (rank, tier), comps in replay._parts.items()
        if rank == critical
    }
    return PerfReport(
        protocol=protocol, shape=shape, ranks=ranks,
        payload_bytes=payload_bytes,
        message_bytes=costs.bytes_per_message,
        pipeline_chunks=pipeline_chunks,
        makespan_s=makespan, critical_rank=critical,
        components=components,
        per_rank=tuple(per_rank), wires=tuple(wires),
        findings=tuple(findings), binding=binding,
    )


def _jump_json(jump: dict) -> dict:
    return {
        "waiter": jump["waiter"].to_json(),
        "producer": jump["producer"].to_json(),
        "jump_us": jump["jump_s"] * 1e6,
        "idle_us": jump["idle_s"] * 1e6,
        "lane": list(jump["lane"]),
        "tier": jump["tier"],
        "text": (f"{jump['waiter']} <- {jump['producer']} "
                 f"(+{jump['jump_s'] * 1e6:.1f} us on {jump['tier']} "
                 f"lane {jump['lane'][0]}->{jump['lane'][1]})"),
    }


def decompose_protocol(
    protocol: str, n: int, chunks: int = 3, slices: int = 2,
    payload_bytes: float = float(PERF_PAYLOAD_BYTES), seed: int = 0,
    verify: bool = True,
) -> PerfReport:
    """Decompose one registered protocol at one shape (the
    ``smi-tpu lint --perf`` engine's unit of work). ``verify=False``
    skips the safety pre-pass — for callers that JUST ran the verifier
    over the same instance (``route --check --lint``,
    ``lint --combined``), where re-proving it would double the
    static-analysis bill."""
    shape: Dict[str, int] = {"n": n}
    if protocol in ("neighbour_stream", "all_reduce_chunked"):
        shape["chunks"] = chunks
    if protocol in ("allreduce_pod", "all_to_all_pod",
                    "all_reduce_quantized"):
        shape["slices"] = slices
    costs, _message, pipeline = _costs_for(protocol, shape, payload_bytes)
    return decompose_generators(
        lambda: build_generators(protocol, n, chunks=chunks,
                                 slices=slices),
        costs, protocol=protocol, shape=shape,
        payload_bytes=payload_bytes, pipeline_chunks=pipeline, seed=seed,
        verify=verify,
    )


def perf_all(
    protocols: Optional[Sequence[str]] = None,
    payload_bytes: float = float(PERF_PAYLOAD_BYTES),
    verify: bool = True,
) -> List[PerfReport]:
    """Decompose every registered protocol (or the named subset) over
    the verifier's default shape grid. ``verify=False`` when the
    caller has already run the safety tier over the same grid."""
    known = list(DEFAULT_SHAPES)
    if protocols is None:
        protocols = known
    else:
        unknown = [p for p in protocols if p not in known]
        if unknown:
            raise ValueError(
                f"unknown protocol(s) {unknown}; known: {known}"
            )
    reports = []
    for protocol in protocols:
        for shape in DEFAULT_SHAPES[protocol]:
            reports.append(decompose_protocol(
                protocol, payload_bytes=payload_bytes, verify=verify,
                **shape
            ))
    return reports


# ---------------------------------------------------------------------------
# Roofline lint (sub-tier b)
# ---------------------------------------------------------------------------


def flash_single_buffer_bytes(bq: int, bk: int, d: int,
                              itemsize: int) -> int:
    """VMEM footprint of ONE buffer generation of the flash forward
    tiles plus the persistent f32 scratch — the quantity that must fit
    in half the scoped-VMEM frame for the HBM->VMEM pipeline to
    double-buffer (mirrors ``cost_model.flash_fwd_vmem_bytes``, which
    books the tiles twice)."""
    tiles = (bq * d + 2 * bk * d) * itemsize
    scratch = bq * d * 4 + 2 * bq * 128 * 4
    return tiles + scratch


def flash_tile_hbm_bytes(s: int, d: int, bq: int, itemsize: int) -> int:
    """HBM traffic a (block_q = ``bq``) forward tiling forces at
    sequence length ``s``: k/v stream once per q-tile pass, q and the
    output move once."""
    passes = max(1, -(-s // bq))
    return passes * 2 * s * d * itemsize + 2 * s * d * itemsize


def flash_ideal_hbm_bytes(s: int, d: int, itemsize: int) -> int:
    """The compulsory traffic: q, k, v in, o out, each once."""
    return 4 * s * d * itemsize


def flash_canonical_flops(s: int, d: int) -> float:
    """QK^T + PV at full attention: 2 matmuls x 2 flops/MAC."""
    return 4.0 * s * s * d


def _shipped_flash_tiles() -> List[dict]:
    """The tile set ``lint --perf`` prices on a clean tree: the seeded
    measured-best blocks (tuning/seeded.py, drift-guarded against
    PERF.json)."""
    from smi_tpu.tuning import seeded

    return [
        {"name": "seeded bf16 causal", "dtype": "bfloat16",
         "block_q": seeded.SEEDED_FLASH_BF16_BLOCKS[0],
         "block_k": seeded.SEEDED_FLASH_BF16_BLOCKS[1]},
        {"name": "seeded bf16 windowed", "dtype": "bfloat16",
         "block_q": seeded.SEEDED_FLASH_BF16_WINDOW_BLOCKS[0],
         "block_k": seeded.SEEDED_FLASH_BF16_WINDOW_BLOCKS[1]},
        {"name": "seeded f32 causal", "dtype": "float32",
         "block_q": seeded.SEEDED_FLASH_F32_BLOCKS[0],
         "block_k": seeded.SEEDED_FLASH_F32_BLOCKS[1]},
    ]


def no_double_buffer_findings(
    tiles: Optional[Sequence[dict]] = None,
    d: int = FLASH_CANONICAL_D,
) -> List[PerfFinding]:
    """``no-double-buffer``: tiles whose single-buffer footprint
    exceeds half the scoped-VMEM frame."""
    findings = []
    for tile in (_shipped_flash_tiles() if tiles is None else tiles):
        itemsize = 2 if tile.get("dtype") == "bfloat16" else 4
        td = tile.get("d", d)
        single = flash_single_buffer_bytes(
            tile["block_q"], tile["block_k"], td, itemsize
        )
        if single > VMEM_DOUBLE_BUFFER_BOUND:
            findings.append(PerfFinding(
                check="no-double-buffer",
                message=(
                    f"flash tile bq{tile['block_q']}/bk{tile['block_k']}"
                    f" ({tile.get('dtype', 'float32')}, d={td}) needs "
                    f"{single // 1024} KiB of VMEM per buffer "
                    f"generation — over the "
                    f"{VMEM_DOUBLE_BUFFER_BOUND // 1024} KiB "
                    f"double-buffer bound of the "
                    f"{cm.VMEM_LIMIT_BYTES // 1024} KiB scoped-VMEM "
                    f"frame, so the HBM->VMEM pipeline cannot prefetch "
                    f"the next tile while computing this one"
                ),
                expected=VMEM_DOUBLE_BUFFER_BOUND, got=single,
            ))
    return findings


def below_roofline_findings(
    tiles: Optional[Sequence[dict]] = None,
    s: int = FLASH_CANONICAL_S,
    d: int = FLASH_CANONICAL_D,
) -> List[PerfFinding]:
    """``below-roofline-tile``: tiles whose forced k/v re-read traffic
    drops their achievable fraction of the ideal roofline under the
    threshold."""
    findings = []
    for tile in (_shipped_flash_tiles() if tiles is None else tiles):
        dtype = tile.get("dtype", "float32")
        itemsize = 2 if dtype == "bfloat16" else 4
        flops = flash_canonical_flops(s, d)
        ideal = cm.kernel_roofline_us(
            flops, flash_ideal_hbm_bytes(s, d, itemsize), dtype
        )
        tiled = cm.kernel_roofline_us(
            flops, flash_tile_hbm_bytes(s, d, tile["block_q"], itemsize),
            dtype,
        )
        if not ideal or not tiled:
            continue
        fraction = ideal / tiled
        if fraction < BELOW_ROOFLINE_FRACTION:
            findings.append(PerfFinding(
                check="below-roofline-tile",
                message=(
                    f"flash tile bq{tile['block_q']}/bk{tile['block_k']}"
                    f" ({dtype}) can reach only {fraction:.2f} of the "
                    f"kernel roofline at S={s}: its "
                    f"{-(-s // tile['block_q'])} k/v streaming passes "
                    f"force "
                    f"{flash_tile_hbm_bytes(s, d, tile['block_q'], itemsize) >> 20}"
                    f" MiB of HBM traffic vs the "
                    f"{flash_ideal_hbm_bytes(s, d, itemsize) >> 20} MiB"
                    f" compulsory minimum — widen block_q or accept "
                    f"the memory-bound tier"
                ),
                fraction=fraction,
                expected=BELOW_ROOFLINE_FRACTION, got=fraction,
            ))
    return findings


def serialized_dma_findings(hlo_text: str) -> List[PerfFinding]:
    """``serialized-dma``: async collective pairs that are part of a
    dependent collective chain yet moved with zero compute scheduled in
    their flight window — the transfer is pure critical path even
    though the program HAS compute to hide behind it."""
    from smi_tpu.parallel import traffic as T

    findings = []
    report = T.overlap_report(hlo_text=hlo_text)
    for rec in report["per_collective"]:
        if not rec["async"]:
            continue
        upstream = rec.get("depends_on_collective")
        if (rec.get("scheduled_ops", 0) == 0
                and rec["computation_compute_bytes"] > 0
                and upstream):
            findings.append(PerfFinding(
                check="serialized-dma",
                message=(
                    f"async {rec['op']} %{rec['name']} depends on "
                    f"collective %{upstream} and has ZERO compute "
                    f"scheduled between its start and done — the "
                    f"dependent DMA chain runs end-to-end on the "
                    f"critical path while the computation holds "
                    f"{rec['computation_compute_bytes']} B of compute "
                    f"that could hide it (see overlap_report)"
                ),
                expected=">0 scheduled bytes", got=0,
            ))
    return findings


# -- stencil stripe stream (r18 roofline closure) ----------------------------

#: The stream model is two ranks: the HBM side (0) pushing stripes and
#: collecting writebacks, and the compute core (1) consuming them.
STENCIL_STREAM_RANKS = 2

#: Default stripe payload of the replay: the shipped pipeline's
#: t=128 x (8192 + 2*128) lanes x 4 B extended stripe.
STENCIL_STRIPE_BYTES = 128 * (8192 + 256) * 4


def stencil_stream_generators(
    chunks: int, buffering: int,
) -> List[Iterator]:
    """Per-rank generators of the stencil stripe stream at one
    buffering depth — the credits-vocabulary twin of the explicit-DMA
    kernel (``kernels/stencil_pipeline.py``), so the PR 7 verifier and
    the decomposer can certify/price the SAME slot-rotation discipline
    the Pallas kernel hand-codes with ``pltpu.SemaphoreType.DMA``.

    ``buffering == 1`` is the synchronous control path: the HBM side
    issues fetch ``i`` only after consuming writeback ``i - 1``, so
    every stripe flight sits on the critical path twice — the shape
    whose replay the ``idle-fraction`` finding must name.

    ``buffering >= 2`` is the slot rotation: ``buffering`` fetches run
    ahead of the consumer (fetch-slot reuse fenced by the consumer's
    read credit — the sim twin of the kernel's writeback-semaphore
    wait before reusing a VMEM slot), and writebacks stream into
    per-stripe HBM-side slots the moment each stripe is consumed —
    HBM is the destination, so there is no landing-slot scarcity to
    fence, exactly as in the kernel. Credit grants are counted exactly
    (``chunks - buffering``) so the verifier's leak check drains to
    zero, and with the canonical stripe count every wait lands inside
    an already-issued DMA window (idle under the threshold on BOTH
    ranks).
    """
    if chunks < 1 or buffering < 1:
        raise ValueError(
            f"stencil stream needs chunks >= 1 and buffering >= 1, "
            f"got chunks={chunks} buffering={buffering}"
        )

    if buffering == 1:
        def hbm_sync():
            for i in range(chunks):
                yield ("dma", 1, 0, ("stripe", i), 0, 0)
                yield ("wait", C.SEM_SEND, 0, 1)
                yield ("wait", C.SEM_RECV, 0, 1)
                done = yield ("read_slot", 0)
                yield ("output", i, done)

        def core_sync():
            for i in range(chunks):
                yield ("wait", C.SEM_RECV, 0, 1)
                stripe = yield ("read_slot", 0)
                yield ("dma", 0, 0, stripe, 0, 0)
                yield ("wait", C.SEM_SEND, 0, 1)

        return [hbm_sync(), core_sync()]

    depth = buffering

    def hbm_stream():
        for i in range(chunks):
            slot = i % depth
            if i >= depth:
                # fetch-slot reuse fenced by the consumer's read credit
                yield ("wait", C.SEM_CREDIT, slot, 1)
            yield ("dma", 1, slot, ("stripe", i), slot, slot)
            yield ("wait", C.SEM_SEND, slot, 1)
            if i >= depth:
                j = i - depth
                yield ("wait", C.SEM_RECV, ("wb", j), 1)
                done = yield ("read_slot", ("wb", j))
                yield ("output", j, done)
        for j in range(max(0, chunks - depth), chunks):
            yield ("wait", C.SEM_RECV, ("wb", j), 1)
            done = yield ("read_slot", ("wb", j))
            yield ("output", j, done)

    def compute_core():
        for i in range(chunks):
            slot = i % depth
            yield ("wait", C.SEM_RECV, slot, 1)
            stripe = yield ("read_slot", slot)
            if i < chunks - depth:
                yield ("signal", 0, C.SEM_CREDIT, slot, 1)
            yield ("dma", 0, ("wb", i), stripe, ("wb", i), ("wb", i))
            yield ("wait", C.SEM_SEND, ("wb", i), 1)

    return [hbm_stream(), compute_core()]


#: Canonical stripe count of the replay: one 8192-row pass at the
#: shipped stripe width t=128 (startup transients amortize away at
#: this length — shorter replays book the fill/drain ramp as idle).
STENCIL_STREAM_STRIPES = 64


def decompose_stencil_stream(
    n_stripes: int = STENCIL_STREAM_STRIPES,
    stripe_bytes: float = float(STENCIL_STRIPE_BYTES),
    buffering: int = 3,
    seed: int = 0,
    verify: bool = True,
) -> PerfReport:
    """Verify + decompose the stencil stripe stream at one buffering
    depth — the overlap PROOF behind the r18 pipeline claim: the
    synchronous replay exceeds :data:`IDLE_FRACTION_THRESHOLD` on the
    DMA wait edge, the pipelined replay stays under it with measured
    wire depth >= 2 (``tests/test_stencil_pipeline.py`` asserts both
    sides, ``bench.py`` ships the pipelined fraction)."""
    costs = C.default_tier_costs(stripe_bytes, 0)
    return decompose_generators(
        lambda: stencil_stream_generators(n_stripes, buffering),
        costs,
        protocol=f"stencil_stream_b{buffering}",
        shape={"n": STENCIL_STREAM_RANKS, "chunks": n_stripes,
               "buffering": buffering},
        payload_bytes=n_stripes * stripe_bytes,
        pipeline_chunks=n_stripes if buffering >= 2 else 1,
        seed=seed, verify=verify,
    )


def stencil_overlap_fraction(report: PerfReport) -> float:
    """The decomposer-measured share of the stripe stream hidden
    behind compute: one minus the worst per-rank idle fraction of the
    replay (1.0 = every wait landed inside an already-issued DMA
    window — perfect overlap)."""
    worst = max((r["idle_fraction"] for r in report.per_rank),
                default=0.0)
    return max(0.0, 1.0 - worst)


# -- analytic regression -----------------------------------------------------

#: Committed static predictions (microseconds) at the published rates —
#: the PERF.json discipline applied to the analyzer itself: a code
#: change that silently reprices one of these shows up as an
#: ``analytic-regression`` finding (and a test_perf_docs failure)
#: instead of a quietly different curve. Regenerate with
#: ``analytic_predictions()`` when the cost model legitimately moves.
ANALYTIC_EXPECTED_US = {
    "pod_allreduce_flat_2x2_4mib_us": 4894.3,
    "pod_allreduce_two_tier_2x2_4mib_us": 1197.3,
    "allreduce_n8_64kib_us": 132.7,
    "allreduce_n8_256kib_us": 163.3,
    "allreduce_n8_1024kib_us": 285.6,
    "allreduce_n8_4096kib_us": 408.1,
    "alltoall_n8_64kib_us": 54.7,
    "alltoall_n8_256kib_us": 61.2,
    "alltoall_n8_1024kib_us": 87.5,
    "alltoall_n8_4096kib_us": 192.3,
    "alltoall_pairwise_2x2_1mib_us": 1548.6,
    "alltoall_two_tier_2x2_1mib_us": 957.4,
    "flash_fwd_bf16_seeded_roofline_us": 174.4,
    "flash_fwd_f32_seeded_roofline_us": 523.2,
    "stencil_pipeline_8192_sweep_us": 318.6,
    "stencil_sync_8192_sweep_us": 390.1,
    # r19 compressed collectives: the 2x2-pod 4 MiB A/B vectors (f32
    # baseline is pod_allreduce_two_tier_2x2_4mib_us above) and the
    # int8 flat curve the bench `compression` row compares against.
    # The acceptance bar: int8/f32 <= 0.55 on BOTH the full makespan
    # (603.1 / 1197.3 = 0.504) and the DCN phase (274.8 / 799.1 =
    # 0.344), tier-1-asserted.
    "quantized_pod_allreduce_int8_2x2_4mib_us": 603.1,
    "quantized_pod_allreduce_bf16_2x2_4mib_us": 801.1,
    "quantized_pod_dcn_phase_f32_2x2_4mib_us": 799.1,
    "quantized_pod_dcn_phase_int8_2x2_4mib_us": 274.8,
    "allreduce_int8_n8_64kib_us": 125.0,
    "allreduce_int8_n8_256kib_us": 132.7,
    "allreduce_int8_n8_1024kib_us": 163.3,
    "allreduce_int8_n8_4096kib_us": 285.6,
}


#: The payload grid of the committed allreduce curve (KiB).
ALLREDUCE_CURVE_SIZES_KB = (64, 256, 1024, 4096)

#: The payload grid of the committed all-to-all curve (KiB, total
#: per-rank payload — one payload/n block per destination).
ALLTOALL_CURVE_SIZES_KB = (64, 256, 1024, 4096)


def allreduce_curve_us(
    sizes_kb: Sequence[int] = ALLREDUCE_CURVE_SIZES_KB, n: int = 8,
) -> List[float]:
    """The best-flat-candidate allreduce latency curve at the published
    ICI rates — the SINGLE pricing used by both the
    ``analytic-regression`` lint rule and the bench.py scoreboard, so
    the two consumers can never silently price the same curve
    differently."""
    link = cm.LinkModel()
    return [
        round(min(
            cm.ring_allreduce_us(kb * 1024, n, link),
            cm.rs_ag_allreduce_us(kb * 1024, n, link),
        ), 1)
        for kb in sizes_kb
    ]


def alltoall_curve_us(
    sizes_kb: Sequence[int] = ALLTOALL_CURVE_SIZES_KB, n: int = 8,
) -> List[float]:
    """The best-flat-candidate all-to-all latency curve (pairwise vs
    Bruck) at the published ICI rates — the SINGLE pricing used by
    both the ``analytic-regression`` lint rule and the bench.py
    ``alltoall`` scoreboard row, mirroring
    :func:`allreduce_curve_us`'s one-pricing discipline."""
    link = cm.LinkModel()
    return [
        round(min(
            cm.pairwise_alltoall_us(kb * 1024, n, link),
            cm.bruck_alltoall_us(kb * 1024, n, link),
        ), 1)
        for kb in sizes_kb
    ]


def quantized_curve_us(
    sizes_kb: Sequence[int] = ALLREDUCE_CURVE_SIZES_KB, n: int = 8,
    precision: str = "int8",
) -> List[float]:
    """The quantized-wire allreduce latency curve at the published ICI
    rates: the best flat candidate priced at the precision's wire bytes
    (:data:`credits.PRECISION_WIRE_RATIO`) — the SINGLE pricing used by
    both the ``analytic-regression`` lint rule and the bench.py
    ``compression`` scoreboard row, mirroring
    :func:`allreduce_curve_us`'s one-pricing discipline. The curves are
    directly comparable point-for-point: same grid, same candidates,
    same rates, only the wire width differs."""
    ratio = C.PRECISION_WIRE_RATIO[precision]
    link = cm.LinkModel()
    return [
        round(min(
            cm.ring_allreduce_us(kb * 1024 * ratio, n, link),
            cm.rs_ag_allreduce_us(kb * 1024 * ratio, n, link),
        ), 1)
        for kb in sizes_kb
    ]


def analytic_predictions() -> Dict[str, float]:
    """Recompute today's static predictions for the committed
    expectation set, at the PUBLISHED rates (a fleet
    ``$SMI_TPU_DCN_BETA`` must not leak into the drift check)."""
    out: Dict[str, float] = {}
    dcn = C.LinkCost(cm.DCN_ALPHA_S, cm.DCN_BETA_BYTES_PER_S)
    rep = C.pod_wallclock_comparison(2, 2, 4 << 20, dcn=dcn)
    out["pod_allreduce_flat_2x2_4mib_us"] = round(rep["flat_s"] * 1e6, 1)
    out["pod_allreduce_two_tier_2x2_4mib_us"] = round(
        rep["hierarchical_s"] * 1e6, 1
    )
    for kb, us in zip(ALLREDUCE_CURVE_SIZES_KB, allreduce_curve_us()):
        out[f"allreduce_n8_{kb}kib_us"] = us
    for kb, us in zip(ALLTOALL_CURVE_SIZES_KB, alltoall_curve_us()):
        out[f"alltoall_n8_{kb}kib_us"] = us
    a2a = C.alltoall_wallclock_comparison(2, 2, float(1 << 20),
                                          dcn=dcn)
    out["alltoall_pairwise_2x2_1mib_us"] = round(
        a2a["pairwise_s"] * 1e6, 1
    )
    out["alltoall_two_tier_2x2_1mib_us"] = round(
        a2a["hierarchical_s"] * 1e6, 1
    )
    # r19: the quantized A/B vectors from the SAME simulator run shape
    # as the committed two-tier baseline, plus the int8 flat curve
    q8 = C.quantized_wallclock_comparison(2, 2, 4 << 20, "int8",
                                          dcn=dcn)
    out["quantized_pod_allreduce_int8_2x2_4mib_us"] = round(
        q8["quantized_s"] * 1e6, 1
    )
    out["quantized_pod_dcn_phase_f32_2x2_4mib_us"] = round(
        q8["f32_dcn_s"] * 1e6, 1
    )
    out["quantized_pod_dcn_phase_int8_2x2_4mib_us"] = round(
        q8["quantized_dcn_s"] * 1e6, 1
    )
    qb = C.quantized_wallclock_comparison(2, 2, 4 << 20, "bf16",
                                          dcn=dcn)
    out["quantized_pod_allreduce_bf16_2x2_4mib_us"] = round(
        qb["quantized_s"] * 1e6, 1
    )
    for kb, us in zip(ALLREDUCE_CURVE_SIZES_KB, quantized_curve_us()):
        out[f"allreduce_int8_n8_{kb}kib_us"] = us
    from smi_tpu.tuning import seeded

    for name, (bq, _bk), dtype in (
        ("flash_fwd_bf16_seeded_roofline_us",
         seeded.SEEDED_FLASH_BF16_BLOCKS, "bfloat16"),
        ("flash_fwd_f32_seeded_roofline_us",
         seeded.SEEDED_FLASH_F32_BLOCKS, "float32"),
    ):
        itemsize = 2 if dtype == "bfloat16" else 4
        out[name] = round(cm.kernel_roofline_us(
            flash_canonical_flops(FLASH_CANONICAL_S, FLASH_CANONICAL_D),
            flash_tile_hbm_bytes(FLASH_CANONICAL_S, FLASH_CANONICAL_D,
                                 bq, itemsize),
            dtype,
        ), 1)
    # r18: one 8192^2 sweep under the seeded pipeline knobs vs the
    # synchronous control — the margin the measured sweep must confirm
    out["stencil_pipeline_8192_sweep_us"] = round(
        cm.stencil_pipeline_us(8192, 8192, 8, 128, "float32"), 1
    )
    out["stencil_sync_8192_sweep_us"] = round(
        cm.stencil_pipeline_us(8192, 8192, 16, 128, "float32",
                               buffering=1), 1
    )
    return out


def analytic_regression_findings(
    predictions: Optional[Dict[str, float]] = None,
    expected: Optional[Dict[str, float]] = None,
) -> List[PerfFinding]:
    """``analytic-regression``: recomputed predictions that drifted
    more than :data:`ANALYTIC_DRIFT_FRACTION` WORSE than the committed
    expectation for the same knobs. Improvements do not fire (they
    should land as updated expectations); a missing prediction is a
    loud finding, never a silent skip."""
    preds = analytic_predictions() if predictions is None else predictions
    exp = ANALYTIC_EXPECTED_US if expected is None else expected
    findings = []
    for name, want in sorted(exp.items()):
        got = preds.get(name)
        if got is None:
            findings.append(PerfFinding(
                check="analytic-regression",
                message=(
                    f"expectation {name!r} has no recomputed "
                    f"prediction — the expectation table and the "
                    f"predictor drifted apart"
                ),
                expected=want, got=None,
            ))
            continue
        if got > want * (1.0 + ANALYTIC_DRIFT_FRACTION):
            findings.append(PerfFinding(
                check="analytic-regression",
                message=(
                    f"static prediction {name} regressed to "
                    f"{got:.1f} us vs the committed {want:.1f} us "
                    f"({got / want:.2f}x, beyond the "
                    f"{ANALYTIC_DRIFT_FRACTION:.0%} drift bound) for "
                    f"unchanged knobs — a cost-model or protocol "
                    f"change made the same configuration analytically "
                    f"slower; re-measure or update the expectation"
                ),
                fraction=got / want, expected=want, got=got,
            ))
    return findings


def roofline_lint(
    flash_tiles: Optional[Sequence[dict]] = None,
    hlo_text: Optional[str] = None,
    check_expectations: bool = True,
) -> List[PerfFinding]:
    """The full sub-tier (b) pass: VMEM double-buffer + tile roofline
    over ``flash_tiles`` (default: the shipped seeded tiles),
    ``serialized-dma`` when an HLO artifact is given, and the analytic
    drift check against the committed expectations."""
    findings = no_double_buffer_findings(flash_tiles)
    findings += below_roofline_findings(flash_tiles)
    if hlo_text is not None:
        findings += serialized_dma_findings(hlo_text)
    if check_expectations:
        findings += analytic_regression_findings()
    return findings


# ---------------------------------------------------------------------------
# Report aggregation (the ``smi-tpu lint --perf`` payload)
# ---------------------------------------------------------------------------


def perf_reports_to_json(
    reports: Sequence[PerfReport],
    roofline: Sequence[PerfFinding] = (),
) -> dict:
    n_findings = (sum(len(r.findings) for r in reports)
                  + len(roofline))
    return {
        "ok": n_findings == 0,
        "tier": "perf",
        "findings": n_findings,
        "checks": list(PERF_CHECKS),
        "idle_fraction_threshold": IDLE_FRACTION_THRESHOLD,
        "protocols": [r.to_json() for r in reports],
        "roofline": [f.to_json() for f in roofline],
    }


def render_perf_reports(
    reports: Sequence[PerfReport],
    roofline: Sequence[PerfFinding] = (),
) -> str:
    lines = [r.describe() for r in reports]
    lines.extend(str(f) for f in roofline)
    n_findings = (sum(len(r.findings) for r in reports)
                  + len(roofline))
    lines.append(
        f"{len(reports)} protocol instance(s) decomposed, "
        f"{n_findings} perf finding(s)"
    )
    return "\n".join(lines)
