"""Deliberately broken protocol variants — the verifier's existence proof.

A verifier that has never caught a bug proves nothing, so each mutant
here reinstates one of the defect classes the credit discipline exists
to prevent, expressed as an *event-stream transformer* wrapped around a
healthy rank's generator (the same adapter shape as
``credits.instance_steps``/``verified_steps``, so the clean state
machines stay untouched):

- :func:`drop_grant` — ``"dropped_wait"``: the credit grant a partner's
  semaphore wait is matched against is dropped, leaving that wait
  dangling forever. Statically a :class:`~.verifier.StaticDeadlock`
  (the starved wait and the ranks transitively blocked behind it);
  dynamically the exhaustive fuzzer's
  :class:`~smi_tpu.parallel.credits.DeadlockError` on every schedule.
- :func:`reuse_slots` — ``"reused_slot"``: two comm buffers collapse to
  one VMEM address (an addressing/codegen bug): DMA destinations and
  local reads/writes are remapped while the semaphore wiring stays
  intact. Statically a :class:`~.verifier.SlotRace` naming both
  accesses; dynamically a
  :class:`~smi_tpu.parallel.credits.ClobberError` (or wrong delivery)
  under the schedules that interleave the aliased writes.
- :func:`duplicate_grant` — ``"unbalanced_grant"``: one credit grant is
  signalled twice. Statically a
  :class:`~.verifier.CreditConservation` finding naming the surplus
  domain; dynamically
  :class:`~smi_tpu.parallel.credits.CreditLeakError` at exit (or a
  clobber when a schedule spends the surplus early).
- :func:`delay_grant` — ``"late_grant"``: every rank holds its credit
  grant until after its own wait — the neighbour handshake becomes a
  genuine cross-rank wait-for *cycle* (every grant still exists; no
  wait is starved), which the deadlock check must report as the
  minimal cycle of (rank, step, primitive) events.

``tests/test_analysis.py``'s differential harness runs every mutant
through BOTH tiers and asserts the verdicts agree — same defect class,
same named events — on every space the dynamic fuzzer can exhaust.
"""

from __future__ import annotations

from typing import Callable, Iterator, List

from smi_tpu.parallel import credits as C

from smi_tpu.analysis.verifier import build_generators


def _transformed(gen: Iterator, fn: Callable[[tuple], List[tuple]]):
    """Apply ``fn`` (action -> replacement actions, possibly empty or
    duplicated) to one rank's stream, staying ``send``-transparent for
    ``read_slot`` feedback."""
    value = None
    while True:
        try:
            action = gen.send(value)
        except StopIteration:
            return
        value = None
        for out in fn(action):
            value = yield out


def drop_grant(gen: Iterator, nth: int = 0):
    """Drop the ``nth`` credit grant this rank signals — the matched
    downstream wait can never complete (the 'dropped wait')."""
    state = {"k": 0}

    def fn(action):
        if action[0] == "signal" and action[2] == C.SEM_CREDIT:
            k = state["k"]
            state["k"] += 1
            if k == nth:
                return []
        return [action]

    return _transformed(gen, fn)


def duplicate_grant(gen: Iterator, nth: int = 0):
    """Signal the ``nth`` credit grant twice — a surplus unit the
    protocol never consumes (or spends on an RDMA it had no right to)."""
    state = {"k": 0}

    def fn(action):
        if action[0] == "signal" and action[2] == C.SEM_CREDIT:
            k = state["k"]
            state["k"] += 1
            if k == nth:
                return [action, action]
        return [action]

    return _transformed(gen, fn)


def reuse_slots(gen: Iterator, slot_map: Callable[[int], int]):
    """Remap the physical slot ADDRESS of every dma / read / write
    while leaving semaphore indices untouched — aliased scratch, the
    realistic codegen bug where two logical buffers share one VMEM
    address."""

    def fn(action):
        kind = action[0]
        if kind == "dma":
            _, target, slot, payload, si, ri = action
            return [("dma", target, slot_map(slot), payload, si, ri)]
        if kind == "read_slot":
            return [("read_slot", slot_map(action[1]))]
        if kind == "write_slot":
            return [("write_slot", slot_map(action[1]), action[2])]
        return [action]

    return _transformed(gen, fn)


def delay_grant(gen: Iterator, nth: int = 0):
    """Hold this rank's ``nth`` credit grant until after its next wait
    has completed. Applied to EVERY rank (a shared scheduling bug),
    each rank then waits for a grant its neighbour is holding behind
    the same wait — a genuine cross-rank wait-for CYCLE, not a
    starvation: every grant still exists in some remaining sequence."""
    state = {"k": 0, "held": None}

    def fn(action):
        if action[0] == "signal" and action[2] == C.SEM_CREDIT:
            k = state["k"]
            state["k"] += 1
            if k == nth:
                state["held"] = action
                return []
        out = [action]
        if action[0] == "wait" and state["held"] is not None:
            out.append(state["held"])
            state["held"] = None
        return out

    return _transformed(gen, fn)


#: Mutant registry. The first three are the acceptance matrix
#: (dropped wait -> StaticDeadlock, reused slot -> slot race,
#: unbalanced grant -> credit-conservation); ``late_grant`` is the
#: cyclic-deadlock shape (a wait-for cycle rather than a starved wait).
MUTANTS = ("dropped_wait", "reused_slot", "unbalanced_grant",
           "late_grant")


# ---------------------------------------------------------------------------
# Control-plane mutants: the model checker's falsifiability story
# ---------------------------------------------------------------------------
#
# Each one breaks exactly one seam of the model's frontend glue — or
# swaps one REAL object for a broken subclass — and must be convicted
# by exactly its named property
# (:data:`smi_tpu.analysis.properties.PROPERTIES`), with the minimal
# counterexample trace replaying as a failing campaign cell
# (``smi_tpu.serving.campaign.replay_model_trace``).


def _model_world_base():
    from smi_tpu.analysis.model import World

    return World


def _leaked_stream_credit_world():
    """``leaked_stream_credit``: a completed stream's credit never
    returns to the admission pool (the release call is lost, e.g. an
    exception path skipping it). Conviction: ``stream-credit`` — the
    pool holds more credits than accepted-incomplete streams at the
    first completion."""
    World = _model_world_base()

    class _LeakedStreamCredit(World):
        def _release_credit(self, st):
            pass  # the completed stream's credit is never released

    return _LeakedStreamCredit


def _skipped_aging_world():
    """``skipped_aging``: the scheduler ships without the starved-first
    ordering term (the aging bump is skipped), so strict class priority
    can pass a ready low-class stream over without bound. Conviction:
    ``starvation`` — a stream's skip counter crosses the aging bound
    plus the concurrent-stream slack."""
    from smi_tpu.serving.qos import CLASS_PRIORITY
    from smi_tpu.serving.scheduler import StreamScheduler

    World = _model_world_base()

    class _NoAgingScheduler(StreamScheduler):
        def _order(self, eligible):
            return sorted(
                eligible,
                key=lambda s: (CLASS_PRIORITY[s.request.qos], s.index),
            )

    class _SkippedAging(World):
        def _make_scheduler(self, scope):
            return _NoAgingScheduler(check_deadlines=False,
                                     max_starve_rounds=scope.starve)

    return _SkippedAging


def _epoch_bump_without_void_world():
    """``epoch_bump_without_void``: the failover bumps the epoch and
    reroutes the stream but skips ``ProgressLog.void_deliveries`` (and
    the delivery/lane-epoch reset), so the dead consumer's partial
    deliveries are silently folded into the rerouted stream.
    Conviction: ``epoch-safety`` — an active stream retains deliveries
    recorded at the dead rank under the old lane epoch."""
    World = _model_world_base()

    class _EpochBumpWithoutVoid(World):
        def _reroute_stream(self, st, owner):
            st.dst = owner  # ...but the dead rank's deliveries remain

    return _EpochBumpWithoutVoid


def _heartbeat_after_confirm_world():
    """``heartbeat_after_confirm``: a killed rank keeps heartbeating
    (the zombie NIC — the host crashed mid-consume but its heartbeat
    path survived), so phi never accrues and the detector can never
    confirm the death. Conviction: ``lost-accepted`` — a stream parked
    on the zombie destination can never complete or fail over."""
    World = _model_world_base()

    class _HeartbeatAfterConfirm(World):
        def _beat_ranks(self):
            return sorted(self.view.members)  # killed ranks beat too

    return _HeartbeatAfterConfirm


def _swap_without_quiesce_world():
    """``swap_without_quiesce``: the swap driver's drain census lies —
    the plan installs while streams keyed to the old plan are still in
    flight (the quiesce step is skipped). Only reachable on ``retune``
    scopes; benign elsewhere. Conviction: ``plan-epoch-safety`` — an
    active stream still carries the retired plan epoch after the
    install, with the BFS-minimal trace admit -> propose -> quiesce ->
    swap."""
    World = _model_world_base()

    class _SwapWithoutQuiesce(World):
        def _swap_ready(self):
            return True  # ...regardless of the drain set

    return _SwapWithoutQuiesce


def _rollback_discards_entry_world():
    """``rollback_discards_entry``: the abort path drops the plan-cache
    entry instead of leaving/restoring the pre-proposal plan — traffic
    keyed to the plan has nothing to run under. Conviction:
    ``swap-lost-accepted`` — the cache no longer holds the entry the
    swap machine's outcome dictates."""
    World = _model_world_base()

    class _RollbackDiscardsEntry(World):
        def _rollback_swap(self, reason):
            self.swap.rollback(reason)
            # ...and the entry goes with it (the defect)
            self.plan_cache.entries.pop(
                self.swap.key.signature(), None
            )

    return _RollbackDiscardsEntry


def _cutover_without_handoff_world():
    """``cutover_without_handoff``: the migration driver's readiness
    check lies — the cutover fires straight from ``draining`` without
    waiting for the handoff shard, so the destination restores from
    nothing and every chunk the source had already delivered is gone.
    Only reachable on ``migrate`` scopes; benign elsewhere.
    Conviction: ``migration-lost-accepted`` — ``mig_lost`` counts the
    delivered state that never crossed."""
    World = _model_world_base()

    class _CutoverWithoutHandoff(World):
        def _cutover_ready(self):
            # ...whether or not the shard was ever packed (the defect)
            return self.migration["state"] in ("draining", "handoff")

    return _CutoverWithoutHandoff


def _scale_in_with_residents_world():
    """``scale_in_with_residents``: the scale-in victim census lies —
    the controller parks a rank without checking for resident streams
    or in-flight frames, stranding accepted work on a non-member.
    Only reachable on ``migrate`` scopes; benign elsewhere.
    Conviction: ``placement-epoch-safety`` — an active stream's
    destination is no longer a member."""
    World = _model_world_base()

    class _ScaleInWithResidents(World):
        def _scale_in_ok(self, rank):
            return True  # ...residents or not (the defect)

    return _ScaleInWithResidents


def _actuate_without_quorum_world():
    """``actuate_without_quorum``: the failover driver's quorum census
    lies — the partitioned rank is failed over even when the side the
    control plane can reach is a minority (the both-sides-minority
    n=2 cut, where the honest world parks every actuator until the
    heal). Only reachable on ``partition`` scopes; benign elsewhere,
    and benign on partition scopes whose reachable side genuinely IS
    a quorum (the lie then agrees with the truth). Conviction:
    ``fenced-actuation`` — the actuation log records a trigger pulled
    with fewer reachable members than ``quorum_size(members)``."""
    World = _model_world_base()

    class _ActuateWithoutQuorum(World):
        def _quorum_ok(self):
            return True  # ...majority reachable or not (the defect)

    return _ActuateWithoutQuorum


def _accept_in_minority_world():
    """``accept_in_minority``: the cut rank ignores its lapsed quorum
    lease and keeps accepting new streams on the stale side. Only
    reachable on ``partition`` scopes; benign on the n=2 cut (no
    quorate majority exists to fail the rank over, so the stale claim
    never collides with an heir). Conviction: ``no-split-brain`` — on
    the n=3 scope the majority legitimately fails the cut rank over,
    and the stale claim plus the heir are two primaries for one
    tenant in one epoch."""
    World = _model_world_base()

    class _AcceptInMinority(World):
        def _accept_ok(self):
            return True  # ...parked or not (the defect)

    return _AcceptInMinority


def _decode_failover_without_kv_handoff_world():
    """``decode_failover_without_kv_handoff``: the decode failover
    treats a generating request like a stateless transport — it
    reroutes the stream to the heir (the kill-scope replay path) but
    never restores the request's resident KV shards there, leaving
    them stranded on the dead rank. The two recovery paths (prefill
    replay vs KV handoff) are confused. Only reachable on ``infer``
    scopes; benign elsewhere. Conviction: ``kv-shard-safety`` — at
    the confirm, the residency map names a non-member rank."""
    World = _model_world_base()

    class _DecodeFailoverWithoutKvHandoff(World):
        def _kv_failover(self, st, heir):
            # ...the stateless-replay path, shards left behind
            self._reroute_stream(st, heir)

    return _DecodeFailoverWithoutKvHandoff


def _stale_kv_after_cutover_world():
    """``stale_kv_after_cutover``: the cutover resumes each decode
    from the propose-time (pre-handoff) shard copy instead of the
    shard set packed at handoff — every token the drain emitted is
    rolled back and silently re-generated. Only reachable on
    ``infer`` scopes; benign elsewhere, and benign on arcs whose
    drain emitted nothing (the stale copy then agrees with the
    blob). Conviction: ``generation-lost-accepted`` —
    ``kv_lost_tokens`` counts the forgotten tokens."""
    World = _model_world_base()

    class _StaleKvAfterCutover(World):
        def _kv_resume(self, idx, restored):
            handed = restored.get(idx)
            st = next(s for s in self.active if s.index == idx)
            delivered = (dict(handed[0]) if handed is not None
                         else dict(st.delivered))
            # ...the token cursor from BEFORE the drain (the defect)
            return (delivered, self.kv_arc["stale"][idx])

    return _StaleKvAfterCutover


#: Control-plane mutant registry: name -> World factory.
_MODEL_MUTANT_FACTORIES = {
    "leaked_stream_credit": _leaked_stream_credit_world,
    "skipped_aging": _skipped_aging_world,
    "epoch_bump_without_void": _epoch_bump_without_void_world,
    "heartbeat_after_confirm": _heartbeat_after_confirm_world,
    "swap_without_quiesce": _swap_without_quiesce_world,
    "rollback_discards_entry": _rollback_discards_entry_world,
    "cutover_without_handoff": _cutover_without_handoff_world,
    "scale_in_with_residents": _scale_in_with_residents_world,
    "actuate_without_quorum": _actuate_without_quorum_world,
    "accept_in_minority": _accept_in_minority_world,
    "decode_failover_without_kv_handoff":
        _decode_failover_without_kv_handoff_world,
    "stale_kv_after_cutover": _stale_kv_after_cutover_world,
}

#: The shipped control-plane mutants, in acceptance-matrix order.
MODEL_MUTANTS = tuple(_MODEL_MUTANT_FACTORIES)

#: The exactly-one property each mutant must be convicted by
#: (docs/analysis.md's control-plane mutant table, drift-guarded).
MODEL_MUTANT_PROPERTY = {
    "leaked_stream_credit": "stream-credit",
    "skipped_aging": "starvation",
    "epoch_bump_without_void": "epoch-safety",
    "heartbeat_after_confirm": "lost-accepted",
    "swap_without_quiesce": "plan-epoch-safety",
    "rollback_discards_entry": "swap-lost-accepted",
    "cutover_without_handoff": "migration-lost-accepted",
    "scale_in_with_residents": "placement-epoch-safety",
    "actuate_without_quorum": "fenced-actuation",
    "accept_in_minority": "no-split-brain",
    "decode_failover_without_kv_handoff": "kv-shard-safety",
    "stale_kv_after_cutover": "generation-lost-accepted",
}


def model_mutant_world(mutant: str):
    """The broken-``World`` class for one control-plane mutant — pass
    it to :func:`smi_tpu.analysis.model.check_scope` as
    ``world_factory``."""
    try:
        factory = _MODEL_MUTANT_FACTORIES[mutant]
    except KeyError:
        raise ValueError(
            f"unknown control-plane mutant {mutant!r}; known: "
            f"{list(MODEL_MUTANTS)}"
        ) from None
    return factory()


def mutant_generators(protocol: str, n: int, mutant: str,
                      chunks: int = 3, slices: int = 2,
                      rank: int = 0, nth: int = 0) -> List[Iterator]:
    """Per-rank generators of ``protocol`` with one mutant applied.

    ``dropped_wait`` / ``unbalanced_grant`` damage a single ``rank``
    (a one-rank firmware bug); ``reused_slot`` and ``late_grant``
    apply to EVERY rank (the compiled kernel is shared, so an
    addressing or scheduling bug ships to all of them).
    """
    gens = build_generators(protocol, n, chunks=chunks, slices=slices)
    if mutant == "dropped_wait":
        gens[rank] = drop_grant(gens[rank], nth=nth)
    elif mutant == "unbalanced_grant":
        gens[rank] = duplicate_grant(gens[rank], nth=nth)
    elif mutant == "late_grant":
        gens = [delay_grant(g, nth=nth) for g in gens]
    elif mutant == "reused_slot":
        if protocol == "all_reduce_chunked":
            slot_map = lambda s: s % 2  # noqa: E731 — collapse the pairs
        elif protocol == "allreduce_pod":
            # collapse phase A's double buffer only: the CROSS-phase
            # addresses are genuinely barrier-protected (aliasing them
            # is race-free — the verifier proves it), so the mutant
            # aliases within a phase where only the credits protect
            slot_map = lambda s: 0 if s < 2 else s  # noqa: E731
        else:
            slot_map = lambda s: 0  # noqa: E731 — both buffers at addr 0
        gens = [reuse_slots(g, slot_map) for g in gens]
    else:
        raise ValueError(
            f"unknown mutant {mutant!r}; known: {MUTANTS}"
        )
    return gens
