"""Routing-layer tests: graph construction, egress/ingress tables,
balancing, serialization.

Reference: ``codegen/tests/test_routing.py`` + ``test_routing_table.py`` —
including the exact golden table contents for the two-device and
double-rail chain topologies, and the no-route error case.
"""

import pytest

from smi_tpu.ops.operations import Pop, Push
from smi_tpu.ops.program import Device, Program, ProgramMapping
from smi_tpu.ops.serialization import Topology
from smi_tpu.parallel.routing import (
    EGRESS_LOCAL,
    EGRESS_WIRE,
    Link,
    NoRouteFound,
    build_routing_context,
    deserialize_table,
    egress_link_toward,
    egress_tables,
    ingress_table,
    serialize_table,
    sibling_index,
    write_routing_tables,
)


def make_topology(connections, program, devices=None):
    """Build a Topology from {(dev_str, link): (dev_str, link)} pairs."""
    conn = {}
    devs = set()
    for (a, la), (b, lb) in connections.items():
        da, db = Device.parse(a), Device.parse(b)
        conn[(da, la)] = (db, lb)
        conn[(db, lb)] = (da, la)
        devs.update([da, db])
    if devices is not None:
        devs.update(Device.parse(d) for d in devices)
    mapping = ProgramMapping(
        programs=[program], device_to_program={d: program for d in devs}
    )
    return Topology(connections=conn, mapping=mapping)


def fmt(table, device, link_index):
    """Render an egress table like the reference tests do: code per
    (rank, port), with WIRE/LOCAL/sibling-forward names."""
    out = []
    for row in table.data:
        rendered = []
        for code in row:
            if code == EGRESS_WIRE:
                rendered.append("WIRE")
            elif code == EGRESS_LOCAL:
                rendered.append("LOCAL")
            else:
                # invert sibling numbering for readability: src->dst
                sib = code - 2
                dst = sib if sib < link_index else sib + 1
                rendered.append(f"{link_index}->{dst}")
        out.append(rendered)
    return out


def test_sibling_index():
    assert sibling_index(0, 1) == 0
    assert sibling_index(0, 3) == 2
    assert sibling_index(2, 0) == 0
    assert sibling_index(2, 3) == 2
    with pytest.raises(ValueError):
        sibling_index(1, 1)


def test_egress_two_device_links_1_3():
    """Reference test_cks_table_1: FA/FB joined on links 1 and 3."""
    program = Program([Push(0), Push(1)])
    topo = make_topology(
        {("NA:0", 1): ("NB:0", 1), ("NA:0", 3): ("NB:0", 3)},
        program,
    )
    ctx = build_routing_context(topo)
    fa = Device("NA", 0)  # NA sorts before NB -> rank 0
    assert [str(d) for d in ctx.devices] == ["NA:0", "NB:0"]
    tables = egress_tables(fa, ctx, program)
    assert fmt(tables[Link(fa, 0)], fa, 0) == [
        ["LOCAL", "LOCAL"], ["0->1", "0->1"]]
    assert fmt(tables[Link(fa, 1)], fa, 1) == [
        ["LOCAL", "LOCAL"], ["WIRE", "1->3"]]
    assert fmt(tables[Link(fa, 2)], fa, 2) == [
        ["LOCAL", "LOCAL"], ["2->1", "2->1"]]
    assert fmt(tables[Link(fa, 3)], fa, 3) == [
        ["LOCAL", "LOCAL"], ["WIRE", "WIRE"]]


def test_egress_two_device_links_0_3():
    """Reference test_cks_table_2: joined on links 0 and 3."""
    program = Program([Push(0), Push(1)])
    topo = make_topology(
        {("NA:0", 0): ("NB:0", 0), ("NA:0", 3): ("NB:0", 3)},
        program,
    )
    ctx = build_routing_context(topo)
    fa = Device("NA", 0)
    tables = egress_tables(fa, ctx, program)
    assert fmt(tables[Link(fa, 0)], fa, 0) == [
        ["LOCAL", "LOCAL"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(fa, 1)], fa, 1) == [
        ["LOCAL", "LOCAL"], ["1->0", "1->3"]]
    assert fmt(tables[Link(fa, 2)], fa, 2) == [
        ["LOCAL", "LOCAL"], ["2->0", "2->0"]]
    assert fmt(tables[Link(fa, 3)], fa, 3) == [
        ["LOCAL", "LOCAL"], ["WIRE", "WIRE"]]


def test_ingress_table_slots():
    """Reference test_ckr_table: 5 ops, slot numbering by deal order."""
    program = Program([Push(0), Pop(1), Push(2), Pop(3), Pop(4)])
    topo = make_topology({("na:0", 0): ("nb:0", 0)}, program)
    ctx = build_routing_context(topo)
    dev = Device("na", 0)

    def table(i):
        return ingress_table(Link(dev, i), ctx, program).flat()

    assert table(0) == [0, 3, 4, 0, 0, 5, 1, 0, 2, 0]
    assert table(1) == [0, 3, 1, 0, 0, 1, 4, 0, 2, 0]
    assert table(2) == [0, 3, 1, 0, 0, 1, 2, 0, 4, 0]
    assert table(3) == [0, 4, 1, 0, 0, 1, 2, 0, 3, 0]


def test_no_route_between_partitions():
    """Reference test_cks_no_route: disconnected topology islands."""
    program = Program([Push(0)])
    topo = make_topology(
        {("N0:0", 0): ("N0:1", 0), ("N1:0", 0): ("N1:2", 1)},
        program,
    )
    ctx = build_routing_context(topo)
    with pytest.raises(NoRouteFound):
        egress_tables(Device("N0", 0), ctx, program)


def test_balancing_spreads_across_wires():
    """Two parallel wires between two devices: balanced pass must not put
    every port on one wire (the balanced_routing test's property,
    ``test/balanced_routing``)."""
    program = Program([Push(p) for p in range(4)], p2p_rendezvous=False)
    topo = make_topology(
        {("A:00", 0): ("B:00", 0), ("A:00", 2): ("B:00", 2)},
        program,
    )
    ctx = build_routing_context(topo)
    dev = Device("A", 0)
    tables = egress_tables(dev, ctx, program)
    # each push's out-data stream sits on its own link (deal order), and
    # the balanced exit alternates between wire 0 and wire 2
    exits = set()
    for port in range(4):
        link = Link(dev, port)  # port p allocated to stream p
        code = tables[link][1, port]
        exits.add((link.index, code))
    wire_exits = {
        (0, EGRESS_WIRE),  # link0 exits its own wire
        (2, EGRESS_WIRE),  # link2 exits its own wire
    }
    assert wire_exits <= exits


def test_serialize_round_trip():
    flat = [0, 1, 2, 255, 7]
    assert deserialize_table(serialize_table(flat, 1), 1) == flat
    big = [0, 300, 65535]
    assert deserialize_table(serialize_table(big, 2), 2) == big


def test_write_routing_tables(tmp_path):
    program = Program([Push(0), Pop(0)])
    topo = make_topology({("NA:0", 1): ("NB:0", 1)}, program)
    write_routing_tables(tmp_path, topo)
    files = sorted(p.name for p in tmp_path.iterdir())
    assert "cks-rank0-channel0" in files
    assert "ckr-rank1-channel3" in files
    assert len(files) == 2 * 2 * 4  # two devices x (cks+ckr) x 4 links
    raw = (tmp_path / "cks-rank0-channel0").read_bytes()
    assert len(raw) == 2 * 1  # ranks x ports, 1 byte each


def test_egress_link_toward():
    program = Program([Push(0)])
    topo = make_topology(
        {("NA:0", 1): ("NB:0", 0), ("NB:0", 1): ("NC:0", 0)},
        program,
    )
    ctx = build_routing_context(topo)
    assert len(ctx.devices) == 3
    link, neighbour = egress_link_toward(ctx.devices[0], ctx.devices[-1], ctx)
    assert link == 1  # leaves through the wire on link 1
    assert neighbour == ctx.devices[1]  # first hop is the middle device


DOUBLE_RAIL = {
    ("N1:F0", 1): ("N1:F1", 0),
    ("N1:F0", 3): ("N1:F1", 2),
    ("N1:F1", 1): ("N2:F0", 0),
    ("N1:F1", 3): ("N2:F0", 2),
    ("N2:F0", 1): ("N2:F1", 0),
    ("N2:F0", 3): ("N2:F1", 2),
    ("N2:F1", 1): ("N1:F0", 0),
    ("N2:F1", 3): ("N1:F0", 2),
}


def test_egress_double_rail_ring():
    """Reference test_cks_table_double_rail: 4 devices in a double-rail
    ring; exercises multi-hop forwarding + balancing across both rails."""
    program = Program([Push(0), Pop(0), Push(1), Pop(1)])
    topo = make_topology(DOUBLE_RAIL, program)
    ctx = build_routing_context(topo)
    f0 = Device("N1", 0)
    tables = egress_tables(f0, ctx, program)
    assert fmt(tables[Link(f0, 0)], f0, 0) == [
        ["LOCAL", "LOCAL"], ["0->1", "0->1"], ["WIRE", "WIRE"], ["0->2", "WIRE"]]
    assert fmt(tables[Link(f0, 1)], f0, 1) == [
        ["LOCAL", "LOCAL"], ["WIRE", "1->3"], ["WIRE", "1->0"], ["1->0", "1->0"]]
    assert fmt(tables[Link(f0, 2)], f0, 2) == [
        ["LOCAL", "LOCAL"], ["2->1", "2->1"], ["WIRE", "WIRE"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(f0, 3)], f0, 3) == [
        ["LOCAL", "LOCAL"], ["WIRE", "WIRE"], ["WIRE", "WIRE"], ["3->0", "3->0"]]

    f1 = Device("N1", 1)
    tables = egress_tables(f1, ctx, program)
    assert fmt(tables[Link(f1, 0)], f1, 0) == [
        ["WIRE", "WIRE"], ["LOCAL", "LOCAL"], ["0->1", "0->1"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(f1, 1)], f1, 1) == [
        ["1->0", "1->2"], ["LOCAL", "LOCAL"], ["WIRE", "1->3"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(f1, 2)], f1, 2) == [
        ["WIRE", "WIRE"], ["LOCAL", "LOCAL"], ["2->1", "2->1"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(f1, 3)], f1, 3) == [
        ["3->0", "3->0"], ["LOCAL", "LOCAL"], ["WIRE", "WIRE"], ["WIRE", "WIRE"]]


def test_egress_link_toward_balanced_per_port():
    """With a program, egress_link_toward follows the balanced tables: on
    a double-wire topology different ports exit different wires
    (code-review regression: it must agree with the emitted tables)."""
    program = Program([Push(p) for p in range(4)], p2p_rendezvous=False)
    topo = make_topology(
        {("A:0", 0): ("B:0", 0), ("A:0", 2): ("B:0", 2)},
        program,
    )
    ctx = build_routing_context(topo)
    a, b = ctx.devices
    wires = {
        egress_link_toward(a, b, ctx, program=program, port=p)[0]
        for p in range(4)
    }
    assert wires == {0, 2}  # balanced across both physical wires
    for p in range(4):
        _link, nbr = egress_link_toward(a, b, ctx, program=program, port=p)
        assert nbr == b


def test_stream_count_mismatch_rejected():
    program = Program([Push(0)], num_streams=8)
    topo = make_topology({("A:0", 0): ("B:0", 0)}, program)
    ctx = build_routing_context(topo)
    with pytest.raises(ValueError, match="streams"):
        egress_tables(Device("A", 0), ctx, program)
    with pytest.raises(ValueError, match="streams"):
        ingress_table(Link(Device("A", 0), 0), ctx, program)


def test_unmapped_passthrough_device_rejected():
    program = Program([Push(0)])
    conn = {
        (Device("A", 0), 0): (Device("GHOST", 0), 0),
        (Device("GHOST", 0), 0): (Device("A", 0), 0),
    }
    mapping = ProgramMapping(
        programs=[program], device_to_program={Device("A", 0): program}
    )
    topo = Topology(connections=conn, mapping=mapping)
    with pytest.raises(KeyError, match="GHOST"):
        build_routing_context(topo)
