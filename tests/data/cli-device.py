"""Generated device module for program "cli_program" — do not edit.

Trace-time analog of ``smi_generated_device.cl`` (reference
``codegen/templates/device.cl``): one monomorphized helper per declared
(op, port, dtype) — the reference's rewriter renames user call sites to
exactly such specialized symbols (``codegen/tests/data/
port-expected.cl:5-19``) so each gets its own hardware FIFOs. Under JAX
the specialization itself is free at trace time; what these helpers pin
down is the *manifest*: the declared port, dtype, reduce operator and
buffer size are baked into each symbol, so a program written against
this module cannot drift from the artifacts its routing tables were
built from.
"""

from smi_tpu.ops.serialization import parse_program as _parse_program

_PROGRAM_JSON = r"""{
  "operations": [
    {
      "type": "push",
      "port": 0,
      "data_type": "float",
      "buffer_size": 17,
      "args": {}
    },
    {
      "type": "pop",
      "port": 0,
      "data_type": "float",
      "buffer_size": 17,
      "args": {}
    },
    {
      "type": "reduce",
      "port": 1,
      "data_type": "int",
      "buffer_size": null,
      "args": {
        "op_type": "max"
      }
    },
    {
      "type": "broadcast",
      "port": 2,
      "data_type": "int",
      "buffer_size": null,
      "args": {}
    }
  ],
  "consecutive_reads": 8,
  "max_ranks": 8,
  "p2p_rendezvous": true
}"""

#: The declared operations (the manifest this module was generated from).
PROGRAM = _parse_program(_PROGRAM_JSON)

#: (family, port, stream-usage) -> stream slot, the port allocation the
#: routing tables were built from (``codegen/notes.txt`` deal order).
STREAMS = dict(PROGRAM.allocation)


def _check_channel(channel, port, dtype):
    if channel.port != port or channel.dtype.value != dtype:
        raise ValueError(
            f"channel (port={channel.port}, dtype="
            f"{channel.dtype.value}) used through the specialized "
            f"symbol for port {port}/{dtype}"
        )


def SMI_Open_send_channel_0_float(ctx, src, dst, count):
    """Open the declared port-0 float channel
    (``include/smi/push.h`` analog; buffer size pinned from the
    manifest)."""
    return ctx.open_channel(port=0, src=src, dst=dst, count=count,
                            dtype="float", buffer_size=17)


def SMI_Push_0_float(ctx, channel, data, backend=None):
    """Move the full message through the port-0 channel (the SPMD
    fusion of the reference's per-element Push loop,
    ``templates/push.cl``)."""
    _check_channel(channel, 0, "float")
    return ctx.transfer(channel, data, backend=backend)


def SMI_Open_receive_channel_0_float(ctx, src, dst, count):
    """Open the declared port-0 float channel
    (``include/smi/pop.h`` analog; buffer size pinned from the
    manifest)."""
    return ctx.open_channel(port=0, src=src, dst=dst, count=count,
                            dtype="float", buffer_size=17)


def SMI_Pop_0_float(ctx, channel, data, backend=None):
    """Move the full message through the port-0 channel (the SPMD
    fusion of the reference's per-element Pop loop,
    ``templates/pop.cl``)."""
    _check_channel(channel, 0, "float")
    return ctx.transfer(channel, data, backend=backend)


def SMI_Reduce_1_int(ctx, x, root=0, backend=None):
    """Port-1 int reduce (``templates/reduce.cl`` analog; operator pinned to MAX)."""
    return ctx.reduce(x, root=root, port=1, op="max",
                        backend=backend)


def SMI_Bcast_2_int(ctx, x, root=0, backend=None):
    """Port-2 int broadcast (``templates/broadcast.cl`` analog)."""
    return ctx.bcast(x, root=root, port=2,
                        backend=backend)
