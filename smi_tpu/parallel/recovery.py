"""Self-healing collectives: progress logs, shrink/re-route, resume.

PR 1 made faults *visible* — every injected fault is tolerated or
raised as a named invariant violation. This module makes them
*survivable*: the four ring protocols become restartable, in the
ULFM shrink-and-continue style production MPI stacks use.

The recovery model is standard write-ahead message logging:

- every rank keeps a :class:`ProgressLog` — a durable, sequence-
  numbered record of its original *contribution* (written before the
  collective starts) and of every chunk it has *delivered* (recorded
  as the protocol outputs it). The log is the WAL: it survives a
  crash-stop of the rank's process even though the rank's in-flight
  protocol state does not.
- on a detected fault (:class:`~credits.DeadlockError` from the
  simulator, a :class:`~smi_tpu.utils.watchdog.WatchdogTimeout` at
  runtime, a ``StalledRank``/``DownLink`` verdict), the runtime
  classifies the failure:

  - **crash-stopped ranks** (named "stalled" in the state dump) are
    *shrunk* out — the surviving ring re-forms in original rank order
    and the dead rank's duties pass to its **heir**, the nearest
    surviving successor, which reads the dead rank's durable log;
  - **down links** are *re-routed* — the logical ring re-forms in an
    order where the dead wire's endpoints are no longer neighbours
    (validated against the routing layer's
    :class:`~smi_tpu.parallel.routing.FailureSet` machinery: the cut
    must leave every surviving pair physically routable). When no such
    order exists (rings of 2 or 3), the higher endpoint is shrunk
    instead — the same decision an operator would make;
  - **everything else** (lost/duplicated credits, in-flight payload
    damage caught by the verified transport) is *transient*: the ring
    retries whole, and the retry replays only what the logs say is
    undelivered.

- the collective then **resumes**: the delivery protocols (all_gather,
  neighbour_stream) replay only the union of chunks some survivor is
  missing, served by each chunk's owner (origin rank, or its heir from
  the durable log) over a recovery ring pass; the reduction protocols
  (all_reduce, reduce_scatter) restart from logged *inputs* — partial
  reduction state is never reused, because replaying a non-idempotent
  combine from a partial double-counts — with dead ranks' inputs folded
  into their heirs' contributions.

The invariant ``tests/test_recovery.py`` enforces: after recovery the
survivors' results are **identical to the fault-free run's** — every
original contribution is accounted for, because contributions are
durably logged before the first packet moves.

The chaos soak harness (:func:`chaos_campaign`) sweeps seeded random
multi-fault plans across all protocols and rank counts; any cell that
ends in silent corruption or fails to recover is delta-debugged down
to a minimal reproducing :class:`~faults.FaultPlan`
(:func:`minimize_plan`) and reported in the campaign JSON — the
``python -m smi_tpu chaos`` subcommand.

Pure Python end to end (no JAX import at module load); the runtime
bridge (:func:`failed_ranks_of`, :func:`recover_communicator`) imports
the mesh layer lazily.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from smi_tpu.parallel import credits as C
from smi_tpu.parallel import faults as F

#: Protocols whose resume path is item replay (recovery ring pass of
#: only-undelivered chunks) vs input restart (re-fold logged inputs).
ITEM_PROTOCOLS = ("all_gather", "neighbour_stream")
REDUCE_PROTOCOLS = ("all_reduce", "reduce_scatter")


class UnrecoverableError(RuntimeError):
    """Recovery exhausted its attempts or its survivors.

    Carries the attempt trail so an operator sees every verdict on the
    way down. ``annihilated`` marks the one *expected* unrecoverable
    shape — every rank crash-stopped, nobody left to shrink onto —
    which the chaos campaign books as its own outcome rather than a
    harness failure."""

    def __init__(self, message: str, attempts=None,
                 annihilated: bool = False):
        super().__init__(message)
        self.attempts = attempts or []
        self.annihilated = annihilated


# ---------------------------------------------------------------------------
# Progress logs (the durable WAL)
# ---------------------------------------------------------------------------


class WalCorruptionError(RuntimeError):
    """A progress-log file is damaged beyond its final record.

    A torn *tail* (the last record cut mid-write by a crash) is the
    expected crash shape and is skipped loudly (a ``RuntimeWarning``
    names the file and the dropped record); damage anywhere *before*
    the tail — or a corrupt header — is not a crash artifact and must
    never be silently truncated into a shorter-but-plausible log."""


@dataclasses.dataclass
class ProgressLog:
    """One rank's durable recovery state.

    ``contribution`` is the sequence-0 entry: the rank's original input
    to the collective, written before the first packet moves — which is
    why a crash can never lose a contribution. ``entries`` maps
    globally-unique item keys to delivered payloads, in delivery order
    (``seq`` numbers them). Records are idempotent: replayed deliveries
    of a known key are dropped, so recovery passes may over-deliver
    without corrupting the log.

    :meth:`save`/:meth:`load` persist the log with the repo's shared
    durability discipline (temp file + fsync + atomic rename; one
    CRC-framed record per line), so the WAL survives not just a rank
    crash but a crash *of the writer mid-save*: a reader either sees
    the previous complete file or the new one, and a torn final record
    inside a file (crash between write and rename on filesystems that
    reorder) is skipped loudly, never parsed as garbage.
    """

    rank: int
    contribution: object = None
    entries: Dict = dataclasses.field(default_factory=dict)
    #: Records dropped by :meth:`load` as a torn tail (0 on a clean
    #: load) — the loud part of "skipped loudly".
    torn_records: int = dataclasses.field(default=0, compare=False)

    @property
    def seq(self) -> int:
        """Next sequence number = deliveries so far. The per-entry
        sequence is the insertion order of ``entries`` (dicts preserve
        it): entry N of ``iter(entries)`` was the Nth delivery."""
        return len(self.entries)

    def record(self, key, payload) -> bool:
        if key in self.entries:
            return False
        self.entries[key] = payload
        return True

    def missing(self, expected_keys) -> Set:
        return {k for k in expected_keys if k not in self.entries}

    def void_deliveries(self) -> int:
        """Forget every delivered entry while keeping the durable
        contribution — the input-restart discipline of the reduction
        protocols applied to a failed-over stream: deliveries consumed
        by a dead destination died with its consumer state, so the
        heir's replay must restart from the contribution, never from
        partial delivery records. Returns the number voided (the
        serving front-end books them as replayed chunks)."""
        voided = len(self.entries)
        self.entries.clear()
        return voided

    # -- durability -----------------------------------------------------

    @staticmethod
    def _frame(seq: int, obj) -> str:
        import base64
        import pickle
        import zlib

        blob = base64.b64encode(pickle.dumps(obj)).decode("ascii")
        crc = zlib.crc32(f"{seq}:{blob}".encode()) & 0xFFFFFFFF
        return f"{seq} {crc:08x} {blob}"

    @staticmethod
    def _unframe(line: str):
        """Decode one framed record; raises ``ValueError`` on any
        damage (truncation, bit rot, wrong sequence text)."""
        import base64
        import pickle
        import zlib

        seq_s, crc_s, blob = line.split(" ", 2)
        seq = int(seq_s)
        want = int(crc_s, 16)
        got = zlib.crc32(f"{seq}:{blob}".encode()) & 0xFFFFFFFF
        if want != got:
            raise ValueError(
                f"record {seq}: crc {got:#010x} != framed {want:#010x}"
            )
        return seq, pickle.loads(base64.b64decode(blob))

    def save(self, path: str) -> str:
        """Persist the WAL atomically (temp + fsync + rename)."""
        from smi_tpu.parallel.checkpoint import write_atomic

        lines = [f"smi-tpu-wal v1 rank {self.rank}"]
        lines.append(self._frame(0, ("contribution", self.contribution)))
        for i, (key, payload) in enumerate(self.entries.items()):
            lines.append(self._frame(i + 1, ("entry", key, payload)))
        write_atomic(path, ("\n".join(lines) + "\n").encode())
        return path

    @classmethod
    def load(cls, path: str) -> "ProgressLog":
        """Load a WAL, skipping a torn final record loudly.

        A record that fails its CRC (or will not parse at all) ends the
        log: if it is the *last* record in the file it is the torn tail
        of an interrupted append — dropped with a ``RuntimeWarning``
        naming the file and counted in ``torn_records``; anything
        damaged before the tail raises :class:`WalCorruptionError`.
        """
        import warnings

        with open(path) as f:
            raw = f.read().split("\n")
        lines = [l for l in raw if l]
        if not lines or not lines[0].startswith("smi-tpu-wal v1 rank "):
            raise WalCorruptionError(
                f"{path!r} is not a smi-tpu WAL (bad header)"
            )
        try:
            rank = int(lines[0].rsplit(" ", 1)[1])
        except ValueError as e:
            raise WalCorruptionError(
                f"{path!r} header names no rank "
                f"({lines[0]!r}): damaged header"
            ) from e
        records = []
        torn = 0
        for i, line in enumerate(lines[1:]):
            try:
                seq, obj = cls._unframe(line)
                if seq != len(records):
                    raise ValueError(
                        f"sequence skip: expected {len(records)}, "
                        f"got {seq}"
                    )
            except (ValueError, KeyError, EOFError) as e:
                if i == len(lines) - 2:
                    torn = 1
                    warnings.warn(
                        f"progress log {path!r}: final record is torn "
                        f"({e}); dropping it — the WAL prefix of "
                        f"{len(records)} record(s) is intact",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    break
                raise WalCorruptionError(
                    f"{path!r} record {i} is damaged before the tail "
                    f"({e}); refusing to truncate a WAL mid-file"
                ) from e
            records.append(obj)
        if not records or records[0][0] != "contribution":
            raise WalCorruptionError(
                f"{path!r} is missing its contribution record "
                f"(sequence 0) — the one entry a WAL must never lose"
            )
        log = cls(rank, contribution=records[0][1])
        for obj in records[1:]:
            _tag, key, payload = obj
            log.record(key, payload)
        log.torn_records = torn
        return log


def logged_steps(gen, log: ProgressLog, item_of: Callable):
    """Adapter recording every delivered ``output`` into the progress
    log before it leaves the rank. ``item_of(key, payload)`` maps a
    protocol output to its globally-unique log entry ``(key, payload)``
    — or None to drop it (padding chunks of a resumed stream)."""
    value = None
    while True:
        try:
            action = gen.send(value)
        except StopIteration:
            return
        if action[0] == "output":
            item = item_of(action[1], action[2])
            if item is not None:
                log.record(item[0], item[1])
        value = yield action


# ---------------------------------------------------------------------------
# Protocol item model: inputs, expected results, ownership
# ---------------------------------------------------------------------------


def canonical_inputs(protocol: str, n: int, chunks: int) -> Dict[int, object]:
    """The per-rank contributions the verdict harnesses circulate —
    recovery uses the same payloads so its fault-free results are
    bit-comparable with :mod:`faults`' matrix."""
    if protocol == "all_gather":
        return {r: f"chunk{r}" for r in range(n)}
    if protocol == "all_reduce":
        return {r: frozenset([r]) for r in range(n)}
    if protocol == "reduce_scatter":
        return {r: tuple(frozenset([(r, b)]) for b in range(n))
                for r in range(n)}
    if protocol == "neighbour_stream":
        return {r: tuple((r, c) for c in range(chunks)) for r in range(n)}
    raise ValueError(
        f"unknown protocol {protocol!r}; known: {F.PROTOCOLS}"
    )


def expected_results(protocol: str, n: int,
                     inputs: Dict[int, object],
                     chunks: int) -> Dict[int, Dict]:
    """The fault-free result at every rank, computed analytically —
    the yardstick every recovered run must match exactly."""
    if protocol == "all_gather":
        full = {o: inputs[o] for o in range(n)}
        return {r: dict(full) for r in range(n)}
    if protocol == "all_reduce":
        total = frozenset().union(*inputs.values())
        return {r: {0: total} for r in range(n)}
    if protocol == "reduce_scatter":
        return {
            r: {r: frozenset().union(
                *(inputs[src][r] for src in range(n))
            )}
            for r in range(n)
        }
    if protocol == "neighbour_stream":
        out: Dict[int, Dict] = {}
        for r in range(n):
            up = (r - 1) % n
            out[r] = {(up, c): (up, c) for c in range(chunks)}
        return out
    raise ValueError(f"unknown protocol {protocol!r}")


def _item_of_fn(protocol: str, me_global: int,
                survivors: Optional[Sequence[int]] = None) -> Callable:
    """Output→log-item mapping per protocol (global keys)."""
    if protocol == "all_gather":
        return lambda key, payload: (key, payload)
    if protocol == "neighbour_stream":
        # payload IS (origin, chunk_index): self-keying
        return lambda key, payload: (payload, payload)
    if protocol == "all_reduce":
        return lambda key, payload: (0, payload)
    if protocol == "reduce_scatter":
        # resumed rings are smaller: local output index j maps back to
        # the survivor's global rank
        def rs_item(key, payload):
            g = survivors[key] if survivors is not None else key
            return (g, payload) if g == me_global else None
        return rs_item
    raise ValueError(f"unknown protocol {protocol!r}")


# ---------------------------------------------------------------------------
# Failure classification and ring re-planning
# ---------------------------------------------------------------------------


def failed_ranks_of(error, survivors: Optional[Sequence[int]] = None
                    ) -> Set[int]:
    """Crash-stopped ranks named by a detected failure.

    Reads the per-rank protocol-state dump attached to simulator
    :class:`~credits.DeadlockError`\\ s and runtime
    :class:`~smi_tpu.utils.watchdog.WatchdogTimeout`\\ s (``.state``):
    every rank the dump marks ``"stalled"``. ``survivors`` maps the
    dump's ring-local indices back to global ranks on resumed rings.
    """
    state = getattr(error, "state", None)
    if not isinstance(state, dict):
        return set()
    failed = set()
    for k, v in state.items():
        if isinstance(k, int) and isinstance(v, dict) \
                and v.get("state") == "stalled":
            failed.add(survivors[k] if survivors is not None else k)
    return failed


def _check_cut_routable(n: int, pair: Tuple[int, int],
                        survivors: Sequence[int]) -> None:
    """Validate a ring-wire cut against the routing layer.

    Builds the 1-D ring topology, declares the dead wire as a
    :class:`~routing.FailureSet`, and asserts every surviving pair
    still routes around it — raising
    :class:`~routing.RouteCutError` (naming the cut) when the failure
    isolates someone. This is the \"re-route via the existing
    FailureSet machinery\" step: the logical ring re-order below is
    only legal because the physical torus still connects the
    survivors.
    """
    from smi_tpu.parallel.routing import (
        FailureSet,
        build_routing_context,
        check_all_pairs_routable,
        grid_topology,
    )

    a, b = sorted(pair)
    if (a + 1) % n != b and (b + 1) % n != a:
        return  # not a ring wire of this topology; nothing to check
    topo = grid_topology(1, n)
    # devices are ranked in grid order; the east wire of device a is
    # the a—a+1 ring link (the wrap link is the east wire of n-1)
    dev = topo.devices[a if (a + 1) % n == b else b]
    cut = FailureSet(links=frozenset({(dev, 0)}))
    ctx = build_routing_context(topo, excluded=cut)
    check_all_pairs_routable(
        ctx, [topo.devices[g] for g in survivors]
    )


def plan_ring(survivors: Sequence[int],
              down_pairs: Sequence[Tuple[int, int]],
              n_original: int) -> Tuple[List[int], Set[int]]:
    """Choose the resumed ring order around the dead wires.

    Returns ``(order, extra_shrunk)``: a cyclic order of (a subset of)
    the survivors in which no down pair is adjacent, plus the ranks
    that had to be shrunk because no such order exists (rings of 2 or
    3 cannot separate a pair). The search is a deterministic
    backtracking walk — rank counts here are single digits.
    """
    order = [r for r in survivors]
    pairs = {tuple(sorted(p)) for p in down_pairs
             if p[0] in order and p[1] in order}
    extra: Set[int] = set()
    while True:
        found = _separating_order(order, pairs)
        if found is not None:
            return found, extra
        # no order separates some pair: shrink the higher endpoint of
        # the first (deterministic) unavoidable pair and retry
        victim = max(sorted(pairs)[0])
        extra.add(victim)
        order = [r for r in order if r != victim]
        pairs = {p for p in pairs if victim not in p}
        if not order:
            raise UnrecoverableError(
                "down links shrunk the ring to nothing"
            )


def _separating_order(ranks: List[int],
                      pairs: Set[Tuple[int, int]]) -> Optional[List[int]]:
    """A cyclic order of ``ranks`` with no pair adjacent, preferring
    the original order (identity when nothing is cut); None if no
    order exists."""
    if not pairs:
        return list(ranks)
    n = len(ranks)
    if n == 1:
        return list(ranks)
    if n == 2:
        return None  # both orders make the pair adjacent

    def bad(a, b):
        return tuple(sorted((a, b))) in pairs

    # fix the first element (cyclic symmetry), try permutations in
    # lexicographic order of the original ranking — deterministic
    head, rest = ranks[0], ranks[1:]
    for perm in itertools.permutations(rest):
        order = [head] + list(perm)
        if any(bad(order[i], order[(i + 1) % n]) for i in range(n)):
            continue
        return order
    return None


def heir_of(rank: int, survivors, n: int) -> int:
    """The nearest surviving successor of ``rank`` on the original
    ring — the rank that inherits its duties (and reads its WAL).

    THE inheritance rule: :meth:`Communicator.heirs` delegates here so
    the simulator's recovery and the runtime bridge's shrink map can
    never drift apart.
    """
    survivors = set(survivors)
    for step in range(1, n + 1):
        cand = (rank + step) % n
        if cand in survivors:
            return cand
    raise UnrecoverableError(f"no surviving heir for rank {rank}")


# ---------------------------------------------------------------------------
# The recovery driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AttemptRecord:
    """What one attempt did and how it ended."""

    ring: Tuple[int, ...]
    verdict: str            # "completed" | "resumed-from-log" | error name
    detail: str = ""
    failed_ranks: Tuple[int, ...] = ()
    replayed_chunks: int = 0
    skipped_chunks: int = 0


@dataclasses.dataclass
class RecoveryOutcome:
    """The end state of a recovered collective."""

    protocol: str
    n: int
    recovered: bool
    survivors: Tuple[int, ...]
    results: Dict[int, Dict]
    expected: Dict[int, Dict]
    attempts: List[AttemptRecord]

    @property
    def ok(self) -> bool:
        """Recovered AND every survivor's result is identical to the
        fault-free run's."""
        return self.recovered and all(
            self.results.get(g) == self.expected[g]
            for g in self.survivors
        )

    @property
    def replayed_chunks(self) -> int:
        """Chunks moved by resume passes (not the first attempt)."""
        return sum(a.replayed_chunks for a in self.attempts[1:])

    @property
    def fault_trail(self) -> List[str]:
        return [a.verdict for a in self.attempts]


def run_with_recovery(
    protocol: str,
    n: int,
    plan: Optional[F.FaultPlan],
    strategy_seed: int = 0,
    chunks: int = 5,
    max_attempts: int = 5,
    followup_plans: Sequence[Optional[F.FaultPlan]] = (),
    membership=None,
    recorder=None,
) -> RecoveryOutcome:
    """Run one ring collective under a fault plan and heal it to
    completion.

    Attempt 1 runs the real protocol over the full ring (verified
    transport + progress logging). Each detected failure is classified
    (shrink / re-route / transient retry, see the module docstring)
    and the collective resumes, replaying only undelivered chunks.
    ``followup_plans[k]`` injects a fresh fault plan into resume
    attempt ``k+2`` (ring-local rank indices) — the double-fault
    torture tests. A resumed run that completes with results different
    from the fault-free run raises :class:`faults.SilentCorruption`;
    exhausting ``max_attempts`` raises :class:`UnrecoverableError`.

    ``membership`` (a
    :class:`~smi_tpu.parallel.membership.MembershipView`) makes
    failure knowledge *proactive* instead of purely error-parsed:
    ranks the phi-accrual detector has already confirmed dead are
    shrunk out **before the first attempt** — the collective never
    even tries the ring that would deadlock — and each later attempt
    re-consults the view, unioning its dead set with whatever the
    raised error's state dump names. The error-parsing path is
    unchanged when no view is given.

    ``recorder`` (duck-typed flight recorder,
    :class:`smi_tpu.obs.events.FlightRecorder`) rides into every
    attempt's simulator — wire-level events — and each recovery
    transition emits a ``ctl.recover`` control-plane event (tick =
    attempt number, reason = the attempt verdict), so a healed run's
    history shows WHY it took the attempts it took.
    """
    inputs = canonical_inputs(protocol, n, chunks)
    expected = expected_results(protocol, n, inputs, chunks)
    logs = {r: ProgressLog(r, contribution=inputs[r]) for r in range(n)}
    survivors: List[int] = list(range(n))
    down_pairs: Set[Tuple[int, int]] = set()
    attempts: List[AttemptRecord] = []
    current_plan: Optional[F.FaultPlan] = plan
    followups = list(followup_plans)

    pre_shrunk = False
    for attempt in range(max_attempts):
        first = attempt == 0
        if membership is not None:
            known_dead = {
                r for r in survivors if r in membership.dead
            }
            if known_dead:
                pre_shrunk = pre_shrunk or first
                survivors = [
                    r for r in survivors if r not in known_dead
                ]
                if not survivors:
                    raise UnrecoverableError(
                        f"{protocol}: membership confirmed every rank "
                        f"dead", attempts, annihilated=True,
                    )
                attempts.append(AttemptRecord(
                    ring=tuple(survivors),
                    verdict="membership-shrink",
                    detail=(
                        f"detector confirmed {sorted(known_dead)} dead "
                        f"before any attempt"
                        if first else
                        f"detector confirmed {sorted(known_dead)} dead"
                    ),
                    failed_ranks=tuple(sorted(known_dead)),
                ))
        # a membership pre-shrink makes even attempt 1 a RESUME pass:
        # the dead ranks' logged contributions must be served by their
        # heirs, which the fresh-run builder does not do
        fresh = first and not pre_shrunk
        ring, extra = plan_ring(survivors, down_pairs, n)
        if extra:
            survivors = [r for r in survivors if r not in extra]
            ring = [r for r in ring if r not in extra]
        total = sum(len(expected[g]) for g in survivors)
        done = total - sum(
            len(logs[g].missing(expected[g])) for g in survivors
        )
        if not fresh and done == total:
            # resume after the last chunk: every survivor's log is
            # already complete — nothing to replay, no network pass
            attempts.append(AttemptRecord(
                ring=tuple(ring), verdict="resumed-from-log",
                detail="all chunks already delivered",
                replayed_chunks=0, skipped_chunks=done,
            ))
            break
        if len(ring) == 1:
            _assemble_single(protocol, ring[0], logs, expected, n)
            attempts.append(AttemptRecord(
                ring=tuple(ring), verdict="completed",
                detail="single survivor: assembled locally from WALs",
                replayed_chunks=len(expected[ring[0]]),
                skipped_chunks=done,
            ))
            break
        gens, moved = _build_attempt(
            protocol, ring, survivors, logs, inputs, expected,
            n, chunks, fresh,
        )
        entries_before = sum(len(logs[g].entries) for g in survivors)
        # keep known-dead wires enforced in resumed attempts (mapped
        # to the ring's local indices): a buggy re-route then fails
        # loudly as a deadlock instead of silently using a dead link
        effective_plan = current_plan
        if down_pairs and not fresh:
            local = frozenset(
                (ring.index(a), ring.index(b))
                for a, b in down_pairs if a in ring and b in ring
            )
            if local:
                base = current_plan if current_plan is not None \
                    else F.FaultPlan()
                effective_plan = dataclasses.replace(
                    base, down_links=frozenset(base.down_links) | local
                )
        try:
            C.RingSimulator(
                gens, C.Strategy(strategy_seed + attempt),
                faults=effective_plan, recorder=recorder,
            ).run()
        except F.DETECTED_ERRORS as e:
            failed = failed_ranks_of(e, ring)
            # `fresh`, not `first`: the simulator applies plan indices
            # to ring-local slots, and after a membership pre-shrink
            # attempt 1's ring is already a subset — booking the
            # local pair as global would blame the wrong wire
            newly_down = _down_pairs_of(current_plan, ring, fresh)
            # a failed attempt books only what it actually DELIVERED
            # before the fault (the log delta), never its planned
            # replay size — the retry re-moves the rest and would
            # otherwise double-count
            delivered = sum(
                len(logs[g].entries) for g in survivors
            ) - entries_before
            attempts.append(AttemptRecord(
                ring=tuple(ring), verdict=type(e).__name__,
                detail=str(e).splitlines()[0],
                failed_ranks=tuple(sorted(failed)),
                replayed_chunks=0 if fresh else delivered,
            ))
            if recorder is not None:
                recorder.emit(
                    "ctl.recover", attempt, protocol=protocol,
                    reason=type(e).__name__,
                    failed=str(sorted(failed)),
                )
            if failed:
                survivors = [r for r in survivors if r not in failed]
                if not survivors:
                    raise UnrecoverableError(
                        f"{protocol}: every rank crash-stopped",
                        attempts, annihilated=True,
                    )
            if newly_down:
                for pair in newly_down:
                    _check_cut_routable(n, pair, survivors)
                down_pairs |= newly_down
            # transient faults are consumed by the retry; permanent
            # topology damage now lives in survivors/down_pairs
            current_plan = followups.pop(0) if followups else None
            continue
        attempts.append(AttemptRecord(
            ring=tuple(ring), verdict="completed",
            detail="" if fresh else "resume pass",
            replayed_chunks=0 if fresh else moved,
            skipped_chunks=0 if fresh else done,
        ))
        if recorder is not None and not fresh:
            recorder.emit("ctl.recover", attempt, protocol=protocol,
                          reason="resume-completed")
        break
    else:
        raise UnrecoverableError(
            f"{protocol} n={n}: no clean attempt within "
            f"{max_attempts} tries", attempts,
        )

    results = {
        g: {k: logs[g].entries[k] for k in expected[g]
            if k in logs[g].entries}
        for g in survivors
    }
    outcome = RecoveryOutcome(
        protocol=protocol, n=n,
        recovered=True,
        survivors=tuple(survivors),
        results=results, expected=expected, attempts=attempts,
    )
    if not outcome.ok:
        raise F.SilentCorruption(
            f"{protocol} n={n}: recovery completed with wrong results "
            f"under {plan!r}: trail {outcome.fault_trail}"
        )
    return outcome


def _down_pairs_of(plan: Optional[F.FaultPlan], ring: Sequence[int],
                   first: bool) -> Set[Tuple[int, int]]:
    """Global down pairs a plan declares (attempt-1 plans are global;
    follow-up plans index the resumed ring)."""
    if plan is None:
        return set()
    pairs = set()
    for a, b in plan.down_links:
        if first:
            pairs.add(tuple(sorted((a, b))))
        else:
            pairs.add(tuple(sorted((ring[a % len(ring)],
                                    ring[b % len(ring)]))))
    return pairs


# ---------------------------------------------------------------------------
# Attempt construction: first run + resume passes
# ---------------------------------------------------------------------------


def _owners(survivors: Sequence[int], n: int) -> Dict[int, int]:
    """origin rank -> surviving executor (itself, or its heir)."""
    return {
        o: (o if o in survivors else heir_of(o, survivors, n))
        for o in range(n)
    }


def _wrap(gen, me_local: int, log: ProgressLog, item_of: Callable):
    """Framing outside, logging inside: outputs are logged in
    delivered (unwrapped) form, payloads framed on the wire."""
    return C.verified_steps(logged_steps(gen, log, item_of), me_local)


def _build_attempt(protocol, ring, survivors, logs, inputs, expected,
                   n, chunks, first):
    """Generators for one attempt and the number of chunks it moves.

    First attempt: the genuine protocol over the full ring. Resume
    attempts: delivery protocols run a recovery ring pass carrying
    only the union of undelivered items (each served by its owner);
    reduction protocols restart from logged inputs with dead ranks'
    contributions folded into their heirs'.
    """
    if first:
        if protocol == "all_gather":
            gens = [
                _wrap(C.all_gather_rank(j, len(ring), inputs[g]),
                      j, logs[g], _item_of_fn(protocol, g))
                for j, g in enumerate(ring)
            ]
            return gens, n * n
        if protocol == "neighbour_stream":
            gens = [
                _wrap(C.neighbour_stream_rank(j, len(ring),
                                              list(inputs[g])),
                      j, logs[g], _item_of_fn(protocol, g))
                for j, g in enumerate(ring)
            ]
            return gens, n * chunks
        if protocol == "all_reduce":
            gens = [
                _wrap(C.all_reduce_rank(j, len(ring), inputs[g],
                                        lambda a, b: a | b),
                      j, logs[g], _item_of_fn(protocol, g))
                for j, g in enumerate(ring)
            ]
            return gens, n
        if protocol == "reduce_scatter":
            gens = [
                _wrap(C.reduce_scatter_rank(j, len(ring),
                                            list(inputs[g]),
                                            lambda a, b: a | b),
                      j, logs[g], _item_of_fn(protocol, g))
                for j, g in enumerate(ring)
            ]
            return gens, n * n
        raise ValueError(f"unknown protocol {protocol!r}")

    owners = _owners(survivors, n)
    if protocol in ITEM_PROTOCOLS:
        # union of items some survivor is still missing, each served
        # once by its origin's executor over a recovery all_gather
        union = frozenset().union(
            *(logs[g].missing(expected[g]) for g in survivors)
        ) if survivors else frozenset()
        bundles = {g: [] for g in survivors}
        for key in sorted(union, key=repr):
            origin = key if protocol == "all_gather" else key[0]
            payload = (inputs[origin] if protocol == "all_gather"
                       else key)
            bundles[owners[origin]].append((key, payload))

        def bundle_item(me_global):
            def item(_key, bundle):
                for k, p in bundle:
                    logs[me_global].record(k, p)
                return None  # recorded inline; nothing else to log
            return item

        gens = [
            _wrap(C.all_gather_rank(j, len(ring),
                                    tuple(bundles[g])),
                  j, logs[g], bundle_item(g))
            for j, g in enumerate(ring)
        ]
        return gens, len(union)

    # reduction protocols: restart from durable inputs, heirs fold the
    # dead ranks' logged contributions into their own
    folded: Dict[int, object] = {}
    for o in range(n):
        executor = owners[o]
        contribution = logs[o].contribution
        if protocol == "all_reduce":
            prev = folded.get(executor, frozenset())
            folded[executor] = prev | contribution
        else:  # reduce_scatter: fold per-destination blocks
            prev = folded.get(
                executor, tuple(frozenset() for _ in range(n))
            )
            folded[executor] = tuple(
                p | b for p, b in zip(prev, contribution)
            )
    if protocol == "all_reduce":
        gens = [
            _wrap(C.all_reduce_rank(j, len(ring), folded[g],
                                    lambda a, b: a | b),
                  j, logs[g], _item_of_fn(protocol, g))
            for j, g in enumerate(ring)
        ]
        return gens, len(ring)
    # reduce_scatter over the resumed ring: local block k targets the
    # survivor at ring position k (dead destinations need no output)
    gens = []
    for j, g in enumerate(ring):
        blocks = [folded[g][ring[k]] for k in range(len(ring))]
        gens.append(
            _wrap(C.reduce_scatter_rank(j, len(ring), blocks,
                                        lambda a, b: a | b),
                  j, logs[g], _item_of_fn(protocol, g, survivors=ring))
        )
    return gens, len(ring)


def _assemble_single(protocol, g, logs, expected, n):
    """A ring of one: every origin's executor is the lone survivor, so
    the result assembles locally from the durable WALs — the deepest
    shrink the model supports."""
    if protocol == "all_gather":
        for o in range(n):
            logs[g].record(o, logs[o].contribution)
    elif protocol == "neighbour_stream":
        for key in logs[g].missing(expected[g]):
            logs[g].record(key, key)
    elif protocol == "all_reduce":
        total = frozenset().union(
            *(logs[o].contribution for o in range(n))
        )
        logs[g].record(0, total)
    elif protocol == "reduce_scatter":
        block = frozenset().union(
            *(logs[o].contribution[g] for o in range(n))
        )
        logs[g].record(g, block)
    else:
        raise ValueError(f"unknown protocol {protocol!r}")


# ---------------------------------------------------------------------------
# Runtime bridge: shrink a live communicator after a detected failure
# ---------------------------------------------------------------------------


def recover_communicator(comm, error_or_ranks):
    """ULFM shrink for the runtime layer: build the surviving
    communicator after a detected failure.

    ``error_or_ranks`` is either an iterable of failed global ranks or
    a caught error carrying a per-rank state dump
    (:class:`~credits.DeadlockError`,
    :class:`~smi_tpu.utils.watchdog.WatchdogTimeout`) — the stalled
    ranks are extracted with :func:`failed_ranks_of`. Returns
    ``(shrunk_comm, heirs)`` where ``heirs`` maps each failed rank to
    the survivor inheriting its duties (its progress log, its logged
    contribution — :meth:`Communicator.heirs`). Raises ``ValueError``
    when the failure names no ranks (nothing actionable to shrink) —
    a transient fault should be retried, not shrunk.
    """
    if isinstance(error_or_ranks, BaseException):
        failed = failed_ranks_of(error_or_ranks)
    else:
        failed = set(error_or_ranks)
    if not failed:
        raise ValueError(
            "failure names no crash-stopped ranks; retry the "
            "collective instead of shrinking"
        )
    return comm.shrink(failed), comm.heirs(failed)


# ---------------------------------------------------------------------------
# Chaos soak: seeded campaigns + delta-debugged reproducers
# ---------------------------------------------------------------------------


def random_chaos_plan(n: int, seed: int, max_faults: int = 2,
                      classes: Sequence[str] = F.FAULT_CLASSES
                      ) -> F.FaultPlan:
    """A deterministic multi-fault plan: 1..max_faults random faults
    drawn (with class repetition allowed) from ``classes``."""
    rng = random.Random(f"chaos:{n}:{seed}:{max_faults}")
    count = rng.randint(1, max_faults)
    parts = []
    for k in range(count):
        cls = classes[rng.randrange(len(classes))]
        parts.extend(
            F.FaultPlan.random(cls, n, rng.randrange(1 << 30)).faults()
        )
    return F.FaultPlan.of(parts)


def _run_cell(protocol: str, n: int, plan: F.FaultPlan,
              strategy_seed: int, chunks: int = 4
              ) -> Tuple[Optional[RecoveryOutcome], Optional[str]]:
    """One chaos cell: (outcome, None) when it heals clean, else
    (None, one-line reason)."""
    try:
        outcome = run_with_recovery(
            protocol, n, plan, strategy_seed=strategy_seed,
            chunks=chunks,
        )
    except F.SilentCorruption as e:
        return None, f"SilentCorruption: {e}"
    except UnrecoverableError as e:
        if e.annihilated:
            return None, "annihilated"
        return None, f"UnrecoverableError: {e}"
    except Exception as e:  # anything unclassified is a harness bug
        return None, f"{type(e).__name__}: {e}"
    if not outcome.ok:
        return None, "completed with wrong results"
    return outcome, None


def cell_fails(protocol: str, n: int, plan: F.FaultPlan,
               strategy_seed: int, chunks: int = 4) -> Optional[str]:
    """The chaos failure predicate: None when the cell heals clean,
    else a one-line reason (the delta-debugger minimizes against
    this)."""
    return _run_cell(protocol, n, plan, strategy_seed, chunks)[1]


def minimize_plan(plan: F.FaultPlan,
                  fails: Callable[[F.FaultPlan], object]
                  ) -> F.FaultPlan:
    """Delta-debug a failing plan down to a minimal reproducer.

    Greedy ddmin over individual faults: repeatedly drop any fault
    whose removal keeps ``fails`` truthy, until the plan is 1-minimal
    (every remaining fault is necessary). Deterministic — the
    predicate must be (and :func:`cell_fails` is, per seed).
    """
    faults = list(plan.faults())
    changed = True
    while changed and len(faults) > 1:
        changed = False
        for i in range(len(faults)):
            candidate = faults[:i] + faults[i + 1:]
            if fails(F.FaultPlan.of(candidate)):
                faults = candidate
                changed = True
                break
    return F.FaultPlan.of(faults)


def chaos_campaign(
    seed: int,
    protocols: Sequence[str] = F.PROTOCOLS,
    ns: Sequence[int] = (2, 3, 4, 5),
    trials: int = 3,
    max_faults: int = 2,
    chunks: int = 4,
) -> Dict:
    """Run a seeded randomized fault campaign over every protocol and
    ring size; delta-debug any failing cell to a minimal reproducer.

    Returns the JSON-able campaign report: per-outcome histogram, the
    failures with their minimized plans, and ``ok`` /
    ``silent_corruptions`` for the CLI's exit code. Deterministic per
    ``seed`` — a red campaign reproduces from its report alone.
    """
    outcomes: Dict[str, int] = {}
    failures: List[Dict] = []
    cells = 0
    replayed_total = 0
    for protocol in protocols:
        for n in ns:
            for trial in range(trials):
                cells += 1
                # cross-process deterministic (never hash(): PYTHONHASHSEED)
                cell_seed = random.Random(
                    f"{seed}:{protocol}:{n}:{trial}"
                ).randrange(1 << 31)
                plan = random_chaos_plan(n, cell_seed,
                                         max_faults=max_faults)
                outcome, reason = _run_cell(protocol, n, plan,
                                            cell_seed, chunks)
                if reason is None:
                    key = ("healed" if len(outcome.attempts) > 1
                           else "tolerated")
                    outcomes[key] = outcomes.get(key, 0) + 1
                    replayed_total += outcome.replayed_chunks
                    continue
                if reason == "annihilated":
                    # every rank crash-stopped: a NAMED end state with
                    # nobody left to recover onto, not a harness bug
                    outcomes["annihilated"] = (
                        outcomes.get("annihilated", 0) + 1
                    )
                    continue
                outcomes["failed"] = outcomes.get("failed", 0) + 1
                minimal = minimize_plan(
                    plan,
                    lambda p: cell_fails(protocol, n, p, cell_seed,
                                         chunks)
                    not in (None, "annihilated"),
                )
                failures.append({
                    "protocol": protocol, "n": n, "trial": trial,
                    "cell_seed": cell_seed, "reason": reason,
                    "plan": plan.describe(),
                    "minimal_plan": minimal.describe(),
                })
    silent = sum(
        1 for f in failures if f["reason"].startswith("SilentCorruption")
    )
    return {
        "seed": seed,
        "protocols": list(protocols),
        "ns": list(ns),
        "trials": trials,
        "max_faults": max_faults,
        "cells": cells,
        "outcomes": outcomes,
        "replayed_chunks": replayed_total,
        "failures": failures,
        "silent_corruptions": silent,
        "ok": not failures,
    }
