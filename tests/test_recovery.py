"""Self-healing collectives: verified transport + progress-logged resume.

The recovery invariant over all four ring protocols: after a
mid-collective crash-stop, down link, or in-flight payload damage, the
runtime shrinks/re-routes/retries and the survivors' results are
IDENTICAL to the fault-free run — every contribution accounted for,
because contributions are durably logged before the first packet moves.

Pure Python end to end (credit-protocol simulator) except the runtime
bridge tests, which use the 8-device emulator mesh.
"""

import pytest

from smi_tpu.parallel import credits as C
from smi_tpu.parallel import faults as F
from smi_tpu.parallel import recovery as R
from smi_tpu.parallel.routing import RouteCutError

pytestmark = pytest.mark.faults

NS = [2, 3, 4, 5]


# ---------------------------------------------------------------------------
# Verified transport framing
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("protocol", F.PROTOCOLS)
@pytest.mark.parametrize("seed", range(4))
def test_framing_transparent_when_healthy(protocol, seed):
    """The framed transport is behaviourally identical to bare
    transport on healthy runs — delivery verified by the harness."""
    F._simulate(protocol, 4, C.Strategy(seed), None, 5, verified=True)


@pytest.mark.parametrize("fault_class", F.INTEGRITY_FAULT_CLASSES)
@pytest.mark.parametrize("protocol", F.PROTOCOLS)
def test_tampering_detected_as_named_integrity_error(fault_class,
                                                     protocol):
    """Every payload-tampering injection must surface as IntegrityError
    naming the receiving rank, source, chunk, and expected vs got —
    never as silent corruption."""
    plan = F.FaultPlan.random(fault_class, 4, 1)
    verdict = F.run_under_faults(protocol, 4, plan, C.Strategy(0))
    assert verdict.detected
    assert verdict.error_name == "IntegrityError"
    e = verdict.error
    assert e.rank is not None and e.src is not None
    assert e.expected is not None and e.got is not None
    assert e.kind in ("checksum", "sequence")
    if fault_class == "reordered_chunks":
        assert e.kind == "sequence"
    else:
        assert e.kind == "checksum"


@pytest.mark.parametrize("fault_class",
                         ["bit_flip_payload", "truncated_dma"])
def test_bare_transport_corrupts_silently(fault_class):
    """WITHOUT framing the same injections complete with wrong data
    (SilentCorruption from the harness's output check) — the framing
    layer's existence proof. Tolerated is also legal: small runs may
    never issue the targeted nth DMA."""
    silent = 0
    for protocol in F.PROTOCOLS:
        for seed in range(3):
            plan = F.FaultPlan.random(fault_class, 4, seed)
            try:
                v = F.run_under_faults(protocol, 4, plan, C.Strategy(0),
                                       verified=False)
                assert v.tolerated, (protocol, seed, v.kind)
            except F.SilentCorruption:
                silent += 1
    assert silent >= len(F.PROTOCOLS)  # the damage is real, and unseen


def test_frame_crc_keys_identity_and_payload():
    f = C.make_frame(2, 7, "payload")
    assert C.frame_crc(2, 7, True, "payload") == f.crc
    assert C.frame_crc(2, 8, True, "payload") != f.crc
    assert C.frame_crc(3, 7, True, "payload") != f.crc
    assert C.frame_crc(2, 7, True, "payloaX") != f.crc


# ---------------------------------------------------------------------------
# Crash-stop recovery (shrink + heir inheritance)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("protocol", F.PROTOCOLS)
@pytest.mark.parametrize("n", [3, 4, 5])
def test_crash_stop_resumes_identical(protocol, n):
    """A rank crash-stopping mid-collective is shrunk out; the
    survivors resume and end with results identical to the fault-free
    run — the dead rank's contribution recovered from its durable
    log."""
    plan = F.FaultPlan.single(F.StalledRank(1, after=6))
    out = R.run_with_recovery(protocol, n, plan, strategy_seed=0)
    assert out.ok
    assert out.survivors == tuple(r for r in range(n) if r != 1)
    assert out.attempts[0].verdict == "DeadlockError"
    assert 1 in out.attempts[0].failed_ranks
    assert out.attempts[-1].verdict in ("completed", "resumed-from-log")
    # identical-to-fault-free is checked per survivor, exactly
    for g in out.survivors:
        assert out.results[g] == out.expected[g]


@pytest.mark.parametrize("protocol", R.ITEM_PROTOCOLS)
def test_resume_replays_only_undelivered(protocol):
    """A late crash leaves most chunks delivered: the delivery
    protocols' resume pass must move strictly less than the full
    volume (only the union of missing items)."""
    plan = F.FaultPlan.single(F.StalledRank(2, after=20))
    out = R.run_with_recovery(protocol, 5, plan, strategy_seed=0,
                              chunks=6)
    assert out.ok
    total = sum(len(out.expected[g]) for g in out.survivors)
    assert 0 < out.replayed_chunks < total, (
        out.replayed_chunks, total
    )


@pytest.mark.parametrize("protocol", R.REDUCE_PROTOCOLS)
def test_reduce_resume_restarts_from_logged_inputs(protocol):
    """Reduction protocols never reuse partial state (a non-idempotent
    combine would double-count): the resume re-folds the durably
    logged INPUTS — at most one contribution per surviving executor
    moves again."""
    plan = F.FaultPlan.single(F.StalledRank(2, after=20))
    out = R.run_with_recovery(protocol, 5, plan, strategy_seed=0,
                              chunks=6)
    assert out.ok
    assert 0 < out.replayed_chunks <= len(out.survivors)


@pytest.mark.parametrize("protocol", F.PROTOCOLS)
def test_resume_after_last_chunk_replays_nothing(protocol):
    """Satellite edge case: when the fault strikes after every chunk
    was delivered (a rank dies parked at its final waits), the resume
    finds complete logs and replays NOTHING — no second network
    pass."""
    hit = None
    for after in range(8, 120):
        plan = F.FaultPlan.single(F.StalledRank(1, after=after))
        try:
            out = R.run_with_recovery(protocol, 3, plan,
                                      strategy_seed=0, chunks=3)
        except R.UnrecoverableError:
            continue
        if (len(out.attempts) > 1
                and out.attempts[-1].verdict == "resumed-from-log"):
            hit = out
            break
    assert hit is not None, "no stall point with complete logs found"
    assert hit.ok
    assert hit.replayed_chunks == 0


@pytest.mark.parametrize("protocol", F.PROTOCOLS)
def test_double_fault_during_replay(protocol):
    """Satellite edge case: a second crash-stop during the resume pass
    shrinks again and still completes identically."""
    out = R.run_with_recovery(
        protocol, 5, F.FaultPlan.single(F.StalledRank(1, after=4)),
        strategy_seed=3,
        followup_plans=[F.FaultPlan.single(F.StalledRank(2, after=3))],
    )
    assert out.ok
    assert len(out.attempts) == 3
    assert len(out.survivors) == 3
    trail = out.fault_trail
    assert trail[0] == "DeadlockError" and trail[1] == "DeadlockError"


@pytest.mark.parametrize("protocol", F.PROTOCOLS)
def test_shrink_to_single_survivor(protocol):
    """Satellite edge case: n=2 with the peer dead shrinks to ONE rank,
    which assembles the full result locally from the durable WALs."""
    out = R.run_with_recovery(
        protocol, 2, F.FaultPlan.single(F.StalledRank(1, after=2)),
        strategy_seed=0, chunks=3,
    )
    assert out.ok
    assert out.survivors == (0,)
    assert out.results[0] == out.expected[0]


def test_every_rank_dead_is_named_annihilation():
    plan = F.FaultPlan.of(
        [F.StalledRank(0, after=5), F.StalledRank(1, after=5)]
    )
    with pytest.raises(R.UnrecoverableError) as e:
        R.run_with_recovery("all_gather", 2, plan, strategy_seed=0)
    assert e.value.annihilated


# ---------------------------------------------------------------------------
# Down-link recovery (re-route via FailureSet, shrink when impossible)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("protocol", F.PROTOCOLS)
@pytest.mark.parametrize("n", [4, 5, 6])
def test_down_link_reroutes_keeping_all_ranks(protocol, n):
    """With n >= 4 a dead wire is re-routed: the logical ring re-forms
    with the endpoints non-adjacent, EVERY rank survives, and the dead
    wire stays enforced in the resumed run (a buggy re-route would
    deadlock, not silently transit it)."""
    plan = F.FaultPlan.single(F.DownLink(0, 1))
    out = R.run_with_recovery(protocol, n, plan, strategy_seed=1)
    assert out.ok
    assert out.survivors == tuple(range(n))
    ring = out.attempts[-1].ring
    pos = {g: i for i, g in enumerate(ring)}
    gap = abs(pos[0] - pos[1])
    assert gap not in (1, len(ring) - 1), f"0 and 1 adjacent in {ring}"


@pytest.mark.parametrize("protocol", F.PROTOCOLS)
def test_down_link_small_ring_shrinks_endpoint(protocol):
    """A 3-ring cannot separate two ranks; the higher endpoint is
    shrunk (deterministically) and the pair's data is still complete
    at the survivors."""
    plan = F.FaultPlan.single(F.DownLink(0, 1))
    out = R.run_with_recovery(protocol, 3, plan, strategy_seed=1)
    assert out.ok
    assert out.survivors == (0, 2)


def test_separating_order_properties():
    assert R._separating_order([0, 1, 2, 3], set()) == [0, 1, 2, 3]
    order = R._separating_order([0, 1, 2, 3], {(0, 1)})
    pos = {g: i for i, g in enumerate(order)}
    assert abs(pos[0] - pos[1]) not in (1, 3)
    assert R._separating_order([0, 1], {(0, 1)}) is None
    assert R._separating_order([0, 1, 2], {(0, 1)}) is None


def test_cut_routability_check_uses_failure_set():
    """The re-route step validates against the routing layer's
    FailureSet machinery: a single ring-wire cut on a torus of n >= 3
    leaves every surviving pair routable the long way around (no
    raise); the same machinery raises RouteCutError when a failure
    set genuinely isolates a destination (the routing property tests
    cover that shape — here we pin the recovery-side call)."""
    for n in (3, 4, 5, 6):
        R._check_cut_routable(n, (0, 1), list(range(n)))
        R._check_cut_routable(n, (n - 1, 0), list(range(n)))  # wrap wire
    # non-ring-wire pairs (no physical wire to cut) are a no-op
    R._check_cut_routable(5, (0, 2), [0, 1, 2, 3, 4])
    # the named-isolation path: every wire of one device cut
    from smi_tpu.parallel.routing import (
        FailureSet, build_routing_context, check_all_pairs_routable,
        grid_topology,
    )

    topo = grid_topology(1, 4)
    victim = topo.devices[1]
    cut = FailureSet(links=frozenset(
        (dev, li) for (dev, li) in topo.connections if dev == victim
    ))
    ctx = build_routing_context(topo, excluded=cut)
    with pytest.raises(RouteCutError):
        check_all_pairs_routable(ctx, topo.devices)


# ---------------------------------------------------------------------------
# Transient faults: retry-with-replay, full ring preserved
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fault", [
    F.BitFlipPayload(1, nth=0),
    F.TruncatedDma(2, nth=1),
    F.ReorderedChunks(0, nth=0),
    F.DroppedGrant(0, nth=0),
])
@pytest.mark.parametrize("protocol", F.PROTOCOLS)
def test_transient_fault_retries_whole_ring(protocol, fault):
    out = R.run_with_recovery(
        protocol, 4, F.FaultPlan.single(fault), strategy_seed=2,
    )
    assert out.ok
    assert out.survivors == (0, 1, 2, 3)


# ---------------------------------------------------------------------------
# Progress logs
# ---------------------------------------------------------------------------


def test_progress_log_idempotent_and_sequenced():
    log = R.ProgressLog(0, contribution="mine")
    assert log.record("a", 1) and log.seq == 1
    assert not log.record("a", 2)  # replayed delivery dropped
    assert log.entries["a"] == 1 and log.seq == 1
    assert log.missing({"a", "b"}) == {"b"}


def test_expected_results_match_simulator_delivery():
    """The analytic fault-free yardstick agrees with what the real
    protocols deliver — per protocol and rank count."""
    for protocol in F.PROTOCOLS:
        for n in (2, 4):
            chunks = 3
            inputs = R.canonical_inputs(protocol, n, chunks)
            expected = R.expected_results(protocol, n, inputs, chunks)
            out = R.run_with_recovery(protocol, n, None,
                                      strategy_seed=0, chunks=chunks)
            assert len(out.attempts) == 1
            for g in range(n):
                assert out.results[g] == expected[g], (protocol, n, g)


# ---------------------------------------------------------------------------
# Runtime bridge: shrink a live communicator from a caught error
# ---------------------------------------------------------------------------


def test_heirs_mapping():
    jax = pytest.importorskip("jax")
    import smi_tpu as smi

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device emulator mesh")
    comm = smi.make_communicator(8, devices=devices[:8])
    assert comm.heirs({2, 3}) == {2: 4, 3: 4}
    assert comm.heirs({7}) == {7: 0}
    assert comm.heirs({6, 7, 0}) == {6: 1, 7: 1, 0: 1}
    with pytest.raises(ValueError, match="no survivors"):
        comm.heirs(range(8))
    with pytest.raises(ValueError, match="out of range"):
        comm.heirs({9})


def test_recover_communicator_from_deadlock_error():
    jax = pytest.importorskip("jax")
    import smi_tpu as smi

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device emulator mesh")
    comm = smi.make_communicator(8, devices=devices[:8])
    with pytest.raises(C.DeadlockError) as e:
        C.simulate_all_reduce(
            8, C.Strategy(0),
            faults=F.FaultPlan.single(F.StalledRank(5, after=3)),
        )
    small, heirs = smi.recover_communicator(comm, e.value)
    assert small.size == 7
    assert heirs == {5: 6}
    kept = [d for i, d in enumerate(devices[:8]) if i != 5]
    assert list(small.mesh.devices.flat) == kept


def test_recover_communicator_from_watchdog_timeout():
    jax = pytest.importorskip("jax")
    import smi_tpu as smi
    from smi_tpu.utils.watchdog import WatchdogTimeout

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device emulator mesh")
    comm = smi.make_communicator(8, devices=devices[:8])
    # a runtime watchdog timeout whose structured dump names rank 3
    state = {3: {"state": "stalled", "pending": None, "outputs": 0},
             0: {"state": "blocked", "pending": None, "outputs": 1}}
    err = WatchdogTimeout("hang", state=state)
    small, heirs = smi.recover_communicator(comm, err)
    assert small.size == 7 and heirs == {3: 4}
    # a transient failure (no ranks named) must NOT be shrunk
    with pytest.raises(ValueError, match="retry"):
        smi.recover_communicator(comm, WatchdogTimeout("hang"))


def test_failed_ranks_of_maps_ring_local_to_global():
    state = {0: {"state": "blocked"}, 1: {"state": "stalled"},
             2: {"state": "finished"}}
    err = C.DeadlockError("dead", state=state)
    assert R.failed_ranks_of(err) == {1}
    assert R.failed_ranks_of(err, survivors=[0, 3, 4]) == {3}
    assert R.failed_ranks_of(ValueError("no dump")) == set()
