"""Compiled-artifact reports: the ``aoc -report`` analog.

Reference parity: the reference exposes first-class report targets that
run the FPGA toolchain in analysis mode before anyone commits to a full
bitstream build — ``aoc -rtl -report`` for area/Fmax inspection
(``/root/reference/CMakeLists.txt:113-118``). The TPU equivalents exist
in XLA (HLO cost analysis, compiled-executable memory analysis) but are
ordinarily buried behind ``jax.stages`` internals; this module surfaces
them per *program operation*: every (op, port, dtype) a program's
manifest declares is compiled as its runtime collective/channel call and
its executable facts are tabulated, so a user can sanity-check the
resource story of a routed program on the emulator tier — and, given a
topology communicator (``parallel/aot.py``), for a real TPU slice —
before running anything.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from smi_tpu.ops.operations import (
    Broadcast,
    Gather,
    Pop,
    Push,
    Reduce,
    Scatter,
)
from smi_tpu.ops.types import dtype_to_jnp
from smi_tpu.parallel.mesh import Communicator

#: default message length per reported operation (elements)
REPORT_COUNT = 4096


def _compile(comm: Communicator, shard_fn, global_shape, dtype):
    sharding = NamedSharding(comm.mesh, P())
    jitted = jax.jit(
        jax.shard_map(
            shard_fn, mesh=comm.mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        )
    )
    shape = jax.ShapeDtypeStruct(global_shape, dtype, sharding=sharding)
    return jitted.lower(shape).compile()


def _op_call(op, comm: Communicator, count: int, backend: str):
    """(shard_fn, global_shape, jnp dtype) realizing one manifest op."""
    from smi_tpu.parallel import collectives
    from smi_tpu.parallel.channels import P2PChannel

    dt = dtype_to_jnp(op.dtype)
    if isinstance(op, (Push, Pop)):
        ch = P2PChannel(
            comm=comm, port=op.port, src=0, dst=comm.size - 1,
            count=count, dtype=op.dtype, buffer_size=op.buffer_size,
        )
        return (lambda x: ch.transfer(x, backend=backend)), (count,), dt
    if isinstance(op, Broadcast):
        return (
            lambda x: collectives.bcast(
                x, comm, root=0, port=op.port, backend=backend
            ),
            (count,), dt,
        )
    if isinstance(op, Reduce):
        return (
            lambda x: collectives.reduce(
                x, comm, op=op.op, root=0, port=op.port, backend=backend
            ),
            (count,), dt,
        )
    if isinstance(op, Scatter):
        return (
            lambda x: collectives.scatter(
                x, comm, root=0, port=op.port, backend=backend
            ),
            (comm.size * count,), dt,
        )
    if isinstance(op, Gather):
        return (
            lambda x: collectives.gather(
                x, comm, root=0, port=op.port, backend=backend
            ),
            (count,), dt,
        )
    raise ValueError(f"unreportable operation type {type(op).__name__}")


def program_report(
    program,
    comm: Communicator,
    count: int = REPORT_COUNT,
    backend: str = "xla",
) -> dict:
    """Per-operation executable report of a routed program.

    Each manifest operation is compiled as its runtime call over
    ``comm`` and measured with XLA's own cost/memory analyses. ``comm``
    may be a live mesh (emulator tier: numbers describe the CPU
    executable) or an abstract topology communicator
    (``aot.topology_communicator``: numbers describe the real TPU
    executable, no hardware needed).
    """
    from smi_tpu.parallel.aot import executable_report

    seen_p2p_ports = set()
    ops_out = []
    for op in program.operations:
        if isinstance(op, (Push, Pop)):
            # a push/pop pair is ONE channel; report it once per port
            if op.port in seen_p2p_ports:
                continue
            seen_p2p_ports.add(op.port)
        shard_fn, shape, dt = _op_call(op, comm, count, backend)
        compiled = _compile(comm, shard_fn, shape, dt)
        entry = {
            "op": type(op).__name__.lower(),
            "port": op.port,
            "dtype": op.dtype.value,
            "count": count,
            **executable_report(compiled),
        }
        ops_out.append(entry)
    return {
        "backend": backend,
        "comm_size": comm.size,
        "count": count,
        "operations": ops_out,
    }


def format_report(report: dict) -> str:
    """Human-readable table (the ``aoc`` report's summary screen)."""
    lines = [
        f"program report — {report['comm_size']} ranks, "
        f"{report['count']} elements/op, backend={report['backend']}",
        f"{'op':<10} {'port':>4} {'dtype':<7} {'flops':>12} "
        f"{'bytes':>14} {'code':>10} {'temp':>10} {'ici_pred_us':>12}",
    ]
    for e in report["operations"]:
        cost = e.get("cost", {})
        mem = e.get("memory", {})
        # the bandwidth-only v5e wall-clock bound of the op's parsed
        # collectives; '-' where withheld (loop-resident or DCN) or
        # where the op compiled to no collective
        pred = e.get("ici_predicted_us")
        lines.append(
            f"{e['op']:<10} {e['port']:>4} {e['dtype']:<7} "
            f"{cost.get('flops', 0):>12.0f} "
            f"{cost.get('bytes accessed', 0):>14.0f} "
            f"{mem.get('generated_code_bytes', 0):>10} "
            f"{mem.get('temp_bytes', 0):>10} "
            f"{pred if pred is not None else '-':>12}"
        )
    return "\n".join(lines)
