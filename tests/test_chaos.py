"""Chaos soak: seeded randomized FaultPlan campaigns over the
self-healing collectives.

Tier-1 runs the seed-pinned short campaign (pure Python, sub-second);
the long soak rides behind the ``slow`` marker. Every cell must heal
(results identical to the fault-free run), be tolerated, or end in a
named annihilation — a silent corruption or an unclassified error
fails the campaign and ships a delta-debugged minimal reproducer.
"""

import json

import pytest

from smi_tpu.parallel import faults as F
from smi_tpu.parallel import recovery as R

pytestmark = pytest.mark.chaos

#: The tier-1 campaign's pinned seed. Do not bump casually: the whole
#: report is deterministic per seed, so a red run reproduces exactly
#: with ``python -m smi_tpu chaos --seed 1729``.
TIER1_SEED = 1729


def _assert_clean(report):
    assert report["silent_corruptions"] == 0, report["failures"]
    assert report["ok"], report["failures"]
    assert not report["failures"]
    healed = report["outcomes"].get("healed", 0)
    tolerated = report["outcomes"].get("tolerated", 0)
    annihilated = report["outcomes"].get("annihilated", 0)
    assert healed + tolerated + annihilated == report["cells"]
    assert healed > 0  # the campaign actually exercised recovery


def test_tier1_seed_pinned_campaign():
    """The default-test-run campaign: all four protocols, rings of
    2..5, two trials each, up to two faults per plan."""
    report = R.chaos_campaign(seed=TIER1_SEED, ns=(2, 3, 4, 5),
                              trials=2, max_faults=2)
    _assert_clean(report)
    assert report["cells"] == 4 * 4 * 2


def test_campaign_deterministic_per_seed():
    a = R.chaos_campaign(seed=5, ns=(3, 4), trials=2)
    b = R.chaos_campaign(seed=5, ns=(3, 4), trials=2)
    assert a == b
    c = R.chaos_campaign(seed=6, ns=(3, 4), trials=2)
    assert c != a  # different seed, different plans


def test_campaign_report_is_json_roundtrippable():
    report = R.chaos_campaign(seed=2, ns=(3,), trials=1)
    assert json.loads(json.dumps(report)) == report


def test_random_chaos_plan_seeded_and_bounded():
    a = R.random_chaos_plan(4, 99, max_faults=3)
    assert a == R.random_chaos_plan(4, 99, max_faults=3)
    described = a.describe()
    assert described and all(isinstance(s, str) for s in described)
    # every draw is a single fault, so max_faults bounds the plan
    # (DownLink dedup can only shrink it)
    for seed in range(40):
        for max_faults in (1, 2, 3):
            plan = R.random_chaos_plan(5, seed, max_faults=max_faults)
            assert 1 <= len(plan.faults()) <= max_faults, (
                seed, max_faults, plan.describe()
            )


def test_minimizer_shrinks_to_necessary_faults():
    """ddmin against a synthetic predicate: only the DownLink matters,
    so the minimal plan is exactly it."""
    plan = F.FaultPlan.of([
        F.DroppedGrant(0), F.DownLink(1, 2), F.BitFlipPayload(3),
        F.StalledRank(2, after=9),
    ])
    minimal = R.minimize_plan(
        plan,
        lambda p: any(isinstance(f, F.DownLink) for f in p.faults()),
    )
    assert minimal.faults() == (F.DownLink(1, 2),)


def test_minimizer_keeps_conjunction():
    """A failure needing BOTH faults keeps both (1-minimality, not
    emptiness)."""
    plan = F.FaultPlan.of([
        F.DroppedGrant(0), F.StalledRank(1), F.StalledRank(2),
    ])

    def needs_both_stalls(p):
        stalls = [f for f in p.faults() if isinstance(f, F.StalledRank)]
        return len(stalls) >= 2

    minimal = R.minimize_plan(plan, needs_both_stalls)
    assert len(minimal.faults()) == 2
    assert all(isinstance(f, F.StalledRank) for f in minimal.faults())


def test_chaos_cli_writes_report_and_exits_zero(tmp_path, capsys):
    from smi_tpu.__main__ import main

    out = tmp_path / "chaos.json"
    rc = main(["chaos", "--seed", "11", "--ranks", "2", "3",
               "--trials", "1", "-o", str(out)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["ok"] and report["silent_corruptions"] == 0
    assert report["seed"] == 11
    printed = capsys.readouterr().out
    assert "campaign ok" in printed


def test_chaos_cli_rejects_unknown_protocol(capsys):
    from smi_tpu.__main__ import main

    rc = main(["chaos", "--protocols", "ring_of_power"])
    assert rc == 2


@pytest.mark.slow
def test_long_soak():
    """The overnight-shaped soak: bigger rings, more trials, triple
    faults — still zero silent corruptions, every cell named."""
    for seed in range(4):
        report = R.chaos_campaign(seed=seed, ns=(2, 3, 4, 5, 6, 7),
                                  trials=6, max_faults=3)
        _assert_clean(report)
