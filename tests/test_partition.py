"""Partition-tolerance tests: faults, fencing, cells, split-brain.

The r17 robustness arc end to end: the partition-class fault trio
(symmetric cut / one-way asymmetric cut / seeded flapping link), the
``$SMI_TPU_QUORUM_FRACTION`` knob and quorum math, the fencing-token
mint/check matrix (stale tokens rejected on the SAME
``StaleEpochError`` rail as superseded incarnations), the serving
front-end's minority-park / loud-refusal / heal-rejoin flow, the
split-brain A/B (incidents present unfenced, ELIMINATED fenced), and
the three seeded campaign cells. The 16-seed x n sweep over all
three cells rides behind ``slow``.
"""

import pytest

from smi_tpu.obs.events import EVENT_KINDS
from smi_tpu.parallel.faults import (
    PARTITION_FAULT_CLASSES,
    AsymmetricLinkFault,
    FlappingLink,
    PartitionFault,
)
from smi_tpu.parallel.membership import (
    DEFAULT_QUORUM_FRACTION,
    QUORUM_FRACTION_ENV,
    FencingToken,
    MembershipView,
    QuorumDecision,
    QuorumLostError,
    StaleEpochError,
    check_fencing_token,
    mint_fencing_token,
    quorum_fraction,
    quorum_size,
)
from smi_tpu.serving.campaign import (
    MODEL_GATES,
    PARTITION_CELLS,
    _run_partition_traffic,
    partition_campaign,
    partition_selftest,
    run_flapping_link_cell,
    run_partition_cell,
    run_partition_migration_cell,
)

pytestmark = pytest.mark.partition


# ---------------------------------------------------------------------------
# The partition-class fault trio
# ---------------------------------------------------------------------------


def test_partition_fault_cuts_both_directions_across_the_cut():
    fault = PartitionFault(minority=frozenset({2}), from_tick=10,
                          until_tick=20)
    assert fault.blocks(2, 0, 10)       # minority -> majority
    assert fault.blocks(0, 2, 19)       # majority -> minority
    assert not fault.blocks(0, 1, 15)   # within the majority
    assert not fault.blocks(2, 2, 15)   # within the minority
    assert not fault.blocks(2, 0, 9)    # before the window
    assert not fault.blocks(2, 0, 20)   # after the heal


def test_asymmetric_fault_cuts_exactly_one_direction():
    fault = AsymmetricLinkFault(src=2, dst=0, from_tick=10,
                                until_tick=20)
    assert fault.blocks(2, 0, 15)       # the dead direction
    assert not fault.blocks(0, 2, 15)   # the live direction
    assert not fault.blocks(2, 1, 15)   # other peers unaffected
    assert not fault.blocks(2, 0, 20)


def test_flapping_link_is_deterministic_and_windowed():
    a = FlappingLink(a=0, b=2, from_tick=40, until_tick=160, seed=7)
    b = FlappingLink(a=0, b=2, from_tick=40, until_tick=160, seed=7)
    ticks_a = [t for t in range(200) if a.blocks(0, 2, t)]
    ticks_b = [t for t in range(200) if b.blocks(2, 0, t)]
    assert ticks_a == ticks_b           # deterministic, symmetric
    assert ticks_a                      # the flap actually flaps
    assert all(40 <= t < 160 for t in ticks_a)
    # a flap is intermittent, never the whole window
    assert len(ticks_a) < 120
    assert not a.blocks(0, 1, 50)       # other links untouched


def test_flapping_link_validation_is_loud():
    with pytest.raises(ValueError, match="DISTINCT"):
        FlappingLink(a=1, b=1)
    with pytest.raises(ValueError, match="down_ticks"):
        FlappingLink(a=0, b=1, period=4, down_ticks=5)
    with pytest.raises(ValueError, match="window is empty"):
        FlappingLink(a=0, b=1, from_tick=50, until_tick=50)


def test_partition_fault_class_registry():
    assert PARTITION_FAULT_CLASSES == (
        "partition", "asymmetric_link", "flapping_link",
    )


# ---------------------------------------------------------------------------
# Quorum fraction: the env knob's loudness discipline
# ---------------------------------------------------------------------------


def test_quorum_fraction_default_is_strict_majority(monkeypatch):
    monkeypatch.delenv(QUORUM_FRACTION_ENV, raising=False)
    assert quorum_fraction() == DEFAULT_QUORUM_FRACTION == 0.5


def test_quorum_fraction_env_and_explicit_precedence(monkeypatch):
    monkeypatch.setenv(QUORUM_FRACTION_ENV, "0.75")
    assert quorum_fraction() == 0.75
    # the explicit argument outranks the environment
    assert quorum_fraction(0.6) == 0.6


@pytest.mark.parametrize("raw", ["garbage", "nan", "inf"])
def test_quorum_fraction_rejects_malformed_env_loudly(monkeypatch,
                                                      raw):
    monkeypatch.setenv(QUORUM_FRACTION_ENV, raw)
    with pytest.raises(ValueError):
        quorum_fraction()


@pytest.mark.parametrize("raw", ["0.49", "1.0", "-1", "2"])
def test_quorum_fraction_rejects_unsafe_range_loudly(monkeypatch,
                                                     raw):
    # below 0.5 two disjoint quorums could coexist; 1.0 needs n+1 of n
    monkeypatch.setenv(QUORUM_FRACTION_ENV, raw)
    with pytest.raises(ValueError):
        quorum_fraction()


@pytest.mark.parametrize("n,expected", [
    (1, 1), (2, 2), (3, 2), (4, 3), (5, 3), (8, 5),
])
def test_quorum_size_is_strict_majority(monkeypatch, n, expected):
    monkeypatch.delenv(QUORUM_FRACTION_ENV, raising=False)
    assert quorum_size(n) == expected


def test_quorum_size_honours_fraction(monkeypatch):
    monkeypatch.delenv(QUORUM_FRACTION_ENV, raising=False)
    assert quorum_size(4, fraction=0.75) == 4
    with pytest.raises(ValueError):
        quorum_size(0)


# ---------------------------------------------------------------------------
# Fencing tokens: mint/check matrix
# ---------------------------------------------------------------------------


def test_mint_fencing_token_full_view_is_trivially_quorate():
    view = MembershipView(4)
    token = mint_fencing_token(view)
    assert token == FencingToken(epoch=0,
                                 quorum_set=frozenset({0, 1, 2, 3}))


def test_mint_fencing_token_minority_raises_loudly():
    view = MembershipView(4)
    with pytest.raises(QuorumLostError) as err:
        mint_fencing_token(view, reachable=[2], rank=2,
                           what="cutover")
    assert err.value.rank == 2
    assert err.value.reachable == frozenset({2})
    assert err.value.needed == 3
    assert "park" in str(err.value)


def test_check_fencing_token_stale_epoch_rides_the_straggler_rail():
    view = MembershipView(4)
    token = mint_fencing_token(view)
    view.confirm_dead(3)  # epoch moves; the token is now a straggler
    with pytest.raises(StaleEpochError):
        check_fencing_token(view, token)


def test_check_fencing_token_filtered_quorum_is_rejected():
    view = MembershipView(4)
    forged = FencingToken(epoch=0, quorum_set=frozenset({1}))
    with pytest.raises(QuorumLostError):
        check_fencing_token(view, forged)


def test_check_fencing_token_none_mints_the_healthy_path():
    view = MembershipView(4)
    token = check_fencing_token(view, None)
    assert token.epoch == view.epoch
    # a valid token round-trips
    assert check_fencing_token(view, token) is token


def test_quorum_decision_fields_match_the_event_schema():
    decision = QuorumDecision(epoch=3, quorum=(0, 1, 2),
                              verdict="minted")
    fields = decision.as_fields()
    plane, keys = EVENT_KINDS["ctl.quorum"]
    assert plane == "control"
    assert set(fields) == set(keys)
    assert fields["quorum"] == "0,1,2"


# ---------------------------------------------------------------------------
# The front end: minority park, loud refusal, heal rejoin
# ---------------------------------------------------------------------------


def test_partition_cell_parks_refuses_loudly_and_rejoins():
    report, fe = run_partition_cell(n=4, seed=0, return_frontend=True)
    assert report["ok"], report["verdict"]
    part = report["partition"]
    assert part["fenced"]
    assert part["quorum_losses"] >= 1
    assert part["quorum_rejections"] >= 1
    # every refusal surfaced to the caller as QuorumLostError
    assert report["quorum_rejected_seen"] == part["quorum_rejections"]
    assert part["heal_rejoins"] >= 1
    assert part["split_brain_incidents"] == 0
    assert part["parked"] == []
    assert report["members"] == [0, 1, 2, 3]
    assert report["stale_epoch_rejections"] >= 1
    assert report["stale_epoch_leaks"] == 0
    assert report["lost_accepted"] == 0
    assert report["digest_match"]
    # the fencing decisions are on the record, loud and structured
    verdicts = {d["verdict"] for d in part["decisions"]}
    assert {"lost", "rejected", "rejoin"} <= verdicts
    kinds = {e["kind"] for e in fe.recorder.tail(10_000)["events"]}
    assert "ctl.quorum" in kinds


def test_split_brain_present_unfenced_eliminated_fenced():
    """The PR's headline A/B: the same cut, with and without the
    quorum fence. Unfenced, the cut rank keeps accepting streams the
    majority has already rerouted — split-brain incidents. Fenced,
    those accepts become loud refusals and the incident count is
    ZERO."""
    unfenced, _, _, unfenced_rejected = _run_partition_traffic(
        4, 0, 240, 3, 64, fenced=False, fault_kind="partition",
        partition_at=60, window=100)
    fenced, _, _, fenced_rejected = _run_partition_traffic(
        4, 0, 240, 3, 64, fenced=True, fault_kind="partition",
        partition_at=60, window=100)
    assert unfenced.split_brain_accepts > 0
    assert unfenced_rejected == 0       # nothing was ever refused
    assert fenced.split_brain_accepts == 0
    assert fenced_rejected > 0          # refusals, loud and counted
    assert fenced.report()["lost_accepted"] == 0


def test_asymmetric_cut_aborts_migration_loudly_loss_free():
    report = run_partition_migration_cell(n=4, seed=0)
    assert report["ok"], report["verdict"]
    migs = report["elasticity"]["migrations"]
    assert [m["state"] for m in migs] == ["aborted"]
    assert migs[0]["abort_reason"] in ("membership-change",
                                       "quorum-lost")
    assert report["lost_accepted"] == 0
    assert report["silent_corruptions"] == 0
    assert report["confirmed"] == [report["src"]]
    assert report["members"] == [0, 1, 2, 3]  # rejoined at the heal


def test_flapping_link_never_moves_membership():
    report = run_flapping_link_cell(n=4, seed=0)
    assert report["ok"], report["verdict"]
    assert report["epoch"] == 0
    assert report["confirmed"] == []
    assert report["suspected"]          # the soak engaged
    assert len(report["cleared"]) == len(report["suspected"])
    part = report["partition"]
    assert part["quorum_losses"] == 0
    assert part["quorum_rejections"] == 0


# ---------------------------------------------------------------------------
# Campaign plumbing
# ---------------------------------------------------------------------------


def test_partition_campaign_aggregates_and_narrows():
    report = partition_campaign(seed=0, n=4, trials=1)
    assert report["ok"], report["failures"]
    assert report["cells"] == len(PARTITION_CELLS) == 3
    assert report["split_brain_incidents"] == 0
    narrowed = partition_campaign(seed=0, n=4, trials=1,
                                  only="flapping-link")
    assert narrowed["ok"]
    assert narrowed["cells"] == 1
    with pytest.raises(ValueError, match="unknown partition cell"):
        partition_campaign(only="nope")


def test_partition_selftest_is_the_clean_cell():
    report = partition_selftest(seed=0)
    assert report["ok"], report["verdict"]
    assert report["partition"]["heal_rejoins"] >= 1


def test_partition_cell_guards_are_loud():
    with pytest.raises(ValueError, match="minimum"):
        run_partition_cell(n=4, duration=60)
    with pytest.raises(ValueError, match="lease"):
        run_partition_cell(n=4, window=10)
    with pytest.raises(ValueError, match="post-heal"):
        run_partition_cell(n=4, duration=240, partition_at=100,
                           window=120)
    with pytest.raises(ValueError, match="stall_at"):
        run_partition_migration_cell(n=4, stall_at=80, migrate_at=70)


def test_model_gates_name_the_partition_properties():
    assert MODEL_GATES["no-split-brain"]
    assert MODEL_GATES["fenced-actuation"]


# ---------------------------------------------------------------------------
# The wide sweep (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("n", [4, 8])
@pytest.mark.parametrize("seed", range(16))
def test_partition_cells_sweep(n, seed):
    for cell in (run_partition_cell, run_partition_migration_cell,
                 run_flapping_link_cell):
        r = cell(n=n, seed=seed)
        assert r["ok"], (cell.__name__, n, seed, r["verdict"])
