"""Per-rank program metadata: validation and stream allocation.

Reference parity: ``codegen/program.py``. A *program* is the set of
communication operations one rank executes. The reference validates port
uniqueness, then round-robins each op's hardware ports across the FPGA's 4
physical QSFP channels per usage class (``codegen/program.py:53-80``,
``codegen/notes.txt``). On TPU the physical substrate is the ICI torus and
XLA does the physical routing, but the allocation layer survives with a new
meaning: logical ports are assigned to a small number of *streams* —
independent communication contexts that the runtime may overlap (concurrent
collectives on distinct ports land on distinct streams, mirroring
``multi_collectives.cl``'s overlap guarantee).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from smi_tpu.ops.operations import (
    ALL_STREAM_KEYS,
    COLLECTIVE_FAMILIES,
    IN_CTRL,
    IN_DATA,
    OUT_CTRL,
    OUT_DATA,
    P2P_FAMILIES,
    SmiOperation,
)

#: Streams per device. The reference has 4 physical QSFP channels per FPGA
#: (``codegen/program.py:9``); a TPU v4/v5 chip likewise has up to 6 ICI
#: links but collective overlap is bounded in practice — 4 keeps the
#: allocation semantics aligned with the reference test suite.
STREAMS_PER_DEVICE = 4


def round_robin(values: Sequence, index: int, size: int) -> List:
    """``values[index::size]`` — reference ``codegen/utils.py:5-10``."""
    return list(values[index::size])


class PortConflict(ValueError):
    """Two operations of one family claim the same logical port."""


@dataclasses.dataclass(frozen=True, order=True)
class Device:
    """A physical device slot: host node + index on that node.

    Reference ``FPGA`` (``codegen/program.py``), addressed "node:index"
    (e.g. ``fpga-0015:1``). On TPU, node = host, index = local chip index.
    """

    node: str
    index: int

    @property
    def key(self) -> Tuple[str, int]:
        return (self.node, self.index)

    def __str__(self) -> str:
        return f"{self.node}:{self.index}"

    @classmethod
    def parse(cls, text: str) -> "Device":
        """Parse ``node:index``. The index component may be a bare integer
        (``host-a:1``) or carry a device-name prefix as in the reference's
        topology files (``fpga-0001:acl1`` → index 1)."""
        node, _, idx = text.rpartition(":")
        if not node:
            raise ValueError(f"device must be 'node:index', got {text!r}")
        digits = "".join(ch for ch in idx if ch.isdigit())
        if not digits:
            raise ValueError(f"device index must contain digits, got {text!r}")
        return cls(node=node, index=int(digits))


class Program:
    """A validated set of operations plus communication tuning flags.

    Flags mirror the reference codegen CLI (``codegen/main.py:40-43``):

    - ``consecutive_reads``: reference CK fairness bound (``READS_LIMIT``,
      ``templates/device.cl:13-14``); on TPU it bounds how many chunks a
      streamed transfer may burst before yielding the stream.
    - ``max_ranks``: upper bound on communicator size the program is
      compiled for (sizes buffers in the reference; sizes masks here).
    - ``p2p_rendezvous``: reference credit-based rendezvous vs eager
      protocol (``templates/push.cl:21-31``); on TPU, True bounds in-flight
      chunks of a streamed P2P transfer to the channel's pipeline depth
      (back-pressure), False streams eagerly.
    """

    def __init__(
        self,
        operations: Sequence[SmiOperation],
        consecutive_reads: int = 8,
        max_ranks: int = 8,
        p2p_rendezvous: bool = True,
        num_streams: int = STREAMS_PER_DEVICE,
    ):
        # Canonical port order for the exposed tuple (the reference sorts at
        # init, codegen/program.py:103). allocate_ports owns the deal-order
        # invariant and re-sorts defensively for direct callers; on this
        # already-sorted input that re-sort is O(n).
        self.operations: Tuple[SmiOperation, ...] = tuple(
            sorted(operations, key=lambda op: op.port)
        )
        self.consecutive_reads = consecutive_reads
        self.max_ranks = max_ranks
        self.p2p_rendezvous = p2p_rendezvous
        self.num_streams = num_streams
        self._validate()
        self._allocation = allocate_ports(
            self.operations, num_streams=num_streams,
            p2p_rendezvous=p2p_rendezvous,
        )

    def _validate(self) -> None:
        """Port-uniqueness per stream class (``codegen/program.py:37-50``).

        Two ops may not claim the same logical port within one stream
        class: Push(0)+Push(0) conflict on out-data, and Push(0)+
        Broadcast(0) conflict too (the broadcast also sends on port 0) —
        while Push(0)+Pop(0), two ends of one channel, touch disjoint
        classes and are fine.
        """
        for key in ALL_STREAM_KEYS:
            seen: Dict[int, SmiOperation] = {}
            for op in self.operations:
                if key not in op.streams(self.p2p_rendezvous):
                    continue
                if op.port in seen:
                    raise PortConflict(
                        f"port {op.port} claimed twice on stream class "
                        f"{key!r}: {seen[op.port]} vs {op}"
                    )
                seen[op.port] = op

    @property
    def logical_port_count(self) -> int:
        """Number of logical ports (sizes routing tables); minimum 1 as in
        the reference (``codegen/program.py:107`` ``max(..., default=0)+1``)
        so even idle MPMD ranks get non-empty tables the bootstrap accepts.
        """
        return max((op.port for op in self.operations), default=0) + 1

    def operations_of_family(self, *families: str) -> List[SmiOperation]:
        fams = families or (P2P_FAMILIES + COLLECTIVE_FAMILIES)
        return [op for op in self.operations if op.family in fams]

    def find(self, family: str, port: int) -> Optional[SmiOperation]:
        for op in self.operations:
            if op.family == family and op.port == port:
                return op
        return None

    def stream_of(self, op: SmiOperation, stream_key: str) -> int:
        """Which stream this op's ``stream_key`` usage was assigned to."""
        return self._allocation.stream_of[(op.family, op.port, stream_key)]

    @property
    def allocation(self) -> Dict[Tuple[str, int, str], int]:
        return dict(self._allocation.stream_of)

    def stream_allocations(self, stream: int) -> List[Tuple[str, int, str]]:
        """Ordered (family, port, key) usages dealt to one stream — the
        reference's ``get_channel_allocations`` (``program.py:113-114``).
        Order is load-bearing: ingress tables number local op slots by it.
        """
        return list(self._allocation.per_stream.get(stream, ()))


@dataclasses.dataclass
class Allocation:
    """Result of dealing stream-usages onto streams."""

    stream_of: Dict[Tuple[str, int, str], int]
    per_stream: Dict[int, List[Tuple[str, int, str]]]


#: Combined deal order per direction (``codegen/notes.txt`` "Data and
#: control hardware ports are combined (in this order) and then
#: distributed"; ``codegen/program.py:58-80``).
OUT_KEYS = (OUT_DATA, OUT_CTRL)
IN_KEYS = (IN_DATA, IN_CTRL)


def allocate_ports(
    operations: Sequence[SmiOperation],
    num_streams: int = STREAMS_PER_DEVICE,
    p2p_rendezvous: bool = True,
) -> Allocation:
    """Deal op stream-usages onto ``num_streams`` streams, reference-style.

    Per direction (out/in), the data usages of all ops (in port order) are
    concatenated with the control usages, and that combined list is dealt
    round-robin: usage *i* lands on stream ``i % num_streams``. This exactly
    reproduces the reference's channel distribution
    (``codegen/program.py:53-80``) so stream indices — and therefore the
    routing tables derived from them — match bit-for-bit.
    """
    ops_sorted = sorted(operations, key=lambda op: op.port)
    stream_of: Dict[Tuple[str, int, str], int] = {}
    per_stream: Dict[int, List[Tuple[str, int, str]]] = {
        s: [] for s in range(num_streams)
    }
    for direction in (OUT_KEYS, IN_KEYS):
        combined = [
            (op.family, op.port, key)
            for key in direction
            for op in ops_sorted
            if key in op.streams(p2p_rendezvous)
        ]
        for i, usage in enumerate(combined):
            stream = i % num_streams
            stream_of[usage] = stream
            per_stream[stream].append(usage)
    return Allocation(stream_of=stream_of, per_stream=per_stream)


def combined_program(mapping: "ProgramMapping") -> Program:
    """Union of every rank's program, for one SPMD trace.

    The reference runs genuinely different bitstreams per rank (MPMD via
    the routing file's program map, ``bandwidth.json``) and its ``route``
    step loads *all* program metadata together to build consistent
    tables (``codegen/main.py:107-133``). Under SPMD one program is
    traced for all ranks, so the equivalent is the union of the per-rank
    operation sets: complementary endpoints (rank 0's ``Push(0)``, rank
    1's ``Pop(0)``) combine into one valid program, while genuine
    conflicts (two ranks both claiming ``Push(0)`` with different
    dtypes) fail the joint validation exactly as the reference's table
    builder would reject them.

    Tuning flags must agree on ``p2p_rendezvous`` (it changes the wire
    protocol); ``consecutive_reads``/``max_ranks`` take the maximum.
    """
    programs = [p for p in mapping.programs if p is not None]
    if not programs:
        raise ValueError("mapping contains no programs")
    rendezvous = {p.p2p_rendezvous for p in programs}
    if len(rendezvous) > 1:
        raise ValueError(
            "MPMD programs disagree on p2p_rendezvous; the protocol must "
            "be uniform across ranks"
        )
    # dedup by the full operation value (frozen dataclass): identical
    # declarations merge (SPMD), while ops differing in ANY field — dtype,
    # buffer size, reduce operator — both reach the joint validation
    seen = dict.fromkeys(
        op for program in programs for op in program.operations
    )
    return Program(
        list(seen),
        consecutive_reads=max(p.consecutive_reads for p in programs),
        max_ranks=max(p.max_ranks for p in programs),
        p2p_rendezvous=rendezvous.pop(),
    )


@dataclasses.dataclass
class ProgramMapping:
    """Which program each device runs (SPMD: all the same; MPMD: differ).

    Reference: the routing file's ``"fpgas"`` program map
    (``codegen/serialization.py:65-109``), which lets e.g. the bandwidth
    benchmark run a sender program on rank 0 and a receiver program on
    rank 1 (``microbenchmarks/kernels/bandwidth.json``).
    """

    programs: List[Program]
    device_to_program: Dict[Device, Program]

    def program_for(self, device: Device) -> Program:
        return self.device_to_program[device]

    @property
    def devices(self) -> List[Device]:
        """Deterministic rank order: sorted by (node, index).

        Reference: ``codegen/routing.py:61-69`` sorts by the same key so
        rank numbering is reproducible across runs.
        """
        return sorted(self.device_to_program, key=lambda d: d.key)

    def rank_of(self, device: Device) -> int:
        return self.devices.index(device)
