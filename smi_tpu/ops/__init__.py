"""Substrate-neutral operation/port/program model.

This is the front half of the SMI "compiler": the taxonomy of communication
operations, the per-rank program metadata, and its JSON wire format. It is
deliberately independent of JAX so it can be unit-tested without devices and
consumed by the native (C++) manifest tooling.

Reference parity: ``codegen/ops.py``, ``codegen/program.py``,
``codegen/serialization.py``.
"""
