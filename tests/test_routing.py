"""Routing-layer tests: graph construction, egress/ingress tables,
balancing, serialization.

Reference: ``codegen/tests/test_routing.py`` + ``test_routing_table.py`` —
including the exact golden table contents for the two-device and
double-rail chain topologies, and the no-route error case.
"""

import pytest

from smi_tpu.ops.operations import Pop, Push
from smi_tpu.ops.program import Device, Program, ProgramMapping
from smi_tpu.ops.serialization import Topology
from smi_tpu.parallel.routing import (
    EGRESS_LOCAL,
    EGRESS_WIRE,
    Link,
    NoRouteFound,
    build_routing_context,
    deserialize_table,
    egress_link_toward,
    egress_tables,
    ingress_table,
    serialize_table,
    sibling_index,
    write_routing_tables,
)


def make_topology(connections, program, devices=None):
    """Build a Topology from {(dev_str, link): (dev_str, link)} pairs."""
    conn = {}
    devs = set()
    for (a, la), (b, lb) in connections.items():
        da, db = Device.parse(a), Device.parse(b)
        conn[(da, la)] = (db, lb)
        conn[(db, lb)] = (da, la)
        devs.update([da, db])
    if devices is not None:
        devs.update(Device.parse(d) for d in devices)
    mapping = ProgramMapping(
        programs=[program], device_to_program={d: program for d in devs}
    )
    return Topology(connections=conn, mapping=mapping)


def fmt(table, device, link_index):
    """Render an egress table like the reference tests do: code per
    (rank, port), with WIRE/LOCAL/sibling-forward names."""
    out = []
    for row in table.data:
        rendered = []
        for code in row:
            if code == EGRESS_WIRE:
                rendered.append("WIRE")
            elif code == EGRESS_LOCAL:
                rendered.append("LOCAL")
            else:
                # invert sibling numbering for readability: src->dst
                sib = code - 2
                dst = sib if sib < link_index else sib + 1
                rendered.append(f"{link_index}->{dst}")
        out.append(rendered)
    return out


def test_sibling_index():
    assert sibling_index(0, 1) == 0
    assert sibling_index(0, 3) == 2
    assert sibling_index(2, 0) == 0
    assert sibling_index(2, 3) == 2
    with pytest.raises(ValueError):
        sibling_index(1, 1)


def test_egress_two_device_links_1_3():
    """Reference test_cks_table_1: FA/FB joined on links 1 and 3."""
    program = Program([Push(0), Push(1)])
    topo = make_topology(
        {("NA:0", 1): ("NB:0", 1), ("NA:0", 3): ("NB:0", 3)},
        program,
    )
    ctx = build_routing_context(topo)
    fa = Device("NA", 0)  # NA sorts before NB -> rank 0
    assert [str(d) for d in ctx.devices] == ["NA:0", "NB:0"]
    tables = egress_tables(fa, ctx, program)
    assert fmt(tables[Link(fa, 0)], fa, 0) == [
        ["LOCAL", "LOCAL"], ["0->1", "0->1"]]
    assert fmt(tables[Link(fa, 1)], fa, 1) == [
        ["LOCAL", "LOCAL"], ["WIRE", "1->3"]]
    assert fmt(tables[Link(fa, 2)], fa, 2) == [
        ["LOCAL", "LOCAL"], ["2->1", "2->1"]]
    assert fmt(tables[Link(fa, 3)], fa, 3) == [
        ["LOCAL", "LOCAL"], ["WIRE", "WIRE"]]


def test_egress_two_device_links_0_3():
    """Reference test_cks_table_2: joined on links 0 and 3."""
    program = Program([Push(0), Push(1)])
    topo = make_topology(
        {("NA:0", 0): ("NB:0", 0), ("NA:0", 3): ("NB:0", 3)},
        program,
    )
    ctx = build_routing_context(topo)
    fa = Device("NA", 0)
    tables = egress_tables(fa, ctx, program)
    assert fmt(tables[Link(fa, 0)], fa, 0) == [
        ["LOCAL", "LOCAL"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(fa, 1)], fa, 1) == [
        ["LOCAL", "LOCAL"], ["1->0", "1->3"]]
    assert fmt(tables[Link(fa, 2)], fa, 2) == [
        ["LOCAL", "LOCAL"], ["2->0", "2->0"]]
    assert fmt(tables[Link(fa, 3)], fa, 3) == [
        ["LOCAL", "LOCAL"], ["WIRE", "WIRE"]]


def test_ingress_table_slots():
    """Reference test_ckr_table: 5 ops, slot numbering by deal order."""
    program = Program([Push(0), Pop(1), Push(2), Pop(3), Pop(4)])
    topo = make_topology({("na:0", 0): ("nb:0", 0)}, program)
    ctx = build_routing_context(topo)
    dev = Device("na", 0)

    def table(i):
        return ingress_table(Link(dev, i), ctx, program).flat()

    assert table(0) == [0, 3, 4, 0, 0, 5, 1, 0, 2, 0]
    assert table(1) == [0, 3, 1, 0, 0, 1, 4, 0, 2, 0]
    assert table(2) == [0, 3, 1, 0, 0, 1, 2, 0, 4, 0]
    assert table(3) == [0, 4, 1, 0, 0, 1, 2, 0, 3, 0]


def test_no_route_between_partitions():
    """Reference test_cks_no_route: disconnected topology islands."""
    program = Program([Push(0)])
    topo = make_topology(
        {("N0:0", 0): ("N0:1", 0), ("N1:0", 0): ("N1:2", 1)},
        program,
    )
    ctx = build_routing_context(topo)
    with pytest.raises(NoRouteFound):
        egress_tables(Device("N0", 0), ctx, program)


def test_balancing_spreads_across_wires():
    """Two parallel wires between two devices: balanced pass must not put
    every port on one wire (the balanced_routing test's property,
    ``test/balanced_routing``)."""
    program = Program([Push(p) for p in range(4)], p2p_rendezvous=False)
    topo = make_topology(
        {("A:00", 0): ("B:00", 0), ("A:00", 2): ("B:00", 2)},
        program,
    )
    ctx = build_routing_context(topo)
    dev = Device("A", 0)
    tables = egress_tables(dev, ctx, program)
    # each push's out-data stream sits on its own link (deal order), and
    # the balanced exit alternates between wire 0 and wire 2
    exits = set()
    for port in range(4):
        link = Link(dev, port)  # port p allocated to stream p
        code = tables[link][1, port]
        exits.add((link.index, code))
    wire_exits = {
        (0, EGRESS_WIRE),  # link0 exits its own wire
        (2, EGRESS_WIRE),  # link2 exits its own wire
    }
    assert wire_exits <= exits


def test_serialize_round_trip():
    flat = [0, 1, 2, 255, 7]
    assert deserialize_table(serialize_table(flat, 1), 1) == flat
    big = [0, 300, 65535]
    assert deserialize_table(serialize_table(big, 2), 2) == big


def test_write_routing_tables(tmp_path):
    program = Program([Push(0), Pop(0)])
    topo = make_topology({("NA:0", 1): ("NB:0", 1)}, program)
    write_routing_tables(tmp_path, topo)
    files = sorted(p.name for p in tmp_path.iterdir())
    assert "cks-rank0-channel0" in files
    assert "ckr-rank1-channel3" in files
    assert len(files) == 2 * 2 * 4  # two devices x (cks+ckr) x 4 links
    raw = (tmp_path / "cks-rank0-channel0").read_bytes()
    assert len(raw) == 2 * 1  # ranks x ports, 1 byte each


def test_egress_link_toward():
    program = Program([Push(0)])
    topo = make_topology(
        {("NA:0", 1): ("NB:0", 0), ("NB:0", 1): ("NC:0", 0)},
        program,
    )
    ctx = build_routing_context(topo)
    assert len(ctx.devices) == 3
    link, neighbour = egress_link_toward(ctx.devices[0], ctx.devices[-1], ctx)
    assert link == 1  # leaves through the wire on link 1
    assert neighbour == ctx.devices[1]  # first hop is the middle device


DOUBLE_RAIL = {
    ("N1:F0", 1): ("N1:F1", 0),
    ("N1:F0", 3): ("N1:F1", 2),
    ("N1:F1", 1): ("N2:F0", 0),
    ("N1:F1", 3): ("N2:F0", 2),
    ("N2:F0", 1): ("N2:F1", 0),
    ("N2:F0", 3): ("N2:F1", 2),
    ("N2:F1", 1): ("N1:F0", 0),
    ("N2:F1", 3): ("N1:F0", 2),
}


def test_egress_double_rail_ring():
    """Reference test_cks_table_double_rail: 4 devices in a double-rail
    ring; exercises multi-hop forwarding + balancing across both rails."""
    program = Program([Push(0), Pop(0), Push(1), Pop(1)])
    topo = make_topology(DOUBLE_RAIL, program)
    ctx = build_routing_context(topo)
    f0 = Device("N1", 0)
    tables = egress_tables(f0, ctx, program)
    assert fmt(tables[Link(f0, 0)], f0, 0) == [
        ["LOCAL", "LOCAL"], ["0->1", "0->1"], ["WIRE", "WIRE"], ["0->2", "WIRE"]]
    assert fmt(tables[Link(f0, 1)], f0, 1) == [
        ["LOCAL", "LOCAL"], ["WIRE", "1->3"], ["WIRE", "1->0"], ["1->0", "1->0"]]
    assert fmt(tables[Link(f0, 2)], f0, 2) == [
        ["LOCAL", "LOCAL"], ["2->1", "2->1"], ["WIRE", "WIRE"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(f0, 3)], f0, 3) == [
        ["LOCAL", "LOCAL"], ["WIRE", "WIRE"], ["WIRE", "WIRE"], ["3->0", "3->0"]]

    f1 = Device("N1", 1)
    tables = egress_tables(f1, ctx, program)
    assert fmt(tables[Link(f1, 0)], f1, 0) == [
        ["WIRE", "WIRE"], ["LOCAL", "LOCAL"], ["0->1", "0->1"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(f1, 1)], f1, 1) == [
        ["1->0", "1->2"], ["LOCAL", "LOCAL"], ["WIRE", "1->3"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(f1, 2)], f1, 2) == [
        ["WIRE", "WIRE"], ["LOCAL", "LOCAL"], ["2->1", "2->1"], ["WIRE", "WIRE"]]
    assert fmt(tables[Link(f1, 3)], f1, 3) == [
        ["3->0", "3->0"], ["LOCAL", "LOCAL"], ["WIRE", "WIRE"], ["WIRE", "WIRE"]]


def test_egress_link_toward_balanced_per_port():
    """With a program, egress_link_toward follows the balanced tables: on
    a double-wire topology different ports exit different wires
    (code-review regression: it must agree with the emitted tables)."""
    program = Program([Push(p) for p in range(4)], p2p_rendezvous=False)
    topo = make_topology(
        {("A:0", 0): ("B:0", 0), ("A:0", 2): ("B:0", 2)},
        program,
    )
    ctx = build_routing_context(topo)
    a, b = ctx.devices
    wires = {
        egress_link_toward(a, b, ctx, program=program, port=p)[0]
        for p in range(4)
    }
    assert wires == {0, 2}  # balanced across both physical wires
    for p in range(4):
        _link, nbr = egress_link_toward(a, b, ctx, program=program, port=p)
        assert nbr == b


def test_stream_count_mismatch_rejected():
    program = Program([Push(0)], num_streams=8)
    topo = make_topology({("A:0", 0): ("B:0", 0)}, program)
    ctx = build_routing_context(topo)
    with pytest.raises(ValueError, match="streams"):
        egress_tables(Device("A", 0), ctx, program)
    with pytest.raises(ValueError, match="streams"):
        ingress_table(Link(Device("A", 0), 0), ctx, program)


def test_unmapped_passthrough_device_rejected():
    program = Program([Push(0)])
    conn = {
        (Device("A", 0), 0): (Device("GHOST", 0), 0),
        (Device("GHOST", 0), 0): (Device("A", 0), 0),
    }
    mapping = ProgramMapping(
        programs=[program], device_to_program={Device("A", 0): program}
    )
    topo = Topology(connections=conn, mapping=mapping)
    with pytest.raises(KeyError, match="GHOST"):
        build_routing_context(topo)


# ---------------------------------------------------------------------------
# Reference scenarios ported verbatim, with byte-identical serialization
# ---------------------------------------------------------------------------
# Each scenario reproduces a case of the reference's own test suite
# (``codegen/tests/test_routing_table.py:18-167``); the expected matrices
# below are the reference's expectations transliterated 1:1 ("QSFP" = exit
# this link's wire, "CKR" = deliver locally, "a->b" = forward from link a to
# sibling link b). ``expected_bytes`` recomputes the reference's serialized
# encoding (QSFP=0, CKR=1, a->b = 2 + sibling index, little-endian bytes,
# ``routing_table.py:25-63``) and asserts our emitted bytes are identical —
# the bit-compatibility claim of ``smi_tpu/parallel/routing.py:24-26``
# backed by the reference's own data.


def ref_code(token, link_index):
    if token == "QSFP":
        return EGRESS_WIRE
    if token == "CKR":
        return EGRESS_LOCAL
    src, dst = token.split("->")
    assert int(src) == link_index
    return 2 + sibling_index(int(src), int(dst))


def assert_tables_match_reference(device, tables, expected_matrices):
    """expected_matrices[i] = the reference's repr-matrix for link i."""
    assert len(expected_matrices) == 4
    for link_index, matrix in enumerate(expected_matrices):
        table = tables[Link(device, link_index)]
        codes = [
            [ref_code(token, link_index) for token in row] for row in matrix
        ]
        assert table.data == codes, (
            f"link {link_index}: {table.data} != reference {codes}"
        )
        expected_bytes = serialize_table(
            [c for row in codes for c in row]
        )
        assert serialize_table(table.flat()) == expected_bytes


def test_reference_cks_table_1_bytes():
    """Reference test_cks_table_1 (links 1+3 between two devices)."""
    program = Program([Push(0), Push(1)])
    topo = make_topology(
        {("NA:0", 1): ("NB:0", 1), ("NA:0", 3): ("NB:0", 3)},
        program,
    )
    ctx = build_routing_context(topo)
    fa = Device("NA", 0)
    assert ctx.rank_of(fa) == 0  # sorted-by-key rank order
    tables = egress_tables(fa, ctx, program)
    assert_tables_match_reference(fa, tables, [
        [["CKR", "CKR"], ["0->1", "0->1"]],
        [["CKR", "CKR"], ["QSFP", "1->3"]],
        [["CKR", "CKR"], ["2->1", "2->1"]],
        [["CKR", "CKR"], ["QSFP", "QSFP"]],
    ])


def test_reference_cks_table_2_bytes():
    """Reference test_cks_table_2 (links 0+3 between two devices)."""
    program = Program([Push(0), Push(1)])
    topo = make_topology(
        {("NA:0", 0): ("NB:0", 0), ("NA:0", 3): ("NB:0", 3)},
        program,
    )
    ctx = build_routing_context(topo)
    fa = Device("NA", 0)
    tables = egress_tables(fa, ctx, program)
    assert_tables_match_reference(fa, tables, [
        [["CKR", "CKR"], ["QSFP", "QSFP"]],
        [["CKR", "CKR"], ["1->0", "1->3"]],
        [["CKR", "CKR"], ["2->0", "2->0"]],
        [["CKR", "CKR"], ["QSFP", "QSFP"]],
    ])


def test_reference_cks_table_double_rail_bytes():
    """Reference test_cks_table_double_rail: 4 devices, double-rail ring;
    checks both N1 devices against the reference matrices."""
    program = Program([Push(0), Pop(0), Push(1), Pop(1)])
    topo = make_topology(DOUBLE_RAIL, program)
    ctx = build_routing_context(topo)

    f0 = Device("N1", 0)
    assert_tables_match_reference(f0, egress_tables(f0, ctx, program), [
        [["CKR", "CKR"], ["0->1", "0->1"], ["QSFP", "QSFP"], ["0->2", "QSFP"]],
        [["CKR", "CKR"], ["QSFP", "1->3"], ["QSFP", "1->0"], ["1->0", "1->0"]],
        [["CKR", "CKR"], ["2->1", "2->1"], ["QSFP", "QSFP"], ["QSFP", "QSFP"]],
        [["CKR", "CKR"], ["QSFP", "QSFP"], ["QSFP", "QSFP"], ["3->0", "3->0"]],
    ])

    f1 = Device("N1", 1)
    assert_tables_match_reference(f1, egress_tables(f1, ctx, program), [
        [["QSFP", "QSFP"], ["CKR", "CKR"], ["0->1", "0->1"], ["QSFP", "QSFP"]],
        [["1->0", "1->2"], ["CKR", "CKR"], ["QSFP", "1->3"], ["QSFP", "QSFP"]],
        [["QSFP", "QSFP"], ["CKR", "CKR"], ["2->1", "2->1"], ["QSFP", "QSFP"]],
        [["3->0", "3->0"], ["CKR", "CKR"], ["QSFP", "QSFP"], ["QSFP", "QSFP"]],
    ])


def test_reference_cks_table_double_rail2_bytes():
    """Reference test_cks_table_double_rail2: 6 devices in a double-rail
    ring; checks device F4's tables — the longest multi-hop case, where
    balanced routes split across both rails."""
    program = Program([Push(0), Pop(0), Push(1), Pop(1)])
    topo = make_topology(
        {
            ("N:F0", 1): ("N:F1", 0),
            ("N:F0", 3): ("N:F1", 2),
            ("N:F1", 1): ("N:F2", 0),
            ("N:F1", 3): ("N:F2", 2),
            ("N:F2", 1): ("N:F3", 0),
            ("N:F2", 3): ("N:F3", 2),
            ("N:F3", 1): ("N:F4", 0),
            ("N:F3", 3): ("N:F4", 2),
            ("N:F4", 1): ("N:F5", 0),
            ("N:F4", 3): ("N:F5", 2),
            ("N:F5", 1): ("N:F0", 0),
            ("N:F5", 3): ("N:F0", 2),
        },
        program,
    )
    ctx = build_routing_context(topo)
    f4 = Device("N", 4)
    assert_tables_match_reference(f4, egress_tables(f4, ctx, program), [
        [["0->1", "0->1"], ["QSFP", "QSFP"], ["QSFP", "QSFP"],
         ["0->2", "QSFP"], ["CKR", "CKR"], ["0->3", "0->1"]],
        [["QSFP", "QSFP"], ["QSFP", "1->0"], ["1->0", "1->0"],
         ["1->0", "1->2"], ["CKR", "CKR"], ["QSFP", "QSFP"]],
        [["2->1", "2->1"], ["QSFP", "QSFP"], ["QSFP", "QSFP"],
         ["QSFP", "QSFP"], ["CKR", "CKR"], ["2->3", "2->1"]],
        [["QSFP", "QSFP"], ["QSFP", "QSFP"], ["3->0", "3->0"],
         ["3->0", "3->0"], ["CKR", "CKR"], ["QSFP", "QSFP"]],
    ])


def test_reference_ckr_table_bytes():
    """Reference test_ckr_table: exact slot numbering AND serialized bytes
    for all four links."""
    program = Program([Push(0), Pop(1), Push(2), Pop(3), Pop(4)])
    topo = make_topology({("na:0", 0): ("nb:0", 0)}, program)
    ctx = build_routing_context(topo)
    dev = Device("na", 0)
    expected = {
        0: [0, 3, 4, 0, 0, 5, 1, 0, 2, 0],
        1: [0, 3, 1, 0, 0, 1, 4, 0, 2, 0],
        2: [0, 3, 1, 0, 0, 1, 2, 0, 4, 0],
        3: [0, 4, 1, 0, 0, 1, 2, 0, 3, 0],
    }
    for i, want in expected.items():
        table = ingress_table(Link(dev, i), ctx, program)
        assert table.flat() == want
        assert serialize_table(table.flat()) == bytes(want)


def test_reference_no_route_bytes():
    """Reference test_cks_no_route: two disconnected islands."""
    program = Program([])
    topo = make_topology(
        {("N0:F0", 0): ("N0:F1", 0), ("N1:F0", 0): ("N1:F2", 1)},
        program,
    )
    ctx = build_routing_context(topo)
    f = Device("N0", 0)
    with pytest.raises(NoRouteFound):
        egress_tables(f, ctx, program)
