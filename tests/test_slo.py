"""Request-level tracing + SLO engine (r15): spans, blame, burn rates.

The acceptance criteria, each pinned:

- **span exactness** — every request's span-component sum is
  bit-identical to the front-end's own measured admission-to-delivery
  latency, across the FULL seeded campaign matrix
  (overload / kill / stall / moe / retune cells);
- **blame ground truth** — the tail-latency blame verdict names the
  injected binding resource in every fault cell: the killed rank
  (``failover:rank<k>``), the stalled rank, the hot wire lane, the
  browned-out class;
- **breach determinism** — the seeded overload (brownout) campaign
  fires ``slo.breach`` deterministically; the fair-weather cell
  (0.5x load) fires ZERO alarms;
- **no silent truncation** — the span builder refuses a wrapped ring
  loudly, naming ``$SMI_TPU_OBS_RING``.
"""

import json
import math

import pytest

from smi_tpu.obs.events import (
    DEFAULT_RECORDER_CAPACITY,
    OBS_RING_ENV,
    FlightRecorder,
    ring_capacity,
)
from smi_tpu.obs.slo import (
    BREACH_BURN,
    DEFAULT_SLOS,
    MIN_WINDOW_EVENTS,
    SLO_WINDOWS,
    SloEngine,
    SloSpec,
    format_health,
)
from smi_tpu.obs.spans import (
    COMPONENTS,
    DELIVERY_COMPONENTS,
    BlameVerdict,
    SpanError,
    blame_report,
    blame_verdict,
    build_spans,
    exactness_problems,
    format_blame,
    frontend_spans,
    parse_blame_resource,
)
from smi_tpu.serving.campaign import run_load_cell, run_retune_cell
from smi_tpu.serving.frontend import ServingFrontend
from smi_tpu.serving.moe import expert_home, run_moe_cell
from smi_tpu.serving.qos import QOS_CLASSES

pytestmark = pytest.mark.slo


# ---------------------------------------------------------------------------
# $SMI_TPU_OBS_RING: the recorder-capacity env override
# ---------------------------------------------------------------------------


class TestRingEnvOverride:
    def test_default_unchanged_when_unset(self, monkeypatch):
        monkeypatch.delenv(OBS_RING_ENV, raising=False)
        assert ring_capacity() == DEFAULT_RECORDER_CAPACITY
        assert FlightRecorder().capacity == DEFAULT_RECORDER_CAPACITY

    def test_env_overrides_the_default(self, monkeypatch):
        monkeypatch.setenv(OBS_RING_ENV, "2048")
        assert FlightRecorder().capacity == 2048
        # ... and a caller-supplied default too (campaigns pass their
        # schedule estimate; the operator's word outranks it)
        assert ring_capacity(default=99_999) == 2048

    def test_explicit_capacity_outranks_the_env(self, monkeypatch):
        monkeypatch.setenv(OBS_RING_ENV, "2048")
        assert FlightRecorder(capacity=4).capacity == 4

    @pytest.mark.parametrize("junk", ["abc", "1.5", "0", "-3", "nan"])
    def test_malformed_is_loud_naming_the_knob(self, monkeypatch, junk):
        monkeypatch.setenv(OBS_RING_ENV, junk)
        with pytest.raises(ValueError, match="SMI_TPU_OBS_RING"):
            ring_capacity()

    def test_empty_means_unset(self, monkeypatch):
        monkeypatch.setenv(OBS_RING_ENV, "  ")
        assert ring_capacity() == DEFAULT_RECORDER_CAPACITY


# ---------------------------------------------------------------------------
# SLO engine unit behaviour
# ---------------------------------------------------------------------------


class TestSloEngine:
    def test_spec_validation_is_loud(self):
        with pytest.raises(ValueError, match="error_budget"):
            SloSpec("interactive", 10, 1.5)
        with pytest.raises(ValueError, match="latency_target"):
            SloSpec("interactive", 0, 0.1)

    def test_missing_class_is_loud(self):
        with pytest.raises(ValueError, match="missing QoS class"):
            SloEngine(specs={"interactive": DEFAULT_SLOS["interactive"]})

    def test_unknown_class_is_loud(self):
        with pytest.raises(ValueError, match="unknown QoS class"):
            SloEngine(specs={
                **DEFAULT_SLOS,
                "premium": SloSpec("premium", 100, 0.05),
            })

    def test_window_validation_is_loud(self):
        with pytest.raises(ValueError, match="short < long"):
            SloEngine(windows=(64, 32))

    def test_below_the_evidence_floor_burn_reads_zero(self):
        engine = SloEngine()
        # a handful of errors, but fewer than MIN_WINDOW_EVENTS
        # events total: one unlucky shed must not page
        for tick in range(1, 6):
            engine.observe_shed("interactive", "brownout:interactive",
                                tick)
            engine.evaluate(tick)
        health = engine.health()
        cls = health["classes"]["interactive"]
        assert cls["errors"] == 5
        assert cls["burn"]["short"] == 0.0
        assert cls["breaches"] == 0

    def test_sustained_errors_breach_then_recover(self):
        rec = FlightRecorder(capacity=4096)
        engine = SloEngine(recorder=rec)
        tick = 0
        # sustained outage: every interactive request shed, enough
        # volume to clear the floor in BOTH windows
        for _ in range(SLO_WINDOWS[1]):
            tick += 1
            for _ in range(2):
                engine.observe_shed("interactive",
                                    "backpressure:rank0", tick)
            engine.evaluate(tick)
        health = engine.health()
        cls = health["classes"]["interactive"]
        assert cls["breached"] is True
        assert cls["breaches"] == 1
        assert health["breached"] is True
        # recovery: healthy traffic until both windows drain
        for _ in range(SLO_WINDOWS[1] + 1):
            tick += 1
            for _ in range(2):
                engine.observe_delivery("interactive", 1, tick)
            engine.evaluate(tick)
        health = engine.health()
        cls = health["classes"]["interactive"]
        assert cls["breached"] is False
        assert cls["recoveries"] == 1
        kinds = [e.kind for e in rec.events()
                 if e.kind.startswith("slo.")]
        assert "slo.breach" in kinds and "slo.recover" in kinds
        # the recover event carries the breach duration
        recover = next(e for e in rec.events()
                       if e.kind == "slo.recover")
        assert dict(recover.fields)["breached_ticks"] > 0

    def test_short_burst_warns_but_does_not_breach(self):
        rec = FlightRecorder(capacity=4096)
        engine = SloEngine(recorder=rec)
        tick = 0
        # a long healthy prefix fills the LONG window with good events
        for _ in range(SLO_WINDOWS[1]):
            tick += 1
            for _ in range(3):
                engine.observe_delivery("batch", 1, tick)
            engine.evaluate(tick)
        # then one short burst of errors: the 32-tick window burns,
        # the 128-tick window (mostly healthy) does not agree
        for _ in range(8):
            tick += 1
            for _ in range(3):
                engine.observe_shed("batch", "brownout:batch", tick)
            engine.evaluate(tick)
        health = engine.health()
        cls = health["classes"]["batch"]
        assert cls["burn_warnings"] >= 1
        assert cls["breaches"] == 0
        kinds = [e.kind for e in rec.events()]
        assert "slo.burn" in kinds and "slo.breach" not in kinds

    def test_tenant_rate_sheds_are_not_slo_errors(self):
        engine = SloEngine()
        engine.observe_shed("batch", "tenant-rate", 1)
        engine.evaluate(1)
        assert engine.health()["classes"]["batch"]["errors"] == 0

    def test_late_delivery_is_a_latency_error(self):
        engine = SloEngine()
        target = DEFAULT_SLOS["batch"].latency_target_ticks
        engine.observe_delivery("batch", target + 1, 1)
        engine.observe_delivery("batch", target, 1)
        engine.evaluate(1)
        cls = engine.health()["classes"]["batch"]
        assert cls["errors"] == 1 and cls["good"] == 1
        assert cls["errors_by_reason"] == {"latency": 1}

    def test_health_snapshot_is_deterministic(self):
        def build():
            engine = SloEngine()
            for tick in range(1, 40):
                engine.observe_delivery("interactive", 2, tick)
                if tick % 3 == 0:
                    engine.observe_shed("best_effort",
                                        "brownout:best_effort", tick)
                engine.evaluate(tick)
            return json.dumps(engine.health(), sort_keys=True)

        assert build() == build()

    def test_format_health_renders_every_class(self):
        engine = SloEngine()
        engine.evaluate(1)
        text = "\n".join(format_health(engine.health()))
        for qos in QOS_CLASSES:
            assert qos in text


# ---------------------------------------------------------------------------
# Span builder: refusal, walk correctness, stall carving
# ---------------------------------------------------------------------------


class TestSpanBuilder:
    def test_truncated_stream_is_refused_naming_the_knob(self):
        fe = ServingFrontend(2, seed=0,
                             recorder=FlightRecorder(capacity=8))
        fe.submit("t0", "batch",
                  tuple(f"c{i}" for i in range(8)))
        fe.drain()
        assert fe.recorder.dropped_events > 0
        with pytest.raises(SpanError, match="SMI_TPU_OBS_RING"):
            build_spans(fe.recorder)
        # the opt-in best-effort path still builds the retained window
        report = build_spans(fe.recorder, allow_partial=True)
        assert report.dropped_events > 0

    def test_single_stream_partition_is_exact(self):
        fe = ServingFrontend(2, seed=0)
        fe.submit("t0", "batch", ("c0", "c1", "c2"))
        fe.drain()
        report = frontend_spans(fe)
        assert exactness_problems(report, fe) == []
        [st] = fe.completed
        tree = report.requests[st.request.stream_id]
        assert tree.latency == st.completed_at - st.admitted_at
        assert tree.delivery_sum() == tree.latency
        # component spans tile: sorted by t0, each starts where the
        # previous ended, from admission to completion
        comp = [s for s in tree.spans if s.kind == "component"
                and s.component != "admit.wait"]
        t = tree.admitted
        for span in comp:
            assert span.t0 == t
            t = span.t1
        assert t == tree.completed

    def test_snapshot_roundtrip_builds_identical_trees(self):
        rep, fe = run_load_cell(n=4, seed=3, duration=120,
                                overload=1.0, return_frontend=True)
        live = frontend_spans(fe)
        recorded = build_spans(fe.recorder.snapshot())
        assert live.requests.keys() == recorded.requests.keys()
        for key in live.requests:
            assert (live.requests[key].to_json()
                    == recorded.requests[key].to_json())

    def test_shed_requests_get_terminal_trees(self):
        rep, fe = run_load_cell(n=4, seed=0, duration=160,
                                overload=2.0, return_frontend=True)
        report = frontend_spans(fe)
        shed = [t for t in report.requests.values()
                if t.shed_reason is not None]
        assert shed, "a 2x overload cell must shed"
        for tree in shed:
            assert tree.outcome.startswith("shed:")
            assert tree.completed is None
        digest = report.digest()
        assert digest["outcomes"]["shed"] == len(shed)

    def test_stall_cell_carves_credit_stall_subspans(self):
        rep, fe = run_load_cell(
            n=4, seed=1, duration=240, overload=1.0, stall_rank=1,
            stall_at=40, stall_ticks=60, return_frontend=True,
        )
        report = frontend_spans(fe)
        stall_ticks = sum(
            t.by_dst.get(("credit.stall", 1), 0)
            for t in report.requests.values()
        )
        assert stall_ticks > 0, (
            "a 60-tick consumer stall never surfaced as credit.stall "
            "time on the stalled lane"
        )
        # the carving is a sub-partition: queue + credit.stall spans
        # never overlap within a tree (every component span tiles)
        for tree in report.delivered():
            assert tree.delivery_sum() == tree.latency

    def test_inconsistent_stream_is_loud(self):
        events = [
            {"kind": "serve.admit", "tick": 5, "tenant": "t0",
             "qos": "batch", "waited": 0, "stream_seq": 0},
            # a consume with no matching send: causally impossible
            {"kind": "serve.consume", "tick": 9, "tenant": "t0",
             "qos": "batch", "chunk": 0, "dst": 1, "stream_seq": 0},
        ]
        with pytest.raises(SpanError, match="no matching send"):
            build_spans(events)

    def test_walk_complete_mismatch_is_loud(self):
        events = [
            {"kind": "serve.admit", "tick": 5, "tenant": "t0",
             "qos": "batch", "waited": 0, "stream_seq": 0},
            {"kind": "serve.send", "tick": 5, "tenant": "t0",
             "qos": "batch", "chunk": 0, "dst": 1, "stream_seq": 0},
            {"kind": "serve.consume", "tick": 7, "tenant": "t0",
             "qos": "batch", "chunk": 0, "dst": 1, "stream_seq": 0},
            {"kind": "serve.complete", "tick": 11, "tenant": "t0",
             "qos": "batch", "dst": 1, "stream_seq": 0},
        ]
        with pytest.raises(SpanError, match="disagree"):
            build_spans(events)


# ---------------------------------------------------------------------------
# Span exactness across the seeded campaign matrix (the acceptance)
# ---------------------------------------------------------------------------


MATRIX_SEEDS = (0, 7, 23)


class TestSpanExactnessMatrix:
    @pytest.mark.parametrize("seed", MATRIX_SEEDS)
    @pytest.mark.parametrize("shape", [
        ("overload", dict(overload=2.0, duration=240)),
        ("kill", dict(overload=1.0, duration=240, kill_rank=2,
                      kill_at=60)),
        ("stall", dict(overload=1.0, duration=240, stall_rank=1,
                       stall_at=40, stall_ticks=60)),
    ], ids=lambda s: s[0])
    def test_load_cells_are_bit_exact(self, shape, seed):
        """Every request's span-component sum == the front-end's own
        measured admission-to-delivery latency, bit-identically — the
        cell's own gate AND an independent re-derivation here."""
        name, kwargs = shape
        rep, fe = run_load_cell(n=4, seed=seed, return_frontend=True,
                                **kwargs)
        assert rep["ok"], rep["verdict"]
        assert rep["span_exact"] is True
        report = frontend_spans(fe)
        assert exactness_problems(report, fe) == []
        # belt and braces: compare stream by stream, == not approx
        for st in fe.completed:
            tree = report.requests[st.request.stream_id]
            assert tree.delivery_sum() == \
                st.completed_at - st.admitted_at

    @pytest.mark.parametrize("seed", MATRIX_SEEDS)
    @pytest.mark.parametrize("hot", [None, 1], ids=["uniform", "hot"])
    def test_moe_cells_are_bit_exact(self, seed, hot):
        rep = run_moe_cell(n=4, seed=seed, duration=120,
                           hot_expert=hot,
                           batches_per_tick=0.75 if hot else 0.5)
        assert rep["ok"], rep["verdict"]
        assert rep["span_exact"] is True

    @pytest.mark.parametrize("seed", MATRIX_SEEDS[:2])
    def test_retune_cell_is_bit_exact(self, seed):
        rep = run_retune_cell(n=4, seed=seed, duration=160)
        assert rep["ok"], rep["verdict"]
        assert rep["span_exact"] is True

    def test_exactness_detects_a_lying_frontend(self):
        """The gate is a real comparison: perturb the front-end's
        bookkeeping after the run and the check must fail."""
        rep, fe = run_load_cell(n=4, seed=0, duration=120,
                                overload=1.0, return_frontend=True)
        report = frontend_spans(fe)
        assert exactness_problems(report, fe) == []
        fe.completed[0].completed_at += 1
        problems = exactness_problems(report, fe)
        assert problems and "span exactness" in problems[0]


# ---------------------------------------------------------------------------
# Tail-latency blame vs injected ground truth
# ---------------------------------------------------------------------------


class TestBlame:
    @pytest.mark.parametrize("seed,kill", [(0, 2), (3, 0), (8, 2),
                                           (11, 3)])
    def test_kill_cell_blames_the_dead_rank(self, seed, kill):
        rep = run_load_cell(n=4, seed=seed, duration=240,
                            overload=1.0, kill_rank=kill, kill_at=60)
        assert rep["ok"], rep["verdict"]
        verdict = blame_verdict(rep["blame"])
        assert verdict.component == "failover"
        assert verdict.kind == "failover" and verdict.rank == kill

    def test_kill_with_nothing_in_flight_blames_the_heirs_wire(self):
        """A kill that caught zero in-flight streams (suspicion
        drained everything first) has no failover time to blame — the
        binding falls to the heir's wire, which is where the diverted
        load actually bound. Seed pinned from the seeded sweep."""
        rep = run_load_cell(n=4, seed=4, duration=240, overload=1.0,
                            kill_rank=1, kill_at=60)
        assert rep["ok"], rep["verdict"]
        assert "failover" not in rep["spans"]["components_ticks"]
        verdict = blame_verdict(rep["blame"])
        assert verdict.kind == "wire" and verdict.rank is not None

    @pytest.mark.parametrize("seed,stall", [(0, 3), (2, 1), (6, 1),
                                            (9, 2)])
    def test_stall_cell_blames_the_stalled_rank(self, seed, stall):
        rep = run_load_cell(n=4, seed=seed, duration=240,
                            overload=1.0, stall_rank=stall,
                            stall_at=40, stall_ticks=60)
        assert rep["ok"], rep["verdict"]
        verdict = blame_verdict(rep["blame"])
        assert verdict.rank == stall, verdict

    @pytest.mark.parametrize("seed", (0, 7, 11))
    def test_overload_cell_blames_wire_and_brownout_class(self, seed):
        rep = run_load_cell(n=4, seed=seed, duration=240,
                            overload=2.0)
        assert rep["ok"], rep["verdict"]
        verdict = blame_verdict(rep["blame"])
        # the tail of DELIVERED requests bound on the saturated wire;
        # the shed pressure names the browned-out class
        assert verdict.kind == "wire" and verdict.rank is not None
        admission = rep["blame"]["admission"]
        assert admission["brownout_class"] == "best_effort"
        assert admission["brownout_sheds"] > 0

    @pytest.mark.parametrize("seed,hot", [(0, 1), (5, 3)])
    def test_moe_hot_expert_blames_its_home_rank(self, seed, hot):
        rep = run_moe_cell(n=4, seed=seed, duration=120,
                           hot_expert=hot, batches_per_tick=0.75)
        assert rep["ok"], rep["verdict"]
        home = expert_home(hot, 4)
        verdict = blame_verdict(rep["blame"])
        assert verdict.rank == home, verdict

    def test_blame_rows_decompose_p99_into_shares(self):
        rep = run_load_cell(n=4, seed=0, duration=240, overload=2.0)
        for qos, row in rep["blame"]["by_qos"].items():
            if row is None:
                continue
            assert row["p99"] >= row["p50"]
            assert set(row["shares"]) <= set(DELIVERY_COMPONENTS)
            if row["shares"]:
                assert abs(sum(row["shares"].values()) - 1.0) < 0.01
            assert row["decile_count"] == max(
                1, math.ceil(0.1 * row["count"])
            )

    def test_bad_decile_is_loud(self):
        rep, fe = run_load_cell(n=4, seed=0, duration=120,
                                overload=1.0, return_frontend=True)
        with pytest.raises(ValueError, match="decile"):
            blame_report(frontend_spans(fe), decile=0.0)

    def test_format_blame_renders_the_verdict(self):
        rep = run_load_cell(n=4, seed=0, duration=240, overload=2.0)
        text = "\n".join(format_blame(rep["blame"]))
        assert "binding" in text and "brownout class best_effort" \
            in text


# ---------------------------------------------------------------------------
# SLO breaches in the seeded campaigns: deterministic, no false alarms
# ---------------------------------------------------------------------------


class TestSloCampaign:
    @pytest.mark.parametrize("seed", (0, 7, 11, 23))
    def test_brownout_campaign_breaches_deterministically(self, seed):
        """The 2x overload (brownout) cell must fire slo.breach on
        best_effort — the class the ceilings shed first — and the
        breach must be in the event stream, not just the snapshot."""
        rep = run_load_cell(n=4, seed=seed, duration=240,
                            overload=2.0)
        assert rep["ok"], rep["verdict"]
        cls = rep["health"]["classes"]["best_effort"]
        assert cls["breaches"] >= 1
        assert rep["obs"]["event_counts"].get("slo.breach", 0) >= 1
        # brownout is the dominant error reason for the class
        assert cls["errors_by_reason"].get("brownout", 0) > 0
        # and the counters agree with the engine's own bookkeeping
        counters = rep["metrics"]["counters"]
        assert counters.get(
            "slo_breaches_total{qos=best_effort}", 0
        ) == cls["breaches"]

    @pytest.mark.parametrize("seed", (0, 7, 11, 23))
    def test_fair_weather_fires_zero_alarms(self, seed):
        """0.5x load: zero breaches AND zero burn warnings, any seed —
        the noise floor of the signal."""
        rep = run_load_cell(n=4, seed=seed, duration=240,
                            overload=0.5)
        assert rep["ok"], rep["verdict"]
        health = rep["health"]
        assert health["breaches_total"] == 0
        assert all(c["burn_warnings"] == 0
                   for c in health["classes"].values())
        assert rep["obs"]["event_counts"].get("slo.breach", 0) == 0
        assert rep["obs"]["event_counts"].get("slo.burn", 0) == 0

    def test_health_rides_every_cell_report(self):
        for rep in (
            run_load_cell(n=4, seed=0, duration=160, overload=2.0),
            run_moe_cell(n=4, seed=0, duration=120),
            run_retune_cell(n=4, seed=0, duration=160),
        ):
            health = rep["health"]
            assert health["windows"] == list(SLO_WINDOWS)
            assert set(health["classes"]) == set(QOS_CLASSES)

    def test_health_is_deterministic_per_seed(self):
        a = run_load_cell(n=4, seed=5, duration=160, overload=2.0)
        b = run_load_cell(n=4, seed=5, duration=160, overload=2.0)
        assert json.dumps(a["health"], sort_keys=True) == \
            json.dumps(b["health"], sort_keys=True)
        assert json.dumps(a["blame"], sort_keys=True) == \
            json.dumps(b["blame"], sort_keys=True)

    def test_breach_is_observation_not_gate(self):
        """An overload cell breaches AND passes its gates: health is
        a signal for the control loop, never a campaign verdict."""
        rep = run_load_cell(n=4, seed=0, duration=240, overload=2.0)
        assert rep["health"]["breaches_total"] > 0
        assert rep["ok"] is True


# ---------------------------------------------------------------------------
# Serving trace export (per-tenant track groups)
# ---------------------------------------------------------------------------


class TestServingTrace:
    def _trace(self, seed=5):
        from smi_tpu.obs.trace import trace_serving

        rep, fe = run_load_cell(n=4, seed=seed, duration=160,
                                overload=2.0, return_frontend=True)
        return trace_serving(frontend_spans(fe), seed=seed)

    def test_same_seed_byte_identical_file(self):
        from smi_tpu.obs.trace import trace_to_json_bytes

        assert trace_to_json_bytes(self._trace(5)) == \
            trace_to_json_bytes(self._trace(5))

    def test_validates_and_groups_by_tenant(self):
        from smi_tpu.obs.trace import validate_chrome_trace

        payload = self._trace()
        validate_chrome_trace(payload)
        other = payload["otherData"]
        assert other["trace_kind"] == "serving"
        # one process per tenant, named
        processes = [e for e in payload["traceEvents"]
                     if e.get("name") == "process_name"]
        assert len(processes) == other["tenants"]
        assert all(e["args"]["name"].startswith("tenant ")
                   for e in processes)
        # spans carry component cats from the span taxonomy
        cats = {e["cat"] for e in payload["traceEvents"]
                if e["ph"] == "X"}
        assert cats <= set(COMPONENTS) | {"annotation"}
        assert "credit.stall" in cats  # the overload signature

    def test_components_ticks_match_the_span_digest(self):
        rep, fe = run_load_cell(n=4, seed=3, duration=160,
                                overload=2.0, return_frontend=True)
        from smi_tpu.obs.trace import trace_serving

        spans = frontend_spans(fe)
        payload = trace_serving(spans, seed=3)
        digest = spans.digest()
        traced = payload["otherData"]["components_ticks"]
        for c, v in digest["components_ticks"].items():
            assert traced.get(c, 0) == v

    def test_protocol_traces_still_validate_at_v2(self):
        from smi_tpu.obs.trace import (
            trace_protocol,
            validate_chrome_trace,
        )

        payload = trace_protocol("all_reduce", 3)
        assert payload["otherData"]["trace_kind"] == "protocol"
        validate_chrome_trace(payload)

    def test_trace_serving_rejects_non_span_input(self):
        from smi_tpu.obs.trace import trace_serving

        with pytest.raises(TypeError, match="SpanReport"):
            trace_serving({"not": "a span report"})


# ---------------------------------------------------------------------------
# bench.py additive slo field
# ---------------------------------------------------------------------------


def test_bench_slo_field_schema_and_legacy_contract():
    import bench

    fields = bench.slo_fields()
    assert set(fields) == {
        "cell", "fair_weather_burn", "breaches", "p99_blame",
        "binding", "span_exact", "ok",
    }
    # fair weather: zero breaches, zero burn — the noise floor
    assert fields["breaches"] == 0
    assert all(v == 0.0 for v in fields["fair_weather_burn"].values())
    assert fields["span_exact"] is True and fields["ok"] is True
    for qos, row in fields["p99_blame"].items():
        assert qos in QOS_CLASSES
        assert set(row) == {"p99_ticks", "binding", "resource",
                            "shares"}
    # additive: the legacy single-line contract is untouched
    line = bench.render_line({
        "metric": "m", "value": 1, "unit": "u", "vs_baseline": 1.0,
        "slo": fields,
    })
    assert json.loads(line)["slo"] == fields


# ---------------------------------------------------------------------------
# BlameVerdict: the structured verdict accessor (r16)
# ---------------------------------------------------------------------------


class TestBlameVerdict:
    @pytest.mark.parametrize("resource,kind,rank", [
        ("none", "none", None),
        ("wire", "wire", None),
        ("consumer", "consumer", None),
        ("replay", "replay", None),
        ("failover", "failover", None),
        ("wire:rank3", "wire", 3),
        ("consumer:rank0", "consumer", 0),
        ("failover:rank11", "failover", 11),
    ])
    def test_parse_round_trips_the_vocabulary(self, resource, kind,
                                              rank):
        v = parse_blame_resource(resource)
        assert (v.kind, v.rank) == (kind, rank)
        assert v.resource == resource

    @pytest.mark.parametrize("bad", [
        "wires", "wire:", "wire:rank", "wire:rankX", "wire:3",
        "replay:rank1", "none:rank0", "wire:rank-2", "", "rank3",
    ])
    def test_malformed_resource_is_loud(self, bad):
        with pytest.raises(ValueError) as e:
            parse_blame_resource(bad)
        assert repr(bad) in str(e.value)

    def test_accessor_reads_report_binding_and_rows(self):
        rep = run_load_cell(n=4, seed=0, duration=240, overload=2.0)
        top = blame_verdict(rep["blame"])
        assert top == blame_verdict(rep["blame"]["binding"])
        assert isinstance(top, BlameVerdict)
        assert top.resource == rep["blame"]["binding"]["resource"]
        for row in rep["blame"]["by_qos"].values():
            if row is None:
                continue
            v = blame_verdict(row)
            assert v.resource == row["resource"]
            assert v.component == row["binding"]

    def test_accessor_rejects_non_blame_dicts(self):
        with pytest.raises(ValueError):
            blame_verdict({"verdict": "wire:rank1"})
        with pytest.raises(ValueError):
            blame_verdict("wire:rank1")  # type: ignore[arg-type]


# ---------------------------------------------------------------------------
# Wide sweeps behind slow
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(12))
def test_wide_matrix_exactness_and_blame(seed):
    import random

    kill = random.Random(f"k{seed}").randrange(4)
    rep = run_load_cell(n=4, seed=seed, duration=240, overload=1.0,
                        kill_rank=kill, kill_at=60)
    assert rep["ok"] and rep["span_exact"], rep["verdict"]
    if "failover" in rep["spans"]["components_ticks"]:
        verdict = blame_verdict(rep["blame"])
        assert verdict.kind == "failover" and verdict.rank == kill
    stall = random.Random(f"s{seed}").randrange(4)
    rep = run_load_cell(n=4, seed=seed, duration=240, overload=1.0,
                        stall_rank=stall, stall_at=40, stall_ticks=60)
    assert rep["ok"] and rep["span_exact"], rep["verdict"]
    assert blame_verdict(rep["blame"]).rank == stall
