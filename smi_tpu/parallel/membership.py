"""Elastic membership: phi-accrual failure detection, epochs, regrow.

PR 1/2 recover *reactively*: a rank is declared dead only when some
operation deadlocks on it (simulator :class:`~credits.DeadlockError`)
or a watchdog budget expires — by which point every survivor has
already burned a full timeout, and the only way forward is to shrink.
This module adds the two standard production pieces on top
(PAPERS.md):

- a **phi-accrual failure detector** (Hayashibara et al., SRDS'04):
  every rank heartbeats on a deterministic step clock; the detector
  keeps a sliding window of inter-arrival times per rank and computes
  ``phi = -log10(P(a heartbeat this late is still coming))`` under a
  normal model of the window. ``phi`` is a *suspicion level*, not a
  binary verdict: crossing :data:`SUSPECT_PHI` emits
  :class:`SuspectRank` (drain new work away from the rank, keep it in
  the ring), crossing :data:`DEAD_PHI` emits :class:`ConfirmedDead`
  (feed :class:`~smi_tpu.parallel.routing.FailureSet`/recovery and
  shrink) — *before* any watchdog fires, because the detector's
  evidence accrues continuously instead of waiting out one fixed
  budget. A heartbeat from a suspected rank clears the suspicion
  (:class:`SuspicionCleared`): a rank that is alive-but-silent
  (:class:`~smi_tpu.parallel.faults.StalledHeartbeat`) is suspected,
  never killed.
- **epoch-numbered membership** with *regrow* — the inverse of
  :meth:`Communicator.shrink`: a recovered rank re-admits under a new
  epoch and a new incarnation number, the ring re-plans via the
  existing :func:`~smi_tpu.parallel.recovery.plan_ring` /
  :func:`~smi_tpu.parallel.routing.grid_topology` machinery, and any
  traffic still tagged with an old epoch raises
  :class:`StaleEpochError` naming the sender, its stale epoch, and the
  current one — the dead incarnation's packets can never be silently
  folded into the regrown job.

Everything here is pure Python and clock-deterministic (the step clock
is the credits simulator's event count, never wall time), so the
elastic kill→detect→shrink→restore→regrow soak
(:func:`run_elastic_cell` / :func:`elastic_campaign`, the
``smi-tpu chaos --elastic`` surface) replays bit-identically per seed.
The runtime bridge — :meth:`Communicator.regrow` — lives in
:mod:`smi_tpu.parallel.mesh` and delegates its ring validation here.
"""

from __future__ import annotations

import dataclasses
import math
import os
import random
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from smi_tpu.parallel import faults as F

#: Detector thresholds (phi is -log10 of the probability the heartbeat
#: is merely late): suspect at phi >= 4 — a 1-in-10^4 late arrival —
#: and confirm dead at phi >= 8. docs/robustness.md quotes both
#: (drift-guarded by tests/test_perf_docs.py).
SUSPECT_PHI = 4.0
DEAD_PHI = 8.0

#: Nominal heartbeat period in step-clock ticks; the elastic soak
#: advances the clock by one period per job iteration.
HEARTBEAT_INTERVAL = 10

#: Confirmation grace: a suspect is only confirmed dead once it has
#: stayed suspected (phi never dipping below the suspect threshold)
#: for four full heartbeat periods. Suspicion is cheap and reversible
#: (drain new work); death is not (shrink + restore) — the grace is
#: what lets an alive-but-silent rank (``StalledHeartbeat``) be
#: suspected and cleared without ever being killed, while a genuine
#: crash still confirms within ~5-6 periods, far inside any watchdog
#: budget. Four periods, not two: the observable silence of a silent-
#: but-alive rank is its *window* plus up to one period of phase on
#: each side (the last heartbeat before the window and the first
#: scheduled one after it), so the grace must absorb ~2 periods of
#: phase beyond the calibrated window or a healthy rank's clearing
#: beat can lose the race to the confirm poll.
CONFIRM_GRACE_TICKS = 4 * HEARTBEAT_INTERVAL

#: Sliding window of inter-arrival samples per rank.
WINDOW = 32

#: Variance floor (ticks). A perfectly regular simulated heartbeat has
#: zero sample variance; the floor keeps phi finite and calibrated:
#: with mean ~10 and sigma 1, phi crosses the suspect threshold about
#: 4 ticks after a heartbeat was due.
MIN_STD = 1.0


class StaleEpochError(RuntimeError):
    """Traffic tagged with a mismatched membership epoch.

    Raised loudly at the first validation point — never silently
    dropped, never folded into the current epoch's state. Carries the
    sending ``rank``, the ``stale`` epoch it claimed, and the
    ``current`` epoch of the validating view. The wording names the
    party at fault: an OLDER tag means the sender is a superseded
    incarnation (re-join via regrow); a NEWER tag means the
    *validator* missed a membership change (split view) — sending the
    operator to regrow the healthy side would be exactly backwards.
    """

    def __init__(self, rank: int, stale: int, current: int,
                 what: str = "message"):
        if stale > current:
            msg = (
                f"future-epoch {what} from rank {rank}: tagged epoch "
                f"{stale} but this view is at epoch {current} — split "
                f"view: the RECEIVER missed a membership change and "
                f"must resynchronize before trusting its own epoch"
            )
        else:
            msg = (
                f"stale-epoch {what} from rank {rank}: tagged epoch "
                f"{stale} but membership is at epoch {current} — the "
                f"sender is a superseded incarnation and must re-join "
                f"via regrow()"
            )
        super().__init__(msg)
        self.rank = rank
        self.stale = stale
        self.current = current


#: Environment knob for the quorum fraction (the ``default_deadline``
#: discipline: explicit argument outranks the environment, the
#: environment outranks the built-in, malformed values raise loudly).
#: A fraction ``f`` means an actuation needs strictly MORE than ``f``
#: of the members reachable — ``floor(f*n) + 1`` ranks — so the
#: built-in 0.5 is the strict majority and no two disjoint quorums can
#: ever coexist (any valid f >= 0.5 keeps that intersection property,
#: which is the whole point: two sides of a partition can never both
#: fence an actuation in the same epoch).
QUORUM_FRACTION_ENV = "SMI_TPU_QUORUM_FRACTION"

#: Built-in quorum fraction: strict majority.
DEFAULT_QUORUM_FRACTION = 0.5


def quorum_fraction(explicit: Optional[float] = None) -> float:
    """Resolve the quorum fraction: explicit argument over
    ``$SMI_TPU_QUORUM_FRACTION`` over the built-in strict majority.
    Malformed or out-of-range values raise ``ValueError`` loudly —
    a silently-defaulted quorum is a silently-broken safety rail."""
    raw: object = explicit
    source = "quorum fraction"
    if raw is None:
        env = os.environ.get(QUORUM_FRACTION_ENV, "").strip()
        if not env:
            return DEFAULT_QUORUM_FRACTION
        raw = env
        source = f"${QUORUM_FRACTION_ENV}"
    try:
        value = float(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"{source} must be a number in [0.5, 1.0), got {raw!r}"
        ) from None
    if not math.isfinite(value):
        raise ValueError(
            f"{source} must be finite, got {value!r}"
        )
    if not 0.5 <= value < 1.0:
        raise ValueError(
            f"{source} must be in [0.5, 1.0) (below 0.5 two disjoint "
            f"quorums could coexist — the split-brain the fence "
            f"exists to prevent; 1.0 would need n+1 of n ranks), "
            f"got {value!r}"
        )
    return value


def quorum_size(n: int, fraction: Optional[float] = None) -> int:
    """Ranks needed to fence an actuation over an ``n``-member view:
    strictly more than the resolved fraction of the members."""
    if n < 1:
        raise ValueError(f"quorum over an empty view is meaningless, n={n}")
    return int(math.floor(quorum_fraction(fraction) * n)) + 1


class QuorumLostError(RuntimeError):
    """An actuation was attempted from a side of the view that cannot
    reach a quorum of the members — the minority side of a partition.

    Raised loudly at the fencing point, never deferred: the minority
    must PARK (stop accepting new streams, stop mutating shared state)
    and rejoin via the :class:`StaleEpochError` straggler rail once
    the partition heals. Carries the acting ``rank`` (or -1 for the
    control plane itself), the ``reachable`` member set the actor
    could muster, and the ``needed`` quorum size.
    """

    def __init__(self, rank: int, reachable, needed: int,
                 what: str = "actuation"):
        reachable = frozenset(reachable)
        super().__init__(
            f"quorum lost for {what}: rank {rank} reaches only "
            f"{sorted(reachable)} ({len(reachable)} of the {needed} "
            f"needed) — minority side of a partition must park, not "
            f"actuate"
        )
        self.rank = rank
        self.reachable = reachable
        self.needed = needed


@dataclasses.dataclass(frozen=True)
class FencingToken:
    """Proof-of-quorum an actuator must present before mutating shared
    state (epoch bumps, scale in/out, migration cutover, placement
    writes).

    Minted by :func:`mint_fencing_token` only when the minter reaches
    a quorum of the current members, and pinned to the epoch it was
    minted under: a token outlives its epoch the moment membership
    moves, so a partitioned minority holding a stale token is rejected
    on the SAME :class:`StaleEpochError` rail a superseded incarnation
    is — fencing is epoch discipline, not a second mechanism.
    """

    epoch: int
    quorum_set: FrozenSet[int]


@dataclasses.dataclass(frozen=True)
class QuorumDecision:
    """Structured record of one fencing decision — the ``ctl.quorum``
    event's payload (epoch, quorum set, verdict), so Perfetto traces
    and the flight recorder show WHY an actuation was allowed or
    refused next to the blame verdicts that motivated it."""

    epoch: int
    quorum: Tuple[int, ...]
    verdict: str  # "minted" | "granted" | "denied" | "stale"

    def as_fields(self) -> Dict[str, object]:
        return {
            "epoch": self.epoch,
            "quorum": ",".join(str(r) for r in self.quorum),
            "verdict": self.verdict,
        }


def _observe_quorum(view: "MembershipView", decision: QuorumDecision,
                    rank: int) -> None:
    recorder = getattr(view, "_recorder", None)
    if recorder is not None:
        recorder.emit("ctl.quorum", view.epoch, rank=rank,
                      **decision.as_fields())


def mint_fencing_token(view: "MembershipView",
                       reachable: Optional[Sequence[int]] = None,
                       fraction: Optional[float] = None,
                       rank: int = -1,
                       what: str = "actuation") -> FencingToken:
    """Mint a :class:`FencingToken` for the current epoch, or raise
    :class:`QuorumLostError` if ``reachable`` (the members the minter
    can currently hear; default: all of them — the healthy fast path)
    falls short of the quorum. Every decision — grant or denial — is
    observed as a ``ctl.quorum`` event when the view has a recorder.
    """
    members = frozenset(view.members)
    if reachable is None:
        quorum = members
    else:
        quorum = frozenset(reachable) & members
    needed = quorum_size(len(members), fraction)
    if len(quorum) < needed:
        _observe_quorum(
            view, QuorumDecision(view.epoch, tuple(sorted(quorum)),
                                 "denied"), rank,
        )
        raise QuorumLostError(rank, quorum, needed, what=what)
    token = FencingToken(epoch=view.epoch, quorum_set=quorum)
    _observe_quorum(
        view, QuorumDecision(view.epoch, tuple(sorted(quorum)),
                             "minted"), rank,
    )
    return token


def check_fencing_token(view: "MembershipView",
                        token: Optional[FencingToken],
                        rank: int = -1,
                        fraction: Optional[float] = None,
                        what: str = "actuation") -> FencingToken:
    """Validate (or mint, when ``token`` is None — the backward-
    compatible healthy path, trivially quorate over the full member
    set) the fencing token guarding an actuation.

    A token from an older epoch is a straggler from before a
    membership change and is rejected as :class:`StaleEpochError` —
    the same rail, deliberately. A current-epoch token whose quorum
    set no longer covers a quorum of the members (possible only if
    the caller forged or filtered it) raises
    :class:`QuorumLostError`. Returns the validated token."""
    if token is None:
        return mint_fencing_token(view, fraction=fraction, rank=rank,
                                  what=what)
    if token.epoch != view.epoch:
        _observe_quorum(
            view, QuorumDecision(token.epoch,
                                 tuple(sorted(token.quorum_set)),
                                 "stale"), rank,
        )
        raise StaleEpochError(rank, token.epoch, view.epoch,
                              what=f"fencing token for {what}")
    members = frozenset(view.members)
    quorum = frozenset(token.quorum_set) & members
    needed = quorum_size(len(members), fraction)
    if len(quorum) < needed:
        _observe_quorum(
            view, QuorumDecision(token.epoch, tuple(sorted(quorum)),
                                 "denied"), rank,
        )
        raise QuorumLostError(rank, quorum, needed, what=what)
    _observe_quorum(
        view, QuorumDecision(token.epoch, tuple(sorted(quorum)),
                             "granted"), rank,
    )
    return token


@dataclasses.dataclass(frozen=True)
class SuspectRank:
    """phi crossed :data:`SUSPECT_PHI`: stop routing new work to the
    rank, keep it in the ring — it may just be slow or silent."""

    rank: int
    phi: float
    step: int


@dataclasses.dataclass(frozen=True)
class SuspicionCleared:
    """A suspected rank heartbeated again: it was alive-but-silent."""

    rank: int
    step: int


@dataclasses.dataclass(frozen=True)
class ConfirmedDead:
    """phi crossed :data:`DEAD_PHI`: treat as crash-stopped — feed the
    FailureSet, shrink, restore. A later heartbeat from this
    incarnation is stale-epoch traffic, not a resurrection."""

    rank: int
    phi: float
    step: int


class StepClock:
    """Deterministic integer clock — the credits simulator's event
    count, never wall time. Everything downstream of it (phi, the
    elastic soak, the campaign reports) replays bit-identically."""

    def __init__(self, start: int = 0):
        self._now = int(start)

    def now(self) -> int:
        return self._now

    def advance(self, ticks: int = 1) -> int:
        if ticks < 0:
            raise ValueError(f"clock cannot run backwards ({ticks})")
        self._now += int(ticks)
        return self._now


def _phi_from(elapsed: float, mean: float, std: float) -> float:
    """phi = -log10(P(interval > elapsed)) under Normal(mean, std)."""
    std = max(std, MIN_STD)
    p_later = 0.5 * math.erfc((elapsed - mean) / (std * math.sqrt(2.0)))
    if p_later <= 0.0:
        return float("inf")
    return -math.log10(p_later)


class PhiAccrualDetector:
    """The phi-accrual failure detector over a :class:`StepClock`.

    Call :meth:`heartbeat` as arrivals land and :meth:`poll` once per
    scheduling decision; ``poll`` returns the *transitions* since the
    last call (:class:`SuspectRank` / :class:`SuspicionCleared` /
    :class:`ConfirmedDead`), each at most once per episode. Ranks with
    fewer than two arrivals are in bootstrap and never suspected —
    there is no interval distribution to accrue against yet.
    """

    def __init__(self, clock: StepClock, ranks: Sequence[int],
                 suspect_phi: float = SUSPECT_PHI,
                 dead_phi: float = DEAD_PHI,
                 window: int = WINDOW,
                 confirm_grace: int = CONFIRM_GRACE_TICKS):
        if dead_phi <= suspect_phi:
            raise ValueError(
                f"dead_phi {dead_phi} must exceed suspect_phi "
                f"{suspect_phi}: suspicion is the milder verdict"
            )
        self.clock = clock
        self.suspect_phi = suspect_phi
        self.dead_phi = dead_phi
        self.window = window
        self.confirm_grace = confirm_grace
        self._last: Dict[int, int] = {}
        self._intervals: Dict[int, List[int]] = {r: [] for r in ranks}
        self.suspected: Set[int] = set()
        self._suspected_at: Dict[int, int] = {}
        self.dead: Set[int] = set()

    def heartbeat(self, rank: int) -> None:
        if rank not in self._intervals:
            raise ValueError(f"unknown rank {rank}")
        if rank in self.dead:
            # the detector's verdict is monotone; resurrection is the
            # membership layer's regrow decision, not a heartbeat's
            return
        now = self.clock.now()
        prev = self._last.get(rank)
        if prev is not None:
            samples = self._intervals[rank]
            samples.append(now - prev)
            if len(samples) > self.window:
                del samples[: len(samples) - self.window]
        self._last[rank] = now

    def phi(self, rank: int) -> float:
        samples = self._intervals.get(rank)
        if not samples or rank not in self._last:
            return 0.0  # bootstrap: no distribution to accrue against
        elapsed = self.clock.now() - self._last[rank]
        mean = sum(samples) / len(samples)
        var = sum((s - mean) ** 2 for s in samples) / len(samples)
        return _phi_from(elapsed, mean, math.sqrt(var))

    def poll(self) -> List:
        """Transitions since the last poll, in rank order.

        Death is two-phase: a rank must first cross the suspect
        threshold, then stay suspected for :attr:`confirm_grace` ticks
        with phi at or above the dead threshold — so a brief silence
        is suspected and cleared, never killed, and no rank can jump
        from healthy to dead in one poll.
        """
        out: List = []
        now = self.clock.now()
        for rank in sorted(self._intervals):
            if rank in self.dead:
                continue
            phi = self.phi(rank)
            if rank in self.suspected:
                if phi < self.suspect_phi:
                    self.suspected.discard(rank)
                    self._suspected_at.pop(rank, None)
                    out.append(SuspicionCleared(rank, now))
                elif (phi >= self.dead_phi
                      and now - self._suspected_at[rank]
                      >= self.confirm_grace):
                    self.suspected.discard(rank)
                    self._suspected_at.pop(rank, None)
                    self.dead.add(rank)
                    out.append(ConfirmedDead(rank, phi, now))
            elif phi >= self.suspect_phi:
                self.suspected.add(rank)
                self._suspected_at[rank] = now
                out.append(SuspectRank(rank, phi, now))
        return out

    def forget(self, rank: int) -> None:
        """Drop a rank's history — called on regrow so the re-admitted
        incarnation bootstraps fresh instead of inheriting the dead
        incarnation's silence."""
        self.dead.discard(rank)
        self.suspected.discard(rank)
        self._suspected_at.pop(rank, None)
        self._last.pop(rank, None)
        self._intervals[rank] = []


@dataclasses.dataclass
class MembershipView:
    """Epoch-numbered view of who is in the job.

    Every change of composition — a confirmed death, a regrow — bumps
    ``epoch``; traffic carries the epoch it was sent under and
    :meth:`validate` rejects anything stale with
    :class:`StaleEpochError`. ``incarnation[r]`` counts how many times
    rank ``r`` has been admitted, so a regrown rank is distinguishable
    from its dead predecessor even within one process.
    """

    n: int
    epoch: int = 0
    members: Set[int] = dataclasses.field(default_factory=set)
    incarnation: Dict[int, int] = dataclasses.field(default_factory=dict)
    #: (epoch, kind, rank) history — the campaign report's audit trail.
    transitions: List[Tuple[int, str, int]] = dataclasses.field(
        default_factory=list
    )

    def __post_init__(self):
        if not self.members:
            self.members = set(range(self.n))
        if not self.incarnation:
            self.incarnation = {r: 0 for r in range(self.n)}

    @property
    def dead(self) -> Set[int]:
        return set(range(self.n)) - self.members

    def attach_recorder(self, recorder) -> "MembershipView":
        """Attach a flight recorder (duck-typed,
        :class:`smi_tpu.obs.events.FlightRecorder`): every epoch bump
        — shrink or regrow — emits a ``ctl.shrink`` / ``ctl.regrow``
        control-plane event stamped with the new epoch. Deliberately
        an instance attribute, NOT a dataclass field: the model
        checker fingerprints views by their fields, and an attached
        recorder must never split behaviourally-identical states.
        Returns ``self`` for chaining."""
        self._recorder = recorder
        return self

    def _observe(self, kind: str, rank: int, reason: str) -> None:
        recorder = getattr(self, "_recorder", None)
        if recorder is not None:
            recorder.emit(kind, self.epoch, rank=rank,
                          epoch=self.epoch, reason=reason)

    def confirm_dead(self, rank: int) -> int:
        """Remove a rank under a new epoch; returns the new epoch."""
        if rank not in self.members:
            raise ValueError(f"rank {rank} is not a member")
        if len(self.members) == 1:
            raise ValueError(
                f"cannot remove rank {rank}: it is the last member"
            )
        self.members.discard(rank)
        self.epoch += 1
        self.transitions.append((self.epoch, "dead", rank))
        self._observe("ctl.shrink", rank, "confirmed-dead")
        return self.epoch

    def regrow(self, rank: int) -> int:
        """Re-admit a recovered rank under a new epoch + incarnation.

        The inverse of shrink. The caller is responsible for restoring
        the rank's application state (checkpoint manifest) and
        re-planning the ring (:func:`plan_regrow_ring`) before routing
        traffic to it. Returns the new epoch.
        """
        if rank in self.members:
            raise ValueError(f"rank {rank} is already a member")
        if not 0 <= rank < self.n:
            raise ValueError(f"rank {rank} out of range for n={self.n}")
        self.members.add(rank)
        self.incarnation[rank] += 1
        self.epoch += 1
        self.transitions.append((self.epoch, "regrow", rank))
        self._observe("ctl.regrow", rank, "rejoin")
        return self.epoch

    def scale_in(self, rank: int, reason: str = "demand") -> int:
        """Park a healthy rank under a new epoch — capacity scale-in.

        Same composition change as :meth:`confirm_dead` (the epoch is
        the safety rail either way) but booked as a ``scale-in``
        transition and observed as a ``ctl.scale`` event: an operator
        reading the audit trail must be able to tell a deliberate
        capacity decision from a death. Returns the new epoch.
        """
        if rank not in self.members:
            raise ValueError(f"rank {rank} is not a member")
        if len(self.members) == 1:
            raise ValueError(
                f"cannot scale in rank {rank}: it is the last member"
            )
        self.members.discard(rank)
        self.epoch += 1
        self.transitions.append((self.epoch, "scale-in", rank))
        recorder = getattr(self, "_recorder", None)
        if recorder is not None:
            recorder.emit("ctl.scale", self.epoch, rank=rank,
                          epoch=self.epoch, direction="in", reason=reason)
        return self.epoch

    def scale_out(self, rank: int, reason: str = "demand") -> int:
        """Re-admit a parked rank under a new epoch + incarnation —
        capacity scale-out, the inverse of :meth:`scale_in`. Booked as
        a ``scale-out`` transition / ``ctl.scale`` event so demand
        actuation and failure recovery stay distinguishable in the
        audit trail. Returns the new epoch."""
        if rank in self.members:
            raise ValueError(f"rank {rank} is already a member")
        if not 0 <= rank < self.n:
            raise ValueError(f"rank {rank} out of range for n={self.n}")
        self.members.add(rank)
        self.incarnation[rank] += 1
        self.epoch += 1
        self.transitions.append((self.epoch, "scale-out", rank))
        recorder = getattr(self, "_recorder", None)
        if recorder is not None:
            recorder.emit("ctl.scale", self.epoch, rank=rank,
                          epoch=self.epoch, direction="out", reason=reason)
        return self.epoch

    def migrate_cutover(self, src: int, dst: int,
                        tenant: str = "",
                        token: Optional[FencingToken] = None) -> int:
        """Bump the epoch for a live-migration lane switch.

        Membership does not change — both ranks stay members — but the
        epoch must move so stragglers still addressed to the source
        lane are rejected as :class:`StaleEpochError` instead of being
        folded into the destination silently (the same rail a failover
        uses, chosen on purpose). The cutover is a fenced actuation:
        ``token`` (minted trivially from the full member set when
        None) must prove quorum under the CURRENT epoch or the switch
        refuses — a partitioned minority can never cut a migration
        over both ways. Returns the new epoch.
        """
        check_fencing_token(self, token, rank=dst,
                            what=f"migration cutover {src}->{dst}")
        for r, role in ((src, "source"), (dst, "destination")):
            if r not in self.members:
                raise ValueError(
                    f"migration {role} rank {r} is not a member"
                )
        if src == dst:
            raise ValueError(
                f"migration source and destination are both rank {src}"
            )
        self.epoch += 1
        self.transitions.append((self.epoch, "migrate", dst))
        recorder = getattr(self, "_recorder", None)
        if recorder is not None:
            recorder.emit("ctl.migrate", self.epoch, rank=dst,
                          epoch=self.epoch, src=src, dst=dst,
                          state="cutover", tenant=tenant)
        return self.epoch

    def validate(self, rank: int, epoch: int, what: str = "message") -> None:
        """Reject traffic from a mismatched epoch or a non-member (the
        error's wording distinguishes stale sender from split view)."""
        if epoch != self.epoch:
            raise StaleEpochError(rank, epoch, self.epoch, what=what)
        if rank not in self.members:
            raise StaleEpochError(rank, epoch, self.epoch,
                                  what=f"{what} from a non-member")

    def failure_set(self, topology=None):
        """The routing :class:`~smi_tpu.parallel.routing.FailureSet`
        for the current dead set — dead ranks' devices go down but
        keep their rank slots, exactly the shape degraded routing
        expects. ``topology`` defaults to the 1-D ring of ``n``."""
        from smi_tpu.parallel.routing import FailureSet, grid_topology

        topo = topology if topology is not None else grid_topology(1, self.n)
        return FailureSet(
            devices=frozenset(topo.devices[r] for r in sorted(self.dead))
        )


def route_owner(view: MembershipView, rank: int,
                n: Optional[int] = None) -> Optional[int]:
    """The member currently serving ``rank``'s duties: the rank itself
    while it is a member, else its heir among the current members
    (:func:`~smi_tpu.parallel.recovery.heir_of` — nearest surviving
    successor), else ``None`` when nobody survives. The single
    authority for "who owns rank r now", shared by the elastic soak's
    block ownership and the serving front-end's tenant failover."""
    from smi_tpu.parallel.recovery import heir_of

    n = view.n if n is None else n
    members = view.members
    if rank in members:
        return rank
    if not members:
        return None
    return heir_of(rank, members, n)


def plan_regrow_ring(view: MembershipView,
                     down_pairs: Sequence[Tuple[int, int]] = ()
                     ) -> List[int]:
    """The ring order after a membership change, re-derived through the
    existing machinery: :func:`~smi_tpu.parallel.recovery.plan_ring`
    orders the members around any down wires, and the 1-D
    :func:`~smi_tpu.parallel.routing.grid_topology` with the dead
    ranks' devices excluded must still route every member pair (a
    regrow that would strand a member raises
    :class:`~smi_tpu.parallel.routing.RouteCutError` naming the cut).
    """
    from smi_tpu.parallel.recovery import plan_ring
    from smi_tpu.parallel.routing import (
        build_routing_context,
        check_all_pairs_routable,
        grid_topology,
    )

    members = sorted(view.members)
    order, extra = plan_ring(members, down_pairs, view.n)
    if extra:
        raise ValueError(
            f"regrow ring cannot separate down pairs {sorted(down_pairs)} "
            f"without shrinking {sorted(extra)}; shrink first"
        )
    cut = view.failure_set()
    topo = grid_topology(1, view.n)
    ctx = build_routing_context(topo, excluded=cut)
    check_all_pairs_routable(ctx, [topo.devices[r] for r in order])
    return order


def shrink_pod(view: MembershipView, detector, rank: int,
               reason: str = "demand",
               token: Optional[FencingToken] = None) -> int:
    """Capacity scale-in actuator: park ``rank`` out of the serving
    pod. The step-clock analog of ``Communicator.shrink_pod``, driven
    by *demand* instead of death: the epoch bumps (``scale-in``
    transition + ``ctl.scale`` event), the post-shrink ring is
    validated routable (:func:`plan_regrow_ring` — a scale-in that
    would strand a member raises instead of landing), and the phi
    detector forgets the rank so a deliberately-parked rank can never
    accrue suspicion while silent. A fenced actuation: ``token``
    (minted trivially from the full member set when None) must prove
    quorum under the current epoch (:func:`check_fencing_token`) or
    the scale-in refuses loudly. Returns the new epoch."""
    check_fencing_token(view, token, rank=rank,
                        what=f"scale-in of rank {rank}")
    epoch = view.scale_in(rank, reason=reason)
    plan_regrow_ring(view)
    if detector is not None:
        detector.forget(rank)
    return epoch


def regrow_pod(view: MembershipView, detector, rank: int,
               reason: str = "demand",
               token: Optional[FencingToken] = None) -> int:
    """Capacity scale-out actuator: re-admit a parked rank (the
    inverse of :func:`shrink_pod`). Epoch bumps under a ``scale-out``
    transition, the grown ring is validated routable, and the detector
    forgets the rank so the fresh incarnation bootstraps its heartbeat
    history clean (the :meth:`MembershipView.regrow` discipline).
    Fenced exactly like :func:`shrink_pod`: no quorum token, no
    capacity change. Returns the new epoch."""
    check_fencing_token(view, token, rank=rank,
                        what=f"scale-out of rank {rank}")
    epoch = view.scale_out(rank, reason=reason)
    plan_regrow_ring(view)
    if detector is not None:
        detector.forget(rank)
    return epoch


# ---------------------------------------------------------------------------
# Pod-of-slices membership: two-tier rings, heirs, elastic soak
# ---------------------------------------------------------------------------


def pod_heir_of(rank: int, survivors, slices: int, per_slice: int) -> int:
    """The pod inheritance rule: a dead rank's duties pass to its
    nearest surviving successor ON ITS SLICE RING first (the heir can
    read the shard over ICI and the slice ring re-closes locally);
    only when the whole slice is dead does inheritance cross to the
    global successor — the flat-fallback shape where DCN is already
    being paid. Degenerates to :func:`~recovery.heir_of` at one
    slice."""
    from smi_tpu.parallel.recovery import heir_of

    n = slices * per_slice
    surv = set(survivors)
    s, i = divmod(rank, per_slice)
    for step in range(1, per_slice):
        cand = s * per_slice + (i + step) % per_slice
        if cand in surv:
            return cand
    return heir_of(rank, surv, n)


@dataclasses.dataclass(frozen=True)
class PodRingPlan:
    """The executable ring layout after a pod membership change.

    ``hierarchical`` layouts carry one (possibly shrunk) ring per
    surviving slice plus the cross-slice leader ring; the
    ``flat_ring`` fallback (any slice annihilated, or a single
    surviving slice) is the one-ring-over-survivors shape every
    collective can always run."""

    slice_rings: Tuple[Tuple[int, ...], ...] = ()
    cross_ring: Tuple[int, ...] = ()
    flat_ring: Optional[Tuple[int, ...]] = None

    @property
    def hierarchical(self) -> bool:
        return self.flat_ring is None


def plan_pod_rings(view: MembershipView, slices: int,
                   per_slice: int) -> PodRingPlan:
    """Ring layout for the current members of a (slices, per_slice)
    pod, validated against the pod topology with the dead devices
    excluded (the same :func:`~routing.check_all_pairs_routable`
    discipline as :func:`plan_regrow_ring` — a plan that would strand
    a member raises :class:`~routing.RouteCutError` naming the cut).

    - a dead RANK shrinks its slice ring: the slice keeps ringing
      over its survivors, the cross ring connects each surviving
      slice's leader (lowest surviving rank), and the hierarchical
      protocol stays on;
    - a dead SLICE (no survivors in some slice) — or a pod reduced to
      one surviving slice — falls back to the flat ring over all
      survivors: with a tier gone there is nothing to tier over.
    """
    from smi_tpu.parallel.routing import (
        FailureSet,
        build_routing_context,
        check_all_pairs_routable,
        pod_topology,
    )

    n = slices * per_slice
    if view.n != n:
        raise ValueError(
            f"view over {view.n} ranks does not match the "
            f"{slices}x{per_slice} pod"
        )
    members = sorted(view.members)
    topo = pod_topology(slices, per_slice)
    cut = FailureSet(
        devices=frozenset(topo.devices[r] for r in sorted(view.dead))
    )
    ctx = build_routing_context(topo, excluded=cut)
    check_all_pairs_routable(ctx, [topo.devices[r] for r in members])
    per = [
        tuple(r for r in members if r // per_slice == s)
        for s in range(slices)
    ]
    live = [ring for ring in per if ring]
    if len(live) < len(per) or len(live) < 2:
        return PodRingPlan(flat_ring=tuple(members))
    return PodRingPlan(
        slice_rings=tuple(live),
        cross_ring=tuple(ring[0] for ring in live),
    )


def run_pod_cell(
    slices: int,
    per_slice: int,
    kill: str,
    seed: int,
    iterations: int = 18,
    cadence: int = 3,
    rows_per_rank: int = 3,
    width: int = 8,
    checkpoint_dir: Optional[str] = None,
) -> Dict:
    """One pod elastic soak cell: the sharded Jacobi job on a
    (slices, per_slice) pod healed through a seeded kill.

    ``kill="rank"`` crash-stops one seeded rank (its slice ring
    shrinks, the plan stays hierarchical); ``kill="slice"`` crash-
    stops a whole seeded slice (the plan must fall back to the flat
    ring over the survivors). Either way: shrink under new epochs,
    restore ALL state from the last complete manifest and replay the
    tail, reject the dead incarnation's stale-epoch traffic loudly,
    regrow under a fresh epoch with the hierarchical plan restored,
    and finish bit-identical to the fault-free run. Deterministic per
    ``(shape, kill, seed)``.
    """
    import numpy as np

    from smi_tpu.parallel.checkpoint import CheckpointStore

    if kill not in ("rank", "slice"):
        raise ValueError(f"kill must be 'rank' or 'slice', got {kill!r}")
    if slices < 2 or per_slice < 1:
        raise ValueError(
            f"pod soak needs >= 2 slices (got {slices}x{per_slice})"
        )
    n = slices * per_slice
    rng = random.Random(f"pod:{slices}x{per_slice}:{kill}:{seed}")
    view = MembershipView(n)
    grid0 = _initial_grid(n * rows_per_rank, width)
    blocks = {
        r: grid0[r * rows_per_rank:(r + 1) * rows_per_rank].copy()
        for r in range(n)
    }
    store = CheckpointStore(checkpoint_dir) if checkpoint_dir else None

    if kill == "rank":
        victims = [rng.randrange(n)]
    else:
        s = rng.randrange(slices)
        victims = list(range(s * per_slice, (s + 1) * per_slice))
    dies_at = 2 + rng.randrange(3)
    rejoins_at = dies_at + 4 + rng.randrange(3)

    report: Dict = {
        "slices": slices, "per_slice": per_slice, "kill": kill,
        "seed": seed, "victims": victims, "dies_at": dies_at,
        "rejoins_at": rejoins_at, "iterations": iterations,
        "shrinks": 0, "regrows": 0, "restores": 0, "checkpoints": 0,
        "replayed_iterations": 0, "stale_epoch_rejections": 0,
        "stale_epoch_leaks": 0, "plan_modes": [], "verdict": "ok",
    }

    def owners_now() -> Dict[int, Optional[int]]:
        members = view.members
        return {
            r: (r if r in members
                else pod_heir_of(r, members, slices, per_slice)
                if members else None)
            for r in range(n)
        }

    def checkpoint() -> None:
        if store is not None:
            store.save(it, blocks, epoch=view.epoch)
            report["checkpoints"] += 1

    it = 0
    checkpoint()
    killed = False
    death_epoch = view.epoch
    while it < iterations:
        if not killed and it == dies_at:
            death_epoch = view.epoch
            for r in victims:
                view.confirm_dead(r)
                report["shrinks"] += 1
            plan = plan_pod_rings(view, slices, per_slice)
            report["plan_modes"].append(
                "hierarchical" if plan.hierarchical else "flat"
            )
            want_hier = kill == "rank" and per_slice > 1
            if plan.hierarchical != want_hier:
                report["verdict"] = (
                    f"{kill} kill planned "
                    f"{'hierarchical' if plan.hierarchical else 'flat'}"
                    f", wanted "
                    f"{'hierarchical' if want_hier else 'flat'}"
                )
                return report
            if store is not None:
                restored = store.restore()
                if restored is None:
                    report["verdict"] = "no complete manifest to restore"
                    return report
                step, shards, _epoch = restored
                for r, payload in shards.items():
                    blocks[r] = payload
                report["restores"] += 1
                report["replayed_iterations"] += it - step
                it = step
            killed = True
            continue
        if killed and victims and it == rejoins_at:
            # the dead incarnation presents its pre-shrink epoch: the
            # gate must reject it loudly, never fold it in
            for r in victims:
                try:
                    view.validate(r, death_epoch, what="rejoin request")
                    report["stale_epoch_leaks"] += 1
                except StaleEpochError:
                    report["stale_epoch_rejections"] += 1
            checkpoint()  # regrow barrier: newcomers restore this state
            for r in victims:
                view.regrow(r)
                report["regrows"] += 1
            plan = plan_pod_rings(view, slices, per_slice)
            report["plan_modes"].append(
                "hierarchical" if plan.hierarchical else "flat"
            )
            if not plan.hierarchical and per_slice > 1:
                report["verdict"] = "regrown pod did not restore tiering"
                return report
            if store is not None:
                restored = store.restore()
                step, shards, _epoch = restored
                for r in victims:
                    blocks[r] = shards[r]
            # one straggler packet from the dead incarnation arrives
            # AFTER the regrow: reject, never fold in
            for r in victims:
                try:
                    view.validate(r, view.epoch - 1,
                                  what="straggler halo")
                    report["stale_epoch_leaks"] += 1
                except StaleEpochError:
                    report["stale_epoch_rejections"] += 1
            victims = []
        owners = owners_now()
        blocks = _jacobi_sweep(blocks, owners, view, n)
        it += 1
        if it % cadence == 0:
            checkpoint()

    final = np.concatenate([blocks[r] for r in range(n)])
    want = _fault_free_grid(grid0, iterations)
    problems = []
    if not np.array_equal(final, want):
        problems.append("silent corruption: final grid differs")
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    if problems:
        report["verdict"] = "; ".join(problems)
    report["epoch"] = view.epoch
    report["members"] = sorted(view.members)
    return report


def pod_campaign(
    seed: int,
    shapes: Sequence[Tuple[int, int]] = ((2, 2), (2, 3), (3, 2)),
    trials: int = 2,
    iterations: int = 18,
    cadence: int = 3,
    checkpoint_root: Optional[str] = None,
) -> Dict:
    """Seeded pod soak: kill-one-rank AND kill-one-slice cells over
    several (slices, per_slice) shapes, gated like the elastic
    campaign on zero silent corruption and zero stale-epoch leaks."""
    import os
    import tempfile

    outcomes: Dict[str, int] = {}
    failures: List[Dict] = []
    cells = 0
    stale_rejections = 0
    for slices, per_slice in shapes:
        for kill in ("rank", "slice"):
            for trial in range(trials):
                cells += 1
                cell_seed = random.Random(
                    f"pod:{seed}:{slices}x{per_slice}:{kill}:{trial}"
                ).randrange(1 << 31)
                with tempfile.TemporaryDirectory(
                    dir=checkpoint_root
                ) as ckpt:
                    report = run_pod_cell(
                        slices, per_slice, kill, cell_seed,
                        iterations=iterations, cadence=cadence,
                        checkpoint_dir=os.path.join(ckpt, "shards"),
                    )
                stale_rejections += report["stale_epoch_rejections"]
                if report["verdict"] != "ok":
                    outcomes["failed"] = outcomes.get("failed", 0) + 1
                    failures.append({
                        "slices": slices, "per_slice": per_slice,
                        "kill": kill, "trial": trial,
                        "cell_seed": cell_seed,
                        "verdict": report["verdict"],
                    })
                    continue
                key = f"regrown-{kill}"
                outcomes[key] = outcomes.get(key, 0) + 1
    silent = sum(
        1 for f in failures if "silent corruption" in f["verdict"]
    )
    stale_leaks = sum(
        1 for f in failures if "stale-epoch" in f["verdict"]
    )
    return {
        "seed": seed,
        "shapes": [list(s) for s in shapes],
        "trials": trials,
        "cells": cells,
        "outcomes": outcomes,
        "failures": failures,
        "silent_corruptions": silent,
        "stale_epoch_leaks": stale_leaks,
        "stale_epoch_rejections": stale_rejections,
        "ok": not failures,
    }


# ---------------------------------------------------------------------------
# The elastic soak: kill -> detect -> shrink -> restore -> regrow
# ---------------------------------------------------------------------------

#: Watchdog budget in clock ticks the detector must beat: the PR-1
#: deadline layer would declare the job hung only after this long with
#: no progress. phi-accrual confirms within ~one heartbeat period.
WATCHDOG_TICKS = 12 * HEARTBEAT_INTERVAL


@dataclasses.dataclass
class _EpochMessage:
    """A halo slab on the elastic job's wire, epoch-tagged."""

    src: int
    epoch: int
    payload: object


def _jacobi_sweep(blocks: Dict[int, "object"], owners: Dict[int, int],
                  view: MembershipView, n: int):
    """One global Jacobi sweep over per-rank row blocks.

    Every block's top/bottom halo rows travel as epoch-tagged messages
    validated by the membership view — the soak's data plane. Math is
    the models' reference update (``models.stencil.reference_stencil``)
    split by row block: Dirichlet boundary rows held, interior cells
    averaging their four neighbours. Owners compute dead ranks' blocks
    (heir inheritance), so the global grid is identical to the
    fault-free run's no matter the membership.
    """
    import numpy as np

    def rows_of(r):
        return blocks[r]

    new: Dict[int, object] = {}
    for r in range(n):
        owner = owners[r]
        if owner is None:
            raise RuntimeError(f"block {r} has no live owner")
        block = rows_of(r)
        up = None if r == 0 else _EpochMessage(
            owners[r - 1], view.epoch, rows_of(r - 1)[-1]
        )
        down = None if r == n - 1 else _EpochMessage(
            owners[r + 1], view.epoch, rows_of(r + 1)[0]
        )
        for msg in (up, down):
            if msg is not None:
                view.validate(msg.src, msg.epoch, what="halo slab")
        h, w = block.shape
        padded = np.zeros((h + 2, w), dtype=block.dtype)
        padded[1:-1] = block
        padded[0] = up.payload if up is not None else block[0]
        padded[-1] = down.payload if down is not None else block[-1]
        avg = 0.25 * (
            padded[:-2, 1:-1] + padded[2:, 1:-1]
            + padded[1:-1, :-2] + padded[1:-1, 2:]
        )
        out = block.copy()
        interior_top = 1 if r == 0 else 0
        interior_bot = h - 1 if r == n - 1 else h
        out[interior_top:interior_bot, 1:-1] = (
            avg[interior_top:interior_bot]
        )
        new[r] = out
    return new


def _initial_grid(x: int, y: int):
    """Hot-top-edge Jacobi start (``models.stencil.initial_grid`` in
    float64, inlined so the soak never imports the JAX model stack)."""
    import numpy as np

    g = np.zeros((x, y), dtype=np.float64)
    g[0, :] = 1.0
    return g


def _fault_free_grid(grid0, iterations: int):
    """Serial Jacobi yardstick — the exact update of
    ``models.stencil.reference_stencil``, term order included, so the
    healed run's bit-identity claim is against the models' math."""
    g = grid0.copy()
    for _ in range(iterations):
        avg = 0.25 * (
            g[:-2, 1:-1] + g[2:, 1:-1] + g[1:-1, :-2] + g[1:-1, 2:]
        )
        g[1:-1, 1:-1] = avg
    return g


def run_elastic_cell(
    n: int,
    plan: F.FaultPlan,
    seed: int,
    iterations: int = 24,
    cadence: int = 4,
    rows_per_rank: int = 3,
    width: int = 8,
    checkpoint_dir: Optional[str] = None,
) -> Dict:
    """One elastic soak cell: a sharded Jacobi job under an elastic
    fault plan, healed end to end.

    The job runs ``iterations`` sweeps of the reference Jacobi update
    over ``n`` per-rank row blocks, checkpointing every ``cadence``
    iterations (sharded CRC-framed shards + atomic manifest,
    :mod:`smi_tpu.parallel.checkpoint`). Heartbeats tick on the step
    clock with seeded jitter; the phi-accrual detector drives:

    - :class:`~faults.FlappingRank` — the rank stops heartbeating and
      computing at ``dies_at``; the job *stalls* (a real collective
      would block) while phi accrues; ``ConfirmedDead`` must land
      before :data:`WATCHDOG_TICKS` of stall, then the survivors
      shrink (epoch bump), heirs inherit the dead block, ALL state
      restores from the last complete manifest, and the tail replays.
      At ``rejoins_at`` the rank's new incarnation first presents its
      old epoch — rejected loudly (:class:`StaleEpochError`, counted)
      — then regrows under a fresh epoch (ring re-planned via
      :func:`plan_regrow_ring`), restores from the manifest the
      survivors cut at the regrow barrier, and finishes in place.
    - :class:`~faults.StalledHeartbeat` — the rank computes but its
      heartbeats go silent for ``silent_for`` ticks: it must be
      *suspected* and then cleared, never confirmed dead, and the job
      must neither shrink nor restore.

    Exit gate per cell: the final global grid is bit-identical to the
    fault-free run's, and every stale-epoch injection was rejected
    loudly. Deterministic per ``(n, plan, seed)``.
    """
    import numpy as np

    from smi_tpu.parallel.checkpoint import CheckpointStore

    if rows_per_rank < 1 or width < 3:
        raise ValueError("grid too small for a Jacobi block per rank")
    rng = random.Random(f"elastic:{n}:{seed}")
    clock = StepClock()
    detector = PhiAccrualDetector(clock, range(n))
    view = MembershipView(n)
    grid0 = _initial_grid(n * rows_per_rank, width)
    blocks = {
        r: grid0[r * rows_per_rank:(r + 1) * rows_per_rank].copy()
        for r in range(n)
    }
    store = CheckpointStore(checkpoint_dir) if checkpoint_dir else None

    flaps = {f.rank: f for f in plan.flapping_ranks}
    silences = {f.rank: f for f in plan.stalled_heartbeats}

    def owners_now() -> Dict[int, Optional[int]]:
        return {r: route_owner(view, r, n) for r in range(n)}

    report: Dict = {
        "n": n, "seed": seed, "plan": plan.describe(),
        "iterations": iterations, "cadence": cadence,
        "suspected": [], "confirmed": [], "cleared": [],
        "shrinks": 0, "regrows": 0, "restores": 0,
        "stale_epoch_rejections": 0, "stale_epoch_leaks": 0,
        "checkpoints": 0, "replayed_iterations": 0,
        "watchdog_fired": False, "detect_ticks": None,
        "verdict": "ok",
    }

    it = 0
    next_beat = clock.now()

    def all_beat() -> None:
        """Every live, non-silenced member heartbeats on schedule."""
        nonlocal next_beat
        if clock.now() < next_beat:
            return
        for r in sorted(view.members):
            flap = flaps.get(r)
            if flap is not None and flap.dies_at <= it:
                continue  # dead: no heartbeat, no compute
            sil = silences.get(r)
            if sil is not None and (
                sil.from_tick <= clock.now()
                < sil.from_tick + sil.silent_for
            ):
                continue  # alive but silent: the suspect-only fault
            detector.heartbeat(r)
        next_beat = clock.now() + HEARTBEAT_INTERVAL + rng.randrange(-1, 2)

    def tick(ticks: int) -> List:
        """Advance the clock in 2-tick poll steps (heartbeats land on
        their own schedule) and collect detector transitions."""
        out: List = []
        left = ticks
        while left > 0:
            step = min(2, left)
            clock.advance(step)
            left -= step
            all_beat()
            out.extend(detector.poll())
        return out

    def checkpoint() -> None:
        if store is None:
            return
        store.save(it, blocks, epoch=view.epoch)
        report["checkpoints"] += 1

    # bootstrap the inter-arrival window before any fault can land
    for _ in range(4):
        for tr in tick(HEARTBEAT_INTERVAL):
            raise RuntimeError(f"transition during bootstrap: {tr}")
    checkpoint()

    stall_started: Optional[int] = None
    pending_dead: Optional[int] = None
    while it < iterations:
        # a dead member blocks the sweep: the job stalls while phi
        # accrues — this is the window the detector must close before
        # the watchdog would
        dead_member = next(
            (r for r in sorted(view.members)
             if r in flaps and flaps[r].dies_at <= it), None,
        )
        if dead_member is not None:
            if stall_started is None:
                stall_started = clock.now()
            for tr in tick(2):
                if isinstance(tr, SuspectRank):
                    report["suspected"].append(tr.rank)
                elif isinstance(tr, ConfirmedDead):
                    report["confirmed"].append(tr.rank)
                    pending_dead = tr.rank
            stalled_for = clock.now() - stall_started
            if pending_dead is None and stalled_for > WATCHDOG_TICKS:
                report["watchdog_fired"] = True
                report["verdict"] = (
                    f"watchdog beat the detector for rank {dead_member}"
                )
                return report
            if pending_dead is None:
                continue
            # detect -> shrink -> restore -> replay the tail
            report["detect_ticks"] = stalled_for
            view.confirm_dead(pending_dead)
            report["shrinks"] += 1
            plan_regrow_ring(view)  # survivors must still ring up
            if store is not None:
                restored = store.restore()
                if restored is None:
                    report["verdict"] = "no complete manifest to restore"
                    return report
                step, shards, _epoch = restored
                for r, payload in shards.items():
                    blocks[r] = payload
                report["restores"] += 1
                report["replayed_iterations"] += it - step
                it = step
            pending_dead = None
            stall_started = None
            continue

        # regrow: a flapped rank whose rejoin time arrived. (A rank
        # that died but is not yet CONFIRMED is still a member — the
        # first check skips it and the stall branch above keeps
        # running until the detector rules.)
        for r, flap in sorted(flaps.items()):
            if r in view.members or flap.rejoins_at > it:
                continue
            # the old incarnation announces itself under its old epoch
            try:
                view.validate(r, 0, what="rejoin request")
                report["stale_epoch_leaks"] += 1
            except StaleEpochError:
                report["stale_epoch_rejections"] += 1
            # survivors cut a barrier checkpoint so the newcomer
            # restores the *current* state, then admit it
            checkpoint()
            view.regrow(r)
            # fresh incarnation, fresh bootstrap: no off-schedule beat
            # here — an immediate beat would seed a tiny first interval
            # and make the next normal gap look like silence
            detector.forget(r)
            report["regrows"] += 1
            plan_regrow_ring(view)
            if store is not None:
                restored = store.restore()
                step, shards, _epoch = restored
                blocks[r] = shards[r]
            del flaps[r]
            # one straggler packet from the dead incarnation arrives
            # AFTER the regrow: it must be rejected, never folded in
            try:
                view.validate(r, view.epoch - 1, what="straggler halo")
                report["stale_epoch_leaks"] += 1
            except StaleEpochError:
                report["stale_epoch_rejections"] += 1

        owners = owners_now()
        blocks = _jacobi_sweep(blocks, owners, view, n)
        it += 1
        for tr in tick(HEARTBEAT_INTERVAL):
            if isinstance(tr, SuspectRank):
                report["suspected"].append(tr.rank)
            elif isinstance(tr, SuspicionCleared):
                report["cleared"].append(tr.rank)
            elif isinstance(tr, ConfirmedDead):
                report["verdict"] = (
                    f"rank {tr.rank} confirmed dead while computing"
                )
                return report
        if it % cadence == 0:
            checkpoint()

    final = np.concatenate([blocks[r] for r in range(n)])
    want = _fault_free_grid(grid0, iterations)
    problems = []
    if not np.array_equal(final, want):
        problems.append("silent corruption: final grid differs")
    if report["stale_epoch_leaks"]:
        problems.append("stale-epoch traffic accepted")
    if problems:
        # both gate violations must survive into the verdict — the
        # campaign counts each by substring, and one masking the other
        # would understate the headline silent-corruption figure
        report["verdict"] = "; ".join(problems)
    report["epoch"] = view.epoch
    report["members"] = sorted(view.members)
    return report


def random_elastic_plan(n: int, seed: int) -> F.FaultPlan:
    """A deterministic single-fault elastic plan: one FlappingRank or
    one StalledHeartbeat, seeded."""
    rng = random.Random(f"elastic-plan:{n}:{seed}")
    cls = F.ELASTIC_FAULT_CLASSES[
        rng.randrange(len(F.ELASTIC_FAULT_CLASSES))
    ]
    return F.FaultPlan.random(cls, n, rng.randrange(1 << 30))


def elastic_campaign(
    seed: int,
    ns: Sequence[int] = (2, 3, 4),
    trials: int = 2,
    iterations: int = 18,
    cadence: int = 3,
    checkpoint_root: Optional[str] = None,
) -> Dict:
    """Seeded elastic soak: kill/detect/shrink/restore/regrow cells
    over several ring sizes, with the same zero-silent-corruption,
    zero-stale-epoch exit gate the base chaos campaign enforces.

    Each cell runs :func:`run_elastic_cell` with a seeded
    :func:`random_elastic_plan`; checkpoints land under
    ``checkpoint_root`` (a fresh tempdir per cell when None).
    Deterministic per ``seed`` — the report reproduces from its JSON
    alone via ``smi-tpu chaos --elastic --seed N``.
    """
    import os
    import tempfile

    outcomes: Dict[str, int] = {}
    failures: List[Dict] = []
    cells = 0
    detect_ticks: List[int] = []
    stale_rejections = 0
    for n in ns:
        for trial in range(trials):
            cells += 1
            cell_seed = random.Random(
                f"elastic:{seed}:{n}:{trial}"
            ).randrange(1 << 31)
            plan = random_elastic_plan(n, cell_seed)
            with tempfile.TemporaryDirectory(
                dir=checkpoint_root
            ) as ckpt:
                report = run_elastic_cell(
                    n, plan, cell_seed, iterations=iterations,
                    cadence=cadence,
                    checkpoint_dir=os.path.join(ckpt, "shards"),
                )
            stale_rejections += report["stale_epoch_rejections"]
            if report["verdict"] != "ok":
                outcomes["failed"] = outcomes.get("failed", 0) + 1
                failures.append({
                    "n": n, "trial": trial, "cell_seed": cell_seed,
                    "plan": plan.describe(),
                    "verdict": report["verdict"],
                })
                continue
            if report["detect_ticks"] is not None:
                detect_ticks.append(report["detect_ticks"])
            key = ("regrown" if report["regrows"]
                   else "suspected-cleared" if report["cleared"]
                   else "healed")
            outcomes[key] = outcomes.get(key, 0) + 1
    silent = sum(
        1 for f in failures if "silent corruption" in f["verdict"]
    )
    stale_leaks = sum(
        1 for f in failures if "stale-epoch" in f["verdict"]
    )
    return {
        "seed": seed,
        "ns": list(ns),
        "trials": trials,
        "cells": cells,
        "outcomes": outcomes,
        "failures": failures,
        "silent_corruptions": silent,
        "stale_epoch_leaks": stale_leaks,
        "stale_epoch_rejections": stale_rejections,
        "max_detect_ticks": max(detect_ticks) if detect_ticks else None,
        "watchdog_budget_ticks": WATCHDOG_TICKS,
        "ok": not failures,
    }
