"""Shared XLA compile options for the framework's TPU programs.

Reference parity: the reference centralizes its toolchain flags in one
place (``aoc`` board/seed/fmax flags assembled by CMake,
``/root/reference/CMakeLists.txt:92-118``) so every kernel builds with
the same hardware assumptions. The TPU analog is a canonical
``compiler_options`` dict handed to ``jax.jit``.

Why the scoped-VMEM override exists: XLA's TPU backend may keep a
loop's carried values *on-chip* between custom-call (Mosaic kernel)
invocations — for the ring-attention schedule that is precisely the
design (K/V blocks and the f32 accumulator stay in VMEM across ring
steps instead of round-tripping HBM) — but its default budget for such
scoped allocations is 16 MB, a fraction of a v5e core's 128 MB VMEM.
An 8-device (dp=2, sp=4) flash train step carries ~30 MB
(q/k/v bf16 tiles + f32 acc) and is rejected with "Ran out of memory
in memory space vmem ... on stack" at the default; raising the cap to
64 MB admits it while leaving half the VMEM for Mosaic kernel frames
and pipelining. The cap is a ceiling, not a reservation — programs
that never carry state on-chip are unaffected. (Found by AOT-compiling
the multi-chip surface, ``tests/test_aot_tpu.py``; the CPU emulator
tier has no VMEM and can never catch it.)
"""

from __future__ import annotations

from typing import Optional

#: scoped-VMEM ceiling (KiB) for TPU compiles — see module docstring
SCOPED_VMEM_KIB = 64 * 1024


def install_jax_compat() -> None:
    """Bridge the pinned JAX to the API surface the framework targets.

    The framework is written against ``jax.shard_map(...,
    check_vma=...)``; older JAX ships it as
    ``jax.experimental.shard_map.shard_map(..., check_rep=...)``. When
    the top-level name is missing, install a signature-adapting alias so
    every call site (and user code following the README) works on both.
    Idempotent; called once from ``smi_tpu.__init__``.
    """
    import jax

    if getattr(jax, "shard_map", None) is not None:
        return
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # pragma: no cover - no known JAX lacks both
        return

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True, **kwargs):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kwargs,
        )

    shard_map.__doc__ = _shard_map.__doc__
    jax.shard_map = shard_map


def pallas_compiler_params(**kwargs):
    """Version-compat constructor for Pallas TPU compiler params.

    JAX renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``
    (and grew fields like ``has_side_effects``) across the versions this
    framework spans; the pinned JAX has only the old name. Every Pallas
    call site builds its params through this shim, which picks whichever
    class exists and drops only the known-safe-to-drop fields when the
    class predates them (``has_side_effects`` suppresses elision of
    kernels whose outputs go unused; every framework kernel's outputs
    are consumed, so older JAX without the field behaves identically).
    Any other unknown keyword is an error — a typo must not silently
    compile with default semantics.
    """
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu

    droppable = {"has_side_effects"}
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(kwargs) - known - droppable
    if unknown:
        raise TypeError(
            f"unknown Pallas compiler param(s) {sorted(unknown)}; "
            f"{cls.__name__} accepts {sorted(known)}"
        )
    return cls(**{k: v for k, v in kwargs.items() if k in known})

TPU_COMPILER_OPTIONS = {
    "xla_tpu_scoped_vmem_limit_kib": str(SCOPED_VMEM_KIB),
}


def tpu_compiler_options(is_tpu: bool) -> Optional[dict]:
    """``compiler_options`` for ``jax.jit`` — TPU meshes only.

    Returns ``None`` off-TPU: the CPU/emulator backend rejects unknown
    ``xla_tpu_*`` flags.
    """
    return dict(TPU_COMPILER_OPTIONS) if is_tpu else None
