"""Deliberately slow protocol variants — the perf analyzer's existence proof.

Same falsifiability discipline as :mod:`smi_tpu.analysis.mutants`, one
tier up: each mutant here is *safe* (the PR 7 verifier proves it clean
— deadlock-free, race-free, credit-balanced) but *slow* in exactly one
named way, and the decomposition must convict it by exactly its rule,
differentially against the timestamped simulator (the mutant's
simulated makespan must actually be worse than the healthy protocol's,
with bit-identical delivery):

- :func:`hold_grants` — ``"halved_wire_credits"``: one rank's credit
  grants are held until its next semaphore wait completes, so every
  grant arrives a scheduling round late — the effective credit window
  is halved. The ring still completes and still delivers bit-identical
  results, but the throttled rank's neighbours now block *before the
  awaited event was even issued* (genuine upstream lateness), which is
  the one component that is exactly zero on every healthy protocol:
  conviction by ``idle-fraction``.
- :func:`all_reduce_chunked_serial_rank` — ``"unoverlapped_chunks"``:
  the chunked pipeline with phase A/B/C fused per chunk — chunk ``c+1``
  starts only after chunk ``c``'s arrival was combined. Credit
  discipline and delivery are byte-identical per chunk; what dies is
  the overlap: no two chunk copies are ever in flight together, so the
  measured wire pipeline depth collapses to 1 against a declared
  ``chunks > 1``: conviction by ``serialized-critical-path``.
- :data:`OVERSIZED_FLASH_TILE` — ``"oversized_flash_tile"``: a flash
  forward tile whose single-buffer VMEM footprint exceeds half the
  scoped-VMEM frame, so the HBM->VMEM pipeline cannot double-buffer:
  conviction by ``no-double-buffer`` (roofline sub-tier — no simulator
  run; the differential evidence is the footprint arithmetic itself,
  pinned against ``cost_model.flash_fwd_vmem_bytes``).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence

from smi_tpu.parallel import credits as C

from smi_tpu.analysis.verifier import build_generators

#: Perf-mutant registry, in acceptance-matrix order.
PERF_MUTANTS = ("halved_wire_credits", "unoverlapped_chunks",
                "oversized_flash_tile")

#: The exactly-one rule each perf mutant must be convicted by
#: (docs/analysis.md's perf mutant table, drift-guarded).
PERF_MUTANT_RULE = {
    "halved_wire_credits": "idle-fraction",
    "unoverlapped_chunks": "serialized-critical-path",
    "oversized_flash_tile": "no-double-buffer",
}

#: The mis-tiled flash compile: bq/bk 4096 needs ~9.4 MiB of VMEM per
#: buffer generation — over the 8 MiB double-buffer bound of the
#: 16 MiB scoped-VMEM frame.
OVERSIZED_FLASH_TILE = {
    "name": "oversized 4096/4096", "dtype": "bfloat16",
    "block_q": 4096, "block_k": 4096,
}


def hold_grants(gen: Iterator):
    """Hold every credit grant this rank signals until its NEXT
    semaphore wait has completed — each grant reaches the neighbour a
    full scheduling round late, halving the usable credit window.

    No grant is ever dropped (grants still held when the generator
    finishes are flushed, so credit conservation is intact) and no
    wait-for cycle is created (the held grant is released by a wait
    satisfied by the *other* neighbour), so the verifier stays clean —
    only the timing rots.
    """
    held: List[tuple] = []
    value = None
    while True:
        try:
            action = gen.send(value)
        except StopIteration:
            for grant in held:
                yield grant
            return
        if action[0] == "signal" and action[2] == C.SEM_CREDIT:
            held.append(action)
            value = None
            continue
        value = yield action
        if action[0] == "wait" and held:
            for grant in held:
                yield grant
            held = []


def all_reduce_chunked_serial_rank(me: int, n: int, values: Sequence,
                                   combine, flow_control: bool = True):
    """The chunked ring all-reduce with its pipeline un-overlapped:
    per ring step, each chunk runs start -> land -> combine -> re-grant
    to completion before the next chunk starts (contrast
    ``credits.all_reduce_chunked_rank``'s start-all-then-combine
    phases). Per chunk the credit discipline and delivered bits are
    identical; only the overlap is gone."""
    left = (me - 1) % n
    right = (me + 1) % n
    k = len(values)
    if flow_control:
        yield from C._barrier_steps(me, n)
    for c in range(k):
        yield ("write_slot", 2 * c, values[c])
        if flow_control:
            yield ("signal", left, C.SEM_CREDIT, 2 * c + 1, 1)
    for s in range(n - 1):
        slot, nslot = s % 2, (s + 1) % 2
        for c in range(k):
            if flow_control:
                yield ("wait", C.SEM_CREDIT, 2 * c + nslot, 1)
            payload = yield ("read_slot", 2 * c + slot)
            yield ("dma", right, 2 * c + nslot, payload,
                   2 * c + slot, 2 * c + nslot)
            yield ("wait", C.SEM_SEND, 2 * c + slot, 1)
            yield ("wait", C.SEM_RECV, 2 * c + nslot, 1)
            arrived = yield ("read_slot", 2 * c + nslot)
            yield ("write_slot", 2 * c + nslot,
                   combine(arrived, values[c]))
            if flow_control and s < n - 2:
                yield ("signal", left, C.SEM_CREDIT, 2 * c + slot, 1)
    final_slot = (n - 1) % 2
    for c in range(k):
        final = yield ("read_slot", 2 * c + final_slot)
        yield ("output", c, final)


def perf_mutant_generators(protocol: str, mutant: str, n: int,
                           chunks: int = 3, slices: int = 2,
                           rank: int = 0) -> List[Iterator]:
    """Per-rank generators of ``protocol`` with one perf mutant
    applied. ``halved_wire_credits`` throttles a single ``rank`` (a
    one-rank firmware/NIC bug — the asymmetry is what turns the lost
    window into neighbour idle); ``unoverlapped_chunks`` replaces the
    chunked protocol wholesale (the compiled kernel is shared) and is
    only meaningful there."""
    if mutant == "halved_wire_credits":
        gens = build_generators(protocol, n, chunks=chunks,
                                slices=slices)
        gens[rank] = hold_grants(gens[rank])
        return gens
    if mutant == "unoverlapped_chunks":
        if protocol != "all_reduce_chunked":
            raise ValueError(
                f"unoverlapped_chunks un-overlaps the chunked "
                f"pipeline; it applies to 'all_reduce_chunked', not "
                f"{protocol!r}"
            )
        return [
            all_reduce_chunked_serial_rank(
                r, n, [frozenset([(r, c)]) for c in range(chunks)],
                lambda a, b: a | b,
            )
            for r in range(n)
        ]
    if mutant == "oversized_flash_tile":
        raise ValueError(
            "oversized_flash_tile is a roofline-tier mutant (a tile "
            "choice, not a protocol transform); run it without "
            "--protocol"
        )
    raise ValueError(
        f"unknown perf mutant {mutant!r}; known: {PERF_MUTANTS}"
    )


def healthy_outputs(protocol: str, n: int, chunks: int = 3,
                    slices: int = 2) -> List[Dict]:
    """The fault-free delivery a mutant run must still match
    bit-identically (slower, never wrong)."""
    sim = C.RingSimulator(
        build_generators(protocol, n, chunks=chunks, slices=slices),
        C.Strategy(0),
    )
    return sim.run()
