"""Execute the quickstart document verbatim.

Reference: the reference's README walks `smi_target()` → `mpirun` by
hand; here `docs/quickstart.md` is the one-page equivalent and this test
runs every fenced code block in it — the doc cannot rot. (VERDICT round
1, item 9.)
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

from smi_tpu.utils import native

DOC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "quickstart.md",
)

pytestmark = pytest.mark.skipif(
    not native.manifest_tool_available(),
    reason="smi-manifest not built (run `make -C native`)",
)


def fenced_blocks(text):
    """(language, body) for each ```lang fenced block, in order."""
    return re.findall(r"```(\w+)\n(.*?)```", text, re.DOTALL)


def test_quickstart_runs_verbatim(tmp_path, eight_devices):
    blocks = fenced_blocks(open(DOC).read())
    langs = [lang for lang, _ in blocks]
    # the text block (AOT verification stage) is illustrative: its
    # commands need the TPU compile service, which the CPU-tier suite
    # does not assume — the AOT tier itself is tests/test_aot_tpu.py
    assert langs == ["python", "bash", "python", "python", "text"], langs
    app_src, build_cmds, run_src, longctx_src = (
        body for lang, body in blocks if lang != "text"
    )

    # 1. the user program, as documented
    (tmp_path / "app.py").write_text(app_src)

    # 2. the build commands, as documented
    for line in build_cmds.strip().splitlines():
        argv = line.split()
        assert argv[:3] == ["python", "-m", "smi_tpu"]
        proc = subprocess.run(
            [sys.executable, "-m", "smi_tpu", *argv[3:]],
            cwd=tmp_path, capture_output=True, text=True,
            env={**os.environ, "PYTHONPATH": os.pathsep.join(
                p for p in [
                    os.path.dirname(DOC).rsplit(os.sep, 1)[0],
                    os.environ.get("PYTHONPATH", ""),
                ] if p
            )},
        )
        assert proc.returncode == 0, f"{line}\n{proc.stderr}"
    for artifact in ("app.json", "smi-routes/hostfile",
                     "smi-routes/cks-rank0-channel0",
                     "smi_generated_device.py",
                     "smi_generated_host.py", "report.json"):
        assert (tmp_path / "build" / artifact).exists(), artifact
    import json
    report = json.loads((tmp_path / "build" / "report.json").read_text())
    ops = {(e["op"], e["port"]) for e in report["operations"]}
    assert ops == {("push", 0), ("broadcast", 1)}, ops
    for e in report["operations"]:
        assert "cost" in e and "memory" in e

    # 3. the run script, as documented (same interpreter: the fake mesh
    # is already configured by conftest)
    cwd = os.getcwd()
    sys_path = list(sys.path)
    os.chdir(tmp_path)
    sys.path.insert(0, str(tmp_path))
    try:
        env = {"__name__": "__quickstart__"}
        exec(compile(run_src, "run.py", "exec"), env)  # noqa: S102
    finally:
        os.chdir(cwd)
        sys.path[:] = sys_path
        for mod in ("app", "smi_generated_host"):
            sys.modules.pop(mod, None)

    # 4. the long-context + hybrid-mesh script, as documented
    exec(compile(longctx_src, "long_context.py", "exec"),
         {"__name__": "__quickstart__"})  # noqa: S102
