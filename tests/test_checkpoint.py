"""Sharded checkpoint/restore + the crash-safety satellites.

Covers the at-rest durability discipline end to end: CRC-framed
shards, atomic write-then-rename manifests as the commit point,
fallback past an incomplete newest checkpoint, loud integrity errors
for damage — plus the satellite crash-safety of the durable
``ProgressLog`` WAL (torn tail skipped loudly) and the tuning plan
cache (atomic save). The model drivers prove crash-at-iteration-*i*
restore + tail replay is bit-identical for Jacobi and K-means.
"""

import json
import os
import warnings

import numpy as np
import pytest

from smi_tpu.parallel import checkpoint as C
from smi_tpu.parallel import recovery as R

pytestmark = pytest.mark.elastic


# ---------------------------------------------------------------------------
# Shard framing
# ---------------------------------------------------------------------------


def test_shard_roundtrip_is_type_exact(tmp_path):
    d = str(tmp_path)
    arr = np.arange(12, dtype=np.float64).reshape(3, 4) / 7
    name, crc = C.write_shard(d, 2, 5, arr)
    rank, step, got, rcrc = C.read_shard(os.path.join(d, name))
    assert (rank, step) == (2, 5) and rcrc == crc
    assert got.dtype == arr.dtype and np.array_equal(got, arr)
    # non-ndarray state must round-trip TYPE-exactly: int dict keys
    # stay ints, tuples stay tuples — a resumed run whose state
    # changed container type diverges from the fault-free run
    state = {0: (1, 2), "k": [1.5]}
    C.write_shard(d, 0, 1, state)
    _, _, payload, _ = C.read_shard(os.path.join(d, C.shard_name(0, 1)))
    assert payload == state
    assert isinstance(payload[0], tuple) and 0 in payload


def test_shard_corruption_is_named_not_parsed(tmp_path):
    d = str(tmp_path)
    C.write_shard(d, 1, 3, np.ones(4))
    path = os.path.join(d, C.shard_name(1, 3))
    blob = bytearray(open(path, "rb").read())
    blob[-2] ^= 0xFF  # bit rot in the payload
    open(path, "wb").write(bytes(blob))
    with pytest.raises(C.CheckpointIntegrityError) as e:
        C.read_shard(path)
    assert e.value.rank == 1 and e.value.step == 3
    assert e.value.expected is not None and e.value.got is not None


def test_shard_truncation_is_a_torn_write(tmp_path):
    d = str(tmp_path)
    C.write_shard(d, 0, 0, np.arange(8))
    path = os.path.join(d, C.shard_name(0, 0))
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-5])
    with pytest.raises(C.CheckpointIntegrityError, match="torn write"):
        C.read_shard(path)


def test_write_atomic_leaves_no_temp_files(tmp_path):
    path = str(tmp_path / "x" / "file.bin")
    C.write_atomic(path, b"payload")
    assert open(path, "rb").read() == b"payload"
    assert sorted(os.listdir(tmp_path / "x")) == ["file.bin"]


# ---------------------------------------------------------------------------
# Store: manifests, fallback, pruning
# ---------------------------------------------------------------------------


def _shards(step):
    return {r: np.full(3, step * 10 + r, dtype=np.int64)
            for r in range(3)}


def test_store_restores_latest_complete(tmp_path):
    store = C.CheckpointStore(str(tmp_path))
    store.save(0, _shards(0), epoch=0)
    store.save(4, _shards(4), epoch=1)
    step, shards, epoch = store.restore()
    assert (step, epoch) == (4, 1)
    assert np.array_equal(shards[2], np.full(3, 42))


def test_store_falls_back_past_incomplete_newest(tmp_path):
    store = C.CheckpointStore(str(tmp_path))
    store.save(2, _shards(2))
    store.save(6, _shards(6))
    # a crash shape: the newest manifest survives but a shard is gone
    os.unlink(str(tmp_path / C.shard_name(1, 6)))
    step, shards, _ = store.restore()
    assert step == 2 and np.array_equal(shards[1], np.full(3, 21))


def test_store_raises_on_corrupt_existing_shard(tmp_path):
    """A shard that exists but fails its CRC is bit rot, not a crash
    artifact: restore must raise, not silently fall back past it."""
    store = C.CheckpointStore(str(tmp_path))
    store.save(1, _shards(1))
    path = str(tmp_path / C.shard_name(0, 1))
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 1
    open(path, "wb").write(bytes(blob))
    with pytest.raises(C.CheckpointIntegrityError):
        store.restore()


def test_store_falls_back_past_mixed_generation_shards(tmp_path):
    """An interrupted RE-save of the same step overwrites shards the
    committed manifest points at: each shard self-verifies, but its
    framed CRC no longer matches the manifest's record. Restore must
    treat that manifest as incomplete and fall back — never silently
    return mixed-generation state."""
    store = C.CheckpointStore(str(tmp_path))
    store.save(2, _shards(2))
    store.save(8, _shards(8))
    # generation B of step 8 crashed after one shard, pre-manifest
    C.write_shard(str(tmp_path), 1, 8,
                  np.full(3, 999, dtype=np.int64))
    step, shards, _ = store.restore()
    assert step == 2
    assert np.array_equal(shards[1], np.full(3, 21))


def test_run_iterative_resume_keeps_the_restored_epoch(tmp_path):
    store = C.CheckpointStore(str(tmp_path))
    C.run_iterative(np.zeros(2), lambda s: s + 1, 4, store=store,
                    cadence=2, epoch=3)
    assert store.restore()[2] == 3
    # resume without restating the epoch: the audit field must not
    # regress to 0
    C.run_iterative(np.zeros(2), lambda s: s + 1, 8, store=store,
                    cadence=2)
    step, shards, epoch = store.restore()
    assert step == 8 and epoch == 3
    assert np.array_equal(shards[0], np.full(2, 8.0))


def test_store_ignores_torn_manifest(tmp_path):
    store = C.CheckpointStore(str(tmp_path))
    store.save(3, _shards(3))
    # a torn manifest write that never renamed in cannot exist by
    # construction; a truncated one (copied in by hand, bad backup)
    # must not mask the complete predecessor
    (tmp_path / "manifest-00000009.json").write_text('{"step": 9')
    step, _, _ = store.restore()
    assert step == 3


def test_store_prunes_beyond_keep(tmp_path):
    store = C.CheckpointStore(str(tmp_path), keep=2)
    for step in (0, 2, 4, 6):
        store.save(step, _shards(step))
    assert len(store.manifests()) == 2
    step, _, _ = store.restore()
    assert step == 6
    # pruned shards are gone too
    assert not os.path.exists(str(tmp_path / C.shard_name(0, 0)))


def test_manifest_schema_version_is_loud(tmp_path):
    store = C.CheckpointStore(str(tmp_path))
    store.save(1, _shards(1))
    path = store.manifests()[0]
    payload = json.load(open(path))
    payload["schema_version"] = 99
    open(path, "w").write(json.dumps(payload))
    with pytest.raises(C.CheckpointError, match="schema_version"):
        C.Manifest.from_json(payload, path)


def test_empty_store_restores_none(tmp_path):
    assert C.CheckpointStore(str(tmp_path / "nope")).restore() is None
    with pytest.raises(C.CheckpointError, match="zero shards"):
        C.CheckpointStore(str(tmp_path)).save(0, {})


# ---------------------------------------------------------------------------
# run_iterative: crash at iteration i -> restore + tail replay
# ---------------------------------------------------------------------------


class _Crash(RuntimeError):
    pass


def _crashing(step_fn, at):
    calls = {"n": 0}

    def fn(state):
        if calls["n"] == at:
            raise _Crash(f"crash at iteration {at}")
        calls["n"] += 1
        return step_fn(state)

    return fn


def test_run_iterative_restores_and_replays_only_the_tail(tmp_path):
    step = lambda s: s * 1.0000001 + 1.0  # noqa: E731 - fp-sensitive
    state0 = np.linspace(0.0, 1.0, 8)
    want, _ = C.run_iterative(state0.copy(), step, 10, store=None)

    store = C.CheckpointStore(str(tmp_path))
    with pytest.raises(_Crash):
        C.run_iterative(state0.copy(), _crashing(step, 7), 10,
                        store=store, cadence=3)
    # the crash left manifests at 0, 3, 6; resume replays 6..10 only
    assert store.latest_step() == 6
    got, start = C.run_iterative(state0.copy(), step, 10, store=store,
                                 cadence=3)
    assert start == 6
    assert np.array_equal(got, want)  # bit-identical, not just close


def test_run_iterative_checkpoint_beyond_request_is_loud(tmp_path):
    store = C.CheckpointStore(str(tmp_path))
    C.run_iterative(np.zeros(2), lambda s: s + 1, 6, store=store,
                    cadence=2)
    with pytest.raises(C.CheckpointError, match="only asks for"):
        C.run_iterative(np.zeros(2), lambda s: s + 1, 3, store=store)


def test_run_iterative_guards_cadence():
    with pytest.raises(ValueError, match="cadence"):
        C.run_iterative(0, lambda s: s, 1, cadence=0)


def test_elastic_env_config(monkeypatch):
    monkeypatch.delenv(C.DIR_ENV, raising=False)
    assert C.elastic_env_config() is None
    monkeypatch.setenv(C.DIR_ENV, "/tmp/ckpt")
    cfg = C.elastic_env_config()
    assert cfg["dir"] == "/tmp/ckpt"
    assert cfg["cadence"] == C.DEFAULT_CADENCE
    assert cfg["detector"]["suspect_phi"] < cfg["detector"]["dead_phi"]
    monkeypatch.setenv(C.CADENCE_ENV, "12")
    assert C.elastic_env_config()["cadence"] == 12
    monkeypatch.setenv(C.CADENCE_ENV, "banana")
    with pytest.raises(C.CheckpointError, match="not an integer"):
        C.elastic_env_config()
    monkeypatch.setenv(C.CADENCE_ENV, "0")
    with pytest.raises(C.CheckpointError, match=">= 1"):
        C.elastic_env_config()


# ---------------------------------------------------------------------------
# The model drivers (JAX, CPU emulator tier)
# ---------------------------------------------------------------------------


def test_run_jacobi_crash_restore_bit_identical(tmp_path, comm8):
    import jax.numpy as jnp

    from smi_tpu.models.stencil import initial_grid, make_stencil_fn
    from smi_tpu.parallel.mesh import make_communicator

    comm = make_communicator(shape=(2, 4), axis_names=("jx", "jy"),
                             devices=comm8.mesh.devices.flat[:8])
    grid = initial_grid(16, 16)
    want = np.asarray(C.run_jacobi(grid, 7, comm=comm, store=None))

    store = C.CheckpointStore(str(tmp_path))
    step = make_stencil_fn(comm, iterations=1)

    def band_shards(s):  # run_jacobi's layout: one band per grid row
        host = np.asarray(s)
        return {0: host[:8], 1: host[8:]}

    with pytest.raises(_Crash):
        C.run_iterative(
            jnp.asarray(grid), _crashing(step, 5), 7, store=store,
            cadence=2,
            shard_fn=band_shards,
            unshard_fn=lambda sh: jnp.asarray(
                np.concatenate([sh[0], sh[1]])
            ),
        )
    assert store.latest_step() == 4
    got = np.asarray(C.run_jacobi(grid, 7, comm=comm, store=store,
                                  cadence=2))
    assert np.array_equal(got, want)


def test_run_jacobi_shards_per_process_row(tmp_path, comm8):
    from smi_tpu.models.stencil import initial_grid
    from smi_tpu.parallel.mesh import make_communicator

    comm = make_communicator(shape=(2, 4), axis_names=("jx", "jy"),
                             devices=comm8.mesh.devices.flat[:8])
    store = C.CheckpointStore(str(tmp_path))
    C.run_jacobi(initial_grid(16, 16), 2, comm=comm, store=store,
                 cadence=2)
    _, shards, _ = store.restore()
    assert sorted(shards) == [0, 1]  # one band per process-grid row
    assert shards[0].shape == (8, 16)


def test_run_kmeans_crash_restore_bit_identical(tmp_path, comm8):
    rng = np.random.RandomState(0)
    points = rng.randn(64, 4).astype(np.float32)
    means0 = points[:3].copy()
    want = np.asarray(C.run_kmeans(points, means0, 6, comm=comm8,
                                   store=None))

    import jax.numpy as jnp

    from smi_tpu.models.kmeans import make_kmeans_fn

    store = C.CheckpointStore(str(tmp_path))
    fn = make_kmeans_fn(comm8, 1)
    pts = jnp.asarray(points)
    with pytest.raises(_Crash):
        C.run_iterative(
            jnp.asarray(means0), _crashing(lambda m: fn(pts, m), 4), 6,
            store=store, cadence=2,
            shard_fn=lambda m: {0: np.asarray(m)},
            unshard_fn=lambda sh: jnp.asarray(sh[0]),
        )
    got = np.asarray(C.run_kmeans(points, means0, 6, comm=comm8,
                                  store=store, cadence=2))
    assert np.array_equal(got, want)


# ---------------------------------------------------------------------------
# Satellite: the durable ProgressLog WAL + the plan cache
# ---------------------------------------------------------------------------


def _wal(tmp_path):
    log = R.ProgressLog(2, contribution=frozenset({2}))
    log.record((0, 0), frozenset({(0, 0)}))
    log.record((1, 0), ("payload", 1))
    path = str(tmp_path / "rank2.wal")
    log.save(path)
    return log, path


def test_progress_log_save_load_roundtrip(tmp_path):
    log, path = _wal(tmp_path)
    got = R.ProgressLog.load(path)
    assert got.rank == log.rank
    assert got.contribution == log.contribution
    assert got.entries == log.entries
    assert list(got.entries) == list(log.entries)  # delivery order too
    assert got.torn_records == 0
    assert not [f for f in os.listdir(tmp_path) if f.startswith(".tmp")]


def test_progress_log_torn_tail_skipped_loudly(tmp_path):
    """The satellite's torn-write test: truncate mid-final-record and
    prove the partial tail is skipped with a warning — the intact WAL
    prefix survives, garbage is never parsed."""
    log, path = _wal(tmp_path)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-9])  # cut into the last record
    with pytest.warns(RuntimeWarning, match="torn"):
        got = R.ProgressLog.load(path)
    assert got.torn_records == 1
    assert got.contribution == log.contribution
    assert list(got.entries) == [(0, 0)]  # the prefix, nothing else


def test_progress_log_mid_file_damage_refuses(tmp_path):
    _log, path = _wal(tmp_path)
    lines = open(path).read().split("\n")
    lines[1] = lines[1][:-4] + "beef"  # damage BEFORE the tail
    open(path, "w").write("\n".join(lines))
    with pytest.raises(R.WalCorruptionError, match="before the tail"):
        R.ProgressLog.load(path)


def test_progress_log_rejects_foreign_files(tmp_path):
    path = str(tmp_path / "junk")
    open(path, "w").write("not a wal\n")
    with pytest.raises(R.WalCorruptionError, match="bad header"):
        R.ProgressLog.load(path)


def test_progress_log_damaged_header_rank_is_classified(tmp_path):
    """A header whose rank field is bit-rotted must raise the
    documented WalCorruptionError, not a bare ValueError."""
    _log, path = _wal(tmp_path)
    lines = open(path).read().split("\n")
    lines[0] = lines[0] + "\xe9"
    open(path, "w").write("\n".join(lines))
    with pytest.raises(R.WalCorruptionError, match="damaged header"):
        R.ProgressLog.load(path)


def test_plan_cache_save_is_atomic(tmp_path):
    from smi_tpu.tuning.cache import CacheEntry, PlanCache
    from smi_tpu.tuning.plan import PlanKey

    cache = PlanCache()
    key = PlanKey(op="all_reduce", detail="test", dtype="float32",
                  device_kind="cpu", topology="1d:8")
    cache.put(key, CacheEntry(knobs={"chunks": 4}, cost_us=1.0))
    path = str(tmp_path / "sub" / "plans.json")
    cache.save(path)
    got = PlanCache.load(path)
    assert got.entries[key.signature()].knobs == {"chunks": 4}
    assert not [f for f in os.listdir(tmp_path / "sub")
                if f.startswith(".tmp")]
