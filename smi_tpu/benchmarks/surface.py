"""Single-chip TPU performance surface: every measurable metric with
roofline context.

Reference parity: the reference's identity is its benchmark surface —
every host computes and publishes a metric with statistics
(``microbenchmarks/host/bandwidth_benchmark.cpp:176-211``,
``latency_benchmark.cpp:158-175``); BASELINE.md tracks its configs. The
multi-chip microbenches need ≥2 devices; this module is the complement:
the full set of metrics one real chip can measure, each reported against
an explicit roofline denominator so the number is interpretable.

Roofline model (TPU v5e, public specification):

- ``PEAK_BF16`` = 197 TFLOP/s — MXU peak with bf16 operands.
- ``PEAK_HBM`` = 819 GB/s HBM bandwidth.
- ``PEAK_VPU_F32`` — derived: the bf16 peak implies a ~1.5 GHz core
  clock (197e12 / (4 MXUs · 128·128 · 2 flops)); the VPU is 4 ALUs over
  an (8, 128) lane grid, giving 4 · 1024 · 1.5e9 ≈ 6.2e12 f32 FLOP/s.
- f32 matmuls run on the bf16 MXU via multi-pass decomposition
  (≥3 passes at HIGHEST precision); f32 MFU is reported against the
  bf16 peak — a deliberately conservative denominator, stated as such.

Output: one JSON line per metric (the ``bench.py`` schema plus a
``roofline`` object) and a combined ``PERF.json``.

Run on the TPU host: ``python -m smi_tpu.benchmarks.surface [--quick]``.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

PEAK_BF16 = 197e12
PEAK_HBM = 819e9
#: reachable f32 matmul peak: the MXU has no f32 datapath, so an f32
#: contraction under Precision.HIGHEST runs as 3 bf16 passes (hi/lo
#: split: hi*hi + hi*lo + lo*hi). An f32 kernel can therefore reach at
#: most a third of the bf16 peak — MFU against PEAK_BF16 alone would
#: make every f32 number look 3x worse than it is.
PEAK_F32_EFFECTIVE = PEAK_BF16 / 3


def _mfu_roofline(tflops: float, dtype_name: str) -> dict:
    """Roofline ratios for a TFLOP/s metric: always vs the bf16 peak,
    plus the reachable f32-effective peak for f32 points (one schema
    for every consumer of PERF.json)."""
    roofline = {"mfu_vs_bf16_peak": tflops * 1e12 / PEAK_BF16,
                "peak_bf16_tflops": PEAK_BF16 / 1e12}
    if dtype_name == "f32":
        roofline["mfu_vs_f32_effective_peak"] = (
            tflops * 1e12 / PEAK_F32_EFFECTIVE
        )
        roofline["peak_f32_effective_tflops"] = PEAK_F32_EFFECTIVE / 1e12
    return roofline
MXU_FLOPS_PER_CYCLE = 4 * 128 * 128 * 2
CLOCK = PEAK_BF16 / MXU_FLOPS_PER_CYCLE           # ≈ 1.5 GHz, derived
PEAK_VPU_F32 = 4 * 8 * 128 * CLOCK                # ≈ 6.2e12, derived

#: VPU ops per cell-sweep of the Jacobi kernels, from the kernel body
#: (``kernels/stencil_temporal.py``): 3 adds + 1 multiply essential
#: arithmetic, plus 4 shifted-operand reads and 2 boundary-mask selects
#: ≈ 10 vector ops per cell.
STENCIL_ESSENTIAL_FLOPS = 4
STENCIL_VPU_OPS = 10


def _timed(fn, runs: int = 5):
    """Best-of-N wall time of ``fn()`` (must block on the result)."""
    fn()  # compile + warm
    return min(_one(fn) for _ in range(runs))


def _one(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def diff_rate(make_fn, work_per_rep: float, r1: int = 1, factor: int = 4,
               min_delta: float = 1.0, runs: int = 3, max_reps: int = 512):
    """Differential throughput: work / (t(r2) - t(r1)).

    The tunneled chip adds ~100-200 ms of fixed dispatch+readback per
    call — at benchmark sizes that swamps the kernel time, so absolute
    timing measures the tunnel, not the chip. Timing two rep counts and
    dividing the *extra* work by the *extra* time cancels every fixed
    cost. Rep counts escalate geometrically until the delta is large
    enough to trust against load noise.

    ``make_fn(r)`` must return a nullary callable running ``r`` reps and
    blocking on the result. Returns ``(rate, (r1, r2, t1, t2))``.

    ``max_reps`` caps the rep count BEFORE a chain is ever built: some
    harnesses grow per-rep state with ``r`` (a grad-of-reps chain stacks
    its VJP residuals r-fold), so "time it first, notice the cap after"
    can compile an HBM-OOM program on the way to the cap.
    """
    # this guard is EAGER: it fires before make_fn is ever called, so a
    # degenerate computed cap fails before any allocation or compile
    if r1 >= max_reps:
        raise ValueError(
            f"diff_rate needs r1 < max_reps to escalate (got r1={r1}, "
            f"max_reps={max_reps}); a same-rep pair has zero work delta "
            f"and would silently record a 0-rate measurement"
        )
    t1 = _timed(make_fn(r1), runs)
    while True:
        r2 = min(r1 * factor, max_reps)
        t2 = _timed(make_fn(r2), runs)
        if t2 - t1 >= min_delta or r2 >= max_reps:
            rate = (r2 - r1) * work_per_rep / max(t2 - t1, 1e-9)
            return rate, (r1, r2, round(t1, 4), round(t2, 4))
        r1, t1 = r2, t2


#: internal callers predate the public promotion
_diff_rate = diff_rate


def _result(metric, value, unit, config, roofline=None):
    rec = {
        "metric": metric,
        "value": round(float(value), 4),
        "unit": unit,
        "config": config,
    }
    if roofline:
        rec["roofline"] = {
            k: round(float(v), 4) for k, v in roofline.items()
        }
    print(json.dumps(rec), flush=True)
    return rec


def _attention_flops(s: int, h: int, d: int, causal: bool,
                     train: bool) -> float:
    """Matmul FLOPs of one attention application.

    Forward: QKᵀ and PV, 2·S²·H·D each. Backward (flash2 recompute):
    five S²-shaped matmuls (scores recompute, dV, dP, dQ, dK). Causal
    halves the live area.
    """
    matmuls = 7 if train else 2
    flops = matmuls * 2 * s * s * h * d
    return flops / 2 if causal else flops


# ---------------------------------------------------------------------------
# Flash attention: forward / train MFU, tier ratios, stock comparison
# ---------------------------------------------------------------------------


def flash_forward_points(comm, quick: bool = False):
    """Flash forward at several (S, dtype) points with MFU."""
    import jax.numpy as jnp
    from jax import lax

    from smi_tpu.models import ring_attention as ra

    h, d = 8, 128
    points = [
        (4096, jnp.float32, lax.Precision.HIGHEST),
        (8192, jnp.float32, lax.Precision.HIGHEST),
        (8192, jnp.bfloat16, None),
        (16384, jnp.bfloat16, None),
    ]
    if quick:
        points = points[:2]
    out = []
    for s, dtype, precision in points:
        rng = np.random.RandomState(0)
        q, k, v = (
            jnp.asarray(rng.randn(s, h, d), dtype) for _ in range(3)
        )

        def make_fn(r, _s=s, _p=precision, _q=q, _k=k, _v=v):
            fn = ra.make_ring_attention_fn(
                comm, causal=True, precision=_p, use_flash=True, reps=r,
            )
            return lambda: np.asarray(
                jnp.sum(fn(_q, _k, _v).astype(jnp.float32)))

        work = _attention_flops(s, h, d, causal=True, train=False)
        rate, trace = _diff_rate(make_fn, work)
        tflops = rate / 1e12
        name = "bf16" if dtype == jnp.bfloat16 else "f32"
        out.append(_result(
            f"flash_attn_fwd_s{s}_{name}", tflops, "TFLOP/s",
            {"S": s, "H": h, "D": d, "dtype": name, "causal": True,
             "timing": trace},
            _mfu_roofline(tflops, name),
        ))
    return out


def flash_train_point(comm, quick: bool = False):
    """Forward+backward (custom-VJP flash) throughput and MFU."""
    import jax
    import jax.numpy as jnp

    from smi_tpu.models import ring_attention as ra

    s, h, d = (4096 if quick else 8192), 8, 128
    out = []
    dtypes = [("f32", jnp.float32)]
    if not quick:
        dtypes.append(("bf16", jnp.bfloat16))
    for name, dtype in dtypes:
        rng = np.random.RandomState(0)
        q, k, v = (
            jnp.asarray(rng.randn(s, h, d), dtype) for _ in range(3)
        )

        def make_fn(r, _q=q, _k=k, _v=v):
            fn = ra.make_ring_attention_fn(comm, causal=True, reps=r)
            grad = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(
                    fn(q, k, v).astype(jnp.float32) ** 2
                ),
                argnums=(0, 1, 2),
            ))
            return lambda: np.asarray(
                jnp.sum(grad(_q, _k, _v)[0].astype(jnp.float32)))

        work = _attention_flops(s, h, d, causal=True, train=True)
        # the grad chain stacks (q, out, stats) residuals per rep
        # (~36 MB/rep at S=8192 bf16, ~2x that in f32); cap the chain
        # so it stays under ~9 GB next to the live buffers
        cap = 256 if dtype == jnp.bfloat16 else 128
        rate, trace = _diff_rate(make_fn, work, max_reps=cap)
        tflops = rate / 1e12
        tokens = rate / work * s
        out.append(_result(
            f"flash_attn_train_tflops_{name}", tflops, "TFLOP/s",
            {"S": s, "H": h, "D": d, "dtype": name, "causal": True,
             "timing": trace},
            _mfu_roofline(tflops, name),
        ))
        out.append(_result(
            f"flash_attn_train_tokens_{name}", tokens / 1e6, "Mtoken/s",
            {"S": s, "H": h, "D": d, "dtype": name},
        ))
    return out


def longcontext_points(comm, quick: bool = False):
    """The long-context claim, measured: 32k to 512k tokens on one
    chip. Full causal at 32k; sliding-window forward at every length
    (compute scaling with S·window, grouped-query K/V from 256k up);
    training through the custom-VJP backward up to 256k (512k trains
    too, but only the rep-chained timing harness no longer fits)."""
    import jax

    import jax.numpy as jnp

    from smi_tpu.models import ring_attention as ra

    if quick:
        return []
    h, d, w = 8, 128, 4096
    out = []
    # (S, window, kv_heads): kv_heads < h is grouped-query attention —
    # the 8x smaller K/V is what carries the 256k point onto one chip
    # 512k is forward-only: a single fwd+bwd step runs (verified), but
    # the rep-chained timing harness itself needs reps x 1 GB for the
    # chained q carry, which no longer fits beside the gradients
    for s, window, h_kv in (
        (32768, None, h), (32768, w, h), (65536, w, h), (131072, w, h),
        (262144, w, 1), (524288, w, 1), (1048576, w, 1),
    ):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(s, h, d), jnp.bfloat16)
        k, v = (
            jnp.asarray(rng.randn(s, h_kv, d), jnp.bfloat16)
            for _ in range(2)
        )

        def make_fn(r, _w=window, _q=q, _k=k, _v=v):
            fn = ra.make_ring_attention_fn(
                comm, causal=True, use_flash=True, reps=r, window=_w,
            )
            return lambda: np.asarray(
                jnp.sum(fn(_q, _k, _v).astype(jnp.float32)))

        # full causal: S²/2 live area; windowed: ~S·window
        if window is None:
            work = _attention_flops(s, h, d, causal=True, train=False)
        else:
            work = 2 * 2 * s * window * h * d
        rate, trace = _diff_rate(make_fn, work)
        tag = "causal" if window is None else f"window{window}"
        if h_kv != h:
            tag = f"gqa{h // h_kv}_{tag}"
        out.append(_result(
            f"flash_attn_fwd_s{s}_bf16_{tag}", rate / 1e12, "TFLOP/s",
            {"S": s, "H": h, "D": d, "kv_heads": h_kv, "dtype": "bf16",
             "window": window, "timing": trace},
            {"mfu_vs_bf16_peak": rate / PEAK_BF16},
        ))

    # long-context *training* ladder, 32k–512k, ONE harness for every
    # row: chained SGD *steps* — gradients complete inside each
    # fori_loop iteration, so memory stays at one step's working set
    # and the timed program is the production shape (grad + update).
    # The older rep-chain harness (grad of chained reps) stacks its VJP
    # residuals r-fold, which pressures HBM (at 256k it reads ~20% low)
    # and stops fitting entirely at 512k; it is kept as a SECONDARY
    # column (``rep_chain_mtokens``) where it fits, for cross-round
    # comparability. 1M training does not fit one chip at all (f32 dq
    # alone is 4 GiB) — that rung is the (dp, sp) sequence-parallel
    # step, AOT-evidenced in ``parallel/aot.py::_longcontext_sp_case``.
    from jax import lax as _lax

    for s, h_kv, rep_chain in (
        (32768, h, True), (65536, h, True), (131072, h, True),
        (262144, 1, True), (524288, 1, False),
    ):
        rng = np.random.RandomState(0)
        q0 = jnp.asarray(rng.randn(s, h, d), jnp.bfloat16)
        k0, v0 = (
            jnp.asarray(rng.randn(s, h_kv, d), jnp.bfloat16)
            for _ in range(2)
        )
        attn = ra.make_ring_attention_fn(
            comm, causal=True, use_flash=True, window=w
        )
        grad = jax.grad(
            lambda q, k, v: jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2),
        )

        def make_steps(r, _q0=q0, _k0=k0, _v0=v0):
            @jax.jit
            def chain(q, k, v):
                def body(i, carry):
                    qq, kk, vv = carry
                    dq, dk, dv = grad(qq, kk, vv)
                    return (qq - 1e-6 * dq.astype(qq.dtype),
                            kk - 1e-6 * dk.astype(kk.dtype),
                            vv - 1e-6 * dv.astype(vv.dtype))
                return _lax.fori_loop(0, r, body, (q, k, v))

            return lambda: np.asarray(
                jnp.sum(chain(_q0, _k0, _v0)[0].astype(jnp.float32)))

        # short rows take many cheap steps to fill the timing window;
        # the 512k row's single step is already ~0.4 s
        r1, factor, cap = (1, 3, 6) if s >= 524288 else (4, 4, 256)
        rate, trace = _diff_rate(make_steps, s, r1=r1, factor=factor,
                                 max_reps=cap, min_delta=1.0)
        tag = "" if h_kv == h else f"_gqa{h // h_kv}"
        cfg = {"S": s, "H": h, "D": d, "kv_heads": h_kv, "dtype": "bf16",
               "window": w, "harness": "step-chain", "timing": trace}

        if rep_chain:
            def make_train(r, _s=s, _q=q0, _k=k0, _v=v0):
                fn = ra.make_ring_attention_fn(
                    comm, causal=True, reps=r, window=w,
                    # 64k+: per-rep grad residuals would exceed HBM
                    remat_reps=_s >= 65536,
                )
                g = jax.jit(jax.grad(
                    lambda q, k, v: jnp.sum(
                        fn(q, k, v).astype(jnp.float32) ** 2
                    ),
                    argnums=(0, 1, 2),
                ))
                return lambda: np.asarray(
                    jnp.sum(g(_q, _k, _v)[0].astype(jnp.float32)))

            rc_rate, rc_trace = _diff_rate(make_train, s)
            cfg["rep_chain_mtokens"] = round(rc_rate / 1e6, 4)
            cfg["rep_chain_timing"] = rc_trace

        out.append(_result(
            f"flash_attn_train_tokens_s{s}{tag}_window{w}_bf16",
            rate / 1e6, "Mtoken/s", cfg,
        ))
    return out


def flash_vs_jnp(comm, quick: bool = False):
    """Flash tier speedup over the jnp (HBM-materialized) tier."""
    import jax.numpy as jnp

    from smi_tpu.models import ring_attention as ra

    s, h, d = 2048 if quick else 4096, 8, 128
    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(s, h, d), jnp.float32) for _ in range(3)
    )
    rates = {}
    for use_flash in (True, False):
        def make_fn(r, _uf=use_flash):
            fn = ra.make_ring_attention_fn(
                comm, causal=True, use_flash=_uf, reps=r
            )
            return lambda: np.asarray(jnp.sum(fn(q, k, v)))

        rates[use_flash], _ = _diff_rate(make_fn, 1.0)
    return [_result(
        "flash_vs_jnp_speedup", rates[True] / rates[False], "x",
        {"S": s, "H": h, "D": d, "dtype": "f32", "causal": True},
    )]


def flash_vs_stock(comm, quick: bool = False):
    """Our flash kernel vs JAX's stock TPU flash attention
    (``jax.experimental.pallas.ops.tpu.flash_attention``), same shapes.

    TWO comparison rows, honestly framed: ``flash_vs_stock_default``
    is stock at its default BlockSizes — the out-of-the-box experience,
    NOT a kernel-quality claim (stock's defaults are tuned for other
    shapes); ``flash_vs_stock_swept`` re-measures stock at the best of
    a hand-swept BlockSizes grid, which historically reaches parity
    (~121 TF/s on this harness, ``docs/perf_notes.md``). The swept row
    is the kernel-vs-kernel comparison.
    """
    import jax
    import jax.numpy as jnp

    from smi_tpu.models import ring_attention as ra

    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes,
            flash_attention as stock,
        )
    except ImportError:
        return []

    s, h, d = 4096 if quick else 8192, 8, 128
    rng = np.random.RandomState(0)
    dtype = jnp.bfloat16
    q, k, v = (jnp.asarray(rng.randn(s, h, d), dtype) for _ in range(3))
    work = _attention_flops(s, h, d, causal=True, train=False)

    def make_ours(r):
        fn = ra.make_ring_attention_fn(
            comm, causal=True, use_flash=True, reps=r
        )
        return lambda: np.asarray(
            jnp.sum(fn(q, k, v).astype(jnp.float32)))

    rate_ours, trace_ours = _diff_rate(make_ours, work)

    # stock layout is (batch, heads, seq, head_dim)
    qb, kb, vb = (a.transpose(1, 0, 2)[None] for a in (q, k, v))

    def make_stock(r, block_sizes=None):
        kwargs = {} if block_sizes is None else {"block_sizes": block_sizes}

        @jax.jit
        def stock_reps(q, k, v):
            # feed the output back as the next query so the call is
            # loop-carried — a loop-invariant body would be hoisted and
            # the measurement would show r× the real rate
            def body(i, qi):
                return stock(qi, k, v, causal=True, **kwargs).astype(
                    q.dtype)
            return jax.lax.fori_loop(0, r, body, q)

        return lambda: np.asarray(
            jnp.sum(stock_reps(qb, kb, vb).astype(jnp.float32)))

    rate_stock, trace_stock = _diff_rate(make_stock, work)
    out = [_result(
        "flash_vs_stock_default", rate_ours / rate_stock, "x",
        {"S": s, "H": h, "D": d, "dtype": "bf16", "causal": True,
         "note": ">1 means ours is faster; stock at DEFAULT "
                 "BlockSizes — see flash_vs_stock_swept for the "
                 "tuned-kernel comparison",
         "timing_ours": trace_ours, "timing_stock": trace_stock},
        {"ours_tflops": rate_ours / 1e12,
         "stock_tflops": rate_stock / 1e12,
         "mfu_ours_vs_bf16_peak": rate_ours / PEAK_BF16},
    )]
    if quick:
        return out

    # hand-swept stock: fixed rep PAIRS (2 compiles/config — the
    # tunnel charges ~30-60 s per compile, so no escalation here),
    # best config wins. The grid covers the block shapes that matter
    # for a (1, 8, 8192, 128) forward.
    def pair_rate(mk, r1=64, r2=256, runs=3):
        t1 = _timed(mk(r1), runs)
        t2 = _timed(mk(r2), runs)
        return (r2 - r1) * work / max(t2 - t1, 1e-9), (r1, r2,
                                                       round(t1, 4),
                                                       round(t2, 4))

    best = (0.0, None, None)
    for bq, bkm, bk in ((512, 512, 512), (1024, 1024, 1024),
                        (512, 1024, 1024), (2048, 1024, 1024)):
        bs = BlockSizes(
            block_q=bq, block_k_major=bkm, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bkm,
            block_q_dkv=bq, block_k_dkv=bk,
            block_q_dq=bq, block_k_major_dq=bkm, block_k_dq=bk,
        )
        r, tr = pair_rate(lambda n, _bs=bs: make_stock(n, _bs))
        if r > best[0]:
            best = (r, (bq, bkm, bk), tr)
    rate_swept, swept_cfg, trace_swept = best
    out.append(_result(
        "flash_vs_stock_swept", rate_ours / rate_swept, "x",
        {"S": s, "H": h, "D": d, "dtype": "bf16", "causal": True,
         "note": ">1 means ours is faster; stock at its best "
                 "hand-swept BlockSizes (the kernel-vs-kernel row)",
         "block_q_kmajor_k": swept_cfg,
         "timing_ours": trace_ours, "timing_stock": trace_swept},
        {"ours_tflops": rate_ours / 1e12,
         "stock_swept_tflops": rate_swept / 1e12},
    ))
    return out


def roll_chain_points(comm, quick: bool = False):
    """Isolated roll-port rates: dependent ``pltpu.roll`` chains with
    NOTHING else in the kernel body (no adds, no loads beyond the tile).

    The stencil ceiling analysis (``docs/perf_notes.md``) rests on the
    lane-roll rate; the r3 probes measured it inside a mixed-op class
    whose small members spread 1.2-4.5 ps/elem between sessions. This
    pins the port rate alone: two chain lengths (R and R/4) per axis,
    each timed differentially over data-dependently chained reps, and
    the per-element rate taken from the R-difference — per-rep HBM
    traffic and dispatch overhead cancel exactly in the subtraction.

    Two variants per axis. ``ilp=1`` is ONE dependent chain: every roll
    waits on the previous, so the rate folds in any per-roll latency the
    scheduler cannot hide — a *latency* pin. ``ilp=2`` runs TWO
    independent chains (half-height arrays, same total elements per
    step), giving the scheduler a second in-flight roll to overlap with
    the first — the *throughput* pin, and the rate the stencil's two
    per-sweep (independent, opposite-direction) rotations actually see.
    The port bound in the notes must use the ilp=2 number.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if quick:
        return []
    rows, cols = 512, 2048
    elems = rows * cols
    r_hi, r_lo = 4096, 1024

    def measure(metric, body, ilp):
        """Chain ``body`` (one whole-array step) ``ilp`` independent
        ways over half-height arrays — total elements per chain step is
        ilp-invariant (ilp arrays of rows/ilp x cols) — and return the
        ps/elem row from the R-differential."""
        n_rows = rows // ilp

        def make_fn_for(R):
            def kernel(*refs):
                ins, outs = refs[:ilp], refs[ilp:]
                final = jax.lax.fori_loop(
                    0, R,
                    lambda i, vs: tuple(body(v) for v in vs),
                    tuple(r[...] for r in ins),
                )
                for o, v in zip(outs, final):
                    o[...] = v

            shape = jax.ShapeDtypeStruct((n_rows, cols), jnp.float32)
            call = pl.pallas_call(kernel, out_shape=(shape,) * ilp)

            def make_fn(r):
                @jax.jit
                def chain(*xs):
                    return jax.lax.fori_loop(
                        0, r, lambda i, vs: call(*vs), xs
                    )

                xs = tuple(
                    jnp.full((n_rows, cols), 1.0 + i, jnp.float32)
                    for i in range(ilp)
                )
                return lambda: np.asarray(
                    sum(jnp.sum(v) for v in chain(*xs))
                )

            return make_fn

        per_rep = {}
        traces = {}
        for R in (r_lo, r_hi):
            rate, trace = _diff_rate(
                make_fn_for(R), 1.0, r1=4, factor=4, max_reps=1024
            )
            per_rep[R], traces[R] = 1.0 / rate, trace
        ps = (per_rep[r_hi] - per_rep[r_lo]) / (
            (r_hi - r_lo) * elems
        ) * 1e12
        return _result(
            metric, ps, "ps/elem",
            {"rows": n_rows, "cols": cols, "chains": ilp,
             "chain_lengths": [r_lo, r_hi],
             "per_rep_s": {str(k): round(v, 6)
                           for k, v in per_rep.items()},
             "timing": traces[r_hi]},
        )

    def roll_body(axis):
        from jax.experimental.pallas import tpu as pltpu

        return lambda v: pltpu.roll(v, 1, axis=axis)

    out = [
        measure(f"roll_chain_{name}{'' if ilp == 1 else f'_ilp{ilp}'}"
                "_ps_per_elem", roll_body(axis), ilp)
        for axis, name in ((1, "lane"), (0, "sublane"))
        for ilp in (1, 2)
    ]
    # Harness floor: the same chain with a pure elementwise add body.
    # A whole-array op chained through ``fori_loop`` cannot keep the
    # 4 MB intermediate in registers, so EVERY chain step pays a VMEM
    # round-trip (8 B/elem) on top of its compute port. The add chain
    # prices that round-trip (plus one ALU add, ~0.05 ps at the VPU
    # rate) — subtracting it from the roll rates isolates the
    # crossbar-port component the stencil bound needs.
    out.append(measure(
        "roll_chain_baseline_add_ps_per_elem",
        lambda v: v + jnp.float32(1.0), 1,
    ))
    return out


def model_train_point(comm, quick: bool = False):
    """Whole-model training throughput: the transformer block (QKV/O +
    MLP matmuls + ring attention + layernorms + SGD) in mixed precision
    — the composition showpiece measured end-to-end, at S=8192 full
    causal and at 32k tokens with the sliding window."""
    import jax.numpy as jnp

    from smi_tpu.models import transformer as tf
    from smi_tpu.parallel.mesh import make_communicator

    if quick:
        return []
    e, h, d = 1024, 8, 128
    comm2 = make_communicator(
        shape=(1, 1), axis_names=("dp", "sp"),
        devices=list(comm.mesh.devices.flat)[:1],
    )
    out = []
    for s, window, layers in (
        (8192, None, 1), (32768, 4096, 1),
        # the windowed 1-layer row: the PROPER per-layer baseline for
        # the 4-block stack below (same attention config — the r4
        # stack budget in docs/perf_notes.md is measured against it)
        (8192, 4096, 1),
        # the 4-block stack (scan + per-block remat): composition
        # overhead shown amortized, not per-block
        (8192, 4096, 4), (32768, 4096, 4),
    ):
        cfg = tf.BlockConfig(embed=e, heads=h, head_dim=d,
                             compute_dtype="bfloat16", window=window)
        params = (tf.init_params(cfg) if layers == 1
                  else tf.init_stack_params(cfg, layers))
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1, s, e).astype(np.float32))

        def make_fn(r, _cfg=cfg, _params=params, _x=x, _layers=layers):
            step = tf.make_train_step(comm2, _cfg, layers=_layers)

            def run():
                p, loss = dict(_params), None
                for _ in range(r):
                    p, loss = step(p, _x, _x)
                return np.asarray(loss)

            return run

        rate, trace = _diff_rate(make_fn, s)
        # block FLOPs per token, fwd+bwd (x3): QKV (2*E*3HD) +
        # O (2*HD*E) + MLP (2*2*ratio*E^2) + attention per token
        # (4*S*H*D/2 causal — the exact causal average; 4*window*H*D
        # windowed — the full-window upper bound, the same S·window
        # convention as longcontext_points, ~7% above the causal-edge
        # average at S=32k/W=4k)
        matmul = (2 * e * 3 * h * d + 2 * h * d * e
                  + 4 * cfg.mlp_ratio * e * e)
        attn = 4 * window * h * d if window else 4 * s * h * d / 2
        # fwd+bwd = 3x fwd flops per layer; per-block remat re-runs each
        # forward once more under the backward (4x total) for layers > 1
        passes = 3 if layers == 1 else 4
        tflops = rate * layers * passes * (matmul + attn) / 1e12
        tag = "" if window is None else f"_s{s}_window{window}"
        if layers > 1:
            tag += f"_l{layers}"
        out.append(_result(
            f"transformer_train_tokens{tag}_bf16", rate / 1e6,
            "Mtoken/s",
            {"S": s, "embed": e, "H": h, "D": d, "compute": "bf16",
             "window": window, "layers": layers, "timing": trace},
            {"approx_tflops": tflops,
             "mfu_vs_bf16_peak": tflops * 1e12 / PEAK_BF16},
        ))
    return out


# ---------------------------------------------------------------------------
# Stencil tiers + roofline
# ---------------------------------------------------------------------------


def stencil_roofline(cells_per_sec: float, depth: int) -> dict:
    """Both roofline views of a stencil rate.

    HBM model: one temporal pass reads+writes the grid once for
    ``depth`` sweeps → 8 bytes / (cell·iter·depth). VPU model: ~10
    vector ops per cell·iter (4 essential FLOPs + shifted reads +
    boundary selects).
    """
    hbm_bytes_per_sec = cells_per_sec * 8.0 / max(depth, 1)
    return {
        "vs_hbm_roofline": hbm_bytes_per_sec / PEAK_HBM,
        "vs_vpu_roofline": cells_per_sec * STENCIL_VPU_OPS / PEAK_VPU_F32,
        "essential_gflops": cells_per_sec * STENCIL_ESSENTIAL_FLOPS / 1e9,
        "depth": depth,
    }


def stencil_tiers(comm, quick: bool = False):
    """Fused (1 sweep/pass) vs temporal (k sweeps/pass) kernel tiers."""
    import jax.numpy as jnp

    from smi_tpu.kernels import stencil as ks
    from smi_tpu.kernels import stencil_temporal as kt
    from smi_tpu.models import stencil
    from smi_tpu.parallel.mesh import make_communicator

    size = 4096 if quick else 8192
    comm2d = make_communicator(
        shape=(1, 1), axis_names=("sx", "sy"),
        devices=list(comm.mesh.devices.flat)[:1],
    )
    grid = jnp.asarray(stencil.initial_grid(size, size))
    out = []
    rates = {}

    depth = kt.pick_temporal_depth(size, size, jnp.float32, 256)
    tiers = []
    if ks.pallas_supported(size, size, jnp.float32):
        tiers.append(
            ("fused",
             lambda it: ks.make_fused_stencil_fn(comm2d, it, size, size), 1)
        )
    if depth is not None:
        tiers.append(
            ("temporal",
             lambda it: kt.make_temporal_stencil_fn(
                 comm2d, it, size, size, depth=depth), depth)
        )
    for name, make, k in tiers:
        # iterations are the rep knob; keep them multiples of the depth
        def make_fn(r, _make=make, _k=k):
            fn = _make(r * _k * 8)
            return lambda: np.asarray(jnp.sum(fn(grid)))

        rate, trace = _diff_rate(make_fn, size * size * k * 8)
        rates[name] = rate
        out.append(_result(
            f"stencil_{name}_gcells", rate / 1e9, "Gcell/s",
            {"size": size, "depth": k, "timing": trace},
            stencil_roofline(rate, k),
        ))
    if len(rates) == 2:
        out.append(_result(
            "stencil_temporal_vs_fused", rates["temporal"] / rates["fused"],
            "x", {"size": size, "depth": depth},
        ))
    return out


# ---------------------------------------------------------------------------
# On-chip application workloads
# ---------------------------------------------------------------------------


def onchip_apps(comm, quick: bool = False):
    """Single-chip GESUMMV (HBM-bound matvec) and K-means."""
    import jax
    import jax.numpy as jnp

    from smi_tpu.models import kmeans, onchip

    out = []
    n = 4096 if quick else 8192
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.rand(n, n), jnp.float32)
    b = jnp.asarray(rng.rand(n, n), jnp.float32)
    x = jnp.asarray(rng.rand(n), jnp.float32)
    gfn = onchip.make_gesummv_onchip_fn(1.5, 0.5)

    def make_gesummv(r):
        @jax.jit
        def chained(a, b, x):
            def body(i, xi):
                y = gfn(a, b, xi)
                return y / jnp.max(jnp.abs(y))  # keep magnitudes bounded
            return jax.lax.fori_loop(0, r, body, x)

        return lambda: np.asarray(jnp.sum(chained(a, b, x)))

    rate, trace = _diff_rate(make_gesummv, 4 * n * n, r1=4, factor=4)
    gflops = rate / 1e9
    # two matvecs: read both matrices once → 8 B/cell → flops/byte = 0.5
    hbm_bound = PEAK_HBM * (4 * n * n) / (8 * n * n) / 1e9
    out.append(_result(
        "gesummv_onchip_gflops", gflops, "GFLOP/s",
        {"n": n, "timing": trace},
        {"vs_hbm_roofline": gflops / hbm_bound,
         "hbm_roofline_gflops": hbm_bound},
    ))

    points, k, dims = 1 << 20, 8, 2
    pts = rng.rand(points, dims).astype(np.float32)
    init = pts[:k].copy()
    pj, ij = jnp.asarray(pts), jnp.asarray(init)

    def make_kmeans(r):
        kfn = kmeans.make_kmeans_fn(comm, iterations=r * 10)
        return lambda: np.asarray(jnp.sum(kfn(pj, ij)))

    rate, trace = _diff_rate(make_kmeans, points * 10)
    out.append(_result(
        "kmeans_mpoint_iters", rate / 1e6,
        "Mpoint-iter/s",
        {"points": points, "k": k, "dims": dims, "timing": trace},
    ))
    return out


# ---------------------------------------------------------------------------


def main(argv=None):
    import argparse

    import jax

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--quick", action="store_true",
                   help="smaller shapes (smoke/CI)")
    p.add_argument("-o", "--output", default=None,
                   help="artifact path (default PERF.json, or "
                        "PERF_quick.json under --quick so quick-shape "
                        "rows never replace committed full-size rows "
                        "of the same name)")
    p.add_argument("--only", nargs="*", default=None,
                   help="subset: fwd train tiers ratio stock apps")
    p.add_argument("--fresh", action="store_true",
                   help="overwrite the output instead of merging by "
                        "metric name (a partial --only/--quick run "
                        "must not clobber the committed artifact)")
    args = p.parse_args(argv)
    if args.output is None:
        args.output = "PERF_quick.json" if args.quick else "PERF.json"

    from smi_tpu.parallel.mesh import make_communicator

    comm = make_communicator(1, devices=jax.devices()[:1])
    sections = {
        "fwd": flash_forward_points,
        "longcontext": longcontext_points,
        "train": flash_train_point,
        "model": model_train_point,
        "ratio": flash_vs_jnp,
        "stock": flash_vs_stock,
        "tiers": stencil_tiers,
        "rolls": roll_chain_points,
        "apps": onchip_apps,
    }
    selected = args.only or list(sections)
    results = []
    for name in selected:
        results.extend(sections[name](comm, quick=args.quick))
    payload = {
        "device": str(jax.devices()[0]),
        "rooflines": {
            "peak_bf16_tflops": PEAK_BF16 / 1e12,
            "peak_hbm_gbps": PEAK_HBM / 1e9,
            "peak_vpu_f32_tflops": PEAK_VPU_F32 / 1e12,
        },
        "metrics": results,
    }
    if not args.fresh and os.path.exists(args.output):
        # merge: fresh measurements replace same-named metrics, every
        # other committed row (and extra keys like "methodology")
        # survives — a --only/--quick run updates its slice of the
        # artifact instead of destroying the rest
        with open(args.output) as f:
            old = json.load(f)
        fresh = {m["metric"] for m in results}
        kept = [m for m in old.get("metrics", [])
                if m["metric"] not in fresh]
        merged = dict(old)
        merged.update(payload)
        merged["metrics"] = kept + results
        payload = merged
    with open(args.output, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
