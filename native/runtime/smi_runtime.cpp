#include "smi_runtime.h"

#include <chrono>
#include <cstdio>
#include <string>
#include <vector>

namespace {

std::string table_path(const char* dir, const char* kind, int rank,
                       int channel) {
  // file naming parity: include/utils/smi_utils.hpp:24-39
  return std::string(dir) + "/" + kind + "-rank" + std::to_string(rank) +
         "-channel" + std::to_string(channel);
}

}  // namespace

extern "C" {

const char* smi_runtime_version() { return "smi_tpu-runtime 0.1.0"; }

int64_t smi_time_usecs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int64_t smi_time_nsecs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int32_t smi_load_routing_table(const char* dir, const char* kind,
                               int32_t rank, int32_t channel, uint8_t* out,
                               int32_t capacity) {
  std::string path = table_path(dir, kind, rank, channel);
  FILE* f = std::fopen(path.c_str(), "rb");
  if (f == nullptr) return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size < 0) {
    std::fclose(f);
    return -1;
  }
  if (size > capacity) {
    std::fclose(f);
    return -2;
  }
  size_t read = std::fread(out, 1, static_cast<size_t>(size), f);
  std::fclose(f);
  if (read != static_cast<size_t>(size)) return -1;
  return static_cast<int32_t>(size);
}

int32_t smi_store_routing_table(const char* dir, const char* kind,
                                int32_t rank, int32_t channel,
                                const uint8_t* data, int32_t count) {
  std::string path = table_path(dir, kind, rank, channel);
  FILE* f = std::fopen(path.c_str(), "wb");
  if (f == nullptr) return -1;
  size_t written = std::fwrite(data, 1, static_cast<size_t>(count), f);
  std::fclose(f);
  return written == static_cast<size_t>(count) ? 0 : -1;
}

int32_t smi_bootstrap_rank(const char* dir, int32_t rank, int32_t channels,
                           int32_t max_ranks) {
  if (channels <= 0 || max_ranks <= 0) return -1;
  std::vector<uint8_t> buf(1 << 20);
  int32_t ports = -1;
  for (int c = 0; c < channels; c++) {
    int32_t cks = smi_load_routing_table(dir, "cks", rank, c, buf.data(),
                                         static_cast<int32_t>(buf.size()));
    if (cks <= 0 || cks % max_ranks != 0) return -1;
    int32_t cks_ports = cks / max_ranks;
    int32_t ckr = smi_load_routing_table(dir, "ckr", rank, c, buf.data(),
                                         static_cast<int32_t>(buf.size()));
    // ckr table is 2 entries (data|ctrl) per logical port
    // (codegen/notes.txt "CKR routing table")
    if (ckr < 0 || ckr != 2 * cks_ports) return -1;
    if (ports == -1) ports = cks_ports;
    if (ports != cks_ports) return -1;
  }
  return ports;
}

}  // extern "C"
