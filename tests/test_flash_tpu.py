"""Compiled (Mosaic) flash-kernel smoke tests — real TPU only.

The regular suite exercises the flash forward/dq/dkdv kernels in
interpret mode on the CPU fake mesh (``tests/test_flash.py``); the
compiled path — the forward's lane-wide (bq, 128) statistics scratch,
the backward's (bq, 1) column / (1, 1, qc) row statistics blocks, and
the windowed relative chunk axis, the most layout-sensitive pieces —
only exists on hardware. These tests run the same checks compiled on
the one real chip; they skip automatically on CPU-only runners.
(ADVICE round 1, item 1.)

Run manually on the TPU host:
``SMI_TPU_RUN_TPU_TESTS=1 python -m pytest tests/test_flash_tpu.py``
"""

import math
import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("SMI_TPU_RUN_TPU_TESTS", "").strip().lower()
    in ("", "0", "false", "no"),
    reason="TPU-only: set SMI_TPU_RUN_TPU_TESTS=1 on a TPU host",
)

jax = pytest.importorskip("jax")


def _tpu_available():
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False


@pytest.fixture(scope="module")
def tpu():
    if not _tpu_available():
        pytest.skip("no TPU device")
    return [d for d in jax.devices() if d.platform != "cpu"][0]


@pytest.mark.parametrize("dtype_name", ["float32", "bfloat16"])
@pytest.mark.parametrize("h,h_kv", [(2, 2), (4, 2)])
def test_compiled_forward_and_backward(tpu, dtype_name, h, h_kv):
    """Forward + custom-VJP backward (dq + dkdv kernels), compiled, GQA
    and plain, vs the jnp tier at the same precision."""
    import jax.numpy as jnp
    import smi_tpu as smi
    from smi_tpu.models import ring_attention as ra

    dtype = jnp.dtype(dtype_name)
    comm = smi.make_communicator(1, devices=[tpu])
    s, d = 512, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(s, h, d), dtype)
    k = jnp.asarray(rng.randn(s, h_kv, d), dtype)
    v = jnp.asarray(rng.randn(s, h_kv, d), dtype)

    fn_flash = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=True, interpret=False
    )
    fn_jnp = ra.make_ring_attention_fn(comm, causal=True, use_flash=False)

    out_f = np.asarray(fn_flash(q, k, v).astype(jnp.float32))
    out_j = np.asarray(fn_jnp(q, k, v).astype(jnp.float32))
    tol = 3e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(out_f, out_j, rtol=tol, atol=tol)

    def loss(fn):
        return lambda *args: (fn(*args).astype(jnp.float32) ** 2).sum()

    gf = jax.grad(loss(fn_flash), argnums=(0, 1, 2))(q, k, v)
    gj = jax.grad(loss(fn_jnp), argnums=(0, 1, 2))(q, k, v)
    gtol = 2e-1 if dtype == jnp.bfloat16 else 2e-3
    for a, b, name in zip(gf, gj, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=gtol, atol=gtol, err_msg=f"d{name}",
        )


def test_compiled_window_chunk_offset(tpu):
    """Windowed schedules whose live span is far shorter than the K/V
    extent, compiled: the streamed grid axis is relative and the
    BlockSpec index maps offset it by a nonzero chunk0 (f32, S=4096,
    window=512: the grid visits 2 of 4 total chunks). The
    Mosaic-compiled twin of
    tests/test_flash.py::test_ring_attention_window_chunk_offset."""
    import jax.numpy as jnp
    import smi_tpu as smi
    from smi_tpu.kernels import flash
    from smi_tpu.models import ring_attention as ra
    comm = smi.make_communicator(1, devices=[tpu])
    s, h, d, w = 4096, 2, 128, 512
    chunk = flash._window_chunk(s, flash.BLOCK_K, d, 4)
    n_grid, n_total = flash._window_chunks(s, chunk, flash.BLOCK_Q, w)
    assert n_grid < n_total, (n_grid, n_total)  # nonzero chunk0
    rng = np.random.RandomState(2)
    q, k, v, wt = (
        jnp.asarray(rng.randn(s, h, d), jnp.float32) for _ in range(4)
    )
    fn_f = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=True, interpret=False, window=w
    )
    fn_j = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=False, window=w
    )
    out_f = np.asarray(fn_f(q, k, v))
    out_j = np.asarray(fn_j(q, k, v))
    np.testing.assert_allclose(out_f, out_j, rtol=2e-4, atol=2e-4)

    gf = jax.grad(lambda q, k, v: jnp.sum(fn_f(q, k, v) * wt),
                  argnums=(0, 1, 2))(q, k, v)
    gj = jax.grad(lambda q, k, v: jnp.sum(fn_j(q, k, v) * wt),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gj, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg=name,
        )


def test_compiled_sliding_window(tpu):
    import jax.numpy as jnp
    import smi_tpu as smi
    from smi_tpu.models import ring_attention as ra

    comm = smi.make_communicator(1, devices=[tpu])
    s, h, d, w = 1024, 2, 128, 256
    rng = np.random.RandomState(1)
    q, k, v = (
        jnp.asarray(rng.randn(s, h, d), jnp.float32) for _ in range(3)
    )
    fn_f = ra.make_ring_attention_fn(
        comm, causal=True, use_flash=True, interpret=False, window=w
    )
    out = np.asarray(fn_f(q, k, v))
    ref = ra.reference_attention(
        np.asarray(q), np.asarray(k), np.asarray(v), causal=True, window=w
    )
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
