"""Disaggregated prefill/decode streaming inference under chaos.

The streaming-inference flagship: prefill ranks stream CRC+seq-framed
KV shards to decode ranks over the per-destination wire lanes, decode
runs continuous batching under interactive QoS (prefill bursts ride
the ``batch`` class, so token latency never queues behind prompt
processing), and the KV-shard lifecycle is ZERO-LOSS end to end —
every accepted token survives any single failure the campaign throws
at it. Everything runs through ONE :class:`ServingFrontend`: per-
tenant token buckets, QoS brownout ceilings, stream credits, per-
destination backpressure caps, phi-accrual failover — none bypassed.

Two recovery paths, never confused:

- **KV-shard handoff** (stateful): a decode rank that saturates (the
  named ``backpressure:rank<r>`` blame verdict) or dies mid-generation
  hands its resident KV shards to the least-loaded surviving decode
  rank through the house migration arc — draining -> handoff ->
  cutover -> committed/aborted — with checkpoint shards
  (:func:`pack_shard`'s CRC+seq framing) as the transport, the lane
  switch keyed by a fresh membership epoch
  (:meth:`MembershipView.migrate_cutover`), and the cutover gated by a
  quorum fencing token (the r17 discipline: no quorum, no cutover —
  abort loudly, loss-free). Generation resumes bit-identically:
  tokens are derived from the resident KV bits plus the accepted-
  token prefix chain, so a stale or corrupt resume DIVERGES instead
  of silently passing.
- **Stateless re-prefill**: a killed PREFILL rank holds nothing
  durable — its in-progress prompts replay from the WAL'd request
  (the engine's submission log) on a surviving prefill rank. No
  handoff is ever minted for a prefill death; the two paths are
  attributed separately in the audit trail and the campaign gates
  that they stay separate.

Gates (``tests/test_inference.py`` pins the campaign; the model
checker's ``Scope.infer`` tier exhausts the small-scope counterpart):
**zero lost accepted tokens** — a token appended to a generation is
checkpointed synchronously (the accept-time WAL) and survives
failover and handoff; **bit-identity** — the kill-decode cell's
delivered generations match the no-fault control arm on the
intersection of completed requests; **exactly-one attribution** —
a decode death commits exactly one KV handoff naming the dead rank;
**no stale-epoch leaks** — post-cutover stragglers from the old
incarnation are rejected by epoch; **saturation is not death** —
the blame-triggered handoff must not ride a membership transition.
"""

from __future__ import annotations

import dataclasses
import pickle
import random
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from smi_tpu.parallel.checkpoint import pack_shard, unpack_shard
from smi_tpu.parallel.membership import QuorumLostError, StaleEpochError
from smi_tpu.serving.admission import DEFAULT_POOL
from smi_tpu.serving.frontend import ServingFrontend
from smi_tpu.serving.qos import AdmissionRejected

#: Prompt chunks per request per QoS class of the SUBMITTING tenant
#: (the KV-shard count: one shard per prompt chunk). Small on purpose
#: — the campaign sweeps many requests, not long prompts.
PROMPT_CHUNKS = {"interactive": 2, "batch": 4}

#: Ticks of prefill compute per prompt chunk (the prefill rank is
#: busy this long before the KV shards hit the wire).
PREFILL_TICKS_PER_CHUNK = 1

#: Tokens generated per request unless the caller says otherwise.
DEFAULT_GEN_LEN = 4

#: Minimum inference campaign cell duration (ticks): long enough for
#: admission, prefill, KV transport, generation, and delivery to
#: complete for the open-loop arrival schedule.
MIN_INFER_DURATION = 80

#: Named backpressure sheds a decode rank must accumulate — while
#: holding resident generations — before the blame verdict arms the
#: handoff arc. A one-off transient (a delivery burst grazing the
#: backlog cap) is not saturation; a stalled consumer's shed stream
#: is. The saturation campaign cell crosses this within its flood
#: window; the no-fault smoke must never reach it.
SATURATION_SHED_MIN = 6

#: The engine-level request states, in lifecycle order. ``shed`` is
#: terminal-by-admission (loud, counted); ``done`` is the only
#: successful terminal state.
REQUEST_STATES = (
    "prefill", "kv-transport", "generating", "delivering", "done",
    "shed",
)


def kv_payload(tenant: str, req_no: int, chunk: int) -> str:
    """Content-addressed KV-shard payload: the decode side's token
    derivation hashes exactly these bits, so wrong routing, wrong
    bits, or a stale resume all diverge visibly."""
    return f"{tenant}/r{req_no}/kv{chunk}"


def decode_token(kv_payloads: Sequence[str],
                 tokens: Sequence[str]) -> str:
    """The next accepted token: a CRC chain over the RESIDENT KV bits
    and the accepted-token prefix. Deterministic, so a handed-off
    generation resumes bit-identically — and a resume from stale KV
    or a rolled-back prefix produces a DIFFERENT token, turning
    silent state loss into a loud bit-identity failure."""
    h = zlib.crc32("|".join(kv_payloads).encode())
    h = zlib.crc32("|".join(tokens).encode(), h)
    return f"tok{len(tokens)}/{h:08x}"


def decode_ranks_for(n: int) -> Tuple[int, ...]:
    """The default disaggregation split: the upper half of the pod
    decodes, the lower half prefills (at n=2: rank 0 prefills, rank 1
    decodes — the smallest disaggregated shape)."""
    if n < 2:
        raise ValueError(f"disaggregation needs >= 2 ranks, got {n}")
    return tuple(range(n // 2, n))


@dataclasses.dataclass
class InferenceRequest:
    """One streaming-inference request's engine bookkeeping."""

    tenant: str
    req_no: int
    prompt: Tuple[str, ...]          # prompt chunk payloads (WAL'd)
    gen_len: int
    prefill_rank: int
    decode_rank: int
    state: str = "prefill"
    prefill_left: int = 0
    kv_stream_id: Optional[Tuple[str, int]] = None
    token_stream_id: Optional[Tuple[str, int]] = None
    kv_payloads: Tuple[str, ...] = ()
    tokens: List[str] = dataclasses.field(default_factory=list)
    submitted_at: int = 0
    ttft: Optional[int] = None       # first accepted token latency
    shed_reason: Optional[str] = None
    replays: int = 0                 # stateless re-prefills
    pinned: bool = False             # caller-pinned decode placement

    @property
    def key(self) -> Tuple[str, int]:
        return (self.tenant, self.req_no)


class InferenceEngine:
    """Prefill/decode disaggregation over ONE serving front-end.

    Prefill ranks turn prompts into KV shards (``batch``-class streams
    to the decode rank's wire lane); decode ranks generate tokens from
    resident shards (continuous batching: one token per resident
    generation per tick) and deliver finished generations as
    ``interactive`` streams. The engine owns the KV-shard residency
    inventory and the zero-loss handoff arc; the front-end owns
    admission, transport, integrity, and membership — the engine never
    reaches around them.

    Wiring: the engine installs itself as the front-end's
    ``on_failover_reroute`` hook (in-flight KV transport restores at
    the heir from a checkpoint round-trip instead of replaying from
    zero) and publishes its residency inventory as
    ``fe.kv_shard_residents`` (the scale-in victim discipline reads
    it: a rank holding resident shards is never a scale-in victim).
    """

    def __init__(self, frontend: ServingFrontend,
                 decode_ranks: Optional[Sequence[int]] = None,
                 seed: int = 0):
        self.fe = frontend
        n = frontend.n
        picked = (tuple(sorted(decode_ranks))
                  if decode_ranks is not None else decode_ranks_for(n))
        if not picked:
            raise ValueError("need at least one decode rank")
        for r in picked:
            if not 0 <= r < n:
                raise ValueError(
                    f"decode rank {r} outside 0..{n - 1}"
                )
        if len(picked) == n:
            raise ValueError(
                "every rank decodes: disaggregation needs at least "
                "one prefill rank"
            )
        self.decode_ranks = picked
        self.prefill_ranks = tuple(
            r for r in range(n) if r not in picked
        )
        self.rng = random.Random(f"infer:{n}:{seed}")
        self.requests: List[InferenceRequest] = []
        self._req_seq: Dict[str, int] = {}
        self._by_kv_stream: Dict[Tuple[str, int], InferenceRequest] = {}
        self._by_token_stream: Dict[
            Tuple[str, int], InferenceRequest
        ] = {}
        #: rank -> {request key -> resident shard count}: THE KV-shard
        #: inventory. Published to the front-end for the scale-in
        #: victim discipline; every gate about "resident shards" reads
        #: this.
        self.residents: Dict[int, Dict[Tuple[str, int], int]] = {
            r: {} for r in range(n)
        }
        #: request key -> CRC-framed checkpoint blob of (kv_payloads,
        #: accepted tokens) — written synchronously at every token
        #: accept (the accept-time WAL the zero-loss gate rides).
        self.checkpoints: Dict[Tuple[str, int], bytes] = {}
        #: the in-flight saturation handoff arc, or None — one at a
        #: time, driven one state transition per step, mirroring the
        #: front-end's live-migration machine.
        self._arc: Optional[Dict] = None
        #: committed/aborted handoff audit trail: every entry names
        #: kind ("handoff" = blame-triggered, "failover" = decode
        #: death), src, dst, moved request keys, and the reason.
        self.handoffs: List[Dict] = []
        self.kv_handoffs_committed = 0
        self.kv_handoffs_aborted = 0
        #: in-flight KV-transport streams restored at an heir through
        #: the failover hook (checkpoint round-trip, NOT a committed
        #: handoff — attribution stays clean).
        self.transport_restores: List[Dict] = []
        self.replayed_prefills = 0
        self.lost_accepted_tokens = 0
        self.wal_restores = 0
        self.tokens_emitted = 0
        self.blame_triggers: List[Dict] = []
        self._confirm_cursor = 0
        self._shed_seen: Dict[int, int] = {}
        self._blame_growth: Dict[int, int] = {}
        frontend.on_failover_reroute = self._on_failover_reroute
        frontend.kv_shard_residents = self.residents
        # chain the admission gate's deferred-shed hook (the MoE
        # dispatcher's discipline): a stream PARKED at submit can
        # still be shed at pump time (admission-timeout, sustained
        # brownout) — a loudly-shed KV transport must move its
        # request to the terminal ``shed`` state, and a shed token
        # delivery must fall back to ``generating`` for a retry,
        # never hang in ``delivering`` forever
        prev_on_shed = frontend.gate.on_shed

        def _on_deferred_shed(rejection, request):
            if prev_on_shed is not None:
                prev_on_shed(rejection, request)
            req = self._by_kv_stream.pop(request.stream_id, None)
            if req is not None and req.state == "kv-transport":
                req.state = "shed"
                req.shed_reason = rejection.reason
            req = self._by_token_stream.pop(request.stream_id, None)
            if req is not None and req.state == "delivering":
                req.state = "generating"
                req.token_stream_id = None

        frontend.gate.on_shed = _on_deferred_shed

    # -- submission ------------------------------------------------------

    def submit(self, tenant: str, qos: str = "interactive",
               prompt_chunks: Optional[int] = None,
               gen_len: int = DEFAULT_GEN_LEN,
               decode_rank: Optional[int] = None) -> InferenceRequest:
        """Accept one request into the engine's WAL. Prefill starts
        next step; admission control applies when the KV shards hit
        the front-end (prefill output rides the ``batch`` class so
        prompt bursts never brown out interactive tokens)."""
        if qos not in PROMPT_CHUNKS:
            raise ValueError(
                f"inference rides {sorted(PROMPT_CHUNKS)} QoS, "
                f"got {qos!r}"
            )
        if gen_len < 0:
            raise ValueError(f"gen_len must be >= 0, got {gen_len}")
        chunks = (prompt_chunks if prompt_chunks is not None
                  else PROMPT_CHUNKS[qos])
        if chunks < 1:
            raise ValueError(f"need >= 1 prompt chunks, got {chunks}")
        if decode_rank is not None and decode_rank not in self.decode_ranks:
            raise ValueError(
                f"decode_rank {decode_rank} is not a decode rank "
                f"(decode ranks: {self.decode_ranks})"
            )
        req_no = self._req_seq.get(tenant, 0)
        self._req_seq[tenant] = req_no + 1
        prompt = tuple(
            kv_payload(tenant, req_no, c) for c in range(chunks)
        )
        req = InferenceRequest(
            tenant=tenant, req_no=req_no, prompt=prompt,
            gen_len=gen_len,
            prefill_rank=self._pick_prefill(),
            decode_rank=(decode_rank if decode_rank is not None
                         else self._pick_decode()),
            pinned=decode_rank is not None,
            prefill_left=chunks * PREFILL_TICKS_PER_CHUNK,
            submitted_at=self.fe.clock.now(),
        )
        self.requests.append(req)
        return req

    def _live(self, ranks: Sequence[int]) -> List[int]:
        members = self.fe.view.members
        return [r for r in ranks if r in members]

    def _pick_prefill(self) -> int:
        live = self._live(self.prefill_ranks)
        if not live:
            # every prefill rank is down: prefill on the least-loaded
            # decode rank (colocated mode) rather than reject — the
            # campaign never exercises this, but the degenerate shape
            # must not crash
            live = self._live(self.decode_ranks)
        if not live:
            raise RuntimeError("no live rank to prefill on")
        return min(live, key=lambda r: (self.fe._rank_load(r), r))

    def _pick_decode(self, exclude: Tuple[int, ...] = ()) -> int:
        live = [r for r in self._live(self.decode_ranks)
                if r not in exclude]
        if not live:
            live = self._live(self.decode_ranks)
        if not live:
            raise RuntimeError("no live decode rank")
        # a draining handoff source takes no NEW residents
        arc = self._arc
        if arc is not None and len(live) > 1:
            live = [r for r in live if r != arc["src"]] or live
        return min(live, key=lambda r: (self.fe._rank_load(r), r))

    # -- the step loop ---------------------------------------------------

    def step(self) -> None:
        """One engine tick: front-end first (transport, membership,
        admission), then the engine's reactions in dependency order —
        deaths before the arc (a dead arc party must abort it), the
        arc before prefill (a draining source takes no new work),
        transports before generation (a shard landing this tick
        generates this tick)."""
        self.fe.step()
        self._note_confirms()
        self._drive_arc()
        self._pump_prefill()
        self._note_transports()
        self._generate()
        self._note_deliveries()
        self._watch_saturation()

    def drain(self, max_ticks: int = 5000) -> None:
        """Step until every request reaches a terminal state (and the
        front-end itself is drained). The bound is a backstop for an
        engine bug, not a tunable."""
        for _ in range(max_ticks):
            if (all(r.state in ("done", "shed") for r in self.requests)
                    and not self.fe.active
                    and not any(
                        q for q in self.fe.gate.pending.values()
                    )):
                return
            self.step()
        stuck = sorted(
            (r.key, r.state) for r in self.requests
            if r.state not in ("done", "shed")
        )
        raise RuntimeError(
            f"inference drain did not converge in {max_ticks} ticks; "
            f"stuck requests: {stuck}"
        )

    # -- decode death: the stateful failover path ------------------------

    def _note_confirms(self) -> None:
        """React to newly confirmed deaths. A decode death with
        resident shards is the STATEFUL path: restore every resident
        generation at the heir from its accept-time checkpoint and
        commit exactly one failover handoff naming the dead rank. A
        prefill death is the STATELESS path: re-prefill from the
        WAL'd request — no handoff, ever."""
        new = self.fe.confirmed[self._confirm_cursor:]
        self._confirm_cursor = len(self.fe.confirmed)
        for dead in new:
            if self.residents.get(dead):
                self._failover_residents(dead)
            if dead in self.prefill_ranks:
                self._replay_prefills(dead)
            # a generation whose residency already retired (tokens
            # complete, delivery retrying) still routes its delivery
            # at the dead rank: move the route, nothing to restore
            for req in self.requests:
                if (req.state == "generating"
                        and req.decode_rank == dead
                        and not any(req.key in inv
                                    for inv in self.residents.values())):
                    try:
                        req.decode_rank = self._pick_decode(
                            exclude=(dead,)
                        )
                    except RuntimeError:
                        pass

    def _failover_residents(self, dead: int) -> None:
        now = self.fe.clock.now()
        keys = sorted(self.residents[dead])
        try:
            heir = self._pick_decode(exclude=(dead,))
        except RuntimeError:
            # no live decode rank left: the shards are orphaned —
            # loudly, in the audit trail, never silently
            self.handoffs.append({
                "kind": "failover", "src": dead, "dst": None,
                "streams": [list(k) for k in keys],
                "state": "aborted", "abort_reason": "no-heir",
                "reason": f"failover:rank{dead}", "at": now,
            })
            self.kv_handoffs_aborted += 1
            return
        try:
            token = self.fe.mint_quorum_token(
                rank=heir, what=f"kv failover {dead}->{heir}",
            )
        except QuorumLostError:
            # the r17 discipline: no quorum, no failover actuation.
            # Abort loudly; the shards stay attributed to the dead
            # rank and the next confirm (post-heal) retries.
            self.handoffs.append({
                "kind": "failover", "src": dead, "dst": heir,
                "streams": [list(k) for k in keys],
                "state": "aborted", "abort_reason": "quorum-lost",
                "reason": f"failover:rank{dead}", "at": now,
            })
            self.kv_handoffs_aborted += 1
            return
        del token  # actuation fenced; the mint is the gate
        moved = []
        for key in keys:
            req = next(
                r for r in self.requests if r.key == key
            )
            shards = self.residents[dead].pop(key)
            if req.state != "generating":
                continue  # transported-not-yet-generating: rebuilt below
            blob = self.checkpoints[key]
            _rank, _step, payload, _crc = unpack_shard(
                blob, origin=f"kv-failover:{key[0]}/r{key[1]}",
            )
            kv, tokens = pickle.loads(payload)
            if len(tokens) < len(req.tokens):
                # the forbidden outcome: the synchronous accept-time
                # checkpoint is BEHIND the accepted prefix
                self.lost_accepted_tokens += (
                    len(req.tokens) - len(tokens)
                )
            req.kv_payloads = tuple(kv)
            req.tokens = list(tokens)
            req.decode_rank = heir
            self.residents[heir][key] = shards
            self.wal_restores += 1
            moved.append(key)
        self.handoffs.append({
            "kind": "failover", "src": dead, "dst": heir,
            "streams": [list(k) for k in moved],
            "state": "committed",
            "reason": f"failover:rank{dead}", "at": now,
        })
        self.kv_handoffs_committed += 1

    def _replay_prefills(self, dead: int) -> None:
        """Stateless re-prefill: prompts in flight on a dead prefill
        rank restart from the WAL'd request on a survivor. KV shards
        already on the wire are the front-end's problem (its WAL
        replays them); shards already resident need nothing."""
        for req in self.requests:
            if req.state != "prefill" or req.prefill_rank != dead:
                continue
            req.prefill_rank = self._pick_prefill()
            req.prefill_left = (
                len(req.prompt) * PREFILL_TICKS_PER_CHUNK
            )
            req.replays += 1
            self.replayed_prefills += 1

    # -- the in-flight KV transport hook ---------------------------------

    def _on_failover_reroute(self, st, dead: int, owner: int) -> bool:
        """The front-end's failover asks: can the engine restore this
        stream's progress at an heir from its own durable state?
        True only for KV-TRANSPORT streams (delivered shards round-
        trip through a CRC-framed checkpoint to the engine's chosen
        decode heir — progress survives, nothing replays from zero).
        Token-delivery streams return False: their chunks live in the
        request WAL, and the stateless void-and-replay path is
        exactly right for them."""
        req = self._by_kv_stream.get(st.request.stream_id)
        if req is None or req.state != "kv-transport":
            return False
        try:
            heir = self._pick_decode(exclude=(dead,))
        except RuntimeError:
            return False
        try:
            token = self.fe.mint_quorum_token(
                rank=heir, what=f"kv transport restore -> {heir}",
            )
        except QuorumLostError:
            # no quorum: fall back to the loud, loss-free stateless
            # replay rather than actuate unfenced
            return False
        del token
        # the delivered prefix survives the death because it round-
        # trips the same CRC+seq framing the handoff arc uses — a
        # corrupt restore raises, never silently resumes
        payload = pickle.dumps(
            (dict(sorted(st.delivered.items())), st.next_to_send)
        )
        blob, _crc = pack_shard(dead, self.fe.view.epoch, payload)
        _rank, _step, back, _crc2 = unpack_shard(
            blob, origin=f"kv-transport:{req.tenant}/r{req.req_no}",
        )
        delivered, next_to_send = pickle.loads(back)
        st.delivered = dict(delivered)
        st.next_to_send = next_to_send
        st.dst = heir
        st.lane_epoch = self.fe.view.epoch
        self.fe.lanes[heir].next_seq[
            (st.index, st.lane_epoch)
        ] = next_to_send
        req.decode_rank = heir
        self.transport_restores.append({
            "stream": list(st.request.stream_id), "src": dead,
            "dst": heir, "restored_chunks": len(st.delivered),
            "at": self.fe.clock.now(),
        })
        return True

    # -- the saturation handoff arc --------------------------------------

    def _start_arc(self, src: int, reason: str) -> None:
        keys = sorted(
            k for k, r in (
                (rq.key, rq) for rq in self.requests
            ) if r.state == "generating" and r.decode_rank == src
        )
        self._arc = {
            "state": "draining", "src": src,
            "dst": self._pick_decode(exclude=(src,)),
            "reqs": keys, "blob": None, "reason": reason,
            "requested_at": self.fe.clock.now(),
        }

    def _fenced(self, req: InferenceRequest) -> bool:
        """True while the request's shards are in the handoff window
        (handoff packed, cutover not yet committed): generation is
        FROZEN so the packed snapshot and the live prefix cannot
        diverge. Draining does NOT fence — tokens accepted during the
        drain are in the snapshot because the pack happens after."""
        arc = self._arc
        return (arc is not None
                and arc["state"] in ("handoff", "cutover")
                and req.key in arc["reqs"])

    def _arc_drained(self) -> bool:
        """No in-flight KV transport still targets the source AND the
        source wire is quiet (no frame in flight or landed-unconsumed
        — the ``_migration_drained`` discipline): the snapshot at
        handoff must not race traffic still landing at the source.
        Monotone while the source lives: a draining source takes no
        new residents (``_pick_decode`` skips it)."""
        src = self._arc["src"]
        lane = self.fe.lanes[src]
        if lane.in_flight or lane.landed:
            return False
        return not any(
            req.state == "kv-transport" and req.decode_rank == src
            for req in self.requests
        )

    def _drive_arc(self) -> None:
        """One handoff-arc transition per tick, the house migration
        discipline applied to KV residency: a membership change
        touching either party aborts FIRST (a failover already moved
        or voided the state; cutting over would resurrect it), then
        draining -> handoff -> cutover -> committed."""
        arc = self._arc
        if arc is None:
            return
        members = self.fe.view.members
        if arc["src"] not in members or arc["dst"] not in members:
            self._abort_arc("membership-change")
            return
        if arc["state"] == "draining":
            if self._arc_drained():
                self._arc_handoff()
        elif arc["state"] == "handoff":
            try:
                self._arc_cutover()
            except QuorumLostError:
                # the cutover's quorum mint failed: committing across
                # a partition could generate the same request on both
                # sides. Abort loudly, loss-free — the fence lifts and
                # generation continues on the source.
                self._abort_arc("quorum-lost")
        elif arc["state"] == "cutover":
            self._arc_commit()

    def _arc_handoff(self) -> None:
        """Fence and pack: the arc requests' KV shards and accepted-
        token prefixes go into ONE CRC+seq-framed checkpoint shard —
        the same framing the elastic soak writes to disk, here as the
        handoff transport. Packed AFTER the fence, so the blob and
        the live prefix agree by construction."""
        arc = self._arc
        arc["state"] = "handoff"  # fence first, then snapshot
        snapshot = sorted(
            (req.key, (req.kv_payloads, tuple(req.tokens)))
            for req in self.requests
            if req.key in arc["reqs"] and req.state == "generating"
        )
        payload = pickle.dumps(snapshot)
        blob, _crc = pack_shard(
            arc["src"], self.fe.view.epoch, payload
        )
        arc["blob"] = blob

    def _arc_cutover(self) -> None:
        arc = self._arc
        # mint BEFORE touching any state: a QuorumLostError must
        # leave the arc cleanly abortable
        token = self.fe.mint_quorum_token(
            rank=arc["dst"],
            what=f"kv handoff cutover {arc['src']}->{arc['dst']}",
        )
        _rank, _step, payload, _crc = unpack_shard(
            arc["blob"], origin=f"kv-handoff:{arc['src']}",
        )
        restored = dict(pickle.loads(payload))
        old_epoch = self.fe.view.epoch
        new_epoch = self.fe.view.migrate_cutover(
            arc["src"], arc["dst"], tenant="kv-handoff", token=token,
        )
        for req in self.requests:
            if req.key not in arc["reqs"]:
                continue
            if req.state != "generating":
                continue  # finished during the drain: nothing resident
            handed = restored.get(req.key)
            if handed is None:
                raise RuntimeError(
                    f"KV handoff lost request {req.key}: not in the "
                    f"shard packed at handoff"
                )
            kv, tokens = handed
            if len(tokens) < len(req.tokens):
                self.lost_accepted_tokens += (
                    len(req.tokens) - len(tokens)
                )
            req.kv_payloads = tuple(kv)
            req.tokens = list(tokens)
            shards = self.residents[arc["src"]].pop(req.key, 0)
            self.residents[arc["dst"]][req.key] = shards
            req.decode_rank = arc["dst"]
        # one straggler from the old incarnation presents the pre-
        # cutover epoch: rejected by epoch, never folded in
        try:
            self.fe.view.validate(
                arc["src"], old_epoch, what="post-handoff straggler",
            )
            self.fe.stale_epoch_leaks += 1
        except StaleEpochError:
            self.fe.stale_epoch_rejections += 1
        del new_epoch
        arc["state"] = "cutover"

    def _arc_commit(self) -> None:
        arc = self._arc
        self.handoffs.append({
            "kind": "handoff", "src": arc["src"], "dst": arc["dst"],
            "streams": [list(k) for k in arc["reqs"]],
            "state": "committed", "reason": arc["reason"],
            "requested_at": arc["requested_at"],
            "committed_at": self.fe.clock.now(),
        })
        self.kv_handoffs_committed += 1
        self._arc = None

    def _abort_arc(self, why: str) -> None:
        arc = self._arc
        self.handoffs.append({
            "kind": "handoff", "src": arc["src"], "dst": arc["dst"],
            "streams": [list(k) for k in arc["reqs"]],
            "state": "aborted", "abort_reason": why,
            "reason": arc["reason"],
            "requested_at": arc["requested_at"],
            "aborted_at": self.fe.clock.now(),
        })
        self.kv_handoffs_aborted += 1
        self._arc = None

    # -- prefill, transport, decode, delivery ----------------------------

    def _pump_prefill(self) -> None:
        for req in self.requests:
            if req.state != "prefill":
                continue
            if (req.prefill_rank not in self.fe.view.members
                    or req.prefill_rank in self.fe.killed):
                # a dead rank computes nothing NOW; recovery waits for
                # the confirm (-> _replay_prefills) like everything else
                continue
            req.prefill_left -= 1
            if req.prefill_left > 0:
                continue
            # placement is decided when the KV is actually ready; a
            # caller's pin holds as long as its rank is live (the
            # wire and the failover path still outrank it)
            if not (req.pinned
                    and req.decode_rank in self._live(self.decode_ranks)
                    and req.decode_rank not in self.fe.killed):
                req.decode_rank = self._pick_decode()
            try:
                fe_req = self.fe.submit(
                    req.tenant, "batch", req.prompt,
                    base_rank=req.decode_rank,
                )
            except AdmissionRejected as e:
                req.state = "shed"
                req.shed_reason = e.reason
                continue
            except QuorumLostError:
                req.prefill_left = 1  # retry next tick
                continue
            req.kv_stream_id = fe_req.stream_id
            self._by_kv_stream[fe_req.stream_id] = req
            req.state = "kv-transport"

    def _note_transports(self) -> None:
        """A completed KV stream installs residency at its landing
        rank: the shards live where the wire put them (which may be a
        failover heir, not the rank chosen at submit)."""
        for st in self.fe.completed:
            req = self._by_kv_stream.pop(st.request.stream_id, None)
            if req is None or req.state != "kv-transport":
                continue
            req.kv_payloads = tuple(
                st.delivered[i] for i in range(st.total_chunks)
            )
            req.decode_rank = st.dst
            self.residents[st.dst][req.key] = len(req.kv_payloads)
            req.state = "generating"
            self._checkpoint(req)
            if req.gen_len == 0:
                # the degenerate zero-token generation: done at
                # arrival, nothing to deliver, shards retire
                self._retire(req)
                req.state = "done"

    def _checkpoint(self, req: InferenceRequest) -> None:
        """The accept-time WAL: (KV bits, accepted prefix) packed
        through the CRC+seq shard framing, synchronously — BEFORE the
        token counts as accepted. This is the zero-loss guarantee's
        entire mechanism."""
        payload = pickle.dumps(
            (req.kv_payloads, tuple(req.tokens))
        )
        blob, _crc = pack_shard(
            req.decode_rank, len(req.tokens), payload
        )
        self.checkpoints[req.key] = blob

    def _generate(self) -> None:
        """Continuous batching: one token per resident, unfenced
        generation per tick. A finished generation submits its tokens
        as an INTERACTIVE stream (token latency is the product)."""
        now = self.fe.clock.now()
        for req in self.requests:
            if req.state != "generating":
                continue
            if self._fenced(req):
                continue
            if (req.decode_rank not in self.fe.view.members
                    or req.decode_rank in self.fe.killed):
                # physically dead = no compute, instantly; the shards
                # stay attributed to the dead rank until the CONFIRM
                # moves them (-> _failover_residents) — detection
                # latency is the control plane's, not physics'
                continue
            if len(req.tokens) < req.gen_len:
                req.tokens.append(
                    decode_token(req.kv_payloads, req.tokens)
                )
                self.tokens_emitted += 1
                self._checkpoint(req)
                if req.ttft is None:
                    req.ttft = now - req.submitted_at
            if len(req.tokens) >= req.gen_len:
                self._try_deliver(req)

    def _try_deliver(self, req: InferenceRequest) -> None:
        try:
            fe_req = self.fe.submit(
                req.tenant, "interactive", tuple(req.tokens),
                base_rank=req.decode_rank,
            )
        except (AdmissionRejected, QuorumLostError):
            return  # retry next tick; tokens are checkpointed
        req.token_stream_id = fe_req.stream_id
        self._by_token_stream[fe_req.stream_id] = req
        # generation is complete and every token checkpointed: the
        # shards have done their job, the inventory releases the rank
        self._retire(req)
        req.state = "delivering"

    def _note_deliveries(self) -> None:
        for st in self.fe.completed:
            req = self._by_token_stream.pop(
                st.request.stream_id, None
            )
            if req is None or req.state != "delivering":
                continue
            req.state = "done"

    def _retire(self, req: InferenceRequest) -> None:
        for inv in self.residents.values():
            inv.pop(req.key, None)
        self.checkpoints.pop(req.key, None)

    # -- saturation blame ------------------------------------------------

    def _watch_saturation(self) -> None:
        """A decode rank accumulating NEW named backpressure sheds
        while holding resident generations is the blame verdict the
        handoff arc keys on. The trigger is the shed counter — an
        admission-edge fact — never a membership event: saturation is
        not death, and the campaign gates that no confirm rides a
        pure-saturation cell."""
        gate = self.fe.gate
        for r in self.decode_ranks:
            reason = f"backpressure:rank{r}"
            count = sum(
                gate.shed[c].get(reason, 0) for c in gate.shed
            )
            grew = count - self._shed_seen.get(r, 0)
            self._shed_seen[r] = count
            if grew <= 0:
                # the accrual DECAYS on quiet ticks (the house
                # _recent_stalls discipline): only SUSTAINED shedding
                # — growth most ticks — reaches the arming threshold;
                # a transient graze halves away
                if self._blame_growth.get(r):
                    self._blame_growth[r] //= 2
                continue
            if r not in self.fe.view.members:
                continue
            if r in self.fe.detector.suspected or r in self.fe.killed:
                # suspicion pauses blame: a rank the detector already
                # doubts is the FAILOVER path's problem — starting a
                # load-balancing handoff from it would race the
                # confirm and muddle the two recovery attributions
                continue
            if not self.residents.get(r):
                continue
            accrued = self._blame_growth.get(r, 0) + grew
            self._blame_growth[r] = accrued
            if accrued < SATURATION_SHED_MIN:
                continue  # a transient graze, not saturation
            self.blame_triggers.append({
                "rank": r, "reason": reason, "sheds": count,
                "at": self.fe.clock.now(),
            })
            if self._arc is not None:
                continue
            live = self._live(self.decode_ranks)
            if len(live) < 2:
                continue  # nowhere to hand off to: named, not acted
            self._blame_growth[r] = 0
            self._start_arc(r, reason=f"blame:{reason}")

    # -- report ----------------------------------------------------------

    def generation_digest(self) -> Dict[Tuple[str, int], Tuple[str, ...]]:
        """(tenant, req_no) -> accepted token tuple, for DONE requests
        — the bit-identity surface the kill-decode cell compares
        against its no-fault control arm on the key intersection."""
        return {
            req.key: tuple(req.tokens)
            for req in self.requests if req.state == "done"
        }

    def report(self) -> Dict:
        states = {s: 0 for s in REQUEST_STATES}
        for req in self.requests:
            states[req.state] += 1
        ttfts = sorted(
            req.ttft for req in self.requests
            if req.ttft is not None
        )
        return {
            "decode_ranks": list(self.decode_ranks),
            "prefill_ranks": list(self.prefill_ranks),
            "requests": len(self.requests),
            "states": states,
            "tokens_emitted": self.tokens_emitted,
            "kv_handoffs_committed": self.kv_handoffs_committed,
            "kv_handoffs_aborted": self.kv_handoffs_aborted,
            "replayed_prefills": self.replayed_prefills,
            "lost_accepted_tokens": self.lost_accepted_tokens,
            "wal_restores": self.wal_restores,
            "transport_restores": len(self.transport_restores),
            "handoffs": [dict(h) for h in self.handoffs],
            "blame_triggers": [dict(b) for b in self.blame_triggers],
            "resident_shards": {
                r: sum(inv.values())
                for r, inv in self.residents.items() if inv
            },
            "ttft": ttfts,
            "arc_state": (self._arc["state"]
                          if self._arc is not None else None),
        }


# -- the traced execution variant ----------------------------------------

def traced_kv_dataflow(comm, requests: int = 2, kv_chunks: int = 4,
                       gen_len: int = 2):
    """The same prefill -> KV-scatter -> decode-gather dataflow as a
    traced program (the SNIPPETS [2]/[3] pjit shard/gather shape):
    prompts enter replicated, the KV projection shards across the
    mesh axis (every device holds its KV slice — the decode
    residency), and the token readout gathers the sharded KV back
    through a CRC-like fold per generation step. Returned alongside
    the tokens is the optimized HLO text, so the static verifier and
    the traffic lint can check the SAME dataflow the serving engine
    runs dynamically.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = comm.axis_names[0]
    n = comm.size
    if kv_chunks % n:
        raise ValueError(
            f"kv_chunks={kv_chunks} must divide over {n} devices"
        )

    def shard_fn(prompts):
        # prefill: the KV projection of each prompt chunk, computed
        # on the shard that will hold it (the scatter IS the layout)
        idx = jax.lax.axis_index(axis)
        local = prompts * (idx + 1).astype(jnp.float32)
        # decode: each step folds the RESIDENT kv slice with the
        # accepted-token prefix — the psum is the gather that makes
        # every token depend on every resident shard, exactly the
        # bit-identity coupling decode_token() gives the engine
        tokens = []
        prefix = jnp.zeros((requests,), jnp.float32)
        for step in range(gen_len):
            folded = jax.lax.psum(
                jnp.sum(local, axis=-1), axis_name=axis
            )
            # the next step's local KV update is independent of this
            # step's gather — the overlap the traffic lint checks for
            # (a gather that gates ALL compute is the sync-no-overlap
            # finding; the serving engine's continuous batching has
            # the same property dynamically)
            local = local + jnp.float32(step + 1)
            prefix = prefix + folded
            tokens.append(prefix)
        return jnp.stack(tokens) if tokens else jnp.zeros(
            (0, requests), jnp.float32
        )

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=P(),
        out_specs=P(), check_vma=False,
    ))
    prompts = (
        jnp.arange(requests * kv_chunks, dtype=jnp.float32)
        .reshape(requests, kv_chunks)
    )
    with comm.mesh:
        compiled = fn.lower(prompts).compile()
        out = compiled(prompts)
    hlo_text = compiled.as_text()
    return jax.device_get(out), hlo_text
