"""Serialization round-trip + topology parsing tests.

Reference: ``codegen/tests/test_parse.py`` — program JSON and routing-file
parsing, including reference-format device keys (``fpga-0001:acl0``).
"""

import json

import pytest

from smi_tpu.ops.operations import Push, Pop, Reduce, Broadcast
from smi_tpu.ops.program import Device, Program
from smi_tpu.ops.serialization import (
    parse_operation,
    parse_program,
    parse_topology_file,
    serialize_operation,
    serialize_program,
)
from smi_tpu.ops.types import SmiOp


def test_operation_round_trip():
    ops = [
        Push(0, "float", buffer_size=100),
        Pop(1, "double"),
        Reduce(2, "int", op=SmiOp.MAX),
        Broadcast(3, "char"),
    ]
    for op in ops:
        assert parse_operation(serialize_operation(op)) == op


def test_program_round_trip():
    prog = Program(
        [Push(0, "float"), Pop(1, "short", buffer_size=64)],
        consecutive_reads=5,
        max_ranks=16,
        p2p_rendezvous=False,
    )
    restored = parse_program(serialize_program(prog))
    assert restored.operations == prog.operations
    assert restored.consecutive_reads == 5
    assert restored.max_ranks == 16
    assert restored.p2p_rendezvous is False


def test_parse_reduce_defaults_to_add():
    op = parse_operation({"type": "reduce", "port": 1, "data_type": "float"})
    assert op.op is SmiOp.ADD


def test_parse_unknown_type_rejected():
    with pytest.raises(ValueError):
        parse_operation({"type": "sendrecv", "port": 0})


TOPOLOGY = {
    "fpgas": {
        "fpga-0001:acl0": "rank0",
        "fpga-0001:acl1": "rank1",
        "fpga-0002:acl0": "rank1",
    },
    "connections": {
        "fpga-0001:acl0:ch2": "fpga-0001:acl1:ch3",
        "fpga-0001:acl0:ch1": "fpga-0002:acl0:ch0",
    },
}


def test_parse_topology_reference_format():
    progs = {"rank0": Program([Push(0)]), "rank1": Program([Pop(0)])}
    topo = parse_topology_file(json.dumps(TOPOLOGY), programs=progs)

    assert [str(d) for d in topo.devices] == [
        "fpga-0001:0",
        "fpga-0001:1",
        "fpga-0002:0",
    ]
    # connections are bidirectional (serialization.py:91-107)
    a = (Device("fpga-0001", 0), 2)
    b = (Device("fpga-0001", 1), 3)
    assert topo.connections[a] == b
    assert topo.connections[b] == a

    d0 = Device("fpga-0001", 0)
    assert topo.mapping.program_for(d0) is progs["rank0"]
    assert topo.mapping.rank_of(d0) == 0

    nbrs = topo.neighbours(d0)
    assert nbrs == [
        (1, Device("fpga-0002", 0), 0),
        (2, Device("fpga-0001", 1), 3),
    ]


def test_parse_topology_missing_program_rejected():
    with pytest.raises(KeyError):
        parse_topology_file(json.dumps(TOPOLOGY), programs={})


def test_parse_topology_ignore_programs():
    topo = parse_topology_file(json.dumps(TOPOLOGY), ignore_programs=True)
    assert len(topo.devices) == 3


def test_parse_topology_duplicate_endpoint_rejected():
    bad = dict(TOPOLOGY)
    bad["connections"] = {
        "a:0:ch0": "b:0:ch0",
        "c:0:ch1": "b:0:ch0",
    }
    with pytest.raises(ValueError):
        parse_topology_file(json.dumps(bad), ignore_programs=True)


def test_parse_reference_nested_reduce_args():
    # the reference nests the operator as args.op_type
    # (codegen/serialization.py:30-38, ops.py:172-174)
    op = parse_operation(
        {"type": "reduce", "port": 2, "data_type": "float",
         "args": {"op_type": "max"}}
    )
    assert op.op is SmiOp.MAX


def test_parse_missing_data_type_defaults_to_int():
    # reference default (codegen/serialization.py:22)
    op = parse_operation({"type": "push", "port": 0})
    assert op.dtype.value == "int"
