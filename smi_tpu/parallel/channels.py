"""Transient point-to-point streaming channels (Push/Pop).

Reference parity: ``include/smi/{push,pop,channel_descriptor}.h`` and the
generated ``templates/{push,pop}.cl``. A reference channel is opened per
message with ``SMI_Open_{send,receive}_channel(count, dtype, peer, port,
comm)``; ``SMI_Push``/``SMI_Pop`` then move one element per call through the
NoC, with a credit-based rendezvous bounding in-flight packets.

TPU re-design — one SPMD collective instead of two endpoint loops:

- Opening a channel is metadata only (:class:`P2PChannel`), as in the
  reference where opens build a descriptor (``push.cl:52-66``).
- The Push loop + NoC hop + Pop loop collapse into ``transfer()``: a masked
  ``lax.ppermute`` over the communicator axis, which every rank of the SPMD
  program executes. At ``dst`` it returns the message; at every other rank
  it returns zeros. XLA lowers this to a direct ICI send/recv — the CK_S/
  CK_R routing tables have no equivalent because the torus routes itself.
- *Streaming* semantics — SMI's defining feature, where the consumer runs
  while the message is still arriving — survive as ``stream()``: the
  message moves in ``pipeline_packets``-sized chunks under ``lax.scan`` and
  a consumer function is applied per chunk, so transfer of chunk *k+1*
  overlaps the consumer of chunk *k*. The channel's buffer size
  ("asynchronicity degree", ``rewrite.py:26-33``) sets the chunk size,
  playing exactly its reference role of pipelining depth.
- ``p2p_rendezvous=False`` (eager, reference ``templates/push.cl:21-31``
  compiled out) sends the whole message in one ppermute.
- ``consecutive_reads`` (the reference's ``READS_LIMIT`` CK fairness
  bound, ``templates/device.cl:13-14``, ``cks.cl:73-81``) bounds how many
  chunks a streamed transfer moves per pipelining step before yielding
  the stream: each ``lax.scan`` step transfers a *burst* of up to that
  many chunks in one ppermute, with the consumer still applied per chunk.
- ``backend="ring"`` on ``transfer``/``stream`` moves the message over
  the explicit credit-flow-controlled neighbour RDMA kernel
  (:mod:`smi_tpu.kernels.ring`), hop by hop through intermediate ranks —
  the faithful analog of packets forwarded through intermediate FPGAs'
  CK pairs (``ckr.cl:50-60``).
"""

from __future__ import annotations

import dataclasses
import zlib
from functools import partial
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from smi_tpu.ops.types import (
    SmiDtype,
    SmiOp,
    dtype_to_jnp,
    elements_per_packet,
)
from smi_tpu.ops.operations import Reduce, pipeline_depth_packets
from smi_tpu.parallel.backend import (
    check_backend,
    combine_fn,
    identity_for,
    reduction_fn,
)
from smi_tpu.parallel.credits import IntegrityError
from smi_tpu.parallel.mesh import Communicator
from smi_tpu.utils.watchdog import Deadline



class FrameCheck(NamedTuple):
    """Host-side verdict material of one verified transfer.

    All three fields are arrays produced inside the traced collective
    (a pytree, so ``shard_map``/``jit`` pass it through): ``expected``
    is the per-chunk checksum vector computed at ``src`` and moved to
    ``dst`` over the same tier as the payload; ``got`` recomputes the
    checksums from the delivered message; ``at_dst`` masks the ranks
    where the comparison is meaningful (everyone else holds zeros).
    :meth:`P2PChannel.verify_frames` turns a mismatch into a named
    :class:`~smi_tpu.parallel.credits.IntegrityError` after readback.
    """

    expected: jax.Array
    got: jax.Array
    at_dst: jax.Array


@dataclasses.dataclass(frozen=True)
class P2PChannel:
    """Descriptor of one transient P2P message channel.

    Mirrors ``SMI_Channel`` (``include/smi/channel_descriptor.h:17-31``):
    message element count, the two endpoint ranks, the logical port, and the
    pipelining depth. ``src``/``dst`` must be Python ints (they become the
    static ``ppermute`` permutation, as the reference's ranks become static
    routing-table entries).
    """

    comm: Communicator
    port: int
    src: int
    dst: int
    count: int
    dtype: SmiDtype = SmiDtype.FLOAT
    buffer_size: Optional[int] = None  # elements; None = default depth
    rendezvous: bool = True
    #: Chunk-burst bound per pipelining step (reference ``READS_LIMIT``,
    #: ``device.cl:13-14``): a streamed transfer moves at most this many
    #: chunks per scan step before yielding the stream.
    consecutive_reads: int = 8

    def __post_init__(self):
        object.__setattr__(self, "dtype", SmiDtype.parse(self.dtype))
        size = self.comm.size
        for name, r in (("src", self.src), ("dst", self.dst)):
            if not (0 <= r < size):
                raise ValueError(f"{name}={r} out of range for comm size {size}")
        if self.src == self.dst:
            raise ValueError("src and dst must differ for a P2P channel")
        if self.count <= 0:
            raise ValueError(f"message count must be positive, got {self.count}")
        if self.consecutive_reads < 1:
            raise ValueError(
                f"consecutive_reads must be >= 1, got {self.consecutive_reads}"
            )

    @property
    def jnp_dtype(self):
        return dtype_to_jnp(self.dtype)

    @property
    def chunk_elements(self) -> int:
        """Elements per in-flight chunk.

        buffer_size elements → whole packets (rounded as the reference
        rounds, ``rewrite.py:26-33``) → elements. Never below one packet.
        """
        packets = pipeline_depth_packets(self.buffer_size, self.dtype)
        return packets * elements_per_packet(self.dtype)

    # ------------------------------------------------------------------
    # Collective implementations (must be traced by ALL ranks)
    # ------------------------------------------------------------------

    def _perm(self) -> Sequence[Tuple[int, int]]:
        return [(self.src, self.dst)]

    def _axis(self):
        """Collective axis argument: the name, or the ordered tuple for
        a multi-axis communicator — ``lax.ppermute`` and the ring
        kernels both treat the tuple as one flattened axis in row-major
        rank order, matching the channel's flattened ``src``/``dst``."""
        names = self.comm.axis_names
        return names[0] if len(names) == 1 else names

    def _ring_stream(self) -> int:
        """Barrier-semaphore stream slot of this channel's port — the
        per-port FIFO independence of the reference's CK pairs (distinct
        ports never share a semaphore domain up to the tier's domain
        count; ``kernels/ring.py::ring_collective_id``)."""
        from smi_tpu.kernels.ring import RING_STREAMS

        return self.port % RING_STREAMS

    def _check_length(self, data: jax.Array) -> None:
        if data.shape[0] != self.count:
            raise ValueError(
                f"message length {data.shape[0]} != channel count {self.count}"
            )

    def _hops(self) -> Tuple[int, int]:
        """(direction, hop count) of the shorter way around the ring."""
        n = self.comm.size
        right = (self.dst - self.src) % n
        left = (self.src - self.dst) % n
        return (1, right) if right <= left else (-1, left)

    def burst_schedule(self) -> List[int]:
        """Element counts moved per pipelining step under rendezvous.

        The observable chunking schedule: chunk size comes from the
        asynchronicity degree (``rewrite.py:26-33``), burst width from
        ``consecutive_reads`` (``READS_LIMIT``) — the first entries are
        scan-steps of ``consecutive_reads`` whole chunks, then leftover
        single chunks, then the element tail.
        """
        chunk = min(self.chunk_elements, self.count)
        burst = self.consecutive_reads * chunk
        n_bursts = self.count // burst
        schedule = [burst] * n_bursts
        remaining = self.count - n_bursts * burst
        schedule += [chunk] * (remaining // chunk)
        tail = remaining % chunk
        if tail:
            schedule.append(tail)
        return schedule

    def _ring_payload(self, data: jax.Array, chunked: bool) -> jax.Array:
        """Masked, zero-padded, ``(n_chunks, chunk, ...)``-shaped payload
        for the ring tier (one row = one in-flight unit)."""
        masked = jnp.where(self.comm.rank() == self.src, data,
                           jnp.zeros_like(data))
        if not chunked:
            return masked[None]
        chunk = min(self.chunk_elements, self.count)
        n_chunks = -(-self.count // chunk)
        pad = n_chunks * chunk - self.count
        if pad:
            masked = jnp.concatenate(
                [masked, jnp.zeros((pad,) + masked.shape[1:],
                                   masked.dtype)]
            )
        return masked.reshape((n_chunks, chunk) + data.shape[1:])

    def _deadline(self, deadline: Optional[Deadline],
                  what: str) -> Optional[Deadline]:
        """Attach this channel's protocol mirror to a caller deadline, so
        a timeout dumps the per-rank state of the matching protocol
        (``what`` is the faults.FAMILY_PROTOCOL key — "transfer" and
        "stream" both mirror the neighbour-stream machine)."""
        if deadline is None:
            return None
        from smi_tpu.parallel.faults import mirror_state_provider

        return deadline.with_provider(
            mirror_state_provider(what, self.comm.size, structured=True)
        )

    def _ring_move(self, chunked_payload: jax.Array,
                   deadline: Optional[Deadline] = None) -> jax.Array:
        """Drive a ``(rows, ...)`` payload hop-by-hop to ``dst`` over the
        neighbour RDMA kernel (the shorter way around the ring), in this
        channel's port stream slot. The deadline is checked before every
        hop AT HOST DISPATCH TIME — each Python-level hop issue, which
        under ``jit`` means while tracing (a compiled, cached program
        re-executes without re-checking). It bounds stuck multi-hop
        *dispatch*; to bound blocking *execution*, wrap the readback in
        :func:`smi_tpu.utils.watchdog.run_with_deadline`."""
        from smi_tpu.kernels import ring as _ring

        direction, hops = self._hops()
        mesh_axes = _ring.mesh_axes_of(self.comm)
        out = chunked_payload
        for hop in range(hops):
            if deadline is not None:
                deadline.check(
                    f"ring hop {hop + 1}/{hops} of port-{self.port} "
                    f"channel {self.src}->{self.dst}"
                )
            out = _ring.neighbour_stream(
                out, self._axis(), self.comm.size, direction=direction,
                interpret=not self.comm.is_tpu,
                stream=self._ring_stream(), mesh_axes=mesh_axes,
            )
        return out

    def _ring_transfer(self, data: jax.Array, chunked: bool,
                       deadline: Optional[Deadline] = None) -> jax.Array:
        """Move the masked message hop-by-hop over the neighbour RDMA
        kernel. Intermediate ranks forward zeros of their own, so only
        ``dst`` ends up with the payload — the SPMD rendition of packets
        transiting intermediate CK pairs (``ckr.cl:50-60``)."""
        out = self._ring_move(self._ring_payload(data, chunked), deadline)
        return out.reshape((-1,) + data.shape[1:])[: self.count]

    def transfer(self, data: jax.Array, backend: str = "xla",
                 deadline: Optional[Deadline] = None) -> jax.Array:
        """Fused Push+Pop: send ``data`` (valid at ``src``) to ``dst``.

        Every rank calls this at the same program point (SPMD); the rank
        holding the payload is ``src``. Returns the message at ``dst`` and
        zeros elsewhere — the reference's non-participants simply never see
        the packets (``ckr.cl:50-60``); here they see a zero buffer.
        ``backend="ring"`` sends over the explicit credit-controlled
        neighbour RDMA tier instead of ``lax.ppermute``.

        ``deadline`` (:class:`smi_tpu.utils.watchdog.Deadline`) bounds
        the host-side dispatch (under ``jit``, the trace — compiled
        re-executions are not re-checked): expiry raises
        ``WatchdogTimeout`` with the protocol's per-rank state mirror
        attached. Hard-bound blocking execution with
        :func:`smi_tpu.utils.watchdog.run_with_deadline`.
        """
        data = jnp.asarray(data, self.jnp_dtype)
        self._check_length(data)
        deadline = self._deadline(deadline, "transfer")
        if deadline is not None:
            deadline.check(f"transfer on port-{self.port} channel")
        if check_backend(backend) == "ring":
            return self._ring_transfer(data, chunked=False,
                                       deadline=deadline)
        return lax.ppermute(data, self._axis(), self._perm())

    def stream(
        self,
        data: jax.Array,
        consumer: Optional[Callable] = None,
        init_carry=None,
        backend: str = "xla",
        deadline: Optional[Deadline] = None,
    ):
        """Streamed transfer: move the message chunk-by-chunk.

        With no ``consumer`` this behaves like :meth:`transfer` but bounds
        in-flight data to a burst of chunks (the rendezvous protocol's
        role, ``push.cl:21-31``). With a ``consumer(carry, chunk) ->
        carry``, the consumer is applied to each received chunk *inside
        the scan*, so XLA can overlap the transfer of the next burst with
        consumer compute — the TPU expression of SMI's
        compute-while-receiving.

        Each scan step moves up to ``consecutive_reads`` chunks in one
        ppermute (the ``READS_LIMIT`` fairness bound: how much one stream
        may burst before yielding, ``cks.cl:73-81``); the consumer still
        sees individual chunks. :meth:`burst_schedule` exposes the
        resulting schedule.

        ``backend="ring"`` moves the chunks over the credit-controlled
        neighbour RDMA kernel (hop-by-hop for non-neighbour endpoints)
        and then applies the consumer per chunk.

        Returns ``(received, carry)`` where ``received`` is the reassembled
        message (valid at ``dst``).
        """
        data = jnp.asarray(data, self.jnp_dtype)
        self._check_length(data)
        check_backend(backend)
        deadline = self._deadline(deadline, "stream")
        if deadline is not None:
            deadline.check(f"stream on port-{self.port} channel")
        if not self.rendezvous:
            out = self.transfer(data, backend=backend, deadline=deadline)
            if consumer is not None:
                carry = consumer(init_carry, out)
                return out, carry
            return out, init_carry

        chunk = min(self.chunk_elements, self.count)

        if backend == "ring":
            received = self._ring_transfer(data, chunked=True,
                                           deadline=deadline)
            carry = init_carry
            if consumer is not None:
                n_full = self.count // chunk
                tail = self.count - n_full * chunk
                if n_full:
                    full = received[: n_full * chunk].reshape(
                        (n_full, chunk) + data.shape[1:]
                    )
                    carry, _ = lax.scan(
                        lambda c, ch: (consumer(c, ch), 0), carry, full
                    )
                if tail:
                    carry = consumer(carry, received[n_full * chunk:])
            return received, carry

        axis, perm = self._axis(), self._perm()

        def consume_chunks(carry, received):
            """Apply the consumer chunk-wise to one received burst."""
            if consumer is None:
                return carry
            rows = received.shape[0]
            for i in range(rows // chunk):
                carry = consumer(carry, received[i * chunk:(i + 1) * chunk])
            if rows % chunk:
                carry = consumer(carry, received[rows - rows % chunk:])
            return carry

        def step(carry, burst_data):
            received = lax.ppermute(burst_data, axis, perm)
            return consume_chunks(carry, received), received

        burst = self.consecutive_reads * chunk
        n_bursts = self.count // burst

        carry = init_carry
        parts = []
        used = n_bursts * burst
        if n_bursts:
            bursts = data[:used].reshape((n_bursts, burst) + data.shape[1:])
            carry, received = lax.scan(step, carry, bursts)
            parts.append(received.reshape((used,) + data.shape[1:]))
        # leftover whole chunks move as single-chunk steps, the element
        # tail as one short chunk — all *outside* the scan so the consumer
        # only ever sees real message elements (no zero-padding leaks into
        # non-additive reductions)
        remaining = self.count - used
        for _ in range(remaining // chunk):
            if deadline is not None:
                deadline.check(f"stream tail on port-{self.port} channel")
            carry, got = step(carry, data[used:used + chunk])
            parts.append(got)
            used += chunk
        if used < self.count:
            carry, got = step(carry, data[used:])
            parts.append(got)
        received = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        return received, carry

    def stream_reduce(
        self,
        data: jax.Array,
        op: Union[str, SmiOp] = SmiOp.ADD,
        lanes: Optional[int] = None,
        backend: str = "xla",
        deadline: Optional[Deadline] = None,
    ):
        """Streamed reduction: pop each arriving chunk and fold it into
        ``lanes`` independent partial accumulators, combined at the end.

        The reference's streaming Reduce masks FP-add pipeline latency
        with a shift register of partial accumulators
        (``templates/reduce.cl:63-70``, config ``codegen/ops.py:110-141``);
        chunk-at-a-time accumulation under ``lax.scan`` has the same
        serial-dependence hazard, and ``lanes`` breaks the chain the same
        way: chunk *k* folds into partial ``k % lanes``. The default comes
        from the op model (:attr:`Reduce.accumulation_lanes`: 4 for
        float/double, 1 for integers), so the knob declared in a program
        manifest governs the runtime schedule.

        Returns ``(received, total)``: the reassembled message and the
        reduction over all its elements (both valid at ``dst``; the
        reduction of the zero buffer elsewhere).
        """
        op = SmiOp.parse(op)
        if lanes is None:
            lanes = Reduce(self.port, self.dtype).accumulation_lanes
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        data = jnp.asarray(data, self.jnp_dtype)
        combine = combine_fn(op)
        chunk_reduce = reduction_fn(op)
        dt = self.jnp_dtype
        partials0 = jnp.full((lanes,) + data.shape[1:], identity_for(op, dt), dt)

        def consumer(carry, chunk_data):
            partials, i = carry
            folded = combine(partials[i % lanes], chunk_reduce(chunk_data, axis=0))
            return partials.at[i % lanes].set(folded), i + 1

        received, (partials, _) = self.stream(
            data, consumer=consumer, init_carry=(partials0, jnp.int32(0)),
            backend=backend, deadline=deadline,
        )
        total = chunk_reduce(partials, axis=0)
        return received, total

    # ------------------------------------------------------------------
    # Verified transport: per-chunk sequence-keyed checksums
    # ------------------------------------------------------------------

    def chunk_checksums(self, data: jax.Array) -> jax.Array:
        """Per-chunk int32 checksums of a message.

        Chunk ``k``'s payload words (the dtype's raw bits) are summed
        with int32 wraparound under ODD pseudo-random position weights
        (``i * 2654435761 | 1`` — multiplicative hashing). Odd weights
        make any single-bit flip visible (an odd multiple of a power
        of two is never 0 mod 2**32); a truncated landing (zeros where
        payload was) changes the weighted sum; and the well-mixed
        position dependence makes the checksum content-order-
        sensitive: swapped chunks, a reordering *within* a chunk, and
        structured patterns a linear weighting misses (reversals of
        symmetric data) all compare unequal unless the weighted sums
        collide — a ~2**-32-shaped accident, the same class as any
        32-bit checksum. Deterministic and identical at both
        endpoints — the comparison in :meth:`verify_frames` is exact,
        not approximate.
        """
        data = jnp.asarray(data, self.jnp_dtype)
        chunk = min(self.chunk_elements, self.count)
        n_chunks = -(-self.count // chunk)
        pad = n_chunks * chunk - self.count
        x = data[: self.count]
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)]
            )
        if jnp.issubdtype(x.dtype, jnp.floating):
            nbits = x.dtype.itemsize * 8
            x = lax.bitcast_convert_type(
                x, jnp.dtype(f"int{nbits}")
            )
        words = x.astype(jnp.int32).reshape(n_chunks, -1)
        # Knuth's 32-bit golden-ratio multiplier, int32 wraparound;
        # | 1 keeps every weight odd
        weights = jnp.bitwise_or(
            jnp.arange(words.shape[1], dtype=jnp.int32)
            * jnp.int32(-1640531527),
            jnp.int32(1),
        )
        return jnp.sum(words * weights[None, :], axis=1,
                       dtype=jnp.int32)

    def _move_checksums(self, sums: jax.Array, backend: str) -> jax.Array:
        """Deliver the src's checksum vector to dst over the payload's
        tier (zeros elsewhere) — the frame header riding its own
        message."""
        masked = jnp.where(self.comm.rank() == self.src, sums,
                           jnp.zeros_like(sums))
        if backend == "ring":
            return self._ring_move(masked[None])[0]
        return lax.ppermute(masked, self._axis(), self._perm())

    def _frame_check(self, data: jax.Array, received: jax.Array,
                     backend: str) -> FrameCheck:
        return FrameCheck(
            expected=self._move_checksums(
                self.chunk_checksums(data), backend
            ),
            got=self.chunk_checksums(received),
            at_dst=(self.comm.rank() == self.dst).astype(jnp.int32),
        )

    def transfer_verified(
        self, data: jax.Array, backend: str = "xla",
        deadline: Optional[Deadline] = None,
    ) -> Tuple[jax.Array, FrameCheck]:
        """:meth:`transfer` plus end-to-end integrity evidence.

        Returns ``(received, check)``; after readback, pass the
        concrete ``check`` to :meth:`verify_frames` — a corrupted,
        truncated, or reordered chunk raises a named
        :class:`~smi_tpu.parallel.credits.IntegrityError` (chunk
        index, expected vs got) instead of flowing silently into the
        consumer.
        """
        data = jnp.asarray(data, self.jnp_dtype)
        received = self.transfer(data, backend=backend,
                                 deadline=deadline)
        return received, self._frame_check(data, received, backend)

    def stream_verified(
        self,
        data: jax.Array,
        consumer: Optional[Callable] = None,
        init_carry=None,
        backend: str = "xla",
        deadline: Optional[Deadline] = None,
    ):
        """:meth:`stream` plus end-to-end integrity evidence.

        Returns ``(received, carry, check)``. The checksum vector is
        computed over the same chunking the stream moves, so the check
        localizes damage to the in-flight unit that suffered it.
        """
        data = jnp.asarray(data, self.jnp_dtype)
        received, carry = self.stream(
            data, consumer=consumer, init_carry=init_carry,
            backend=backend, deadline=deadline,
        )
        return received, carry, self._frame_check(data, received,
                                                  backend)

    def verify_frames(self, check: FrameCheck,
                      context: str = "") -> None:
        """Host-side verdict: raise on any chunk whose delivered
        checksum differs from the one computed at the source.

        Call after readback with concrete arrays (inside a trace the
        comparison has no value yet). No-op at ranks other than
        ``dst`` — their buffers are zeros by contract.
        """
        import numpy as np

        if not bool(np.any(np.asarray(check.at_dst))):
            return
        expected = np.asarray(check.expected)
        got = np.asarray(check.got)
        bad = np.nonzero(expected != got)[0]
        if bad.size == 0:
            return
        k = int(bad[0])
        where = f" during {context}" if context else ""
        raise IntegrityError(
            f"verified transfer on port-{self.port} channel "
            f"{self.src}->{self.dst}{where}: chunk {k} (of "
            f"{expected.size}) arrived corrupted: checksum expected "
            f"{int(expected[k]):#010x}, got {int(got[k]):#010x}"
            + (f"; {bad.size - 1} further chunk(s) also damaged"
               if bad.size > 1 else ""),
            rank=self.dst, src=self.src, seq=k,
            expected=int(expected[k]), got=int(got[k]),
            kind="checksum",
        )


#: Port space for transient per-tenant stream channels. Ports in this
#: range are derived, never hand-assigned; they fold onto the ring
#: tier's barrier-semaphore stream domains via
#: :meth:`P2PChannel._ring_stream` exactly like static ports do.
TENANT_PORT_SPACE = 1 << 16


def tenant_stream_port(tenant: str, stream_seq: int) -> int:
    """Deterministic transient port for one tenant stream.

    The serving front-end's (tenant, per-tenant sequence) stream
    identity hashed into the port space — stable across processes, so
    every rank of an SPMD program derives the same port without
    coordination, the way the reference's transient channels derive
    CK routing-table entries from the (port, comm) pair at open time.
    """
    if stream_seq < 0:
        raise ValueError(f"stream_seq must be >= 0, got {stream_seq}")
    return zlib.crc32(
        f"tenant-stream:{tenant}:{stream_seq}".encode()
    ) % TENANT_PORT_SPACE


def open_tenant_channel(
    comm: Communicator,
    tenant: str,
    stream_seq: int,
    src: int,
    dst: int,
    count: int,
    dtype: SmiDtype = SmiDtype.FLOAT,
    **kwargs,
) -> P2PChannel:
    """A transient per-tenant P2P channel — the serving analog of
    ``SMI_Open_send_channel`` opening a channel per message: metadata
    only (no device work), with the port derived from the tenant
    stream identity (:func:`tenant_stream_port`) so concurrent tenants
    land on distinct ring stream domains (up to the tier's domain
    count) and a tenant's consecutive streams rotate domains instead
    of serializing behind one barrier semaphore. All other
    :class:`P2PChannel` knobs (buffer size, rendezvous,
    consecutive_reads) pass through."""
    return P2PChannel(
        comm, port=tenant_stream_port(tenant, stream_seq),
        src=src, dst=dst, count=count, dtype=dtype, **kwargs,
    )


def stream_concurrent(
    channels: Sequence[P2PChannel],
    datas: Sequence[jax.Array],
    backend: str = "xla",
) -> Tuple[jax.Array, ...]:
    """Move several P2P messages chunk-by-chunk *in lockstep*.

    One ``lax.scan`` advances every channel by one burst per step, so the
    per-step ppermutes are independent ops XLA can overlap — the TPU
    expression of the reference's concurrent channels sharing the NoC
    (``bandwidth_0.cl``'s two app kernels pushing simultaneously).
    ``Channel.stream`` per channel would instead lower to back-to-back
    scans, serializing the transfers.

    The lockstep granularity is the channels' shared ``consecutive_reads``
    burst (``READS_LIMIT``): a channel may move that many chunks per step
    before the other channels advance — exactly the reference CK loop's
    fairness bound between sources (``cks.cl:73-81``).

    ``backend="ring"`` moves the bursts over the credit-flow-controlled
    neighbour RDMA tier instead: the channels' bursts interleave at the
    same ``READS_LIMIT`` granularity (a TPU core runs one kernel at a
    time, so "concurrency" here is the reference's CK *fairness* —
    no channel may starve another beyond one burst), and each channel's
    kernels run in the barrier-semaphore domain of its port
    (:meth:`P2PChannel._ring_stream` — the per-port FIFO independence
    of ``multi_collectives.cl``).

    All channels must agree on message count, chunk size and burst width
    (the benchmark shape). Returns the received message per channel.
    """
    if len(channels) != len(datas):
        raise ValueError("one data array per channel required")
    if not channels:
        return ()
    counts = {ch.count for ch in channels}
    chunks = {min(ch.chunk_elements, ch.count) for ch in channels}
    reads = {ch.consecutive_reads for ch in channels}
    if len(counts) != 1 or len(chunks) != 1 or len(reads) != 1:
        raise ValueError(
            "concurrent streaming requires equal message/chunk/burst "
            f"sizes; got counts {sorted(counts)}, chunks {sorted(chunks)}, "
            f"consecutive_reads {sorted(reads)}"
        )
    datas = tuple(
        jnp.asarray(d, ch.jnp_dtype) for ch, d in zip(channels, datas)
    )
    for ch, d in zip(channels, datas):
        ch._check_length(d)
    if check_backend(backend) == "ring":
        return _stream_concurrent_ring(
            channels, datas, counts.pop(), chunks.pop(), reads.pop()
        )
    count, chunk = counts.pop(), chunks.pop() * reads.pop()

    axes_perms = [(ch._axis(), ch._perm()) for ch in channels]

    def step(carry, xs):
        outs = tuple(
            lax.ppermute(x, axis, perm)
            for (axis, perm), x in zip(axes_perms, xs)
        )
        return carry, outs

    n_full = count // chunk
    tail = count - n_full * chunk
    parts = [[] for _ in channels]
    if n_full:
        stacked = tuple(
            d[: n_full * chunk].reshape((n_full, chunk) + d.shape[1:])
            for d in datas
        )
        _, received = lax.scan(step, (), stacked)
        for i, r in enumerate(received):
            parts[i].append(r.reshape((n_full * chunk,) + datas[i].shape[1:]))
    if tail:
        _, tails = step((), tuple(d[n_full * chunk:] for d in datas))
        for i, r in enumerate(tails):
            parts[i].append(r)
    return tuple(
        p[0] if len(p) == 1 else jnp.concatenate(p) for p in parts
    )


def _stream_concurrent_ring(
    channels: Sequence[P2PChannel],
    datas: Sequence[jax.Array],
    count: int,
    chunk: int,
    reads: int,
) -> Tuple[jax.Array, ...]:
    """Ring-tier concurrent streaming: burst-interleaved fair schedule.

    Each round moves ONE ``reads``-chunk burst of every channel (in
    channel order) over the neighbour RDMA kernel before any channel
    advances to its next burst — the CK loop's ``READS_LIMIT`` fairness
    between sources (``cks.cl:73-81``) made into the kernel schedule.
    Per-channel stream slots keep the barrier-semaphore domains apart.
    """
    del chunk  # shared by validation; each channel re-derives it
    per = [
        ch._ring_payload(d, chunked=True)
        for ch, d in zip(channels, datas)
    ]
    n_chunks = per[0].shape[0]
    received: List[List[jax.Array]] = [[] for _ in channels]
    for b0 in range(0, n_chunks, reads):
        for i, ch in enumerate(channels):
            received[i].append(ch._ring_move(per[i][b0:b0 + reads]))
    outs = []
    for i, d in enumerate(datas):
        whole = (received[i][0] if len(received[i]) == 1
                 else jnp.concatenate(received[i]))
        outs.append(whole.reshape((-1,) + d.shape[1:])[:count])
    return tuple(outs)


def ring_shift(
    x: jax.Array,
    comm: Communicator,
    offset: int = 1,
    axis_name: Optional[str] = None,
    backend: str = "xla",
) -> jax.Array:
    """Shift ``x`` to rank ``(r + offset) % size`` along a comm axis.

    The TPU analog of the reference's rank-pipeline pattern
    (``microbenchmarks/kernels/pipeline.cl:16-31``): each rank pops from
    rank-1 and pushes to rank+1. One ``ppermute`` with the full ring
    permutation rides neighbour ICI links; ``backend="ring"`` makes the
    same move over the explicit neighbour RDMA kernel, one hop per
    offset step.
    """
    name = axis_name or comm.axis_names[0]
    n = comm.mesh.shape[name]
    if check_backend(backend) == "ring" and x.size:
        # (zero-size payloads fall through to the ppermute path: the
        # ring kernel has no 0-element block shape, and moving nothing
        # is backend-indifferent)
        from smi_tpu.kernels import ring as _ring

        direction = 1 if offset >= 0 else -1
        out = x[None]
        mesh_axes = _ring.mesh_axes_of(comm)
        for _ in range(abs(offset) % n):
            out = _ring.neighbour_stream(
                out, name, n, direction=direction,
                interpret=not comm.is_tpu, mesh_axes=mesh_axes,
            )
        return out[0]
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, name, perm)
