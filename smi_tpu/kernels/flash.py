"""Flash-attention block kernel for the ring-attention schedule.

The jnp block-attend path (``models/ring_attention.py::_block_attend``)
materializes the ``(H, Sq, Sk)`` score tensor in HBM — at long context
that traffic, not the MXU, bounds throughput. This kernel is the
TPU-native fix: the classic blockwise online-softmax (flash) schedule,
where score tiles live only in VMEM and the running ``(m, l, acc)``
state never leaves the chip.

It deliberately has the *same contract* as ``_block_attend`` — fold one
K/V block into carried online-softmax state, with global ``q_off`` /
``k_off`` positions for exact causal masking — so one ring step is one
kernel launch and the ring's cross-device accumulation is unchanged.
This mirrors how the reference overlaps neighbour streaming with
pipelined compute (``examples/kernels/stencil_smi.cl:236-386``): the
ppermute moves the next K/V block while this kernel consumes the
current one.

Schedule: the grid is ``(H, n_q, n_kc)`` over 4096-lane key *chunks*;
each grid step runs a VMEM-resident ``fori_loop`` over 512-wide key
sub-tiles, so per-step dispatch overhead amortizes over 8 MXU tiles.
The online-softmax state is a value carry of the inner loop and a VMEM
scratch carry across chunks. Causality is enforced at both levels from
global positions: fully-masked chunks are skipped by ``pl.when``, and
the inner loop's trip count is clipped to the last live sub-tile — the
causal schedule does ~half the dense work.

Layouts are head-major — ``q``/``k``/``v``/``acc`` as ``(H, S, D)``,
``m``/``l`` as ``(H, S, 1)`` — so every tile the kernel touches has a
lane-tileable minor dimension and the softmax statistics are column
vectors, avoiding in-kernel relayouts.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

#: query tile rows (per grid step)
BLOCK_Q = 512
#: key sub-tile columns (per inner-loop iteration)
BLOCK_K = 512
#: key-chunk budget (per grid step) in rows at head_dim 128; scaled
#: down for wider heads so double-buffered K/V chunks (2048 rows x 128
#: lanes x 4 B x 2 bufs x {k,v} = 4 MB) plus q/acc tiles and loop
#: temporaries stay inside the 16 MB scoped-VMEM limit
CHUNK_K = 2048
#: widest supported head_dim (q/acc tiles and K/V chunks scale with d)
MAX_HEAD_DIM = 512


def _pick_block(extent: int, target: int, multiple: int = 8) -> Optional[int]:
    """Largest divisor of ``extent`` that is ≤ target and a multiple of
    the dtype's sublane tile (8 rows f32, 16 rows bf16)."""
    for b in range(min(extent, target), multiple - 1, -1):
        if extent % b == 0 and b % multiple == 0:
            return b
    return None


def _sublane(dtype) -> int:
    return 16 if dtype == jnp.bfloat16 else 8


def flash_supported(s_q: int, s_k: int, d: int, dtype) -> bool:
    """The fast path needs f32/bf16 (scores and the online-softmax
    state are always f32), lane-aligned head_dim, and tileable sequence
    extents; callers fall back to the jnp path otherwise."""
    if dtype not in (jnp.float32, jnp.bfloat16):
        return False
    mult = _sublane(dtype)
    return (
        d % 128 == 0
        and d <= MAX_HEAD_DIM
        and _pick_block(s_q, BLOCK_Q, mult) is not None
        and _pick_block(s_k, BLOCK_K, mult) is not None
    )


def _flash_kernel(
    offs_ref,   # scalar prefetch: [q_off, k_off] global block positions
    q_ref,      # (1, bq, D) query tile, head h
    k_ref,      # (1, kc, D) key chunk
    v_ref,      # (1, kc, D) value chunk
    m_in_ref,   # (1, bq, 1) carried running row-max, head h
    l_in_ref,   # (1, bq, 1) carried normalizer
    acc_in_ref,  # (1, bq, D) carried weighted value sum
    m_out_ref,  # (1, bq, 1)
    l_out_ref,  # (1, bq, 1)
    acc_out_ref,  # (1, bq, D)
    m_s,        # scratch (bq, 1)
    l_s,        # scratch (bq, 1)
    acc_s,      # scratch (bq, D)
    *,
    block_q: int,
    block_k: int,
    chunk_k: int,
    n_kc: int,
    causal: bool,
    scale: float,
    precision,
):
    qi = pl.program_id(1)
    kci = pl.program_id(2)
    bq, bk, kc = block_q, block_k, chunk_k
    n_sub = kc // bk

    @pl.when(kci == 0)
    def _load_carry():
        m_s[...] = m_in_ref[0]
        l_s[...] = l_in_ref[0]
        acc_s[...] = acc_in_ref[0]

    # Global positions of this tile's rows and of the chunk's first
    # column; chunks wholly inside the causal future are skipped.
    q_first = offs_ref[0] + qi * bq
    c_first = offs_ref[1] + kci * kc
    live = (not causal) or (c_first <= q_first + bq - 1)

    @pl.when(live)
    def _attend():
        q = q_ref[0]
        if causal:
            # sub-tiles past the diagonal contribute nothing: clip the
            # trip count to the last live one
            n_live = jnp.minimum(
                (q_first + bq - 1 - c_first) // bk + 1, n_sub
            )
        else:
            n_live = n_sub

        def body(ki, carry):
            m, l, acc = carry
            kb = k_ref[0, pl.ds(ki * bk, bk), :]
            scores = lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                precision=precision, preferred_element_type=jnp.float32,
            ) * scale  # (bq, bk)
            if causal:
                k_first = c_first + ki * bk
                q_pos = q_first + lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0
                )
                k_pos = k_first + lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1
                )
                scores = jnp.where(k_pos > q_pos, NEG_INF, scores)
            m_new = jnp.maximum(m, scores.max(axis=1, keepdims=True))
            # exp(-1e30 - -1e30) = 1 for still-all-masked rows:
            # transient garbage, zeroed by this same correction once a
            # live key lands (the jnp path's semantics)
            correction = jnp.exp(m - m_new)
            p = jnp.exp(scores - m_new)
            l = l * correction + p.sum(axis=1, keepdims=True)
            vb = v_ref[0, pl.ds(ki * bk, bk), :]
            # match V's dtype for the MXU (free for f32; for bf16
            # inputs p ∈ [0,1] rounds at ~2^-8, the bf16 tier's noise)
            acc = acc * correction + lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                precision=precision, preferred_element_type=jnp.float32,
            )
            return m_new, l, acc

        m, l, acc = lax.fori_loop(
            0, n_live, body, (m_s[...], l_s[...], acc_s[...])
        )
        m_s[...] = m
        l_s[...] = l
        acc_s[...] = acc

    @pl.when(kci == n_kc - 1)
    def _store_carry():
        m_out_ref[0] = m_s[...]
        l_out_ref[0] = l_s[...]
        acc_out_ref[0] = acc_s[...]


def flash_block_attend(
    q: jax.Array,       # (H, Sq, D)
    k: jax.Array,       # (H, Sk, D)
    v: jax.Array,       # (H, Sk, D)
    m: jax.Array,       # (H, Sq, 1)
    l: jax.Array,       # (H, Sq, 1)
    acc: jax.Array,     # (H, Sq, D)
    q_off,
    k_off,
    causal: bool,
    scale: float,
    precision=None,
    interpret: bool = False,
):
    """Fold one K/V block into the online-softmax carry (flash tier).

    Head-major twin of ``_block_attend``: same math, same global-offset
    causal mask, but score tiles never leave VMEM. ``q_off``/``k_off``
    may be traced (they arrive via scalar prefetch).
    """
    h, s_q, d = q.shape
    s_k = k.shape[1]
    mult = _sublane(q.dtype)
    bq = _pick_block(s_q, BLOCK_Q, mult)
    bk = _pick_block(s_k, BLOCK_K, mult)
    if bq is None or bk is None:
        raise ValueError(f"untileable extents Sq={s_q}, Sk={s_k}")
    # chunk = as many sub-tiles as fit the VMEM budget, which shrinks
    # for wide heads and grows for narrow dtypes (K/V chunk bytes scale
    # with d * itemsize)
    budget_rows = max(1, CHUNK_K * 128 * 4 // (d * q.dtype.itemsize))
    kc = bk * max(1, min(budget_rows // bk, s_k // bk))
    while s_k % kc:
        kc -= bk
    n_q, n_kc = s_q // bq, s_k // kc
    if precision is None:
        precision = lax.Precision.HIGHEST
    if q.dtype == jnp.bfloat16:
        # HIGHEST requests an f32-precision contraction, which Mosaic
        # rejects for bf16 operands (and which bf16 inputs cannot honor
        # anyway) — the MXU's native bf16 pass is the faithful mode
        precision = lax.Precision.DEFAULT

    kernel = functools.partial(
        _flash_kernel, block_q=bq, block_k=bk, chunk_k=kc, n_kc=n_kc,
        causal=causal, scale=scale, precision=precision,
    )
    offs = jnp.stack(
        [jnp.asarray(q_off), jnp.asarray(k_off)]
    ).astype(jnp.int32)
    qspec = pl.BlockSpec((1, bq, d), lambda hh, qi, ki, offs: (hh, qi, 0))
    kspec = pl.BlockSpec((1, kc, d), lambda hh, qi, ki, offs: (hh, ki, 0))
    colspec = pl.BlockSpec(
        (1, bq, 1), lambda hh, qi, ki, offs: (hh, qi, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, n_q, n_kc),
        in_specs=[qspec, kspec, kspec, colspec, colspec, qspec],
        out_specs=[colspec, colspec, qspec],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((h, s_q, 1), jnp.float32),
            jax.ShapeDtypeStruct((h, s_q, 1), jnp.float32),
            jax.ShapeDtypeStruct((h, s_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(offs, q, k, v, m, l, acc)
