"""Static verification of the credits protocol zoo + the control plane.

The compile-time correctness tiers: :mod:`.verifier` proves
deadlock-freedom, slot-race-freedom, credit conservation, and wire-lane
monotonicity over every schedule of a registered protocol from a single
symbolic replay per rank (happens-before analysis — Lamport CACM'78,
Eraser SOSP'97; see PAPERS.md); :mod:`.model` + :mod:`.properties` are
the control-plane analog — an explicit-state model checker that
exhaustively verifies the epoch, admission, and recovery state machines
at small scopes by driving the REAL serving/membership/WAL objects
(``smi-tpu lint --model``); :mod:`.perf` is the PERFORMANCE tier —
critical-path decomposition of every registered protocol's makespan on
the timestamped simulator plus a kernel roofline lint
(``smi-tpu lint --perf``), pricing what the safety tiers prove;
:mod:`.mutants` and :mod:`.perf_mutants` ship the broken variants —
protocol-tier event-stream transformers, control-plane seam breaks,
and safe-but-slow timing mutants — that prove every check can fail. Pure Python — no JAX, no devices — so
``smi-tpu lint`` runs anywhere in seconds and CI can gate merges on it.
The dynamic schedule fuzzer (``credits.explore_all_schedules``) and the
chaos campaigns remain the authority on *faulted wire* behaviour;
``docs/analysis.md`` states exactly what each tier does and does not
prove.
"""

from smi_tpu.analysis.verifier import (  # noqa: F401
    CHECKS,
    DEFAULT_SHAPES,
    MAX_LINT_N,
    AnalysisError,
    CreditConservation,
    Finding,
    SlotRace,
    StaticDeadlock,
    StaticReport,
    VerifyEvent,
    WireLaneViolation,
    build_generators,
    lint_all,
    render_reports,
    reports_to_json,
    symbolic_events,
    verify_generators,
    verify_protocol,
)
from smi_tpu.analysis.mutants import (  # noqa: F401
    MODEL_MUTANT_PROPERTY,
    MODEL_MUTANTS,
    MUTANTS,
    model_mutant_world,
    mutant_generators,
)
from smi_tpu.analysis.model import (  # noqa: F401
    DEFAULT_SCOPES,
    ModelFinding,
    ModelReport,
    Scope,
    World,
    check_scope,
    check_scopes,
    model_reports_to_json,
    parse_scope,
    render_model_reports,
)
from smi_tpu.analysis.properties import PROPERTIES  # noqa: F401
from smi_tpu.analysis.perf import (  # noqa: F401
    ANALYTIC_DRIFT_FRACTION,
    ANALYTIC_EXPECTED_US,
    BELOW_ROOFLINE_FRACTION,
    IDLE_FRACTION_THRESHOLD,
    PERF_CHECKS,
    PERF_LINT_CHECKS,
    PERF_PAYLOAD_BYTES,
    PERF_PROTOCOL_CHECKS,
    VMEM_DOUBLE_BUFFER_BOUND,
    PerfFinding,
    PerfReport,
    analytic_predictions,
    analytic_regression_findings,
    below_roofline_findings,
    decompose_generators,
    decompose_protocol,
    no_double_buffer_findings,
    perf_all,
    perf_reports_to_json,
    render_perf_reports,
    roofline_lint,
    serialized_dma_findings,
)
from smi_tpu.analysis.perf_mutants import (  # noqa: F401
    OVERSIZED_FLASH_TILE,
    PERF_MUTANT_RULE,
    PERF_MUTANTS,
    perf_mutant_generators,
)
