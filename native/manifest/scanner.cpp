#include "scanner.h"

#include <cctype>
#include <map>
#include <set>
#include <sstream>

namespace smi {

const char* op_kind_name(OpKind k) {
  switch (k) {
    case OpKind::Push: return "push";
    case OpKind::Pop: return "pop";
    case OpKind::Broadcast: return "broadcast";
    case OpKind::Reduce: return "reduce";
    case OpKind::Scatter: return "scatter";
    case OpKind::Gather: return "gather";
  }
  return "?";
}

namespace {

// ---------------------------------------------------------------------
// Tokenizer: just enough Python lexing for call-argument extraction —
// identifiers, numbers, strings, punctuation; comments skipped.
// ---------------------------------------------------------------------

struct Token {
  enum Type { Ident, Number, String, Punct, End } type = End;
  std::string text;
  int line = 1;
};

class Lexer {
 public:
  explicit Lexer(const std::string& src) : src_(src) {}

  Token next() {
    skip_ws_and_comments();
    Token t;
    t.line = line_;
    if (pos_ >= src_.size()) return t;  // End
    char c = src_[pos_];
    if (std::isalpha(static_cast<unsigned char>(c)) || c == '_') {
      size_t start = pos_;
      while (pos_ < src_.size() &&
             (std::isalnum(static_cast<unsigned char>(src_[pos_])) ||
              src_[pos_] == '_'))
        pos_++;
      t.type = Token::Ident;
      t.text = src_.substr(start, pos_ - start);
    } else if (std::isdigit(static_cast<unsigned char>(c))) {
      size_t start = pos_;
      while (pos_ < src_.size() &&
             (std::isalnum(static_cast<unsigned char>(src_[pos_])) ||
              src_[pos_] == '_' || src_[pos_] == '.'))
        pos_++;
      t.type = Token::Number;
      t.text = src_.substr(start, pos_ - start);
    } else if (c == '"' || c == '\'') {
      char quote = c;
      size_t start = ++pos_;
      while (pos_ < src_.size() && src_[pos_] != quote) {
        if (src_[pos_] == '\\') pos_++;
        if (pos_ < src_.size() && src_[pos_] == '\n') line_++;
        pos_++;
      }
      t.type = Token::String;
      t.text = src_.substr(start, pos_ - start);
      if (pos_ < src_.size()) pos_++;  // closing quote
    } else {
      t.type = Token::Punct;
      t.text = std::string(1, c);
      pos_++;
    }
    return t;
  }

 private:
  void skip_ws_and_comments() {
    while (pos_ < src_.size()) {
      char c = src_[pos_];
      if (c == '\n') {
        line_++;
        pos_++;
      } else if (std::isspace(static_cast<unsigned char>(c))) {
        pos_++;
      } else if (c == '#') {
        while (pos_ < src_.size() && src_[pos_] != '\n') pos_++;
      } else {
        break;
      }
    }
  }

  const std::string& src_;
  size_t pos_ = 0;
  int line_ = 1;
};

// One parsed call argument: positional index or keyword, literal value.
struct Arg {
  std::string keyword;  // empty = positional
  Token value;          // first token of the value (literal extraction)
  bool literal = true;  // value is a single literal token
};

// Parse a balanced argument list starting after '('. Returns tokens
// consumed; literal extraction only looks at single-token values.
std::vector<Arg> parse_args(Lexer& lex, Token& tok) {
  std::vector<Arg> args;
  int depth = 1;
  Arg cur;
  int value_tokens = 0;
  bool pending_kw = false;
  std::string last_ident;

  while (depth > 0) {
    tok = lex.next();
    if (tok.type == Token::End) break;
    if (tok.type == Token::Punct) {
      if (tok.text == "(" || tok.text == "[" || tok.text == "{") {
        depth++;
        cur.literal = false;
        value_tokens++;
        continue;
      }
      if (tok.text == ")" || tok.text == "]" || tok.text == "}") {
        depth--;
        if (depth == 0) break;
        value_tokens++;
        continue;
      }
      if (tok.text == "," && depth == 1) {
        if (value_tokens > 0) args.push_back(cur);
        cur = Arg();
        value_tokens = 0;
        pending_kw = false;
        last_ident.clear();
        continue;
      }
      if (tok.text == "=" && depth == 1 && value_tokens == 1 &&
          !last_ident.empty() && !pending_kw) {
        cur.keyword = last_ident;
        cur.value = Token();
        value_tokens = 0;
        pending_kw = true;
        continue;
      }
      cur.literal = false;
      value_tokens++;
      continue;
    }
    // Ident / Number / String
    if (value_tokens == 0) {
      cur.value = tok;
      cur.literal = true;
    } else {
      cur.literal = false;
    }
    if (tok.type == Token::Ident) last_ident = tok.text;
    value_tokens++;
  }
  if (value_tokens > 0) args.push_back(cur);
  return args;
}

const std::map<std::string, OpKind> kCallNames = {
    {"Push", OpKind::Push},
    {"Pop", OpKind::Pop},
    {"Broadcast", OpKind::Broadcast},
    {"Reduce", OpKind::Reduce},
    {"Scatter", OpKind::Scatter},
    {"Gather", OpKind::Gather},
    {"bcast", OpKind::Broadcast},
    {"reduce", OpKind::Reduce},
    {"scatter", OpKind::Scatter},
    {"gather", OpKind::Gather},
};

const std::set<std::string> kOpenNames = {
    "open_channel", "open_send_channel", "open_receive_channel"};

const std::set<std::string> kDtypes = {"int", "float", "double", "char",
                                       "short"};
const std::set<std::string> kReduceOps = {"add", "max", "min"};

// Names the grammar recognizes — imports may alias exactly these
// (`from smi_tpu import Push as P`), mirroring how the reference binds
// only SMI_* symbols (source-rewriter/src/rewrite.cpp:35-46).
bool is_known_op_name(const std::string& name) {
  return kCallNames.count(name) > 0 || kOpenNames.count(name) > 0;
}

// Scan-time symbol state: import aliases (alias -> canonical op name)
// and module-level integer constants (the reference resolves const ints
// through variable declarations, source-rewriter/src/ops/utils.cpp:5-48).
struct Symbols {
  std::map<std::string, std::string> aliases;
  std::map<std::string, long> constants;
  std::set<std::string> assigned;  // names seen on an assignment LHS
};

std::optional<long> as_int(const Arg& a, const Symbols& syms) {
  if (!a.literal) return std::nullopt;
  if (a.value.type == Token::Ident) {
    auto it = syms.constants.find(a.value.text);
    if (it != syms.constants.end()) return it->second;
    return std::nullopt;
  }
  if (a.value.type != Token::Number) return std::nullopt;
  try {
    return std::stol(a.value.text);
  } catch (...) {
    return std::nullopt;
  }
}

std::optional<std::string> as_string(const Arg& a) {
  if (!a.literal || a.value.type != Token::String) return std::nullopt;
  return a.value.text;
}

const Arg* find_arg(const std::vector<Arg>& args, const std::string& kw,
                    int positional) {
  for (const auto& a : args)
    if (a.keyword == kw) return &a;
  int pos = 0;
  for (const auto& a : args) {
    if (!a.keyword.empty()) continue;
    if (pos == positional) return &a;
    pos++;
  }
  return nullptr;
}

// Parse `from <module> import name [as alias] {, name [as alias]}`,
// recording aliases for recognized op names. Leaves `tok` on the first
// token after the import statement.
void parse_from_import(Lexer& lex, Token& tok, Symbols& syms) {
  // skip the dotted module path up to `import`
  while (tok.type != Token::End &&
         !(tok.type == Token::Ident && tok.text == "import"))
    tok = lex.next();
  if (tok.type == Token::End) return;
  tok = lex.next();
  if (tok.type == Token::Punct && tok.text == "(") tok = lex.next();
  while (tok.type == Token::Ident) {
    std::string target = tok.text;
    std::string local = target;
    tok = lex.next();
    if (tok.type == Token::Ident && tok.text == "as") {
      tok = lex.next();
      if (tok.type != Token::Ident) break;
      local = tok.text;
      tok = lex.next();
    }
    if (is_known_op_name(target)) syms.aliases[local] = target;
    if (tok.type == Token::Punct && (tok.text == "," || tok.text == ")")) {
      bool close = tok.text == ")";
      tok = lex.next();
      if (close) break;
    } else {
      break;
    }
  }
}

// Pass 1 of the scan: walk the whole source once, recording import
// aliases and top-level constant bindings. Running this to completion
// BEFORE op extraction makes the "bound once" rule retroactive: a name
// rebound anywhere in the file — even after a call site — is poisoned,
// because the scanner cannot know which binding that call site sees.
void collect_symbols(const std::string& source, Symbols& syms) {
  Lexer lex(source);
  Token tok = lex.next();
  bool after_dot = false;
  int depth = 0;

  while (tok.type != Token::End) {
    if (tok.type != Token::Ident) {
      after_dot = tok.type == Token::Punct && tok.text == ".";
      if (tok.type == Token::Punct) {
        if (tok.text == "(" || tok.text == "[" || tok.text == "{") depth++;
        if (tok.text == ")" || tok.text == "]" || tok.text == "}")
          depth = depth > 0 ? depth - 1 : 0;
      }
      tok = lex.next();
      continue;
    }
    std::string name = tok.text;
    bool qualified = after_dot;
    after_dot = false;

    // import-alias statements (`from smi_tpu import Push as P`)
    if (!qualified && name == "from") {
      parse_from_import(lex, tok, syms);
      continue;
    }

    Token after = lex.next();
    bool is_call = after.type == Token::Punct && after.text == "(";

    // top-level integer constants (`PORT = 3`) — SINGLE assignment of a
    // bare literal. A second assignment (any RHS, anywhere in the file)
    // poisons the name (docs/manifest.md "bound once").
    if (!qualified && !is_call && depth == 0 &&
        after.type == Token::Punct && after.text == "=") {
      Token value = lex.next();
      if (value.type == Token::Punct && value.text == "=") {
        // `==` comparison, not an assignment
        tok = lex.next();
        continue;
      }
      bool reassigned = syms.assigned.count(name) > 0;
      syms.assigned.insert(name);
      if (value.type != Token::Number) {
        // non-literal RHS: not a constant; re-process the RHS token
        syms.constants.erase(name);
        tok = value;
        continue;
      }
      Token trailing = lex.next();
      // the literal stands alone only if the statement ends here: next
      // token on a later line, end of file, or a statement separator.
      // Any same-line continuation (`+ 1`, `if fast else 4`, `, 5`,
      // `< x`) makes the value computed, not constant.
      bool simple = trailing.type == Token::End ||
                    trailing.line > value.line ||
                    (trailing.type == Token::Punct && trailing.text == ";");
      if (simple && !reassigned) {
        try {
          syms.constants[name] = std::stol(value.text);
        } catch (...) {
          syms.constants.erase(name);
        }
      } else {
        syms.constants.erase(name);  // computed or rebound: not constant
      }
      tok = trailing;
      continue;
    }
    tok = after;
  }
}

}  // namespace

ScanResult scan_source(const std::string& source,
                       const std::string& filename) {
  ScanResult result;
  Symbols syms;
  collect_symbols(source, syms);  // pass 1: aliases + constants
  Lexer lex(source);
  Token tok = lex.next();
  bool after_dot = false;  // previous token was `.` (attribute access)
  int depth = 0;           // bracket depth outside matched-call arg lists

  while (tok.type != Token::End) {
    if (tok.type != Token::Ident) {
      after_dot = tok.type == Token::Punct && tok.text == ".";
      if (tok.type == Token::Punct) {
        if (tok.text == "(" || tok.text == "[" || tok.text == "{") depth++;
        if (tok.text == ")" || tok.text == "]" || tok.text == "}")
          depth = depth > 0 ? depth - 1 : 0;
      }
      tok = lex.next();
      continue;
    }
    std::string name = tok.text;
    int call_line = tok.line;
    bool qualified = after_dot;
    after_dot = false;

    // symbols were collected in pass 1; here the import statement's
    // tokens only need to be skipped (an RHS op call after `=` still
    // falls through to extraction below)
    if (!qualified && name == "from") {
      Symbols scratch;
      parse_from_import(lex, tok, scratch);
      continue;
    }

    Token after = lex.next();
    bool is_call =
        after.type == Token::Punct && after.text == "(";

    // resolve import aliases (the canonical name drives matching; the
    // attribute qualifier, if any, is ignored as the reference ignores
    // the callee's scope once the name matches)
    if (!qualified) {
      auto alias = syms.aliases.find(name);
      if (alias != syms.aliases.end()) name = alias->second;
    }

    auto handle = [&](OpKind kind, const std::vector<Arg>& args) {
      Operation op;
      op.kind = kind;
      op.line = call_line;
      bool is_ctor = std::isupper(static_cast<unsigned char>(name[0]));

      const Arg* port_arg =
          is_ctor ? find_arg(args, "port", 0) : find_arg(args, "port", -1);
      if (port_arg == nullptr) {
        // context collectives: port is keyword-only and optional
        if (!is_ctor) return;  // collective without explicit port: skip
        result.errors.push_back(filename + ":" +
                                std::to_string(call_line) + ": " + name +
                                " call without a port argument");
        return;
      }
      auto port = as_int(*port_arg, syms);
      if (!port) {
        // ports must be compile-time constants — integer literals or
        // names bound once to one, as in the reference
        // (source-rewriter/src/ops/utils.cpp:5-48)
        result.errors.push_back(
            filename + ":" + std::to_string(call_line) + ": " + name +
            " port is not a compile-time integer constant");
        return;
      }
      op.port = static_cast<int>(*port);

      if (const Arg* d = find_arg(args, "dtype", is_ctor ? 1 : -1)) {
        if (auto ds = as_string(*d)) {
          if (kDtypes.count(*ds) == 0) {
            result.errors.push_back(filename + ":" +
                                    std::to_string(call_line) +
                                    ": unknown dtype '" + *ds + "'");
            return;
          }
          op.dtype = *ds;
        }
      }
      if (const Arg* b = find_arg(args, "buffer_size", is_ctor ? 2 : -1)) {
        if (auto bi = as_int(*b, syms)) op.buffer_size = *bi;
      }
      if (kind == OpKind::Reduce) {
        if (const Arg* o = find_arg(args, "op", -1)) {
          if (auto os = as_string(*o)) {
            if (kReduceOps.count(*os)) op.reduce_op = *os;
          }
        }
      }
      result.ops.push_back(op);
    };

    if (is_call) {
      auto it = kCallNames.find(name);
      if (it != kCallNames.end()) {
        std::vector<Arg> args = parse_args(lex, tok);
        handle(it->second, args);
        tok = lex.next();
        continue;
      }
      if (kOpenNames.count(name) > 0) {
        std::vector<Arg> args = parse_args(lex, tok);
        // a channel open declares both endpoints' ops at that port
        const Arg* port_arg = find_arg(args, "port", 0);
        auto port = port_arg ? as_int(*port_arg, syms)
                             : std::optional<long>();
        if (!port) {
          result.errors.push_back(filename + ":" +
                                  std::to_string(call_line) +
                                  ": open_channel port is not a "
                                  "compile-time integer constant");
        } else {
          Operation op;
          op.port = static_cast<int>(*port);
          op.line = call_line;
          if (const Arg* d = find_arg(args, "dtype", -1)) {
            if (auto ds = as_string(*d)) {
              if (kDtypes.count(*ds) == 0) {
                result.errors.push_back(filename + ":" +
                                        std::to_string(call_line) +
                                        ": unknown dtype '" + *ds + "'");
                tok = lex.next();
                continue;
              }
              op.dtype = *ds;
            }
          }
          if (const Arg* b = find_arg(args, "buffer_size", -1))
            if (auto bi = as_int(*b, syms)) op.buffer_size = *bi;
          if (name != "open_receive_channel") {
            op.kind = OpKind::Push;
            result.ops.push_back(op);
          }
          if (name != "open_send_channel") {
            op.kind = OpKind::Pop;
            result.ops.push_back(op);
          }
        }
        tok = lex.next();
        continue;
      }
    }
    tok = after;
  }
  return result;
}

std::vector<std::string> validate_ops(const std::vector<Operation>& ops,
                                      bool p2p_rendezvous) {
  // stream classes per op kind (ops.py channel_usage analog)
  std::vector<std::string> errors;
  const char* classes[4] = {"out_data", "out_ctrl", "in_data", "in_ctrl"};
  for (int c = 0; c < 4; c++) {
    std::map<int, const Operation*> seen;
    for (const auto& op : ops) {
      bool uses = false;
      switch (op.kind) {
        case OpKind::Push:
          uses = (c == 0) || (p2p_rendezvous && c == 3);
          break;
        case OpKind::Pop:
          uses = (c == 2) || (p2p_rendezvous && c == 1);
          break;
        default:
          uses = true;  // collectives use all four classes
      }
      if (!uses) continue;
      auto it = seen.find(op.port);
      if (it != seen.end()) {
        errors.push_back(
            std::string("port ") + std::to_string(op.port) +
            " claimed twice on stream class " + classes[c] + " (" +
            op_kind_name(it->second->kind) + " line " +
            std::to_string(it->second->line) + " vs " +
            op_kind_name(op.kind) + " line " + std::to_string(op.line) +
            ")");
      } else {
        seen[op.port] = &op;
      }
    }
  }
  return errors;
}

std::string to_json_lines(const std::vector<Operation>& ops) {
  std::ostringstream out;
  for (const auto& op : ops) {
    out << "{\"type\": \"" << op_kind_name(op.kind)
        << "\", \"port\": " << op.port << ", \"data_type\": \"" << op.dtype
        << "\", \"buffer_size\": ";
    if (op.buffer_size)
      out << *op.buffer_size;
    else
      out << "null";
    out << ", \"args\": {";
    if (op.kind == OpKind::Reduce)
      out << "\"op_type\": \"" << op.reduce_op << "\"";
    out << "}, \"line\": " << op.line << "}\n";
  }
  return out.str();
}

}  // namespace smi
