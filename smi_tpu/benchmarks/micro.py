"""The microbenchmark suite.

Metric formulas follow the reference hosts (SURVEY §6):

- ``bandwidth``: payload bits / transfer time, two concurrent channels
  (``bandwidth_benchmark.cpp:188-194``, ``bandwidth_0.cl:14-33``);
- ``latency``: mean RTT/2 of a 1-element ping-pong
  (``latency_0.cl:10-12``, ``latency_benchmark.cpp:158-175``);
- ``injection``: time per 1-element message, back-to-back
  (``injection_rate_benchmark.cpp:150-171``);
- ``broadcast``/``reduce``/``scatter``/``gather``: N-element rooted
  collective time vs root placement (``broadcast_benchmark.cpp`` etc.);
- ``multi_collectives``: overlapped vs serialized broadcasts on distinct
  ports (``multi_collectives.cl:1-12``);
- ``pipeline``: R ring hops, rendezvous (chunked) vs eager
  (``pipeline.cl:9-34``; eager variants
  ``microbenchmarks/CMakeLists.txt:16-17``).

All benchmarks run the real shard_map/collective code path; completion is
forced with a scalar readback per timed run.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from smi_tpu.benchmarks.stats import Measurement, timed_samples
from smi_tpu.parallel.channels import P2PChannel, ring_shift, stream_concurrent
from smi_tpu.parallel import collectives as coll
from smi_tpu.parallel.mesh import Communicator, make_communicator


def _force(fn):
    """Wrap a jitted fn so each call forces completion via readback."""

    def run():
        np.asarray(fn())

    return run


#: Public name of the readback-forcing wrapper: the tuning sweep driver
#: (``smi_tpu.tuning.sweep``) times its candidate plans with THIS
#: harness — same completion forcing, same ``timed_samples`` warmup and
#: repeat discipline — so a sweep-measured cost is comparable with the
#: microbenchmark suite's numbers.
force_readback = _force


def bench_bandwidth(
    comm: Communicator, size_kb: int = 512, runs: int = 10, repeats: int = 4,
    rendezvous: bool = False, buffer_size: int = 2048,
    backend: str = "xla",
) -> Measurement:
    """Two concurrent P2P channels rank0→rank1; payload Gbit/s.

    ``rendezvous=True`` moves each message in bounded
    ``buffer_size``-element chunks (the reference's credit protocol,
    asynchronicity degree 2048 as in ``bandwidth_0.cl:14``);
    ``False`` is the eager variant (``bandwidth_eager``,
    ``microbenchmarks/CMakeLists.txt:26``).
    """
    n = max(1, size_kb * 1024 // 4 // 2)  # floats per channel
    axis = comm.axis_names[0]

    def shard_fn(x):
        ch0 = P2PChannel(comm=comm, port=0, src=0, dst=1, count=n,
                         dtype="float", rendezvous=rendezvous,
                         buffer_size=buffer_size)
        ch1 = P2PChannel(comm=comm, port=1, src=0, dst=1, count=n,
                         dtype="float", rendezvous=rendezvous,
                         buffer_size=buffer_size)

        def one(carry, _):
            if rendezvous:
                # lockstep chunking keeps the two channels concurrent
                # (separate .stream calls would serialize their scans)
                a, b = stream_concurrent((ch0, ch1), (x, x * 2),
                                         backend=backend)
            else:
                a = ch0.transfer(x, backend=backend)
                b = ch1.transfer(x * 2, backend=backend)
            return carry + jnp.sum(a) + jnp.sum(b), ()

        total, _ = lax.scan(one, jnp.zeros((), jnp.float32), None,
                            length=repeats)
        return total[None]

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=P(), out_specs=P(axis),
        check_vma=False,
    ))
    x = jnp.ones(n, jnp.float32)
    samples = timed_samples(_force(lambda: fn(x)), runs)
    bytes_moved = 2 * n * 4 * repeats
    gbits = [bytes_moved * 8 / t / 1e9 for t in samples]
    name = "bandwidth" if rendezvous else "bandwidth-eager"
    return Measurement(name, "Gbit/s", gbits,
                       {"size_kb": size_kb, "channels": 2,
                        "repeats": repeats, "rendezvous": rendezvous,
                        "backend": backend})


def bench_bandwidth_eager(comm, size_kb: int = 512, runs: int = 10,
                          repeats: int = 4, backend: str = "xla"):
    return bench_bandwidth(comm, size_kb, runs, repeats, rendezvous=False,
                           backend=backend)


def bench_bandwidth_rendezvous(comm, size_kb: int = 512, runs: int = 10,
                               repeats: int = 4, backend: str = "xla"):
    return bench_bandwidth(comm, size_kb, runs, repeats, rendezvous=True,
                           backend=backend)


def bench_latency(
    comm: Communicator, pingpongs: int = 100, runs: int = 10,
    backend: str = "xla",
) -> Measurement:
    """1-element ping-pong rank0↔rank1; half round trip in usec."""
    axis = comm.axis_names[0]

    def shard_fn(x):
        fwd = P2PChannel(comm=comm, port=0, src=0, dst=1, count=1,
                         dtype="int", rendezvous=False)
        bwd = P2PChannel(comm=comm, port=1, src=1, dst=0, count=1,
                         dtype="int", rendezvous=False)

        def one(carry, _):
            there = fwd.transfer(carry, backend=backend)
            back = bwd.transfer(there + 1, backend=backend)
            return back, ()

        out, _ = lax.scan(one, x, None, length=pingpongs)
        return out[None]

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=P(), out_specs=P(axis),
        check_vma=False,
    ))
    x = jnp.zeros(1, jnp.int32)
    samples = timed_samples(_force(lambda: fn(x)), runs)
    usecs = [t / (2 * pingpongs) * 1e6 for t in samples]
    return Measurement("latency", "usec", usecs,
                       {"pingpongs": pingpongs, "backend": backend})


def bench_injection(
    comm: Communicator, messages: int = 100, runs: int = 10,
    backend: str = "xla",
) -> Measurement:
    """Back-to-back 1-element sends; per-message overhead in usec."""
    axis = comm.axis_names[0]

    def shard_fn(x):
        ch = P2PChannel(comm=comm, port=0, src=0, dst=1, count=1,
                        dtype="int", rendezvous=False)

        def one(carry, _):
            got = ch.transfer(carry, backend=backend)
            return got + carry, ()

        out, _ = lax.scan(one, x, None, length=messages)
        return out[None]

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=P(), out_specs=P(axis),
        check_vma=False,
    ))
    x = jnp.ones(1, jnp.int32)
    samples = timed_samples(_force(lambda: fn(x)), runs)
    usecs = [t / messages * 1e6 for t in samples]
    return Measurement("injection", "usec/msg", usecs,
                       {"messages": messages, "backend": backend})


def _bench_collective(
    name: str, comm: Communicator, elements: int, root: int, runs: int,
    op: Optional[str] = None, backend: str = "xla",
) -> Measurement:
    axis = comm.axis_names[0]
    size = comm.size

    def shard_fn(x):
        r = comm.rank().astype(x.dtype)
        if name == "broadcast":
            out = coll.bcast(x + r, root=root, comm=comm, port=0,
                             backend=backend)
        elif name == "reduce":
            out = coll.reduce(x + r, comm, op=op or "add", root=root,
                              port=0, backend=backend)
        elif name == "scatter":
            out = coll.scatter(
                jnp.tile(x, size) + r, comm, root=root, port=0,
                backend=backend,
            )
        else:  # gather
            out = coll.gather(x + r, comm, root=root, port=0,
                              backend=backend)
        return jnp.sum(out)[None]

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=P(), out_specs=P(axis),
        check_vma=False,
    ))
    x = jnp.ones(elements, jnp.float32)
    samples = timed_samples(_force(lambda: fn(x)), runs)
    usecs = [t * 1e6 for t in samples]
    return Measurement(
        f"{name}-root{root}", "usec", usecs,
        {"elements": elements, "root": root, "ranks": size, "op": op,
         "backend": backend},
    )


def bench_broadcast(comm, elements: int = 65536, root: int = 0,
                    runs: int = 10, backend: str = "xla"):
    return _bench_collective("broadcast", comm, elements, root, runs,
                             backend=backend)


def bench_reduce(comm, elements: int = 65536, root: int = 0, runs: int = 10,
                 op: str = "add", backend: str = "xla"):
    return _bench_collective("reduce", comm, elements, root, runs, op=op,
                             backend=backend)


def bench_scatter(comm, elements: int = 8192, root: int = 0, runs: int = 10,
                  backend: str = "xla"):
    return _bench_collective("scatter", comm, elements, root, runs,
                             backend=backend)


def bench_gather(comm, elements: int = 8192, root: int = 0, runs: int = 10,
                 backend: str = "xla"):
    return _bench_collective("gather", comm, elements, root, runs,
                             backend=backend)


def bench_multi_collectives(
    comm: Communicator, elements: int = 16384, runs: int = 10,
    backend: str = "xla",
) -> Measurement:
    """Overlap benefit: 3 independent broadcasts on distinct ports vs 3
    serialized ones (data-dependent chain)."""
    axis = comm.axis_names[0]

    r1, r2 = 1 % comm.size, 2 % comm.size  # stay valid on tiny comms

    def overlapped(x):
        a = coll.bcast(x, comm, root=0, port=0, backend=backend)
        b = coll.bcast(x * 2, comm, root=r1, port=1, backend=backend)
        c = coll.bcast(x * 3, comm, root=r2, port=2, backend=backend)
        return (jnp.sum(a) + jnp.sum(b) + jnp.sum(c))[None]

    def serialized(x):
        a = coll.bcast(x, comm, root=0, port=0, backend=backend)
        b = coll.bcast(a * 2, comm, root=r1, port=0,
                       backend=backend)  # depends on a
        c = coll.bcast(b * 3, comm, root=r2, port=0, backend=backend)
        return jnp.sum(c)[None]

    x = jnp.ones(elements, jnp.float32)
    results = {}
    for tag, body in (("overlapped", overlapped), ("serialized", serialized)):
        fn = jax.jit(jax.shard_map(
            body, mesh=comm.mesh, in_specs=P(), out_specs=P(axis),
            check_vma=False,
        ))
        samples = timed_samples(_force(lambda: fn(x)), runs)
        results[tag] = [t * 1e6 for t in samples]
    # report the overlapped time; serialized mean lands in config
    m = Measurement("multi_collectives", "usec", results["overlapped"],
                    {"elements": elements, "backend": backend,
                     "serialized_mean_usec":
                         sum(results["serialized"]) / runs})
    return m


def bench_pipeline(
    comm: Communicator, elements: int = 4096, rounds: int = 16,
    runs: int = 10, rendezvous: bool = True, backend: str = "xla",
) -> Measurement:
    """Ring pipeline: every rank forwards to rank+1 for R rounds."""
    axis = comm.axis_names[0]

    def shard_fn(x):
        def one(carry, _):
            if rendezvous:
                # bounded in-flight: move in default-depth chunks
                chunk = 112  # 16 packets of float
                n_chunks = max(1, elements // chunk)
                parts = carry[: n_chunks * chunk].reshape(n_chunks, -1)
                _, shifted = lax.scan(
                    lambda c, part: (c, ring_shift(part, comm,
                                                   backend=backend)),
                    (), parts
                )
                out = jnp.concatenate(
                    [shifted.reshape(-1),
                     ring_shift(carry[n_chunks * chunk:], comm,
                                backend=backend)]
                ) if elements % chunk else shifted.reshape(-1)
            else:
                out = ring_shift(carry, comm, backend=backend)
            return out + 1.0, ()

        out, _ = lax.scan(one, x, None, length=rounds)
        return jnp.sum(out)[None]

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=P(), out_specs=P(axis),
        check_vma=False,
    ))
    x = jnp.ones(elements, jnp.float32)
    samples = timed_samples(_force(lambda: fn(x)), runs)
    usecs = [t / rounds * 1e6 for t in samples]
    name = "pipeline" if rendezvous else "pipeline-eager"
    return Measurement(name, "usec/round", usecs,
                       {"elements": elements, "rounds": rounds,
                        "rendezvous": rendezvous, "backend": backend})


def bench_pipeline_double_rail(
    comm: Communicator, elements: int = 4096, rounds: int = 16,
    runs: int = 10, backend: str = "xla",
) -> Measurement:
    """Ring pipeline with the payload split into two messages per hop.

    Reference ``pipeline_double_rail.cl`` splits each hop's payload over
    both QSFP rails. ICI has no user-visible rail selection — XLA owns
    link scheduling — so the TPU rendition sends two *independent*
    ppermutes per hop (free for XLA to overlap or coalesce onto the
    available links) and the comparison against :func:`bench_pipeline`
    measures what the split costs or gains.
    """
    axis = comm.axis_names[0]
    half = elements // 2

    def shard_fn(x):
        def one(carry, _):
            a, b = carry[:half], carry[half:]
            a = ring_shift(a, comm, backend=backend)      # rail 0
            b = ring_shift(b, comm, backend=backend)      # rail 1
            return jnp.concatenate([a, b]) + 1.0, ()

        out, _ = lax.scan(one, x, None, length=rounds)
        return jnp.sum(out)[None]

    fn = jax.jit(jax.shard_map(
        shard_fn, mesh=comm.mesh, in_specs=P(), out_specs=P(axis),
        check_vma=False,
    ))
    x = jnp.ones(elements, jnp.float32)
    samples = timed_samples(_force(lambda: fn(x)), runs)
    usecs = [t / rounds * 1e6 for t in samples]
    return Measurement("pipeline-double-rail", "usec/round", usecs,
                       {"elements": elements, "rounds": rounds, "rails": 2,
                        "backend": backend})


def bench_overlap(
    comm: Communicator, size_kb: int = 256, chunks: int = 4,
    repeats: int = 2, runs: int = 10,
    sweep_kb: tuple = (16, 64, 256), backend: str = "xla",
) -> Measurement:
    """Overlap efficiency of the chunked pipelined allreduce.

    Times a chain of ``repeats`` allreduce+compute steps twice — once
    unchunked (bulk-synchronous: all compute waits for the whole
    payload) and once with ``chunks=`` pipeline chunks — across a
    payload sweep. The reported samples are the unchunked/chunked time
    ratios at ``size_kb`` (>1 = the pipeline hid communication);
    ``config["sweep"]`` carries the per-size mean seconds for both
    variants, and ``config["overlap_report"]`` the static
    comm/compute-overlap evidence of the chunked executable
    (:func:`smi_tpu.parallel.traffic.overlap_report`) — the measured
    and the compiled views of the same property, feeding PERF.json.
    """
    if size_kb not in sweep_kb:
        sweep_kb = tuple(sweep_kb) + (size_kb,)
    axis = comm.axis_names[0]
    scale = 1.0 / comm.size

    def make(n_elems: int, n_chunks: int):
        def shard_fn(x):
            def one(carry, _):
                y = coll.allreduce(carry, comm, backend=backend,
                                   chunks=n_chunks)
                # the compute a pipelined schedule can hide: depends
                # only on the carry, not on this step's collective
                return y * scale + carry * 0.5, ()

            out, _ = lax.scan(one, x, None, length=repeats)
            return jnp.sum(out)[None]

        return jax.jit(jax.shard_map(
            shard_fn, mesh=comm.mesh, in_specs=P(), out_specs=P(axis),
            check_vma=False,
        ))

    sweep = {}
    ratio_samples = None
    static_report = None
    for kb in sweep_kb:
        n_elems = max(1, kb * 1024 // 4)
        x = jnp.ones(n_elems, jnp.float32)
        base_fn, chunk_fn = make(n_elems, 1), make(n_elems, chunks)
        base = timed_samples(_force(lambda: base_fn(x)), runs)
        chunked = timed_samples(_force(lambda: chunk_fn(x)), runs)
        sweep[kb] = {
            "unchunked_mean_s": sum(base) / len(base),
            "chunked_mean_s": sum(chunked) / len(chunked),
        }
        if kb == size_kb:
            ratio_samples = [b / c for b, c in zip(base, chunked)]
            try:
                from smi_tpu.parallel import traffic

                rep = traffic.overlap_report(
                    chunk_fn.lower(x).compile()
                )
                static_report = {
                    k: rep[k]
                    for k in ("collectives", "async_pairs",
                              "overlappable_bytes", "overlap_fraction")
                }
            except Exception as e:  # static evidence is best-effort
                static_report = {"error": f"{type(e).__name__}: {e}"}
    return Measurement(
        "overlap", "x", ratio_samples,
        {"size_kb": size_kb, "chunks": chunks, "repeats": repeats,
         "backend": backend, "sweep": sweep,
         "overlap_report": static_report},
    )


BENCHMARKS: Dict[str, Callable] = {
    "bandwidth": bench_bandwidth_rendezvous,
    "bandwidth_eager": bench_bandwidth_eager,
    "latency": bench_latency,
    "injection": bench_injection,
    "broadcast": bench_broadcast,
    "reduce": bench_reduce,
    "scatter": bench_scatter,
    "gather": bench_gather,
    "multi_collectives": bench_multi_collectives,
    "pipeline": bench_pipeline,
    "pipeline_double_rail": bench_pipeline_double_rail,
    "overlap": bench_overlap,
}

# application-level benchmarks join the same registry
from smi_tpu.benchmarks.apps import APP_BENCHMARKS  # noqa: E402

BENCHMARKS.update(APP_BENCHMARKS)


def run_benchmark(name: str, comm: Optional[Communicator] = None,
                  out_dir: Optional[str] = None, **params) -> Measurement:
    if name not in BENCHMARKS:
        raise KeyError(
            f"unknown benchmark {name!r}; have {sorted(BENCHMARKS)}"
        )
    if comm is None:
        comm = make_communicator()
    fn = BENCHMARKS[name]
    import inspect

    sig = inspect.signature(fn)
    if "backend" in params and "backend" not in sig.parameters and not any(
        p.kind is inspect.Parameter.VAR_KEYWORD
        for p in sig.parameters.values()
    ):
        # benchmarks without backend tiers (the app benchmarks) reject
        # the kwarg; dropping backend='xla' is harmless (it IS the
        # default tier), but a requested non-default tier must never be
        # silently substituted with an XLA measurement
        dropped = params.pop("backend")
        if dropped != "xla":
            raise ValueError(
                f"benchmark {name!r} has no backend tiers; refusing to "
                f"record backend={dropped!r} as an XLA measurement"
            )
    m = fn(comm, **params)
    backend = params.get("backend", "xla")
    if backend != "xla" and not m.name.endswith(f"-{backend}"):
        # result files are keyed by name; a ring run must never
        # clobber the xla run's .dat/.json in a shared out-dir
        import dataclasses as _dc

        m = _dc.replace(m, name=f"{m.name}-{backend}")
    print(m.summary())
    if out_dir:
        m.write_dat(out_dir)
    return m
