"""Credit flow-control protocol: schedule-fuzzed state-machine tests.

Reference: the SMI NoC's credit protocols (``templates/push.cl:21-31``,
``pop.cl:35-51``, ``reduce.cl:13-32``) are exercised by the strict
channel-depth emulator; here the equivalent protocol that guards the ring
kernels' RDMA slots (:mod:`smi_tpu.kernels.ring`) is specified in
:mod:`smi_tpu.parallel.credits` and driven through random, adversarial,
and (for tiny configurations) exhaustive schedules.

These tests are pure Python — no JAX — and they are the evidence that
``flow_control=True`` in the kernels implements a sound protocol: no
clobber, no deadlock, no credit leak, correct delivery, under every
explored interleaving. The companion mutation tests show the harness
*can* see the race: with credits disabled, adversarial schedules corrupt
data.
"""

import pytest

from smi_tpu.parallel import credits as C

NS = [2, 3, 5, 8]
SEEDS = range(12)


@pytest.mark.parametrize("n", NS)
@pytest.mark.parametrize("seed", SEEDS)
def test_all_gather_random_schedules(n, seed):
    C.simulate_all_gather(n, C.Strategy(seed))


@pytest.mark.parametrize("n", NS)
@pytest.mark.parametrize("seed", SEEDS)
def test_all_reduce_random_schedules(n, seed):
    C.simulate_all_reduce(n, C.Strategy(seed))


@pytest.mark.parametrize("n", NS)
@pytest.mark.parametrize("seed", SEEDS)
def test_reduce_scatter_random_schedules(n, seed):
    C.simulate_reduce_scatter(n, C.Strategy(seed))


@pytest.mark.parametrize("n", NS)
@pytest.mark.parametrize("direction", [1, -1])
@pytest.mark.parametrize("seed", SEEDS)
def test_neighbour_stream_random_schedules(n, direction, seed):
    C.simulate_neighbour_stream(n, 5, C.Strategy(seed), direction=direction)


@pytest.mark.parametrize("n", [3, 5])
@pytest.mark.parametrize("seed", SEEDS)
def test_adversarial_delayed_dmas(n, seed):
    """DMAs land as late as possible — maximal clobber window."""
    C.simulate_all_gather(n, C.DelayDmaStrategy(seed))
    C.simulate_all_reduce(n, C.DelayDmaStrategy(seed))
    C.simulate_reduce_scatter(n, C.DelayDmaStrategy(seed))
    C.simulate_neighbour_stream(n, 6, C.DelayDmaStrategy(seed))


@pytest.mark.parametrize("n", [3, 5])
@pytest.mark.parametrize("seed", SEEDS)
def test_adversarial_favoured_rank(n, seed):
    """One rank races ahead while the others lag — the fast-writer /
    slow-consumer scenario the credits exist for."""
    for fav in range(n):
        C.simulate_all_gather(n, C.FavourRankStrategy(fav, seed))
        C.simulate_neighbour_stream(n, 6, C.FavourRankStrategy(fav, seed))


@pytest.mark.parametrize("name,make", [
    ("neighbour_stream_n2c2", lambda: [
        C.neighbour_stream_rank(r, 2, [(r, c) for c in range(2)])
        for r in range(2)
    ]),
    ("neighbour_stream_n2c3", lambda: [
        C.neighbour_stream_rank(r, 2, [(r, c) for c in range(3)])
        for r in range(2)
    ]),
    ("all_gather_n2", lambda: [
        C.all_gather_rank(r, 2, f"c{r}") for r in range(2)
    ]),
    ("all_reduce_n2", lambda: [
        C.all_reduce_rank(r, 2, frozenset([r]), lambda a, b: a | b)
        for r in range(2)
    ]),
    ("reduce_scatter_n2", lambda: [
        C.reduce_scatter_rank(
            r, 2, [frozenset([(r, b)]) for b in range(2)], lambda a, b: a | b
        )
        for r in range(2)
    ]),
])
def test_exhaustive_tiny_configs(name, make):
    """Every scheduler interleaving (communication-boundary granularity)
    of the two-rank protocols passes all invariants."""
    explored = C.explore_all_schedules(make, max_schedules=500_000)
    assert explored > 50  # genuinely many distinct schedules


def test_mutation_no_credits_is_caught_fuzzed():
    """Disabling flow control must produce a detectable violation under
    adversarial schedules — proof the harness can see the race."""
    caught = 0
    for seed in range(60):
        for fav in range(3):
            try:
                C.simulate_neighbour_stream(
                    3, 8, C.FavourRankStrategy(fav, seed), flow_control=False
                )
            except C.ProtocolError:
                caught += 1
    assert caught > 0


def test_mutation_no_credits_all_gather_corrupts():
    """all_gather without credits: an overtaking landing corrupts the
    gathered payload (caught as clobber or as wrong output)."""
    caught = 0
    for seed in range(60):
        for fav in range(3):
            try:
                C.simulate_all_gather(
                    3, C.FavourRankStrategy(fav, seed), flow_control=False
                )
            except C.ProtocolError:
                caught += 1
    assert caught > 0


def test_deadlock_detection_works():
    """A rank waiting on a credit nobody grants must be reported as a
    deadlock, not an infinite loop."""

    def stuck_rank():
        yield ("wait", C.SEM_CREDIT, 0, 1)

    with pytest.raises(C.DeadlockError):
        C.RingSimulator([stuck_rank()], C.Strategy(0)).run()


def test_credit_leak_detection_works():
    """A dangling semaphore count at exit must be reported."""

    def leaky_rank():
        yield ("signal", 0, C.SEM_CREDIT, 0, 1)

    with pytest.raises(C.CreditLeakError):
        C.RingSimulator([leaky_rank()], C.Strategy(0)).run()
